"""OpenAPI 3.1 contract, generated from one source of truth.

Parity: the reference ships a hand-exported OpenAPI file
(``api/gpu-docker-api.openapi.json``, 2,187 lines) that is "the canonical
interface doc" (api/gpu-docker-api-sample-interface.md:3) but can silently
drift from the gin routes. Here the contract is *generated*: every path below
is asserted against the live router in tests (test_openapi.py), so the
committed ``api/openapi.json`` cannot drift without failing CI.

Regenerate with::

    python -m tpu_docker_api.api.openapi > api/openapi.json
"""

from __future__ import annotations

import json
from typing import Any

_ENVELOPE_NOTE = (
    "All responses are HTTP 200 with the outcome in the body envelope "
    "{code, msg, data}: code 200 = success, 10xxx = application error "
    "(see api/codes.py)."
)


def _obj(props: dict[str, Any], required: list[str] | None = None,
         desc: str = "") -> dict:
    out: dict[str, Any] = {"type": "object", "properties": props}
    if required:
        out["required"] = required
    if desc:
        out["description"] = desc
    return out


def _arr(items: dict) -> dict:
    return {"type": "array", "items": items}


_STR = {"type": "string"}
_INT = {"type": "integer"}
_BOOL = {"type": "boolean"}

_BIND = _obj({"src": _STR, "dest": _STR}, ["src", "dest"])
_CONTAINER_PORT = _obj(
    {"containerPort": _INT, "hostPort": {**_INT, "description": "0 = scheduler-assigned"},
     "protocol": {**_STR, "default": "tcp"}},
    ["containerPort"],
)

_SCHEMAS: dict[str, dict] = {
    "Envelope": _obj(
        {"code": {**_INT, "description": "200 or application error code"},
         "msg": _STR, "data": {}},
        ["code", "msg"], _ENVELOPE_NOTE),
    "ContainerRun": _obj(
        {"imageName": _STR,
         "containerName": {**_STR, "description":
                           "base name, [a-zA-Z0-9_.]+ (no '-'); versions are name-N"},
         "chipCount": {**_INT, "description": "TPU chips to attach; 0 = cardless"},
         "sliceShape": {**_STR, "description":
                        "optional explicit ICI block, e.g. \"2x2\" — disables scattered fallback"},
         "binds": _arr(_BIND), "env": _arr(_STR), "cmd": _arr(_STR),
         "containerPorts": _arr(_CONTAINER_PORT)},
        ["imageName", "containerName"]),
    "ContainerDelete": _obj({"force": _BOOL,
                             "delEtcdInfoAndVersionRecord": _BOOL}),
    "ContainerExecute": _obj({"workDir": _STR, "cmd": _arr(_STR)}, ["cmd"]),
    "ContainerPatchChips": _obj(
        {"chipCount": {**_INT, "description":
                       "desired chip count; rolling-replaces into name-(N+1)"}},
        ["chipCount"]),
    "ContainerPatchVolume": _obj(
        {"oldBind": _BIND, "newBind": _BIND}, ["oldBind", "newBind"]),
    "ContainerCommit": _obj({"newImageName": _STR}, ["newImageName"]),
    "VolumeCreate": _obj(
        {"volumeName": _STR,
         "size": {**_STR, "description": "e.g. \"20GB\"; units KB|MB|GB|TB"}},
        ["volumeName"]),
    "VolumeDelete": _obj({"delEtcdInfoAndVersionRecord": _BOOL}),
    "VolumeSize": _obj({"size": _STR}, ["size"]),
    "JobRun": _obj(
        {"imageName": _STR,
         "jobName": {**_STR, "description": "base name, [a-zA-Z0-9_.]+"},
         "chipCount": {**_INT, "description":
                       "total chips; whole-host multiples span hosts"},
         "acceleratorType": {**_STR, "description":
                             "alternative ask, e.g. \"v5p-64\""},
         "binds": _arr({**_STR, "description": "\"src:dest\""}),
         "env": _arr(_STR), "cmd": _arr(_STR),
         "numSlices": {**_INT, "description":
                       ">1 = multislice: chipCount splits into numSlices "
                       "ICI slices stitched over DCN (MEGASCALE_* env)"},
         "priorityClass": {**_STR, "description":
                           "capacity-market class (default ladder: system > "
                           "production > batch > preemptible; \"\" = the "
                           "configured default). With admission enabled a "
                           "full pool queues the job (phase \"queued\") "
                           "instead of refusing, and higher classes may "
                           "preempt strictly-lower ones"},
         "elastic": {**_BOOL, "description":
                     "elastic data-parallel gang: host loss / drain / "
                     "partial preemption SHRINK the gang to its surviving "
                     "hosts (never below minMembers) instead of killing "
                     "it, and a durable grow-back record re-admits the "
                     "lost members through the capacity market once "
                     "pressure lifts. Requires a single-slice whole-host "
                     "gang spanning >= 2 hosts. Job info then reports "
                     "membersDesired/membersActual/minMembers, lastResize "
                     "and growbackQueuePosition"},
         "minMembers": {**_INT, "description":
                        "smallest member (host) count an elastic gang may "
                        "shrink to (default 1; elastic only)"}},
        ["imageName", "jobName"]),
    "JobPatchChips": _obj({"chipCount": _INT, "acceleratorType": _STR}),
    "JobDelete": _obj({"force": _BOOL, "delStateAndVersionRecord": _BOOL}),
    "ServiceCreate": _obj(
        {"serviceName": {**_STR, "description": "base name, [a-zA-Z0-9_.]+"},
         "imageName": _STR,
         "chipsPerReplica": {**_INT, "description":
                             "chips per replica gang (each replica is a "
                             "distributed job)"},
         "acceleratorType": {**_STR, "description":
                             "alternative per-replica ask, e.g. \"v5e-8\""},
         "replicas": {**_INT, "description": "initial replica count"},
         "minReplicas": _INT, "maxReplicas": _INT,
         "priorityClass": {**_STR, "description":
                           "capacity-market class for every replica gang "
                           "(default production — traffic-driven scale-ups "
                           "may preempt batch/preemptible training)"},
         "binds": _arr({**_STR, "description": "\"src:dest\""}),
         "env": _arr(_STR), "cmd": _arr(_STR),
         "ttftP95TargetMs": {"type": "number", "description":
                             "SLO: scale up when worst replica TTFT p95 "
                             "exceeds this"},
         "queueDepthTarget": {**_INT, "description":
                              "SLO: scale up when worst replica queue "
                              "depth exceeds this"},
         "replicaCapacityRps": {"type": "number", "description":
                                "synthetic-load model: requests/s one "
                                "replica absorbs before breaching"},
         "metricsPath": {**_STR, "description":
                         "replica-reported SLO endpoint path scraped on "
                         "the coordinator port (the paged engine's SLO "
                         "export); \"\" = synthetic signals only"}},
        ["serviceName", "imageName"]),
    "ServicePatch": _obj(
        {"replicas": {**_INT, "description":
                      "MANUAL scale (audited; the autoscaler keeps ruling "
                      "afterwards)"},
         "minReplicas": _INT, "maxReplicas": _INT,
         "imageName": {**_STR, "description":
                       "weight/spec update: a new immutable service "
                       "version, rolled replica-by-replica"},
         "ttftP95TargetMs": {"type": "number"},
         "queueDepthTarget": _INT}),
    "ServiceLoad": _obj(
        {"rps": {"type": "number", "description":
                 "offered load (requests/s) for the synthetic signal "
                 "model; fake-runtime replicas synthesize TTFT/queue "
                 "signals from it"}},
        ["rps"]),
    "WorkflowStep": _obj(
        {"name": {**_STR, "description": "DAG node name, unique per "
                  "workflow, [a-zA-Z0-9_.]+"},
         "kind": {**_STR, "enum": ["job", "promote"], "default": "job",
                  "description": "job = run a gang to completion; "
                  "promote = roll `service` to `image` through the "
                  "Service rolling-update machinery"},
         "deps": _arr({**_STR, "description": "step names that must "
                       "succeed before this step launches"}),
         "imageName": _STR,
         "chipCount": {**_INT, "description": "gang chip ask "
                       "(kind job)"},
         "acceleratorType": {**_STR, "description":
                             "alternative ask, e.g. \"v5e-8\""},
         "binds": _arr({**_STR, "description": "\"src:dest\", on top "
                        "of the workflow's shared binds"}),
         "env": _arr(_STR), "cmd": _arr(_STR),
         "service": {**_STR, "description":
                     "promote target Service (kind promote)"},
         "maxRetries": {**_INT, "description":
                        "per-step retry budget; -1 = config "
                        "workflow_max_step_retries. Exhausting it "
                        "settles the WHOLE workflow \"failed\""}},
        ["name"]),
    "WorkflowCreate": _obj(
        {"workflowName": {**_STR, "description":
                          "base name, [a-zA-Z0-9_.]+"},
         "steps": _arr({"$ref": "#/components/schemas/WorkflowStep"}),
         "priorityClass": {**_STR, "description":
                           "capacity-market class every step gang admits "
                           "at (\"\" = config workflow_default_class)"},
         "binds": _arr({**_STR, "description":
                        "artifact hand-off volume: \"src:dest\" mounted "
                        "into EVERY job step"}),
         "cronIntervalS": {"type": "number", "description":
                           "re-fire the DAG every N seconds (0 = one "
                           "run, no cron)"},
         "cronCatchup": {**_STR, "enum": ["fire_once", "skip"],
                         "default": "skip", "description":
                         "missed-tick policy across downtime: skip = "
                         "drop missed ticks entirely (default); "
                         "fire_once = one catch-up run, the remaining "
                         "missed ticks counted skipped"},
         "cronEnabled": {**_BOOL, "description":
                         "false parks the cron without deleting the "
                         "workflow (default true when cronIntervalS > 0)"}},
        ["workflowName", "steps"]),
    "WorkflowPatch": _obj(
        {"cronIntervalS": {"type": "number"},
         "cronEnabled": _BOOL,
         "cronCatchup": {**_STR, "enum": ["fire_once", "skip"]}},
        desc="Cron retune only — steps are immutable once created "
             "(delete and recreate to change the DAG)"),
    "Rollback": _obj(
        {"version": {**_INT, "description": "stored version to roll back to"},
         "dataFrom": {**_STR, "enum": ["latest", "target"],
                      "default": "latest",
                      "description": "latest = keep newest data under the old "
                      "spec; target = snapshot restore from the retained "
                      "retired version"}},
        ["version"]),
}

#: (method, path, operationId, summary, request schema name | None)
_ROUTES: list[tuple[str, str, str, str, str | None]] = [
    ("POST", "/api/v1/containers", "runContainer",
     "Create a TPU (or cardless) container; allocates chips + host ports, "
     "persists the validated spec, returns name-0", "ContainerRun"),
    ("GET", "/api/v1/containers", "listContainers",
     "Paginated family list ({items: [{name, version}], continue, rev}): "
     "?limit= bounds raw keys scanned per page, ?continue= walks a "
     "rev-anchored consistent snapshot — a concurrent write under the "
     "prefix expires the token with HTTP 410 (code 10505), never a "
     "silent dup/skip", None),
    ("GET", "/api/v1/containers/{name}", "getContainerInfo",
     "Persisted spec + live runtime state; historical versions readable", None),
    ("DELETE", "/api/v1/containers/{name}", "deleteContainer",
     "Remove container versions, return chips/ports to schedulers",
     "ContainerDelete"),
    ("POST", "/api/v1/containers/{name}/execute", "executeContainer",
     "Exec a command in the running container, return demuxed stdout",
     "ContainerExecute"),
    ("PATCH", "/api/v1/containers/{name}/tpu", "patchContainerChips",
     "Rolling chip rescale: quiesce → copy data → start name-(N+1)",
     "ContainerPatchChips"),
    ("PATCH", "/api/v1/containers/{name}/gpu", "patchContainerChipsCompat",
     "Reference-compatible alias of /tpu", "ContainerPatchChips"),
    ("PATCH", "/api/v1/containers/{name}/volume", "patchContainerVolume",
     "Swap one bind onto name-(N+1) with data migration",
     "ContainerPatchVolume"),
    ("POST", "/api/v1/containers/{name}/stop", "stopContainer",
     "Graceful stop; chips stay allocated for restart", None),
    ("PATCH", "/api/v1/containers/{name}/restart", "restartContainer",
     "Restart; carded containers re-apply chips via a new version", None),
    ("POST", "/api/v1/containers/{name}/commit", "commitContainer",
     "Snapshot container filesystem to an image", "ContainerCommit"),
    ("GET", "/api/v1/containers/{name}/history", "getContainerHistory",
     "Stored version history of the family (per-version store — the "
     "rollback the reference advertises but cannot deliver)", None),
    ("PATCH", "/api/v1/containers/{name}/rollback", "rollbackContainer",
     "Roll forward to a NEW version built from an older version's spec; "
     "data from latest or from the retained target (snapshot restore)",
     "Rollback"),
    ("POST", "/api/v1/volumes", "createVolume",
     "Create a named, size-capped volume (overlay2/xfs analog)", "VolumeCreate"),
    ("GET", "/api/v1/volumes", "listVolumes",
     "Paginated volume-family list (same limit/continue contract as "
     "GET /api/v1/containers)", None),
    ("GET", "/api/v1/volumes/{name}", "getVolumeInfo",
     "Persisted volume spec + mountpoint", None),
    ("DELETE", "/api/v1/volumes/{name}", "deleteVolume",
     "Remove volume versions", "VolumeDelete"),
    ("PATCH", "/api/v1/volumes/{name}/size", "patchVolumeSize",
     "Resize via new volume + data copy; shrink below used size refused",
     "VolumeSize"),
    ("GET", "/api/v1/volumes/{name}/history", "getVolumeHistory",
     "Stored version history of the volume family", None),
    ("PATCH", "/api/v1/volumes/{name}/rollback", "rollbackVolume",
     "New version with an older version's size; data from latest or the "
     "retained target volume", "Rollback"),
    ("POST", "/api/v1/jobs", "runJob",
     "Place a distributed JAX job: one process container per host over an "
     "ICI-contiguous slice, coordinator + TPU_PROCESS_* env rendered", "JobRun"),
    ("GET", "/api/v1/jobs", "listJobs",
     "Paginated job-family list (same limit/continue contract as "
     "GET /api/v1/containers)", None),
    ("GET", "/api/v1/jobs/{name}", "getJobInfo",
     "Job spec + per-process live state + gang phase/restarts/failureReason; "
     "historical versions readable", None),
    ("DELETE", "/api/v1/jobs/{name}", "deleteJob",
     "Remove all job versions, free slices and ports", "JobDelete"),
    ("PATCH", "/api/v1/jobs/{name}/tpu", "patchJobChips",
     "Rolling rescale onto a new slice: create-new → quiesce-old → start-new",
     "JobPatchChips"),
    ("POST", "/api/v1/jobs/{name}/stop", "stopJob",
     "Quiesce every process container (checkpoint flush)", None),
    ("PATCH", "/api/v1/jobs/{name}/restart", "restartJob",
     "Whole-gang restart: stop every member (coordinator last), start in "
     "process order (coordinator first); resets the restart budget", None),
    ("POST", "/api/v1/services", "createService",
     "Create a replicated service: N replica gangs (each a distributed "
     "job at the service's priority class) behind one declarative record; "
     "the SLO-driven autoscaler owns the replica count", "ServiceCreate"),
    ("GET", "/api/v1/services", "listServices",
     "Every service: phase, replica counts, last autoscale decision; with "
     "?limit=/?continue= the same rev-anchored pagination contract as "
     "GET /api/v1/containers ({items, continue, rev})", None),
    ("GET", "/api/v1/services/{name}", "getServiceInfo",
     "Replica fleet detail (per-replica phase/queue position), SLO targets "
     "+ last observed signals, and the last autoscale decision with its "
     "reason — the no-log-reading scaling audit", None),
    ("PATCH", "/api/v1/services/{name}", "patchService",
     "Manual replica count (audited), min/max + SLO target retune, or an "
     "imageName weight update rolled replica-by-replica through the "
     "immutable-version replace sequencing", "ServicePatch"),
    ("DELETE", "/api/v1/services/{name}", "deleteService",
     "Tear down every replica gang (gang-ordered quiesce + one-batch "
     "release) and drop the service family", None),
    ("POST", "/api/v1/services/{name}/load", "setServiceLoad",
     "Synthetic traffic injection: offered requests/s for the fake-runtime "
     "signal model (bench/test load generators)", "ServiceLoad"),
    ("POST", "/api/v1/workflows", "createWorkflow",
     "Declare a DAG workflow: job steps (gangs admitted through the "
     "capacity market at the workflow's class) and promote steps (roll a "
     "Service through the rolling-update machinery), with shared "
     "artifact binds and optional cron re-fire. Every step transition is "
     "journaled with an idempotency key — a crashed daemon's replacement "
     "replays the DAG forward, never re-running a completed effect",
     "WorkflowCreate"),
    ("GET", "/api/v1/workflows", "listWorkflows",
     "Every workflow: phase, run counter, priority class, last "
     "transition; with ?limit=/?continue= the same rev-anchored "
     "pagination contract as GET /api/v1/containers "
     "({items, continue, rev})", None),
    ("GET", "/api/v1/workflows/{name}", "getWorkflowInfo",
     "Per-step status (state/attempts/error, live gang phase + queue "
     "position, promote target) and cron bookkeeping (lastFireTs, "
     "firedRuns, suppressed/skipped ticks) — the no-log-reading audit of "
     "where the DAG stands", None),
    ("PATCH", "/api/v1/workflows/{name}", "patchWorkflow",
     "Cron retune: interval, enable/disable, catch-up policy; steps are "
     "immutable once created", "WorkflowPatch"),
    ("DELETE", "/api/v1/workflows/{name}", "deleteWorkflow",
     "Tear down the DAG: mark deleting (durable), stop + delete every "
     "owned step gang, drop the family — mid-flight deletes are crash-"
     "safe (reconcile finishes a half-done teardown)", None),
    ("GET", "/api/v1/gateway", "getGatewayStatus",
     "Serving-gateway introspection: instance identity, the watch-fed "
     "routing table (per-endpoint breaker/EWMA/in-flight/generation), "
     "draining families, and the shed/retry/hedge/drain-ack counters; "
     "present only when gateway_enabled", None),
    ("GET", "/api/v1/resources/tpus", "getTpus",
     "Chip map: coords, owner, fragmentation (largest free block)", None),
    ("GET", "/api/v1/resources/gpus", "getTpusCompat",
     "Reference-compatible alias of /resources/tpus", None),
    ("GET", "/api/v1/resources/ports", "getUsedPorts",
     "Host-port scheduler state", None),
    ("GET", "/api/v1/resources/slices", "getSlices",
     "Pod view: host grid, per-host free chips + schedulability "
     "(cordon/down), active slice grants", None),
    ("POST", "/api/v1/hosts/{name}/cordon", "cordonHost",
     "No new placements on the host (persisted; survives daemon restarts); "
     "existing workloads untouched", None),
    ("POST", "/api/v1/hosts/{name}/uncordon", "uncordonHost",
     "Lift a cordon: the host is schedulable again", None),
    ("POST", "/api/v1/hosts/{name}/drain", "drainHost",
     "Cordon the host and migrate every gang off it (async via the work "
     "queue); no healthy capacity ⇒ the migration fails loudly and frees "
     "nothing", None),
    ("GET", "/api/v1/health/hosts", "getHostHealth",
     "Per-host probe state (healthy/suspect/down), circuit-breaker state, "
     "cordon/schedulability", None),
    ("GET", "/api/v1/events", "getHealthEvents",
     "Container liveness transitions (health watcher) merged with gang "
     "lifecycle events (job supervisor), host health transitions "
     "(host monitor), leadership transitions, informer degradations and "
     "slow-trace events — pre-sorted rings merged by timestamp; "
     "?traceId= filters to the events stamped by one trace", None),
    ("GET", "/api/v1/traces", "listTraces",
     "Recent trace summaries from the bounded in-process trace ring "
     "(telemetry/trace.py): root span name, span count, status "
     "(ok/error/lost), duration, cross-trace links — newest first, plus "
     "the ring's dropped/open-span counters; ?limit= bounds the page",
     None),
    ("GET", "/api/v1/traces/{traceId}", "getTrace",
     "One trace's full span tree: every span's name, parentId, attrs, "
     "monotonic start, duration, status and links — the 'where did this "
     "request's latency go' view. The trace id is the request's "
     "X-Request-Id (or traceparent trace-id), so a user-reported request "
     "id greps straight to its tree", None),
    ("GET", "/api/v1/health/containers", "getHealthStatus",
     "Per-container liveness + restart bookkeeping", None),
    ("GET", "/api/v1/health/jobs", "getJobHealth",
     "Per-job gang status: phase (running/restarting/migrating/failed/"
     "stopped), restart + migration budgets, dead/missing members, "
     "unreachable hosts, backoff remaining", None),
    ("GET", "/api/v1/leader", "getLeader",
     "HA control-plane election view: this replica's role (single/leader/"
     "standby), the lease holder, the monotonically increasing fencing "
     "epoch, and the lease deadline. Standbys answer every mutation with "
     "503 + this holder as the redirect hint. With read_cache=informer the "
     "watch-fed read-cache state rides along (synced, lastRev, watchLagMs, "
     "event/relist/cache-hit counters)", None),
    ("GET", "/api/v1/shards", "getShards",
     "Sharded writer plane map: every shard's lease holder, fencing epoch, "
     "deadline and advertise address (heartbeat-observed — zero store "
     "reads), plus which shards THIS replica holds. Mutations for a "
     "family another shard owns 503 with that shard's holder as the "
     "redirect hint. Unsharded deployments answer with one implicit "
     "shard carrying the single election's state", None),
    ("GET", "/api/v1/admission", "getAdmissionQueue",
     "Capacity-market admission queue: depth, per-class counts, entry "
     "positions/skip budgets, the configured priority ladder, and the "
     "admission/preemption counters (the same books /metrics exports). "
     "Queued jobs place automatically — backfilling holes, preempting "
     "strictly-lower-priority gangs, defragmenting via migration", None),
    ("GET", "/api/v1/queue", "getQueueStats",
     "Durable work-queue view: in-memory depth, journal lifecycle counts "
     "(pending/inflight/dead), degradation events and counters", None),
    ("GET", "/api/v1/dead-letters", "getDeadLetters",
     "Async tasks that exhausted retries — journaled in the KV store, so "
     "they survive daemon restarts (never silently dropped)", None),
    ("GET", "/api/v1/debug/deadletters", "getDeadLettersDebug",
     "Legacy alias of GET /api/v1/dead-letters", None),
    ("POST", "/api/v1/dead-letters/retry", "retryDeadLetters",
     "Re-enqueue every dead-lettered task (durable + ephemeral) with a "
     "fresh retry budget", None),
    ("GET", "/api/v1/reconcile", "reconcile",
     "Sweep KV desired state vs runtime actual state and repair drift "
     "(orphans, half-completed replaces, leaked chips/ports); "
     "?dryRun=true reports without mutating; ?mode=full|dirty|auto "
     "forces the anti-entropy full scan or the O(changes) watch-fed "
     "dirty pass — the report's `mode` names which ran", None),
    ("POST", "/api/v1/reconcile", "reconcilePost",
     "Canonical mutating reconcile trigger (same semantics as GET)", None),
    ("GET", "/api/v1/reconcile/events", "getReconcileEvents",
     "Recent drift-repair actions (ring buffer, newest last)", None),
    ("POST", "/api/v1/compact", "compactHistory",
     "Run one history-compaction pass now (history_retention_versions > "
     "0): trim version records past retention — never the latest pointer "
     "or a live-referenced version — purge settled admission records, "
     "sweep acked queue markers; returns the trim report", None),
    ("GET", "/api/v1/debug/threads", "getThreadDump",
     "Per-thread stack dump (the pprof-goroutine analog): hung copies and "
     "deadlocked family locks are visible here", None),
    ("GET", "/healthz", "healthz",
     "Process liveness + HA role; with read_cache=informer also the "
     "watch-fed read-cache health (a degraded informer still serves via "
     "read-through fallback, but slower — visible here)", None),
    ("GET", "/metrics", "metrics",
     "Prometheus text format: request/latency/chip/port/queue gauges", None),
]


#: GET list endpoints carrying the limit/continue pagination contract
_PAGED_LIST_PATHS = {"/api/v1/containers", "/api/v1/volumes",
                     "/api/v1/jobs", "/api/v1/services",
                     "/api/v1/workflows"}


def build_spec() -> dict:
    paths: dict[str, dict] = {}
    for method, path, op_id, summary, req_schema in _ROUTES:
        if path == "/metrics":
            # the one non-envelope endpoint: Prometheus exposition text
            response = {
                "description": "Prometheus text exposition format",
                "content": {"text/plain": {"schema": {"type": "string"}}},
            }
        else:
            response = {
                "description": _ENVELOPE_NOTE,
                "content": {"application/json": {
                    "schema": {"$ref": "#/components/schemas/Envelope"}}},
            }
        op: dict[str, Any] = {
            "operationId": op_id,
            "summary": summary,
            "responses": {"200": response},
        }
        if "{name}" in path:
            op["parameters"] = [{
                "name": "name", "in": "path", "required": True,
                "schema": _STR,
                "description": "base name (latest version) or versioned "
                               "name-N (optimistic concurrency check)",
            }]
        if "{traceId}" in path:
            op["parameters"] = [{
                "name": "traceId", "in": "path", "required": True,
                "schema": _STR,
                "description": "trace id — the request's X-Request-Id or "
                               "traceparent trace-id",
            }]
        if path == "/api/v1/events":
            op["parameters"] = [
                {"name": "limit", "in": "query", "required": False,
                 "schema": _INT,
                 "description": "max events returned (default 100)"},
                {"name": "traceId", "in": "query", "required": False,
                 "schema": _STR,
                 "description": "only events stamped by this trace — "
                                "joins the event rings to "
                                "/api/v1/traces/{traceId}"},
            ]
        if method == "GET" and path in _PAGED_LIST_PATHS:
            op["parameters"] = [
                {"name": "limit", "in": "query", "required": False,
                 "schema": _INT,
                 "description": "max raw keys scanned per page (clamped "
                                "to list_max_limit; 0/absent = the "
                                "configured list_default_limit, whose 0 "
                                "default keeps the legacy unbounded "
                                "single-page scan)"},
                {"name": "continue", "in": "query", "required": False,
                 "schema": _STR,
                 "description": "opaque token from the previous page; the "
                                "walk serves one rev-anchored consistent "
                                "snapshot or fails HTTP 410 "
                                "ContinueExpired (code 10505)"},
            ]
        if req_schema:
            op["requestBody"] = {"required": True, "content": {
                "application/json": {"schema": {
                    "$ref": f"#/components/schemas/{req_schema}"}}}}
        paths.setdefault(path, {})[method.lower()] = op
    return {
        "openapi": "3.1.0",
        "info": {
            "title": "tpu-docker-api",
            "version": "1.0.0",
            "description": (
                "TPU-native container control plane: versioned rolling-replace "
                "containers and volumes (gpu-docker-api parity) plus "
                "multi-host distributed JAX jobs over ICI-contiguous slices. "
                + _ENVELOPE_NOTE),
        },
        "paths": paths,
        "components": {"schemas": _SCHEMAS},
    }


def route_inventory() -> set[tuple[str, str]]:
    """(METHOD, path) pairs — consumed by the drift test."""
    return {(m, p) for m, p, *_ in _ROUTES}


if __name__ == "__main__":
    print(json.dumps(build_spec(), indent=2, sort_keys=False))
