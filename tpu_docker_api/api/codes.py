"""Response codes.

Parity: reference ``internal/api/code.go`` — app-level codes carried in a
uniform envelope with HTTP 200 (their messages are Chinese; ours English).
Codes live on the exception classes in ``tpu_docker_api.errors``; this module
adds the non-error codes and the fallback messages.
"""

from __future__ import annotations

SUCCESS = 200
SERVER_ERROR = 500
BAD_REQUEST = 10001

MESSAGES: dict[int, str] = {
    SUCCESS: "success",
    SERVER_ERROR: "internal server error",
    BAD_REQUEST: "bad request",
    10201: "no patch required",
    10202: "version does not match the latest",
    10301: "container already exists",
    10302: "container does not exist",
    10401: "volume already exists",
    10402: "volume does not exist",
    10403: "bytes in use exceed the requested size",
    10501: "not found in state store",
    10502: "state store unavailable",
    10503: "guarded write lost its compare",
    10506: "state store degraded; mutations held until it heals",
    10601: "not enough free TPU chips",
    10602: "not enough free host ports",
    10603: "unknown TPU topology",
    10701: "host engine unreachable",
    10801: "work queue saturated; retry later",
    10802: "work queue closed",
    10901: "not the leader; send mutations to the lease holder",
}


def message(code: int) -> str:
    return MESSAGES.get(code, "unknown error")
