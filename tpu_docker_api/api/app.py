"""HTTP server: routing, validation, error mapping.

Parity: the reference's gin routes (api/container.go:19-38, volume.go:19-28,
resource.go:12-15) on a stdlib ThreadingHTTPServer — the reference's 14
routes + health, plus the TPU-native additions: 6 ``/api/v1/jobs/*`` routes
(distributed multi-host jobs) and ``GET /api/v1/resources/slices``.
Name-format validation follows the reference: base names must not contain
``-`` on create (api/container.go:66-70); other ops accept ``name`` (latest)
or ``name-version`` (optimistic check). The reference's six fall-through
validation bugs (missing ``return`` after ResponseError, SURVEY.md appendix)
are structurally impossible here: validation raises.

Route table:

    POST   /api/v1/containers                  run container
    GET    /api/v1/containers/{name}           info
    DELETE /api/v1/containers/{name}           delete
    POST   /api/v1/containers/{name}/execute   exec
    PATCH  /api/v1/containers/{name}/tpu       patch chip count (alias: /gpu)
    PATCH  /api/v1/containers/{name}/volume    patch bind
    POST   /api/v1/containers/{name}/stop      stop
    PATCH  /api/v1/containers/{name}/restart   restart
    POST   /api/v1/containers/{name}/commit    commit to image
    GET    /api/v1/containers/{name}/history   stored version history
    PATCH  /api/v1/containers/{name}/rollback  roll to an older version's spec
    POST   /api/v1/volumes                     create volume
    GET    /api/v1/volumes/{name}              info
    DELETE /api/v1/volumes/{name}              delete
    PATCH  /api/v1/volumes/{name}/size         resize
    GET    /api/v1/volumes/{name}/history      stored version history
    PATCH  /api/v1/volumes/{name}/rollback     roll to an older version's size
    POST   /api/v1/services                    create a replicated service
    GET    /api/v1/services                    list services
    GET    /api/v1/services/{name}             replica fleet + last autoscale decision
    PATCH  /api/v1/services/{name}             manual scale / policy / spec roll
    DELETE /api/v1/services/{name}             tear down every replica
    POST   /api/v1/services/{name}/load        synthetic offered-load injection
    POST   /api/v1/workflows                   create a DAG workflow (steps + cron)
    GET    /api/v1/workflows                   list workflows
    GET    /api/v1/workflows/{name}            per-step status + cron state
    PATCH  /api/v1/workflows/{name}            cron enable/interval/catch-up
    DELETE /api/v1/workflows/{name}            tear down the DAG + step gangs
    GET    /api/v1/resources/tpus              chip scheduler view (alias: /gpus)
    GET    /api/v1/resources/ports             port scheduler view
    POST   /api/v1/hosts/{name}/cordon         no new placements on the host
    POST   /api/v1/hosts/{name}/uncordon       lift the cordon
    POST   /api/v1/hosts/{name}/drain          cordon + migrate gangs off (async)
    GET    /api/v1/health/hosts                per-host probe + breaker state
    GET    /api/v1/leader                      election role, holder, epoch, lease deadline
    GET    /api/v1/queue                       durable work-queue stats
    GET    /api/v1/dead-letters                durable dead-letter set
    POST   /api/v1/dead-letters/retry          re-enqueue the dead letters
    GET    /api/v1/debug/threads               per-thread stack dump (pprof analog)
    GET    /healthz
"""

from __future__ import annotations

import collections
import heapq
import json
import logging
import math
import re
import threading
import time
import urllib.parse
import uuid
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

from tpu_docker_api import errors
from tpu_docker_api.buildinfo import build_info
from tpu_docker_api.api import codes, response
from tpu_docker_api.schemas.container import (
    Bind,
    ContainerCommit,
    ContainerDelete,
    ContainerExecute,
    ContainerPatchChips,
    ContainerPatchVolume,
    ContainerRollback,
    ContainerRun,
)
from tpu_docker_api.schemas.volume import (
    VolumeCreate,
    VolumeDelete,
    VolumeRollback,
    VolumeSize,
)
from tpu_docker_api.service.container import ContainerService
from tpu_docker_api.service.volume import VolumeService

log = logging.getLogger(__name__)

from tpu_docker_api.state.keys import BASE_NAME_RE as _NAME_RE

# base-name charset + optional "-N" version suffix, derived so the two rules
# cannot drift
_VERSIONED_RE = re.compile(_NAME_RE.pattern.rstrip("$") + r"(-\d+)?$")


def _validate_base_name(name: str) -> None:
    """Create-time rule: nonempty, no '-' (reference api/container.go:66-70)."""
    if not name or not _NAME_RE.match(name):
        raise errors.BadRequest(
            f"invalid base name {name!r}: must be nonempty without '-'"
        )


def _validate_ref_name(name: str) -> None:
    if not name or not _VERSIONED_RE.match(name):
        raise errors.BadRequest(f"invalid container/volume name {name!r}")


#: resources whose mutation routes carry a family name (the shard unit)
_FAMILY_SEGMENTS = frozenset(("containers", "volumes", "jobs", "services",
                              "workflows"))
#: create bodies carry the family name under the resource's own field
_CREATE_NAME_FIELDS = ("containerName", "volumeName", "jobName",
                       "serviceName", "workflowName")


def _shard_for_request(plane, path: str, raw: bytes) -> int:
    """Owning shard for a mutation: family routes shard by the (version-
    stripped) name in the path, creates by the name in the body; anything
    else — host ops, reconcile, dead-letter retry — is shard 0. Unparsable
    input classifies as shard 0 too: the gate must never mask the
    validation error the handler would raise."""
    from tpu_docker_api.state import keys

    seg = path.split("/")
    if len(seg) < 4 or seg[3] not in _FAMILY_SEGMENTS:
        return 0
    if len(seg) >= 5 and seg[4]:
        base, _ = keys.split_versioned_name(seg[4])
        return plane.map.shard_of(base)
    try:
        body = json.loads(raw) if raw else {}
    except ValueError:
        return 0
    if isinstance(body, dict):
        for field in _CREATE_NAME_FIELDS:
            name = body.get(field)
            if isinstance(name, str) and name:
                return plane.map.shard_of(name)
    return 0


class Router:
    """Tiny method+pattern router; patterns use ``{name}`` segments. Carries
    its own metrics registry so each server instance exposes only its own
    series at /metrics."""

    def __init__(self, metrics=None) -> None:
        from tpu_docker_api.telemetry.metrics import MetricsRegistry

        self._routes: list[tuple[str, re.Pattern, str, callable]] = []
        self.metrics = metrics if metrics is not None else MetricsRegistry()
        #: HA role gate; build_router sets it (None = no gating)
        self.leader_elector = None
        #: sharded writer plane (service/shard.py); build_router sets it —
        #: when present, the mutation gate routes per shard instead
        self.shard_plane = None
        #: trace sink (telemetry/trace.py); build_router sets it (None =
        #: request tracing off)
        self.tracer = None

    def add(self, method: str, pattern: str, handler) -> None:
        regex = re.compile(
            "^" + re.sub(r"\{(\w+)\}", r"(?P<\1>[^/]+)", pattern) + "$"
        )
        self._routes.append((method, regex, pattern, handler))

    def match(self, method: str, path: str):
        """(handler, path_params, route_pattern) or None. The pattern is the
        low-cardinality metrics label (never the raw path)."""
        for m, regex, pattern, handler in self._routes:
            if m != method:
                continue
            match = regex.match(path)
            if match:
                return handler, match.groupdict(), pattern
        return None

    def dispatch(self, method: str, path: str, body: dict):
        found = self.match(method, path)
        if found is None:
            raise errors.BadRequest(f"no route for {method} {path}")
        handler, params, _ = found
        return handler(body=body, **params)


def build_router(container_svc: ContainerService, volume_svc: VolumeService,
                 chip_scheduler, port_scheduler, work_queue=None,
                 health_watcher=None, metrics=None,
                 job_svc=None, pod_scheduler=None, reconciler=None,
                 job_supervisor=None, host_monitor=None,
                 leader_elector=None, shard_plane=None,
                 informer=None, fanout=None,
                 admission=None, serving=None, workflow_svc=None,
                 compactor=None,
                 gateway=None,
                 store_health=None,
                 list_default_limit: int = 0,
                 list_max_limit: int = 5000,
                 tracer=None) -> Router:
    from tpu_docker_api.state import pager
    from tpu_docker_api.state.keys import Resource

    r = Router(metrics=metrics)
    r.tracer = tracer

    def _page_params(body) -> tuple[int, str]:
        """(effective limit, continue token) for a list request. No (or
        non-positive) ?limit means the configured default — 0 keeps the
        legacy unbounded single-page scan; an explicit limit is clamped
        to list_max_limit."""
        try:
            # query params arrive as strings; a JSON body may send a number
            limit = int(body.get("limit", 0))
        except (TypeError, ValueError):
            raise errors.BadRequest("limit must be an integer") from None
        limit = min(limit, list_max_limit) if limit > 0 else list_default_limit
        token = str(body.get("continue", "") or "")
        return limit, token

    def _family_list(resource: Resource):
        def handler(body, **_):
            limit, token = _page_params(body)
            # the read-switch store: one bounded rev-anchored page per
            # request (state/pager.py) — never an O(objects) scan unless
            # the caller explicitly asked for the legacy unbounded shape
            return pager.list_families(
                container_svc.store.kv, resource, limit=limit, token=token)
        return handler
    # HA role gate (service/leader.py): on a standby replica every non-GET
    # request is answered 503 + the leader hint BEFORE dispatch — reads
    # stay local, mutations belong to the lease holder. None (single-
    # process, or election disabled) gates nothing.
    r.leader_elector = leader_elector
    # sharded writer plane: same contract, per shard — a mutation is
    # answered 503 + the OWNING shard's leader hint unless this process
    # holds that shard's lease (api-layer routing is a redirect, never a
    # proxy: the client retries against the advertised holder)
    r.shard_plane = shard_plane
    # store brownout gate (service/store_health.py): while the store is in
    # outage mode every mutation is refused up front — typed 503 +
    # Retry-After, zero store round trips — except the single-flight heal
    # probe; reads served from the informer mirror are marked stale
    # (envelope field + X-Stale-Read header). None gates nothing.
    r.store_health = store_health

    # -- containers (reference api/container.go:19-38) ---------------------------

    def run(body, **_):
        req = ContainerRun.from_dict(body)
        if not req.image_name:
            raise errors.BadRequest("imageName is required")
        _validate_base_name(req.container_name)
        if req.chip_count < 0:
            raise errors.BadRequest("chipCount must be >= 0")
        return container_svc.run_container(req)

    def c_info(body, name):
        _validate_ref_name(name)
        return container_svc.get_container_info(name)

    def c_delete(body, name):
        _validate_ref_name(name)
        container_svc.delete_container(name, ContainerDelete(
            force=bool(body.get("force", True)),
            del_etcd_info_and_version_record=bool(
                body.get("delEtcdInfoAndVersionRecord", False)),
        ))
        return None

    def c_exec(body, name):
        _validate_ref_name(name)
        cmd = body.get("cmd", [])
        if not cmd:
            raise errors.BadRequest("cmd is required")
        out = container_svc.execute_container(
            name, ContainerExecute(work_dir=body.get("workDir", ""), cmd=list(cmd))
        )
        return {"stdout": out}

    def c_patch_chips(body, name):
        _validate_ref_name(name)
        if "chipCount" not in body and "gpuCount" not in body:
            raise errors.BadRequest("chipCount is required")
        want = errors.as_int(
            body.get("chipCount", body.get("gpuCount", 0)), "chipCount")
        return container_svc.patch_container_chips(
            name, ContainerPatchChips(chip_count=want)
        )

    def c_patch_volume(body, name):
        _validate_ref_name(name)
        old, new = body.get("oldBind"), body.get("newBind")
        if not old or not new:
            raise errors.BadRequest("oldBind and newBind are required")
        return container_svc.patch_container_volume(name, ContainerPatchVolume(
            old_bind=Bind(old["src"], old["dest"]),
            new_bind=Bind(new["src"], new["dest"]),
        ))

    def c_stop(body, name):
        _validate_ref_name(name)
        container_svc.stop_container(name)
        return None

    def c_restart(body, name):
        _validate_ref_name(name)
        return container_svc.restart_container(name)

    def c_commit(body, name):
        _validate_ref_name(name)
        image_id = container_svc.commit_container(
            name, ContainerCommit(new_image_name=body.get("newImageName", ""))
        )
        return {"imageId": image_id}

    def c_history(body, name):
        _validate_ref_name(name)
        return container_svc.get_container_history(name)

    def _version_of(body):
        if "version" not in body:
            raise errors.BadRequest("version is required")
        try:
            return int(body["version"])
        except (TypeError, ValueError):
            raise errors.BadRequest("version must be an integer")

    def c_rollback(body, name):
        _validate_ref_name(name)
        return container_svc.rollback_container(name, ContainerRollback(
            version=_version_of(body),
            data_from=body.get("dataFrom", "latest"),
        ))

    r.add("POST", "/api/v1/containers", run)
    # paginated family lists ({items: [{name, version}], continue, rev};
    # ?limit= + ?continue= walk a rev-anchored snapshot, HTTP 410
    # ContinueExpired when the prefix moved under the walk)
    r.add("GET", "/api/v1/containers", _family_list(Resource.CONTAINERS))
    r.add("GET", "/api/v1/containers/{name}", c_info)
    r.add("DELETE", "/api/v1/containers/{name}", c_delete)
    r.add("POST", "/api/v1/containers/{name}/execute", c_exec)
    r.add("PATCH", "/api/v1/containers/{name}/tpu", c_patch_chips)
    r.add("PATCH", "/api/v1/containers/{name}/gpu", c_patch_chips)  # reference path
    r.add("PATCH", "/api/v1/containers/{name}/volume", c_patch_volume)
    r.add("POST", "/api/v1/containers/{name}/stop", c_stop)
    r.add("PATCH", "/api/v1/containers/{name}/restart", c_restart)
    r.add("POST", "/api/v1/containers/{name}/commit", c_commit)
    r.add("GET", "/api/v1/containers/{name}/history", c_history)
    r.add("PATCH", "/api/v1/containers/{name}/rollback", c_rollback)

    # -- volumes (reference api/volume.go:19-28) ---------------------------------

    def v_create(body, **_):
        name = body.get("volumeName", "")
        _validate_base_name(name)
        return volume_svc.create_volume(
            VolumeCreate(volume_name=name, size=body.get("size", ""))
        )

    def v_info(body, name):
        _validate_ref_name(name)
        return volume_svc.get_volume_info(name)

    def v_delete(body, name):
        _validate_ref_name(name)
        volume_svc.delete_volume(name, VolumeDelete(
            del_etcd_info_and_version_record=bool(
                body.get("delEtcdInfoAndVersionRecord", False)),
        ))
        return None

    def v_patch_size(body, name):
        _validate_ref_name(name)
        size = body.get("size", "")
        if not size:
            raise errors.BadRequest("size is required")
        return volume_svc.patch_volume_size(name, VolumeSize(size=size))

    def v_history(body, name):
        _validate_ref_name(name)
        return volume_svc.get_volume_history(name)

    def v_rollback(body, name):
        _validate_ref_name(name)
        return volume_svc.rollback_volume(name, VolumeRollback(
            version=_version_of(body),
            data_from=body.get("dataFrom", "latest"),
        ))

    r.add("POST", "/api/v1/volumes", v_create)
    r.add("GET", "/api/v1/volumes", _family_list(Resource.VOLUMES))
    r.add("GET", "/api/v1/volumes/{name}", v_info)
    r.add("DELETE", "/api/v1/volumes/{name}", v_delete)
    r.add("PATCH", "/api/v1/volumes/{name}/size", v_patch_size)
    r.add("GET", "/api/v1/volumes/{name}/history", v_history)
    r.add("PATCH", "/api/v1/volumes/{name}/rollback", v_rollback)

    # -- distributed jobs (TPU-native addition: multi-host slices,
    #    SURVEY.md hard part #3; no reference analog) -----------------------------

    if job_svc is not None:
        from tpu_docker_api.schemas.job import JobDelete, JobPatchChips, JobRun

        def j_run(body, **_):
            req = JobRun.from_dict(body)
            _validate_base_name(req.job_name)
            return job_svc.run_job(req)

        def j_info(body, name):
            _validate_ref_name(name)
            return job_svc.get_job_info(name)

        def j_delete(body, name):
            _validate_ref_name(name)
            job_svc.delete_job(name, JobDelete.from_dict(body))
            return None

        def j_patch_chips(body, name):
            _validate_ref_name(name)
            return job_svc.patch_job_chips(name, JobPatchChips.from_dict(body))

        def j_stop(body, name):
            _validate_ref_name(name)
            job_svc.stop_job(name)
            return None

        def j_restart(body, name):
            _validate_ref_name(name)
            return job_svc.restart_job(name)

        r.add("POST", "/api/v1/jobs", j_run)
        r.add("GET", "/api/v1/jobs", _family_list(Resource.JOBS))
        r.add("GET", "/api/v1/jobs/{name}", j_info)
        r.add("DELETE", "/api/v1/jobs/{name}", j_delete)
        r.add("PATCH", "/api/v1/jobs/{name}/tpu", j_patch_chips)
        r.add("POST", "/api/v1/jobs/{name}/stop", j_stop)
        r.add("PATCH", "/api/v1/jobs/{name}/restart", j_restart)
    if admission is not None:
        # capacity market: queue depth, per-class counts, positions and the
        # preemption/admission counters (the same books /metrics exports)
        r.add("GET", "/api/v1/admission",
              lambda body, **_: admission.status_view())

    # -- Services (declarative replicated serving, service/serving.py) ------------

    if serving is not None:
        from tpu_docker_api.schemas.service import ServiceCreate, ServicePatch

        def s_create(body, **_):
            req = ServiceCreate.from_dict(body)
            _validate_base_name(req.service_name)
            return serving.create_service(req)

        def s_info(body, name):
            _validate_ref_name(name)
            return serving.service_info(name)

        def s_patch(body, name):
            _validate_ref_name(name)
            return serving.patch_service(name, ServicePatch.from_dict(body))

        def s_delete(body, name):
            _validate_ref_name(name)
            serving.delete_service(name)
            return None

        def s_load(body, name):
            # synthetic traffic injection (fake-runtime replicas): the
            # load generator states offered rps; the autoscaler's next
            # tick synthesizes per-replica SLO signals from it
            _validate_ref_name(name)
            if "rps" not in body:
                raise errors.BadRequest("rps is required")
            try:
                rps = float(body["rps"])
            except (TypeError, ValueError):
                raise errors.BadRequest("rps must be a number") from None
            return serving.set_offered_load(name, rps)

        def s_list(body, **_):
            limit, token = _page_params(body)
            if limit <= 0 and not token:
                # legacy shape: the unbounded flat list
                return serving.list_services()
            page = pager.list_families(
                container_svc.store.kv, Resource.SERVICES,
                limit=limit, token=token)
            items = []
            for it in page["items"]:
                s = serving.service_summary(it["name"])
                if s is not None:
                    items.append(s)
            return {"items": items, "continue": page["continue"],
                    "rev": page["rev"]}

        r.add("POST", "/api/v1/services", s_create)
        r.add("GET", "/api/v1/services", s_list)
        r.add("GET", "/api/v1/services/{name}", s_info)
        r.add("PATCH", "/api/v1/services/{name}", s_patch)
        r.add("DELETE", "/api/v1/services/{name}", s_delete)
        r.add("POST", "/api/v1/services/{name}/load", s_load)

    # -- Workflows (durable DAG orchestration, service/workflow.py) ---------------

    if workflow_svc is not None:
        from tpu_docker_api.schemas.workflow import (WorkflowCreate,
                                                     WorkflowPatch)

        def w_create(body, **_):
            req = WorkflowCreate.from_dict(body)
            _validate_base_name(req.workflow_name)
            return workflow_svc.create_workflow(req)

        def w_info(body, name):
            _validate_ref_name(name)
            return workflow_svc.workflow_info(name)

        def w_patch(body, name):
            _validate_ref_name(name)
            return workflow_svc.patch_workflow(name,
                                               WorkflowPatch.from_dict(body))

        def w_delete(body, name):
            _validate_ref_name(name)
            workflow_svc.delete_workflow(name)
            return None

        def w_list(body, **_):
            limit, token = _page_params(body)
            if limit <= 0 and not token:
                # legacy shape: the unbounded flat list
                return workflow_svc.list_workflows()
            page = pager.list_families(
                container_svc.store.kv, Resource.WORKFLOWS,
                limit=limit, token=token)
            items = []
            for it in page["items"]:
                s = workflow_svc.workflow_summary(it["name"])
                if s is not None:
                    items.append(s)
            return {"items": items, "continue": page["continue"],
                    "rev": page["rev"]}

        r.add("POST", "/api/v1/workflows", w_create)
        r.add("GET", "/api/v1/workflows", w_list)
        r.add("GET", "/api/v1/workflows/{name}", w_info)
        r.add("PATCH", "/api/v1/workflows/{name}", w_patch)
        r.add("DELETE", "/api/v1/workflows/{name}", w_delete)
    if pod_scheduler is not None:
        r.add("GET", "/api/v1/resources/slices",
              lambda body, **_: pod_scheduler.status())

        # -- host failure domains (docs/robustness.md): cordon/uncordon are
        #    pure scheduler state (persisted in KV); drain + health need
        #    the monitor --------------------------------------------------------
        def h_cordon(body, name):
            if host_monitor is not None:
                return host_monitor.cordon(name)
            return pod_scheduler.cordon_host(name)

        def h_uncordon(body, name):
            if host_monitor is not None:
                return host_monitor.uncordon(name)
            return pod_scheduler.uncordon_host(name)

        r.add("POST", "/api/v1/hosts/{name}/cordon", h_cordon)
        r.add("POST", "/api/v1/hosts/{name}/uncordon", h_uncordon)
    if host_monitor is not None:
        # async drain: cordon now, gang migrations ride the work queue
        r.add("POST", "/api/v1/hosts/{name}/drain",
              lambda body, name: host_monitor.drain(name))
        # per-host probe state + breaker + schedulability
        r.add("GET", "/api/v1/health/hosts",
              lambda body, **_: host_monitor.status_view())

    # -- resource views (reference api/resource.go:12-29) ------------------------

    r.add("GET", "/api/v1/resources/tpus", lambda body, **_: chip_scheduler.status())
    r.add("GET", "/api/v1/resources/gpus", lambda body, **_: chip_scheduler.status())
    r.add("GET", "/api/v1/resources/ports", lambda body, **_: port_scheduler.status())

    def healthz(body, **_):
        # role surfaced next to liveness: load balancers route mutations by
        # it, and "single" keeps the no-election deployment unambiguous
        if shard_plane is not None:
            held = sorted(shard_plane.held)
            role = "leader" if held else "standby"
        else:
            role = ("single" if leader_elector is None
                    else ("leader" if leader_elector.is_leader
                          else "standby"))
        out = {"status": "ok", "role": role, **build_info()}
        if shard_plane is not None:
            # which slice of the writer plane this replica carries: load
            # balancers shard mutations by it, operators eyeball spread
            out["shards"] = {"count": shard_plane.map.count, "held": held}
        if informer is not None:
            # read-path health rides liveness: a standby whose informer is
            # degraded still serves (read-through fallback) but slower —
            # load balancers and operators see it here
            out["informer"] = informer.status_view()
        if fanout is not None:
            # fan-out saturation (workers/in-flight/batches): a pool pinned
            # at its worker cap is the "lifecycle flows are serializing
            # again" smell, surfaced next to liveness
            out["fanout"] = fanout.status_view()
        if admission is not None:
            # capacity-market health: queue depth + the admission/
            # preemption counters read back from the metrics registry
            # (one set of books — /healthz and /metrics cannot disagree)
            out["admission"] = admission.health_view()
        if reconciler is not None:
            dirty = reconciler.dirty_view()
            if dirty is not None:
                # event-driven reconcile health: pending dirty families +
                # whether the next pass is forced full (startup/relist)
                out["reconcileDirty"] = dirty
        if job_svc is not None:
            pools = {}
            for hid, host in sorted(job_svc.pod.hosts.items()):
                try:
                    view = host.runtime.pool_view()
                except AttributeError:
                    continue  # engine without a connection pool (fake)
                pools[hid] = view
            if pools:
                out["enginePools"] = pools
        if gateway is not None:
            # serving-ingress health next to liveness: in-flight load,
            # retry-budget level, breaker/shed counters and the routing
            # table's per-endpoint view (one set of books — identical to
            # the gateway listener's own /healthz)
            out["gateway"] = gateway.status_view()
        if store_health is not None:
            # the store failure domain rides liveness too: mode (healthy/
            # degraded/outage), failure streak and the op/outage counters
            # read back from the registry — load balancers can stop
            # routing mutations at a replica whose store is browned out
            out["storeHealth"] = store_health.status_view()
        return out

    r.add("GET", "/healthz", healthz)

    def leader_view(body, **_):
        def _with_store(out):
            # lease health and store health are one story: a leader whose
            # renewals are failing IS a store brownout in progress
            if store_health is not None:
                out["storeHealth"] = store_health.status_view()
            return out

        if shard_plane is not None:
            # shard-aware: the single-lease fields generalize to the full
            # per-shard table (satellite of docs/robustness.md "Sharded
            # writer plane"); holder/epoch/deadline come from each
            # elector's heartbeat-observed cache — zero store reads
            out = shard_plane.status_view()
            out["election"] = True
            out["sharded"] = True
            if informer is not None:
                out["informer"] = informer.status_view()
            return _with_store(out)
        if leader_elector is None:
            return _with_store(
                {"election": False, "role": "single", "accepting": True,
                 "selfId": None, "holderId": None, "epoch": None,
                 "deadline": None, "advertise": "", "ttlS": None,
                 "fencingEpoch": 0})
        out = leader_elector.status_view()
        if informer is not None:
            out["informer"] = informer.status_view()
        return _with_store(out)

    r.add("GET", "/api/v1/leader", leader_view)

    def shards_view(body, **_):
        if shard_plane is None:
            # unsharded deployments still answer: one implicit shard whose
            # lease state is the single elector's (or a bare single role)
            out = {"sharded": False, "shardCount": 1,
                   "held": [], "shards": []}
            if leader_elector is not None:
                sv = leader_elector.status_view()
                sv["shard"] = 0
                out["shards"] = [sv]
                if leader_elector.is_leader:
                    out["held"] = [0]
            return out
        out = shard_plane.status_view()
        out["sharded"] = True
        return out

    r.add("GET", "/api/v1/shards", shards_view)
    if gateway is not None:
        # serving-gateway introspection (docs/robustness.md "Serving
        # gateway"): instance identity, the watch-fed routing table with
        # per-endpoint breaker/drain/in-flight state, budget levels and
        # the shed/retry/hedge counters — read straight from the gateway
        # engine, zero store reads
        r.add("GET", "/api/v1/gateway",
              lambda body, **_: gateway.status_view())
    if (health_watcher is not None or job_supervisor is not None
            or host_monitor is not None or leader_elector is not None
            or shard_plane is not None
            or informer is not None or admission is not None
            or serving is not None or tracer is not None
            or gateway is not None or store_health is not None):
        # one events ring for the operator: container liveness transitions
        # (health watcher) merged with gang lifecycle events (job
        # supervisor), host health transitions (host monitor), leadership
        # transitions (elector), informer degradations and capacity-market
        # admissions/preemptions, ordered by timestamp (SURVEY.md §5.3)
        def h_events(body, **_):
            try:
                limit = int(body.get("limit", 100))
            except (TypeError, ValueError):
                raise errors.BadRequest("limit must be an integer") from None
            if limit <= 0:
                return []
            # each source ring is already time-ordered (append-only deques
            # stamped at append time), so MERGE the sorted rings instead of
            # re-sorting the concatenation on every request — this is a hot
            # observability path under bench load, and n·log(n) over the
            # combined rings per GET was pure waste
            # ?traceId= joins events to traces (every ring entry appended
            # under an active span carries the id) — filtered BEFORE the
            # tail so the caller gets up to `limit` MATCHING events, not
            # whatever survives a blind truncation. A filtered request
            # must also fetch each ring at FULL depth: per-ring `limit`
            # truncation happens before the filter, so a trace's events
            # older than the newest `limit` entries of their ring would
            # silently vanish from the join
            trace_id = str(body.get("traceId", "") or "")
            per_ring = 1 << 20 if trace_id else limit
            rings = [src.events_view(limit=per_ring)
                     for src in (health_watcher, job_supervisor,
                                 host_monitor, leader_elector, shard_plane,
                                 informer, admission, serving, workflow_svc,
                                 tracer, gateway, store_health)
                     if src is not None]
            merged = heapq.merge(*rings, key=lambda e: e.get("ts", 0))
            if trace_id:
                merged = (e for e in merged
                          if e.get("traceId") == trace_id)
            # a bounded tail, not a materialize-then-slice: the merge is
            # lazy, so pushing it through a maxlen deque keeps the cost
            # O(total ring entries) time and O(limit) MEMORY — building
            # list(merged) first held every ring's worth of dicts live
            # per request on a hot observability path
            tail: collections.deque = collections.deque(merged, maxlen=limit)
            return list(tail)

        r.add("GET", "/api/v1/events", h_events)
    if health_watcher is not None:
        r.add("GET", "/api/v1/health/containers",
              lambda body, **_: health_watcher.status_view())
    if job_supervisor is not None:
        # per-gang phase / restart budget / backoff state
        r.add("GET", "/api/v1/health/jobs",
              lambda body, **_: job_supervisor.status_view())
    if work_queue is not None:
        # failed async tasks must be observable (fix for the reference's
        # silent infinite-retry loop, workQueue.go:33-47) — and, since the
        # durable journal, they survive daemon restarts
        r.add("GET", "/api/v1/dead-letters",
              lambda body, **_: work_queue.dead_letter_view())
        r.add("GET", "/api/v1/debug/deadletters",  # legacy alias
              lambda body, **_: work_queue.dead_letter_view())
        # ... and recoverable: re-enqueue after the operator fixed the cause
        r.add("POST", "/api/v1/dead-letters/retry",
              lambda body, **_: {"requeued": work_queue.retry_dead_letters()})
        # queue depth / journal lifecycle / degradation counters
        r.add("GET", "/api/v1/queue", lambda body, **_: work_queue.stats())
    if reconciler is not None:
        # KV-vs-runtime drift sweep (service/reconcile.py); ?dryRun=true
        # reports the planned repairs without mutating anything; ?mode=
        # forces the event-driven split (full = anti-entropy O(objects)
        # scan, dirty = O(changes) watch-fed pass, auto = cadence) and
        # the report names which one actually ran
        def reconcile_view(body, **_):
            dry = str(body.get("dryRun", "false")).lower() in ("1", "true", "yes")
            mode = str(body.get("mode", "auto"))
            if mode not in ("auto", "full", "dirty"):
                raise errors.BadRequest(
                    f"mode must be auto|full|dirty, got {mode!r}")
            return reconciler.reconcile(dry_run=dry, mode=mode)

        r.add("GET", "/api/v1/reconcile", reconcile_view)
        # canonical mutating trigger (GET kept for the reference-style
        # always-200 tooling; prefer POST from anything GET-assuming)
        r.add("POST", "/api/v1/reconcile", reconcile_view)
        r.add("GET", "/api/v1/reconcile/events",
              lambda body, **_: reconciler.events_view())
    if compactor is not None:
        # bounded history (service/compactor.py): run one compaction pass
        # now and return its report (what was trimmed / spared / purged)
        r.add("POST", "/api/v1/compact",
              lambda body, **_: compactor.compact_once())

    if tracer is not None:
        # trace exporters (telemetry/trace.py, docs/observability.md):
        # recent trace summaries + one full span tree, served from the
        # bounded in-process ring
        def t_list(body, **_):
            try:
                limit = int(body.get("limit", 100))
            except (TypeError, ValueError):
                raise errors.BadRequest("limit must be an integer") from None
            return tracer.summaries(limit=limit)

        def t_get(body, traceId):  # noqa: N803 — route param name
            view = tracer.trace_view(traceId)
            if view is None:
                # a request that carried BOTH traceparent and X-Request-Id
                # is keyed by the traceparent trace-id, but the envelope
                # echoed the X-Request-Id — honor the runbook's "grep by
                # requestId" by falling back to the root-attr index
                view = tracer.find_by_request_id(traceId)
            if view is None:
                raise errors.NotExistInStore(f"trace {traceId}")
            return view

        r.add("GET", "/api/v1/traces", t_list)
        r.add("GET", "/api/v1/traces/{traceId}", t_get)

    def debug_threads(body, **_):
        """Per-thread stack dump — the pprof-goroutine analog SURVEY.md §5.1
        asks for (the reference exposes nothing; a hung copy task or a
        deadlocked family lock shows up here)."""
        import sys
        import threading
        import traceback

        names = {t.ident: t.name for t in threading.enumerate()}
        out = []
        for ident, frame in sys._current_frames().items():
            out.append({
                "threadId": ident,
                "name": names.get(ident, "?"),
                "stack": [
                    {"file": f.filename, "line": f.lineno, "fn": f.name}
                    for f in traceback.extract_stack(frame)
                ],
            })
        return {"threads": out}

    r.add("GET", "/api/v1/debug/threads", debug_threads)

    # pull-time utilization gauges for /metrics (SURVEY.md §5.5)
    r.metrics.gauge_fn(
        "tpu_chips_free",
        lambda: chip_scheduler.status().get("freeChips", 0),
        help="Unallocated TPU chips on this host")
    r.metrics.gauge_fn(
        "tpu_chips_total",
        lambda: chip_scheduler.status().get("totalChips", 0),
        help="Total TPU chips on this host")
    r.metrics.gauge_fn(
        "host_ports_used",
        lambda: len(port_scheduler.status().get("usedPorts", [])),
        help="Host ports handed out by the port scheduler")
    if work_queue is not None:
        from tpu_docker_api.state.workqueue import queue_depth

        r.metrics.gauge_fn("workqueue_depth", lambda: queue_depth(work_queue),
                           help="Pending async tasks")
    return r


#: http_request_ms histogram buckets (milliseconds — the registry default
#: is second-scaled and would collapse every request into two bins)
_HTTP_MS_BUCKETS = (0.5, 1.0, 2.5, 5.0, 10.0, 25.0, 50.0, 100.0, 250.0,
                    500.0, 1000.0, 5000.0)


def build_handler(router: Router):
    registry = router.metrics

    class Handler(BaseHTTPRequestHandler):
        server_version = "tpu-docker-api"
        protocol_version = "HTTP/1.1"

        def log_message(self, fmt, *args):  # route through logging, not stderr
            log.debug("http: " + fmt, *args)

        def _handle(self, method: str) -> None:
            from tpu_docker_api.service.store_health import consume_stale_read
            from tpu_docker_api.telemetry import trace

            # drop any stale-read marker a previous request on this
            # keep-alive thread left behind (e.g. its handler errored
            # after a mirror read) — staleness must never bleed across
            # requests
            consume_stale_read()

            # request identity (SURVEY.md §5.1 — absent in the reference):
            # a W3C traceparent names the remote trace context exactly;
            # otherwise the caller's X-Request-Id doubles as the trace id
            # (a user-reported failure is greppable straight into
            # /api/v1/traces); otherwise both are freshly generated
            tp = trace.parse_traceparent(self.headers.get("traceparent"))
            # sanitize before echoing: http.client preserves obs-fold
            # CRLFs inside a header value, and writing one back verbatim
            # via send_header would let a client inject response-header
            # lines (response splitting); bound the length too
            raw_id = self.headers.get("X-Request-Id") or ""
            req_id = ("".join(c for c in raw_id
                              if c.isprintable() and c not in "\r\n")[:128]
                      or (tp[0] if tp else uuid.uuid4().hex[:12]))
            path, _, query = self.path.partition("?")
            if method == "GET" and path == "/metrics":
                body_bytes = registry.render().encode()
                self.send_response(200)
                self.send_header("Content-Type",
                                 "text/plain; version=0.0.4; charset=utf-8")
                self.send_header("Content-Length", str(len(body_bytes)))
                self.end_headers()
                self.wfile.write(body_bytes)
                return
            found = router.match(method, path)
            route = found[2] if found else "unmatched"
            t0 = time.perf_counter()
            app_code = codes.SUCCESS
            http_status = 200
            stale_lag_ms = None
            retry_after_s = None
            # root span per request: the trace id continues the remote
            # context (traceparent wins, then X-Request-Id); the span
            # brackets everything from body read to envelope build, and
            # the dispatch child below covers the actual route handler —
            # time between the two is the HTTP layer's own overhead
            tracer = router.tracer
            span_scope = (tracer.span(
                f"http:{method} {route}",
                trace_id=(tp[0] if tp else req_id),
                parent_id=(tp[1] if tp else ""),
                # a traceparent-continued request has a REMOTE parent yet
                # is still this process's serving root: it must count as
                # rooted and fire slow-trace events
                root=True,
                attrs={"method": method, "route": route,
                       "requestId": req_id})
                if tracer is not None else trace.NOOP)
            with span_scope as root_span:
                try:
                    if found is None:
                        raise errors.BadRequest(
                            f"no route for {method} {path}")
                    handler, params, _ = found
                    # body read (drained even for requests we reject: leaving
                    # it on a keep-alive socket would desync the connection —
                    # the next request would be parsed from leftover bytes)...
                    length = int(self.headers.get("Content-Length") or 0)
                    raw = self.rfile.read(length) if length else b""
                    # ...but the HA standby contract gates BEFORE parsing or
                    # validating it: reads (GET) serve locally, every mutation
                    # gets 503 + the leader hint — a standby never
                    # half-validates a request it will not execute. Mutations
                    # are also rejected while a NEW leader's writer subsystems
                    # are still booting (accepts_mutations), so no request can
                    # race the leadership-handoff cache reload
                    elector = router.leader_elector
                    if (method != "GET" and elector is not None
                            and not elector.accepts_mutations):
                        raise errors.NotLeader(elector.standby_message())
                    # sharded plane: the same gate per shard. The target
                    # shard comes from the family name (path segment, or
                    # the create body's *Name field) — zero store reads;
                    # non-family mutations belong to shard 0, the
                    # singleton-of-last-resort. Wrong shard ⇒ 503 naming
                    # the OWNING shard's advertised holder (a redirect,
                    # never a proxy).
                    plane = router.shard_plane
                    if method != "GET" and plane is not None:
                        shard = _shard_for_request(plane, path, raw)
                        if not plane.accepting(shard):
                            raise errors.NotLeader(
                                plane.standby_message(shard))
                    # store brownout gate: in outage mode a mutation that
                    # cannot journal its intent must never half-apply —
                    # fail fast with the typed 503 + Retry-After (zero
                    # store round trips), except the single-flight heal
                    # probe admit_mutation lets through. Reads pass: they
                    # serve the informer mirror (marked stale below) or
                    # pay the deadline-bounded store attempt
                    store_health = getattr(router, "store_health", None)
                    if method != "GET" and store_health is not None:
                        store_health.admit_mutation()
                    body = json.loads(raw) if raw else {}
                    if not isinstance(body, dict):
                        raise errors.BadRequest("body must be a JSON object")
                    # query params merge under the body (body wins): GET
                    # handlers take options like ?limit=5 the natural way
                    for k, vs in urllib.parse.parse_qs(query).items():
                        body.setdefault(k, vs[-1])
                    with trace.child(f"dispatch:{route}"):
                        data = handler(body=body, **params)
                    # a read the handler served from the informer mirror
                    # during a store outage marked this thread — surface
                    # the staleness explicitly (envelope + header below)
                    stale_lag_ms = consume_stale_read()
                    payload = response.success(
                        data, stale=(None if stale_lag_ms is None
                                     else {"lagMs": stale_lag_ms}))
                except errors.ApiError as e:
                    app_code = e.code
                    # the one deviation from always-200: backpressure errors
                    # (QueueSaturated) carry a real 429 so clients and
                    # proxies treat them as retryable, never as success
                    http_status = e.http_status or 200
                    # typed backoff hint (StoreDegraded): surfaced as the
                    # Retry-After header so retry-aware clients hold off
                    # instead of burning their budget against a brownout
                    retry_after_s = getattr(e, "retry_after_s", None)
                    payload = response.error(e.code, str(e), data=e.data,
                                             request_id=req_id)
                except json.JSONDecodeError as e:
                    app_code = codes.BAD_REQUEST
                    payload = response.error(codes.BAD_REQUEST,
                                             f"invalid JSON: {e}",
                                             request_id=req_id)
                except Exception as e:  # noqa: BLE001 — envelope every failure
                    app_code = codes.SERVER_ERROR
                    log.exception("unhandled error on %s %s id=%s",
                                  method, self.path, req_id)
                    payload = response.error(codes.SERVER_ERROR, str(e),
                                             request_id=req_id)
                if root_span is not None:
                    root_span.attrs["code"] = app_code
                    if app_code != codes.SUCCESS:
                        root_span.status = "error"
            dur = time.perf_counter() - t0
            labels = {"method": method, "route": route, "code": str(app_code)}
            registry.counter_inc("api_requests_total", labels,
                                 help="API requests by route and app code")
            registry.observe("api_request_duration_seconds",
                             dur, {"method": method, "route": route},
                             help="API request latency")
            # the satellite pair keyed by HTTP status (route template, not
            # raw path — label cardinality stays bounded by the route table)
            registry.counter_inc(
                "http_requests_total",
                {"method": method, "route": route, "code": str(http_status)},
                help="HTTP requests by route, method and status")
            registry.observe(
                "http_request_ms", dur * 1e3,
                {"method": method, "route": route},
                buckets=_HTTP_MS_BUCKETS,
                help="HTTP request wall time, milliseconds")
            log.info("%s %s code=%d dur=%.1fms id=%s",
                     method, path, app_code, dur * 1e3, req_id)
            # reference: always HTTP 200, app code in envelope
            # (response.go:15-29) — except typed backpressure (429 above)
            self.send_response(http_status)
            self.send_header("Content-Type", "application/json")
            self.send_header("X-Request-Id", req_id)
            if retry_after_s is not None:
                # integer seconds, never 0 — Retry-After: 0 reads as
                # "retry immediately", the opposite of the hint
                self.send_header("Retry-After",
                                 str(max(1, math.ceil(retry_after_s))))
            if stale_lag_ms is not None:
                # the header twin of the envelope's stale field, for
                # clients and proxies that only look at headers
                self.send_header("X-Stale-Read", str(stale_lag_ms))
            if root_span is not None:
                # the W3C echo: tell the caller which span served them
                # (only emittable when the trace id is wire-legal 32-hex —
                # opaque X-Request-Id trace keys have no valid traceparent)
                tp_out = trace.format_traceparent(root_span)
                if tp_out:
                    self.send_header("traceparent", tp_out)
            self.send_header("Content-Length", str(len(payload)))
            self.end_headers()
            self.wfile.write(payload)

        def do_GET(self):  # noqa: N802
            self._handle("GET")

        def do_POST(self):  # noqa: N802
            self._handle("POST")

        def do_DELETE(self):  # noqa: N802
            self._handle("DELETE")

        def do_PATCH(self):  # noqa: N802
            self._handle("PATCH")

    return Handler


class ApiServer:
    """Serving wrapper: bind, serve in a thread, close (reference
    program.Start's gin goroutine, main.go:95-110)."""

    def __init__(self, router: Router, host: str = "127.0.0.1", port: int = 0) -> None:
        self._httpd = ThreadingHTTPServer((host, port), build_handler(router))
        self._thread: threading.Thread | None = None

    @property
    def port(self) -> int:
        return self._httpd.server_address[1]

    def start(self) -> None:
        self._thread = threading.Thread(
            target=self._httpd.serve_forever, name="http-serve", daemon=True
        )
        self._thread.start()

    def close(self) -> None:
        self._httpd.shutdown()
        self._httpd.server_close()
        if self._thread:
            self._thread.join()
            self._thread = None
