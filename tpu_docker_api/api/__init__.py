"""HTTP API layer (parity: reference L1 — ``internal/api/``)."""

from tpu_docker_api.api.app import ApiServer, build_handler  # noqa: F401
