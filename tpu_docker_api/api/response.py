"""Uniform JSON envelope ``{code, msg, data}`` (parity: reference
``internal/api/response.go:9-29`` — always HTTP 200; the app code carries the
outcome)."""

from __future__ import annotations

import json
from typing import Any

from tpu_docker_api.api import codes


def success(data: Any = None, stale: dict | None = None) -> bytes:
    """``stale`` defaults to None — the legacy success shape byte-for-byte.
    During a store outage the HTTP layer attaches ``{"lagMs": ...}`` so a
    read served from the informer mirror (service/store_health.py) is
    EXPLICITLY marked: the caller learns both that the answer is cached
    and how far behind the dead store's last proven instant it may be."""
    body = {"code": codes.SUCCESS, "msg": codes.message(codes.SUCCESS),
            "data": data}
    if stale is not None:
        body["stale"] = stale
    return json.dumps(body).encode()


def error(code: int, msg: str = "", data: Any = None,
          request_id: str = "") -> bytes:
    """``data`` defaults to None — the legacy error shape byte-for-byte;
    typed errors may attach structured context (errors.ApiError.data).
    ``request_id`` (the HTTP layer passes its X-Request-Id) is echoed as
    ``requestId`` so a user-reported failure is greppable in traces and
    events; empty keeps the legacy three-key envelope exactly."""
    body = {"code": code, "msg": msg or codes.message(code), "data": data}
    if request_id:
        body["requestId"] = request_id
    return json.dumps(body).encode()
