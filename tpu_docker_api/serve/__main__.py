"""Inference-serving entrypoint — what an inference container image runs
(BASELINE.json config #3: a v5e-4 slice provisioned through the control
plane serving Llama).

    python -m tpu_docker_api.serve --preset llama3-1b --ckpt-dir /ckpt \
        --port 8000 [--quantize] [--tp 4]

HTTP surface (stdlib server, same envelope as the control plane):

    GET  /healthz               → {"status": "ok", "model": ..., ...}
    POST /generate              → {"tokens": [[...]], "lengths": [...]}
        body: {"tokens": [[...prompt ids...]] ,
               "maxNewTokens": 64, "temperature": 0.8,
               "topK": 0, "topP": 1.0, "eosId": 2,
               "stream": false}
        with a tokenizer loaded (--tokenizer / --hf-ckpt), "text":
        ["..."] replaces the token rows; the response adds decoded
        "texts" and eosId defaults to the tokenizer's (explicit wins).
        Streaming still emits token-id lines (BPE pieces don't decode
        one id at a time); the final done line carries the full "text".
        "stream": true (one prompt row, slot path only) switches the
        response to chunked ndjson — {"t": token} per token as the
        engine resolves it, then {"done": true, "length": n}.
    POST /prefixes {"tokens": [...]} → {"prefixId", "length"}
        register a shared prompt prefix (system prompt): /generate
        prompts starting with it prefill only the suffix (slot path).
        With a tokenizer loaded, {"text": "..."} (ONE string) works too.
    GET  /prefixes              → {"prefixes": [{"id", "length", "bytes"}]}
    DELETE /prefixes/{id}       → {"removed": bool}
    GET  /metrics               → Prometheus text (r5): serve_ttft_seconds
        + serve_itl_seconds histograms per completed request,
        serve_requests_completed, and — when paged — serve_pages_free /
        serve_deferred_admissions gauges. /healthz additionally carries
        the engine-side percentile snapshot under slotEngine.latency.

Family presets mirror the trainer CLI: ``--preset moe:NAME`` serves
through the same KV-cached engine and body; ``--preset encdec:NAME``
serves seq2seq — the body uses ``srcTokens`` instead of ``tokens``
(rows may be ragged on the slot path), and temperature/topK/topP sample
through the same semantics as the llama engine. Round 4: encdec rides
its own continuous-batching slot engine (infer/encdec_slots.py) on a
single device — concurrent seq2seq clients share the chip, and the
response carries ``lengths`` like every slot-path family. The legacy
serialized path (meshes, ``--slots 0``) keeps its old contract: equal-
length rows, ``lengths`` only with ``eosId``. ViT has no generative
serving path.

Design notes, TPU-first:

- **continuous batching by default** (llama/moe, single device): requests
  stream through a slot-based engine (infer/slots.py) — a fixed-capacity
  KV cache of ``--slots`` slots, K-step decode chunks, admission into
  freed slots between chunks. Concurrent clients share the chip instead
  of serializing behind a lock; greedy, per-request temperature, and
  per-request top-k/top-p all run through it (the filtered-sampling
  chunk variant dispatches only while a top-k/top-p slot is active).
  Prompt rows in one body may be ragged — each row is its own request.
- legacy path (encdec, meshes, ``--slots 0``): one compiled
  generate program per (batch, prompt_len, maxNewTokens, sampler) shape
  bucket — jax caches compilations, so repeated traffic at the same
  shape pays zero retrace; prompts in a batch are dense (callers
  left-pad, engine.make_generate_fn docstring); a global lock serializes
  generations.
- sharded serving: ``--dp/--fsdp/--tp`` build the same mesh/rules the
  trainer uses; params restore (orbax) directly into their shards.
- ``--quantize`` rewrites projections to int8 at load
  (infer/quantize.py) — decode is weight-bandwidth-bound.
- ``--lora-ckpt`` merges trained LoRA adapters (train/lora.py) into the
  base weights once at load (before quantization); serving then runs
  the ordinary forward — zero per-token adapter cost.
- the distributed bootstrap mirrors the trainer: JAX_NUM_PROCESSES > 1 ⇒
  jax.distributed.initialize from the control plane's rendered env.
"""

from __future__ import annotations

import argparse
import json
import os
import signal
import sys
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

from tpu_docker_api import errors


def main(argv: list[str] | None = None) -> None:
    p = argparse.ArgumentParser(prog="python -m tpu_docker_api.serve")
    p.add_argument("--preset", default="llama3-1b")
    p.add_argument("--ckpt-dir", default="",
                   help="orbax checkpoint to restore; '' serves random init "
                        "(smoke/bench)")
    p.add_argument("--hf-ckpt", default="",
                   help="HF-layout llama checkpoint dir (config.json + "
                        "safetensors): geometry comes from its "
                        "config.json (--preset is ignored) and weights "
                        "import via models/import_weights.py; composes "
                        "with --quantize as int8-at-load (no bf16 tree "
                        "ever materializes — llama3-8b on one v5e)")
    p.add_argument("--tokenizer", default="",
                   help="local HF tokenizer (dir or tokenizer.json): "
                        "/generate additionally accepts {\"text\": "
                        "[...]} and replies with decoded \"texts\". "
                        "Defaults to --hf-ckpt's tokenizer.json when "
                        "present")
    p.add_argument("--quantize", action="store_true",
                   help="int8 weight quantization at load")
    p.add_argument("--no-fuse-proj", action="store_true",
                   help="disable the default q|k|v and gate|up "
                        "projection fusion (llama, single device, "
                        "merged weights). Fusion is bit-identical and "
                        "measured 20.9 → 15.1 ms/tok on 8B-int8 decode "
                        "(50 → 69%% of the HBM roof) — this flag exists "
                        "for A/B measurement, not production use")
    p.add_argument("--host", default="0.0.0.0")
    p.add_argument("--port", type=int, default=8000)
    p.add_argument("--max-seq", type=int, default=0, help="0 = model default")
    p.add_argument("--dp", type=int, default=-1,
                   help="-1 = fill with remaining devices (trainer default)")
    p.add_argument("--fsdp", type=int, default=1)
    p.add_argument("--tp", type=int, default=1)
    p.add_argument("--platform", default="",
                   help="force a jax platform (tests: cpu)")
    p.add_argument("--virtual-devices", type=int, default=0,
                   help="force N virtual CPU devices (tests)")
    p.add_argument("--slots", type=int, default=8,
                   help="continuous-batching slots (0 disables the slot "
                        "engine; llama/moe single-device only)")
    p.add_argument("--chunk", type=int, default=8,
                   help="decode steps per slot-engine dispatch")
    p.add_argument("--prefill-chunk", type=int, default=0,
                   help="> 0: prompts longer than this prefill in "
                        "segments interleaved with decode (bounds the "
                        "stall a long admission inflicts on active "
                        "streams); 0 = whole-prompt admission. "
                        "Measured trade (perf-notes): a clear win on "
                        "8B/960-token prompts (1.37x stall reduction) "
                        "but phase-dependent on small models — "
                        "llama3-1b spanned 0.83-1.73x across captures "
                        "(sometimes a REGRESSION) and the segmented "
                        "long request itself slows ~2.7x; enable for "
                        "big-model long-prompt traffic only")
    p.add_argument("--page-size", type=int, default=0,
                   help="> 0: paged KV cache (infer/paged.py) — the "
                        "slot cache becomes a page pool and HBM scales "
                        "with --total-pages instead of slots×max-seq. "
                        "llama presets, single device or tp-only mesh "
                        "(r5: kv-heads shard over tp); /prefixes "
                        "(refcounted shared pages) and --prefill-chunk "
                        "(page-aware segments) compose (r5); excludes "
                        "--draft-preset")
    p.add_argument("--total-pages", type=int, default=0,
                   help="pool size in pages (0 = dense-equivalent "
                        "capacity); only with --page-size")
    p.add_argument("--max-prefix-bytes", type=int, default=256 * 2**20,
                   help="HBM budget for POST /prefixes K/V pairs in "
                        "bytes (0 = unbounded); registrations past it "
                        "get a 400 instead of risking an engine OOM")
    p.add_argument("--lora-ckpt", default="",
                   help="adapter-only checkpoint dir (train --lora-rank): "
                        "merged into the base weights at load. "
                        "--lora-rank/--lora-alpha/--lora-targets must "
                        "match the training run")
    p.add_argument("--lora-rank", type=int, default=0)
    p.add_argument("--lora-alpha", type=float, default=16.0)
    p.add_argument("--lora-targets", default="wq,wv")
    p.add_argument("--lora-forward", default="merged",
                   choices=["merged", "attached"],
                   help="merged: fold adapters into the base once at "
                        "load (zero per-token cost); attached: serve "
                        "the unmerged Wx + s·B(Ax) forward — with "
                        "--quantize this reproduces QLoRA training "
                        "numerics exactly (int8 base + f32 adapters), "
                        "and the bf16 merged tree never materializes")
    p.add_argument("--draft-preset", default="",
                   help="serve speculatively: a (smaller) llama preset "
                        "as the draft model. Greedy-only; pays at small "
                        "batch/low concurrency (perf-notes: at 8 busy "
                        "streams plain batching wins)")
    p.add_argument("--draft-ckpt", default="",
                   help="orbax checkpoint for the draft ('' = random "
                        "init — mechanism smoke only)")
    p.add_argument("--n-spec", type=int, default=4,
                   help="draft tokens proposed per speculative round")
    args = p.parse_args(argv)
    if args.total_pages and not args.page_size:
        raise SystemExit("--total-pages requires --page-size (the "
                         "dense engine has no page pool)")
    # r5: --page-size composes with --prefill-chunk (page-aware
    # segments, infer/paged.py) — the v1 rejection is gone

    from tpu_docker_api.workload.jaxenv import bootstrap_jax

    bootstrap_jax(args.platform, args.virtual_devices)
    import jax

    from tpu_docker_api.infer.engine import GenerateConfig, make_generate_fn
    from tpu_docker_api.models import model_fns
    from tpu_docker_api.parallel.mesh import MeshPlan, build_mesh
    from tpu_docker_api.train.trainer import create_train_state

    from tpu_docker_api.models import resolve_preset

    # family-prefixed presets, one parser shared with the trainer CLI:
    # moe:NAME serves through the same KV-cached engine; encdec:NAME
    # switches /generate to the seq2seq path (srcTokens → sampled decode)
    if args.hf_ckpt:
        if args.ckpt_dir:
            raise SystemExit("--hf-ckpt and --ckpt-dir are exclusive")
        from tpu_docker_api.models.import_weights import hf_llama_config

        family, cfg = "llama", hf_llama_config(args.hf_ckpt)
        args.preset = os.path.basename(os.path.normpath(args.hf_ckpt))
    else:
        family, cfg = resolve_preset(args.preset)
    if family == "vit":
        raise SystemExit("vit presets have no generative serving path")
    is_encdec = family == "encdec"
    if args.quantize and family != "llama":
        raise SystemExit("--quantize currently supports llama presets only")
    mesh = build_mesh(MeshPlan(dp=args.dp, fsdp=args.fsdp, tp=args.tp, sp=1))
    quantized_at_load = False
    if args.hf_ckpt:
        from tpu_docker_api.models.import_weights import import_hf_llama

        if mesh.devices.size > 1:
            # meshes: import bf16 to HOST, place into shards, quantize
            # on device (the shard-local halves of the existing
            # quantize path); single-chip 8B must take the streaming
            # int8 branch below instead — bf16 wouldn't fit
            from tpu_docker_api.parallel.sharding import param_shardings
            from tpu_docker_api.models import model_fns as _mf

            _, host = import_hf_llama(args.hf_ckpt, cfg, to_device=False)
            abstract = jax.tree_util.tree_map(
                lambda x: jax.ShapeDtypeStruct(x.shape, x.dtype), host)
            params = jax.device_put(
                host, param_shardings(abstract, mesh,
                                      _mf(cfg)[2]))
            del host
        else:
            # LoRA MERGE must precede (lossy) quantization, so with a
            # merged --lora-ckpt the import stays bf16 and the shared
            # merge-then-quantize path below runs; the ATTACHED forward
            # wants the opposite order (int8 base first — QLoRA's
            # training numerics), so int8-at-load stays on
            q_now = args.quantize and (not args.lora_ckpt
                                       or args.lora_forward == "attached")
            _, params = import_hf_llama(args.hf_ckpt, cfg,
                                        quantize=q_now)
            quantized_at_load = q_now
        step = 0
    elif args.ckpt_dir:
        # params-only restore: the optimizer moments are never read
        # (PLACEHOLDER) — works whatever optimizer the training run
        # used, and at 8B the moments would not even fit one chip
        from tpu_docker_api.train.checkpoint import restore_model_params

        params, step = restore_model_params(args.ckpt_dir, cfg, mesh)
    else:
        if mesh.devices.size > 1:
            state, _ = create_train_state(cfg, mesh, jax.random.PRNGKey(0))
            params = state.params
            del state
        else:
            init_fn, _, _ = model_fns(cfg)
            params = init_fn(cfg, jax.random.PRNGKey(0))
        step = 0
    if not args.lora_ckpt and (
            args.lora_rank > 0 or args.lora_alpha != 16.0
            or args.lora_targets != "wq,wv"
            or args.lora_forward != "merged"):
        # mirror of the trainer's guard: a lora flag without --lora-ckpt
        # would silently serve the unmodified base with exit 0
        raise SystemExit(
            "--lora-rank/--lora-alpha/--lora-targets/--lora-forward "
            "require --lora-ckpt")
    if args.lora_ckpt:
        # merged: fold adapters into the base ONCE at load, BEFORE the
        # lossy int8 quantization; attached: quantize FIRST (matching
        # --qlora training numerics) and wrap projections in LoraLinear
        # leaves — the merged tree never materializes
        if args.lora_rank < 1:
            raise SystemExit("--lora-ckpt requires --lora-rank (the rank "
                             "the adapters were trained at)")
        from tpu_docker_api.train.lora import (
            attach_lora, merge_lora, restore_adapters)

        targets = tuple(t.strip() for t in args.lora_targets.split(",")
                        if t.strip())
        adapters = restore_adapters(args.lora_ckpt, cfg, mesh,
                                    args.lora_rank, targets)
        if args.lora_forward == "attached":
            if args.quantize and not quantized_at_load:
                from tpu_docker_api.infer.quantize import (
                    quantize_llama_params)

                params = quantize_llama_params(params)
                quantized_at_load = True
            params = attach_lora(params, adapters,
                                 alpha=args.lora_alpha)
        else:
            params = merge_lora(params, adapters, alpha=args.lora_alpha)
        del adapters
    if args.quantize and not quantized_at_load:
        from tpu_docker_api.infer.quantize import quantize_llama_params

        params = quantize_llama_params(params)

    # projection fusion is DEFAULT-ON wherever legal: bit-identical
    # math, measured 20.9 → 15.1 ms/tok on 8B-int8 decode (the round-4
    # dispatch-overhead recovery). Skipped silently where the transform
    # doesn't apply: non-llama families, meshes (the concat axis would
    # mix q/kv-head shards under tp), attached-LoRA trees (adapters
    # hang off the unfused leaf names).
    if (family == "llama" and mesh.devices.size == 1
            and not (args.lora_ckpt and args.lora_forward == "attached")
            and not args.no_fuse_proj):
        from tpu_docker_api.infer.quantize import fuse_llama_projections

        params = fuse_llama_projections(params)

    tokenizer = None
    tok_path = args.tokenizer
    if not tok_path and args.hf_ckpt and os.path.exists(
            os.path.join(args.hf_ckpt, "tokenizer.json")):
        tok_path = args.hf_ckpt
    if tok_path:
        from tpu_docker_api.models.import_weights import load_tokenizer

        tokenizer = load_tokenizer(tok_path)

    max_seq = args.max_seq or (cfg.max_tgt_len if is_encdec
                               else cfg.max_seq_len)

    # continuous batching: the default llama/moe path — single device,
    # or a tensor-parallel mesh (tp/fsdp; the cache's kv-head dim shards
    # over tp, slots stay replicated). dp/sp meshes keep the legacy
    # whole-generation path.
    slot_engine = None
    multi = mesh.devices.size > 1
    tp_only = all(mesh.shape.get(ax, 1) == 1 for ax in ("dp", "sp"))
    slot_ok_here = (family in ("llama", "moe") and args.slots > 0
                    and (not multi or tp_only))
    if args.page_size > 0 and not slot_ok_here:
        # erroring beats silently serving on the legacy dense path
        raise SystemExit(
            "--page-size requires the slot-engine path (llama preset, "
            "--slots > 0, single device or tp-only mesh)")
    if is_encdec and args.slots > 0 and not multi:
        # seq2seq continuous batching (round 4): sources may be ragged,
        # decode runs through the same slot machinery as llama/moe; the
        # legacy serialized path remains for meshes and --slots 0
        from tpu_docker_api.infer.encdec_slots import EncDecSlotEngine

        if args.prefill_chunk:
            raise SystemExit(
                "--prefill-chunk does not apply to seq2seq admission "
                "(sources are bounded by max_src_len)")
        if args.draft_preset:
            raise SystemExit(
                "--draft-preset is not supported with encdec presets")

        slot_engine = EncDecSlotEngine(
            cfg, params, slots=args.slots, max_seq=max_seq,
            chunk=args.chunk, max_pending=args.slots * 8,
            seed=int.from_bytes(os.urandom(4), "little"))
        slot_engine.warmup(buckets=())
        slot_engine.start()
    elif slot_ok_here:
        from tpu_docker_api.infer.slots import SlotEngine

        if args.draft_preset:
            # speculative serving: greedy-only, single-device — the
            # small-batch latency mode (measured trade in perf-notes)
            from tpu_docker_api.infer.slots import SpeculativeSlotEngine

            if args.page_size:
                raise SystemExit(
                    "--page-size and --draft-preset are exclusive (the "
                    "speculative engine uses the dense cache)")
            if family != "llama" or multi:
                raise SystemExit(
                    "--draft-preset requires a llama preset on a single "
                    "device")
            if args.prefill_chunk:
                # the speculative engine would reject it; erroring here
                # beats silently serving with whole-prompt admission
                raise SystemExit(
                    "--prefill-chunk is not supported with --draft-preset "
                    "(speculative segments would fill the target cache "
                    "only)")
            _, draft_cfg = resolve_preset(args.draft_preset)
            if args.draft_ckpt:
                from tpu_docker_api.train.checkpoint import (
                    restore_model_params)

                draft_params, _ = restore_model_params(
                    args.draft_ckpt, draft_cfg, mesh)
            else:
                dinit, _, _ = model_fns(draft_cfg)
                draft_params = dinit(draft_cfg, jax.random.PRNGKey(0))
            slot_engine = SpeculativeSlotEngine(
                cfg, params, draft_cfg=draft_cfg,
                draft_params=draft_params, n_spec=args.n_spec,
                slots=args.slots, max_seq=max_seq,
                max_pending=args.slots * 8)
        elif args.page_size > 0:
            from tpu_docker_api.infer.paged import PagedSlotEngine

            if family != "llama":
                raise SystemExit(
                    "--page-size requires a llama preset "
                    "(paged engine v1 scope)")
            # r5: tp-only meshes compose — the pool's kv-head dim
            # shards over tp, the page table stays a host operand
            slot_engine = PagedSlotEngine(
                cfg, params, page_size=args.page_size,
                total_pages=args.total_pages or None,
                slots=args.slots, max_seq=max_seq, chunk=args.chunk,
                prefill_chunk=args.prefill_chunk,
                max_pending=args.slots * 8,
                mesh=mesh if multi else None,
                seed=int.from_bytes(os.urandom(4), "little"))
        else:
            slot_engine = SlotEngine(
                cfg, params, slots=args.slots, max_seq=max_seq,
                chunk=args.chunk,
                prefill_chunk=args.prefill_chunk,
                max_prefix_bytes=args.max_prefix_bytes,
                mesh=mesh if multi else None,
                # shed load once the queue is 8x the slot count deep —
                # beyond that, added requests only buy latency, not
                # throughput
                max_pending=args.slots * 8,
                seed=int.from_bytes(os.urandom(4), "little"))
        # SLO export (VERDICT r4 next #5): every completed request
        # lands its TTFT/ITL in the Prometheus registry served at
        # GET /metrics; a paged pool additionally exposes its pressure
        # gauges. The hook runs on the engine thread — REGISTRY ops
        # are one lock acquisition, far below a chunk's host work.
        from tpu_docker_api.telemetry.metrics import REGISTRY

        def _slo_hook(ttft, itl, n_tokens):
            REGISTRY.observe(
                "serve_ttft_seconds", ttft,
                help="submit to first host-resolved token, per request")
            if itl is not None:
                REGISTRY.observe(
                    "serve_itl_seconds", itl,
                    help="mean inter-token gap per request "
                         "(chunk-granular cadence)")
            REGISTRY.counter_inc(
                "serve_tokens_emitted_total", value=n_tokens,
                help="tokens emitted by completed requests")

        slot_engine.metrics_hook = _slo_hook
        _eng = slot_engine
        REGISTRY.counter_fn("serve_requests_completed_total",
                            lambda: _eng.stats["completed"],
                            help="requests completed by the slot engine")
        if "pages_free" in slot_engine.stats:
            REGISTRY.gauge_fn("serve_pages_free",
                              lambda: _eng.stats["pages_free"],
                              help="free pages in the paged KV pool")
            REGISTRY.counter_fn(
                "serve_deferred_admissions_total",
                lambda: _eng.stats["deferred_admissions"],
                help="admissions deferred on pool pressure")
        # compile the shared decode chunk before binding the port: a
        # mid-service compile on the engine thread stalls every active
        # slot, and /healthz must not report ok before the program
        # exists. Prefill buckets compile on first use (one stall per
        # bucket size ever).
        slot_engine.warmup(buckets=())
        slot_engine.start()
    # jitted generate fns keyed by sampling config. Bounded LRU: sampler
    # params are client-controlled, and each distinct tuple costs an XLA
    # compile — an unbounded dict would let traffic grow compile caches
    # forever. Floats are rounded so near-equal values share a program.
    import collections

    fns: collections.OrderedDict[tuple, object] = collections.OrderedDict()
    fn_lock = threading.Lock()
    _FN_CACHE_MAX = 16

    def get_fn(max_new: int, temperature: float, top_k: int, top_p: float,
               eos_id: int | None = None):
        # rounding 0 < top_p < 5e-4 to exactly 0.0 would turn a valid
        # "≡ greedy" request into a make_sampler rejection — floor it at
        # the rounding resolution instead (semantically identical: both
        # keep only the argmax token)
        top_p_r = round(top_p, 3)
        if top_p > 0 and top_p_r == 0.0:
            top_p_r = 0.001
        temp_r = round(temperature, 3)
        if temp_r == 0.0:
            # greedy ignores the filters (make_sampler docstring) — don't
            # let assorted topK/topP burn an identical compiled program
            # + LRU slot each
            top_k, top_p_r = 0, 1.0
        key = (max_new, temp_r, top_k, top_p_r, eos_id)
        with fn_lock:
            if key in fns:
                fns.move_to_end(key)
                return fns[key]
            if is_encdec:
                if key[0] > max_seq:
                    # the llama path's capacity check lives in the engine;
                    # this is the seq2seq analog — an unbounded client
                    # value would trace a key[0]-step scan and allocate a
                    # (Ld, b, key[0], kvh, hd) cache
                    raise ValueError(
                        f"maxNewTokens {key[0]} exceeds capacity {max_seq}")
                from tpu_docker_api.infer.sampling import make_sampler
                from tpu_docker_api.models.encdec import encdec_generate

                # temperature/top-k/top-p ride encdec_generate's sampler
                # (shared make_sampler semantics with the llama engine);
                # sampler knobs are static per compiled fn, rng is traced.
                # Validate them EAGERLY (the llama branch gets this from
                # make_generate_fn): a deferred trace-time ValueError
                # would cache a poisoned fn in the LRU and evict a
                # compiled program per bad request
                make_sampler(key[1], top_k=key[2], top_p=key[3])
                if eos_id is not None:
                    fn = jax.jit(lambda p, src, rng: encdec_generate(
                        p, src, cfg, max_new_tokens=key[0],
                        eos_id=eos_id, temperature=key[1], top_k=key[2],
                        top_p=key[3], rng=rng))
                else:
                    fn = jax.jit(lambda p, src, rng: {
                        "tokens": encdec_generate(
                            p, src, cfg, max_new_tokens=key[0],
                            temperature=key[1], top_k=key[2],
                            top_p=key[3], rng=rng),
                    })
            else:
                fn = make_generate_fn(
                    cfg,
                    GenerateConfig(max_new_tokens=key[0], temperature=key[1],
                                   top_k=key[2], top_p=key[3],
                                   eos_id=eos_id, max_seq=max_seq),
                    mesh,
                )
            fns[key] = fn
            while len(fns) > _FN_CACHE_MAX:
                fns.popitem(last=False)
            return fn

    import jax.numpy as jnp
    import numpy as np

    rng_state = {"key": jax.random.PRNGKey(int.from_bytes(os.urandom(4),
                                                          "little"))}
    gen_lock = threading.Lock()  # one TPU, one generation at a time

    def valid_token_row(row) -> bool:
        """One definition of a well-formed token-id list — shared by
        /generate rows and /prefixes bodies so the two surfaces can
        never diverge on what a token id is."""
        return (isinstance(row, list) and bool(row)
                and all(isinstance(t, int) and not isinstance(t, bool)
                        and 0 <= t < cfg.vocab_size for t in row))

    class Handler(BaseHTTPRequestHandler):
        # HTTP/1.1 for chunked streaming responses; every non-streamed
        # reply carries Content-Length so keep-alive stays correct
        protocol_version = "HTTP/1.1"

        def log_message(self, *a):  # quiet; structured line below instead
            pass

        def _chunk(self, data: bytes) -> None:
            self.wfile.write(f"{len(data):x}\r\n".encode() + data + b"\r\n")

        def _reply(self, code: int, payload: dict) -> None:
            body = json.dumps(payload).encode()
            self.send_response(code)
            self.send_header("Content-Type", "application/json")
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)

        def do_GET(self):
            if self.path == "/prefixes":
                if slot_engine is None:
                    self._reply(400, {"error": "prefix caching requires "
                                               "the slot engine path"})
                    return
                self._reply(200, {"prefixes": slot_engine.prefixes()})
                return
            if self.path == "/metrics":
                from tpu_docker_api.telemetry.metrics import REGISTRY

                body = REGISTRY.render().encode()
                self.send_response(200)
                self.send_header("Content-Type",
                                 "text/plain; version=0.0.4")
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)
                return
            if self.path == "/healthz":
                payload = {
                    "status": "ok", "model": args.preset, "step": step,
                    "quantized": args.quantize,
                    "devices": len(jax.devices()),
                    "tokenizer": tokenizer is not None,
                }
                code = 200
                if slot_engine is not None:
                    payload["slotEngine"] = {
                        "slots": slot_engine.slots,
                        "chunk": slot_engine.chunk,
                        **slot_engine.stats,
                        "latency": slot_engine.latency_stats(),
                    }
                    if hasattr(slot_engine, "n_spec"):
                        payload["slotEngine"]["speculative"] = True
                        payload["slotEngine"]["nSpec"] = slot_engine.n_spec
                    if slot_engine.dead:
                        # degraded must be visible at the HTTP level —
                        # orchestrator health checks key on the status
                        # code, not the body
                        payload["status"] = "degraded"
                        payload["slotEngine"]["dead"] = slot_engine.dead
                        code = 503
                self._reply(code, payload)
            else:
                self._reply(404, {"error": f"no route {self.path}"})

        def do_DELETE(self):
            if self.path.startswith("/prefixes/"):
                if slot_engine is None:
                    self._reply(400, {"error": "prefix caching requires "
                                               "the slot engine path"})
                    return
                pid = self.path[len("/prefixes/"):]
                self._reply(200, {"removed":
                                  slot_engine.unregister_prefix(pid)})
                return
            self._reply(404, {"error": f"no route {self.path}"})

        def do_POST(self):
            if self.path == "/prefixes":
                # register a shared prompt prefix (system prompt / few-shot
                # header): subsequent /generate prompts starting with it
                # prefill only the suffix (slot-engine path only)
                try:
                    if slot_engine is None:
                        raise ValueError(
                            "prefix caching requires the slot engine path "
                            "(not encdec / dp-sp mesh / --slots 0)")
                    length = int(self.headers.get("Content-Length", 0))
                    req = json.loads(self.rfile.read(length) or b"{}")
                    toks = req.get("tokens") if isinstance(req, dict) else None
                    text = (req.get("text") if isinstance(req, dict)
                            else None)
                    if text is not None:
                        # same diagnostics contract as /generate: text
                        # without a tokenizer (or of the wrong shape)
                        # must say so, not "tokens must be ids"
                        if tokenizer is None:
                            raise ValueError(
                                '"text" requires --tokenizer (or an '
                                '--hf-ckpt shipping a tokenizer.json)')
                        if toks is not None:
                            raise ValueError(
                                '"text" and "tokens" are exclusive')
                        if not isinstance(text, str) or not text:
                            raise ValueError(
                                '"text" must be ONE non-empty string '
                                'here (a prefix is a single shared '
                                'header, not a batch)')
                        toks = tokenizer.encode(text)
                    if not valid_token_row(toks):
                        raise ValueError(
                            f"tokens must be a non-empty list of ids in "
                            f"[0, {cfg.vocab_size})")
                    pid = slot_engine.register_prefix(toks)
                    self._reply(200, {"prefixId": pid,
                                      "length": len(toks)})
                except (ValueError, errors.BadRequest) as e:
                    self._reply(400, {"error": str(e)})
                except Exception as e:  # noqa: BLE001
                    self._reply(500, {"error": f"{type(e).__name__}: {e}"})
                return
            if self.path != "/generate":
                self._reply(404, {"error": f"no route {self.path}"})
                return
            try:
                length = int(self.headers.get("Content-Length", 0))
                req = json.loads(self.rfile.read(length) or b"{}")
                if not isinstance(req, dict):
                    raise ValueError("body must be a JSON object")
                prompts = req.get("srcTokens" if is_encdec else "tokens")
                texts_in = req.get("text")
                if texts_in is not None:
                    if tokenizer is None:
                        raise ValueError(
                            '"text" requires --tokenizer (or an '
                            '--hf-ckpt shipping a tokenizer.json)')
                    if prompts is not None:
                        raise ValueError(
                            '"text" and token-id rows are exclusive')
                    if (not isinstance(texts_in, list) or not texts_in
                            or not all(isinstance(t, str) and t
                                       for t in texts_in)):
                        raise ValueError(
                            '"text" must be a non-empty list of '
                            'non-empty strings')
                    prompts = [tokenizer.encode(t) for t in texts_in]
                if not prompts or not all(
                        valid_token_row(r) for r in prompts):
                    raise ValueError(
                        ("srcTokens" if is_encdec else "tokens")
                        + " must be a non-empty list of non-empty rows "
                        f"of token ids in [0, {cfg.vocab_size})")

                def req_int(name, default):
                    return errors.as_int(req.get(name, default), name)

                def req_float(name, default):
                    v = req.get(name, default)
                    if isinstance(v, bool) or not isinstance(v,
                                                             (int, float)):
                        raise ValueError(f"{name} must be a number")
                    return float(v)

                max_new = req_int("maxNewTokens", 64)
                if max_new < 1:
                    raise ValueError(
                        f"maxNewTokens must be >= 1, got {max_new}")
                temperature = req_float("temperature", 0.0)
                top_k = req_int("topK", 0)
                if top_k > cfg.vocab_size:
                    # lax.top_k would reject this at TRACE time — after
                    # the jitted fn is already cached (a poisoned-LRU
                    # slot per distinct bad value)
                    raise ValueError(
                        f"topK must be <= vocab size {cfg.vocab_size}, "
                        f"got {top_k}")
                top_p = req_float("topP", 1.0)
                eos_id = (req_int("eosId", 0)
                          if "eosId" in req else None)
                if (eos_id is None and texts_in is not None
                        and tokenizer.eos_id is not None):
                    # text-mode requests stop at the tokenizer's eos by
                    # default — that's what "serve a real model" means;
                    # an explicit eosId still wins
                    eos_id = tokenizer.eos_id
                do_stream = req.get("stream", False)
                if not isinstance(do_stream, bool):
                    raise ValueError("stream must be a JSON boolean")

                # a dead engine (device error on its thread) falls back
                # to the legacy path instead of 500ing forever; a
                # SPECULATIVE engine is greedy-only, so sampled requests
                # fall back too rather than 400
                slot_ok = (slot_engine is not None
                           and not slot_engine.dead)
                if (slot_ok and hasattr(slot_engine, "n_spec")
                        and (temperature != 0.0 or top_k != 0
                             or top_p != 1.0)):
                    slot_ok = False
                if do_stream and not slot_ok:
                    raise ValueError(
                        "stream requires the slot engine path "
                        "(--slots > 0, single device; every family "
                        "incl. encdec has one as of round 4)")
                if do_stream and len(prompts) != 1:
                    raise ValueError("stream serves exactly one prompt row")

                if slot_ok:
                    # continuous batching: each row is its own request;
                    # rows may be ragged. Responses keep the legacy dense
                    # contract (pad to maxNewTokens + lengths).
                    from tpu_docker_api.infer.slots import QueueFull

                    # validate EVERY row + queue room before submitting
                    # any — a failure mid-list would orphan the earlier
                    # rows into the engine (decoding for nobody)
                    for r in prompts:
                        slot_engine.validate(r, max_new, top_k=top_k,
                                             top_p=top_p)
                    if not slot_engine.has_room(len(prompts)):
                        self._reply(503, {
                            "error": "admission queue at capacity"})
                        return
                    try:
                        handles = [slot_engine.submit(
                            r, max_new, temperature, eos_id=eos_id,
                            stream=do_stream, top_k=top_k, top_p=top_p)
                            for r in prompts]
                    except QueueFull as e:
                        self._reply(503, {"error": str(e)})
                        return
                    if do_stream:
                        # chunked ndjson: one {"t": token} line per token
                        # as the engine resolves it, then a "done" line.
                        # Once headers are out, an error must DROP the
                        # connection (a _reply(500) here would write a
                        # second status line mid-chunk and poison the
                        # keep-alive stream)
                        self.send_response(200)
                        self.send_header("Content-Type",
                                         "application/x-ndjson")
                        self.send_header("Transfer-Encoding", "chunked")
                        self.end_headers()
                        try:
                            for t in handles[0].stream(timeout=600):
                                self._chunk(json.dumps({"t": t}).encode()
                                            + b"\n")
                            res = handles[0].result(0)
                            done: dict = {"done": True,
                                          "length": res["length"]}
                            if texts_in is not None:
                                # per-token decode is lossy for BPE
                                # (multi-byte pieces); the full decoded
                                # text rides the done line instead
                                done["text"] = tokenizer.decode(
                                    res["tokens"][:res["length"]])
                            self._chunk(json.dumps(done).encode()
                                        + b"\n")
                            self.wfile.write(b"0\r\n\r\n")
                        except Exception:  # noqa: BLE001
                            self.close_connection = True
                        return
                    outs = [h.result(timeout=600) for h in handles]
                    payload = {
                        "tokens": [o["tokens"]
                                   + [0] * (max_new - o["length"])
                                   for o in outs],
                        "lengths": [o["length"] for o in outs],
                    }
                    if texts_in is not None:
                        payload["texts"] = [
                            tokenizer.decode(o["tokens"][:o["length"]])
                            for o in outs]
                    self._reply(200, payload)
                    return

                lens = {len(r) for r in prompts}
                if len(lens) > 1:
                    raise ValueError(
                        "this serving path (encdec / mesh / --slots 0) "
                        "needs equal-length prompt rows — left-pad them")
                prompt = jnp.asarray(np.array(prompts, np.int32))
                fn = get_fn(max_new, temperature, top_k, top_p, eos_id)
                with gen_lock:
                    key, sub = jax.random.split(rng_state["key"])
                    rng_state["key"] = key
                    out = fn(params, prompt, sub)
                payload = {"tokens": np.asarray(out["tokens"]).tolist()}
                if "lengths" in out:
                    payload["lengths"] = np.asarray(out["lengths"]).tolist()
                if texts_in is not None:
                    lens = payload.get("lengths",
                                       [max_new] * len(payload["tokens"]))
                    payload["texts"] = [
                        tokenizer.decode(row[:n]) for row, n in
                        zip(payload["tokens"], lens)]
                self._reply(200, payload)
            except (ValueError, errors.BadRequest) as e:
                self._reply(400, {"error": str(e)})
            except Exception as e:  # noqa: BLE001 — serving must not die
                self._reply(500, {"error": f"{type(e).__name__}: {e}"})

    httpd = ThreadingHTTPServer((args.host, args.port), Handler)

    def _stop(signum, _frame):
        threading.Thread(target=httpd.shutdown, daemon=True).start()

    signal.signal(signal.SIGTERM, _stop)
    signal.signal(signal.SIGINT, _stop)
    print(json.dumps({"event": "serving", "model": args.preset,
                      "port": httpd.server_address[1],
                      "quantized": args.quantize,
                      "slots": slot_engine.slots if slot_engine else 0}),
          flush=True)
    httpd.serve_forever()
    if slot_engine is not None:
        # drain: handler threads may still be blocked on handles after
        # shutdown() returns — finish their requests instead of failing.
        # 8s, NOT more: the control plane stops containers with a 10s
        # SIGTERM→SIGKILL grace (runtime/base.py container_stop), and a
        # drain that outlives the grace gets SIGKILLed mid-flight with
        # no cleanup at all
        slot_engine.close(drain=8)
    print(json.dumps({"event": "stopped"}), flush=True)


if __name__ == "__main__":
    sys.exit(main())
