"""Multi-host pod scheduler + distributed JobService.

The capability tier the reference lacks entirely (single docker socket,
single GPU map — SURVEY.md §4 "multi-node is untested and unsupported"):
a v5p-64-class pod of 8 fake hosts, host-granular slice allocation, one
process container per host with the JAX/libtpu bootstrap env, and rolling
job rescale with quiesce→replace ordering.
"""

import pytest

from tpu_docker_api import errors
from tpu_docker_api.runtime.fake import FakeRuntime
from tpu_docker_api.scheduler.pod import Pod, PodHost, PodScheduler
from tpu_docker_api.scheduler.ports import PortScheduler
from tpu_docker_api.scheduler.slices import ChipScheduler
from tpu_docker_api.scheduler.topology import GENERATIONS, HostTopology
from tpu_docker_api.schemas.job import JobDelete, JobPatchChips, JobRun
from tpu_docker_api.service.job import JobService
from tpu_docker_api.state import keys
from tpu_docker_api.state.kv import CountingKV, MemoryKV
from tpu_docker_api.state.store import StateStore
from tpu_docker_api.state.txn import StoreTxn
from tpu_docker_api.state.version import VersionMap


def make_pod(kv, grid=(2, 2, 2), acc="v5p-8"):
    """Pod of v5p hosts (4 chips each, 2x2x1) on a host grid — (2,2,2) grid
    = 32 chips = a v5p-64 slice."""
    hosts = []
    i = 0
    for z in range(grid[2]):
        for y in range(grid[1]):
            for x in range(grid[0]):
                hid = f"h{i}"
                topo = HostTopology.build(acc)
                hosts.append(PodHost(
                    host_id=hid,
                    address=f"10.0.0.{i + 1}",
                    grid_coord=(x, y, z),
                    topology=topo,
                    runtime=FakeRuntime(),
                    chips=ChipScheduler(topo, kv, keys.host_chips_key(hid)),
                    ports=PortScheduler(kv, 40000, 40100,
                                        store_key=keys.host_ports_key(hid)),
                ))
                i += 1
    return Pod(GENERATIONS["v5p"], grid, hosts)


@pytest.fixture
def kv():
    return MemoryKV()


@pytest.fixture
def pod(kv):
    return make_pod(kv)


@pytest.fixture
def sched(pod, kv):
    return PodScheduler(pod, kv)


@pytest.fixture
def svc(pod, sched, kv):
    return JobService(pod, sched, StateStore(kv), VersionMap(kv, keys.VERSIONS_JOB_KEY))


class TestPodScheduler:
    def test_full_pod_slice(self, sched):
        grant = sched.apply_slice(n_chips=32, owner="big-1")
        assert grant.n_chips == 32
        assert len(grant.hosts) == 8
        assert grant.host_block_shape == (2, 2, 2)
        assert grant.ici_contiguous

    def test_multi_host_block_is_contiguous(self, pod, sched):
        grant = sched.apply_slice(n_chips=16, owner="j-1")
        assert len(grant.hosts) == 4
        coords = [pod.hosts[h].grid_coord for h, _ in grant.hosts]
        spans = [max(c[d] for c in coords) - min(c[d] for c in coords) + 1
                 for d in range(3)]
        assert spans[0] * spans[1] * spans[2] == 4  # fills its bounding box

    def test_host_granularity_enforced(self, sched):
        with pytest.raises(errors.BadRequest):
            sched.apply_slice(n_chips=6, owner="odd-1")  # 1.5 hosts
        with pytest.raises(errors.BadRequest):
            sched.apply_slice(n_chips=24, owner="odd-2")  # 6 hosts ∤ 2x2x2

    def test_sub_host_delegates_to_one_host(self, pod, sched):
        grant = sched.apply_slice(n_chips=2, owner="small-1")
        assert len(grant.hosts) == 1
        host_id, chips = grant.hosts[0]
        assert len(chips) == 2
        assert set(pod.hosts[host_id].chips.free_chips) == {0, 1, 2, 3} - set(chips)

    def test_sub_host_tightest_fit(self, pod, sched):
        sched.apply_slice(n_chips=2, owner="a-1")
        # next 2-chip ask should pack onto the same (now tightest) host
        grant = sched.apply_slice(n_chips=2, owner="b-1")
        assert grant.hosts[0][0] == "h0"
        assert pod.hosts["h0"].chips.free_chips == []

    def test_partial_host_blocks_multi_host_slice(self, sched):
        sched.apply_slice(n_chips=1, owner="frag-1")  # dirties one host
        grant = sched.apply_slice(n_chips=16, owner="j-1")  # still 7 clean hosts? need 4
        assert len(grant.hosts) == 4
        with pytest.raises(errors.ChipNotEnough):
            sched.apply_slice(n_chips=16, owner="j2-1")  # only 3 clean hosts left

    def test_restore_slice_owner_guarded(self, pod, sched):
        grant = sched.apply_slice(n_chips=8, owner="j-1")
        sched.restore_slice("j-1")
        for host_id, chips in grant.hosts:
            assert set(chips) <= set(pod.hosts[host_id].chips.free_chips)
        sched.restore_slice("j-1")  # double restore is a no-op
        assert sched.get_grant("j-1") is None

    def test_duplicate_owner_rejected(self, sched):
        sched.apply_slice(n_chips=4, owner="j-1")
        with pytest.raises(errors.ContainerExisted):
            sched.apply_slice(n_chips=4, owner="j-1")

    def test_grants_survive_restart(self, pod, kv, sched):
        sched.apply_slice(n_chips=16, owner="j-1")
        # new scheduler over the same KV (crash-restart) sees the grant and
        # the per-host chip claims
        pod2 = make_pod(kv)
        sched2 = PodScheduler(pod2, kv)
        g = sched2.get_grant("j-1")
        assert g is not None and g.n_chips == 16
        with pytest.raises(errors.ChipNotEnough):
            sched2.apply_slice(n_chips=32, owner="j2-1")

    def test_status_view(self, sched):
        sched.apply_slice(n_chips=8, owner="j-1")
        st = sched.status()
        assert st["totalChips"] == 32
        assert st["chipsPerHost"] == 4
        assert st["freeHosts"] == 6
        assert st["globalMeshShape"] == [4, 4, 2]
        assert "j-1" in st["slices"]


class TestGangClaims:
    """PodScheduler.apply_slices — the gang-level all-or-nothing claim the
    job flows commit through (one lock hold, one persist / one deferred
    StoreTxn commit for the WHOLE gang)."""

    def test_whole_gang_is_one_apply(self):
        kv = CountingKV(MemoryKV())
        pod = make_pod(kv)
        sched = PodScheduler(pod, kv)
        txn = StoreTxn(kv)
        base = kv.snapshot()
        grants = sched.apply_slices([(f"g#{k}", 8, "") for k in range(4)],
                                    txn=txn)
        assert [len(g.hosts) for g in grants] == [2, 2, 2, 2]
        # nothing written yet: every participant deferred into the txn
        assert CountingKV.delta(base, kv.snapshot()) == {}
        txn.commit()
        # slice registry + all 8 host chip maps = ONE store round trip
        assert CountingKV.delta(base, kv.snapshot()) == {"apply": 1}
        # and the commit is durable: a restarted scheduler sees every grant
        sched2 = PodScheduler(make_pod(kv), kv)
        assert all(sched2.get_grant(f"g#{k}") is not None for k in range(4))

    def test_infeasible_member_releases_whole_gang(self, pod, sched):
        # 3×16 chips > the pod's 32: the third member cannot place
        with pytest.raises(errors.ChipNotEnough):
            sched.apply_slices([("g#0", 16, ""), ("g#1", 16, ""),
                                ("g#2", 16, "")])
        for k in range(3):
            assert sched.get_grant(f"g#{k}") is None
        # every chip of the unwound members is allocatable again
        grant = sched.apply_slice(n_chips=32, owner="whole")
        assert grant.n_chips == 32

    def test_duplicate_owner_mid_batch_releases_earlier_members(self, sched):
        sched.apply_slice(n_chips=4, owner="taken")
        with pytest.raises(errors.ContainerExisted):
            sched.apply_slices([("fresh", 4, ""), ("taken", 4, "")])
        assert sched.get_grant("fresh") is None
        # 'fresh' was fully unwound: the same owner can claim again
        assert sched.apply_slice(n_chips=4, owner="fresh").n_chips == 4

    def test_txn_failure_persists_nothing(self):
        kv = MemoryKV()
        pod = make_pod(kv)
        sched = PodScheduler(pod, kv)
        txn = StoreTxn(kv)
        with pytest.raises(errors.ChipNotEnough):
            sched.apply_slices([("g#0", 16, ""), ("g#1", 16, ""),
                                ("g#2", 16, "")], txn=txn)
        # the failed gang never touched the store: a fresh boot sees a
        # completely clean pod
        assert kv.range_prefix("") == {}
        sched2 = PodScheduler(make_pod(kv), kv)
        assert sched2.apply_slice(n_chips=32, owner="all").n_chips == 32


class TestJobService:
    def test_run_multi_host_job(self, pod, svc):
        info = svc.run_job(JobRun(image_name="maxtext:tpu", job_name="train",
                                  chip_count=16, cmd=["python", "train.py"],
                                  binds=["/nfs/ckpt:/ckpt"]))
        assert info["name"] == "train-0"
        assert len(info["processes"]) == 4
        # one container per host, running, with the distributed bootstrap env
        seen_hosts = set()
        for proc in info["processes"]:
            host = pod.hosts[proc["hostId"]]
            seen_hosts.add(proc["hostId"])
            ci = host.runtime.container_inspect(proc["container"])
            assert ci.running
            env = dict(e.split("=", 1) for e in ci.spec.env)
            assert env["JAX_PROCESS_ID"] == str(proc["processId"])
            assert env["JAX_NUM_PROCESSES"] == "4"
            assert env["CLOUD_TPU_TASK_ID"] == str(proc["processId"])
            assert env["TPU_CHIPS_PER_PROCESS_BOUNDS"] == "2,2,1"
            assert env["TPU_PROCESS_BOUNDS"].count(",") == 2
            assert len(env["TPU_PROCESS_ADDRESSES"].split(",")) == 4
            assert "/nfs/ckpt:/ckpt" in ci.spec.binds
            # coordinator names process 0's host
            assert env["JAX_COORDINATOR_ADDRESS"].startswith(
                pod.hosts[info["processes"][0]["hostId"]].address)
        assert len(seen_hosts) == 4

    def test_multislice_job(self, pod, svc, sched):
        """numSlices=2 ⇒ two independent ICI slices stitched over DCN:
        per-slice libtpu mesh env, MEGASCALE_* on every process, megascale
        port published on slice 0's first container."""
        info = svc.run_job(JobRun(image_name="i", job_name="ms",
                                  chip_count=16, num_slices=2))
        assert info["numSlices"] == 2
        assert len(info["processes"]) == 4  # 2 slices x 2 hosts
        ms_port = info["megascalePort"]
        assert ms_port > 0
        for proc in info["processes"]:
            host = pod.hosts[proc["hostId"]]
            ci = host.runtime.container_inspect(proc["container"])
            env = dict(e.split("=", 1) for e in ci.spec.env)
            assert env["MEGASCALE_NUM_SLICES"] == "2"
            assert env["MEGASCALE_SLICE_ID"] == str(proc["sliceId"])
            assert env["MEGASCALE_PORT"] == str(ms_port)
            # the libtpu ICI mesh is scoped to THIS slice (2 hosts), not
            # the whole 4-process job
            assert len(env["TPU_PROCESS_ADDRESSES"].split(",")) == 2
            assert env["JAX_NUM_PROCESSES"] == "4"  # DCN level sees all
        assert {p["sliceId"] for p in info["processes"]} == {0, 1}
        # global process 0 carries coordinator + megascale port bindings
        p0 = info["processes"][0]
        ci0 = pod.hosts[p0["hostId"]].runtime.container_inspect(p0["container"])
        bound = {pb.host_port for pb in ci0.spec.port_bindings}
        assert info["coordinatorPort"] in bound and ms_port in bound

    def test_multislice_rescale_and_delete_free_all_slices(self, pod, svc,
                                                           sched):
        svc.run_job(JobRun(image_name="i", job_name="ms", chip_count=8,
                           num_slices=2))
        free_after_run = sum(
            len(h.chips.free_chips) for h in pod.hosts.values())
        assert free_after_run == 32 - 8
        # rescale keeps the slice count, doubles the chips
        info = svc.patch_job_chips("ms-0", JobPatchChips(chip_count=16))
        assert info["numSlices"] == 2
        assert info["chipCount"] == 16
        svc.delete_job("ms-1", JobDelete(force=True,
                                         del_state_and_version_record=True))
        assert sum(len(h.chips.free_chips) for h in pod.hosts.values()) == 32

    def test_multislice_indivisible_chip_count_rejected(self, svc):
        with pytest.raises(errors.BadRequest, match="divide"):
            svc.run_job(JobRun(image_name="i", job_name="bad",
                               chip_count=10, num_slices=3))

    def test_multislice_accelerator_type_rejected(self, svc):
        """acceleratorType sizes ONE slice; combined with numSlices > 1 it
        would over-allocate the type per slice — rejected up front."""
        with pytest.raises(errors.BadRequest, match="acceleratorType"):
            svc.run_job(JobRun(image_name="i", job_name="bad",
                               accelerator_type="v5p-8", num_slices=2))

    def test_process_bounds_match_host_block(self, pod, svc):
        info = svc.run_job(JobRun(image_name="i", job_name="j", chip_count=32))
        ci = pod.hosts[info["processes"][0]["hostId"]].runtime.container_inspect(
            info["processes"][0]["container"])
        env = dict(e.split("=", 1) for e in ci.spec.env)
        assert env["TPU_PROCESS_BOUNDS"] == "2,2,2"

    def test_single_host_job(self, pod, svc):
        info = svc.run_job(JobRun(image_name="i", job_name="small", chip_count=2))
        assert len(info["processes"]) == 1
        ci = pod.hosts[info["processes"][0]["hostId"]].runtime.container_inspect(
            info["processes"][0]["container"])
        env = dict(e.split("=", 1) for e in ci.spec.env)
        assert env["TPU_PROCESS_BOUNDS"] == "1,1,1"

    def test_accelerator_type_ask(self, svc):
        info = svc.run_job(JobRun(image_name="i", job_name="j",
                                  accelerator_type="v5p-64"))
        assert info["chipCount"] == 32
        assert len(info["processes"]) == 8

    def test_duplicate_job_rejected(self, svc):
        svc.run_job(JobRun(image_name="i", job_name="j", chip_count=4))
        with pytest.raises(errors.ContainerExisted):
            svc.run_job(JobRun(image_name="i", job_name="j", chip_count=4))

    def test_rolling_rescale_grows(self, pod, svc, sched):
        svc.run_job(JobRun(image_name="i", job_name="t", chip_count=8,
                           binds=["/nfs/ckpt:/ckpt"]))
        info = svc.patch_job_chips("t", JobPatchChips(chip_count=16))
        assert info["name"] == "t-1"
        assert info["chipCount"] == 16
        # old version quiesced (stopped, not removed), new running, old slice freed
        old = svc.store.get_job("t-0")
        assert old.desired_running is False
        for host_id, cname, *_ in old.placements:
            assert pod.hosts[host_id].runtime.container_inspect(cname).running is False
        assert sched.get_grant("t-0") is None
        assert sched.get_grant("t-1") is not None
        # checkpoint bind carried over
        p0 = info["processes"][0]
        ci = pod.hosts[p0["hostId"]].runtime.container_inspect(p0["container"])
        assert "/nfs/ckpt:/ckpt" in ci.spec.binds

    def test_rescale_noop(self, svc):
        svc.run_job(JobRun(image_name="i", job_name="t", chip_count=8))
        with pytest.raises(errors.NoPatchRequired):
            svc.patch_job_chips("t", JobPatchChips(chip_count=8))

    def test_rescale_uses_freed_capacity(self, svc):
        """Grow 16→32 on a 32-chip pod: only possible because the old slice is
        quiesced and freed before the new allocation."""
        svc.run_job(JobRun(image_name="i", job_name="t", chip_count=16))
        info = svc.patch_job_chips("t", JobPatchChips(chip_count=32))
        assert info["chipCount"] == 32

    def test_rescale_version_check(self, svc):
        svc.run_job(JobRun(image_name="i", job_name="t", chip_count=8))
        with pytest.raises(errors.VersionNotMatch):
            svc.patch_job_chips("t-7", JobPatchChips(chip_count=16))

    def test_stop_restart(self, pod, svc):
        info = svc.run_job(JobRun(image_name="i", job_name="t", chip_count=8))
        svc.stop_job("t")
        for proc in info["processes"]:
            assert not pod.hosts[proc["hostId"]].runtime.container_inspect(
                proc["container"]).running
        svc.restart_job("t")
        for proc in info["processes"]:
            assert pod.hosts[proc["hostId"]].runtime.container_inspect(
                proc["container"]).running

    def test_delete_frees_everything(self, pod, svc, sched):
        svc.run_job(JobRun(image_name="i", job_name="t", chip_count=16))
        svc.patch_job_chips("t", JobPatchChips(chip_count=8))
        svc.delete_job("t", JobDelete(force=True, del_state_and_version_record=True))
        for host in pod.hosts.values():
            assert len(host.chips.free_chips) == 4
            assert host.ports.status()["usedPorts"] == []
            assert host.runtime.container_list() == []
        assert svc.versions.get("t") is None

    def test_job_info_live_state(self, svc):
        svc.run_job(JobRun(image_name="i", job_name="t", chip_count=8))
        info = svc.get_job_info("t")
        assert all(p["running"] for p in info["processes"])
        svc.stop_job("t")
        info = svc.get_job_info("t")
        assert not any(p["running"] for p in info["processes"])

    def test_rescale_fast_path_frees_old_slice(self, svc, sched):
        """Grow 8→16 with room for both: allocate-first path; old slice freed
        after the swap, historical version still inspectable."""
        svc.run_job(JobRun(image_name="i", job_name="t", chip_count=8))
        info = svc.patch_job_chips("t", JobPatchChips(chip_count=16))
        assert info["chipCount"] == 16
        assert sched.get_grant("t-0") is None
        assert sched.get_grant("t-1") is not None
        old = svc.get_job_info("t-0")  # historical read allowed
        assert old["desiredRunning"] is False
        assert not any(p["running"] for p in old["processes"])

    def test_unsatisfiable_rescale_leaves_job_untouched(self, pod, svc):
        """Deterministic validation errors (non-host-multiple, > pod size)
        must not bounce a healthy job through quiesce/relaunch."""
        info = svc.run_job(JobRun(image_name="i", job_name="t", chip_count=8))
        with pytest.raises(errors.BadRequest):
            svc.patch_job_chips("t", JobPatchChips(chip_count=6))
        with pytest.raises(errors.ChipNotEnough):
            svc.patch_job_chips("t", JobPatchChips(chip_count=64))
        with pytest.raises(errors.BadRequest):
            # 24 chips = 6 hosts: no 6-host axis-aligned block tiles a 2x2x2
            # grid — deterministic shape infeasibility, not capacity
            svc.patch_job_chips("t", JobPatchChips(chip_count=24))
        assert svc.get_job_info("t")["name"] == "t-0"
        for proc in info["processes"]:
            assert pod.hosts[proc["hostId"]].runtime.container_inspect(
                proc["container"]).running

    def test_rescale_swap_failure_resumes_old(self, pod, svc, sched, monkeypatch):
        """If the new version fails to start after the old quiesced, the old
        version is resumed and the new one fully torn down."""
        svc.run_job(JobRun(image_name="i", job_name="t", chip_count=8))
        calls = {"n": 0}
        orig = svc._start_members

        def exploding_start(st):
            calls["n"] += 1
            if calls["n"] == 1:  # fail the new version; let the resume work
                raise RuntimeError("docker daemon away")
            return orig(st)

        monkeypatch.setattr(svc, "_start_members", exploding_start)
        with pytest.raises(RuntimeError):
            svc.patch_job_chips("t", JobPatchChips(chip_count=16))
        monkeypatch.setattr(svc, "_start_members", orig)
        # old version is latest again, running, holding its slice
        info = svc.get_job_info("t")
        assert info["name"] == "t-0"
        assert sched.get_grant("t-0") is not None
        assert sched.get_grant("t-1") is None
        for proc in info["processes"]:
            assert pod.hosts[proc["hostId"]].runtime.container_inspect(
                proc["container"]).running
        # and a later rescale still works
        assert svc.patch_job_chips("t", JobPatchChips(chip_count=16))["chipCount"] == 16

    def test_heterogeneous_pod_rejected(self, kv):
        hosts = make_pod(kv).hosts
        lst = list(hosts.values())
        lst[0].topology = HostTopology.build("v5e-8")
        with pytest.raises(ValueError, match="heterogeneous"):
            Pod(GENERATIONS["v5p"], (2, 2, 2), lst)

    def test_duplicate_host_id_rejected(self, kv):
        lst = list(make_pod(kv, grid=(2, 1, 1)).hosts.values())
        lst[1].host_id = lst[0].host_id
        with pytest.raises(ValueError, match="duplicate host ids"):
            Pod(GENERATIONS["v5p"], (2, 1, 1), lst)

    def test_bad_job_names_rejected(self, svc):
        for bad in ("", "a/b", "a b", "a-b"):
            with pytest.raises(errors.BadRequest):
                svc.run_job(JobRun(image_name="i", job_name=bad, chip_count=4))

    def test_daemon_local_host_shares_chip_accounting(self):
        """A pod_hosts entry with local=true must reuse the container
        service's chip scheduler — chips handed to a local container are not
        re-grantable to a job."""
        from tpu_docker_api.config import Config
        from tpu_docker_api.daemon import Program
        from tpu_docker_api.schemas.container import ContainerRun

        cfg = Config(port=0, runtime_backend="fake", accelerator_type="v5p-8",
                     health_watch_interval=0,
                     pod_hosts=[
                         {"host_id": "me", "address": "10.0.0.1",
                          "grid_coord": [0, 0, 0], "local": True},
                         {"host_id": "h1", "address": "10.0.0.2",
                          "grid_coord": [1, 0, 0], "runtime_backend": "fake"},
                     ])
        prg = Program(cfg)
        prg.init()
        try:
            assert prg.pod.hosts["me"].chips is prg.chip_scheduler
            assert prg.pod.hosts["me"].runtime is prg.runtime
            prg.container_svc.run_container(ContainerRun(
                image_name="i", container_name="c", chip_count=3))
            # "me" now has 1 free chip; an 8-chip (2-host) job cannot use it
            with pytest.raises(errors.ChipNotEnough):
                prg.job_svc.run_job(JobRun(image_name="i", job_name="j",
                                           chip_count=8))
            # but a 4-chip job fits on the clean remote host
            info = prg.job_svc.run_job(JobRun(image_name="i", job_name="j2",
                                              chip_count=4))
            assert info["processes"][0]["hostId"] == "h1"
        finally:
            prg.wq.close()

    def test_create_failure_rolls_back(self, pod, svc, sched):
        # occupy a name on one host so container_create collides
        victim_host = pod.hosts["h0"]
        from tpu_docker_api.runtime.spec import ContainerSpec
        victim_host.runtime.container_create(
            ContainerSpec(name="boom-0-p0", image="x"))
        with pytest.raises(errors.ContainerExisted):
            svc.run_job(JobRun(image_name="i", job_name="boom", chip_count=32))
        # everything returned: slice grant gone, chips free, no version record
        assert sched.get_grant("boom-0") is None
        for host in pod.hosts.values():
            assert len(host.chips.free_chips) == 4
        assert svc.versions.get("boom") is None
