"""The inference-serving entrypoint (python -m tpu_docker_api.serve) — the
container command for BASELINE config #3 deployments."""

import json
import os
import signal
import subprocess
import sys
import time
import urllib.error
import urllib.request

import pytest

#: JAX-compile heavy: excluded from the `-m 'not slow'` quick tier so it
#: fits its time budget; still runs in `make test` (the full suite)
pytestmark = pytest.mark.slow


REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _post(port, path, payload, timeout=60):
    req = urllib.request.Request(
        f"http://127.0.0.1:{port}{path}",
        data=json.dumps(payload).encode(),
        headers={"Content-Type": "application/json"},
    )
    with urllib.request.urlopen(req, timeout=timeout) as r:
        return json.loads(r.read())


def _get(port, path, timeout=10):
    with urllib.request.urlopen(
            f"http://127.0.0.1:{port}{path}", timeout=timeout) as r:
        return json.loads(r.read())


def _spawn_server(argv, timeout=180):
    """Spawn `python -m tpu_docker_api.serve --port 0 <argv>` and wait
    for its '"event": "serving"' ready line — THE spawn/readiness
    protocol, in one place (a protocol change must not need N edits)."""
    p = subprocess.Popen(
        [sys.executable, "-m", "tpu_docker_api.serve",
         "--platform", "cpu", "--host", "127.0.0.1", "--port", "0",
         "--virtual-devices", "1", *argv],
        stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
        env={**os.environ, "PYTHONPATH": REPO})
    port, lines = None, []
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if p.poll() is not None:
            raise RuntimeError(
                "server died:\n" + "".join(lines) + p.stdout.read())
        line = p.stdout.readline()
        lines.append(line)
        if '"event": "serving"' in line:
            port = json.loads(line)["port"]
            break
    assert port, "server never became ready:\n" + "".join(lines)
    return p, port


@pytest.fixture(scope="module")
def server():
    port = 18791
    env = {**os.environ, "PYTHONPATH": REPO}
    p = subprocess.Popen(
        [sys.executable, "-m", "tpu_docker_api.serve",
         "--preset", "tiny", "--platform", "cpu", "--host", "127.0.0.1",
         "--port", str(port), "--max-seq", "64", "--virtual-devices", "1",
         "--slots", "4", "--chunk", "4"],
        stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True, env=env)
    deadline = time.monotonic() + 120
    while time.monotonic() < deadline:
        if p.poll() is not None:
            raise RuntimeError(f"server died: {p.stdout.read()}")
        try:
            if _get(port, "/healthz")["status"] == "ok":
                break
        except (urllib.error.URLError, OSError):
            time.sleep(0.3)
    else:
        p.kill()
        raise RuntimeError("server never became healthy")
    yield port, p
    p.send_signal(signal.SIGTERM)
    p.communicate(timeout=30)


class TestServe:
    def test_healthz(self, server):
        port, _ = server
        h = _get(port, "/healthz")
        assert h["model"] == "tiny"
        assert h["quantized"] is False

    def test_generate(self, server):
        port, _ = server
        out = _post(port, "/generate",
                    {"tokens": [[1, 2, 3, 4]], "maxNewTokens": 8})
        assert len(out["tokens"]) == 1
        assert len(out["tokens"][0]) == 8
        assert out["lengths"] == [8]
        assert all(0 <= t < 256 for t in out["tokens"][0])

    def test_greedy_is_deterministic(self, server):
        port, _ = server
        body = {"tokens": [[5, 6, 7, 8]], "maxNewTokens": 6,
                "temperature": 0.0}
        a = _post(port, "/generate", body)
        b = _post(port, "/generate", body)
        assert a["tokens"] == b["tokens"]

    def test_bad_requests(self, server):
        port, _ = server
        for payload in ({}, {"tokens": []}, {"tokens": [[]]},
                        {"tokens": [[999999]]},
                        {"tokens": [[1, 2]], "maxNewTokens": 0},
                        {"tokens": [[1, 2]], "maxNewTokens": -3},
                        {"tokens": [[1, 2]], "maxNewTokens": 1.9},
                        {"tokens": [[1, 2]], "maxNewTokens": True},
                        {"tokens": [[1, 2]], "topK": "4"}):
            with pytest.raises(urllib.error.HTTPError) as e:
                _post(port, "/generate", payload)
            assert e.value.code == 400

    def test_prefix_register_generate_unregister(self, server):
        port, _ = server
        prefix = [9, 8, 7, 6, 5, 4, 3, 2, 1, 2, 3, 4]
        prompt = prefix + [11, 12]
        base = _post(port, "/generate",
                     {"tokens": [prompt], "maxNewTokens": 6})
        reg = _post(port, "/prefixes", {"tokens": prefix})
        assert reg["length"] == len(prefix)
        (snap,) = _get(port, "/prefixes")["prefixes"]
        assert snap["id"] == reg["prefixId"]
        assert snap["length"] == len(prefix) and snap["bytes"] > 0
        # suffix-only prefill must be token-exact vs the full prefill
        hit = _post(port, "/generate",
                    {"tokens": [prompt], "maxNewTokens": 6})
        assert hit["tokens"] == base["tokens"]
        assert _get(port, "/healthz")["slotEngine"]["prefix_hits"] >= 1
        # register is idempotent; bad bodies 400
        assert _post(port, "/prefixes",
                     {"tokens": prefix})["prefixId"] == reg["prefixId"]
        for bad in ({}, {"tokens": []}, {"tokens": [99999]},
                    {"tokens": "abc"}):
            with pytest.raises(urllib.error.HTTPError) as e:
                _post(port, "/prefixes", bad)
            assert e.value.code == 400
        # DELETE removes it; second delete reports removed: false
        req = urllib.request.Request(
            f"http://127.0.0.1:{port}/prefixes/{reg['prefixId']}",
            method="DELETE")
        with urllib.request.urlopen(req, timeout=30) as r:
            assert json.loads(r.read())["removed"] is True
        with urllib.request.urlopen(
                urllib.request.Request(
                    f"http://127.0.0.1:{port}/prefixes/{reg['prefixId']}",
                    method="DELETE"), timeout=30) as r:
            assert json.loads(r.read())["removed"] is False
        assert _get(port, "/prefixes")["prefixes"] == []

    def test_unknown_route_404(self, server):
        port, _ = server
        with pytest.raises(urllib.error.HTTPError) as e:
            _post(port, "/nope", {})
        assert e.value.code == 404

    def test_ragged_rows_on_slot_path(self, server):
        """Rows of different lengths in one body — each row is its own
        slot-engine request (the legacy dense path can't do this)."""
        port, _ = server
        out = _post(port, "/generate",
                    {"tokens": [[1, 2, 3, 4, 5, 6], [9, 8]],
                     "maxNewTokens": 5})
        assert len(out["tokens"]) == 2
        assert all(len(r) == 5 for r in out["tokens"])
        assert out["lengths"] == [5, 5]

    def test_concurrent_clients_share_the_engine(self, server):
        """4 clients in flight at once — all complete, and healthz shows
        the slot engine actually ran them (no gen_lock serialization)."""
        import threading

        port, _ = server
        results = [None] * 4

        def client(i):
            results[i] = _post(port, "/generate",
                               {"tokens": [[i + 1, i + 2, i + 3]],
                                "maxNewTokens": 6}, timeout=120)

        threads = [threading.Thread(target=client, args=(i,))
                   for i in range(4)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=120)
        assert all(r is not None and len(r["tokens"][0]) == 6
                   for r in results)
        h = _get(port, "/healthz")
        assert h["slotEngine"]["completed"] >= 4
        assert h["slotEngine"]["slots"] == 4

    def test_streaming_ndjson(self, server):
        """stream:true — chunked ndjson, one token line at a time, then a
        done line; tokens equal the non-streamed greedy response."""
        port, _ = server
        body = {"tokens": [[4, 9, 2]], "maxNewTokens": 6,
                "temperature": 0.0}
        plain = _post(port, "/generate", body)
        req = urllib.request.Request(
            f"http://127.0.0.1:{port}/generate",
            data=json.dumps({**body, "stream": True}).encode(),
            headers={"Content-Type": "application/json"})
        lines = []
        with urllib.request.urlopen(req, timeout=120) as r:
            assert r.headers["Content-Type"] == "application/x-ndjson"
            for line in r:
                lines.append(json.loads(line))
        assert lines[-1]["done"] is True
        toks = [ln["t"] for ln in lines[:-1]]
        assert toks == plain["tokens"][0]
        assert lines[-1]["length"] == plain["lengths"][0]

    def test_streaming_rejects_multi_row(self, server):
        port, _ = server
        with pytest.raises(urllib.error.HTTPError) as e:
            _post(port, "/generate", {"tokens": [[1, 2], [3, 4]],
                                      "maxNewTokens": 2, "stream": True})
        assert e.value.code == 400
        # top-k streams fine now (slot path handles filtered sampling)
        req = urllib.request.Request(
            f"http://127.0.0.1:{port}/generate",
            data=json.dumps({"tokens": [[1, 2]], "maxNewTokens": 3,
                             "topK": 3, "temperature": 0.5,
                             "stream": True}).encode(),
            headers={"Content-Type": "application/json"})
        with urllib.request.urlopen(req, timeout=120) as r:
            lines = [json.loads(line) for line in r]
        assert lines[-1]["done"] is True
        assert len(lines) == 4  # 3 token lines + done

    def test_eos_id_truncates(self, server):
        port, _ = server
        base = {"tokens": [[6, 2, 8]], "maxNewTokens": 8,
                "temperature": 0.0}
        free = _post(port, "/generate", base)
        eos = free["tokens"][0][2]
        out = _post(port, "/generate", {**base, "eosId": eos})
        n = out["lengths"][0]
        assert n == free["tokens"][0].index(eos) + 1
        assert out["tokens"][0][n - 1] == eos
        assert out["tokens"][0][n:] == [0] * (8 - n)  # padded

    def test_topk_topp_on_slot_path(self, server):
        """top-k/top-p serve through the slot engine too (round-3: the
        filtered chunk variant) — ragged rows included."""
        port, _ = server
        out = _post(port, "/generate",
                    {"tokens": [[5, 6, 7], [1, 2]], "maxNewTokens": 4,
                     "topK": 3, "temperature": 0.9, "topP": 0.95})
        assert [len(r) for r in out["tokens"]] == [4, 4]
        # top_p out of range is a 400, not a 500
        with pytest.raises(urllib.error.HTTPError) as e:
            _post(port, "/generate",
                  {"tokens": [[1, 2]], "maxNewTokens": 2, "topP": 0.0,
                   "temperature": 0.9})
        assert e.value.code == 400

    def test_greedy_matches_slotless_server(self, server):
        """The slot engine's output is token-exact vs a --slots 0 server
        with identical params (same preset, same init seed)."""
        port, _ = server
        body = {"tokens": [[7, 3, 2, 9]], "maxNewTokens": 6,
                "temperature": 0.0}
        a = _post(port, "/generate", body)

        port2 = 18795
        env = {**os.environ, "PYTHONPATH": REPO}
        p2 = subprocess.Popen(
            [sys.executable, "-m", "tpu_docker_api.serve",
             "--preset", "tiny", "--platform", "cpu", "--host", "127.0.0.1",
             "--port", str(port2), "--max-seq", "64",
             "--virtual-devices", "1", "--slots", "0"],
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
            env=env)
        try:
            deadline = time.monotonic() + 120
            while time.monotonic() < deadline:
                if p2.poll() is not None:
                    raise RuntimeError(f"server died: {p2.stdout.read()}")
                try:
                    if _get(port2, "/healthz")["status"] == "ok":
                        break
                except (urllib.error.URLError, OSError):
                    time.sleep(0.3)
            else:
                raise RuntimeError("slotless server never became healthy")
            assert "slotEngine" not in _get(port2, "/healthz")
            b = _post(port2, "/generate", body)
        finally:
            p2.send_signal(signal.SIGTERM)
            p2.communicate(timeout=30)
        assert a["tokens"] == b["tokens"]

    def test_graceful_stop_last(self, server):
        # fixture teardown asserts SIGTERM exits cleanly via communicate();
        # here just confirm the process is still alive at end of suite
        _, p = server
        assert p.poll() is None


class TestServeQuantized:
    def test_quantized_server_generates(self):
        port = 18792
        env = {**os.environ, "PYTHONPATH": REPO}
        p = subprocess.Popen(
            [sys.executable, "-m", "tpu_docker_api.serve",
             "--preset", "tiny", "--platform", "cpu", "--host", "127.0.0.1",
             "--port", str(port), "--max-seq", "64", "--quantize",
             "--virtual-devices", "1"],
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
            env=env)
        try:
            deadline = time.monotonic() + 120
            while time.monotonic() < deadline:
                if p.poll() is not None:
                    raise RuntimeError(f"server died: {p.stdout.read()}")
                try:
                    if _get(port, "/healthz")["quantized"]:
                        break
                except (urllib.error.URLError, OSError):
                    time.sleep(0.3)
            else:
                raise RuntimeError("server never became healthy")
            out = _post(port, "/generate",
                        {"tokens": [[1, 2, 3]], "maxNewTokens": 4})
            assert len(out["tokens"][0]) == 4
        finally:
            p.send_signal(signal.SIGTERM)
            p.communicate(timeout=30)


class TestMeshServing:
    def test_serves_on_tp_mesh_with_slot_engine(self):
        """A 2-device tp mesh: round-3 final — the slot engine runs ON
        the mesh (kv heads sharded over tp, slots replicated), so
        multi-chip models get continuous batching too."""
        port = 18796
        env = {**os.environ, "PYTHONPATH": REPO}
        p = subprocess.Popen(
            [sys.executable, "-m", "tpu_docker_api.serve",
             "--preset", "tiny", "--platform", "cpu", "--host", "127.0.0.1",
             "--port", str(port), "--max-seq", "64",
             "--virtual-devices", "2", "--tp", "2"],
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
            env=env)
        try:
            deadline = time.monotonic() + 120
            while time.monotonic() < deadline:
                if p.poll() is not None:
                    raise RuntimeError(f"server died: {p.stdout.read()}")
                try:
                    h = _get(port, "/healthz")
                    if h["status"] == "ok":
                        break
                except (urllib.error.URLError, OSError):
                    time.sleep(0.3)
            else:
                raise RuntimeError("mesh server never became healthy")
            assert h["devices"] == 2
            assert h["slotEngine"]["slots"] > 0  # engine ON the mesh
            out = _post(port, "/generate",
                        {"tokens": [[1, 2, 3]], "maxNewTokens": 4,
                         "temperature": 0.0}, timeout=120)
            assert len(out["tokens"][0]) == 4
            assert _get(port, "/healthz")["slotEngine"]["completed"] >= 1
        finally:
            p.send_signal(signal.SIGTERM)
            p.communicate(timeout=30)


class TestSpeculativeServing:
    def test_draft_preset_serves_greedy_exact_and_falls_back_sampled(self):
        """--draft-preset tiny (same weights as the target: acceptance 1)
        — greedy responses must match a plain server; sampled requests
        fall back to the legacy path instead of 400ing."""
        port = 18797
        env = {**os.environ, "PYTHONPATH": REPO}
        p = subprocess.Popen(
            [sys.executable, "-m", "tpu_docker_api.serve",
             "--preset", "tiny", "--platform", "cpu", "--host", "127.0.0.1",
             "--port", str(port), "--max-seq", "64",
             "--virtual-devices", "1", "--slots", "2",
             "--draft-preset", "tiny", "--n-spec", "3"],
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
            env=env)
        try:
            deadline = time.monotonic() + 180
            while time.monotonic() < deadline:
                if p.poll() is not None:
                    raise RuntimeError(f"server died: {p.stdout.read()}")
                try:
                    h = _get(port, "/healthz")
                    if h["status"] == "ok":
                        break
                except (urllib.error.URLError, OSError):
                    time.sleep(0.3)
            else:
                raise RuntimeError("spec server never became healthy")
            assert h["slotEngine"]["speculative"] is True
            assert h["slotEngine"]["nSpec"] == 3
            body = {"tokens": [[7, 3, 2, 9]], "maxNewTokens": 6,
                    "temperature": 0.0}
            out = _post(port, "/generate", body, timeout=120)
            # same preset + same init seed as the module fixture server:
            # compare against a fresh isolated greedy reference instead
            # (no second server needed — greedy spec is exact by
            # construction and the engine tests prove it; here we check
            # the serving contract shape + sampled fallback)
            assert len(out["tokens"][0]) == 6
            sampled = _post(port, "/generate",
                            {"tokens": [[1, 2, 3]], "maxNewTokens": 4,
                             "temperature": 0.9}, timeout=120)
            assert len(sampled["tokens"][0]) == 4  # legacy fallback
        finally:
            p.send_signal(signal.SIGTERM)
            p.communicate(timeout=30)


class TestLoraServing:
    def test_serve_merged_adapters(self, tmp_path):
        """Train tiny adapters (CLI, sharded mesh), then serve with
        --lora-ckpt on one device: the adapter checkpoint restores
        across mesh shapes and merges into the base at load."""
        env = {**os.environ, "PYTHONPATH": REPO}
        ckpt = tmp_path / "adapters"
        r = subprocess.run(
            [sys.executable, "-m", "tpu_docker_api.train",
             "--preset", "tiny", "--batch", "8", "--seq", "32",
             "--steps", "4", "--platform", "cpu", "--virtual-devices", "4",
             "--fsdp", "2", "--lora-rank", "2", "--ckpt-dir", str(ckpt),
             "--save-every", "2", "--log-every", "2"],
            capture_output=True, text=True, env=env, timeout=300)
        assert r.returncode == 0, r.stdout + r.stderr
        port = 18795
        p = subprocess.Popen(
            [sys.executable, "-m", "tpu_docker_api.serve",
             "--preset", "tiny", "--platform", "cpu", "--host", "127.0.0.1",
             "--port", str(port), "--max-seq", "64",
             "--virtual-devices", "1", "--lora-ckpt", str(ckpt),
             "--lora-rank", "2"],
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
            env=env)
        try:
            deadline = time.monotonic() + 120
            while time.monotonic() < deadline:
                if p.poll() is not None:
                    raise RuntimeError(f"server died: {p.stdout.read()}")
                try:
                    if _get(port, "/healthz")["status"] == "ok":
                        break
                except (urllib.error.URLError, OSError):
                    time.sleep(0.3)
            out = _post(port, "/generate",
                        {"tokens": [[1, 2, 3]], "maxNewTokens": 4})
            assert len(out["tokens"][0]) == 4
        finally:
            p.terminate()
            p.wait(timeout=30)

    def test_lora_ckpt_without_rank_exits(self):
        env = {**os.environ, "PYTHONPATH": REPO}
        r = subprocess.run(
            [sys.executable, "-m", "tpu_docker_api.serve",
             "--preset", "tiny", "--platform", "cpu",
             "--virtual-devices", "1", "--lora-ckpt", "/nope"],
            capture_output=True, text=True, env=env, timeout=120)
        assert r.returncode != 0
        assert "--lora-rank" in r.stderr + r.stdout


class TestFamilyPresets:
    def _spawn(self, preset, extra=()):
        return _spawn_server(["--preset", preset, *extra], timeout=120)

    def test_moe_preset_serves(self):
        p, port = self._spawn("moe:moe-tiny", ("--max-seq", "64"))
        try:
            out = _post(port, "/generate",
                        {"tokens": [[1, 2, 3]], "maxNewTokens": 4,
                         "temperature": 0.0}, timeout=180)
            assert len(out["tokens"][0]) == 4
        finally:
            p.terminate()
            p.wait(timeout=30)

    def test_encdec_preset_serves_seq2seq(self):
        """Round 4: encdec rides its own slot engine — ragged sources,
        lengths always reported, concurrent clients share the chip."""
        p, port = self._spawn("encdec:tiny")
        try:
            out = _post(port, "/generate",
                        {"srcTokens": [[5, 6, 7, 8]], "maxNewTokens": 4,
                         "temperature": 0.0}, timeout=180)
            assert len(out["tokens"][0]) == 4
            assert out["lengths"] == [4]  # slot-path contract
            # ragged rows in one body — impossible on the legacy path
            ragged = _post(port, "/generate",
                           {"srcTokens": [[5, 6, 7, 8], [9, 1]],
                            "maxNewTokens": 4, "temperature": 0.0},
                           timeout=120)
            assert ragged["tokens"][0] == out["tokens"][0]
            # top_k=1 is exact greedy; a free temperature draw stays
            # in-vocab
            out_k1 = _post(port, "/generate",
                           {"srcTokens": [[5, 6, 7, 8]], "maxNewTokens": 4,
                            "temperature": 0.7, "topK": 1}, timeout=60)
            assert out_k1["tokens"] == out["tokens"]
            out_t = _post(port, "/generate",
                          {"srcTokens": [[1, 2]], "maxNewTokens": 2,
                           "temperature": 0.7}, timeout=60)
            assert all(0 <= t < 256 for t in out_t["tokens"][0])
            # eosId truncates (pad tail + lengths, host-side)
            eos = out["tokens"][0][1]
            out2 = _post(port, "/generate",
                         {"srcTokens": [[5, 6, 7, 8]], "maxNewTokens": 4,
                          "temperature": 0.0, "eosId": eos}, timeout=60)
            assert out2["lengths"] == [2]
            assert out2["tokens"][0][:2] == out["tokens"][0][:2]
            h = _get(port, "/healthz")
            assert h["slotEngine"]["completed"] >= 5
        finally:
            p.terminate()
            p.wait(timeout=30)

    def test_encdec_legacy_path_with_slots_0(self):
        """--slots 0 keeps the serialized legacy contract: equal-length
        rows, lengths only with eosId."""
        p, port = self._spawn("encdec:tiny", ("--slots", "0"))
        try:
            out = _post(port, "/generate",
                        {"srcTokens": [[5, 6, 7, 8]], "maxNewTokens": 4,
                         "temperature": 0.0}, timeout=180)
            assert len(out["tokens"][0]) == 4
            assert "lengths" not in out
            with pytest.raises(urllib.error.HTTPError):
                _post(port, "/generate",
                      {"srcTokens": [[1, 2], [3, 4, 5]],
                       "maxNewTokens": 2})
        finally:
            p.terminate()
            p.wait(timeout=30)


class TestHFCheckpointServing:
    """--hf-ckpt: an HF-layout safetensors checkpoint (+ tokenizer.json)
    serves end-to-end, token-exact vs the in-tree tree it was exported
    from, and {"text": ...} bodies round-trip through the tokenizer."""

    @pytest.fixture(scope="class")
    def hf_dir(self, tmp_path_factory):
        import jax as _jax

        from tpu_docker_api.models.import_weights import export_hf_llama
        from tpu_docker_api.models.llama import llama_init, llama_presets
        from tokenizers import Tokenizer as RustTokenizer
        from tokenizers.models import WordLevel
        from tokenizers.pre_tokenizers import Whitespace

        cfg = llama_presets()["tiny"]
        # PRNGKey(0) = the same tree a random-init `serve --preset tiny`
        # builds, so greedy outputs must match the plain server's
        params = llama_init(cfg, _jax.random.PRNGKey(0))
        out = tmp_path_factory.mktemp("hf-tiny")
        export_hf_llama(params, cfg, str(out))
        vocab = {w: i for i, w in enumerate(
            ["<unk>"] + [f"w{i}" for i in range(1, 32)])}
        tok = RustTokenizer(WordLevel(vocab, unk_token="<unk>"))
        tok.pre_tokenizer = Whitespace()
        tok.save(str(out / "tokenizer.json"))
        return str(out)

    def _spawn(self, hf_dir, extra=()):
        return _spawn_server(["--hf-ckpt", hf_dir, "--max-seq", "64",
                              "--slots", "4", "--chunk", "4", *extra])

    def test_hf_ckpt_serves_token_exact_with_text(self, hf_dir, server):
        base_port, _ = server
        p, port = self._spawn(hf_dir)
        try:
            h = _get(port, "/healthz")
            assert h["tokenizer"] is True
            body = {"tokens": [[1, 2, 3, 4]], "maxNewTokens": 6,
                    "temperature": 0.0}
            assert (_post(port, "/generate", body)["tokens"]
                    == _post(base_port, "/generate", body)["tokens"])
            out = _post(port, "/generate",
                        {"text": ["w1 w3 w2"], "maxNewTokens": 4,
                         "temperature": 0.0})
            assert out["lengths"] == [4]
            assert isinstance(out["texts"][0], str)
            # text+tokens together is a 400, as is text w/o tokenizer
            with pytest.raises(urllib.error.HTTPError):
                _post(port, "/generate",
                      {"text": ["w1"], "tokens": [[1]],
                       "maxNewTokens": 2})
            # /prefixes accepts ONE text string through the tokenizer
            reg = _post(port, "/prefixes", {"text": "w1 w2 w3 w4"})
            assert reg["length"] == 4
            with pytest.raises(urllib.error.HTTPError) as e:
                _post(port, "/prefixes", {"text": ["w1"]})
            assert e.value.code == 400
            # streaming text mode: id lines + full decoded text on done
            req = urllib.request.Request(
                f"http://127.0.0.1:{port}/generate",
                data=json.dumps({"text": ["w2 w1"], "maxNewTokens": 3,
                                 "temperature": 0.0,
                                 "stream": True}).encode(),
                headers={"Content-Type": "application/json"})
            with urllib.request.urlopen(req, timeout=120) as r:
                stream_lines = [json.loads(ln) for ln in
                                r.read().decode().splitlines() if ln]
            assert stream_lines[-1]["done"] is True
            assert isinstance(stream_lines[-1]["text"], str)
        finally:
            p.terminate()
            p.wait(timeout=30)

    def test_hf_ckpt_quantized_int8_at_load(self, hf_dir):
        p, port = self._spawn(hf_dir, ("--quantize",))
        try:
            assert _get(port, "/healthz")["quantized"] is True
            out = _post(port, "/generate",
                        {"tokens": [[1, 2, 3]], "maxNewTokens": 4})
            assert len(out["tokens"][0]) == 4
        finally:
            p.terminate()
            p.wait(timeout=30)

    def test_text_without_tokenizer_400(self, server):
        port, _ = server
        with pytest.raises(urllib.error.HTTPError) as e:
            _post(port, "/generate", {"text": ["hi"], "maxNewTokens": 2})
        assert e.value.code == 400


class TestPagedServing:
    def test_page_size_serves_with_page_stats(self):
        p, port = _spawn_server(
            ["--preset", "tiny", "--max-seq", "64", "--slots", "4",
             "--chunk", "4", "--page-size", "16", "--total-pages", "8"])
        try:
            h = _get(port, "/healthz")
            assert h["slotEngine"]["pages_total"] == 8
            out = _post(port, "/generate",
                        {"tokens": [[1, 2, 3, 4], [9, 8]],
                         "maxNewTokens": 6, "temperature": 0.0})
            assert [len(r) for r in out["tokens"]] == [6, 6]
            # r5: /prefixes composes with the paged engine via shared
            # pages — registration runs on the live engine thread
            px = list(range(2, 20))  # ≥ one 16-token page
            reg = _post(port, "/prefixes", {"tokens": px})
            assert reg["length"] == len(px)
            free_before = _get(port, "/healthz")["slotEngine"]["pages_free"]
            out = _post(port, "/generate",
                        {"tokens": [px + [21, 22]], "maxNewTokens": 4,
                         "temperature": 0.0})
            assert len(out["tokens"][0]) == 4
            h = _get(port, "/healthz")["slotEngine"]
            assert h["prefix_hits"] >= 1
            assert h["pages_free"] == free_before  # private pages freed
            # a sub-page prefix still refuses loudly (shares nothing)
            with pytest.raises(urllib.error.HTTPError) as e:
                _post(port, "/prefixes", {"tokens": [1, 2, 3]})
            assert e.value.code == 400
        finally:
            p.terminate()
            p.wait(timeout=30)


def _get_text(port, path, timeout=10):
    with urllib.request.urlopen(
            f"http://127.0.0.1:{port}{path}", timeout=timeout) as r:
        return r.read().decode()


class TestServingSLOMetrics:
    """GET /metrics SLO export (VERDICT r4 next #5): TTFT/ITL
    histograms per completed request, the engine-side percentile
    snapshot in /healthz, and the paged pressure gauges."""

    def test_metrics_histograms_under_load(self, server):
        port, _ = server
        for i in range(3):
            _post(port, "/generate",
                  {"tokens": [[1 + i, 2, 3]], "maxNewTokens": 4,
                   "temperature": 0.0})
        text = _get_text(port, "/metrics")
        for name in ("serve_ttft_seconds", "serve_itl_seconds"):
            assert f"# TYPE {name} histogram" in text
            count = next(ln for ln in text.splitlines()
                         if ln.startswith(f"{name}_count"))
            assert float(count.split()[-1]) >= 3
        completed = next(ln for ln in text.splitlines()
                         if ln.startswith("serve_requests_completed_total"))
        assert float(completed.split()[-1]) >= 3
        # monotonic series export as TYPE counter, not gauge (rate()
        # reset-handling depends on the hint)
        assert "# TYPE serve_requests_completed_total counter" in text
        # engine-side percentile snapshot rides /healthz
        lat = _get(port, "/healthz")["slotEngine"]["latency"]
        assert lat["n"] >= 3
        assert lat["ttft_p50_ms"] is not None and lat["ttft_p50_ms"] > 0
        assert lat["itl_p50_ms"] is not None and lat["itl_p50_ms"] >= 0

    def test_paged_pool_gauges(self):
        p, port = _spawn_server(
            ["--preset", "tiny", "--max-seq", "64", "--slots", "4",
             "--chunk", "4", "--page-size", "16", "--total-pages", "8"])
        try:
            _post(port, "/generate",
                  {"tokens": [[1, 2, 3]], "maxNewTokens": 4,
                   "temperature": 0.0})
            text = _get_text(port, "/metrics")
            free = next(ln for ln in text.splitlines()
                        if ln.startswith("serve_pages_free"))
            assert float(free.split()[-1]) == 8  # all returned
            assert any(ln.startswith("serve_deferred_admissions_total")
                       for ln in text.splitlines())
            assert ("# TYPE serve_deferred_admissions_total counter"
                    in text)
        finally:
            p.terminate()
            p.wait(timeout=30)


class TestPagedTensorParallelServing:
    def test_page_size_on_tp_mesh(self):
        """r5: --page-size composes with --tp — the pool's kv-head dim
        shards over the mesh and /prefixes still works."""
        p, port = _spawn_server(
            ["--preset", "tiny", "--max-seq", "64", "--slots", "4",
             "--chunk", "4", "--page-size", "16", "--total-pages", "12",
             "--virtual-devices", "2", "--tp", "2"])
        try:
            h = _get(port, "/healthz")
            assert h["devices"] == 2
            assert h["slotEngine"]["pages_total"] == 12
            out = _post(port, "/generate",
                        {"tokens": [[1, 2, 3, 4], [9, 8]],
                         "maxNewTokens": 6, "temperature": 0.0})
            assert [len(r) for r in out["tokens"]] == [6, 6]
            px = list(range(2, 20))
            _post(port, "/prefixes", {"tokens": px})
            out = _post(port, "/generate",
                        {"tokens": [px + [21]], "maxNewTokens": 4,
                         "temperature": 0.0})
            assert len(out["tokens"][0]) == 4
            assert (_get(port, "/healthz")["slotEngine"]["prefix_hits"]
                    >= 1)
        finally:
            p.terminate()
            p.wait(timeout=30)


class TestPagedChunkedPrefillServing:
    def test_page_size_with_prefill_chunk(self):
        """r5: --page-size composes with --prefill-chunk — a prompt
        past the largest prefill bucket serves through page-aware
        segments."""
        p, port = _spawn_server(
            ["--preset", "tiny", "--max-seq", "96", "--slots", "4",
             "--chunk", "4", "--page-size", "16", "--total-pages", "16",
             "--prefill-chunk", "8"])
        try:
            long_prompt = list(range(3, 43))  # 40 tokens
            out = _post(port, "/generate",
                        {"tokens": [long_prompt], "maxNewTokens": 6,
                         "temperature": 0.0})
            assert len(out["tokens"][0]) == 6
            h = _get(port, "/healthz")["slotEngine"]
            assert h["segment_prefills"] >= 1
            assert h["pages_free"] == h["pages_total"]
        finally:
            p.terminate()
            p.wait(timeout=30)
