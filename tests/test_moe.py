"""MoE model + expert parallelism tests (SURVEY.md §2.3 EP row).

Runs on the virtual 8-device CPU mesh from conftest. Covers: routing
invariants (capacity, top-k mass), dense-reference equivalence of the
dispatch/combine einsum path, training-step integration through the generic
trainer, and ep-sharded vs single-device numerical agreement (the all-to-all
lowering must not change the math).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from tpu_docker_api.models.moe import (
    MoEConfig,
    _moe_mlp,
    _route,
    moe_forward,
    moe_init,
    moe_loss,
    moe_presets,
)
from tpu_docker_api.parallel.mesh import MeshPlan, build_mesh


def tiny_cfg(**kw) -> MoEConfig:
    import dataclasses

    return dataclasses.replace(moe_presets()["moe-tiny"], **kw)


class TestRouting:
    def test_dispatch_respects_capacity(self):
        cfg = tiny_cfg(n_experts=4, top_k=2, capacity_factor=1.0)
        x = jax.random.normal(jax.random.PRNGKey(0), (64, cfg.dim))
        router = jax.random.normal(jax.random.PRNGKey(1), (cfg.dim, 4))
        dispatch, combine, aux = _route(x, router, cfg)
        C = cfg.capacity(64)
        # each (expert, slot) holds at most one token
        per_slot = jnp.sum(dispatch, axis=0)  # (E, C)
        assert float(jnp.max(per_slot)) <= 1.0 + 1e-6
        assert dispatch.shape == (64, 4, C)
        # every kept token's combine mass ≤ 1 (normalized top-k gates)
        per_token = jnp.sum(combine, axis=(1, 2))
        assert float(jnp.max(per_token)) <= 1.0 + 1e-5
        assert np.isfinite(float(aux))

    def test_top1_token_always_kept_with_headroom(self):
        """With capacity_factor ≥ E (absurd headroom) nothing is dropped."""
        cfg = tiny_cfg(n_experts=4, top_k=2, capacity_factor=8.0)
        x = jax.random.normal(jax.random.PRNGKey(0), (32, cfg.dim))
        router = jax.random.normal(jax.random.PRNGKey(1), (cfg.dim, 4))
        dispatch, combine, _ = _route(x, router, cfg)
        # all 32 tokens placed for both choices
        assert float(jnp.sum(dispatch)) == pytest.approx(64.0)
        per_token = jnp.sum(combine, axis=(1, 2))
        np.testing.assert_allclose(np.asarray(per_token), 1.0, atol=1e-5)

    def test_moe_mlp_matches_dense_reference(self):
        """Dispatch/combine einsums == explicit per-token top-k expert sum
        when nothing overflows."""
        cfg = tiny_cfg(n_experts=4, top_k=2, capacity_factor=8.0)
        key = jax.random.PRNGKey(0)
        params = moe_init(cfg, key)
        layer_moe = jax.tree_util.tree_map(lambda p: p[0], params["layers"]["moe"])
        x = jax.random.normal(jax.random.PRNGKey(2), (2, 8, cfg.dim),
                              dtype=cfg.dtype)
        out, _ = _moe_mlp(x, layer_moe, cfg, mesh=None)

        # dense reference: every expert on every token, combined by gates
        xf = x.reshape(-1, cfg.dim)
        logits = xf.astype(jnp.float32) @ layer_moe["router"]
        probs = jax.nn.softmax(logits, axis=-1)
        gv, gi = jax.lax.top_k(probs, cfg.top_k)
        gv = gv / jnp.sum(gv, axis=-1, keepdims=True)
        ref = jnp.zeros_like(xf)
        for e in range(cfg.n_experts):
            h = jax.nn.silu(xf @ layer_moe["w_gate"][e]) * (
                xf @ layer_moe["w_up"][e])
            ye = h @ layer_moe["w_down"][e]
            w = jnp.sum(jnp.where(gi == e, gv, 0.0), axis=-1)
            ref = ref + w[:, None].astype(xf.dtype) * ye
        np.testing.assert_allclose(
            np.asarray(out.reshape(-1, cfg.dim), dtype=np.float32),
            np.asarray(ref, dtype=np.float32), atol=2e-2, rtol=2e-2)


class TestMoEModel:
    def test_forward_shapes_and_finite(self):
        cfg = tiny_cfg()
        params = moe_init(cfg, jax.random.PRNGKey(0))
        tokens = jax.random.randint(jax.random.PRNGKey(1), (2, 16), 0,
                                    cfg.vocab_size, dtype=jnp.int32)
        logits, aux = jax.jit(lambda p, t: moe_forward(p, t, cfg))(params, tokens)
        assert logits.shape == (2, 16, cfg.vocab_size)
        assert logits.dtype == jnp.float32
        assert np.isfinite(np.asarray(logits)).all()
        assert np.isfinite(float(aux))

    def test_loss_includes_aux_and_is_finite(self):
        cfg = tiny_cfg()
        params = moe_init(cfg, jax.random.PRNGKey(0))
        tokens = jax.random.randint(jax.random.PRNGKey(1), (2, 17), 0,
                                    cfg.vocab_size, dtype=jnp.int32)
        loss = float(moe_loss(params, tokens, cfg))
        assert np.isfinite(loss)
        assert loss > 0

    def test_ep_sharded_matches_single_device(self):
        cfg = tiny_cfg()
        params = moe_init(cfg, jax.random.PRNGKey(0))
        tokens = jax.random.randint(jax.random.PRNGKey(1), (4, 16), 0,
                                    cfg.vocab_size, dtype=jnp.int32)
        l_single = float(moe_loss(params, tokens, cfg))
        mesh = build_mesh(MeshPlan(dp=2, fsdp=1, tp=1, sp=1, ep=4))
        with mesh:
            l_ep = float(jax.jit(
                lambda p, t: moe_loss(p, t, cfg, mesh))(params, tokens))
        np.testing.assert_allclose(l_ep, l_single, rtol=2e-2, atol=2e-2)


class TestMoEInference:
    def test_cached_prefill_matches_full_forward(self):
        from tpu_docker_api.infer.engine import init_kv_cache
        from tpu_docker_api.models.moe import moe_forward_cached

        # f32 model: the training forward applies rope in the storage dtype
        # while the cached path applies it in f32 (ops/rope.py); with an f32
        # model both coincide, so this stays a TIGHT cache-mechanics gate
        cfg = tiny_cfg(dtype=jnp.float32)
        params = moe_init(cfg, jax.random.PRNGKey(0))
        tokens = jax.random.randint(jax.random.PRNGKey(1), (2, 16), 0,
                                    cfg.vocab_size, dtype=jnp.int32)
        full, _ = moe_forward(params, tokens, cfg)
        cache = init_kv_cache(cfg, 2, 32, dtype=jnp.float32)
        cached, _, _ = moe_forward_cached(
            params, tokens, cfg, cache.k, cache.v, jnp.int32(0))
        np.testing.assert_allclose(np.asarray(full), np.asarray(cached),
                                   rtol=2e-4, atol=2e-4)

    def test_generate_runs_on_moe(self):
        """The serving engine is model-agnostic: MoE configs dispatch to
        moe_forward_cached through models.cached_forward_fn."""
        from tpu_docker_api.infer.engine import (
            GenerateConfig, make_generate_fn)

        cfg = tiny_cfg()
        params = moe_init(cfg, jax.random.PRNGKey(0))
        fn = make_generate_fn(
            cfg, GenerateConfig(max_new_tokens=8, temperature=0.0,
                                max_seq=64), mesh=None)
        prompt = jax.random.randint(jax.random.PRNGKey(2), (2, 12), 0,
                                    cfg.vocab_size, dtype=jnp.int32)
        out = fn(params, prompt, jax.random.PRNGKey(3))
        assert out["tokens"].shape == (2, 8)
        assert (np.asarray(out["tokens"]) >= 0).all()


class TestMoETrainer:
    def test_train_step_over_ep_mesh(self):
        from tpu_docker_api.train.trainer import (
            create_train_state,
            make_train_step,
            synthetic_batch,
        )

        cfg = tiny_cfg()
        mesh = build_mesh(MeshPlan(dp=2, fsdp=1, tp=1, sp=1, ep=4))
        state, opt = create_train_state(cfg, mesh, jax.random.PRNGKey(0))
        step = make_train_step(cfg, mesh, opt)
        tokens = synthetic_batch(jax.random.PRNGKey(1), 4, 16, cfg.vocab_size)
        losses = []
        for _ in range(3):
            state, metrics = step(state, tokens)
            losses.append(float(metrics["loss"]))
        assert all(np.isfinite(l) for l in losses)
        assert losses[-1] < losses[0]  # memorizes the repeated batch

    def test_expert_weights_sharded_on_ep(self):
        from tpu_docker_api.train.trainer import create_train_state

        cfg = tiny_cfg()
        mesh = build_mesh(MeshPlan(dp=2, fsdp=1, tp=1, sp=1, ep=4))
        state, _ = create_train_state(cfg, mesh, jax.random.PRNGKey(0))
        spec = state.params["layers"]["moe"]["w_gate"].sharding.spec
        assert "ep" in str(spec)


class TestDispatchImplEquivalence:
    """The gather/scatter dispatch (single-device fast path) must produce
    EXACTLY the einsum dispatch's output — same routing, same drops, same
    gate weighting — in both training and drop-free (decode) modes."""

    @pytest.mark.parametrize("drop_free", [False, True])
    def test_paths_agree(self, drop_free):
        import dataclasses

        from tpu_docker_api.models.moe import _moe_mlp

        cfg = moe_presets()["moe-tiny"]
        params = moe_init(dataclasses.replace(cfg, n_layers=1),
                          jax.random.PRNGKey(0))
        layer_moe = jax.tree_util.tree_map(lambda p: p[0],
                                           params["layers"]["moe"])
        x = jax.random.normal(jax.random.PRNGKey(1), (2, 32, cfg.dim),
                              cfg.dtype)
        out_g, aux_g = _moe_mlp(
            x, layer_moe, dataclasses.replace(cfg, dispatch_impl="gather"),
            mesh=None, drop_free=drop_free)
        out_e, aux_e = _moe_mlp(
            x, layer_moe, dataclasses.replace(cfg, dispatch_impl="einsum"),
            mesh=None, drop_free=drop_free)
        np.testing.assert_allclose(
            np.asarray(out_g, np.float32), np.asarray(out_e, np.float32),
            rtol=1e-5, atol=1e-5)
        np.testing.assert_allclose(float(aux_g), float(aux_e), rtol=1e-6)

    def test_gradients_agree(self):
        import dataclasses

        from tpu_docker_api.models.moe import _moe_mlp

        cfg = dataclasses.replace(moe_presets()["moe-tiny"], n_layers=1,
                                  dtype=jnp.float32)
        params = moe_init(cfg, jax.random.PRNGKey(0))
        layer_moe = jax.tree_util.tree_map(lambda p: p[0],
                                           params["layers"]["moe"])
        x = jax.random.normal(jax.random.PRNGKey(1), (2, 32, cfg.dim),
                              jnp.float32)

        def loss(impl, lm, x):
            out, aux = _moe_mlp(
                x, lm, dataclasses.replace(cfg, dispatch_impl=impl),
                mesh=None)
            return jnp.sum(out.astype(jnp.float32) ** 2) + aux

        g_g = jax.grad(lambda lm, x: loss("gather", lm, x),
                       argnums=(0, 1))(layer_moe, x)
        g_e = jax.grad(lambda lm, x: loss("einsum", lm, x),
                       argnums=(0, 1))(layer_moe, x)
        jax.tree_util.tree_map(
            lambda a, b: np.testing.assert_allclose(
                np.asarray(a), np.asarray(b), rtol=2e-4, atol=2e-5),
            g_g, g_e)


class TestDispatchImplValidation:
    def test_unknown_impl_raises(self):
        import dataclasses

        from tpu_docker_api.models.moe import _moe_mlp

        cfg = dataclasses.replace(moe_presets()["moe-tiny"], n_layers=1,
                                  dispatch_impl="scatter")
        params = moe_init(cfg, jax.random.PRNGKey(0))
        layer_moe = jax.tree_util.tree_map(lambda p: p[0],
                                           params["layers"]["moe"])
        x = jnp.zeros((1, 8, cfg.dim), cfg.dtype)
        with pytest.raises(ValueError, match="unknown dispatch impl"):
            _moe_mlp(x, layer_moe, cfg, mesh=None)

    def test_gather_on_mesh_raises(self):
        import dataclasses

        from tpu_docker_api.models.moe import _moe_mlp
        from tpu_docker_api.parallel.mesh import MeshPlan, build_mesh

        cfg = dataclasses.replace(moe_presets()["moe-tiny"], n_layers=1,
                                  dispatch_impl="gather")
        params = moe_init(cfg, jax.random.PRNGKey(0))
        layer_moe = jax.tree_util.tree_map(lambda p: p[0],
                                           params["layers"]["moe"])
        x = jnp.zeros((2, 8, cfg.dim), cfg.dtype)
        mesh = build_mesh(MeshPlan(dp=2), devices=jax.devices()[:2])
        with pytest.raises(ValueError, match="single-device only"):
            _moe_mlp(x, layer_moe, cfg, mesh=mesh)


class TestSortDispatch:
    """The "sort" (dense-packed, constrained) dispatch — round 4's
    answer to the einsum path's measured 2.6x MFU deficit: identical
    math to gather on one device, mesh-legal via ep constraints."""

    @pytest.mark.parametrize("drop_free", [False, True])
    def test_sort_equals_gather_single_device(self, drop_free):
        import dataclasses

        from tpu_docker_api.models.moe import _moe_mlp

        cfg = moe_presets()["moe-tiny"]
        params = moe_init(dataclasses.replace(cfg, n_layers=1),
                          jax.random.PRNGKey(0))
        layer_moe = jax.tree_util.tree_map(lambda p: p[0],
                                           params["layers"]["moe"])
        x = jax.random.normal(jax.random.PRNGKey(1), (2, 32, cfg.dim),
                              cfg.dtype)
        out_s, aux_s = _moe_mlp(
            x, layer_moe, dataclasses.replace(cfg, dispatch_impl="sort"),
            mesh=None, drop_free=drop_free)
        out_g, aux_g = _moe_mlp(
            x, layer_moe, dataclasses.replace(cfg, dispatch_impl="gather"),
            mesh=None, drop_free=drop_free)
        np.testing.assert_array_equal(np.asarray(out_s),
                                      np.asarray(out_g))
        assert float(aux_s) == float(aux_g)

    def test_sort_gradients_equal_gather(self):
        import dataclasses

        from tpu_docker_api.models.moe import _moe_mlp

        cfg = dataclasses.replace(moe_presets()["moe-tiny"], n_layers=1,
                                  dtype=jnp.float32)
        params = moe_init(cfg, jax.random.PRNGKey(0))
        layer_moe = jax.tree_util.tree_map(lambda p: p[0],
                                           params["layers"]["moe"])
        x = jax.random.normal(jax.random.PRNGKey(1), (2, 32, cfg.dim),
                              jnp.float32)

        def loss(impl, lm, x):
            out, aux = _moe_mlp(
                x, lm, dataclasses.replace(cfg, dispatch_impl=impl),
                mesh=None)
            return jnp.sum(out.astype(jnp.float32) ** 2) + aux

        g_s = jax.grad(lambda lm, x: loss("sort", lm, x),
                       argnums=(0, 1))(layer_moe, x)
        g_g = jax.grad(lambda lm, x: loss("gather", lm, x),
                       argnums=(0, 1))(layer_moe, x)
        jax.tree_util.tree_map(
            lambda a, b: np.testing.assert_array_equal(
                np.asarray(a), np.asarray(b)),
            g_s, g_g)

    def test_sort_on_ep_mesh_matches_single_device(self):
        """The mesh-legality claim: sort compiles and runs under GSPMD
        on a dp×ep mesh and agrees with the single-device result."""
        import dataclasses

        cfg = dataclasses.replace(tiny_cfg(), dispatch_impl="sort")
        params = moe_init(cfg, jax.random.PRNGKey(0))
        tokens = jax.random.randint(jax.random.PRNGKey(1), (4, 16), 0,
                                    cfg.vocab_size, dtype=jnp.int32)
        l_single = float(moe_loss(params, tokens, cfg))
        mesh = build_mesh(MeshPlan(dp=2, fsdp=1, tp=1, sp=1, ep=4))
        with mesh:
            l_ep = float(jax.jit(
                lambda p, t: moe_loss(p, t, cfg, mesh))(params, tokens))
        np.testing.assert_allclose(l_ep, l_single, rtol=2e-2, atol=2e-2)

    def test_sort_train_step_over_ep_mesh(self):
        """Grad path under GSPMD: one train step on the ep mesh with
        sort dispatch descends and stays finite."""
        import dataclasses

        from tpu_docker_api.train.trainer import (
            create_train_state, make_train_step, synthetic_batch)

        cfg = dataclasses.replace(tiny_cfg(), dispatch_impl="sort")
        mesh = build_mesh(MeshPlan(dp=2, fsdp=1, tp=1, sp=1, ep=4))
        state, opt = create_train_state(cfg, mesh, jax.random.PRNGKey(0))
        step = make_train_step(cfg, mesh, opt)
        batch = synthetic_batch(jax.random.PRNGKey(1), 4, 16,
                                cfg.vocab_size)
        losses = []
        for _ in range(4):
            state, m = step(state, batch)
            losses.append(float(m["loss"]))
        assert all(np.isfinite(l) for l in losses)
        assert losses[-1] < losses[0]


class TestAutoDispatchSelection:
    """r5 (VERDICT r4 weak #4): auto must select the form the hardware
    evidence favors — gather on one device, SORT on meshes (the einsum
    form's single-device proxy measured 2.6x lower model-flops MFU and
    nothing selected sort before this round)."""

    def test_auto_is_sort_on_mesh_and_gather_solo(self):
        import dataclasses

        from tpu_docker_api.models.moe import _moe_mlp
        from tpu_docker_api.parallel.mesh import MeshPlan, build_mesh

        cfg = moe_presets()["moe-tiny"]
        params = moe_init(dataclasses.replace(cfg, n_layers=1),
                          jax.random.PRNGKey(0))
        layer_moe = jax.tree_util.tree_map(lambda p: p[0],
                                           params["layers"]["moe"])
        x = jax.random.normal(jax.random.PRNGKey(1), (4, 16, cfg.dim),
                              cfg.dtype)
        mesh = build_mesh(MeshPlan(dp=2, fsdp=1, tp=1, sp=1, ep=4))
        # on a mesh: auto == sort bit-for-bit (and therefore NOT the
        # einsum form, whose bf16 contraction order differs)
        out_auto, aux_auto = _moe_mlp(
            x, layer_moe, dataclasses.replace(cfg, dispatch_impl="auto"),
            mesh=mesh)
        out_sort, aux_sort = _moe_mlp(
            x, layer_moe, dataclasses.replace(cfg, dispatch_impl="sort"),
            mesh=mesh)
        np.testing.assert_array_equal(np.asarray(out_auto),
                                      np.asarray(out_sort))
        assert float(aux_auto) == float(aux_sort)
        # single device: auto == gather, unchanged
        out_a1, aux_a1 = _moe_mlp(
            x, layer_moe, dataclasses.replace(cfg, dispatch_impl="auto"),
            mesh=None)
        out_g1, aux_g1 = _moe_mlp(
            x, layer_moe,
            dataclasses.replace(cfg, dispatch_impl="gather"), mesh=None)
        np.testing.assert_array_equal(np.asarray(out_a1),
                                      np.asarray(out_g1))
        assert float(aux_a1) == float(aux_g1)
