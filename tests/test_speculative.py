"""Speculative decoding: the greedy-exactness contract.

The whole point of exact verification is that the output equals
target-only greedy decoding REGARDLESS of the draft — a perfect draft
(the target itself) accepts everything, a garbage draft accepts ~nothing,
and both must emit identical text. These tests pin that invariant.
"""

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from tpu_docker_api.infer.engine import GenerateConfig, make_generate_fn
from tpu_docker_api.infer.speculative import (
    SpeculativeConfig,
    make_speculative_generate_fn,
)
from tpu_docker_api.models.llama import llama_init, llama_presets


@pytest.fixture(scope="module")
def models():
    cfg = llama_presets()["tiny"]
    target = llama_init(cfg, jax.random.PRNGKey(0))
    draft = llama_init(cfg, jax.random.PRNGKey(7))  # different weights
    return cfg, target, draft


def _greedy_reference(cfg, params, prompt, n):
    fn = make_generate_fn(
        cfg, GenerateConfig(max_new_tokens=n, temperature=0.0, max_seq=128))
    return np.asarray(fn(params, prompt, jax.random.PRNGKey(0))["tokens"])


@pytest.fixture(scope="module")
def prompt(models):
    cfg = models[0]
    return jax.random.randint(jax.random.PRNGKey(1), (1, 16), 0,
                              cfg.vocab_size, dtype=jnp.int32)


class TestSpeculative:
    def test_perfect_draft_accepts_everything(self, models, prompt):
        """draft == target: every proposal accepted, output == greedy, and
        the round count shows k+1 tokens per round."""
        cfg, target, _ = models
        k, n = 4, 20
        fn = make_speculative_generate_fn(
            cfg, cfg, SpeculativeConfig(max_new_tokens=n, n_speculative=k,
                                        max_seq=128))
        out = fn(target, target, prompt)
        ref = _greedy_reference(cfg, target, prompt, n)
        np.testing.assert_array_equal(np.asarray(out["tokens"]), ref)
        rounds = int(out["rounds"])
        assert rounds <= -(-(n - 1) // (k + 1)) + 1, rounds
        # all proposals accepted in every full round
        assert int(out["accepted"]) >= (rounds - 1) * k

    def test_mismatched_draft_still_exact(self, models, prompt):
        """A draft with different random weights: acceptance may be near
        zero, the emitted text must not change."""
        cfg, target, draft = models
        n = 20
        fn = make_speculative_generate_fn(
            cfg, cfg, SpeculativeConfig(max_new_tokens=n, n_speculative=3,
                                        max_seq=128))
        out = fn(target, draft, prompt)
        ref = _greedy_reference(cfg, target, prompt, n)
        np.testing.assert_array_equal(np.asarray(out["tokens"]), ref)

    def test_draft_architecture_may_differ(self, models, prompt):
        """The draft can be a structurally different (smaller) model."""
        cfg, target, _ = models
        small_cfg = dataclasses.replace(cfg, n_layers=1)
        small = llama_init(small_cfg, jax.random.PRNGKey(9))
        n = 12
        fn = make_speculative_generate_fn(
            cfg, small_cfg,
            SpeculativeConfig(max_new_tokens=n, n_speculative=2, max_seq=128))
        out = fn(target, small, prompt)
        ref = _greedy_reference(cfg, target, prompt, n)
        np.testing.assert_array_equal(np.asarray(out["tokens"]), ref)

    @pytest.mark.parametrize("k", [1, 5])
    def test_k_extremes_exact(self, models, prompt, k):
        cfg, target, draft = models
        n = 11
        fn = make_speculative_generate_fn(
            cfg, cfg, SpeculativeConfig(max_new_tokens=n, n_speculative=k,
                                        max_seq=128))
        out = fn(target, draft, prompt)
        np.testing.assert_array_equal(
            np.asarray(out["tokens"]),
            _greedy_reference(cfg, target, prompt, n))

    def test_budget_one(self, models, prompt):
        cfg, target, draft = models
        fn = make_speculative_generate_fn(
            cfg, cfg, SpeculativeConfig(max_new_tokens=1, n_speculative=4,
                                        max_seq=128))
        out = fn(target, draft, prompt)
        assert out["tokens"].shape == (1, 1)
        np.testing.assert_array_equal(
            np.asarray(out["tokens"]),
            _greedy_reference(cfg, target, prompt, 1))

    def test_capacity_guard(self, models, prompt):
        cfg, target, draft = models
        fn = make_speculative_generate_fn(
            cfg, cfg, SpeculativeConfig(max_new_tokens=200, n_speculative=4,
                                        max_seq=128))
        with pytest.raises(ValueError, match="capacity"):
            fn(target, draft, prompt)
