"""HF safetensors checkpoint import/export (models/import_weights.py).

The golden contract: an HF-layout llama checkpoint — synthetic here, the
real thing in production — must import into the in-tree param tree such
that (a) export→import round-trips bit-exactly, (b) forward passes on
imported weights equal forwards on the originals, (c) int8-at-load
equals import-then-quantize bit-exactly, and (d) tied-embedding and
sharded-index layouts resolve. Plus the tokenizer hook (text↔ids) that
lets serve take {"text": ...}."""

import dataclasses
import json
import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from tpu_docker_api.models.import_weights import (
    HFCheckpoint,
    export_hf_llama,
    hf_llama_config,
    import_hf_llama,
    load_tokenizer,
)
from tpu_docker_api.models.llama import llama_forward, llama_init, llama_presets


@pytest.fixture(scope="module")
def tiny():
    cfg = llama_presets()["tiny"]
    params = llama_init(cfg, jax.random.PRNGKey(0))
    return cfg, params


@pytest.fixture(scope="module")
def exported(tiny, tmp_path_factory):
    cfg, params = tiny
    out = tmp_path_factory.mktemp("hf_ckpt")
    export_hf_llama(params, cfg, str(out))
    return cfg, params, str(out)


def tree_equal(a, b) -> bool:
    la = jax.tree_util.tree_leaves(a)
    lb = jax.tree_util.tree_leaves(b)
    return len(la) == len(lb) and all(
        x.dtype == y.dtype and x.shape == y.shape
        and np.array_equal(np.asarray(x), np.asarray(y))
        for x, y in zip(la, lb))


class TestRoundTrip:
    def test_export_import_bit_exact(self, exported):
        cfg, params, out = exported
        cfg2, imported = import_hf_llama(out)
        assert dataclasses.asdict(cfg2) == dataclasses.asdict(
            dataclasses.replace(cfg, attention_impl=cfg2.attention_impl,
                                remat=cfg2.remat,
                                loss_chunk_rows=cfg2.loss_chunk_rows))
        assert tree_equal(params, imported)

    def test_forward_parity(self, exported):
        cfg, params, out = exported
        _, imported = import_hf_llama(out, cfg)
        toks = jax.random.randint(jax.random.PRNGKey(1), (2, 12), 0,
                                  cfg.vocab_size, dtype=jnp.int32)
        np.testing.assert_array_equal(
            np.asarray(llama_forward(params, toks, cfg)),
            np.asarray(llama_forward(imported, toks, cfg)))

    def test_config_json_written_and_parsed(self, exported):
        cfg, _, out = exported
        hf = json.load(open(os.path.join(out, "config.json")))
        assert hf["architectures"] == ["LlamaForCausalLM"]
        parsed = hf_llama_config(out)
        assert parsed.dim == cfg.dim and parsed.n_layers == cfg.n_layers
        assert parsed.n_kv_heads == cfg.n_kv_heads
        assert parsed.rope_theta == cfg.rope_theta

    def test_explicit_cfg_shape_mismatch_raises(self, exported):
        cfg, _, out = exported
        wrong = dataclasses.replace(cfg, ffn_dim=cfg.ffn_dim * 2)
        with pytest.raises(ValueError, match="shape"):
            import_hf_llama(out, wrong)

    def test_non_llama_architecture_rejected(self, exported, tmp_path):
        _, _, out = exported
        bad = json.load(open(os.path.join(out, "config.json")))
        bad["architectures"] = ["MistralForCausalLM"]
        (tmp_path / "config.json").write_text(json.dumps(bad))
        with pytest.raises(ValueError, match="not a llama"):
            hf_llama_config(str(tmp_path))


_LLAMA31_SCALING = {
    # verbatim block from a real Llama-3.1 config.json — the artifact
    # --hf-ckpt exists for (VERDICT r4 missing #2)
    "rope_type": "llama3",
    "factor": 8.0,
    "low_freq_factor": 1.0,
    "high_freq_factor": 4.0,
    "original_max_position_embeddings": 8192,
}


class TestRopeScalingBridge:
    """A llama-3.1-style rope_scaling block must flow config.json →
    LlamaConfig → the rope tables the forward actually builds — or be
    rejected, never silently ignored."""

    def _config_with(self, exported, tmp_path, block):
        _, _, out = exported
        hf = json.load(open(os.path.join(out, "config.json")))
        if block is None:
            hf.pop("rope_scaling", None)
        else:
            hf["rope_scaling"] = block
        (tmp_path / "config.json").write_text(json.dumps(hf))
        st = tmp_path / "model.safetensors"
        if not st.exists():  # weights unchanged — only the config varies
            os.symlink(os.path.join(out, "model.safetensors"), st)
        return str(tmp_path)

    def test_llama3_block_parses(self, exported, tmp_path):
        cfg = hf_llama_config(
            self._config_with(exported, tmp_path, _LLAMA31_SCALING))
        rs = cfg.rope_scaling
        assert rs is not None and rs.factor == 8.0
        assert rs.low_freq_factor == 1.0 and rs.high_freq_factor == 4.0
        assert rs.original_max_position_embeddings == 8192

    def test_old_style_type_key_parses(self, exported, tmp_path):
        block = dict(_LLAMA31_SCALING)
        block["type"] = block.pop("rope_type")
        cfg = hf_llama_config(
            self._config_with(exported, tmp_path, block))
        assert cfg.rope_scaling is not None

    def test_default_type_is_noop(self, exported, tmp_path):
        cfg = hf_llama_config(self._config_with(
            exported, tmp_path, {"rope_type": "default"}))
        assert cfg.rope_scaling is None

    def test_unknown_type_hard_rejected(self, exported, tmp_path):
        for rtype in ("yarn", "linear", "dynamic", "longrope"):
            with pytest.raises(ValueError, match="not.*supported"):
                hf_llama_config(self._config_with(
                    exported, tmp_path,
                    {"rope_type": rtype, "factor": 2.0}))

    def test_scaling_reaches_forward_tables(self, exported, tmp_path,
                                            monkeypatch):
        """The config's scaling object must be the one the forward's
        table builder receives — parse-but-drop would pass every other
        test here while still computing wrong frequencies."""
        import tpu_docker_api.models.llama as llama_mod
        from tpu_docker_api.ops.rope import rope_frequencies as real_rf

        cfg_dir = self._config_with(exported, tmp_path, _LLAMA31_SCALING)
        cfg, params = import_hf_llama(cfg_dir)
        seen = []

        def spy(head_dim, seq, theta=10000.0, scaling=None):
            seen.append(scaling)
            return real_rf(head_dim, seq, theta, scaling)

        monkeypatch.setattr(llama_mod, "rope_frequencies", spy)
        toks = jnp.zeros((1, 8), jnp.int32)
        llama_forward(params, toks, cfg)
        assert seen == [cfg.rope_scaling]
        assert seen[0].factor == 8.0

    def test_forward_matches_reference_scaled_tables(self, exported,
                                                     tmp_path):
        """Golden: logits under the imported scaling equal logits where
        the ONLY change is rope tables built from an independent
        reference implementation of the llama3 formula (and differ from
        the unscaled forward at positions where scaling bites)."""
        import tpu_docker_api.models.llama as llama_mod
        from unittest import mock

        from tests.test_ops import _ref_llama3_inv_freq

        cfg_dir = self._config_with(exported, tmp_path, _LLAMA31_SCALING)
        cfg, params = import_hf_llama(cfg_dir)
        # f32 end-to-end and positions deep enough (96) that the scaled
        # low-frequency phases measurably diverge from unscaled — in
        # bf16 at short positions the difference drowns in rounding
        cfg = dataclasses.replace(cfg, dtype=jnp.float32)
        params = jax.tree_util.tree_map(
            lambda a: a.astype(jnp.float32), params)
        toks = jax.random.randint(jax.random.PRNGKey(3), (1, 96), 0,
                                  cfg.vocab_size, dtype=jnp.int32)
        got = np.asarray(llama_forward(params, toks, cfg))

        def ref_tables(head_dim, seq, theta=10000.0, scaling=None):
            if scaling is None:
                inv = (1.0 / (theta ** (np.arange(0, head_dim, 2)
                                        / head_dim))).astype(np.float32)
            else:
                inv = _ref_llama3_inv_freq(
                    head_dim, theta, scaling.factor,
                    scaling.low_freq_factor, scaling.high_freq_factor,
                    scaling.original_max_position_embeddings)
            freqs = np.outer(np.arange(seq, dtype=np.float32), inv)
            return jnp.cos(freqs), jnp.sin(freqs)

        with mock.patch.object(llama_mod, "rope_frequencies",
                               ref_tables):
            want = np.asarray(llama_forward(params, toks, cfg))
            unscaled = np.asarray(llama_forward(
                params, toks, dataclasses.replace(cfg,
                                                  rope_scaling=None)))
        np.testing.assert_allclose(got, want, rtol=1e-4, atol=1e-4)
        assert not np.allclose(got, unscaled, rtol=1e-4, atol=1e-4)

    def test_export_round_trips_scaling_block(self, tiny, tmp_path):
        from tpu_docker_api.ops.rope import RopeScaling

        cfg, params = tiny
        scfg = dataclasses.replace(cfg, rope_scaling=RopeScaling(
            factor=8.0, low_freq_factor=1.0, high_freq_factor=4.0,
            original_max_position_embeddings=8192))
        out = tmp_path / "ck"
        export_hf_llama(params, scfg, str(out))
        written = json.load(open(out / "config.json"))["rope_scaling"]
        assert written == _LLAMA31_SCALING
        cfg2, _ = import_hf_llama(str(out))
        assert cfg2.rope_scaling == scfg.rope_scaling


class TestLayouts:
    def test_tied_embeddings(self, tiny, tmp_path):
        """No lm_head.weight in the checkpoint ⇒ the head is the
        embedding transposed (llama-3.2-1B layout)."""
        cfg, params = tiny
        export_hf_llama(params, cfg, str(tmp_path), tie_embeddings=True)
        names = HFCheckpoint(str(tmp_path)).names()
        assert "lm_head.weight" not in names
        _, imported = import_hf_llama(str(tmp_path), cfg)
        np.testing.assert_array_equal(
            np.asarray(imported["lm_head"]),
            np.asarray(params["embed"]["tokens"]).T)

    def test_sharded_index_resolves(self, exported, tmp_path):
        """Two-shard checkpoint + model.safetensors.index.json loads
        identically to the single file."""
        from safetensors.numpy import load_file, save_file

        cfg, params, out = exported
        all_t = load_file(os.path.join(out, "model.safetensors"))
        names = sorted(all_t)
        half = len(names) // 2
        shards = {"model-00001-of-00002.safetensors": names[:half],
                  "model-00002-of-00002.safetensors": names[half:]}
        weight_map = {}
        for fname, keys in shards.items():
            save_file({k: all_t[k] for k in keys}, str(tmp_path / fname))
            weight_map.update({k: fname for k in keys})
        (tmp_path / "model.safetensors.index.json").write_text(
            json.dumps({"metadata": {}, "weight_map": weight_map}))
        (tmp_path / "config.json").write_text(
            open(os.path.join(out, "config.json")).read())
        _, imported = import_hf_llama(str(tmp_path))
        assert tree_equal(params, imported)

    def test_bare_file_path(self, exported):
        cfg, params, out = exported
        _, imported = import_hf_llama(
            os.path.join(out, "model.safetensors"), cfg)
        assert tree_equal(params, imported)

    def test_missing_tensor_raises(self, tmp_path, exported):
        from safetensors.numpy import load_file, save_file

        cfg, _, out = exported
        all_t = load_file(os.path.join(out, "model.safetensors"))
        all_t.pop("model.norm.weight")
        save_file(all_t, str(tmp_path / "model.safetensors"))
        (tmp_path / "config.json").write_text(
            open(os.path.join(out, "config.json")).read())
        with pytest.raises(KeyError, match="model.norm.weight"):
            import_hf_llama(str(tmp_path))


class TestQuantizeAtLoad:
    def test_matches_import_then_quantize(self, exported):
        """Streaming int8-at-load must be bit-identical to importing
        bf16 and quantizing on device — host np.round and device
        jnp.round both round half to even."""
        from tpu_docker_api.infer.quantize import quantize_llama_params

        cfg, _, out = exported
        _, bf16 = import_hf_llama(out, cfg)
        ref = quantize_llama_params(bf16)
        _, q = import_hf_llama(out, cfg, quantize=True)
        assert tree_equal(ref, q)

    def test_generation_runs_on_quantized_import(self, exported):
        from tpu_docker_api.infer.engine import (
            GenerateConfig, make_generate_fn)

        cfg, _, out = exported
        _, q = import_hf_llama(out, cfg, quantize=True)
        fn = make_generate_fn(cfg, GenerateConfig(
            max_new_tokens=6, temperature=0.0, max_seq=64))
        outp = fn(q, jnp.asarray([[1, 2, 3]], jnp.int32),
                  jax.random.PRNGKey(0))
        assert outp["tokens"].shape == (1, 6)


class TestServeParity:
    def test_trained_export_import_serves_identically(self, exported):
        """The e2e the verdict asked for: an in-tree param tree exported
        to HF layout, imported back, and served — greedy tokens must be
        IDENTICAL to serving the original tree."""
        from tpu_docker_api.infer.engine import (
            GenerateConfig, make_generate_fn)

        cfg, params, out = exported
        _, imported = import_hf_llama(out, cfg)
        fn = make_generate_fn(cfg, GenerateConfig(
            max_new_tokens=8, temperature=0.0, max_seq=64))
        prompt = jnp.asarray([[5, 9, 2, 7]], jnp.int32)
        a = fn(params, prompt, jax.random.PRNGKey(0))
        b = fn(imported, prompt, jax.random.PRNGKey(0))
        np.testing.assert_array_equal(np.asarray(a["tokens"]),
                                      np.asarray(b["tokens"]))

    def test_slot_engine_on_imported_weights(self, exported):
        from tpu_docker_api.infer.slots import SlotEngine

        cfg, params, out = exported
        _, imported = import_hf_llama(out, cfg)
        eng = SlotEngine(cfg, imported, slots=2, max_seq=64, chunk=4)
        ref = SlotEngine(cfg, params, slots=2, max_seq=64, chunk=4)
        h1, h2 = eng.submit([1, 2, 3], 6), ref.submit([1, 2, 3], 6)
        for e, h in ((eng, h1), (ref, h2)):
            while not h.done():
                e.step()
        assert h1.result(0)["tokens"] == h2.result(0)["tokens"]


def _write_tiny_tokenizer(path: str, vocab_words: list[str]) -> str:
    """A minimal real tokenizer.json (WordLevel + whitespace split) via
    the tokenizers rust lib — hermetic, no hub traffic."""
    from tokenizers import Tokenizer as RustTokenizer
    from tokenizers.models import WordLevel
    from tokenizers.pre_tokenizers import Whitespace

    vocab = {w: i for i, w in enumerate(vocab_words)}
    tok = RustTokenizer(WordLevel(vocab, unk_token="<unk>"))
    tok.pre_tokenizer = Whitespace()
    tok.save(path)
    return path


class TestTokenizer:
    def test_encode_decode_roundtrip(self, tmp_path):
        path = _write_tiny_tokenizer(
            str(tmp_path / "tokenizer.json"),
            ["<unk>", "hello", "world", "tpu", "serving"])
        tok = load_tokenizer(str(tmp_path / "tokenizer.json"))
        ids = tok.encode("hello tpu world")
        assert ids == [1, 3, 2]
        assert tok.decode(ids) == "hello tpu world"

    def test_directory_with_tokenizer_json(self, tmp_path):
        _write_tiny_tokenizer(str(tmp_path / "tokenizer.json"),
                              ["<unk>", "a", "b"])
        tok = load_tokenizer(str(tmp_path))
        assert tok.encode("b a") == [2, 1]


def test_llama31_preset_matches_real_checkpoint_import():
    """The llama31-8b preset must equal, field for field, what
    importing a verbatim Meta-Llama-3.1-8B config.json produces — the
    preset exists to assert against --hf-ckpt imports, so ANY drift
    (the r5 review caught max_seq_len at 8192 vs the real 131072) must
    fail here."""
    import tempfile

    from tpu_docker_api.models.llama import llama_presets

    cfg_json = {
        "architectures": ["LlamaForCausalLM"], "model_type": "llama",
        "vocab_size": 128256, "hidden_size": 4096,
        "num_hidden_layers": 32, "num_attention_heads": 32,
        "num_key_value_heads": 8, "intermediate_size": 14336,
        "max_position_embeddings": 131072, "rope_theta": 500000.0,
        "rms_norm_eps": 1e-05,
        "rope_scaling": dict(_LLAMA31_SCALING),
    }
    with tempfile.TemporaryDirectory() as d:
        (pathlib := __import__("pathlib")).Path(
            d, "config.json").write_text(json.dumps(cfg_json))
        parsed = hf_llama_config(d)
    preset = llama_presets()["llama31-8b"]
    for f in ("vocab_size", "dim", "n_layers", "n_heads", "n_kv_heads",
              "ffn_dim", "max_seq_len", "rope_theta", "norm_eps",
              "rope_scaling"):
        assert getattr(parsed, f) == getattr(preset, f), f
