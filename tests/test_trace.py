"""Unit tier for the control-plane tracer (telemetry/trace.py):
span-tree shape, ring bounds, link-vs-parentage semantics across the
work queue, crash/orphan parity, and the disabled-mode no-op contract."""

import threading

from tpu_docker_api.state import keys
from tpu_docker_api.state.kv import MemoryKV
from tpu_docker_api.state.workqueue import TaskRecord, WorkQueue
from tpu_docker_api.telemetry import trace
from tpu_docker_api.telemetry.metrics import MetricsRegistry
from tpu_docker_api.telemetry.trace import Tracer


class TestSpanTree:
    def test_child_parentage_and_buffer_grouping(self):
        t = Tracer(buffer_size=8)
        with t.span("root", attrs={"k": "v"}) as root:
            with trace.child("mid") as mid:
                with trace.child("leaf", n=3) as leaf:
                    pass
        assert mid.trace_id == root.trace_id == leaf.trace_id
        assert mid.parent_id == root.span_id
        assert leaf.parent_id == mid.span_id
        view = t.trace_view(root.trace_id)
        assert [s["name"] for s in view["spans"]] == ["root", "mid", "leaf"]
        assert all(s["status"] == "ok" for s in view["spans"])
        assert view["spans"][2]["attrs"] == {"n": 3}
        # durations nest: children never outlast the root
        r, m, le = view["spans"]
        assert r["durationMs"] >= m["durationMs"] >= le["durationMs"] >= 0

    def test_summaries_newest_first_with_root_info(self):
        t = Tracer(buffer_size=8)
        with t.span("first"):
            pass
        with t.span("second"):
            with trace.child("kid"):
                pass
        out = t.summaries()
        assert [i["root"] for i in out["items"]] == ["second", "first"]
        assert out["items"][0]["spans"] == 2
        assert out["items"][0]["rootCount"] == 1
        assert out["dropped"] == 0 and out["openSpans"] == 0

    def test_exception_marks_error_baseexception_marks_lost(self):
        t = Tracer(buffer_size=8)
        try:
            with t.span("bad") as s1:
                raise ValueError("x")
        except ValueError:
            pass
        assert t.trace_view(s1.trace_id)["spans"][0]["status"] == "error"

        class Kill(BaseException):
            pass

        try:
            with t.span("killed") as s2:
                raise Kill()
        except Kill:
            pass
        assert t.trace_view(s2.trace_id)["spans"][0]["status"] == "lost"
        assert t.summaries()["items"][0]["status"] == "lost"

    def test_child_of_cross_thread(self):
        t = Tracer(buffer_size=8)
        with t.span("batch") as batch:
            def work():
                with trace.child_of(batch, "engine.create", key="h1"):
                    pass
            th = threading.Thread(target=work)
            th.start()
            th.join()
        spans = t.trace_view(batch.trace_id)["spans"]
        eng = next(s for s in spans if s["name"] == "engine.create")
        assert eng["parentId"] == batch.span_id
        assert eng["attrs"]["key"] == "h1"


class TestBufferBounds:
    def test_ring_evicts_oldest_and_counts_dropped(self):
        reg = MetricsRegistry()
        t = Tracer(buffer_size=3, registry=reg)
        ids = []
        for i in range(5):
            with t.span(f"r{i}") as s:
                ids.append(s.trace_id)
        assert t.stats()["dropped"] == 2
        assert t.trace_view(ids[0]) is None and t.trace_view(ids[1]) is None
        assert t.trace_view(ids[4]) is not None
        assert reg.counter_value("trace_dropped_total",
                                 {"kind": "trace"}) == 2
        assert len(t.summaries()["items"]) == 3

    def test_per_trace_span_cap(self, monkeypatch):
        monkeypatch.setattr(trace, "MAX_SPANS_PER_TRACE", 4)
        t = Tracer(buffer_size=4)
        with t.span("root") as root:
            for i in range(6):
                with trace.child(f"c{i}"):
                    pass
        view = t.trace_view(root.trace_id)
        assert len(view["spans"]) == 4
        assert view["droppedSpans"] == 3  # 2 surplus children + the root

    def test_orphans_closed_lost_on_tracer_close(self):
        t = Tracer(buffer_size=4)
        scope = t.span("leaked")
        span = scope.__enter__()  # deliberately never exited
        assert t.stats()["openSpans"] == 1
        assert t.close_orphans() == 1
        assert t.stats()["openSpans"] == 0
        assert t.trace_view(span.trace_id)["spans"][0]["status"] == "lost"
        trace._current.reset(scope._token)  # leave a clean context


class TestDisabledMode:
    def test_disabled_tracer_records_nothing(self):
        t = Tracer(buffer_size=4, enabled=False)
        with t.span("nope") as s:
            assert s is None
            # no current span either — children are no-ops too
            with trace.child("kid") as k:
                assert k is None
        assert t.summaries()["items"] == []
        assert t.stats()["openSpans"] == 0

    def test_child_without_active_trace_is_noop(self):
        assert trace.current() is None
        with trace.child("orphan") as s:
            assert s is None

    def test_runtime_toggle(self):
        t = Tracer(buffer_size=4, enabled=True)
        t.set_enabled(False)
        with t.span("off"):
            pass
        assert t.summaries()["items"] == []
        t.set_enabled(True)
        with t.span("on"):
            pass
        assert t.summaries()["items"][0]["root"] == "on"


class TestLoopPassTrim:
    def test_idle_pass_discarded_busy_pass_kept(self):
        t = Tracer(buffer_size=8)
        with trace.pass_span(t, "reconcile.pass"):
            pass  # no children, ok → trimmed
        assert t.summaries()["items"] == []
        with trace.pass_span(t, "reconcile.pass") as busy:
            with trace.child("kv.apply"):
                pass
        assert t.trace_view(busy.trace_id) is not None

    def test_failed_idle_pass_kept(self):
        t = Tracer(buffer_size=8)
        try:
            with trace.pass_span(t, "admission.pass") as s:
                raise RuntimeError("boom")
        except RuntimeError:
            pass
        assert t.trace_view(s.trace_id)["spans"][0]["status"] == "error"

    def test_pass_inside_request_rides_the_request_trace(self):
        t = Tracer(buffer_size=8)
        with t.span("http:GET /api/v1/reconcile") as root:
            with trace.pass_span(t, "reconcile.pass") as p:
                pass
        # a child, not a root — and therefore never trimmed
        assert p.trace_id == root.trace_id
        assert p.parent_id == root.span_id
        names = [s["name"] for s in t.trace_view(root.trace_id)["spans"]]
        assert "reconcile.pass" in names


class TestQueueTraceContext:
    def _drain(self, kv):
        return not kv.range_prefix(keys.QUEUE_TASKS_PREFIX)

    def test_record_json_roundtrip_and_backcompat(self):
        rec = TaskRecord(task_id="t1", kind="put_kv", params={"k": "v"},
                         seq=3, trace_id="tr", span_id="sp")
        back = TaskRecord.from_json(rec.to_json())
        assert (back.trace_id, back.span_id) == ("tr", "sp")
        # a journal written before this field existed still parses
        legacy = ('{"id": "t2", "kind": "put_kv", "params": {}, "seq": 1, '
                  '"state": "pending", "attempts": 0, "error": "", '
                  '"idempotencyKey": ""}')
        old = TaskRecord.from_json(legacy)
        assert old.trace_id == "" and old.span_id == ""

    def test_same_process_execution_continues_the_trace(self):
        kv = MemoryKV()
        t = Tracer(buffer_size=16)
        wq = WorkQueue(kv, metrics=MetricsRegistry(), tracer=t)
        with t.span("http:POST") as root:
            wq.submit_record("put_kv", {"key": "/apis/v1/x", "value": "1"})
        wq.start()
        wq.drain()
        wq.close()
        spans = t.trace_view(root.trace_id)["spans"]
        task = next(s for s in spans if s["name"] == "queue.task:put_kv")
        assert task["parentId"] == root.span_id
        assert task["links"] == []

    def test_adopted_replay_links_origin_trace(self):
        kv = MemoryKV()
        submitter = Tracer(buffer_size=16)
        wq1 = WorkQueue(kv, metrics=MetricsRegistry(), tracer=submitter)
        with submitter.span("http:DELETE") as root:
            wq1.submit_record("put_kv", {"key": "/apis/v1/y", "value": "2"})
        # the submitting daemon "dies": a second queue over the same store
        # adopts the journal (records are not local to it)
        replayer = Tracer(buffer_size=16)
        wq2 = WorkQueue(kv, metrics=MetricsRegistry(), tracer=replayer)
        out = wq2.replay_journal()
        assert [o["state"] for o in out] == ["done"]
        assert self._drain(kv)
        items = replayer.summaries()["items"]
        replay = next(i for i in items if i["root"] == "queue.replay:put_kv")
        # a fresh root LINKING the origin — not parented into it
        assert replay["links"] == [root.trace_id]
        assert replay["rootCount"] == 1


class TestEventStamping:
    def test_stamp_attaches_current_trace_id(self):
        t = Tracer(buffer_size=4)
        evt = {"ts": 1.0, "event": "x"}
        assert "traceId" not in trace.stamp(dict(evt))
        with t.span("root") as s:
            stamped = trace.stamp(dict(evt))
        assert stamped["traceId"] == s.trace_id

    def test_slow_trace_event(self):
        t = Tracer(buffer_size=4, slow_ms=0.0001)
        with t.span("slowroot"):
            pass
        evts = t.events_view()
        assert evts and evts[-1]["event"] == "slow-trace"
        assert evts[-1]["name"] == "slowroot"
        # children never emit slow-trace events, only roots
        t2 = Tracer(buffer_size=4, slow_ms=0.0001)
        with t2.span("r"):
            with trace.child("kid"):
                pass
        assert all(e["name"] == "r" for e in t2.events_view())


class TestTraceparent:
    def test_parse_valid(self):
        tid, sid = "0af7651916cd43dd8448eb211c80319c", "b7ad6b7169203331"
        assert trace.parse_traceparent(f"00-{tid}-{sid}-01") == (tid, sid)

    def test_parse_garbage(self):
        assert trace.parse_traceparent(None) is None
        assert trace.parse_traceparent("") is None
        assert trace.parse_traceparent("xx-yy") is None
        assert trace.parse_traceparent("00-short-b7ad6b7169203331-01") is None
        assert trace.parse_traceparent(
            "00-" + "0" * 32 + "-b7ad6b7169203331-01") is None
        assert trace.parse_traceparent(
            "00-" + "g" * 32 + "-b7ad6b7169203331-01") is None

    def test_format_roundtrip_and_opaque_ids(self):
        t = Tracer(buffer_size=4)
        with t.span("r") as s:
            header = trace.format_traceparent(s)
        assert trace.parse_traceparent(header) == (s.trace_id, s.span_id)
        s.trace_id = "my-opaque-request-id"
        assert trace.format_traceparent(s) == ""


class TestReviewHardening:
    def test_double_finish_never_duplicates_the_span(self):
        # close_orphans racing the owning scope's exit: whoever pops the
        # open entry first records the span; the loser is a no-op
        t = Tracer(buffer_size=4)
        scope = t.span("raced")
        span = scope.__enter__()
        assert t.close_orphans() == 1
        scope.__exit__(None, None, None)  # the late unwind
        view = t.trace_view(span.trace_id)
        assert len(view["spans"]) == 1
        assert view["spans"][0]["status"] == "lost"
        assert t.summaries()["items"][0]["rootCount"] == 1

    def test_find_by_request_id_fallback(self):
        t = Tracer(buffer_size=8)
        with t.span("http:GET /x", trace_id="w3c-trace-id",
                    attrs={"requestId": "userreq"}):
            pass
        assert t.trace_view("userreq") is None
        found = t.find_by_request_id("userreq")
        assert found is not None and found["traceId"] == "w3c-trace-id"
        assert t.find_by_request_id("ghost") is None

    def test_contextless_record_is_a_task_not_a_replay(self):
        # submitted while tracing was off (the bench's disabled-mode
        # pass), executed after re-enable: an ordinary first execution —
        # never labeled queue.replay, never carrying phantom links
        kv = MemoryKV()
        t = Tracer(buffer_size=16, enabled=False)
        wq = WorkQueue(kv, metrics=MetricsRegistry(), tracer=t)
        wq.submit_record("put_kv", {"key": "/apis/v1/z", "value": "3"})
        t.set_enabled(True)
        wq.start()
        wq.drain()
        wq.close()
        roots = [i["root"] for i in t.summaries()["items"]]
        assert "queue.replay:put_kv" not in roots
        for i in t.summaries()["items"]:
            assert i["links"] == []
