"""Contract drift tests: the generated OpenAPI spec, the committed
api/openapi.json, and the live router must agree — the failure mode the
reference's hand-exported contract cannot catch."""

import json
import pathlib

from tpu_docker_api.api.app import build_router
from tpu_docker_api.api.openapi import build_spec, route_inventory

REPO = pathlib.Path(__file__).resolve().parent.parent


def _live_routes():
    """Every route a fully-wired router exposes, plus the raw /metrics
    endpoint served outside the router."""

    class _Any:
        def __getattr__(self, _):
            return lambda *a, **k: {}

    svc = _Any()
    r = build_router(svc, svc, svc, svc, work_queue=svc, health_watcher=svc,
                     metrics=None, job_svc=svc, pod_scheduler=svc,
                     reconciler=svc, job_supervisor=svc, host_monitor=svc,
                     admission=svc, serving=svc, compactor=svc, tracer=svc,
                     gateway=svc, workflow_svc=svc)
    routes = {(m, p) for m, _, p, _ in r._routes}
    routes.add(("GET", "/metrics"))
    return routes


def test_spec_covers_every_live_route():
    assert _live_routes() <= route_inventory()


def test_spec_has_no_phantom_routes():
    assert route_inventory() <= _live_routes()


def test_committed_contract_in_sync():
    committed = json.loads((REPO / "api" / "openapi.json").read_text())
    assert committed == build_spec(), (
        "api/openapi.json is stale — regenerate with "
        "`python -m tpu_docker_api.api.openapi > api/openapi.json`"
    )


def test_example_config_loads():
    from tpu_docker_api.config import Config, load

    cfg = load(str(REPO / "etc" / "config.toml"))
    # the example documents the defaults; keys must stay in sync with Config
    assert cfg.port == Config().port
    assert cfg.runtime_backend == "docker"


def test_request_schemas_resolve():
    spec = build_spec()
    schemas = spec["components"]["schemas"]
    for path, ops in spec["paths"].items():
        for op in ops.values():
            body = op.get("requestBody")
            if body:
                ref = body["content"]["application/json"]["schema"]["$ref"]
                assert ref.rsplit("/", 1)[1] in schemas, f"{path}: dangling {ref}"
