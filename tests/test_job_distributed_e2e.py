"""The control plane's OWN job output boots a real distributed run.

test_distributed_e2e.py proves the env *renderer* executes; this closes
the remaining gap through the service layer: POST /jobs on a 2-host fake
pod, read back the env the JobService actually injected into each host's
container, and launch real processes from that env verbatim (fake host
addresses rewritten to loopback — the only thing a test cannot own).
Each process runs ``bootstrap_jax`` → ``jax.distributed.initialize`` →
a cross-process global sum. This is the full TPU analog of the
reference's port-wiring duty (service/container.go:489-501), proven from
the HTTP surface down.
"""

import json
import os
import pathlib
import subprocess
import sys
import time

import pytest

from tpu_docker_api.config import Config
from tpu_docker_api.daemon import Program

REPO_ROOT = pathlib.Path(__file__).resolve().parent.parent

CHILD_CODE = """
import os
from tpu_docker_api.workload.jaxenv import bootstrap_jax
bootstrap_jax(platform="cpu", virtual_devices=2)
import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P
assert jax.process_count() == 2, jax.process_count()
mesh = Mesh(np.array(jax.devices()).reshape(4,), ("dp",))
local = np.full((2, 4), float(jax.process_index() + 1), np.float32)
arr = jax.make_array_from_process_local_data(NamedSharding(mesh, P("dp")), local)
with mesh:
    total = float(jax.jit(lambda x: x.sum())(arr))
assert total == 24.0, total  # 2 rows x 4 cols x (1 + 2)
print(f"JOB-CHILD-OK p{jax.process_index()} total={total}")
"""


MULTISLICE_CHILD_CODE = """
import os
from functools import partial
from tpu_docker_api.workload.jaxenv import bootstrap_jax
bootstrap_jax(platform="cpu", virtual_devices=2)
import jax
import numpy as np
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P
from jax import shard_map

sid = int(os.environ["MEGASCALE_SLICE_ID"])
assert os.environ["MEGASCALE_NUM_SLICES"] == "2"
assert jax.process_count() == 2, jax.process_count()
# device order is process-major, and the service placed one slice per
# process — so axis 0 of this mesh IS the slice axis
devs = np.array(jax.devices()).reshape(2, 2)
mesh = Mesh(devs, ("slice", "dp"))
local = np.full((2, 4), float(sid + 1), np.float32)
arr = jax.make_array_from_process_local_data(
    NamedSharding(mesh, P(("slice", "dp"))), local)

@partial(shard_map, mesh=mesh, in_specs=P(("slice", "dp")),
         out_specs=P("slice"))
def slice_sums(x):
    # psum over dp ONLY: slice-local reduction — the CPU/gloo analog of
    # per-slice ICI collectives under MEGASCALE DCN stitching
    return lax.psum(x.sum(), "dp")[None]

totals = jax.jit(slice_sums)(arr)
# the result is sharded over the slice axis: each process addresses only
# its own slice's entry — which is exactly the slice-locality assertion
mine = float(np.asarray(totals.addressable_shards[0].data)[0])
assert mine == 8.0 * (sid + 1), (sid, mine)  # 2 rows x 4 x (sid+1)
# and the cross-slice (DCN-analog) reduction still sees the whole world
grand = float(jax.jit(lambda x: x.sum())(arr))
assert grand == 24.0, grand
print(f"MS-CHILD-OK p{jax.process_index()} slice={sid} mine={mine}")
"""


def _run_children(envs, coord_rewrites, child_code, marker):
    """Launch one child per env dict (JAX_*/MEGASCALE_* taken verbatim,
    addresses rewritten to loopback) and assert all exit 0 with the
    marker in their output."""
    procs = []
    for env_dict in envs:
        env = {k: v for k, v in os.environ.items()
               if not k.startswith(("JAX_", "TPU_", "MEGASCALE_"))}
        env.update({k: v for k, v in env_dict.items()
                    if k.startswith(("JAX_", "MEGASCALE_"))})
        for var, (old, new) in coord_rewrites.items():
            if var in env:
                env[var] = env[var].replace(old, new)
        env["PYTHONPATH"] = os.pathsep.join(
            [str(REPO_ROOT), env.get("PYTHONPATH", "")]).rstrip(":")
        procs.append(subprocess.Popen(
            [sys.executable, "-c", child_code], env=env,
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
            text=True, cwd=str(REPO_ROOT)))
    try:
        deadline = time.monotonic() + 300
        pending = dict(enumerate(procs))
        outputs = {}
        while pending:
            if time.monotonic() > deadline:
                raise AssertionError(f"children {sorted(pending)} hung")
            for pid, p in list(pending.items()):
                if p.poll() is None:
                    continue
                outputs[pid] = p.stdout.read()
                assert p.returncode == 0, (
                    f"child {pid} rc={p.returncode}:\n{outputs[pid]}")
                del pending[pid]
            time.sleep(0.2)
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
    for pid, text in outputs.items():
        assert marker in text, text
    return outputs


@pytest.mark.slow
def test_multislice_job_env_boots_real_processes_with_slice_grouping():
    """numSlices=2 end-to-end (VERDICT r2 item 5): POST /jobs renders one
    ICI slice per host plus MEGASCALE_* DCN stitching; the test launches
    real processes from that env verbatim. MEGASCALE itself is libtpu-
    only, so the executed proof is the gloo world with SLICE-LOCAL
    grouping asserted: a shard_map psum over the dp axis alone reduces
    within each slice, the global sum crosses them."""
    cfg = Config(
        port=0, store_backend="memory", runtime_backend="fake",
        accelerator_type="v5e-4", start_port=42200, end_port=42299,
        health_watch_interval=0,
        pod_hosts=[
            {"host_id": "h0", "address": "10.0.0.1",
             "grid_coord": [0, 0, 0], "local": True},
            {"host_id": "h1", "address": "10.0.0.2",
             "grid_coord": [1, 0, 0], "runtime_backend": "fake"},
        ],
    )
    prog = Program(cfg, host="127.0.0.1")
    prog.init()
    prog.start()
    try:
        import urllib.request

        req = urllib.request.Request(
            f"http://127.0.0.1:{prog.api_server.port}/api/v1/jobs",
            method="POST",
            data=json.dumps({"imageName": "workload", "jobName": "ms",
                             "chipCount": 8, "numSlices": 2}).encode(),
            headers={"Content-Type": "application/json"})
        with urllib.request.urlopen(req) as resp:
            out = json.loads(resp.read())
        assert out["code"] == 200, out

        envs = []
        for host in prog.pod.hosts.values():
            for name in host.runtime.container_list():
                if name.startswith("ms"):
                    spec = host.runtime.container_inspect(name).spec
                    envs.append(dict(e.split("=", 1) for e in spec.env))
        assert len(envs) == 2, [list(e) for e in envs]
        envs.sort(key=lambda e: int(e["JAX_PROCESS_ID"]))

        # the service-injected multislice contract, per container
        assert [e["MEGASCALE_SLICE_ID"] for e in envs] == ["0", "1"]
        for e in envs:
            assert e["MEGASCALE_NUM_SLICES"] == "2"
            assert e["JAX_NUM_PROCESSES"] == "2"  # ONE jax world
        ms_addrs = {e["MEGASCALE_COORDINATOR_ADDRESS"] for e in envs}
        assert len(ms_addrs) == 1  # every slice stitches to one endpoint
        assert {e["MEGASCALE_PORT"] for e in envs} != {""}
        # the libtpu ICI mesh must be SLICE-LOCAL: each container's peer
        # list contains only its own host (no ICI path across slices)
        for e in envs:
            peers = e["TPU_PROCESS_ADDRESSES"].split(",")
            assert len(peers) == 1, peers

        coord = envs[0]["JAX_COORDINATOR_ADDRESS"]
        _run_children(
            envs,
            {"JAX_COORDINATOR_ADDRESS": ("10.0.0.1", "127.0.0.1"),
             "MEGASCALE_COORDINATOR_ADDRESS": ("10.0.0.1", "127.0.0.1")},
            MULTISLICE_CHILD_CODE, "MS-CHILD-OK")
        assert coord.startswith("10.0.0.1:")
    finally:
        prog.stop()


@pytest.mark.slow
def test_job_service_env_boots_real_distributed_processes(tmp_path):
    cfg = Config(
        port=0, store_backend="memory", runtime_backend="fake",
        accelerator_type="v5e-4", start_port=42000, end_port=42099,
        health_watch_interval=0,
        pod_hosts=[
            {"host_id": "h0", "address": "10.0.0.1",
             "grid_coord": [0, 0, 0], "local": True},
            {"host_id": "h1", "address": "10.0.0.2",
             "grid_coord": [1, 0, 0], "runtime_backend": "fake"},
        ],
    )
    prog = Program(cfg, host="127.0.0.1")
    prog.init()
    prog.start()
    try:
        import urllib.request

        req = urllib.request.Request(
            f"http://127.0.0.1:{prog.api_server.port}/api/v1/jobs",
            method="POST",
            data=json.dumps({"imageName": "workload", "jobName": "jd",
                             "chipCount": 8}).encode(),
            headers={"Content-Type": "application/json"})
        with urllib.request.urlopen(req) as resp:
            out = json.loads(resp.read())
        assert out["code"] == 200, out

        # the env the SERVICE injected into each host's container
        envs = []
        for host in prog.pod.hosts.values():
            for name in host.runtime.container_list():
                if name.startswith("jd"):
                    spec = host.runtime.container_inspect(name).spec
                    envs.append(dict(e.split("=", 1) for e in spec.env))
        assert len(envs) == 2, [list(e) for e in envs]
        envs.sort(key=lambda e: int(e["JAX_PROCESS_ID"]))
        assert envs[0]["JAX_NUM_PROCESSES"] == "2"
        coord = envs[0]["JAX_COORDINATOR_ADDRESS"]
        assert coord.startswith("10.0.0.1:")  # process 0's pod host

        procs = []
        for env_dict in envs:
            env = {k: v for k, v in os.environ.items()
                   if not k.startswith(("JAX_", "TPU_", "MEGASCALE_"))}
            env.update({k: v for k, v in env_dict.items()
                        if k.startswith("JAX_")})
            # fake pod addresses -> loopback: the single rewrite a test
            # host needs to actually own the rendezvous endpoint
            env["JAX_COORDINATOR_ADDRESS"] = coord.replace(
                "10.0.0.1", "127.0.0.1")
            env["PYTHONPATH"] = os.pathsep.join(
                [str(REPO_ROOT), env.get("PYTHONPATH", "")]).rstrip(":")
            procs.append(subprocess.Popen(
                [sys.executable, "-c", CHILD_CODE], env=env,
                stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
                text=True, cwd=str(REPO_ROOT)))

        try:
            deadline = time.monotonic() + 300
            pending = dict(enumerate(procs))
            outputs = {}
            while pending:
                if time.monotonic() > deadline:
                    raise AssertionError(f"children {sorted(pending)} hung")
                for pid, p in list(pending.items()):
                    if p.poll() is None:
                        continue
                    outputs[pid] = p.stdout.read()
                    assert p.returncode == 0, (
                        f"child {pid} rc={p.returncode}:\n{outputs[pid]}")
                    del pending[pid]
                time.sleep(0.2)
        finally:
            for p in procs:
                if p.poll() is None:
                    p.kill()
        for pid, text in outputs.items():
            assert "JOB-CHILD-OK" in text, text
    finally:
        prog.stop()
