"""Data layer: deterministic step→batch mapping, sharding, file formats.

The loader is the data half of the quiesce→resume contract: a resumed job
must see exactly the batches the stopped job would have seen (stateless
(seed, step) mapping — tpu_docker_api/data/loader.py), and multi-host
processes must read disjoint rows of the same global batch.
"""

import numpy as np
import pytest

from tpu_docker_api.data.loader import (
    TokenSource,
    make_batch_fn,
    open_token_files,
    rows_for_process,
    write_token_file,
)


def _source(n_tokens=1000, window=9, dtype=np.int32):
    return TokenSource(
        arrays=(np.arange(n_tokens, dtype=dtype),), window=window)


class TestTokenSource:
    def test_windows_tile_the_stream(self):
        src = _source(n_tokens=100, window=10)
        assert src.n_windows == 10
        np.testing.assert_array_equal(src.read_window(3),
                                      np.arange(30, 40))

    def test_window_index_wraps_epochs(self):
        src = _source(n_tokens=100, window=10)
        np.testing.assert_array_equal(src.read_window(13),
                                      src.read_window(3))

    def test_window_spans_file_boundary(self):
        src = TokenSource(
            arrays=(np.arange(0, 7, dtype=np.int32),
                    np.arange(7, 20, dtype=np.int32)),
            window=5,
        )
        # window 1 = tokens 5..9 — crosses the 7-token first file
        np.testing.assert_array_equal(src.read_window(1), np.arange(5, 10))

    def test_too_few_tokens_rejected(self):
        with pytest.raises(ValueError, match="at least"):
            TokenSource(arrays=(np.arange(3, dtype=np.int32),), window=5)


class TestFiles:
    def test_bin_roundtrip(self, tmp_path):
        tokens = np.arange(64, dtype=np.int32) % 500
        write_token_file(tokens, tmp_path / "a.bin")
        src = open_token_files(tmp_path / "a.bin", window=8)
        np.testing.assert_array_equal(src.read_window(0), tokens[:8])

    def test_npy_roundtrip(self, tmp_path):
        tokens = np.arange(64, dtype=np.int32)
        np.save(tmp_path / "a.npy", tokens)
        src = open_token_files(tmp_path / "a.npy", window=8)
        np.testing.assert_array_equal(src.read_window(7), tokens[56:])

    def test_directory_concatenates_sorted(self, tmp_path):
        write_token_file(np.arange(0, 10), tmp_path / "00.bin")
        write_token_file(np.arange(10, 20), tmp_path / "01.bin")
        src = open_token_files(tmp_path, window=5)
        np.testing.assert_array_equal(src.read_window(1), np.arange(5, 10))
        np.testing.assert_array_equal(src.read_window(2), np.arange(10, 15))

    def test_bin_rejects_overflow(self, tmp_path):
        with pytest.raises(ValueError, match="fit"):
            write_token_file(np.array([70000]), tmp_path / "a.bin")


class TestBatchFn:
    def test_deterministic_across_instances(self):
        """Two loaders (fresh process ≈ resume) give identical batches."""
        a = make_batch_fn(_source(), 4, seed=7)
        b = make_batch_fn(_source(), 4, seed=7)
        for step in (0, 3, 1000):
            np.testing.assert_array_equal(a(step), b(step))

    def test_seed_changes_order(self):
        a = make_batch_fn(_source(), 4, seed=0)
        b = make_batch_fn(_source(), 4, seed=1)
        assert not np.array_equal(a(0), b(0))

    def test_batch_shape_and_content(self):
        src = _source(n_tokens=1000, window=9)
        fn = make_batch_fn(src, 4, seed=0)
        batch = fn(0)
        assert batch.shape == (4, 9)
        assert batch.dtype == np.int32
        # every row is a real window: contiguous ids in this corpus
        for row in batch:
            np.testing.assert_array_equal(row, np.arange(row[0], row[0] + 9))

    def test_epoch_covers_every_window_once(self):
        """Across one epoch every window appears exactly once (the affine
        map is a permutation, not sampling-with-replacement)."""
        src = _source(n_tokens=120, window=10)  # 12 windows
        fn = make_batch_fn(src, 4, seed=3)
        starts = [int(fn(step)[i][0]) for step in range(3) for i in range(4)]
        assert sorted(starts) == [w * 10 for w in range(12)]

    def test_sharded_processes_partition_global_batch(self):
        src = _source()
        whole = make_batch_fn(src, 8, seed=5)(2)
        parts = [
            make_batch_fn(src, 8, seed=5, process_index=p, process_count=4)(2)
            for p in range(4)
        ]
        np.testing.assert_array_equal(np.concatenate(parts), whole)

    def test_rows_for_process_requires_divisibility(self):
        with pytest.raises(ValueError, match="divide"):
            rows_for_process(10, 0, 3)


class TestNativeLoader:
    """C++ fast path (tpu_native/dataloader.cc): bit-identical to the
    numpy path for every (seed, step, shard, dtype, file-layout) — the
    correctness contract that lets make_batch_fn swap it in silently."""

    @pytest.fixture(scope="class")
    def lib(self):
        import os
        import subprocess

        from tpu_docker_api.data import loader

        native_dir = os.path.join(os.path.dirname(os.path.dirname(
            os.path.abspath(__file__))), "tpu_native")
        try:
            subprocess.run(["make", "-C", native_dir, "libtpudata.so"],
                           capture_output=True, timeout=120)
        except FileNotFoundError:
            pass  # no make: the lib may still be prebuilt, else skip
        loader._native_cache.clear()
        lib = loader._native_lib()
        if lib is None:
            pytest.skip("libtpudata.so unavailable (no toolchain)")
        return lib

    def _multi_file_source(self, tmp_path, dtype="uint16", window=9):
        from tpu_docker_api.data.loader import (
            open_token_files, write_token_file)

        rng = np.random.default_rng(7)
        hi = 60_000 if dtype == "uint16" else 2 ** 30
        # sizes chosen so windows straddle file boundaries
        for i, n in enumerate((101, 57, 260)):
            write_token_file(rng.integers(0, hi, n), tmp_path / f"{i}.bin",
                             bin_dtype=dtype)
        return open_token_files(tmp_path, window=window, bin_dtype=dtype)

    def _numpy_fn(self, source, *args, **kwargs):
        import dataclasses

        from tpu_docker_api.data.loader import make_batch_fn

        return make_batch_fn(dataclasses.replace(source, bin_paths=None),
                             *args, **kwargs)

    @pytest.mark.parametrize("dtype", ["uint16", "int32"])
    def test_bit_exact_vs_numpy_with_epoch_wrap(self, tmp_path, lib,
                                                dtype):
        from tpu_docker_api.data.loader import _NativeBatcher, make_batch_fn

        src = self._multi_file_source(tmp_path, dtype=dtype)
        for seed in (0, 3):
            native = make_batch_fn(src, 8, seed=seed)
            assert isinstance(native, _NativeBatcher)
            ref = self._numpy_fn(src, 8, seed=seed)
            # sequential steps (lookahead hits) spanning an epoch wrap
            for step in range(0, src.n_windows // 8 + 3):
                np.testing.assert_array_equal(native(step), ref(step))

    def test_bit_exact_sharded_and_random_access(self, tmp_path, lib):
        from tpu_docker_api.data.loader import make_batch_fn

        src = self._multi_file_source(tmp_path)
        for pi in range(4):
            native = make_batch_fn(src, 8, seed=5, process_index=pi,
                                   process_count=4)
            ref = self._numpy_fn(src, 8, seed=5, process_index=pi,
                                 process_count=4)
            # non-sequential steps: every lookahead misses, still exact
            for step in (11, 2, 2, 30, 0):
                np.testing.assert_array_equal(native(step), ref(step))

    def test_env_kill_switch(self, tmp_path, lib, monkeypatch):
        from tpu_docker_api.data import loader

        src = self._multi_file_source(tmp_path)
        monkeypatch.setenv("TPU_DOCKER_API_NATIVE_DATA", "0")
        loader._native_cache.clear()
        try:
            fn = loader.make_batch_fn(src, 8, seed=0)
            assert not isinstance(fn, loader._NativeBatcher)
        finally:
            loader._native_cache.clear()

    def test_int16_dtype_stays_on_numpy_path(self, tmp_path, lib):
        """int16 shares uint16's itemsize — the native widen loop is
        sign-blind, so anything but uint16/int32 must stay on numpy
        (negative tokens would silently decode as 65535...)."""
        from tpu_docker_api.data import loader

        write_token_file(np.array([-1, -2, 5, 6, 7, 8], np.int16),
                         tmp_path / "t.bin", bin_dtype="int16")
        src = loader.open_token_files(tmp_path / "t.bin", window=3,
                                      bin_dtype="int16")
        fn = loader.make_batch_fn(src, 2, seed=0)
        assert not isinstance(fn, loader._NativeBatcher)
        assert -1 in fn(0)  # sign preserved by the numpy path

    def test_npy_sources_stay_on_numpy_path(self, tmp_path, lib):
        from tpu_docker_api.data import loader

        np.save(tmp_path / "t.npy",
                np.arange(500, dtype=np.int32))
        src = loader.open_token_files(tmp_path / "t.npy", window=9)
        assert src.bin_paths is None
        fn = loader.make_batch_fn(src, 4, seed=0)
        assert not isinstance(fn, loader._NativeBatcher)


class TestTrainerIntegration:
    @pytest.mark.slow
    def test_trainer_runs_on_file_data_and_resumes(self, tmp_path):
        import json
        import os
        import subprocess
        import sys

        rng = np.random.default_rng(0)
        write_token_file(rng.integers(0, 256, 4096), tmp_path / "corpus.bin")
        ckpt = tmp_path / "ckpt"

        def run(steps):
            env = {**os.environ,
                   "PYTHONPATH": os.path.dirname(os.path.dirname(__file__))}
            out = subprocess.run(
                [sys.executable, "-m", "tpu_docker_api.train",
                 "--preset", "tiny", "--steps", str(steps), "--batch", "4",
                 "--seq", "32", "--platform", "cpu", "--virtual-devices", "2",
                 "--fsdp", "2", "--data", str(tmp_path / "corpus.bin"),
                 "--ckpt-dir", str(ckpt), "--save-every", "4",
                 "--log-every", "4"],
                capture_output=True, text=True, timeout=300, env=env)
            assert out.returncode == 0, out.stderr
            return [json.loads(l) for l in out.stdout.splitlines()]

        first = run(4)
        assert first[-1] == {"event": "done", "step": 4}
        resumed = run(8)  # restores step 4, continues on the same corpus
        assert resumed[-1] == {"event": "done", "step": 8}
        losses = [e["loss"] for e in resumed if "loss" in e]
        assert all(np.isfinite(l) for l in losses)
