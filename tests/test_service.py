"""Service-resource tests (docs/robustness.md "Service & autoscaler").

Property tier, pinned:

- a Service owns exactly replica gang families ``0..replicas-1``, each a
  real distributed job admitted at the service's priority class (default
  ``production``) — a traffic-driven scale-up enters the capacity market
  and may preempt strictly-lower classes (``batch`` training);
- scale-down quiesces workers-first (coordinator strictly last) before
  releasing the replica's slices and ports;
- cooldowns + the hysteresis watermark keep an oscillating signal from
  flapping the fleet;
- delete tears down every replica (no orphan fleet); replica gangs whose
  owning service is gone are garbage-collected marker-verified (a user
  job that merely LOOKS replica-shaped is never touched);
- the real signal path scrapes a replica-reported HTTP endpoint (the
  paged engine's SLO export shape);
- chaos matrix: a daemon kill at every ``service.*`` crash point
  converges — after reboot + reconcile, exactly one fully-owned replica
  set, zero leaks, fixpoint.
"""

import json
import threading
from http.server import BaseHTTPRequestHandler, HTTPServer

import pytest

from tpu_docker_api import config as config_mod
from tpu_docker_api import errors
from tpu_docker_api.daemon import Program
from tpu_docker_api.runtime.fake import FakeRuntime
from tpu_docker_api.schemas.job import JobRun
from tpu_docker_api.schemas.service import (
    SERVICE_OWNER_ENV,
    ServiceCreate,
    ServicePatch,
)
from tpu_docker_api.service.crashpoints import (
    SERVICE_CRASH_POINTS,
    SimulatedCrash,
    armed,
)
from tpu_docker_api.service.invariants import (
    check_invariants,
    check_job_invariants,
    check_service_invariants,
)
from tpu_docker_api.service.serving import replica_base, split_replica_base
from tpu_docker_api.state.keys import Resource
from tpu_docker_api.state.kv import MemoryKV


def boot(n_hosts: int = 1, kv=None, runtimes=None, **scale_cfg) -> Program:
    """A Program over a fake pod with inline-driven loops (admission +
    autoscale intervals 0, zero cooldowns unless overridden)."""
    kv = kv if kv is not None else MemoryKV()
    runtimes = runtimes or {f"h{i}": FakeRuntime() for i in range(n_hosts)}
    cfg = config_mod.Config(
        store_backend="memory", runtime_backend="fake",
        health_watch_interval=0, end_port=40099,
        admission_enabled=True, admission_interval_s=0,
        autoscale_interval_s=0,
        autoscale_up_cooldown_s=scale_cfg.pop("up_cooldown", 0),
        autoscale_down_cooldown_s=scale_cfg.pop("down_cooldown", 0),
        autoscale_down_watermark=scale_cfg.pop("watermark", 0.5),
        pod_hosts=[] if n_hosts == 1 else [
            {"host_id": f"h{i}", "address": f"10.0.0.{i + 1}",
             "grid_coord": [i, 0, 0],
             **({"local": True} if i == 0 else {"runtime_backend": "fake"})}
            for i in range(n_hosts)
        ],
    )
    prg = Program(cfg, kv=kv, runtime=runtimes["h0"],
                  pod_runtimes={h: r for h, r in runtimes.items()
                                if h != "h0"})
    prg.init()
    return prg


def create(prg, name="web", chips=2, replicas=1, max_replicas=3, **kw):
    return prg.serving.create_service(ServiceCreate(
        service_name=name, image_name="serve", chips_per_replica=chips,
        replicas=replicas, max_replicas=max_replicas, **kw))


def oracle(prg) -> list[str]:
    problems = check_service_invariants(
        prg.store, prg.service_versions, prg.job_versions)
    problems += check_job_invariants(
        prg.pod, prg.pod_scheduler, prg.store, prg.job_versions)
    problems += check_invariants(
        prg.runtime, prg.store, prg.container_versions,
        prg.chip_scheduler, prg.port_scheduler,
        job_versions=prg.job_versions)
    return problems


def job_phase(prg, base):
    return prg.store.get_job(
        f"{base}-{prg.job_versions.get(base)}").phase


class TestNaming:
    def test_replica_base_round_trips(self):
        assert replica_base("web", 2) == "web.r2"
        assert split_replica_base("web.r2") == ("web", 2)
        assert split_replica_base("a.b.r10") == ("a.b", 10)
        assert split_replica_base("web") is None
        assert split_replica_base("web.rx") is None
        assert split_replica_base(".r1") is None


class TestServiceLifecycle:
    def test_create_owns_exactly_n_replica_gangs(self):
        prg = boot()
        out = create(prg, replicas=2)
        assert out["phase"] == "active"
        assert out["readyReplicas"] == 2
        assert [r["family"] for r in out["replicaStatus"]] == [
            "web.r0", "web.r1"]
        # each replica is a REAL job at the service's class, marker-owned
        st = prg.store.get_job("web.r0-0")
        assert st.priority_class == "production"
        assert f"{SERVICE_OWNER_ENV}=web" in st.env
        assert oracle(prg) == []

    def test_duplicate_and_bad_requests_reject(self):
        prg = boot()
        create(prg)
        with pytest.raises(errors.ServiceExisted):
            create(prg)
        with pytest.raises(errors.BadRequest):
            create(prg, name="bad", replicas=9)  # outside [min, max]
        with pytest.raises(errors.BadRequest):
            prg.serving.create_service(ServiceCreate(
                service_name="x", image_name="serve"))  # no chips
        with pytest.raises(errors.ServiceNotExist):
            prg.serving.service_info("ghost")

    def test_delete_tears_down_all_replicas(self):
        prg = boot()
        create(prg, replicas=3, max_replicas=3)
        assert len(prg.job_versions.snapshot()) == 3
        prg.serving.delete_service("web")
        assert prg.service_versions.snapshot() == {}
        assert prg.job_versions.snapshot() == {}
        assert prg.pod_scheduler.status()["slices"] == {}
        assert oracle(prg) == []
        kinds = [e["event"] for e in prg.serving.events_view()]
        assert "service-created" in kinds and "service-deleted" in kinds

    def test_manual_scale_is_counted_and_audited(self):
        prg = boot()
        create(prg, replicas=1)
        out = prg.serving.patch_service("web", ServicePatch(replicas=3))
        assert out["replicas"] == 3 and out["readyReplicas"] == 3
        assert out["manualScaleTotal"] == 1
        assert out["lastScale"]["trigger"] == "manual"
        assert out["lastScale"]["from"] == 1 and out["lastScale"]["to"] == 3
        with pytest.raises(errors.BadRequest):
            prg.serving.patch_service("web", ServicePatch(replicas=9))
        assert oracle(prg) == []

    def test_rolling_spec_update_rolls_every_replica(self):
        prg = boot()
        create(prg, replicas=2)
        out = prg.serving.patch_service(
            "web", ServicePatch(image_name="serve:v2"))
        assert out["version"] == 1 and out["image"] == "serve:v2"
        for rb in ("web.r0", "web.r1"):
            st = prg.store.get_job(f"{rb}-{prg.job_versions.get(rb)}")
            assert st.image == "serve:v2"
            assert st.phase == "running"
        assert oracle(prg) == []

    def test_orphan_replica_gangs_gc_marker_verified(self):
        prg = boot()
        create(prg, replicas=2)
        # a user job that merely LOOKS replica-shaped (no marker env)
        prg.job_svc.run_job(JobRun(image_name="jax", job_name="user.r1",
                                   chip_count=2))
        # out-of-band surgery: the service family vanishes, the fleet stays
        prg.store.delete_family(Resource.SERVICES, "web")
        prg.service_versions.remove("web")
        report = prg.reconciler.reconcile()
        gcd = [a for a in report["actions"]
               if a["action"] == "gc-orphan-replica"]
        assert {a["target"] for a in gcd} == {"web.r0", "web.r1"}
        assert "web.r0" not in prg.job_versions.snapshot()
        # the lookalike user job is untouched
        assert job_phase(prg, "user.r1") == "running"
        assert oracle(prg) == []


class TestAutoscalePolicy:
    def test_scale_up_admits_at_service_class_and_preempts(self):
        """The tentpole scenario: a traffic burst scales the service up
        THROUGH the admission market, preempting strictly-lower-class
        batch training for the last replica."""
        prg = boot()
        prg.job_svc.run_job(JobRun(image_name="jax", job_name="train",
                                   chip_count=4, priority_class="batch"))
        create(prg, replicas=1, max_replicas=3)
        prg.serving.set_offered_load("web", 280)  # wants ceil(1*2.8) = 3
        prg.serving.tick()
        info = prg.serving.service_info("web")
        assert info["replicas"] == 3
        assert info["lastScale"]["trigger"] == "autoscale"
        # r1 filled the free hole; r2 had to queue — the admission pass
        # preempts the batch gang for it (production > batch)
        assert job_phase(prg, "web.r2") == "queued"
        assert prg.admission.admit_once()
        assert job_phase(prg, "web.r2") == "running"
        assert job_phase(prg, "train") == "preempted"
        assert prg.store.get_job("web.r2-1").priority_class == "production"
        assert oracle(prg) == []
        # burst over: scale-down releases capacity and training re-admits
        prg.serving.set_offered_load("web", 10)
        prg.serving.tick()
        assert prg.serving.service_info("web")["replicas"] == 1
        assert prg.admission.admit_once()
        assert job_phase(prg, "train") == "running"
        assert oracle(prg) == []

    def test_scale_up_never_preempts_equal_or_higher(self):
        prg = boot()
        prg.job_svc.run_job(JobRun(image_name="jax", job_name="prod",
                                   chip_count=6,
                                   priority_class="production"))
        create(prg, replicas=1, max_replicas=2)
        prg.serving.set_offered_load("web", 150)
        prg.serving.tick()
        assert prg.serving.service_info("web")["replicas"] == 2
        # no strictly-lower victim exists: the replica stays queued
        prg.admission.admit_once()
        assert job_phase(prg, "web.r1") == "queued"
        assert job_phase(prg, "prod") == "running"

    def test_scale_down_quiesces_workers_first(self):
        """The surplus replica is a 2-host gang: its teardown must stop
        the worker BEFORE the coordinator (the PR 3 gang quiesce), then
        delete and release."""
        rt0, rt1 = FakeRuntime(), FakeRuntime()
        prg = boot(n_hosts=2, runtimes={"h0": rt0, "h1": rt1})
        create(prg, chips=16, replicas=1, max_replicas=1,
               min_replicas=0)
        stops: list[str] = []
        for host in prg.pod.hosts.values():
            orig = host.runtime.container_stop

            def spy(name, *a, _orig=orig, **kw):
                stops.append(name)
                return _orig(name, *a, **kw)

            host.runtime.container_stop = spy
        prg.serving.patch_service("web", ServicePatch(replicas=0))
        assert stops == ["web.r0-0-p1", "web.r0-0-p0"], stops
        assert prg.job_versions.snapshot() == {}
        assert prg.pod_scheduler.status()["slices"] == {}
        assert oracle(prg) == []

    def test_oscillating_signal_never_flaps(self):
        """Cooldowns + the hysteresis watermark: a signal oscillating
        around the target changes nothing; only a SUSTAINED idle past the
        down cooldown sheds a replica."""
        prg = boot(up_cooldown=5.0, down_cooldown=10.0, watermark=0.5)
        now = [0.0]
        prg.serving._clock = lambda: now[0]
        create(prg, replicas=1, max_replicas=3)
        prg.serving.set_offered_load("web", 150)  # breach: scale 1 → 2
        prg.serving.tick()
        assert prg.serving.service_info("web")["replicas"] == 2

        for t in range(1, 10):
            now[0] = float(t)
            # oscillate around the target: 150 rps / 2 replicas = 0.75 of
            # target (dead zone), 90 rps / 2 = 0.45 (below watermark, but
            # inside the down cooldown)
            prg.serving.set_offered_load("web", 150 if t % 2 else 90)
            prg.serving.tick()
            assert prg.serving.service_info("web")["replicas"] == 2, (
                f"flapped at t={t}")
        info = prg.serving.service_info("web")
        assert info["autoscaleTotal"] == 1  # exactly the initial scale-up

        # sustained idle PAST the cooldown: one clean scale-down
        now[0] = 20.0
        prg.serving.set_offered_load("web", 40)
        prg.serving.tick()
        info = prg.serving.service_info("web")
        assert info["replicas"] == 1
        assert info["autoscaleTotal"] == 2
        assert oracle(prg) == []

    def test_scale_from_zero_recovers(self):
        """A service at minReplicas=0 must come back when traffic does —
        zero ready replicas is a breach when load is offered, not a
        signal blackout."""
        prg = boot()
        create(prg, replicas=1, min_replicas=0, max_replicas=2)
        prg.serving.set_offered_load("web", 0)
        prg.serving.tick()
        assert prg.serving.service_info("web")["replicas"] == 0
        assert prg.job_versions.snapshot() == {}
        prg.serving.set_offered_load("web", 150)
        prg.serving.tick()
        info = prg.serving.service_info("web")
        assert info["replicas"] >= 1 and info["readyReplicas"] >= 1
        assert oracle(prg) == []

    def test_patch_rejects_nonpositive_targets_and_nan_load(self):
        prg = boot()
        create(prg)
        with pytest.raises(errors.BadRequest):
            prg.serving.patch_service(
                "web", ServicePatch(queue_depth_target=0))
        with pytest.raises(errors.BadRequest):
            prg.serving.patch_service(
                "web", ServicePatch(ttft_p95_target_ms=-1.0))
        with pytest.raises(errors.BadRequest):
            prg.serving.set_offered_load("web", float("nan"))
        with pytest.raises(errors.BadRequest):
            prg.serving.set_offered_load("web", float("inf"))
        # DTO layer: malformed floats are 400s, never 500s; NaN rejected
        with pytest.raises(errors.BadRequest):
            ServiceCreate.from_dict({"serviceName": "x", "imageName": "i",
                                     "ttftP95TargetMs": "200ms"})
        with pytest.raises(errors.BadRequest):
            ServiceCreate.from_dict({"serviceName": "x", "imageName": "i",
                                     "replicaCapacityRps": float("nan")})

    def test_min_max_retune_clamp_is_audited_as_manual(self):
        prg = boot()
        create(prg, replicas=3, max_replicas=3)
        out = prg.serving.patch_service("web", ServicePatch(max_replicas=1))
        assert out["replicas"] == 1
        assert out["lastScale"]["trigger"] == "manual"
        assert out["manualScaleTotal"] == 1
        assert sorted(prg.job_versions.snapshot()) == ["web.r0"]
        assert oracle(prg) == []

    def test_no_signal_means_no_action(self):
        prg = boot()
        create(prg, replicas=2)
        prg.serving.tick()  # no offered load, no metrics path
        assert prg.serving.service_info("web")["replicas"] == 2
        assert prg.serving.service_info("web")["lastScale"] is None

    def test_http_scrape_drives_scale_up(self):
        """The real signal path: the autoscaler scrapes the replica's
        reported SLO endpoint (the paged engine's export shape) on the
        coordinator port."""
        prg = boot()
        create(prg, replicas=1, max_replicas=2, metrics_path="/slo")
        jst = prg.store.get_job("web.r0-0")

        class Handler(BaseHTTPRequestHandler):
            def do_GET(self):
                body = json.dumps({"ttftP95Ms": 900.0, "itlP95Ms": 42.0,
                                   "queueDepth": 1}).encode()
                self.send_response(200)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def log_message(self, *a):
                pass

        httpd = HTTPServer(("127.0.0.1", jst.coordinator_port), Handler)
        t = threading.Thread(target=httpd.serve_forever, daemon=True)
        t.start()
        try:
            prg.serving.tick()
        finally:
            httpd.shutdown()
            httpd.server_close()
        info = prg.serving.service_info("web")
        assert info["replicas"] == 2
        assert info["slo"]["lastObserved"]["ttftP95Ms"] == 900.0
        assert "slo breach" in info["lastScale"]["reason"]


class TestFailedReplicaHealing:
    def test_failed_replica_is_replaced(self):
        prg = boot()
        create(prg, replicas=2)
        prg.job_svc.fail_job("web.r1", "crash loop (test)")
        assert job_phase(prg, "web.r1") == "failed"
        prg.serving.tick()
        assert job_phase(prg, "web.r1") == "running"
        assert oracle(prg) == []


@pytest.mark.chaos
class TestServiceChaos:
    """Kill the daemon at every service.* crash point; a fresh Program
    over the same store + engines must reconcile to exactly one
    fully-owned replica set (families 0..replicas-1, nothing beyond),
    zero leaked chips/ports, and a fixpoint second sweep."""

    def _drive(self, prg, point):
        if point == "service.create.after_record":
            create(prg, replicas=2)
        elif point == "service.scale_up.after_mark":
            create(prg, replicas=1)
            prg.serving.set_offered_load("web", 250)  # wants 3
            prg.serving.tick()
        elif point in ("service.scale_down.after_mark",
                       "service.scale_down.after_quiesce"):
            create(prg, replicas=2)
            prg.serving.patch_service("web", ServicePatch(replicas=1))
        elif point == "service.roll.after_version":
            create(prg, replicas=2)
            prg.serving.patch_service(
                "web", ServicePatch(image_name="serve:v2"))
        elif point == "service.delete.after_mark":
            create(prg, replicas=2)
            prg.serving.delete_service("web")
        else:  # pragma: no cover — keep the matrix exhaustive
            raise AssertionError(f"unmapped crash point {point}")

    @pytest.mark.parametrize("point", SERVICE_CRASH_POINTS)
    def test_crash_converges_to_one_owned_replica_set(self, point):
        kv = MemoryKV()
        rt = FakeRuntime()
        prg = boot(kv=kv, runtimes={"h0": rt})
        with armed(point):
            with pytest.raises(SimulatedCrash):
                self._drive(prg, point)

        # the daemon is dead; a fresh control plane boots over same state
        prg2 = boot(kv=kv, runtimes={"h0": rt})
        prg2.reconciler.reconcile()
        # drain any admission records the repair enqueued (full pool case)
        for _ in range(4):
            if not prg2.admission.admit_once():
                break
        problems = oracle(prg2)
        assert problems == [], f"{point}: {problems}"

        if point == "service.delete.after_mark":
            # teardown intent was durable: the sweep finished it
            assert prg2.service_versions.snapshot() == {}
            assert prg2.job_versions.snapshot() == {}
            assert prg2.pod_scheduler.status()["slices"] == {}
        else:
            info = prg2.serving.service_info("web")
            want = info["replicas"]
            fams = sorted(prg2.job_versions.snapshot())
            assert fams == [f"web.r{i}" for i in range(want)], (
                f"{point}: fleet {fams} vs want {want}")
            assert info["readyReplicas"] == want
            if point == "service.roll.after_version":
                # the new spec version won: every replica rolled forward
                for rb in fams:
                    assert prg2.store.get_job(
                        f"{rb}-{prg2.job_versions.get(rb)}"
                    ).image == "serve:v2"

        # the repair is a fixpoint
        assert prg2.reconciler.reconcile()["actions"] == [], point

    def test_scale_up_crash_with_full_pool_queues_through_market(self):
        """The scale_up.after_mark kill with a batch gang holding the
        capacity: the NEXT daemon's reconcile submits the missing replica
        through the admission queue, which preempts the batch gang."""
        kv = MemoryKV()
        rt = FakeRuntime()
        prg = boot(kv=kv, runtimes={"h0": rt})
        prg.job_svc.run_job(JobRun(image_name="jax", job_name="train",
                                   chip_count=4, priority_class="batch"))
        create(prg, replicas=1, max_replicas=3)
        prg.serving.set_offered_load("web", 280)
        with armed("service.scale_up.after_mark"):
            with pytest.raises(SimulatedCrash):
                prg.serving.tick()

        prg2 = boot(kv=kv, runtimes={"h0": rt})
        prg2.reconciler.reconcile()
        for _ in range(4):
            if not prg2.admission.admit_once():
                break
        assert prg2.serving.service_info("web")["readyReplicas"] == 3
        assert job_phase(prg2, "train") == "preempted"
        assert oracle(prg2) == []
        assert prg2.reconciler.reconcile()["actions"] == []


class TestConfigValidation:
    def test_load_validates_service_keys(self, tmp_path):
        good = tmp_path / "good.toml"
        good.write_text('service_default_class = "system"\n'
                        "autoscale_down_watermark = 0.8\n")
        assert config_mod.load(str(good)).service_default_class == "system"
        for bad in ('service_default_class = "gold"\n',
                    "autoscale_down_watermark = 1.5\n",
                    "autoscale_down_watermark = 0.0\n",
                    "autoscale_interval_s = -1\n",
                    "autoscale_up_cooldown_s = -1\n"):
            p = tmp_path / "bad.toml"
            p.write_text(bad)
            with pytest.raises(ValueError):
                config_mod.load(str(p))


class TestHttpSurface:
    def test_service_routes_and_events(self):
        import urllib.request

        prg = boot()
        prg.start()
        port = prg.api_server.port

        def call(method, path, body=None):
            req = urllib.request.Request(
                f"http://127.0.0.1:{port}{path}", method=method,
                data=json.dumps(body).encode() if body is not None else None,
                headers={"Content-Type": "application/json"})
            with urllib.request.urlopen(req, timeout=10) as resp:
                return json.loads(resp.read())

        try:
            out = call("POST", "/api/v1/services", {
                "serviceName": "llm", "imageName": "serve",
                "chipsPerReplica": 2, "replicas": 1, "maxReplicas": 2})
            assert out["code"] == 200
            assert out["data"]["priorityClass"] == "production"
            assert call("GET", "/api/v1/services")["data"][0]["name"] == \
                "llm-0"
            out = call("POST", "/api/v1/services/llm/load", {"rps": 50})
            assert out["data"]["offeredRps"] == 50.0
            out = call("PATCH", "/api/v1/services/llm", {"replicas": 2})
            assert out["data"]["replicas"] == 2
            assert out["data"]["manualScaleTotal"] == 1
            info = call("GET", "/api/v1/services/llm")["data"]
            assert info["lastScale"]["trigger"] == "manual"
            events = call("GET", "/api/v1/events?limit=100")["data"]
            kinds = {e.get("event") for e in events}
            assert {"service-created", "service-scaled"} <= kinds
            assert call("DELETE", "/api/v1/services/llm")["code"] == 200
            events = call("GET", "/api/v1/events?limit=100")["data"]
            assert "service-deleted" in {e.get("event") for e in events}
            out = call("GET", "/api/v1/services/llm")
            assert out["code"] == errors.ServiceNotExist.code
        finally:
            prg.stop()
