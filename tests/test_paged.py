"""Paged-KV slot engine (infer/paged.py + ops/paged.py).

The dense engine's exactness contract re-proven over the page pool —
per-stream outputs token-exact vs an isolated greedy decode for any
admission order, slot reuse, pool exhaustion, deferred admissions, and
page recycling — plus the capacity claims: a pool smaller than
slots × max_seq serves traffic the dense allocation could not fit, and
immediate page reuse stays safe under stale pipeline-lag lanes (the
donated pool buffers serialize device execution).
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from tpu_docker_api.infer.engine import GenerateConfig, make_generate_fn
from tpu_docker_api.infer.paged import PagedSlotEngine
from tpu_docker_api.models.llama import llama_init, llama_presets

MAX_SEQ = 96
PAGE = 16


@pytest.fixture(scope="module")
def setup():
    cfg = llama_presets()["tiny"]
    params = llama_init(cfg, jax.random.PRNGKey(7))
    return cfg, params


def isolated_greedy(cfg, params, prompt, max_new, eos_id=None,
                    max_seq=MAX_SEQ):
    fn = make_generate_fn(
        cfg, GenerateConfig(max_new_tokens=max_new, temperature=0.0,
                            eos_id=eos_id, max_seq=max_seq))
    out = fn(params, jnp.asarray([prompt], jnp.int32),
             jax.random.PRNGKey(0))
    toks = np.asarray(out["tokens"])[0]
    n = int(np.asarray(out["lengths"])[0])
    return toks[:n].tolist()


def run_all(eng, handles, limit=500):
    for _ in range(limit):
        if all(h.done() for h in handles):
            return
        eng.step()
    raise AssertionError("requests did not complete")


class TestTokenExact:
    def test_single_request_matches_isolated(self, setup):
        cfg, params = setup
        eng = PagedSlotEngine(cfg, params, page_size=PAGE, slots=4,
                              max_seq=MAX_SEQ, chunk=4)
        prompt = [3, 1, 4, 1, 5]
        h = eng.submit(prompt, max_new=12)
        run_all(eng, [h])
        got = h.result(0)
        assert got["tokens"] == isolated_greedy(cfg, params, prompt, 12)
        assert got["length"] == 12

    def test_concurrent_mixed_lengths_token_exact(self, setup):
        cfg, params = setup
        eng = PagedSlotEngine(cfg, params, page_size=PAGE, slots=4,
                              max_seq=MAX_SEQ, chunk=4)
        prompts = [[2, 7, 1], [9] * 20, [5, 5], [1, 2, 3, 4, 5, 6, 7],
                   [8, 6, 4], [11, 13]]
        max_news = [10, 6, 13, 9, 5, 16]
        handles = [eng.submit(p, m) for p, m in zip(prompts, max_news)]
        run_all(eng, handles)
        for p, m, h in zip(prompts, max_news, handles):
            assert h.result(0)["tokens"] == isolated_greedy(
                cfg, params, p, m)

    def test_slot_reuse_recycles_pages_exactly(self, setup):
        """More requests than slots: completions recycle pages into
        later admissions — late requests stay exact."""
        cfg, params = setup
        eng = PagedSlotEngine(cfg, params, page_size=PAGE, slots=2,
                              max_seq=MAX_SEQ, chunk=3)
        prompts = [[i + 1, i + 2, i + 3] for i in range(7)]
        handles = [eng.submit(p, 8) for p in prompts[:3]]
        for step in range(400):
            eng.step()
            if step == 2:
                handles += [eng.submit(p, 8) for p in prompts[3:]]
            if len(handles) == 7 and all(h.done() for h in handles):
                break
        assert eng.stats["completed"] == 7
        for p, h in zip(prompts, handles):
            assert h.result(0)["tokens"] == isolated_greedy(
                cfg, params, p, 8)
        # every page returned immediately on completion (frees don't
        # wait on the pipeline lag — device ordering makes reuse safe)
        assert eng.stats["pages_free"] == eng.stats["pages_total"]

    def test_sampling_paths_run(self, setup):
        cfg, params = setup
        eng = PagedSlotEngine(cfg, params, page_size=PAGE, slots=2,
                              max_seq=MAX_SEQ, chunk=4)
        hs = [eng.submit([1, 2, 3], 6, temperature=0.8),
              eng.submit([4, 5], 6, temperature=0.9, top_k=4,
                         top_p=0.9)]
        run_all(eng, hs)
        for h in hs:
            toks = h.result(0)["tokens"]
            assert len(toks) == 6
            assert all(0 <= t < cfg.vocab_size for t in toks)

    def test_eos_and_max_new_1(self, setup):
        cfg, params = setup
        prompt = [3, 1, 4, 1, 5]
        ref = isolated_greedy(cfg, params, prompt, 12)
        eos = ref[3]
        eng = PagedSlotEngine(cfg, params, page_size=PAGE, slots=2,
                              max_seq=MAX_SEQ, chunk=4)
        h = eng.submit(prompt, 12, eos_id=eos)
        h1 = eng.submit([7, 7, 7], 1)
        run_all(eng, [h, h1])
        assert h.result(0)["tokens"] == ref[:ref.index(eos) + 1]
        assert h1.result(0)["length"] == 1
        assert h1.result(0)["tokens"] == isolated_greedy(
            cfg, params, [7, 7, 7], 1)


class TestCapacity:
    def test_pool_smaller_than_dense_serves_short_requests(self, setup):
        """The capacity point: 4 slots × 96 capacity would need 24
        dense pages/slot-row; a 12-page pool (1/8 of dense) still
        serves 4 concurrent short requests, token-exact."""
        cfg, params = setup
        eng = PagedSlotEngine(cfg, params, page_size=PAGE, slots=4,
                              max_seq=MAX_SEQ, chunk=4, total_pages=12)
        assert eng.stats["pages_total"] == 12
        prompts = [[i + 1, i + 2] for i in range(4)]
        handles = [eng.submit(p, 8) for p in prompts]
        run_all(eng, handles)
        for p, h in zip(prompts, handles):
            assert h.result(0)["tokens"] == isolated_greedy(
                cfg, params, p, 8)

    def test_exhausted_pool_defers_then_completes_fcfs(self, setup):
        """Pool covers ~one long request: concurrent submits defer and
        complete serially, in order, token-exact — no leapfrogging."""
        cfg, params = setup
        eng = PagedSlotEngine(cfg, params, page_size=PAGE, slots=4,
                              max_seq=MAX_SEQ, chunk=4, total_pages=4)
        # each needs 3 pages (bucket 32 → 2, +tokens) → only one fits
        prompts = [[9] * 30, [1] * 30, [5] * 30]
        handles = [eng.submit(p, 16) for p in prompts]
        run_all(eng, handles, limit=900)
        assert eng.stats["deferred_admissions"] >= 1
        done_order = sorted(range(3),
                            key=lambda i: handles[i].completed_at)
        assert done_order == [0, 1, 2]
        for p, h in zip(prompts, handles):
            assert h.result(0)["tokens"] == isolated_greedy(
                cfg, params, p, 16)

    def test_request_larger_than_pool_rejected(self, setup):
        cfg, params = setup
        eng = PagedSlotEngine(cfg, params, page_size=PAGE, slots=2,
                              max_seq=MAX_SEQ, chunk=4, total_pages=2)
        with pytest.raises(ValueError, match="pages"):
            eng.submit([1] * 40, 30)

    def test_stale_lanes_cannot_corrupt_reissued_pages(self, setup):
        """Immediate page reuse under maximal pressure: a tiny pool
        with deep pipeline lag and constant resubmission — stale lanes
        still decoding must never corrupt pages already handed to a
        new request (safe because donated pool buffers serialize
        device execution; this test is the regression net for that
        argument)."""
        cfg, params = setup
        eng = PagedSlotEngine(cfg, params, page_size=PAGE, slots=3,
                              max_seq=MAX_SEQ, chunk=3, total_pages=6,
                              pipeline=3)
        prompts = [[i + 2, i + 5, i + 1] for i in range(9)]
        handles = [eng.submit(p, 7) for p in prompts]
        run_all(eng, handles, limit=900)
        for p, h in zip(prompts, handles):
            assert h.result(0)["tokens"] == isolated_greedy(
                cfg, params, p, 7)


class TestEdges:
    def test_capacity_boundary_request_admits(self, setup):
        """prompt+max_new-1 == max_seq (validate's boundary) must fit
        the table row exactly — the reservation can never exceed
        max_pages_per_slot (review r4: the off-by-one killed the
        engine thread)."""
        cfg, params = setup
        eng = PagedSlotEngine(cfg, params, page_size=PAGE, slots=2,
                              max_seq=MAX_SEQ, chunk=4)
        prompt = [((i * 3) % 250) + 1 for i in range(65)]
        h = eng.submit(prompt, 32)  # 65 + 32 - 1 == 96 == max_seq
        run_all(eng, [h], limit=900)
        assert h.result(0)["tokens"] == isolated_greedy(
            cfg, params, prompt, 32)

    def test_non_pow2_max_seq_bucket_divisibility_rejected(self, setup):
        """max_seq 48 yields bucket 48; page 32 divides the smallest
        bucket but not 48 — must be rejected at construction, not
        crash at first admission."""
        cfg, params = setup
        with pytest.raises(ValueError, match="every prefill bucket"):
            PagedSlotEngine(cfg, params, page_size=32, slots=2,
                            max_seq=48, chunk=4)

    def test_deferred_handles_fail_on_close_and_die(self, setup):
        cfg, params = setup
        eng = PagedSlotEngine(cfg, params, page_size=PAGE, slots=4,
                              max_seq=MAX_SEQ, chunk=4, total_pages=5)
        h1 = eng.submit([9] * 30, 40)   # takes the whole pool, long-run
        h2 = eng.submit([1] * 30, 40)   # deferred
        for _ in range(6):
            eng.step()
        assert eng._deferred and not h2.done()
        eng.close()
        with pytest.raises(RuntimeError, match="engine closed"):
            h2.result(0)
        # _die path: park a deferred handle, then kill the engine
        eng2 = PagedSlotEngine(cfg, params, page_size=PAGE, slots=4,
                               max_seq=MAX_SEQ, chunk=4, total_pages=5)
        d1 = eng2.submit([9] * 30, 40)
        d2 = eng2.submit([1] * 30, 40)
        for _ in range(6):
            eng2.step()
        assert eng2._deferred
        eng2._die(RuntimeError("boom"))
        with pytest.raises(RuntimeError, match="engine failed"):
            d2.result(0)
        del h1, d1

    def test_deferred_counter_counts_once(self, setup):
        cfg, params = setup
        eng = PagedSlotEngine(cfg, params, page_size=PAGE, slots=4,
                              max_seq=MAX_SEQ, chunk=4, total_pages=4)
        h1 = eng.submit([9] * 30, 16)
        h2 = eng.submit([1] * 30, 16)
        for _ in range(12):  # many re-attempts while h1 decodes
            eng.step()
        assert eng.stats["deferred_admissions"] == 1
        run_all(eng, [h1, h2], limit=900)
        assert eng.stats["deferred_admissions"] == 1


class TestScope:
    def test_v1_scope_rejections(self, setup):
        cfg, params = setup
        with pytest.raises(ValueError, match="power of two"):
            PagedSlotEngine(cfg, params, page_size=3)
        with pytest.raises(ValueError, match="chunked prefill"):
            PagedSlotEngine(cfg, params, page_size=PAGE,
                            prefill_chunk=8)
        eng = PagedSlotEngine(cfg, params, page_size=PAGE, slots=2,
                              max_seq=MAX_SEQ, chunk=4)
        with pytest.raises(ValueError, match="not supported"):
            eng.register_prefix([1, 2, 3])

    def test_warmup_then_thread_loop(self, setup):
        cfg, params = setup
        eng = PagedSlotEngine(cfg, params, page_size=PAGE, slots=2,
                              max_seq=MAX_SEQ, chunk=4)
        eng.warmup(buckets=(32,))
        with eng:
            h = eng.submit([2, 4, 6], 8)
            assert h.result(60)["tokens"] == isolated_greedy(
                cfg, params, [2, 4, 6], 8)
