"""Paged-KV slot engine (infer/paged.py + ops/paged.py).

The dense engine's exactness contract re-proven over the page pool —
per-stream outputs token-exact vs an isolated greedy decode for any
admission order, slot reuse, pool exhaustion, deferred admissions, and
page recycling — plus the capacity claims: a pool smaller than
slots × max_seq serves traffic the dense allocation could not fit, and
immediate page reuse stays safe under stale pipeline-lag lanes (the
donated pool buffers serialize device execution).
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

#: JAX-compile heavy: excluded from the `-m 'not slow'` quick tier so it
#: fits its time budget; still runs in `make test` (the full suite)
pytestmark = pytest.mark.slow


from tpu_docker_api.infer.engine import GenerateConfig, make_generate_fn
from tpu_docker_api.infer.paged import PagedSlotEngine
from tpu_docker_api.models.llama import llama_init, llama_presets

MAX_SEQ = 96
PAGE = 16


@pytest.fixture(scope="module")
def setup():
    cfg = llama_presets()["tiny"]
    params = llama_init(cfg, jax.random.PRNGKey(7))
    return cfg, params


def isolated_greedy(cfg, params, prompt, max_new, eos_id=None,
                    max_seq=MAX_SEQ):
    fn = make_generate_fn(
        cfg, GenerateConfig(max_new_tokens=max_new, temperature=0.0,
                            eos_id=eos_id, max_seq=max_seq))
    out = fn(params, jnp.asarray([prompt], jnp.int32),
             jax.random.PRNGKey(0))
    toks = np.asarray(out["tokens"])[0]
    n = int(np.asarray(out["lengths"])[0])
    return toks[:n].tolist()


def run_all(eng, handles, limit=500):
    for _ in range(limit):
        if all(h.done() for h in handles):
            return
        eng.step()
    raise AssertionError("requests did not complete")


class TestTokenExact:
    def test_single_request_matches_isolated(self, setup):
        cfg, params = setup
        eng = PagedSlotEngine(cfg, params, page_size=PAGE, slots=4,
                              max_seq=MAX_SEQ, chunk=4)
        prompt = [3, 1, 4, 1, 5]
        h = eng.submit(prompt, max_new=12)
        run_all(eng, [h])
        got = h.result(0)
        assert got["tokens"] == isolated_greedy(cfg, params, prompt, 12)
        assert got["length"] == 12

    def test_concurrent_mixed_lengths_token_exact(self, setup):
        cfg, params = setup
        eng = PagedSlotEngine(cfg, params, page_size=PAGE, slots=4,
                              max_seq=MAX_SEQ, chunk=4)
        prompts = [[2, 7, 1], [9] * 20, [5, 5], [1, 2, 3, 4, 5, 6, 7],
                   [8, 6, 4], [11, 13]]
        max_news = [10, 6, 13, 9, 5, 16]
        handles = [eng.submit(p, m) for p, m in zip(prompts, max_news)]
        run_all(eng, handles)
        for p, m, h in zip(prompts, max_news, handles):
            assert h.result(0)["tokens"] == isolated_greedy(
                cfg, params, p, m)

    def test_slot_reuse_recycles_pages_exactly(self, setup):
        """More requests than slots: completions recycle pages into
        later admissions — late requests stay exact."""
        cfg, params = setup
        eng = PagedSlotEngine(cfg, params, page_size=PAGE, slots=2,
                              max_seq=MAX_SEQ, chunk=3)
        prompts = [[i + 1, i + 2, i + 3] for i in range(7)]
        handles = [eng.submit(p, 8) for p in prompts[:3]]
        for step in range(400):
            eng.step()
            if step == 2:
                handles += [eng.submit(p, 8) for p in prompts[3:]]
            if len(handles) == 7 and all(h.done() for h in handles):
                break
        assert eng.stats["completed"] == 7
        for p, h in zip(prompts, handles):
            assert h.result(0)["tokens"] == isolated_greedy(
                cfg, params, p, 8)
        # every page returned immediately on completion (frees don't
        # wait on the pipeline lag — device ordering makes reuse safe)
        assert eng.stats["pages_free"] == eng.stats["pages_total"]

    def test_sampling_paths_run(self, setup):
        cfg, params = setup
        eng = PagedSlotEngine(cfg, params, page_size=PAGE, slots=2,
                              max_seq=MAX_SEQ, chunk=4)
        hs = [eng.submit([1, 2, 3], 6, temperature=0.8),
              eng.submit([4, 5], 6, temperature=0.9, top_k=4,
                         top_p=0.9)]
        run_all(eng, hs)
        for h in hs:
            toks = h.result(0)["tokens"]
            assert len(toks) == 6
            assert all(0 <= t < cfg.vocab_size for t in toks)

    def test_eos_and_max_new_1(self, setup):
        cfg, params = setup
        prompt = [3, 1, 4, 1, 5]
        ref = isolated_greedy(cfg, params, prompt, 12)
        eos = ref[3]
        eng = PagedSlotEngine(cfg, params, page_size=PAGE, slots=2,
                              max_seq=MAX_SEQ, chunk=4)
        h = eng.submit(prompt, 12, eos_id=eos)
        h1 = eng.submit([7, 7, 7], 1)
        run_all(eng, [h, h1])
        assert h.result(0)["tokens"] == ref[:ref.index(eos) + 1]
        assert h1.result(0)["length"] == 1
        assert h1.result(0)["tokens"] == isolated_greedy(
            cfg, params, [7, 7, 7], 1)


class TestCapacity:
    def test_pool_smaller_than_dense_serves_short_requests(self, setup):
        """The capacity point: 4 slots × 96 capacity would need 24
        dense pages/slot-row; a 12-page pool (1/8 of dense) still
        serves 4 concurrent short requests, token-exact."""
        cfg, params = setup
        eng = PagedSlotEngine(cfg, params, page_size=PAGE, slots=4,
                              max_seq=MAX_SEQ, chunk=4, total_pages=12)
        assert eng.stats["pages_total"] == 12
        prompts = [[i + 1, i + 2] for i in range(4)]
        handles = [eng.submit(p, 8) for p in prompts]
        run_all(eng, handles)
        for p, h in zip(prompts, handles):
            assert h.result(0)["tokens"] == isolated_greedy(
                cfg, params, p, 8)

    def test_exhausted_pool_defers_then_completes_fcfs(self, setup):
        """Pool covers ~one long request: concurrent submits defer and
        complete serially, in order, token-exact — no leapfrogging."""
        cfg, params = setup
        # reservation="full": the r4 worst-case policy, kept as the
        # escape hatch — ITS contract is strict FCFS completion order;
        # the r5 default ("grow") trades that for admission concurrency
        # (preemption may reorder completions; TestGrowthPreemption)
        eng = PagedSlotEngine(cfg, params, page_size=PAGE, slots=4,
                              max_seq=MAX_SEQ, chunk=4, total_pages=4,
                              reservation="full")
        # each needs 3 pages (bucket 32 → 2, +tokens) → only one fits
        prompts = [[9] * 30, [1] * 30, [5] * 30]
        handles = [eng.submit(p, 16) for p in prompts]
        run_all(eng, handles, limit=900)
        assert eng.stats["deferred_admissions"] >= 1
        done_order = sorted(range(3),
                            key=lambda i: handles[i].completed_at)
        assert done_order == [0, 1, 2]
        for p, h in zip(prompts, handles):
            assert h.result(0)["tokens"] == isolated_greedy(
                cfg, params, p, 16)

    def test_request_larger_than_pool_rejected(self, setup):
        cfg, params = setup
        eng = PagedSlotEngine(cfg, params, page_size=PAGE, slots=2,
                              max_seq=MAX_SEQ, chunk=4, total_pages=2)
        with pytest.raises(ValueError, match="pages"):
            eng.submit([1] * 40, 30)

    def test_stale_lanes_cannot_corrupt_reissued_pages(self, setup):
        """Immediate page reuse under maximal pressure: a tiny pool
        with deep pipeline lag and constant resubmission — stale lanes
        still decoding must never corrupt pages already handed to a
        new request (safe because donated pool buffers serialize
        device execution; this test is the regression net for that
        argument)."""
        cfg, params = setup
        eng = PagedSlotEngine(cfg, params, page_size=PAGE, slots=3,
                              max_seq=MAX_SEQ, chunk=3, total_pages=6,
                              pipeline=3)
        prompts = [[i + 2, i + 5, i + 1] for i in range(9)]
        handles = [eng.submit(p, 7) for p in prompts]
        run_all(eng, handles, limit=900)
        for p, h in zip(prompts, handles):
            assert h.result(0)["tokens"] == isolated_greedy(
                cfg, params, p, 7)


class TestEdges:
    def test_capacity_boundary_request_admits(self, setup):
        """prompt+max_new-1 == max_seq (validate's boundary) must fit
        the table row exactly — the reservation can never exceed
        max_pages_per_slot (review r4: the off-by-one killed the
        engine thread)."""
        cfg, params = setup
        eng = PagedSlotEngine(cfg, params, page_size=PAGE, slots=2,
                              max_seq=MAX_SEQ, chunk=4)
        prompt = [((i * 3) % 250) + 1 for i in range(65)]
        h = eng.submit(prompt, 32)  # 65 + 32 - 1 == 96 == max_seq
        run_all(eng, [h], limit=900)
        assert h.result(0)["tokens"] == isolated_greedy(
            cfg, params, prompt, 32)

    def test_non_pow2_max_seq_bucket_divisibility_rejected(self, setup):
        """max_seq 48 yields bucket 48; page 32 divides the smallest
        bucket but not 48 — must be rejected at construction, not
        crash at first admission."""
        cfg, params = setup
        with pytest.raises(ValueError, match="every prefill bucket"):
            PagedSlotEngine(cfg, params, page_size=32, slots=2,
                            max_seq=48, chunk=4)

    def test_deferred_handles_fail_on_close_and_die(self, setup):
        cfg, params = setup
        eng = PagedSlotEngine(cfg, params, page_size=PAGE, slots=4,
                              max_seq=MAX_SEQ, chunk=4, total_pages=5)
        h1 = eng.submit([9] * 30, 40)   # takes the whole pool, long-run
        h2 = eng.submit([1] * 30, 40)   # deferred
        for _ in range(6):
            eng.step()
        assert eng._deferred and not h2.done()
        eng.close()
        with pytest.raises(RuntimeError, match="engine closed"):
            h2.result(0)
        # _die path: park a deferred handle, then kill the engine
        eng2 = PagedSlotEngine(cfg, params, page_size=PAGE, slots=4,
                               max_seq=MAX_SEQ, chunk=4, total_pages=5)
        d1 = eng2.submit([9] * 30, 40)
        d2 = eng2.submit([1] * 30, 40)
        for _ in range(6):
            eng2.step()
        assert eng2._deferred
        eng2._die(RuntimeError("boom"))
        with pytest.raises(RuntimeError, match="engine failed"):
            d2.result(0)
        del h1, d1

    def test_deferred_counter_counts_once(self, setup):
        cfg, params = setup
        eng = PagedSlotEngine(cfg, params, page_size=PAGE, slots=4,
                              max_seq=MAX_SEQ, chunk=4, total_pages=4,
                              reservation="full")
        h1 = eng.submit([9] * 30, 16)
        h2 = eng.submit([1] * 30, 16)
        for _ in range(12):  # many re-attempts while h1 decodes
            eng.step()
        assert eng.stats["deferred_admissions"] == 1
        run_all(eng, [h1, h2], limit=900)
        assert eng.stats["deferred_admissions"] == 1


class TestScope:
    def test_v1_scope_rejections(self, setup):
        cfg, params = setup
        with pytest.raises(ValueError, match="power of two"):
            PagedSlotEngine(cfg, params, page_size=3)
        # r5: chunked prefill composes (TestPagedChunkedPrefill) — the
        # construction that used to reject must now build cleanly
        PagedSlotEngine(cfg, params, page_size=PAGE, prefill_chunk=8)
        eng = PagedSlotEngine(cfg, params, page_size=PAGE, slots=2,
                              max_seq=MAX_SEQ, chunk=4)
        # r5: prefix caching is supported — but a sub-page prefix shares
        # nothing read-only, so it refuses loudly instead of no-opping
        with pytest.raises(ValueError, match="shorter than one page"):
            eng.register_prefix([1, 2, 3])

    def test_warmup_then_thread_loop(self, setup):
        cfg, params = setup
        eng = PagedSlotEngine(cfg, params, page_size=PAGE, slots=2,
                              max_seq=MAX_SEQ, chunk=4)
        eng.warmup(buckets=(32,))
        with eng:
            h = eng.submit([2, 4, 6], 8)
            assert h.result(60)["tokens"] == isolated_greedy(
                cfg, params, [2, 4, 6], 8)


class TestPrefixSharing:
    """Paged × prefix caching (VERDICT r4 next #3): refcounted
    read-only shared pages. Exactness, accounting, unregister with
    live readers, and the capacity math that makes sharing the point."""

    PX = list(range(7, 7 + 20))  # 20 tokens: 1 shared page + 4-token
    #                              unaligned tail at PAGE=16

    def _engine(self, setup, **kw):
        cfg, params = setup
        kw.setdefault("page_size", PAGE)
        kw.setdefault("slots", 4)
        kw.setdefault("max_seq", MAX_SEQ)
        kw.setdefault("chunk", 4)
        return cfg, params, PagedSlotEngine(cfg, params, **kw)

    def test_shared_prefix_token_exact_across_slots(self, setup):
        cfg, params, eng = self._engine(setup)
        pid = eng.register_prefix(self.PX)
        assert pid.startswith("px-")
        suffixes = [[30 + i, 40 + i, 50 + i] for i in range(4)]
        handles = [eng.submit(self.PX + sfx, 10) for sfx in suffixes]
        run_all(eng, handles)
        for sfx, h in zip(suffixes, handles):
            assert h.result(0)["tokens"] == isolated_greedy(
                cfg, params, self.PX + sfx, 10)
        assert eng.stats["prefix_hits"] == 4

    def test_page_aligned_sharing_only(self, setup):
        """20-token prefix at page 16 shares exactly ONE page; the
        4-token tail re-prefills with each suffix (read-only sharing's
        price, ≤ page−1 tokens)."""
        _, _, eng = self._engine(setup)
        total = eng.stats["pages_total"]
        eng.register_prefix(self.PX)
        assert eng.stats["pages_free"] == total - 1
        ent = next(iter(eng._prefixes.values()))
        assert ent.shared_len == PAGE and len(ent.page_ids) == 1

    def test_pool_accounting_through_lifecycle(self, setup):
        _, _, eng = self._engine(setup)
        total = eng.stats["pages_total"]
        pid = eng.register_prefix(self.PX)
        free_after_reg = eng.stats["pages_free"]
        assert free_after_reg == total - 1
        handles = [eng.submit(self.PX + [60 + i], 6) for i in range(3)]
        eng.step()  # admission reserves private pages
        assert eng.stats["pages_free"] < free_after_reg
        run_all(eng, handles)
        # completions release private pages; the shared page stays
        assert eng.stats["pages_free"] == free_after_reg
        assert eng.unregister_prefix(pid)
        assert eng.stats["pages_free"] == total

    def test_unregister_with_live_readers_defers_reclaim(self, setup):
        cfg, params, eng = self._engine(setup)
        total = eng.stats["pages_total"]
        pid = eng.register_prefix(self.PX)
        prompt = self.PX + [33, 44]
        h = eng.submit(prompt, 16)
        eng.step()  # admit; slot now reads the shared page
        assert eng.stats["prefix_hits"] == 1
        assert eng.unregister_prefix(pid)
        assert eng.prefixes() == []  # no new admissions can attach
        # the shared page is NOT back in the pool while the reader lives
        assert eng.stats["pages_free"] < total
        run_all(eng, [h])
        eng.step()  # reclaim pass after the completion
        assert eng.stats["pages_free"] == total
        # and the in-flight request stayed exact throughout
        assert h.result(0)["tokens"] == isolated_greedy(
            cfg, params, prompt, 16)

    def test_late_joiner_shares_with_active_decoders(self, setup):
        """A request admitted while earlier hits are mid-decode reads
        the same shared page — concurrency across admission waves."""
        cfg, params, eng = self._engine(setup)
        first = eng.submit(self.PX + [81], 12)
        for _ in range(3):
            eng.step()
        late = eng.submit(self.PX + [82, 83], 12)
        run_all(eng, [first, late])
        assert first.result(0)["tokens"] == isolated_greedy(
            cfg, params, self.PX + [81], 12)
        assert late.result(0)["tokens"] == isolated_greedy(
            cfg, params, self.PX + [82, 83], 12)

    def test_sharing_capacity_math(self, setup):
        """The point of sharing: a pool sized for ONE copy of the
        prefix + per-request private pages admits all requests at once;
        the same pool must defer if every request carried its own full
        reservation."""
        cfg, params = setup
        # 4 requests: prompt 20+2=22, max_new 6 → reach 27 → 2 pages;
        # private need per request = max(sfx_pages(1, 32)=2, 2-1) = 2.
        # Pool: 1 shared + 4×2 private = 9 pages. Without sharing each
        # request needs ceil(max(32, 27)/16) = 2 pages... the SHARED
        # page is what the 4 full-prefill requests would each re-own:
        # full need = 2 pages each at bucket 32, but prompt 22 + 6 - 1
        # = 27 → bucket 32 → need 2. So make suffixes longer to widen
        # the gap: prompt 22, max_new 12 → reach 33 → 3 pages full,
        # private 2. Pool = 1 + 4×2 = 9 < 4×3 = 12.
        eng = PagedSlotEngine(cfg, params, page_size=PAGE, slots=4,
                              max_seq=MAX_SEQ, chunk=4, total_pages=9)
        eng.register_prefix(self.PX)
        handles = [eng.submit(self.PX + [70 + i, 90 + i], 12)
                   for i in range(4)]
        eng.step()
        # all four admitted in one wave — nothing deferred
        assert eng.stats["deferred_admissions"] == 0
        run_all(eng, handles)
        for i, h in enumerate(handles):
            assert h.result(0)["tokens"] == isolated_greedy(
                cfg, params, self.PX + [70 + i, 90 + i], 12)

    def test_register_via_engine_thread(self, setup):
        """register_prefix with the loop running routes through the
        command queue and joins the donation chain."""
        cfg, params, eng = self._engine(setup)
        with eng:
            pid = eng.register_prefix(self.PX)
            assert pid.startswith("px-")
            h = eng.submit(self.PX + [21, 22], 8)
            assert h.result(120)["tokens"] == isolated_greedy(
                cfg, params, self.PX + [21, 22], 8)
            assert eng.stats["prefix_hits"] == 1

    def test_prompt_past_bucket_ceiling_via_prefix(self, setup):
        """Dense-engine parity: a prompt longer than the largest
        prefill bucket admits when a registered prefix covers the
        overflow (suffix-only prefill)."""
        cfg, params = setup
        eng = PagedSlotEngine(cfg, params, page_size=PAGE, slots=2,
                              max_seq=MAX_SEQ, chunk=4, buckets=(32,))
        px = list(range(1, 33))  # exactly 2 pages, == bucket 32
        eng.register_prefix(px)
        prompt = px + [40, 41, 42, 43]  # 36 > bucket 32
        h = eng.submit(prompt, 8)
        run_all(eng, [h])
        assert h.result(0)["tokens"] == isolated_greedy(
            cfg, params, prompt, 8)
        # and without a covering prefix the same length refuses
        eng2 = PagedSlotEngine(cfg, params, page_size=PAGE, slots=2,
                               max_seq=MAX_SEQ, chunk=4, buckets=(32,))
        with pytest.raises(ValueError, match="largest prefill bucket"):
            eng2.submit(prompt, 8)

    def test_dedupe_returns_same_pid(self, setup):
        _, _, eng = self._engine(setup)
        a = eng.register_prefix(self.PX)
        b = eng.register_prefix(self.PX)
        assert a == b
        assert len(eng.prefixes()) == 1


class TestGrowthPreemption:
    """Grow-as-you-decode reservation (r5 — VERDICT r4 next #6):
    admission holds only prefill pages, chunks claim pages at the
    reservation edge, and preempt-lowest-progress with exact restore is
    the pressure valve. Exactness everywhere: restored requests must be
    token-identical to never-preempted ones (greedy)."""

    def test_growth_accounting(self, setup):
        """A lone long-decode request starts with bucket pages only and
        grows page by page as chunks cross page boundaries."""
        cfg, params = setup
        eng = PagedSlotEngine(cfg, params, page_size=PAGE, slots=2,
                              max_seq=MAX_SEQ, chunk=4, total_pages=6)
        h = eng.submit([7, 8, 9], 40)  # bucket 32 → 2 pages at admission
        eng.step()
        assert len(eng._slot_pages[next(
            i for i, s in eng._table.items() if s is not None)]) >= 2
        run_all(eng, [h])
        assert eng.stats["grown_pages"] >= 1
        assert eng.stats["preemptions"] == 0
        assert h.result(0)["tokens"] == isolated_greedy(
            cfg, params, [7, 8, 9], 40)
        assert eng.stats["pages_free"] == eng.stats["pages_total"]

    def test_admission_concurrency_beats_full_reservation(self, setup):
        """The measured claim, hermetic form: a pool that worst-case
        reservation can only serve serially admits everything at once
        under grow mode — requests PROMISE max_new=40 but emit 6 (eos),
        so their reservations were never going to be used."""
        cfg, params = setup
        prompts = [[i + 1, i + 2, i + 3] for i in range(4)]
        # eos = the 6th greedy token → each request stops at its FIRST
        # occurrence (inclusive), well before the promised 40
        refs = [isolated_greedy(cfg, params, p, 40) for p in prompts]
        eos_ids = [r[5] for r in refs]
        expected = [r[:r.index(e) + 1] for r, e in zip(refs, eos_ids)]
        # full need per request: ceil(max(32, 3+40-1)/16) = 3 pages →
        # 4 concurrent need 12; give 8: full mode MUST defer, grow
        # mode admits all 4 on 2 pages each and never grows past the
        # 6 emitted tokens
        results = {}
        for mode in ("full", "grow"):
            eng = PagedSlotEngine(cfg, params, page_size=PAGE, slots=4,
                                  max_seq=MAX_SEQ, chunk=4,
                                  total_pages=8, reservation=mode)
            handles = [eng.submit(p, 40, eos_id=e)
                       for p, e in zip(prompts, eos_ids)]
            eng.step()
            admitted = sum(s is not None for s in eng._table.values())
            run_all(eng, handles)
            results[mode] = (admitted, eng.stats["deferred_admissions"],
                             [h.result(0)["tokens"] for h in handles])
        assert results["full"][0] <= 2       # worst-case: pool-bound
        assert results["full"][1] >= 1
        assert results["grow"][0] == 4       # grow: all admitted at once
        assert results["grow"][1] == 0
        for mode in ("full", "grow"):
            for want, got in zip(expected, results[mode][2]):
                assert got == want

    def test_preemption_exact_restore(self, setup):
        """Pool pressure forces a preemption mid-decode; the preempted
        request completes token-identical to an isolated decode, and
        every page returns."""
        cfg, params = setup
        # 2 slots, pool 5: two requests admit on 2 pages each (bucket
        # 32); both need page 3 as decode crosses 32 positions — only
        # one page left, so the lower-progress slot preempts
        eng = PagedSlotEngine(cfg, params, page_size=PAGE, slots=2,
                              max_seq=MAX_SEQ, chunk=4, total_pages=5)
        pa, pb = [9] * 30, [1] * 30
        ha = eng.submit(pa, 30)
        hb = eng.submit(pb, 30)
        run_all(eng, [ha, hb], limit=900)
        assert eng.stats["preemptions"] >= 1
        assert ha.result(0)["tokens"] == isolated_greedy(
            cfg, params, pa, 30)
        assert hb.result(0)["tokens"] == isolated_greedy(
            cfg, params, pb, 30)
        assert eng.stats["pages_free"] == eng.stats["pages_total"]

    def test_preempted_stream_never_loses_or_repeats_tokens(self, setup):
        """A streaming client across a preemption sees each token
        exactly once, in order (the restore re-seeds tokens directly,
        not through emit)."""
        cfg, params = setup
        eng = PagedSlotEngine(cfg, params, page_size=PAGE, slots=2,
                              max_seq=MAX_SEQ, chunk=4, total_pages=5)
        import threading

        pa, pb = [9] * 30, [1] * 30
        got: list[int] = []
        ha = eng.submit(pa, 30, stream=True)
        t = threading.Thread(
            target=lambda: got.extend(ha.stream(timeout=300)))
        t.start()
        hb = eng.submit(pb, 30)
        run_all(eng, [ha, hb], limit=900)
        t.join(timeout=60)
        assert eng.stats["preemptions"] >= 1
        assert got == isolated_greedy(cfg, params, pa, 30)

    def test_growth_with_prefix_sharing(self, setup):
        """Grow mode composes with shared-page prefixes: hits reserve
        only suffix pages, grow privately, and preemption restores
        re-attach to the shared pages (prompt still extends the
        prefix)."""
        cfg, params = setup
        eng = PagedSlotEngine(cfg, params, page_size=PAGE, slots=3,
                              max_seq=MAX_SEQ, chunk=4, total_pages=9)
        px = list(range(7, 7 + 20))
        eng.register_prefix(px)
        prompts = [px + [50 + i] for i in range(3)]
        handles = [eng.submit(p, 24) for p in prompts]
        run_all(eng, handles, limit=900)
        for p, h in zip(prompts, handles):
            assert h.result(0)["tokens"] == isolated_greedy(
                cfg, params, p, 24)
        assert eng.stats["prefix_hits"] >= 3
        # shared page still held by the registry, private all returned
        assert eng.stats["pages_free"] == eng.stats["pages_total"] - 1

    def test_reservation_validation(self, setup):
        cfg, params = setup
        with pytest.raises(ValueError, match="reservation"):
            PagedSlotEngine(cfg, params, page_size=PAGE,
                            reservation="lazy")


class TestPinnedPageValidation:
    def test_request_exceeding_unpinned_pool_rejected(self, setup):
        """A registered prefix pins its pages for the engine's
        lifetime; a request whose need exceeds usable-minus-pinned can
        NEVER admit and must raise at submit — not hang the strict-FCFS
        queue (full mode) or preempt-restore livelock (grow mode)."""
        cfg, params = setup
        for mode in ("grow", "full"):
            eng = PagedSlotEngine(cfg, params, page_size=PAGE, slots=4,
                                  max_seq=MAX_SEQ, chunk=4,
                                  total_pages=4, reservation=mode)
            eng.register_prefix(list(range(7, 7 + 32)))  # pins 2 of 4
            # unrelated request needing 3 pages: 3 > 4-2 → reject now
            with pytest.raises(ValueError, match="pinned"):
                eng.submit([1] * 30, 16)
            # a PREFIX-extending request only needs private pages
            # beyond the shared ones — still admissible
            h = eng.submit(list(range(7, 7 + 32)) + [9], 8)
            run_all(eng, [h])
            assert h.result(0)["tokens"] == isolated_greedy(
                cfg, params, list(range(7, 7 + 32)) + [9], 8)
            assert eng.unregister_prefix(eng.prefixes()[0]["id"])
            eng.step()
            # with the pins released the same request now validates
            eng.validate([1] * 30, 16)

    def test_registration_fails_now_unfittable_pending_request(self, setup):
        """The converse order: a request validates, THEN a registration
        pins its headroom away. It must fail loudly at admission —
        silently admitting it in grow mode livelocks on self-preempt
        (no junior holds pages, the pool can never grow it)."""
        cfg, params = setup
        eng = PagedSlotEngine(cfg, params, page_size=PAGE, slots=4,
                              max_seq=MAX_SEQ, chunk=4, total_pages=4,
                              reservation="grow")
        h = eng.submit([1] * 30, 16)        # needs 3 of 4: fine today
        eng.register_prefix(list(range(7, 7 + 32)))  # pins 2 of 4
        eng.step()                          # admission re-check fires
        assert h.done()
        with pytest.raises(ValueError, match="pinned"):
            h.result(0)
        # the engine itself keeps serving prefix-extending traffic
        h2 = eng.submit(list(range(7, 7 + 32)) + [9], 8)
        run_all(eng, [h2])
        assert h2.result(0)["tokens"] == isolated_greedy(
            cfg, params, list(range(7, 7 + 32)) + [9], 8)

    def test_registration_evicts_now_unfittable_active_slot(self, setup):
        """An already-ADMITTED request whose worst-case remaining need no
        longer fits usable-minus-pinned is failed and its pages freed at
        registration time — the in-flight half of the livelock guard."""
        cfg, params = setup
        eng = PagedSlotEngine(cfg, params, page_size=PAGE, slots=4,
                              max_seq=MAX_SEQ, chunk=4, total_pages=6,
                              reservation="grow")
        # worst case 5 pages (30 + 50 - 1 tokens) of 6: admissible now
        h = eng.submit([1] * 30, 50)
        eng.step()                          # admitted, holds 2 pages
        assert not h.done()
        eng.register_prefix(list(range(7, 7 + 32)))  # pins 2 → cap 4 < 5
        assert h.done()
        with pytest.raises(ValueError, match="pinned"):
            h.result(0)
        # its pages came back: 6 total - 2 pinned = 4 free
        assert eng.stats["pages_free"] == 4
        # pool still serves requests that DO fit the shrunken capacity
        h2 = eng.submit([2] * 20, 8)        # 2 pages
        run_all(eng, [h2])
        assert h2.result(0)["tokens"] == isolated_greedy(
            cfg, params, [2] * 20, 8)


class TestPagedTensorParallel:
    """Paged engine on a tp mesh (r5 — VERDICT r4 next #3 secondary):
    the pool's kv-head dim shards over tp like the dense cache; the
    page table stays a replicated host operand. f32 config so
    mesh-vs-unsharded is numerically tight (the TestMeshEngine rule:
    in bf16 the tp collectives' reduction order rounds logits ~1e-2
    apart and random-init near-tie argmaxes flip — a numerics
    artifact, not a sharding bug; observed here at token 22 of a
    30-token decode before switching to f32). Prefix sharing and
    growth ride along."""

    def _setup_f32(self):
        import dataclasses

        from tpu_docker_api.parallel.mesh import MeshPlan, build_mesh
        from tpu_docker_api.parallel.sharding import (
            LLAMA_RULES, param_shardings)

        cfg = llama_presets()["tiny"]
        cfg = dataclasses.replace(cfg, dtype=jnp.float32)
        params = llama_init(cfg, jax.random.PRNGKey(7))
        mesh = build_mesh(MeshPlan(dp=1, fsdp=1, tp=2, sp=1),
                          devices=jax.devices()[:2])
        params_s = jax.device_put(
            params, param_shardings(params, mesh, LLAMA_RULES))
        return cfg, params, params_s, mesh

    def test_tp_mesh_token_exact(self):
        cfg, params, params_s, mesh = self._setup_f32()
        eng = PagedSlotEngine(cfg, params_s, mesh=mesh, page_size=PAGE,
                              slots=4, max_seq=MAX_SEQ, chunk=4)
        prompts = [[2, 7, 1], [9] * 20, [5, 5], [1, 2, 3, 4, 5]]
        handles = [eng.submit(p, 10) for p in prompts]
        run_all(eng, handles)
        for p, h in zip(prompts, handles):
            assert h.result(0)["tokens"] == isolated_greedy(
                cfg, params, p, 10)  # unsharded single-device reference

    def test_tp_mesh_prefix_and_growth(self):
        cfg, params, params_s, mesh = self._setup_f32()
        eng = PagedSlotEngine(cfg, params_s, mesh=mesh, page_size=PAGE,
                              slots=2, max_seq=MAX_SEQ, chunk=4,
                              total_pages=6)
        px = list(range(7, 7 + 20))
        eng.register_prefix(px)
        h = eng.submit(px + [42], 30)  # decode crosses page boundaries
        run_all(eng, [h])
        assert eng.stats["prefix_hits"] == 1
        assert eng.stats["grown_pages"] >= 1
        assert h.result(0)["tokens"] == isolated_greedy(
            cfg, params, px + [42], 30)
        assert eng.stats["pages_free"] == eng.stats["pages_total"] - 1

    def test_dp_mesh_still_rejected(self, setup):
        cfg, params = setup
        from tpu_docker_api.parallel.mesh import MeshPlan, build_mesh

        mesh = build_mesh(MeshPlan(dp=2, fsdp=1, tp=1, sp=1),
                          devices=jax.devices()[:2])
        with pytest.raises(ValueError, match="tp/fsdp"):
            PagedSlotEngine(cfg, params, page_size=PAGE, mesh=mesh)


class TestPagedChunkedPrefill:
    """Chunked prefill × paged (r5 — the third of the v1 exclusions to
    fall): segments gather the slot's pages into a dense temp row,
    prefill at the offset, and scatter every covered page back; parked
    lanes route to the trash page via paged_write's beyond-view bound
    (position maxp·page — max_seq itself is unsafe when not
    page-aligned)."""

    def test_long_prompt_segments_token_exact(self, setup):
        """A prompt past the largest prefill bucket serves via
        segmentation, token-exact, while a short stream decodes
        through the interleaved chunks."""
        cfg, params = setup
        eng = PagedSlotEngine(cfg, params, page_size=PAGE, slots=3,
                              max_seq=MAX_SEQ, chunk=4,
                              prefill_chunk=8, buckets=(32,))
        long_p = list(range(3, 43))   # 40 > bucket 32
        short_p = [5, 6, 7]
        h1 = eng.submit(long_p, 12)
        h2 = eng.submit(short_p, 12)
        run_all(eng, [h1, h2])
        assert h1.result(0)["tokens"] == isolated_greedy(
            cfg, params, long_p, 12)
        assert h2.result(0)["tokens"] == isolated_greedy(
            cfg, params, short_p, 12)
        assert eng.stats["segment_prefills"] >= 5
        assert eng.stats["pages_free"] == eng.stats["pages_total"]

    def test_short_prompts_skip_segmentation(self, setup):
        cfg, params = setup
        eng = PagedSlotEngine(cfg, params, page_size=PAGE, slots=2,
                              max_seq=MAX_SEQ, chunk=4,
                              prefill_chunk=16)
        h = eng.submit([1, 2, 3], 8)  # 3 <= prefill_chunk: one dispatch
        run_all(eng, [h])
        assert eng.stats["segment_prefills"] == 0
        assert h.result(0)["tokens"] == isolated_greedy(
            cfg, params, [1, 2, 3], 8)

    def test_segments_grow_pages_and_release(self, setup):
        """Grow-mode: a chunked admission reserves ZERO pages; every
        page arrives with its segment and all return at completion."""
        cfg, params = setup
        eng = PagedSlotEngine(cfg, params, page_size=PAGE, slots=2,
                              max_seq=MAX_SEQ, chunk=4,
                              prefill_chunk=8, buckets=(32,),
                              total_pages=5)
        h = eng.submit(list(range(2, 42)), 8)  # 40 tokens → 3 pages
        eng.step()  # admission reserves NOTHING; the same step's first
        #             segment claims exactly its one page — not the 3
        #             a full reservation would pin
        assert eng.stats["pages_free"] == 4
        run_all(eng, [h])
        assert eng.stats["grown_pages"] >= 3
        assert h.result(0)["tokens"] == isolated_greedy(
            cfg, params, list(range(2, 42)), 8)
        assert eng.stats["pages_free"] == 5

    def test_full_reservation_mode_chunks_too(self, setup):
        cfg, params = setup
        eng = PagedSlotEngine(cfg, params, page_size=PAGE, slots=2,
                              max_seq=MAX_SEQ, chunk=4,
                              prefill_chunk=8, buckets=(32,),
                              reservation="full")
        p = list(range(2, 40))
        h = eng.submit(p, 10)
        eng.step()
        # full mode pins the whole need up front
        held = eng.stats["pages_total"] - eng.stats["pages_free"]
        assert held >= 3
        run_all(eng, [h])
        assert h.result(0)["tokens"] == isolated_greedy(
            cfg, params, p, 10)

    def test_segment_pressure_preempts_decoder(self, setup):
        """Pool pressure between a senior decoder and a junior
        segmenter resolves by seniority-scoped preemption (the senior's
        growth takes the junior's pages, never the reverse); both
        requests still finish token-exact with all pages returned."""
        cfg, params = setup
        eng = PagedSlotEngine(cfg, params, page_size=PAGE, slots=2,
                              max_seq=MAX_SEQ, chunk=4,
                              prefill_chunk=8, buckets=(32,),
                              total_pages=4)
        ha = eng.submit([9] * 30, 20)            # decoder: 2-3 pages
        for _ in range(3):
            eng.step()
        hb = eng.submit(list(range(2, 42)), 8)   # segmenter needs 3
        run_all(eng, [ha, hb], limit=900)
        assert eng.stats["preemptions"] >= 1
        assert ha.result(0)["tokens"] == isolated_greedy(
            cfg, params, [9] * 30, 20)
        assert hb.result(0)["tokens"] == isolated_greedy(
            cfg, params, list(range(2, 42)), 8)
        assert eng.stats["pages_free"] == eng.stats["pages_total"]

    def test_preempted_long_restore_rechunks(self, setup):
        """A preempted decode slot whose prompt+progress exceeds the
        largest bucket restores THROUGH segmentation — with
        prefill_chunk on, no restore is ever non-admissible."""
        cfg, params = setup
        eng = PagedSlotEngine(cfg, params, page_size=PAGE, slots=2,
                              max_seq=MAX_SEQ, chunk=4,
                              prefill_chunk=8, buckets=(32,),
                              total_pages=6)
        pa = list(range(3, 31))                   # 28 tokens
        ha = eng.submit(pa, 30)                   # will reach 58 > 32
        for _ in range(6):
            eng.step()
        hb = eng.submit(list(range(2, 34)), 20)   # pressure
        run_all(eng, [ha, hb], limit=1200)
        assert ha.result(0)["tokens"] == isolated_greedy(
            cfg, params, pa, 30)
        assert hb.result(0)["tokens"] == isolated_greedy(
            cfg, params, list(range(2, 34)), 20)


class TestChunkedPagedReviewRegressions:
    """Pins for the r5 review findings on the chunked×paged seams."""

    def test_two_segmenters_tight_pool_both_complete(self, setup):
        """Rotation must advance past a page-stalled junior (review: a
        stalled junior re-picked forever starves the page-holding
        senior — both hang)."""
        cfg, params = setup
        eng = PagedSlotEngine(cfg, params, page_size=PAGE, slots=2,
                              max_seq=MAX_SEQ, chunk=4,
                              prefill_chunk=8, buckets=(32,),
                              total_pages=3)
        pa, pb = list(range(3, 43)), list(range(50, 90))
        ha = eng.submit(pa, 6)
        hb = eng.submit(pb, 6)
        run_all(eng, [ha, hb], limit=1500)
        assert ha.result(0)["tokens"] == isolated_greedy(
            cfg, params, pa, 6)
        assert hb.result(0)["tokens"] == isolated_greedy(
            cfg, params, pb, 6)
        assert eng.stats["pages_free"] == eng.stats["pages_total"]

    def test_validate_uses_chunked_need(self, setup):
        """A chunk-routed request is feasibility-checked with the
        segment path's need, not the bucket-rounded one (review:
        bucket rounding rejected servable requests)."""
        cfg, params = setup
        eng = PagedSlotEngine(cfg, params, page_size=PAGE, slots=2,
                              max_seq=MAX_SEQ, chunk=4,
                              prefill_chunk=8, buckets=(64,),
                              total_pages=3)
        # bucket-rounded need = ceil(64/16) = 4 > 3 would reject;
        # chunked need = ceil((40+8-1)/16) = 3 fits — and serves
        h = eng.submit(list(range(2, 42)), 8)
        run_all(eng, [h], limit=900)
        assert h.result(0)["tokens"] == isolated_greedy(
            cfg, params, list(range(2, 42)), 8)

    def test_long_suffix_prefix_hit_segments(self, setup):
        """A registered-prefix hit whose suffix exceeds prefill_chunk
        falls through to segmentation (bounded-stall contract), still
        token-exact."""
        cfg, params = setup
        eng = PagedSlotEngine(cfg, params, page_size=PAGE, slots=2,
                              max_seq=MAX_SEQ, chunk=4,
                              prefill_chunk=8, buckets=(32,))
        px = list(range(7, 7 + 16))
        eng.register_prefix(px)
        prompt = px + list(range(40, 64))  # suffix 24 > prefill_chunk 8
        h = eng.submit(prompt, 8)
        run_all(eng, [h])
        assert eng.stats["segment_prefills"] >= 2  # segmented, not px
        assert eng.stats["prefix_hits"] == 0
        assert h.result(0)["tokens"] == isolated_greedy(
            cfg, params, prompt, 8)
