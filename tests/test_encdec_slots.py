"""Encdec slot engine (infer/encdec_slots.py): continuous batching for
seq2seq. The exactness contract: per-stream outputs token-exact vs an
isolated greedy ``encdec_generate`` of the same source — for ragged
sources in one engine, any admission order, and slot reuse. Closes
VERDICT r3 missing #4 (encdec was the last single-flight family)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

#: JAX-compile heavy: excluded from the `-m 'not slow'` quick tier so it
#: fits its time budget; still runs in `make test` (the full suite)
pytestmark = pytest.mark.slow


from tpu_docker_api.infer.encdec_slots import EncDecSlotEngine
from tpu_docker_api.models.encdec import (
    encdec_generate,
    encdec_init,
    encdec_presets,
)

TINY = encdec_presets()["tiny"]


@pytest.fixture(scope="module")
def setup():
    params = encdec_init(TINY, jax.random.PRNGKey(5))
    return TINY, params


def isolated_greedy(cfg, params, src, max_new, eos_id=None):
    fn = jax.jit(lambda p, s: encdec_generate(
        p, s, cfg, max_new_tokens=max_new, eos_id=eos_id,
        temperature=0.0))
    out = fn(params, jnp.asarray([src], jnp.int32))
    if eos_id is None:
        return np.asarray(out)[0].tolist()
    toks = np.asarray(out["tokens"])[0]
    n = int(np.asarray(out["lengths"])[0])
    return toks[:n].tolist()


def run_all(eng, handles, limit=500):
    for _ in range(limit):
        if all(h.done() for h in handles):
            return
        eng.step()
    raise AssertionError("requests did not complete")


class TestTokenExact:
    def test_single_request_matches_isolated(self, setup):
        cfg, params = setup
        eng = EncDecSlotEngine(cfg, params, slots=4, chunk=4)
        src = [3, 1, 4, 1, 5, 9, 2, 6]
        h = eng.submit(src, max_new=12)
        run_all(eng, [h])
        assert h.result(0)["tokens"] == isolated_greedy(
            cfg, params, src, 12)

    def test_ragged_sources_concurrent_token_exact(self, setup):
        """Mixed source lengths across buckets decode together in one
        engine — the equal-length-rows restriction is gone."""
        cfg, params = setup
        eng = EncDecSlotEngine(cfg, params, slots=4, chunk=4)
        srcs = [[2, 7, 1], [9] * 40, [5, 5], [1, 2, 3, 4, 5, 6, 7],
                [8, 6, 4, 2], [11, 13]]
        max_news = [10, 6, 13, 9, 5, 16]
        handles = [eng.submit(s, m) for s, m in zip(srcs, max_news)]
        run_all(eng, handles)
        for s, m, h in zip(srcs, max_news, handles):
            assert h.result(0)["tokens"] == isolated_greedy(
                cfg, params, s, m)

    def test_slot_reuse_and_stale_cross_kv_isolation(self, setup):
        """More requests than slots: a reused slot's cross K/V and
        self-cache from the previous occupant must never leak into the
        next request's decode."""
        cfg, params = setup
        eng = EncDecSlotEngine(cfg, params, slots=2, chunk=3)
        srcs = [[i + 1, i + 2, i + 3, i + 4] for i in range(7)]
        handles = [eng.submit(s, 8) for s in srcs[:3]]
        for step in range(400):
            eng.step()
            if step == 2:
                handles += [eng.submit(s, 8) for s in srcs[3:]]
            if len(handles) == 7 and all(h.done() for h in handles):
                break
        assert eng.stats["completed"] == 7
        for s, h in zip(srcs, handles):
            assert h.result(0)["tokens"] == isolated_greedy(
                cfg, params, s, 8)

    def test_long_source_short_source_mix(self, setup):
        """A source at the largest bucket next to a tiny one — the
        kv_len mask keeps the bucketed encode exact for both."""
        cfg, params = setup
        eng = EncDecSlotEngine(cfg, params, slots=2, chunk=4)
        long_src = [((i * 7) % 250) + 1 for i in range(60)]
        hs = [eng.submit(long_src, 8), eng.submit([4, 2], 8)]
        run_all(eng, hs)
        assert hs[0].result(0)["tokens"] == isolated_greedy(
            cfg, params, long_src, 8)
        assert hs[1].result(0)["tokens"] == isolated_greedy(
            cfg, params, [4, 2], 8)

    def test_eos_and_max_new_1(self, setup):
        cfg, params = setup
        src = [3, 1, 4, 1, 5]
        ref = isolated_greedy(cfg, params, src, 12)
        eos = ref[2]
        eng = EncDecSlotEngine(cfg, params, slots=2, chunk=4)
        h = eng.submit(src, 12, eos_id=eos)
        h1 = eng.submit([7, 7], 1)
        run_all(eng, [h, h1])
        assert h.result(0)["tokens"] == ref[:ref.index(eos) + 1]
        assert h1.result(0)["tokens"] == isolated_greedy(
            cfg, params, [7, 7], 1)

    def test_sampling_paths_stay_in_vocab(self, setup):
        cfg, params = setup
        eng = EncDecSlotEngine(cfg, params, slots=2, chunk=4)
        hs = [eng.submit([1, 2, 3], 6, temperature=0.8),
              eng.submit([4, 5], 6, temperature=0.9, top_k=4,
                         top_p=0.9)]
        run_all(eng, hs)
        for h in hs:
            toks = h.result(0)["tokens"]
            assert len(toks) == 6
            assert all(0 <= t < cfg.vocab_size for t in toks)


class TestScopeAndValidation:
    def test_validation(self, setup):
        cfg, params = setup
        eng = EncDecSlotEngine(cfg, params, slots=2, chunk=4)
        with pytest.raises(ValueError, match="non-empty"):
            eng.submit([], 4)
        with pytest.raises(ValueError, match="largest source bucket"):
            eng.submit([1] * (cfg.max_src_len + 1), 4)
        with pytest.raises(ValueError, match="decoder cache capacity"):
            eng.submit([1, 2], cfg.max_tgt_len + 1)
        with pytest.raises(ValueError, match="prefix registry"):
            eng.register_prefix([1, 2, 3])
        with pytest.raises(ValueError, match="chunked prefill"):
            EncDecSlotEngine(cfg, params, prefill_chunk=8)

    def test_warmup_and_thread_loop(self, setup):
        cfg, params = setup
        eng = EncDecSlotEngine(cfg, params, slots=2, chunk=4)
        eng.warmup(buckets=(32,))
        with eng:
            h = eng.submit([2, 4, 6], 8)
            assert h.result(60)["tokens"] == isolated_greedy(
                cfg, params, [2, 4, 6], 8)

    def test_bos_id_respected(self, setup):
        """A non-default BOS changes the first decode step — engine and
        isolated reference must agree when configured alike."""
        cfg, params = setup
        eng = EncDecSlotEngine(cfg, params, slots=1, chunk=4, bos_id=7)
        h = eng.submit([1, 2, 3], 6)
        run_all(eng, [h])
        fn = jax.jit(lambda p, s: encdec_generate(
            p, s, cfg, max_new_tokens=6, bos_id=7, temperature=0.0))
        ref = np.asarray(fn(params, jnp.asarray([[1, 2, 3]],
                                                jnp.int32)))[0].tolist()
        assert h.result(0)["tokens"] == ref
