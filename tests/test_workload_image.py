"""The workload image artifact (docker/Dockerfile.workload) + the gated
build-and-run e2e: build the CPU variant, provision it through the
control plane, and run the in-container trainer — the TPU counterpart of
the reference's core story (README.md:64-92: run real images through the
API). Runs only where a docker daemon exists (same gate as
test_docker_http.TestRealDockerSmoke); everywhere else the artifact
checks keep the Dockerfile honest.
"""

import json
import os
import pathlib
import shutil
import subprocess
import urllib.request

import pytest

REPO_ROOT = pathlib.Path(__file__).resolve().parent.parent
DOCKERFILE = REPO_ROOT / "docker" / "Dockerfile.workload"
DOCKER_SOCK = "/var/run/docker.sock"
IMAGE = "tpu-workload:cpu-selftest"


class TestArtifact:
    """Hermetic checks that the in-tree Dockerfile stays wired to the
    real package entrypoints."""

    def test_dockerfile_exists_and_names_both_entrypoints(self):
        text = DOCKERFILE.read_text()
        assert "tpu_docker_api.train" in text
        assert "tpu_docker_api.serve" in text
        assert "COPY tpu_docker_api" in text

    def test_entrypoints_are_runnable_modules(self):
        # the image runs `python -m tpu_docker_api.train/.serve`; both
        # must exist as modules with a main
        import importlib

        for mod in ("tpu_docker_api.train.__main__",
                    "tpu_docker_api.serve.__main__"):
            assert importlib.util.find_spec(mod) is not None, mod


@pytest.mark.slow
@pytest.mark.skipif(not os.path.exists(DOCKER_SOCK),
                    reason="no docker daemon on this host")
@pytest.mark.skipif(shutil.which("docker") is None,
                    reason="no docker CLI to build the image")
class TestBuildAndRun:
    def test_build_provision_train(self):
        build = subprocess.run(
            ["docker", "build", "-f", str(DOCKERFILE),
             "--build-arg", "JAX_SPEC=jax", "-t", IMAGE, str(REPO_ROOT)],
            capture_output=True, text=True, timeout=1800)
        assert build.returncode == 0, build.stderr[-2000:]

        from tpu_docker_api.config import Config
        from tpu_docker_api.daemon import Program

        prog = Program(Config(
            port=0, store_backend="memory", runtime_backend="docker",
            start_port=43000, end_port=43099, health_watch_interval=0,
        ), host="127.0.0.1")
        prog.init()
        prog.start()

        def call(method, path, body=None):
            req = urllib.request.Request(
                f"http://127.0.0.1:{prog.api_server.port}{path}",
                method=method,
                data=json.dumps(body).encode() if body is not None else None,
                headers={"Content-Type": "application/json"})
            with urllib.request.urlopen(req, timeout=600) as resp:
                return json.loads(resp.read())

        try:
            out = call("POST", "/api/v1/containers", {
                "imageName": IMAGE, "containerName": "wk", "chipCount": 0,
                "cmd": ["sleep", "600"]})
            assert out["code"] == 200, out
            # the image's trainer entrypoint, inside the container the
            # control plane just provisioned (BASELINE config #1 shape,
            # with the real workload image instead of a stock python)
            out = call("POST", "/api/v1/containers/wk-0/execute", {
                "cmd": ["python", "-m", "tpu_docker_api.train",
                        "--preset", "tiny", "--steps", "2", "--batch", "2",
                        "--seq", "16", "--platform", "cpu",
                        "--log-every", "1"]})
            assert out["code"] == 200, out
            assert '"loss"' in out["data"]["stdout"], out["data"]
        finally:
            try:
                call("DELETE", "/api/v1/containers/wk-0", {
                    "force": True, "delEtcdInfoAndVersionRecord": True})
            except Exception:
                pass
            prog.stop()
