"""Runtime layer: fake engine behavior and TPU attachment rendering."""

import sys

import pytest

from tpu_docker_api import errors
from tpu_docker_api.runtime.fake import FakeRuntime
from tpu_docker_api.runtime.spec import (
    ContainerSpec,
    PortBinding,
    render_tpu_attachment,
)
from tpu_docker_api.scheduler.topology import HostTopology


@pytest.fixture
def rt(tmp_path):
    runtime = FakeRuntime(root=str(tmp_path))
    yield runtime


class TestFakeRuntime:
    def test_container_lifecycle(self, rt):
        spec = ContainerSpec(name="web-0", image="busybox")
        cid = rt.container_create(spec)
        assert len(cid) == 12
        info = rt.container_inspect("web-0")
        assert not info.running and info.data_dir
        rt.container_start("web-0")
        assert rt.container_inspect("web-0").running
        rt.container_stop("web-0")
        assert not rt.container_inspect("web-0").running
        rt.container_remove("web-0")
        assert not rt.container_exists("web-0")

    def test_create_duplicate_raises(self, rt):
        rt.container_create(ContainerSpec(name="a-0", image="x"))
        with pytest.raises(errors.ContainerExisted):
            rt.container_create(ContainerSpec(name="a-0", image="x"))

    def test_inspect_missing_raises(self, rt):
        with pytest.raises(errors.ContainerNotExist):
            rt.container_inspect("ghost-0")

    def test_remove_running_needs_force(self, rt):
        rt.container_create(ContainerSpec(name="a-0", image="x"))
        rt.container_start("a-0")
        with pytest.raises(errors.ApiError):
            rt.container_remove("a-0")
        rt.container_remove("a-0", force=True)

    def test_volume_lifecycle(self, rt):
        info = rt.volume_create("data-0", {"size": "10GB"})
        assert info.mountpoint
        assert rt.volume_inspect("data-0").driver_opts == {"size": "10GB"}
        rt.volume_remove("data-0")
        with pytest.raises(errors.VolumeNotExist):
            rt.volume_inspect("data-0")

    def test_exec_requires_running(self, rt):
        rt.container_create(ContainerSpec(name="a-0", image="x"))
        with pytest.raises(errors.ApiError):
            rt.container_exec("a-0", ["true"])

    def test_real_exec_runs_subprocess(self, tmp_path):
        rt = FakeRuntime(root=str(tmp_path), allow_exec=True)
        rt.container_create(ContainerSpec(name="a-0", image="x", env=["FOO=bar"]))
        rt.container_start("a-0")
        res = rt.container_exec(
            "a-0", [sys.executable, "-c", "import os; print(os.environ['FOO'])"]
        )
        assert res.exit_code == 0
        assert res.output.strip() == "bar"

    def test_commit(self, rt):
        rt.container_create(ContainerSpec(name="a-0", image="x"))
        img = rt.container_commit("a-0", "snapshot:v1")
        assert img.startswith("sha256:")


class TestTpuAttachment:
    def setup_method(self):
        self.topo = HostTopology.build("v5e-8")

    def test_render_devices_and_env(self):
        spec = ContainerSpec(name="t-0", image="jax")
        render_tpu_attachment(spec, [0, 1, 2, 3], self.topo)
        dev_paths = [d.host_path for d in spec.devices]
        assert dev_paths == ["/dev/accel0", "/dev/accel1", "/dev/accel2", "/dev/accel3"]
        env = dict(e.split("=", 1) for e in spec.env)
        assert env["TPU_VISIBLE_CHIPS"] == "0,1,2,3"
        # chips 0-3 of a 2x4 mesh form a 2x2 block
        assert env["TPU_CHIPS_PER_PROCESS_BOUNDS"] == "2,2,1"
        assert env["TPU_PROCESS_BOUNDS"] == "1,1,1"
        assert env["CLOUD_TPU_TASK_ID"] == "0"

    def test_cardless_renders_nothing(self):
        spec = ContainerSpec(name="t-0", image="jax")
        render_tpu_attachment(spec, [], self.topo)
        assert spec.devices == [] and spec.env == [] and spec.chip_ids == []

    def test_rerender_does_not_stack(self):
        """Patching chip count must replace, not append, the TPU artifacts."""
        spec = ContainerSpec(name="t-0", image="jax", env=["USER_VAR=1"])
        render_tpu_attachment(spec, [0, 1, 2, 3], self.topo)
        render_tpu_attachment(spec, [0, 1], self.topo)
        env = [e for e in spec.env if e.startswith("TPU_VISIBLE_CHIPS=")]
        assert env == ["TPU_VISIBLE_CHIPS=0,1"]
        assert "USER_VAR=1" in spec.env
        assert len(spec.devices) == 2

    def test_scattered_pick_falls_back_to_line_bounds(self):
        spec = ContainerSpec(name="t-0", image="jax")
        # chips 0 and 7 are opposite corners: bounding box 2x4 != count 2
        render_tpu_attachment(spec, [0, 7], self.topo, ici_contiguous=False)
        env = dict(e.split("=", 1) for e in spec.env)
        assert env["TPU_CHIPS_PER_PROCESS_BOUNDS"] == "2,1,1"

    def test_libtpu_bind_mount(self):
        spec = ContainerSpec(name="t-0", image="jax")
        render_tpu_attachment(spec, [0], self.topo, libtpu_path="/opt/libtpu.so")
        assert "/opt/libtpu.so:/lib/libtpu.so:ro" in spec.binds
        assert "TPU_LIBRARY_PATH=/lib/libtpu.so" in spec.env

    def test_spec_roundtrip(self):
        spec = ContainerSpec(
            name="t-0", image="jax",
            port_bindings=[PortBinding(8080, 40000)],
        )
        render_tpu_attachment(spec, [0, 1], self.topo)
        again = ContainerSpec.from_dict(spec.to_dict())
        assert again == spec
