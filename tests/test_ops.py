"""Op correctness: RMSNorm, RoPE, dense vs Pallas-flash attention."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from tpu_docker_api.ops.attention import _dense_attention, multihead_attention
from tpu_docker_api.ops.norms import rms_norm
from tpu_docker_api.ops.rope import apply_rope, rope_frequencies


class TestRmsNorm:
    def test_matches_reference(self):
        x = jax.random.normal(jax.random.PRNGKey(0), (4, 32))
        w = jax.random.normal(jax.random.PRNGKey(1), (32,)) + 1.0
        got = rms_norm(x, w)
        ref = x / np.sqrt(np.mean(np.asarray(x) ** 2, -1, keepdims=True) + 1e-6) * w
        np.testing.assert_allclose(got, ref, rtol=1e-5)

    def test_bf16_computes_in_f32(self):
        x = (jax.random.normal(jax.random.PRNGKey(0), (4, 256)) * 100).astype(
            jnp.bfloat16
        )
        w = jnp.ones((256,), jnp.bfloat16)
        got = rms_norm(x, w)
        assert got.dtype == jnp.bfloat16
        # rms of output ~1 even with large-magnitude bf16 inputs
        rms = float(jnp.sqrt(jnp.mean(got.astype(jnp.float32) ** 2)))
        assert 0.9 < rms < 1.1


class TestRope:
    def test_norm_preserved(self):
        cos, sin = rope_frequencies(64, 128)
        x = jax.random.normal(jax.random.PRNGKey(0), (2, 128, 4, 64))
        rotated = apply_rope(x, cos, sin)
        np.testing.assert_allclose(
            jnp.linalg.norm(rotated, axis=-1),
            jnp.linalg.norm(x, axis=-1),
            rtol=1e-4,
        )

    def test_position_zero_identity(self):
        cos, sin = rope_frequencies(32, 16)
        x = jax.random.normal(jax.random.PRNGKey(0), (1, 16, 2, 32))
        rotated = apply_rope(x, cos, sin)
        np.testing.assert_allclose(rotated[:, 0], x[:, 0], rtol=1e-5)

    def test_relative_property(self):
        """<rope(q,m), rope(k,n)> depends only on m-n."""
        cos, sin = rope_frequencies(32, 64)
        q = jax.random.normal(jax.random.PRNGKey(0), (1, 1, 1, 32))
        k = jax.random.normal(jax.random.PRNGKey(1), (1, 1, 1, 32))
        pos = jnp.array([[5]]), jnp.array([[3]])
        q5 = apply_rope(q, cos, sin, positions=pos[0])
        k3 = apply_rope(k, cos, sin, positions=pos[1])
        q12 = apply_rope(q, cos, sin, positions=jnp.array([[12]]))
        k10 = apply_rope(k, cos, sin, positions=jnp.array([[10]]))
        np.testing.assert_allclose(
            jnp.sum(q5 * k3), jnp.sum(q12 * k10), rtol=1e-4
        )


def _ref_llama3_inv_freq(head_dim, theta, factor, low_f, high_f, orig):
    """Independent scalar-loop reference of the HF llama3 rope_scaling
    formula (transformers _compute_llama3_parameters) — the golden the
    vectorized ops.rope.RopeScaling.apply is checked against."""
    import math

    out = []
    for i in range(0, head_dim, 2):
        inv = 1.0 / (theta ** (i / head_dim))
        wavelen = 2.0 * math.pi / inv
        if wavelen < orig / high_f:
            out.append(inv)  # high-frequency band: untouched
        elif wavelen > orig / low_f:
            out.append(inv / factor)  # low-frequency band: stretched
        else:
            smooth = (orig / wavelen - low_f) / (high_f - low_f)
            out.append((1 - smooth) * inv / factor + smooth * inv)
    return np.array(out, np.float32)


class TestRopeScaling:
    """Llama-3.x band scaling (VERDICT r4 missing #2 / next #2)."""

    def test_matches_reference_formula(self):
        from tpu_docker_api.ops.rope import RopeScaling

        hd, theta = 128, 500000.0
        sc = RopeScaling(factor=8.0, low_freq_factor=1.0,
                         high_freq_factor=4.0,
                         original_max_position_embeddings=8192)
        ref = _ref_llama3_inv_freq(hd, theta, 8.0, 1.0, 4.0, 8192)
        t = np.arange(64, dtype=np.float32)
        cos, sin = rope_frequencies(hd, 64, theta, sc)
        np.testing.assert_allclose(
            np.asarray(cos), np.cos(np.outer(t, ref)), atol=1e-5)
        np.testing.assert_allclose(
            np.asarray(sin), np.sin(np.outer(t, ref)), atol=1e-5)

    def test_band_structure(self):
        """High-freq bands identical to unscaled, lowest band scaled by
        exactly 1/factor — the two regimes that make llama3 scaling
        different from plain linear position interpolation."""
        from tpu_docker_api.ops.rope import RopeScaling

        hd, theta, factor = 128, 500000.0, 8.0
        sc = RopeScaling(factor=factor,
                         original_max_position_embeddings=8192)
        base = 1.0 / (theta ** (np.arange(0, hd, 2) / hd))
        scaled = np.asarray(sc.apply(jnp.asarray(base, jnp.float32)))
        wavelen = 2 * np.pi / base
        hi = wavelen < 8192 / sc.high_freq_factor
        lo = wavelen > 8192 / sc.low_freq_factor
        assert hi.any() and lo.any()
        np.testing.assert_allclose(scaled[hi], base[hi], rtol=1e-6)
        np.testing.assert_allclose(scaled[lo], base[lo] / factor,
                                   rtol=1e-6)
        # in-between bands interpolate strictly inside the two regimes
        mid = ~hi & ~lo
        assert np.all(scaled[mid] < base[mid])
        assert np.all(scaled[mid] > base[mid] / factor)


class TestAttention:
    def _qkv(self, heads=4, kv_heads=4, seq=128, hd=128, dtype=jnp.float32):
        ks = jax.random.split(jax.random.PRNGKey(0), 3)
        q = jax.random.normal(ks[0], (2, seq, heads, hd), dtype)
        k = jax.random.normal(ks[1], (2, seq, kv_heads, hd), dtype)
        v = jax.random.normal(ks[2], (2, seq, kv_heads, hd), dtype)
        return q, k, v

    def test_dense_causality(self):
        """Changing a future token must not affect earlier outputs."""
        q, k, v = self._qkv(seq=16, hd=32)
        out1 = _dense_attention(q, k, v, causal=True)
        k2 = k.at[:, -1].add(100.0)
        v2 = v.at[:, -1].add(100.0)
        out2 = _dense_attention(q, k2, v2, causal=True)
        np.testing.assert_allclose(out1[:, :-1], out2[:, :-1], rtol=1e-5)
        assert not np.allclose(out1[:, -1], out2[:, -1])

    def test_dense_softmax_rows_sum(self):
        """First position attends only to itself: out[0] == v[0]."""
        q, k, v = self._qkv(seq=8, hd=32)
        out = _dense_attention(q, k, v, causal=True)
        np.testing.assert_allclose(out[:, 0], v[:, 0], rtol=1e-5)

    @pytest.mark.parametrize("kv_heads", [4, 1, 2])
    def test_flash_matches_dense(self, kv_heads):
        q, k, v = self._qkv(heads=4, kv_heads=kv_heads, seq=256, hd=128)
        ref = _dense_attention(q, k, v, causal=True)
        got = multihead_attention(q, k, v, causal=True, impl="flash_interpret")
        np.testing.assert_allclose(got, ref, rtol=2e-3, atol=2e-3)

    def test_flash_kvgrid_multiblock_matches_dense(self):
        """Multiple kv grid steps per q block (seq > block_k) must agree
        with dense — exercises the scratch-accumulator carry across kv
        steps and the diagonal/full tile split."""
        from tpu_docker_api.ops.flash_pallas import flash_attention

        q, k, v = self._qkv(heads=2, kv_heads=1, seq=256, hd=128)
        ref = _dense_attention(q, k, v, causal=True)
        got = flash_attention(
            q.transpose(0, 2, 1, 3), k.transpose(0, 2, 1, 3),
            v.transpose(0, 2, 1, 3), causal=True, block_q=128, block_k=128,
            interpret=True,
        ).transpose(0, 2, 1, 3)
        np.testing.assert_allclose(got, ref, rtol=2e-3, atol=2e-3)

    @pytest.mark.parametrize("kv_heads", [4, 2])
    def test_flash_backward_matches_dense(self, kv_heads):
        """The custom VJP (FlashAttention-2 recomputation backward) must
        produce the same dq/dk/dv as differentiating dense attention —
        training on TPU runs through this path."""
        q, k, v = self._qkv(heads=4, kv_heads=kv_heads, seq=256, hd=64)

        def loss_flash(q, k, v):
            o = multihead_attention(q, k, v, causal=True,
                                    impl="flash_interpret")
            return jnp.sum(o.astype(jnp.float32) ** 2)

        def loss_dense(q, k, v):
            o = _dense_attention(q, k, v, causal=True)
            return jnp.sum(o.astype(jnp.float32) ** 2)

        got = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
        ref = jax.grad(loss_dense, argnums=(0, 1, 2))(q, k, v)
        for g, r in zip(got, ref):
            np.testing.assert_allclose(np.asarray(g), np.asarray(r),
                                       rtol=2e-3, atol=2e-3)

    def test_flash_head_dim_64(self):
        q, k, v = self._qkv(heads=4, kv_heads=2, seq=128, hd=64)
        ref = _dense_attention(q, k, v, causal=True)
        got = multihead_attention(q, k, v, causal=True, impl="flash_interpret")
        np.testing.assert_allclose(got, ref, rtol=2e-3, atol=2e-3)

    def test_flash_non_causal(self):
        q, k, v = self._qkv(seq=128, hd=128)
        ref = _dense_attention(q, k, v, causal=False)
        got = multihead_attention(q, k, v, causal=False, impl="flash_interpret")
        np.testing.assert_allclose(got, ref, rtol=2e-3, atol=2e-3)

    def test_auto_on_cpu_is_dense(self):
        q, k, v = self._qkv(seq=8, hd=32)
        out = multihead_attention(q, k, v, impl="auto")  # must not crash
        assert out.shape == q.shape
