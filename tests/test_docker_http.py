"""runtime/docker_http.py against an in-process unix-socket Engine mock.

VERDICT r1 item 4: the adapter had zero coverage — a typo in any path
string would ship silently. These tests stand up a real AF_UNIX HTTP
server speaking the Docker Engine API's golden shapes (payloads modeled
on the reference's captured transcripts,
api/gpu-docker-api-sample-interface.md:51-68, and the Engine API docs)
and assert BOTH directions of every adapter method: the exact method,
path, query and body the adapter sends, and correct decoding of the
responses — including the 8-byte stdcopy stream demux, 304
already-in-state handling, and 404 → typed-error mapping.

A separate integration tier runs the same smoke flow against the real
dockerd when /var/run/docker.sock exists.
"""

import json
import os
import socket
import socketserver
import struct
import threading
import urllib.parse
from http.server import BaseHTTPRequestHandler

import pytest

from tpu_docker_api import errors
from tpu_docker_api.runtime.docker_http import (
    DockerRuntime,
    _demux_docker_stream,
)
from tpu_docker_api.runtime.spec import ContainerSpec, DeviceMount, PortBinding


def mux_frames(*frames: tuple[int, bytes]) -> bytes:
    """Encode (stream_id, payload) pairs in docker's stdcopy framing."""
    return b"".join(
        struct.pack(">BxxxL", sid, len(payload)) + payload
        for sid, payload in frames
    )


class _Engine:
    """Minimal in-memory dockerd: state + request journal."""

    def __init__(self):
        self.containers: dict[str, dict] = {}
        self.volumes: dict[str, dict] = {}
        self.execs: dict[str, dict] = {}
        self.requests: list[tuple[str, str, dict, dict | None]] = []
        self.known_images = {"jax:latest"}

    def last(self):
        return self.requests[-1]


class _Handler(BaseHTTPRequestHandler):
    protocol_version = "HTTP/1.1"

    # AF_UNIX: client_address is b'' — stub the peer-name helpers
    def address_string(self):
        return "unix"

    def log_message(self, *args):
        pass

    @property
    def engine(self) -> _Engine:
        return self.server.engine

    def _reply(self, status: int, payload=None, raw: bytes | None = None):
        body = raw if raw is not None else (
            json.dumps(payload).encode() if payload is not None else b"")
        self.send_response(status)
        self.send_header("Content-Type",
                         "application/octet-stream" if raw is not None
                         else "application/json")
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        if body:
            self.wfile.write(body)

    def _handle(self, method: str):
        parsed = urllib.parse.urlsplit(self.path)
        # the adapter must version-prefix every request
        assert parsed.path.startswith("/v1.41/"), parsed.path
        path = parsed.path[len("/v1.41"):]
        params = dict(urllib.parse.parse_qsl(parsed.query))
        length = int(self.headers.get("Content-Length") or 0)
        body = json.loads(self.rfile.read(length)) if length else None
        self.engine.requests.append((method, path, params, body))
        route = (method, path)

        if route == ("GET", "/_ping"):
            return self._reply(200, raw=b"OK")

        if route == ("POST", "/containers/create"):
            name = params["name"]
            if body["Image"] not in self.engine.known_images:
                return self._reply(404, {"message": f"No such image: {body['Image']}"})
            cid = f"id-{name}"
            self.engine.containers[name] = {
                "Id": cid, "Name": f"/{name}", "Config": {
                    "Image": body["Image"], "Cmd": body.get("Cmd"),
                    "Env": body.get("Env"), "Labels": body.get("Labels"),
                    "OpenStdin": body.get("OpenStdin", False),
                    "Tty": body.get("Tty", False),
                },
                "HostConfig": body.get("HostConfig", {}),
                "State": {"Running": False, "Pid": 0, "ExitCode": 0},
                "GraphDriver": {"Name": "overlay2", "Data": {
                    "MergedDir": f"/var/lib/docker/overlay2/{cid}/merged"}},
            }
            return self._reply(201, {"Id": cid, "Warnings": []})

        name_op = path.split("/")
        if method == "POST" and len(name_op) == 4 and name_op[1] == "containers":
            _, _, name, op = name_op
            if op in ("start", "stop", "restart"):
                c = self.engine.containers.get(name)
                if c is None:
                    return self._reply(404, {"message": "no such container"})
                want = op != "stop"
                if op != "restart" and c["State"]["Running"] == want:
                    return self._reply(304)
                c["State"]["Running"] = want
                c["State"]["Pid"] = 4242 if want else 0
                return self._reply(204)
            if op == "exec":
                if name not in self.engine.containers:
                    return self._reply(404, {"message": "no such container"})
                eid = f"exec-{len(self.engine.execs)}"
                self.engine.execs[eid] = {"ExitCode": 3, "Cmd": body["Cmd"]}
                return self._reply(201, {"Id": eid})

        if method == "POST" and path.startswith("/exec/") and path.endswith("/start"):
            eid = path.split("/")[2]
            assert eid in self.engine.execs
            return self._reply(200, raw=mux_frames(
                (1, b"out-line-1\n"), (2, b"err-line\n"), (1, b"out-line-2\n")))

        if method == "GET" and path.startswith("/exec/"):
            eid = path.split("/")[2]
            return self._reply(200, self.engine.execs[eid])

        if route == ("POST", "/commit"):
            cname = params["container"]
            if cname not in self.engine.containers:
                return self._reply(404, {"message": "no such container"})
            return self._reply(
                201, {"Id": f"sha256:{cname}-{params['repo']}-{params['tag']}"})

        if method == "GET" and path == "/containers/json":
            return self._reply(200, [
                {"Id": c["Id"], "Names": [c["Name"]]}
                for c in self.engine.containers.values()])

        if method == "GET" and len(name_op) == 4 and name_op[3] == "json":
            c = self.engine.containers.get(name_op[2])
            if c is None:
                return self._reply(404, {"message": "no such container"})
            return self._reply(200, c)

        if method == "DELETE" and len(name_op) == 3 and name_op[1] == "containers":
            if self.engine.containers.pop(name_op[2], None) is None:
                return self._reply(404, {"message": "no such container"})
            return self._reply(204)

        if route == ("POST", "/volumes/create"):
            self.engine.volumes[body["Name"]] = {
                "Name": body["Name"], "Driver": body["Driver"],
                "Options": body.get("DriverOpts") or {},
                "Mountpoint": f"/var/lib/docker/volumes/{body['Name']}/_data",
            }
            return self._reply(201, self.engine.volumes[body["Name"]])

        if method == "GET" and len(name_op) == 3 and name_op[1] == "volumes":
            v = self.engine.volumes.get(name_op[2])
            if v is None:
                return self._reply(404, {"message": "no such volume"})
            return self._reply(200, v)

        if method == "DELETE" and len(name_op) == 3 and name_op[1] == "volumes":
            if self.engine.volumes.pop(name_op[2], None) is None:
                return self._reply(404, {"message": "no such volume"})
            return self._reply(204)

        return self._reply(500, {"message": f"unhandled {method} {path}"})

    def do_GET(self):
        self._handle("GET")

    def do_POST(self):
        self._handle("POST")

    def do_DELETE(self):
        self._handle("DELETE")


class _UnixHTTPServer(socketserver.ThreadingUnixStreamServer):
    daemon_threads = True

    def __init__(self, path: str, engine: _Engine):
        super().__init__(path, _Handler)
        self.engine = engine


@pytest.fixture()
def engine(tmp_path):
    sock_path = str(tmp_path / "docker.sock")
    eng = _Engine()
    server = _UnixHTTPServer(sock_path, eng)
    t = threading.Thread(target=server.serve_forever, daemon=True)
    t.start()
    eng.socket_path = sock_path
    try:
        yield eng
    finally:
        server.shutdown()
        server.server_close()


@pytest.fixture()
def rt(engine):
    return DockerRuntime(f"unix://{engine.socket_path}")


def make_spec(name="t0") -> ContainerSpec:
    return ContainerSpec(
        name=name, image="jax:latest", cmd=["python", "-c", "1"],
        env=["A=1"], binds=["v0:/data"],
        port_bindings=[PortBinding(8080, 40001)],
        devices=[DeviceMount("/dev/accel0", "/dev/accel0")],
        chip_ids=[0, 1], ici_contiguous=True,
    )


class TestTransport:
    def test_init_pings(self, engine):
        DockerRuntime(f"unix://{engine.socket_path}")
        assert engine.last() == ("GET", "/_ping", {}, None)

    def test_tcp_host_rejected(self):
        with pytest.raises(ValueError):
            DockerRuntime("tcp://10.0.0.1:2375")


class TestContainerFlows:
    def test_create_sends_golden_request(self, rt, engine):
        cid = rt.container_create(make_spec())
        assert cid == "id-t0"
        method, path, params, body = engine.last()
        assert (method, path) == ("POST", "/containers/create")
        assert params == {"name": "t0"}
        assert body["Image"] == "jax:latest"
        assert body["ExposedPorts"] == {"8080/tcp": {}}
        assert body["HostConfig"]["PortBindings"] == {
            "8080/tcp": [{"HostPort": "40001"}]}
        assert body["HostConfig"]["Binds"] == ["v0:/data"]
        assert body["HostConfig"]["Devices"] == [{
            "PathOnHost": "/dev/accel0", "PathInContainer": "/dev/accel0",
            "CgroupPermissions": "rwm"}]
        assert body["Labels"] == {"tpu-docker-api.chips": "0,1",
                                  "tpu-docker-api.ici": "1"}

    def test_create_unknown_image_maps_404(self, rt):
        spec = make_spec()
        spec.image = "missing:latest"
        with pytest.raises(errors.ApiError, match="missing:latest not found"):
            rt.container_create(spec)

    def test_start_stop_restart_and_304(self, rt, engine):
        rt.container_create(make_spec())
        rt.container_start("t0")
        assert engine.last()[:2] == ("POST", "/containers/t0/start")
        rt.container_start("t0")          # already running -> 304, no raise
        rt.container_stop("t0", timeout_s=7)
        assert engine.last() == ("POST", "/containers/t0/stop", {"t": "7"}, None)
        rt.container_stop("t0")           # already stopped -> 304, no raise
        rt.container_restart("t0")
        assert rt.container_inspect("t0").running

    def test_ops_on_missing_container_raise_typed(self, rt):
        for op in (rt.container_start, rt.container_stop, rt.container_restart,
                   rt.container_inspect):
            with pytest.raises(errors.ContainerNotExist):
                op("ghost")
        with pytest.raises(errors.ContainerNotExist):
            rt.container_remove("ghost")

    def test_inspect_round_trips_spec(self, rt):
        spec = make_spec()
        rt.container_create(spec)
        rt.container_start("t0")
        info = rt.container_inspect("t0")
        assert info.id == "id-t0" and info.running and info.pid == 4242
        assert info.data_dir == "/var/lib/docker/overlay2/id-t0/merged"
        got = info.spec
        assert (got.name, got.image, got.cmd, got.env) == (
            "t0", spec.image, spec.cmd, spec.env)
        assert got.port_bindings == spec.port_bindings
        assert got.devices == spec.devices
        assert got.chip_ids == [0, 1] and got.ici_contiguous

    def test_exists_and_list(self, rt):
        assert not rt.container_exists("t0")
        rt.container_create(make_spec())
        rt.container_create(make_spec("t1"))
        assert rt.container_exists("t0")
        assert rt.container_list() == ["t0", "t1"]

    def test_remove(self, rt, engine):
        rt.container_create(make_spec())
        rt.container_remove("t0", force=True)
        assert engine.last() == ("DELETE", "/containers/t0",
                                 {"force": "true"}, None)
        assert not rt.container_exists("t0")

    def test_exec_demux_and_exit_code(self, rt, engine):
        rt.container_create(make_spec())
        res = rt.container_exec("t0", ["ls", "-l"], workdir="/srv")
        # stdout and stderr frames interleaved, in order
        assert res.output == "out-line-1\nerr-line\nout-line-2\n"
        assert res.exit_code == 3
        create = next(r for r in engine.requests
                      if r[1] == "/containers/t0/exec")
        assert create[3] == {"AttachStdout": True, "AttachStderr": True,
                             "Cmd": ["ls", "-l"], "WorkingDir": "/srv"}
        start = next(r for r in engine.requests if r[1].endswith("/start")
                     and r[1].startswith("/exec/"))
        assert start[3] == {"Detach": False, "Tty": False}

    def test_exec_missing_container(self, rt):
        with pytest.raises(errors.ContainerNotExist):
            rt.container_exec("ghost", ["true"])

    def test_commit(self, rt, engine):
        rt.container_create(make_spec())
        image_id = rt.container_commit("t0", "snap:v2")
        assert image_id == "sha256:t0-snap-v2"
        assert engine.last() == ("POST", "/commit",
                                 {"container": "t0", "repo": "snap",
                                  "tag": "v2"}, None)
        # default tag
        assert rt.container_commit("t0", "snap") == "sha256:t0-snap-latest"


class TestVolumeFlows:
    def test_create_inspect_remove(self, rt, engine):
        vol = rt.volume_create("v0", {"size": "10GB"})
        assert engine.last() == ("POST", "/volumes/create", {}, {
            "Name": "v0", "Driver": "local", "DriverOpts": {"size": "10GB"}})
        assert vol.mountpoint == "/var/lib/docker/volumes/v0/_data"
        assert vol.driver_opts == {"size": "10GB"}
        assert rt.volume_exists("v0")
        got = rt.volume_inspect("v0")
        assert got == vol
        rt.volume_remove("v0", force=True)
        assert engine.last() == ("DELETE", "/volumes/v0",
                                 {"force": "true"}, None)
        assert not rt.volume_exists("v0")

    def test_missing_volume_typed_errors(self, rt):
        with pytest.raises(errors.VolumeNotExist):
            rt.volume_inspect("ghost")
        with pytest.raises(errors.VolumeNotExist):
            rt.volume_remove("ghost")


class TestDemux:
    def test_frames(self):
        data = mux_frames((1, b"abc"), (2, b"DEF"))
        assert _demux_docker_stream(data) == "abcDEF"

    def test_truncated_trailing_header_ignored(self):
        data = mux_frames((1, b"abc")) + b"\x01\x00\x00"  # partial header
        assert _demux_docker_stream(data) == "abc"

    def test_tty_raw_passthrough(self):
        assert _demux_docker_stream(b"raw tty bytes") == "raw tty bytes"

    def test_midstream_corruption_keeps_parsed_frames(self):
        # An invalid header AFTER valid frames is corruption, not tty mode:
        # the demuxed frames must survive (not be re-emitted with their
        # binary headers), and the unparseable tail is appended raw.
        data = mux_frames((1, b"abc"), (2, b"DEF")) + b"\x07garbage!"
        assert _demux_docker_stream(data) == "abcDEF\x07garbage!"

    def test_empty(self):
        assert _demux_docker_stream(b"") == ""


class TestStopTimeout:
    """The HTTP timeout must scale with the engine-side stop grace: dockerd
    holds the POST open for up to ``t`` seconds before SIGKILL, so a flat
    60 s transport timeout made any stop with t > 60 raise on a healthy
    daemon."""

    def _capture(self, rt):
        seen = {}

        def fake_request(method, path, params=None, body=None,
                         timeout=60.0, retry=None):
            seen.update(method=method, path=path, params=params,
                        timeout=timeout)
            return 204, b""

        rt._request = fake_request
        return seen

    def test_long_grace_extends_http_timeout(self, rt):
        seen = self._capture(rt)
        rt.container_stop("t0", timeout_s=120)
        assert seen["params"] == {"t": 120}
        assert seen["timeout"] >= 150  # grace + margin

    def test_default_grace_keeps_default_timeout(self, rt):
        seen = self._capture(rt)
        rt.container_stop("t0")  # timeout_s=10
        assert seen["timeout"] == 60.0


class TestTransientRetry:
    """Connection-level failures (dockerd restarting) are retried with
    backoff on idempotent GETs only; non-idempotent POSTs stay one-shot —
    a blindly repeated create/stop could double-apply."""

    def _flaky_connect(self, rt, exc, fail_times):
        real_open = type(rt)._open_connection
        counter = {"n": 0}

        def flaky(timeout):
            counter["n"] += 1
            if counter["n"] <= fail_times:
                raise exc
            return real_open(rt, timeout)

        # drain the keep-alive pool so the next request must CONNECT —
        # these tests are about the reconnect policy, not reuse
        rt._pool.clear()
        rt._open_connection = flaky
        rt.RETRY_BACKOFF_S = 0.001
        return counter

    def test_get_retries_connection_refused(self, rt, engine):
        rt.container_create(make_spec())
        counter = self._flaky_connect(rt, ConnectionRefusedError(), 2)
        info = rt.container_inspect("t0")  # succeeds on 3rd attempt
        assert info.name == "t0"
        assert counter["n"] == 3

    def test_get_exhausted_retries_raise(self, rt):
        self._flaky_connect(rt, ConnectionResetError(), 99)
        with pytest.raises(ConnectionResetError):
            rt.container_inspect("t0")

    def test_post_is_one_shot(self, rt, engine):
        rt.container_create(make_spec())
        counter = self._flaky_connect(rt, ConnectionResetError(), 1)
        with pytest.raises(ConnectionResetError):
            rt.container_start("t0")
        assert counter["n"] == 1


class TestInspectStatus:
    def test_status_round_trips(self, rt, engine):
        rt.container_create(make_spec())
        engine.containers["t0"]["State"]["Status"] = "created"
        assert rt.container_inspect("t0").status == "created"
        rt.container_start("t0")
        engine.containers["t0"]["State"]["Status"] = "running"
        assert rt.container_inspect("t0").status == "running"


class TestConnectionPool:
    """The keep-alive pool behind every request (runtime fan-out made the
    per-request connect() the transport bottleneck): reuse across
    requests, stale-socket detection before reuse, bounded idle
    retention — while the retry contract stays GET-only (TestTransientRetry
    above proves POSTs are still one-shot)."""

    def test_requests_reuse_one_keep_alive_connection(self, rt):
        # the constructor's ping opened (and pooled) the first connection
        assert rt.pool_view()["created"] == 1
        rt.container_list()
        rt.container_list()
        view = rt.pool_view()
        assert view["created"] == 1, "a request dialed instead of reusing"
        assert view["reused"] >= 2
        assert view["idle"] == 1 and view["inUse"] == 0

    def test_posts_ride_the_pool_too(self, rt, engine):
        rt.container_create(make_spec())
        rt.container_start("t0")
        assert rt.pool_view()["created"] == 1

    def test_stale_socket_detected_and_replaced(self, rt):
        rt.container_list()
        assert rt.pool_view()["idle"] == 1
        # dockerd restart while the connection idles: the server half
        # goes away — model it by shutting the socket down, which makes
        # it readable (EOF), the pre-reuse staleness signal
        idle_conn = rt._pool._idle[0]
        idle_conn.sock.shutdown(socket.SHUT_RDWR)
        # the next GET must detect the dead socket BEFORE reusing it and
        # dial fresh — no error surfaces to the caller
        assert rt.container_list() == []
        view = rt.pool_view()
        assert view["staleDropped"] == 1
        assert view["created"] == 2

    def test_closed_fd_counts_as_stale(self, rt):
        rt.container_list()
        rt._pool._idle[0].sock.close()
        assert rt.container_list() == []
        assert rt.pool_view()["staleDropped"] == 1

    def test_idle_retention_is_bounded(self, rt):
        conns = [rt._pool.acquire(rt._open_connection, 5.0)[0]
                 for _ in range(7)]
        for c in conns:
            rt._pool.release(c, reusable=True)
        view = rt.pool_view()
        assert view["idle"] <= view["size"] == 4
        assert view["inUse"] == 0

    def test_close_drains_the_pool(self, rt):
        rt.container_list()
        rt.close()
        assert rt.pool_view()["idle"] == 0

    def test_stale_socket_storm_cannot_exhaust_pool(self, rt):
        """Regression: a dockerd restart kills EVERY idle connection at
        once. A concurrent request burst right after must (a) drop each
        corpse exactly once, (b) surface zero errors, (c) dial a bounded
        number of replacements — never a connection per request — and
        (d) leave no leaked in-use slots behind."""
        pool = rt._pool
        conns = [pool.acquire(rt._open_connection, 5.0)[0]
                 for _ in range(pool.size)]
        for c in conns:
            c.connect()  # sock is dialed lazily; a parked conn has one
            pool.release(c, reusable=True)
        assert rt.pool_view()["idle"] == pool.size
        for c in list(pool._idle):
            c.sock.shutdown(socket.SHUT_RDWR)
        created_before = rt.pool_view()["created"]
        errs: list[Exception] = []

        def worker():
            try:
                for _ in range(5):
                    rt.container_list()
            except Exception as e:  # noqa: BLE001 — collected for assert
                errs.append(e)

        workers = [threading.Thread(target=worker) for _ in range(8)]
        for t in workers:
            t.start()
        for t in workers:
            t.join(timeout=30)
        assert errs == []
        view = rt.pool_view()
        assert view["staleDropped"] == pool.size
        assert view["inUse"] == 0
        assert view["idle"] <= view["size"]
        # 8 workers can race past the idle list simultaneously, but the
        # storm's dial count is bounded by concurrency, not by the 40
        # requests served
        assert view["created"] - created_before <= 8


DOCKER_SOCK = "/var/run/docker.sock"


@pytest.mark.slow
@pytest.mark.skipif(not os.path.exists(DOCKER_SOCK),
                    reason="no docker daemon on this host")
class TestRealDockerSmoke:
    """The cardless smoke flow (BASELINE.json config #1) on real dockerd."""

    def test_cardless_lifecycle(self):
        rt = DockerRuntime()
        name = "tpu-docker-api-selftest"
        if rt.container_exists(name):
            rt.container_remove(name, force=True)
        spec = ContainerSpec(name=name, image="busybox:latest",
                             cmd=["sleep", "30"])
        rt.container_create(spec)
        try:
            rt.container_start(name)
            info = rt.container_inspect(name)
            assert info.running
            res = rt.container_exec(name, ["echo", "hi"])
            assert res.exit_code == 0 and res.output.strip() == "hi"
            rt.container_stop(name)
        finally:
            rt.container_remove(name, force=True)
