"""State layer: KV backends, per-version store, version maps, work queue."""

import json
import sqlite3
import threading
import time

import pytest

from tpu_docker_api import errors
from tpu_docker_api.schemas.state import ContainerState, VolumeState
from tpu_docker_api.service.crashpoints import SimulatedCrash, armed
from tpu_docker_api.state import keys
from tpu_docker_api.state.keys import Resource, split_versioned_name
from tpu_docker_api.state.kv import CountingKV, MemoryKV, SqliteKV
from tpu_docker_api.state.store import StateStore
from tpu_docker_api.state.version import VersionMap
from tpu_docker_api.state.workqueue import (
    CopyTask,
    DelKeyTask,
    FnTask,
    PutKVTask,
    WorkQueue,
)


@pytest.fixture(params=["memory", "sqlite"])
def kv(request, tmp_path):
    if request.param == "memory":
        yield MemoryKV()
    else:
        store = SqliteKV(str(tmp_path / "state.db"))
        yield store
        store.close()


class TestKV:
    def test_put_get_delete(self, kv):
        kv.put("/a", "1")
        assert kv.get("/a") == "1"
        kv.put("/a", "2")
        assert kv.get("/a") == "2"
        kv.delete("/a")
        with pytest.raises(errors.NotExistInStore):
            kv.get("/a")
        kv.delete("/a")  # idempotent, etcd semantics

    def test_range_prefix_sorted(self, kv):
        for k in ["/x/b", "/x/a", "/y/a", "/x/c"]:
            kv.put(k, k)
        assert list(kv.range_prefix("/x/")) == ["/x/a", "/x/b", "/x/c"]
        kv.delete_prefix("/x/")
        assert kv.range_prefix("/x/") == {}
        assert kv.get("/y/a") == "/y/a"

    def test_sqlite_survives_reopen(self, tmp_path):
        path = str(tmp_path / "s.db")
        s1 = SqliteKV(path)
        s1.put("/k", "v")
        s1.close()
        s2 = SqliteKV(path)
        assert s2.get("/k") == "v"
        s2.close()

    def test_sqlite_busy_timeout_configured(self, tmp_path):
        """A foreign lock holder makes ops wait (bounded), not raise
        'database is locked' instantly — the PRAGMA must be live on the
        connection."""
        s = SqliteKV(str(tmp_path / "b.db"), busy_timeout_s=2.5)
        assert s._conn.execute("PRAGMA busy_timeout").fetchone()[0] == 2500
        s.close()
        d = SqliteKV(str(tmp_path / "d.db"))  # default is nonzero too
        assert d._conn.execute("PRAGMA busy_timeout").fetchone()[0] > 0
        d.close()


class TestKVApply:
    """``KV.apply`` — the atomic multi-key batch every version transition
    commits through (the etcd-txn / apiserver write pattern). EtcdKV's
    native-txn implementation is covered in test_etcd_kv.py."""

    def test_mixed_batch_applies(self, kv):
        kv.put("/f/old", "x")
        kv.put("/purge/a", "1")
        kv.put("/purge/b", "2")
        kv.apply([
            ("put", "/f/v/0", "spec"),
            ("put", "/f/latest", "0"),
            ("delete", "/f/old"),
            ("delete_prefix", "/purge/"),
        ])
        assert kv.get("/f/v/0") == "spec"
        assert kv.get("/f/latest") == "0"
        assert kv.get_or("/f/old") is None
        assert kv.range_prefix("/purge/") == {}

    def test_empty_batch_is_a_noop(self, kv):
        kv.apply([])

    def test_malformed_op_rejected_before_any_write(self, kv):
        for bad in [("frob", "/k"), ("put", "/k"), ("delete", "/k", "v"),
                    ("put", "/k", "v", "extra")]:
            with pytest.raises(ValueError):
                kv.apply([("put", "/ok", "1"), bad])
        # validation runs over the WHOLE batch before the first write
        assert kv.get_or("/ok") is None

    def test_sqlite_mid_batch_failure_rolls_back_everything(self, tmp_path):
        s = SqliteKV(str(tmp_path / "atomic.db"))
        s.put("/keep", "safe")
        with pytest.raises((sqlite3.InterfaceError, sqlite3.ProgrammingError)):
            # second op is unbindable: the first must not survive it
            s.apply([("put", "/a", "1"), ("put", "/b", object())])
        assert s.get_or("/a") is None
        assert s.get("/keep") == "safe"
        s.put("/after", "ok")  # the connection stays usable post-rollback
        assert s.get("/after") == "ok"
        s.close()

    def test_crash_point_brackets_the_commit(self, kv):
        """Both halves of the txn-boundary contract, at the KV layer: a
        crash at txn.before_apply leaves NOTHING applied, a crash at
        txn.after_apply leaves EVERYTHING applied."""
        with armed("txn.before_apply"):
            with pytest.raises(SimulatedCrash):
                kv.apply([("put", "/t", "1")])
        assert kv.get_or("/t") is None
        with armed("txn.after_apply"):
            with pytest.raises(SimulatedCrash):
                kv.apply([("put", "/t", "1"), ("put", "/u", "2")])
        assert kv.get("/t") == "1"
        assert kv.get("/u") == "2"

    def test_crash_point_skip_targets_kth_apply(self, kv):
        """skip=k lets chaos cases walk the crash across a flow's k-th
        commit — the first k applies must land untouched."""
        with armed("txn.before_apply", skip=1):
            kv.apply([("put", "/first", "1")])
            with pytest.raises(SimulatedCrash):
                kv.apply([("put", "/second", "2")])
        assert kv.get("/first") == "1"
        assert kv.get_or("/second") is None

    def test_sqlite_foreign_lock_is_bounded_and_atomic(self, tmp_path):
        """A foreign writer holding the database (backup tooling, a second
        daemon by mistake) makes the batched mutations fail after the
        bounded busy wait — as the TYPED ``StoreUnavailable`` (so
        StoreHealth classifies it like any other store failure, never a
        raw ``sqlite3.OperationalError`` leaking backend vocabulary) and
        with the whole batch rolled back, never half of it."""
        path = str(tmp_path / "locked.db")
        s = SqliteKV(path, busy_timeout_s=0.05)
        s.put("/fam/a", "1")
        s.put("/fam/b", "2")
        foreign = sqlite3.connect(path)
        foreign.execute("BEGIN IMMEDIATE")  # foreign write lock
        try:
            t0 = time.monotonic()
            with pytest.raises(errors.StoreUnavailable):
                s.delete_prefix("/fam/")
            with pytest.raises(errors.StoreUnavailable):
                s.apply([("put", "/fam/c", "3"), ("delete", "/fam/a")])
            assert time.monotonic() - t0 < 5.0  # bounded wait, not a hang
        finally:
            foreign.rollback()
            foreign.close()
        # WAL readers see the untouched pre-batch state
        assert s.range_prefix("/fam/") == {"/fam/a": "1", "/fam/b": "2"}
        s.delete_prefix("/fam/")  # and the lock's release unblocks writes
        assert s.range_prefix("/fam/") == {}
        s.close()


class TestCountingKV:
    """The churn benchmark's round-trip instrumentation (bench.py gates on
    its deltas, so its counting semantics are load-bearing)."""

    def test_counts_each_round_trip_once(self):
        kv = CountingKV(MemoryKV())
        kv.put("/a", "1")
        kv.get("/a")
        kv.apply([("put", "/b", "2"), ("delete", "/a")])
        assert kv.snapshot() == {"put": 1, "get": 1, "apply": 1}
        assert kv.inner.get("/b") == "2"  # delegated for real
        assert CountingKV.delta({"put": 1}, kv.snapshot()) == {
            "get": 1, "apply": 1}

    def test_apply_fires_crash_points_once_per_batch(self):
        """The wrapper delegates to the inner BACKEND's ``_apply`` — if it
        went through the inner ``apply`` template the txn crash points
        would fire twice per batch and skip-indexed chaos cases would land
        on the wrong commit."""
        kv = CountingKV(MemoryKV())
        with armed("txn.before_apply", skip=1):
            kv.apply([("put", "/x", "1")])  # a double fire would crash here
        assert kv.inner.get("/x") == "1"
        assert kv.snapshot()["apply"] == 1

    def test_state_store_version_transition_is_one_round_trip(self):
        """The tentpole invariant at its smallest: a put_container (version
        record + latest pointer) is ONE apply, not two puts."""
        kv = CountingKV(MemoryKV())
        StateStore(kv).put_container(
            ContainerState("web-0", 0, {"name": "web-0"}))
        assert kv.snapshot() == {"apply": 1}
        assert StateStore(kv).get_container("web").container_name == "web-0"


class TestKeys:
    def test_split_versioned_name(self):
        assert split_versioned_name("train-3") == ("train", 3)
        assert split_versioned_name("train") == ("train", None)
        assert split_versioned_name("train-x") == ("train-x", None)

    def test_version_keys_sort_numerically(self):
        k2 = keys.version_key(Resource.CONTAINERS, "a", 2)
        k10 = keys.version_key(Resource.CONTAINERS, "a", 10)
        assert k2 < k10  # zero-padding keeps lexicographic == numeric


class TestStateStore:
    def test_container_versions_retained(self, kv):
        """Unlike the reference (one key per family, latest wins —
        etcd/common.go:75-81), every version must be retrievable."""
        store = StateStore(kv)
        for v in range(3):
            store.put_container(ContainerState(f"web-{v}", v, {"name": f"web-{v}"}))
        assert store.get_container("web").container_name == "web-2"  # latest
        assert store.get_container("web-0").container_name == "web-0"
        assert store.get_container("web-1").container_name == "web-1"
        assert store.history(Resource.CONTAINERS, "web") == [0, 1, 2]
        assert store.latest_version(Resource.CONTAINERS, "web") == 2

    def test_delete_family(self, kv):
        store = StateStore(kv)
        store.put_volume(VolumeState("data-0", 0, "10GB"))
        store.put_volume(VolumeState("data-1", 1, "20GB"))
        store.delete_family(Resource.VOLUMES, "data-1")
        with pytest.raises(errors.NotExistInStore):
            store.get_volume("data")

    def test_missing_raises(self, kv):
        store = StateStore(kv)
        with pytest.raises(errors.NotExistInStore):
            store.get_container("ghost")
        with pytest.raises(errors.NotExistInStore):
            store.get_container("ghost-4")


class TestVersionMap:
    def test_bump_sequence(self, kv):
        vm = VersionMap(kv, "/test/versions")
        assert vm.get("a") is None
        assert vm.next_version("a") == 0
        assert vm.next_version("a") == 1
        assert vm.next_version("b") == 0
        assert vm.get("a") == 1

    def test_persisted_every_mutation(self, kv):
        """Reference flushes only on Close (version.go:55-63) — we persist on
        every bump so a crash loses nothing."""
        vm = VersionMap(kv, "/test/versions")
        vm.next_version("a")
        vm2 = VersionMap(kv, "/test/versions")  # simulated restart
        assert vm2.get("a") == 0

    def test_rollback(self, kv):
        vm = VersionMap(kv, "/test/versions")
        vm.next_version("a")
        vm.rollback("a", None)
        assert vm.get("a") is None
        vm.next_version("a")
        vm.next_version("a")
        vm.rollback("a", 0)
        assert vm.get("a") == 0

    def test_concurrent_bumps_unique(self, kv):
        vm = VersionMap(kv, "/test/versions")
        got: list[int] = []
        lock = threading.Lock()

        def bump():
            v = vm.next_version("x")
            with lock:
                got.append(v)

        threads = [threading.Thread(target=bump) for _ in range(20)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        assert sorted(got) == list(range(20))


class TestWorkQueue:
    def test_put_and_del(self, kv):
        wq = WorkQueue(kv)
        wq.start()
        wq.submit(PutKVTask("/wq/a", "1"))
        wq.submit(DelKeyTask("/wq/a"))
        wq.submit(PutKVTask("/wq/b", "2"))
        wq.drain()
        wq.close()
        assert kv.get_or("/wq/a") is None
        assert kv.get("/wq/b") == "2"

    def test_copy_task_moves_data_then_fires_on_done(self, kv, tmp_path):
        src = tmp_path / "old"
        dst = tmp_path / "new"
        src.mkdir()
        (src / "ckpt.bin").write_bytes(b"\x00" * 1024)
        (src / "sub").mkdir()
        (src / "sub" / "x.txt").write_text("hi")
        fired = []

        wq = WorkQueue(kv)
        wq.start()
        wq.submit(CopyTask(
            resource="volumes", old_name="old", new_name="new",
            resolve=lambda n: str(tmp_path / n),
            on_done=lambda: fired.append(True),
        ))
        wq.drain()
        wq.close()
        assert (dst / "ckpt.bin").read_bytes() == b"\x00" * 1024
        assert (dst / "sub" / "x.txt").read_text() == "hi"
        assert fired == [True]

    def test_bounded_retry_dead_letters(self, kv):
        """Reference re-enqueues forever with no backoff (workQueue.go:33-47);
        we retry a bounded number of times then dead-letter."""
        attempts = []

        def boom():
            attempts.append(1)
            raise RuntimeError("nope")

        wq = WorkQueue(kv, max_retries=3, backoff_base_s=0.001)
        wq.start()
        wq.submit(FnTask(fn=boom, description="boom"))
        wq.drain()
        wq.close()
        assert len(attempts) == 3
        assert len(wq.dead_letters) == 1

    def test_retry_backoff_capped_and_jittered(self, kv):
        """The retry sleep is min(cap, base·2^attempt) ± jitter — unbounded
        2^attempt would stall the single sync thread for minutes, and
        jitterless sleeps synchronize every daemon hit by the same outage."""
        wq = WorkQueue(kv, backoff_base_s=0.5, backoff_max_s=2.0,
                       backoff_jitter=0.25, seed=42)
        delays = [wq.retry_delay_s(a) for a in range(8)]
        # clamped: even attempt 7 (raw 64 s) stays within cap + jitter
        assert all(d <= 2.0 * 1.25 for d in delays)
        # jittered around the schedule, not exactly on it
        assert delays[0] != 0.5 and abs(delays[0] - 0.5) <= 0.125
        assert abs(delays[1] - 1.0) <= 0.25
        # deterministic under a seed (replayable chaos runs)
        wq2 = WorkQueue(kv, backoff_base_s=0.5, backoff_max_s=2.0,
                        backoff_jitter=0.25, seed=42)
        assert [wq2.retry_delay_s(a) for a in range(8)] == delays
        # jitter can be disabled for exact-schedule tests
        wq3 = WorkQueue(kv, backoff_base_s=0.5, backoff_max_s=2.0,
                        backoff_jitter=0.0)
        assert [wq3.retry_delay_s(a) for a in range(4)] == [0.5, 1.0, 2.0, 2.0]

    def test_tasks_execute_in_order(self, kv):
        order = []
        wq = WorkQueue(kv)
        wq.start()
        for i in range(10):
            wq.submit(FnTask(fn=lambda i=i: order.append(i)))
        wq.drain()
        wq.close()
        assert order == list(range(10))

    def test_close_drains_submitted(self, kv):
        wq = WorkQueue(kv)
        wq.start()
        for i in range(50):
            wq.submit(PutKVTask(f"/drain/{i:02d}", str(i)))
        wq.close()  # no explicit drain: close itself must finish the backlog
        assert len(kv.range_prefix("/drain/")) == 50

    def test_close_terminates_with_failing_queued_task(self, kv):
        """close() while a poison task sits in the queue: the bounded retry
        must dead-letter it and close must return — not spin forever on the
        failing task (the reference's infinite re-enqueue would hang here),
        and tasks queued behind the poison one must still run."""
        attempts = []

        def boom():
            attempts.append(1)
            raise RuntimeError("poison")

        wq = WorkQueue(kv, max_retries=3, backoff_base_s=0.001)
        wq.start()
        wq.submit(FnTask(fn=boom, description="poison"))
        wq.submit(PutKVTask("/after/poison", "survived"))
        wq.close()  # must terminate
        assert len(attempts) == 3  # bounded, not spinning
        assert len(wq.dead_letters) == 1
        assert kv.get("/after/poison") == "survived"

    def test_retry_dead_letters_reruns_with_fresh_budget(self, kv):
        """A dead-lettered task succeeds on operator retry once the
        underlying fault is gone (POST /api/v1/dead-letters/retry)."""
        healthy = []

        def flaky():
            if not healthy:
                raise OSError("disk full")

        wq = WorkQueue(kv, max_retries=2, backoff_base_s=0.001)
        wq.start()
        wq.submit(FnTask(fn=flaky, description="flaky"))
        wq.drain()
        assert len(wq.dead_letters) == 1
        # retried while the fault persists: dead-letters again, no spin
        assert wq.retry_dead_letters() == 1
        wq.drain()
        assert len(wq.dead_letters) == 1

        healthy.append(True)  # "the disk was cleaned up"
        assert wq.retry_dead_letters() == 1
        wq.drain()
        wq.close()
        assert wq.dead_letters == []

    def test_retry_dead_letters_after_close_is_a_safe_noop(self, kv):
        """A retry racing shutdown must not strand tasks in a consumerless
        queue (and must keep them observable in the dead-letter view)."""
        wq = WorkQueue(kv, max_retries=1, backoff_base_s=0.001)
        wq.start()
        wq.submit(FnTask(fn=lambda: (_ for _ in ()).throw(OSError("x")),
                         description="doomed"))
        wq.drain()
        wq.close()
        assert len(wq.dead_letters) == 1
        assert wq.retry_dead_letters() == 0
        assert len(wq.dead_letters) == 1  # still observable


class TestTaskRecords:
    """Declarative record shape (the durable-queue contract; full lifecycle
    coverage in test_workqueue_durable.py)."""

    def test_json_roundtrip(self):
        from tpu_docker_api.state.workqueue import TaskRecord

        rec = TaskRecord(task_id="abc", kind="copy_volume_data",
                         params={"copyFrom": "d-0", "newName": "d-1"},
                         seq=7, state="inflight", attempts=2,
                         error="OSError: x", idempotency_key="copy:d-0->d-1")
        back = TaskRecord.from_json(rec.to_json())
        assert back == rec

    def test_journal_key_order_matches_seq_order(self):
        assert keys.queue_task_key(2) < keys.queue_task_key(10)
        assert keys.queue_task_key(0).startswith(keys.QUEUE_TASKS_PREFIX)

    def test_legacy_tasks_are_ephemeral(self, kv):
        """Closure tasks never touch the journal — only records do."""
        wq = WorkQueue(kv)
        wq.start()
        wq.submit(FnTask(fn=lambda: None))
        wq.submit(PutKVTask("/e/k", "v"))
        wq.drain()
        wq.close()
        assert kv.range_prefix(keys.QUEUE_TASKS_PREFIX) == {}


class TestEtcdKVHelpers:
    def test_prefix_end(self):
        from tpu_docker_api.state.kv import _prefix_end

        assert _prefix_end("/a/") == "/a0"  # '/' + 1 == '0'
        assert _prefix_end("ab") == "ac"


class TestHistoryOrdering:
    def test_history_numeric_order_past_ten_versions(self):
        """KV prefix scans are lexicographic (v/10 < v/2); history() must
        sort numerically — the rollback endpoints expose this ordering."""
        from tpu_docker_api.schemas.state import VolumeState
        from tpu_docker_api.state.keys import Resource
        from tpu_docker_api.state.kv import MemoryKV
        from tpu_docker_api.state.store import StateStore

        store = StateStore(MemoryKV())
        for v in range(12):
            store.put_volume(VolumeState(
                volume_name=f"d-{v}", version=v, size="1GB", driver_opts={}))
        assert store.history(Resource.VOLUMES, "d") == list(range(12))


class TestAsInt:
    """errors.as_int guards every user-supplied int field (ADVICE r2)."""

    def test_accepts_ints_and_integral_floats(self):
        assert errors.as_int(3, "f") == 3
        assert errors.as_int(0, "f") == 0
        assert errors.as_int(3.0, "f") == 3  # JSON clients sending 3.0

    def test_rejects_bool(self):
        with pytest.raises(errors.BadRequest):
            errors.as_int(True, "chipCount")
        with pytest.raises(errors.BadRequest):
            errors.as_int(False, "chipCount")

    def test_rejects_truncating_float(self):
        with pytest.raises(errors.BadRequest):
            errors.as_int(3.9, "chipCount")

    def test_rejects_strings_none_nan(self):
        for bad in ("3", "x", None, float("nan"), float("inf"), [1]):
            with pytest.raises(errors.BadRequest):
                errors.as_int(bad, "chipCount")
