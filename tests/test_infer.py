"""Inference engine: KV-cached decode must match the full forward pass, and
sampling/generation must be deterministic, eos-aware, and mesh-shardable."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from tpu_docker_api.infer import (
    GenerateConfig,
    decode_one,
    init_kv_cache,
    make_generate_fn,
    make_sampler,
    prefill_and_first_token,
)
from tpu_docker_api.models.llama import (
    llama_forward,
    llama_forward_cached,
    llama_init,
    llama_presets,
)
from tpu_docker_api.parallel.mesh import MeshPlan, build_mesh


@pytest.fixture(scope="module")
def tiny():
    cfg = llama_presets()["tiny"]
    params = llama_init(cfg, jax.random.PRNGKey(0))
    return cfg, params


# The cached path applies rope in f32 (cross-lowering bit-determinism for
# serving) while the training forward applies it in the storage dtype (~3%
# faster step, ops/rope.py). The cache-MECHANICS gates below therefore run
# an f32-dtype model, where the two applications coincide and the tight
# 2e-4 tolerance still catches off-by-one positions / stale-slot bugs; the
# bf16 model's cached-vs-full agreement is only at bf16 noise and is
# covered by the serving-internal exactness tests (sharded==plain
# generate, speculative verify).


@pytest.fixture(scope="module")
def tiny_f32():
    import dataclasses

    cfg = dataclasses.replace(llama_presets()["tiny"], dtype=jnp.float32)
    params = llama_init(cfg, jax.random.PRNGKey(0))
    return cfg, params


class TestCachedForward:
    def test_prefill_matches_full_forward(self, tiny_f32):
        cfg, params = tiny_f32
        tokens = jax.random.randint(
            jax.random.PRNGKey(1), (2, 16), 0, cfg.vocab_size, dtype=jnp.int32
        )
        full = llama_forward(params, tokens, cfg)
        cache = init_kv_cache(cfg, 2, 32, dtype=jnp.float32)
        cached, _, _ = llama_forward_cached(
            params, tokens, cfg, cache.k, cache.v, jnp.int32(0)
        )
        np.testing.assert_allclose(np.asarray(full), np.asarray(cached),
                                   rtol=2e-4, atol=2e-4)

    def test_incremental_decode_matches_full_forward(self, tiny_f32):
        """Prefill s tokens then decode 4 one at a time; each step's logits
        must equal the full-forward logits at that position."""
        cfg, params = tiny_f32
        total, prefill_len = 12, 8
        tokens = jax.random.randint(
            jax.random.PRNGKey(2), (2, total), 0, cfg.vocab_size,
            dtype=jnp.int32,
        )
        full = llama_forward(params, tokens, cfg)

        cache = init_kv_cache(cfg, 2, 16, dtype=jnp.float32)
        logits, k, v = llama_forward_cached(
            params, tokens[:, :prefill_len], cfg, cache.k, cache.v,
            jnp.int32(0),
        )
        np.testing.assert_allclose(
            np.asarray(full[:, :prefill_len]), np.asarray(logits),
            rtol=2e-4, atol=2e-4,
        )
        for pos in range(prefill_len, total):
            logits, k, v = llama_forward_cached(
                params, tokens[:, pos:pos + 1], cfg, k, v, jnp.int32(pos)
            )
            np.testing.assert_allclose(
                np.asarray(full[:, pos]), np.asarray(logits[:, 0]),
                rtol=2e-4, atol=2e-4,
            )

    def test_decode_one_and_prefill_helpers(self, tiny):
        cfg, params = tiny
        prompt = jax.random.randint(
            jax.random.PRNGKey(3), (1, 8), 0, cfg.vocab_size, dtype=jnp.int32
        )
        cache = init_kv_cache(cfg, 1, 16)
        tok, cache = prefill_and_first_token(params, prompt, cfg, cache)
        assert tok.shape == (1,)
        logits, cache = decode_one(params, tok, jnp.int32(8), cache, cfg)
        assert logits.shape == (1, cfg.vocab_size)


class TestSampler:
    def test_greedy_is_argmax(self):
        logits = jnp.array([[0.1, 3.0, -1.0], [2.0, 0.0, 5.0]])
        tok = make_sampler(0.0)(logits, jax.random.PRNGKey(0))
        np.testing.assert_array_equal(np.asarray(tok), [1, 2])

    def test_top_k_1_equals_greedy(self):
        logits = jax.random.normal(jax.random.PRNGKey(4), (4, 64))
        tok = make_sampler(1.0, top_k=1)(logits, jax.random.PRNGKey(5))
        np.testing.assert_array_equal(
            np.asarray(tok), np.argmax(np.asarray(logits), axis=-1)
        )

    def test_top_k_restricts_support(self):
        logits = jnp.tile(jnp.arange(16.0)[None], (64, 1))
        tok = make_sampler(2.0, top_k=3)(logits, jax.random.PRNGKey(6))
        assert set(np.asarray(tok).tolist()) <= {13, 14, 15}

    def test_top_p_keeps_nucleus(self):
        # one dominant token (p=0.9+): tiny top_p must always pick it
        logits = jnp.array([[10.0, 0.0, 0.0, 0.0]])
        sampler = make_sampler(1.0, top_p=0.5)
        for seed in range(8):
            tok = sampler(logits, jax.random.PRNGKey(seed))
            assert int(tok[0]) == 0

    def test_top_p_1_is_plain_sampling(self):
        logits = jax.random.normal(jax.random.PRNGKey(7), (2, 32))
        a = make_sampler(1.0, top_p=1.0)(logits, jax.random.PRNGKey(8))
        b = jax.random.categorical(jax.random.PRNGKey(8), logits, axis=-1)
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))

    def test_validation(self):
        with pytest.raises(ValueError):
            make_sampler(-1.0)
        with pytest.raises(ValueError):
            make_sampler(1.0, top_p=0.0)
        with pytest.raises(ValueError):
            make_sampler(1.0, top_k=-2)


class TestGenerate:
    def test_greedy_generate_matches_manual_loop(self, tiny):
        cfg, params = tiny
        prompt = jax.random.randint(
            jax.random.PRNGKey(9), (2, 8), 0, cfg.vocab_size, dtype=jnp.int32
        )
        gen = make_generate_fn(
            cfg, GenerateConfig(max_new_tokens=5, max_seq=16)
        )
        out = gen(params, prompt, jax.random.PRNGKey(0))
        assert out["tokens"].shape == (2, 5)

        # manual: repeatedly run the FULL forward and take argmax
        seq = prompt
        for _ in range(5):
            logits = llama_forward(params, seq, cfg)
            nxt = jnp.argmax(logits[:, -1], axis=-1).astype(jnp.int32)
            seq = jnp.concatenate([seq, nxt[:, None]], axis=1)
        np.testing.assert_array_equal(
            np.asarray(out["tokens"]), np.asarray(seq[:, 8:])
        )

    def test_generate_deterministic_per_key(self, tiny):
        cfg, params = tiny
        prompt = jnp.ones((1, 4), jnp.int32)
        gen = make_generate_fn(
            cfg,
            GenerateConfig(max_new_tokens=6, temperature=0.8, top_k=8,
                           max_seq=16),
        )
        a = gen(params, prompt, jax.random.PRNGKey(1))
        b = gen(params, prompt, jax.random.PRNGKey(1))
        c = gen(params, prompt, jax.random.PRNGKey(2))
        np.testing.assert_array_equal(np.asarray(a["tokens"]),
                                      np.asarray(b["tokens"]))
        assert not np.array_equal(np.asarray(a["tokens"]),
                                  np.asarray(c["tokens"]))

    def test_eos_stops_and_pads(self, tiny):
        cfg, params = tiny
        prompt = jnp.ones((3, 4), jnp.int32)
        # every token is "eos": generation must stop at length 1
        gen = make_generate_fn(
            cfg,
            GenerateConfig(max_new_tokens=6, max_seq=16, eos_id=None),
        )
        free_run = gen(params, prompt, jax.random.PRNGKey(0))
        eos_id = int(free_run["tokens"][0, 0])
        gen2 = make_generate_fn(
            cfg,
            GenerateConfig(max_new_tokens=6, max_seq=16, eos_id=eos_id,
                           pad_id=0),
        )
        out = gen2(params, prompt, jax.random.PRNGKey(0))
        lengths = np.asarray(out["lengths"])
        toks = np.asarray(out["tokens"])
        assert lengths[0] == 1
        # after eos: pad_id everywhere
        assert (toks[0, 1:] == 0).all()

    def test_cache_overflow_rejected_at_trace_time(self, tiny):
        cfg, params = tiny
        prompt = jnp.ones((1, 12), jnp.int32)
        gen = make_generate_fn(
            cfg, GenerateConfig(max_new_tokens=10, max_seq=16)
        )
        with pytest.raises(ValueError, match="cache capacity"):
            gen(params, prompt, jax.random.PRNGKey(0))

    def test_max_new_tokens_zero_rejected(self, tiny):
        cfg, _ = tiny
        with pytest.raises(ValueError, match="max_new_tokens"):
            make_generate_fn(cfg, GenerateConfig(max_new_tokens=0))

    def test_max_new_tokens_1(self, tiny):
        cfg, params = tiny
        prompt = jnp.ones((1, 4), jnp.int32)
        gen = make_generate_fn(cfg, GenerateConfig(max_new_tokens=1, max_seq=8))
        out = gen(params, prompt, jax.random.PRNGKey(0))
        assert out["tokens"].shape == (1, 1)
        assert int(out["lengths"][0]) == 1


class TestShardedGenerate:
    def test_generate_on_dp_tp_mesh(self, tiny_f32):
        """Whole generate loop jitted over a dp=2×tp=2 mesh (8 virtual CPU
        devices, fsdp=2 absorbing the rest): must run and match unsharded.
        f32 params: with bf16, the sharded collectives' reduction order vs
        the unsharded matmuls rounds logits ~1e-2 apart and random-init
        near-tie argmaxes flip — a numerics artifact, not a sharding bug."""
        cfg, params = tiny_f32
        mesh = build_mesh(MeshPlan(dp=2, fsdp=2, tp=2, sp=1),
                          devices=jax.devices()[:8])
        prompt = jax.random.randint(
            jax.random.PRNGKey(10), (4, 8), 0, cfg.vocab_size, dtype=jnp.int32
        )
        gen_cfg = GenerateConfig(max_new_tokens=4, max_seq=16)
        sharded = make_generate_fn(cfg, gen_cfg, mesh=mesh)
        plain = make_generate_fn(cfg, gen_cfg)
        a = sharded(params, prompt, jax.random.PRNGKey(0))
        b = plain(params, prompt, jax.random.PRNGKey(0))
        np.testing.assert_array_equal(np.asarray(a["tokens"]),
                                      np.asarray(b["tokens"]))

    def test_sharded_cache_init(self, tiny):
        cfg, _ = tiny
        mesh = build_mesh(MeshPlan(dp=2, fsdp=2, tp=2, sp=1),
                          devices=jax.devices()[:8])
        cache = init_kv_cache(cfg, 4, 16, mesh=mesh)
        assert cache.k.shape == (cfg.n_layers, 4, 16, cfg.n_kv_heads,
                                 cfg.head_dim)
        assert not cache.k.sharding.is_fully_replicated
