"""Test bootstrap: force an 8-device virtual CPU platform so sharding tests
exercise a real multi-device mesh without TPU hardware.

This environment preimports jax via an axon sitecustomize, so exporting
JAX_PLATFORMS/XLA_FLAGS before pytest is too late (and pre-startup
JAX_PLATFORMS=cpu hangs the axon plugin registration). The reliable sequence
is: set XLA_FLAGS in os.environ (the CPU client reads it at backend init),
then flip the platform with jax.config.update BEFORE any backend use.
"""

import os

flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (flags + " --xla_force_host_platform_device_count=8").strip()

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")


def pytest_configure(config):
    config.addinivalue_line(
        "markers", "slow: long-running end-to-end flows (subprocess trainers)")
    config.addinivalue_line(
        "markers", "chaos: crash/fault-injection suite (`make chaos`); "
        "hermetic and fast — also runs in the default tier")
# the CPU backend's default matmul precision is low; exactness tests
# (flash vs dense, ring vs dense) need deterministic f32 accumulation
jax.config.update("jax_default_matmul_precision", "float32")
