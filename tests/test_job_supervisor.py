"""Gang supervision unit tests (service/job_supervisor.py):

- watcher/job interaction: a dying job member is DELEGATED to the gang
  supervisor — the per-container restart path must decline it;
- whole-gang restart ordering (stop workers first / coordinator last, start
  coordinator first) — including the restart_job regression;
- per-container HealthWatcher restart backoff (service/watch.py satellite).
"""

import pytest

from tpu_docker_api import config as config_mod, errors
from tpu_docker_api.daemon import Program
from tpu_docker_api.runtime.fake import FakeRuntime
from tpu_docker_api.runtime.spec import ContainerSpec
from tpu_docker_api.schemas.job import JobRun
from tpu_docker_api.service.job_supervisor import JobSupervisor
from tpu_docker_api.service.watch import HealthWatcher
from tpu_docker_api.state.kv import MemoryKV


def boot_pod(kv=None, local_rt=None, remote_rt=None):
    cfg = config_mod.Config(
        store_backend="memory", runtime_backend="fake",
        health_watch_interval=0, end_port=40099,
        pod_hosts=[
            {"host_id": "h0", "address": "10.0.0.1", "grid_coord": [0, 0, 0],
             "local": True},
            {"host_id": "h1", "address": "10.0.0.2", "grid_coord": [1, 0, 0],
             "runtime_backend": "fake"},
        ],
    )
    prg = Program(cfg, kv=kv or MemoryKV(), runtime=local_rt or FakeRuntime(),
                  pod_runtimes={"h1": remote_rt or FakeRuntime()})
    prg.init()
    return prg


def _gang_calls(rt: FakeRuntime) -> list:
    return [c for c in rt.calls if c[0] in ("stop", "start", "restart")]


class TestWatcherJobInteraction:
    """A dying gang member must never be restarted by the container path."""

    def test_watcher_delegates_job_member_to_supervisor(self):
        rt0 = FakeRuntime()
        prg = boot_pod(local_rt=rt0)
        prg.job_svc.run_job(JobRun(image_name="jax", job_name="train",
                                   chip_count=16))
        watcher = HealthWatcher(
            rt0, interval_s=3600, restart_policy="on-failure",
            crash_handler=prg.container_svc.handle_crash,
            job_crash_handler=prg.job_supervisor.handle_member_death)
        watcher.poll_once()  # observe train-0-p0 on the local runtime
        rt0.crash_container("train-0-p0", exit_code=137)
        watcher.poll_once()
        kinds = [e["event"] for e in watcher.events_view()]
        assert "delegated-to-job-supervisor" in kinds
        # the container path never touched it: no restart event, no budget,
        # and recovery does NOT run on the watcher thread — the member is
        # still down until the supervisor's own loop takes over
        assert "restarted" not in kinds
        assert watcher.status_view()["restarts"] == {}
        assert not rt0.container_inspect("train-0-p0").running
        sup_events = [e["event"] for e in prg.job_supervisor.events_view()]
        assert "member-died-delegated" in sup_events
        # ... and the SUPERVISOR recovers the whole gang, not one member
        prg.job_supervisor.poll_once()
        assert rt0.container_inspect("train-0-p0").running
        sup_events = [e["event"] for e in prg.job_supervisor.events_view()]
        assert "gang-restarting" in sup_events
        assert prg.store.get_job("train-0").restarts == 1

    def test_whole_gang_restarts_not_single_member(self):
        rt0, rt1 = FakeRuntime(), FakeRuntime()
        prg = boot_pod(local_rt=rt0, remote_rt=rt1)
        prg.job_svc.run_job(JobRun(image_name="jax", job_name="train",
                                   chip_count=16))
        rt0.calls.clear()
        rt1.calls.clear()
        # the member on h1 dies; recovery must bounce BOTH members
        rt1.crash_container("train-0-p1")
        prg.job_supervisor.poll_once()
        assert _gang_calls(rt0) == [("stop", "train-0-p0"),
                                    ("start", "train-0-p0")]
        assert _gang_calls(rt1) == [("stop", "train-0-p1"),
                                    ("start", "train-0-p1")]
        assert rt0.container_inspect("train-0-p0").running
        assert rt1.container_inspect("train-0-p1").running

    def test_handle_member_death_declines_non_members(self):
        prg = boot_pod()
        assert prg.job_supervisor.handle_member_death("plain-0") is False
        assert prg.job_svc.owns_member("plain-0") is None
        assert prg.job_svc.owns_member("train-0-p0") is None  # no such job

    def test_container_service_crash_handler_refuses_job_members(self):
        """handle_crash keys off the container version map — a gang member
        is not a container family, so the accounting path declines too."""
        rt0 = FakeRuntime()
        prg = boot_pod(local_rt=rt0)
        prg.job_svc.run_job(JobRun(image_name="jax", job_name="train",
                                   chip_count=16))
        rt0.crash_container("train-0-p0")
        assert prg.container_svc.handle_crash("train-0-p0") is False
        assert not rt0.container_inspect("train-0-p0").running


class TestGangOrdering:
    def test_restart_job_coordinator_first(self):
        """Regression: restart_job must stop the gang (coordinator LAST) and
        start it in process order (coordinator FIRST) — not per-member
        container_restart in placement order."""
        rt0, rt1 = FakeRuntime(), FakeRuntime()
        prg = boot_pod(local_rt=rt0, remote_rt=rt1)
        prg.job_svc.run_job(JobRun(image_name="jax", job_name="train",
                                   chip_count=16))
        rt0.calls.clear()
        rt1.calls.clear()
        out = prg.job_svc.restart_job("train")
        assert out["phase"] == "running"
        # per-runtime journals: worker stopped before the coordinator...
        assert _gang_calls(rt1)[0] == ("stop", "train-0-p1")
        assert _gang_calls(rt0) == [("stop", "train-0-p0"),
                                    ("start", "train-0-p0")]
        # ... and the coordinator started before the worker: p1's start can
        # only be ordered after p0's because starts run in process order —
        # check via the supervisor-visible end state + event
        events = [e["event"] for e in prg.job_supervisor.events_view()]
        assert "job-restarted" in events

    def test_restart_job_resets_budget(self):
        rt1 = FakeRuntime()
        prg = boot_pod(remote_rt=rt1)
        prg.job_svc.run_job(JobRun(image_name="jax", job_name="train",
                                   chip_count=16))
        rt1.crash_container("train-0-p1")
        prg.job_supervisor.poll_once()
        assert prg.store.get_job("train-0").restarts == 1
        prg.job_svc.restart_job("train")
        assert prg.store.get_job("train-0").restarts == 0

    def test_manual_restart_clears_backoff_window(self):
        """A manual restart resets the persisted budget AND the supervisor's
        in-memory backoff deadline — the next crash recovers immediately."""
        rt1 = FakeRuntime()
        prg = boot_pod(remote_rt=rt1)
        prg.job_svc.run_job(JobRun(image_name="jax", job_name="train",
                                   chip_count=16))
        clock = {"now": 0.0}
        sup = JobSupervisor(
            prg.pod, prg.job_svc, prg.store, prg.job_versions,
            max_restarts=5, backoff_base_s=50.0, backoff_max_s=60.0,
            backoff_jitter=0.0, clock=lambda: clock["now"])
        rt1.crash_container("train-0-p1")
        sup.poll_once()  # restart #1 arms a 50 s deadline
        prg.job_svc.restart_job("train")
        assert prg.store.get_job("train-0").restarts == 0
        rt1.crash_container("train-0-p1")
        clock["now"] = 1.0  # far inside the old window
        sup.poll_once()
        assert rt1.container_inspect("train-0-p1").running
        assert prg.store.get_job("train-0").restarts == 1

    def test_restart_gang_declines_healthy_gang(self):
        """A stale crash observation must not bounce a gang someone else
        already recovered — no restart, no budget burn."""
        rt1 = FakeRuntime()
        prg = boot_pod(remote_rt=rt1)
        prg.job_svc.run_job(JobRun(image_name="jax", job_name="train",
                                   chip_count=16))
        rt1.calls.clear()
        st = prg.job_svc.restart_gang("train", reason="stale observation")
        assert st.restarts == 0
        assert _gang_calls(rt1) == []

    def test_fail_job_declines_stopped_job(self):
        """A user stop that races a lock-free missing-member verdict wins:
        the stopped job must not be condemned as failed."""
        prg = boot_pod()
        prg.job_svc.run_job(JobRun(image_name="jax", job_name="train",
                                   chip_count=16))
        prg.job_svc.stop_job("train")
        st = prg.job_svc.fail_job("train", "stale verdict")
        assert st.phase == "stopped"
        assert prg.store.get_job("train-0").phase == "stopped"

    def test_restart_of_failed_job_rejected(self):
        rt1 = FakeRuntime()
        prg = boot_pod(remote_rt=rt1)
        prg.job_svc.run_job(JobRun(image_name="jax", job_name="train",
                                   chip_count=16))
        prg.job_svc.fail_job("train", "test says so")
        with pytest.raises(errors.BadRequest, match="failed"):
            prg.job_svc.restart_job("train")

    def test_stop_job_reverse_order(self):
        rt0, rt1 = FakeRuntime(), FakeRuntime()
        prg = boot_pod(local_rt=rt0, remote_rt=rt1)
        prg.job_svc.run_job(JobRun(image_name="jax", job_name="train",
                                   chip_count=16))
        rt0.calls.clear()
        rt1.calls.clear()
        prg.job_svc.stop_job("train")
        # the worker's stop lands while the coordinator is still up; the
        # coordinator's own journal records its stop as the gang's last call
        assert _gang_calls(rt1) == [("stop", "train-0-p1")]
        assert _gang_calls(rt0) == [("stop", "train-0-p0")]
        st = prg.store.get_job("train-0")
        assert st.phase == "stopped" and not st.desired_running

    def test_clean_whole_gang_exit_is_completion_not_crash(self):
        """All members exiting 0 = the job RAN TO COMPLETION: no gang
        restart, no budget burn, no terminal failed — settled as stopped."""
        rt0, rt1 = FakeRuntime(), FakeRuntime()
        prg = boot_pod(local_rt=rt0, remote_rt=rt1)
        prg.job_svc.run_job(JobRun(image_name="jax", job_name="train",
                                   chip_count=16))
        rt0.crash_container("train-0-p0", exit_code=0)
        rt1.crash_container("train-0-p1", exit_code=0)
        rt0.calls.clear()
        rt1.calls.clear()
        prg.job_supervisor.poll_once()
        st = prg.store.get_job("train-0")
        assert st.phase == "stopped" and not st.desired_running
        assert st.restarts == 0
        assert _gang_calls(rt0) == [] and _gang_calls(rt1) == []
        events = [e["event"] for e in prg.job_supervisor.events_view()]
        assert "job-completed" in events and "gang-restarting" not in events
        # the reconciler agrees: a fresh sweep settles an identical gang
        # the same way and finds nothing afterwards
        assert prg.reconciler.reconcile()["actions"] == []

    def test_partial_clean_exit_leaves_gang_alone(self):
        """One member finishing (exit 0) while its peer still runs is an
        early finisher, not a crash — the gang must not be bounced."""
        rt1 = FakeRuntime()
        prg = boot_pod(remote_rt=rt1)
        prg.job_svc.run_job(JobRun(image_name="jax", job_name="train",
                                   chip_count=16))
        rt1.crash_container("train-0-p1", exit_code=0)
        prg.job_supervisor.poll_once()
        st = prg.store.get_job("train-0")
        assert st.phase == "running" and st.restarts == 0
        assert not rt1.container_inspect("train-0-p1").running

    def test_reconciler_settles_completed_job(self):
        rt0, rt1 = FakeRuntime(), FakeRuntime()
        prg = boot_pod(local_rt=rt0, remote_rt=rt1)
        prg.job_svc.run_job(JobRun(image_name="jax", job_name="train",
                                   chip_count=16))
        rt0.crash_container("train-0-p0", exit_code=0)
        rt1.crash_container("train-0-p1", exit_code=0)
        report = prg.reconciler.reconcile()
        assert "settle-completed-job" in [a["action"] for a in report["actions"]]
        st = prg.store.get_job("train-0")
        assert st.phase == "stopped" and st.restarts == 0
        assert prg.reconciler.reconcile()["actions"] == []

    def test_missing_member_fails_job_terminally(self):
        rt1 = FakeRuntime()
        prg = boot_pod(remote_rt=rt1)
        prg.job_svc.run_job(JobRun(image_name="jax", job_name="train",
                                   chip_count=16))
        rt1.container_remove("train-0-p1", force=True)
        prg.job_supervisor.poll_once()
        st = prg.store.get_job("train-0")
        assert st.phase == "failed"
        assert "train-0-p1" in st.failure_reason
        # slices/ports freed — both hosts fully reusable
        assert all(len(h.chips.free_chips) == 8
                   for h in prg.pod.hosts.values())


class TestDeletedJobLeftAlone:
    def test_delete_keeping_spec_quiesces_record(self):
        from tpu_docker_api.schemas.job import JobDelete
        from tpu_docker_api.service.invariants import check_job_invariants

        rt1 = FakeRuntime()
        prg = boot_pod(remote_rt=rt1)
        prg.job_svc.run_job(JobRun(image_name="jax", job_name="train",
                                   chip_count=16))
        prg.job_svc.delete_job("train", JobDelete(force=True))
        # the kept spec must not read as a running job with missing members:
        # neither the supervisor nor the reconciler may touch it
        prg.job_supervisor.poll_once()
        st = prg.store.get_job("train-0")
        assert st.phase == "stopped" and not st.desired_running
        assert prg.reconciler.reconcile()["actions"] == []
        assert check_job_invariants(
            prg.pod, prg.pod_scheduler, prg.store, prg.job_versions) == []


class TestSupervisorStatusApi:
    def test_status_view_and_health_route(self):
        prg = boot_pod()
        prg.job_svc.run_job(JobRun(image_name="jax", job_name="train",
                                   chip_count=16))
        view = prg.job_supervisor.status_view()
        assert view["jobs"]["train"]["phase"] == "running"
        assert view["jobs"]["train"]["restarts"] == 0
        assert view["jobs"]["train"]["deadMembers"] == []

    def test_job_info_surfaces_phase_and_reason(self):
        rt1 = FakeRuntime()
        prg = boot_pod(remote_rt=rt1)
        prg.job_svc.run_job(JobRun(image_name="jax", job_name="train",
                                   chip_count=16))
        info = prg.job_svc.get_job_info("train")
        assert info["phase"] == "running" and info["restarts"] == 0
        prg.job_svc.fail_job("train", "oom loop")
        info = prg.job_svc.get_job_info("train")
        assert info["phase"] == "failed"
        assert info["failureReason"] == "oom loop"


class TestWatcherRestartBackoff:
    """Satellite: _try_restart paces attempts — a tight crash loop must not
    burn the whole budget in consecutive polls."""

    def _mk(self, clock, backoff=2.0, cap=8.0, max_restarts=5):
        rt = FakeRuntime()
        w = HealthWatcher(rt, interval_s=3600, restart_policy="on-failure",
                          max_restarts=max_restarts, restart_backoff_s=backoff,
                          restart_backoff_max_s=cap, clock=clock)
        return rt, w

    def test_restart_deferred_inside_backoff_window(self):
        clock = {"now": 0.0}
        rt, w = self._mk(lambda: clock["now"])
        rt.container_create(ContainerSpec(image="i", name="c-0"))
        rt.container_start("c-0")
        w.poll_once()
        rt.crash_container("c-0", exit_code=1)
        w.poll_once()  # restart #1 immediate, arms a 2 s deadline
        assert rt.container_inspect("c-0").running
        rt.crash_container("c-0", exit_code=1)
        clock["now"] = 1.0
        w.poll_once()  # inside the window: deferred
        assert not rt.container_inspect("c-0").running
        kinds = [e["event"] for e in w.events_view()]
        assert "restart-deferred" in kinds
        assert kinds.count("restarted") == 1
        # budget untouched by the deferral
        assert w.status_view()["restarts"]["c-0"] == 1

    def test_deferred_restart_retries_after_deadline(self):
        clock = {"now": 0.0}
        rt, w = self._mk(lambda: clock["now"])
        rt.container_create(ContainerSpec(image="i", name="c-0"))
        rt.container_start("c-0")
        w.poll_once()
        rt.crash_container("c-0", exit_code=1)
        w.poll_once()           # restart #1
        rt.crash_container("c-0", exit_code=1)
        clock["now"] = 0.5
        w.poll_once()           # deferred (no running→dead edge re-fires)
        clock["now"] = 1.0
        w.poll_once()           # still deferred
        assert not rt.container_inspect("c-0").running
        clock["now"] = 2.5      # past the 2 s deadline
        w.poll_once()
        assert rt.container_inspect("c-0").running
        kinds = [e["event"] for e in w.events_view()]
        assert kinds.count("restarted") == 2

    def test_backoff_doubles_and_clamps(self):
        clock = {"now": 0.0}
        rt, w = self._mk(lambda: clock["now"], backoff=2.0, cap=5.0)
        rt.container_create(ContainerSpec(image="i", name="c-0"))
        rt.container_start("c-0")
        w.poll_once()
        # drive repeated crash→restart cycles, always past the deadline
        gaps = []
        t = 0.0
        for _ in range(4):
            rt.crash_container("c-0", exit_code=1)
            before = clock["now"]
            w.poll_once()
            if not rt.container_inspect("c-0").running:
                # deferred — find the armed deadline by advancing until it runs
                while not rt.container_inspect("c-0").running:
                    clock["now"] += 0.5
                    w.poll_once()
            gaps.append(clock["now"] - before)
            t = clock["now"]
        # delays: 0 (first immediate), then 2, 4, then clamped at 5
        assert gaps[0] == 0.0
        assert 2.0 <= gaps[1] <= 2.5
        assert 4.0 <= gaps[2] <= 4.5
        assert 5.0 <= gaps[3] <= 5.5

    def test_clean_stop_after_crash_restart_not_resurrected(self):
        """A successful crash-restart arms the next-attempt deadline; a
        LATER deliberate stop (exit 0) must clear it — the deferred-retry
        branch must never resurrect a user-stopped container."""
        clock = {"now": 0.0}
        rt, w = self._mk(lambda: clock["now"])
        rt.container_create(ContainerSpec(image="i", name="c-0"))
        rt.container_start("c-0")
        w.poll_once()
        rt.crash_container("c-0", exit_code=1)
        w.poll_once()  # restart #1, deadline armed
        assert rt.container_inspect("c-0").running
        rt.crash_container("c-0", exit_code=0)  # clean stop
        clock["now"] = 100.0  # far past any deadline
        w.poll_once()
        w.poll_once()
        assert not rt.container_inspect("c-0").running
        kinds = [e["event"] for e in w.events_view()]
        assert kinds.count("restarted") == 1

    def test_zero_backoff_preserves_legacy_behavior(self):
        rt = FakeRuntime()
        w = HealthWatcher(rt, interval_s=3600, restart_policy="on-failure",
                          max_restarts=2)
        rt.container_create(ContainerSpec(image="i", name="c-0"))
        rt.container_start("c-0")
        w.poll_once()
        for _ in range(4):
            rt.crash_container("c-0", exit_code=1)
            w.poll_once()
        kinds = [e["event"] for e in w.events_view()]
        assert kinds.count("restarted") == 2
        assert "restart-budget-exhausted" in kinds
        assert "restart-deferred" not in kinds
