"""Paginated lists (ISSUE 12): the bounded-range primitives
(``keys_prefix`` / ``range_prefix_page``) on all three KV backends, the
rev-anchored continue-token contract (a page walk is a consistent
snapshot or a typed ContinueExpired — NEVER a silent dup/skip), and the
HTTP list endpoints riding them."""

import json
import urllib.error
import urllib.request

import pytest
from etcd_gateway import start_gateway, stop_gateway

from tpu_docker_api import errors
from tpu_docker_api.state import pager
from tpu_docker_api.state.keys import Resource
from tpu_docker_api.state.kv import EtcdKV, MemoryKV, SqliteKV

P = "/apis/v1/containers/"


def seed(kv, n=9):
    for i in range(n):
        kv.put(f"{P}f{i}/latest", str(i))
    kv.put("/apis/v1/volumes/other/latest", "0")  # outside the prefix


@pytest.fixture()
def gateway():
    server, _ = start_gateway()
    try:
        yield server
    finally:
        stop_gateway(server)


@pytest.fixture(params=["memory", "sqlite", "etcd"])
def kv(request, tmp_path, gateway):
    if request.param == "memory":
        yield MemoryKV()
    elif request.param == "sqlite":
        k = SqliteKV(str(tmp_path / "kv.db"))
        yield k
        k.close()
    else:
        yield EtcdKV(f"http://127.0.0.1:{gateway.server_address[1]}")


class TestKeysPrefix:
    def test_keys_only_sorted_and_scoped(self, kv):
        seed(kv)
        ks = kv.keys_prefix(P)
        assert ks == sorted(ks)
        assert ks == [f"{P}f{i}/latest" for i in range(9)]

    def test_limit_and_start_after(self, kv):
        seed(kv)
        first = kv.keys_prefix(P, limit=4)
        assert len(first) == 4
        rest = kv.keys_prefix(P, start_after=first[-1])
        assert first + rest == kv.keys_prefix(P)

    def test_matches_range_prefix_keys(self, kv):
        seed(kv)
        assert kv.keys_prefix(P) == list(kv.range_prefix(P))


class TestRangePrefixPage:
    def walk(self, kv, limit):
        items, last, rev = {}, "", 0
        while True:
            page, rev = kv.range_prefix_page(P, limit, start_after=last,
                                             at_rev=rev)
            items.update(page)
            if len(page) < limit:
                return items, rev
            last = max(page)

    def test_full_walk_equals_range(self, kv):
        seed(kv)
        items, _ = self.walk(kv, limit=4)
        assert items == kv.range_prefix(P)

    def test_limit_bounds_each_page(self, kv):
        seed(kv)
        page, rev = kv.range_prefix_page(P, 3)
        assert len(page) == 3 and rev > 0
        assert list(page) == kv.keys_prefix(P, limit=3)

    def test_start_after_is_exclusive(self, kv):
        seed(kv)
        page, _ = kv.range_prefix_page(P, 3, start_after=f"{P}f0/latest")
        assert f"{P}f0/latest" not in page

    def test_insert_between_pages_is_snapshot_or_410(self, kv):
        """Both legal outcomes of a concurrent insert, NEVER a dup/skip:
        an MVCC backend (etcd) serves the anchored snapshot — the new key
        is invisible at that revision — while the log-proof backends
        (memory/sqlite) conservatively expire the token."""
        seed(kv)
        page, rev = kv.range_prefix_page(P, 4)
        kv.put(f"{P}f0a/latest", "9")  # lands INSIDE the walked window
        try:
            rest, _ = kv.range_prefix_page(P, 99, start_after=max(page),
                                           at_rev=rev)
        except errors.ContinueExpired:
            return
        assert f"{P}f0a/latest" not in rest
        assert list(page) + list(rest) == [
            f"{P}f{i}/latest" for i in range(9)]

    def test_delete_between_pages_expires_the_token(self, kv):
        seed(kv)
        page, rev = kv.range_prefix_page(P, 4)
        kv.delete(f"{P}f7/latest")
        with pytest.raises(errors.ContinueExpired):
            kv.range_prefix_page(P, 4, start_after=max(page), at_rev=rev)

    def test_writes_outside_the_prefix_do_not_expire(self, kv):
        seed(kv)
        page, rev = kv.range_prefix_page(P, 4)
        kv.put("/apis/v1/volumes/noise/latest", "1")
        rest, _ = kv.range_prefix_page(P, 99, start_after=max(page),
                                       at_rev=rev)
        assert list(page) + list(rest) == kv.keys_prefix(P)

    def test_never_dup_never_skip_under_churn(self, kv):
        """The end-to-end contract: whatever interleaves with the walk,
        the caller either gets the anchored snapshot exactly once or a
        typed 410 — restart on 410 and the final walk is exact."""
        seed(kv, n=12)
        expected = set(kv.keys_prefix(P))
        mutated = False
        while True:
            got: list[str] = []
            last, rev = "", 0
            try:
                while True:
                    page, rev = kv.range_prefix_page(P, 5, start_after=last,
                                                     at_rev=rev)
                    got.extend(page)
                    if not mutated:
                        # sabotage mid-walk exactly once
                        kv.put(f"{P}f5a/latest", "x")
                        expected.add(f"{P}f5a/latest")
                        mutated = True
                    if len(page) < 5:
                        break
                    last = max(page)
            except errors.ContinueExpired:
                continue  # restart the walk from a fresh anchor
            assert sorted(got) == sorted(expected)
            assert len(got) == len(set(got)), "a page walk duplicated keys"
            return

    def test_requires_positive_limit(self, kv):
        with pytest.raises(ValueError):
            kv.range_prefix_page(P, 0)


class TestMemoryLogTrim:
    def test_trimmed_log_expires_instead_of_guessing(self):
        kv = MemoryKV(log_retain=8)
        seed(kv, n=4)
        _, rev = kv.range_prefix_page(P, 2)
        for i in range(20):  # push the anchor past the trimmed window
            kv.put(f"/apis/v1/volumes/n{i}/latest", "0")
        with pytest.raises(errors.ContinueExpired):
            kv.range_prefix_page(P, 2, start_after=f"{P}f0/latest",
                                 at_rev=rev)


class TestTokens:
    def test_roundtrip(self):
        tok = pager.encode_token(Resource.CONTAINERS, 42, f"{P}f3/latest")
        assert pager.decode_token(tok, Resource.CONTAINERS) == (
            42, f"{P}f3/latest")

    def test_resource_mismatch_is_bad_request(self):
        tok = pager.encode_token(Resource.CONTAINERS, 42, "k")
        with pytest.raises(errors.BadRequest):
            pager.decode_token(tok, Resource.VOLUMES)

    def test_garbage_is_bad_request(self):
        for garbage in ("notatoken", "e30", ""):
            with pytest.raises(errors.BadRequest):
                pager.decode_token(garbage, Resource.CONTAINERS)


class TestListFamilies:
    def test_folds_latest_pointers_only(self):
        kv = MemoryKV()
        kv.put(f"{P}a/latest", "2")
        kv.put(f"{P}a/v/0000000002", "{}")
        out = pager.list_families(kv, Resource.CONTAINERS, limit=10)
        assert out["items"] == [{"name": "a", "version": 2}]
        assert out["continue"] is None

    def test_walk_visits_every_family_once(self):
        kv = MemoryKV()
        for i in range(25):
            kv.put(f"{P}f{i:02d}/latest", "0")
            kv.put(f"{P}f{i:02d}/v/0000000000", "{}")
        names, token = [], ""
        while True:
            out = pager.list_families(kv, Resource.CONTAINERS, limit=7,
                                      token=token)
            names.extend(it["name"] for it in out["items"])
            token = out["continue"]
            if not token:
                break
        assert names == sorted(names) and len(names) == 25
        assert len(set(names)) == 25

    def test_unlimited_is_one_consistent_snapshot(self):
        kv = MemoryKV()
        seed(kv, n=5)
        out = pager.list_families(kv, Resource.CONTAINERS)
        assert [it["name"] for it in out["items"]] == [
            f"f{i}" for i in range(5)]
        assert out["continue"] is None


class TestHttpListEndpoints:
    @pytest.fixture()
    def prog(self):
        from tpu_docker_api.config import Config
        from tpu_docker_api.daemon import Program

        prg = Program(Config(
            port=0, store_backend="memory", runtime_backend="fake",
            health_watch_interval=0, host_probe_interval_s=0,
            job_supervise_interval=0, autoscale_interval_s=0,
            start_port=46000, end_port=46099,
        ), host="127.0.0.1")
        prg.init()
        prg.start()
        yield prg
        prg.stop()

    def call(self, prog, method, path, body=None):
        req = urllib.request.Request(
            f"http://127.0.0.1:{prog.api_server.port}{path}", method=method,
            data=json.dumps(body).encode() if body is not None else None,
            headers={"Content-Type": "application/json"})
        with urllib.request.urlopen(req, timeout=10) as resp:
            return json.loads(resp.read())

    def _mk(self, prog, name):
        from tpu_docker_api.schemas.container import ContainerRun

        prog.container_svc.run_container(ContainerRun(
            image_name="jax", container_name=name, chip_count=0))

    def test_container_walk_over_http(self, prog):
        for i in range(6):
            self._mk(prog, f"w{i}")
        names, token = [], ""
        while True:
            q = "/api/v1/containers?limit=4" + (
                f"&continue={token}" if token else "")
            out = self.call(prog, "GET", q)
            assert out["code"] == 200
            names.extend(it["name"] for it in out["data"]["items"])
            token = out["data"]["continue"]
            if not token:
                break
        assert names == [f"w{i}" for i in range(6)]

    def test_concurrent_write_is_http_410(self, prog):
        for i in range(6):
            self._mk(prog, f"x{i}")
        out = self.call(prog, "GET", "/api/v1/containers?limit=3")
        token = out["data"]["continue"]
        self._mk(prog, "x9")  # mutate under the prefix mid-walk
        with pytest.raises(urllib.error.HTTPError) as err:
            self.call(prog, "GET",
                      f"/api/v1/containers?limit=3&continue={token}")
        assert err.value.code == 410
        body = json.loads(err.value.read())
        assert body["code"] == errors.ContinueExpired.code

    def test_unlimited_legacy_shape(self, prog):
        self._mk(prog, "solo")
        out = self.call(prog, "GET", "/api/v1/containers")
        assert out["data"]["continue"] is None
        assert out["data"]["items"] == [{"name": "solo", "version": 0}]

    def test_volume_and_job_lists_exist(self, prog):
        for path in ("/api/v1/volumes", "/api/v1/jobs"):
            out = self.call(prog, "GET", path + "?limit=5")
            assert out["code"] == 200
            assert out["data"]["items"] == []

    def test_services_paged_shape_and_legacy(self, prog):
        legacy = self.call(prog, "GET", "/api/v1/services")
        assert legacy["data"] == []
        paged = self.call(prog, "GET", "/api/v1/services?limit=5")
        assert paged["data"]["items"] == []
        assert paged["data"]["continue"] is None
