"""Workflow tests (docs/robustness.md "Workflows").

Property tier, pinned:

- a Workflow owns a DAG of job steps (family ``<wf>.s<run>_<idx>``, run
  ordinal baked into the name) admitted at the workflow's priority
  class, with the workflow's shared binds mounted into every step (the
  artifact hand-off) and the owner/run env markers rendered durably;
- step transitions are journaled TaskRecords with attempt-scoped
  idempotency keys; the step-complete marker lands BEFORE any successor
  launches (the PR 5 copy-marker pattern);
- failed steps retry on capped exponential backoff up to their budget;
  past budget the WHOLE workflow settles terminal ``failed`` and frees
  every gang it owns;
- a ``promote`` step rolls a Service through the rolling-update
  machinery exactly once (marker + image-comparison belt-and-braces);
- cron: overlapping-run suppression, missed-tick catch-up (``skip`` vs
  ``fire_once``) across restarts, disable mid-flight — all under a
  virtual clock, no sleeps;
- chaos matrix: a daemon kill at every ``workflow.*`` crash point (and
  a leader failover mid-workflow) converges — a fresh Program drives
  the DAG to completion or terminal failure, every step effect applied
  exactly once, zero orphan gangs, fixpoint.
"""

import json

import pytest

from tpu_docker_api import config as config_mod
from tpu_docker_api import errors
from tpu_docker_api.daemon import Program
from tpu_docker_api.runtime.fake import FakeRuntime
from tpu_docker_api.schemas.job import JobRun
from tpu_docker_api.schemas.service import ServiceCreate
from tpu_docker_api.schemas.workflow import (
    WORKFLOW_OWNER_ENV,
    WORKFLOW_RUN_ENV,
    WorkflowCreate,
    WorkflowPatch,
    WorkflowStep,
)
from tpu_docker_api.service.crashpoints import (
    WORKFLOW_CRASH_POINTS,
    SimulatedCrash,
    armed,
)
from tpu_docker_api.service.invariants import (
    check_invariants,
    check_job_invariants,
    check_workflow_invariants,
)
from tpu_docker_api.service.workflow import split_step_base, step_base
from tpu_docker_api.state import keys
from tpu_docker_api.state.keys import Resource
from tpu_docker_api.state.kv import MemoryKV


def boot(kv=None, runtime=None, **cfg_kw) -> Program:
    """A Program with inline-driven loops: the work queue is NOT started
    (tests replay its journal by hand, under armed crash points) and the
    engine is ticked explicitly."""
    kv = kv if kv is not None else MemoryKV()
    runtime = runtime if runtime is not None else FakeRuntime()
    cfg = config_mod.Config(
        store_backend="memory", runtime_backend="fake",
        health_watch_interval=0, end_port=40099,
        admission_enabled=True, admission_interval_s=0,
        **cfg_kw)
    prg = Program(cfg, kv=kv, runtime=runtime)
    prg.init()
    return prg


def two_steps(chips: int = 1) -> list[WorkflowStep]:
    return [
        WorkflowStep(name="train", image="jax:train", chip_count=chips),
        WorkflowStep(name="evaluate", image="jax:eval", chip_count=chips,
                     deps=["train"]),
    ]


def create_wf(prg, name="pipe", steps=None, **kw):
    return prg.workflow.create_workflow(WorkflowCreate(
        workflow_name=name, steps=steps if steps is not None else two_steps(),
        **kw))


def oracle(prg) -> list[str]:
    problems = check_workflow_invariants(
        prg.store, prg.workflow_versions, prg.job_versions)
    problems += check_job_invariants(
        prg.pod, prg.pod_scheduler, prg.store, prg.job_versions)
    problems += check_invariants(
        prg.runtime, prg.store, prg.container_versions,
        prg.chip_scheduler, prg.port_scheduler,
        job_versions=prg.job_versions)
    return problems


def drive(prg, name, rounds: int = 24) -> dict:
    """Run a workflow's DAG to a terminal phase: replay journaled step
    records inline, drain the admission queue, complete running gangs
    (FakeRuntime members never exit on their own), tick the engine."""
    info = prg.workflow.workflow_info(name)
    for _ in range(rounds):
        if info["phase"] in ("succeeded", "failed"):
            return info
        prg.wq.replay_journal(include_local=True)
        for _ in range(4):
            if not prg.admission.admit_once():
                break
        info = prg.workflow.workflow_info(name)
        for s in info["steps"]:
            if s["state"] == "running" and s.get("jobPhase") == "running":
                prg.job_svc.mark_gang_completed(s["job"])
        prg.workflow.tick()
        prg.wq.replay_journal(include_local=True)
        info = prg.workflow.workflow_info(name)
    return info


def pump(prg, n: int = 2) -> None:
    """Replay + tick without completing anything (steps reach running)."""
    for _ in range(n):
        prg.wq.replay_journal(include_local=True)
        prg.workflow.tick()


def created_names(rt: FakeRuntime) -> list[str]:
    return [c[1] for c in rt.calls if c[0] == "create"]


class TestNaming:
    def test_step_base_round_trips(self):
        assert step_base("pipe", 2, 1) == "pipe.s2_1"
        assert split_step_base("pipe.s2_1") == ("pipe", 2, 1)
        assert split_step_base("a.b.s10_3") == ("a.b", 10, 3)
        assert split_step_base("pipe") is None
        assert split_step_base("pipe.s2") is None
        assert split_step_base("pipe.sx_1") is None
        assert split_step_base(".s1_0") is None


class TestValidation:
    def test_create_rejects_bad_dags(self):
        prg = boot()
        bad = [
            [],  # empty
            [WorkflowStep(name="a", image="i", chip_count=1),
             WorkflowStep(name="a", image="i", chip_count=1)],  # dup names
            [WorkflowStep(name="a", image="i", chip_count=1,
                          deps=["ghost"])],  # unknown dep
            [WorkflowStep(name="a", image="i", chip_count=1, deps=["b"]),
             WorkflowStep(name="b", image="i", chip_count=1,
                          deps=["a"])],  # cycle
            [WorkflowStep(name="a", image="", chip_count=1)],  # no image
            [WorkflowStep(name="a", image="i")],  # no chips/accelerator
            [WorkflowStep(name="a", image="i", kind="promote")],  # no svc
            [WorkflowStep(name="a", image="i", kind="teleport",
                          chip_count=1)],  # unknown kind
        ]
        for steps in bad:
            with pytest.raises(errors.BadRequest):
                create_wf(prg, steps=steps)
        with pytest.raises(errors.BadRequest):
            create_wf(prg, priority_class="gold")
        with pytest.raises(errors.BadRequest):
            create_wf(prg, cron_catchup="rewind")
        assert prg.workflow_versions.snapshot() == {}

    def test_double_create_and_missing_lookup_are_typed(self):
        prg = boot()
        create_wf(prg)
        with pytest.raises(errors.WorkflowExisted):
            create_wf(prg)
        with pytest.raises(errors.WorkflowNotExist):
            prg.workflow.workflow_info("ghost")
        with pytest.raises(errors.WorkflowNotExist):
            prg.workflow.delete_workflow("ghost")


class TestWorkflowFamily:
    """The tier-1 lifecycle pin: DAG to completion, artifact binds,
    retry/backoff, past-budget terminal settlement, promote, delete."""

    def test_linear_dag_runs_to_success(self):
        prg = boot()
        out = create_wf(prg, binds=["/mnt/artifacts:/artifacts"])
        assert out["phase"] == "running"
        assert [s["state"] for s in out["steps"]] == ["launching", "pending"]

        prg.wq.replay_journal(include_local=True)
        # the train gang is a real job at the workflow's class, with the
        # shared artifact bind and the durable owner/run markers
        jb = step_base("pipe", 0, 0)
        jst = prg.store.get_job(f"{jb}-{prg.job_versions.get(jb)}")
        assert jst.binds == ["/mnt/artifacts:/artifacts"]
        assert f"{WORKFLOW_OWNER_ENV}=pipe" in jst.env
        assert f"{WORKFLOW_RUN_ENV}=0" in jst.env
        info = prg.workflow.workflow_info("pipe")
        assert info["steps"][0]["state"] == "running"
        assert info["steps"][0]["jobPhase"] == "running"
        assert info["steps"][1]["state"] == "pending"  # deps unmet

        info = drive(prg, "pipe")
        assert info["phase"] == "succeeded"
        assert all(s["state"] == "succeeded" for s in info["steps"])
        assert info["lastTransition"]["to"] == "succeeded"
        # finished gangs are GC'd: a terminal workflow owns nothing
        assert prg.job_versions.snapshot() == {}
        assert oracle(prg) == []
        kinds = {e["event"] for e in prg.workflow.events_view()}
        assert {"workflow-created", "workflow-step-succeeded"} <= kinds
        # list/summary view
        summ = prg.workflow.list_workflows()
        assert [s["name"] for s in summ] == ["pipe-0"]
        assert summ[0]["steps"] == {"train": "succeeded",
                                    "evaluate": "succeeded"}

    def test_failed_step_retries_with_fresh_idempotency_key(self):
        prg = boot(workflow_backoff_base_s=0.0, workflow_backoff_max_s=0.0)
        create_wf(prg, steps=[WorkflowStep(name="solo", image="jax",
                                           chip_count=1)])
        pump(prg, 1)
        prg.job_svc.fail_job(step_base("pipe", 0, 0), "injected boom")
        prg.workflow.tick()  # verdict: failed → retry (attempt 1)
        info = prg.workflow.workflow_info("pipe")
        assert info["phase"] == "running"
        assert info["steps"][0]["attempts"] == 1
        assert "injected boom" in info["steps"][0]["error"]
        info = drive(prg, "pipe")
        assert info["phase"] == "succeeded"
        assert info["steps"][0]["attempts"] == 1  # carried through success
        assert prg.metrics.counter_value(
            "workflow_step_retries_total", {"workflow": "pipe"}) == 1.0
        assert oracle(prg) == []

    def test_past_budget_settles_terminal_failed_and_frees_everything(self):
        prg = boot(workflow_backoff_base_s=0.0, workflow_backoff_max_s=0.0)
        create_wf(prg, steps=[
            WorkflowStep(name="doomed", image="jax", chip_count=1,
                         max_retries=0),
            WorkflowStep(name="never", image="jax", chip_count=1,
                         deps=["doomed"]),
        ])
        pump(prg, 1)
        prg.job_svc.fail_job(step_base("pipe", 0, 0), "oom")
        prg.workflow.tick()
        info = prg.workflow.workflow_info("pipe")
        assert info["phase"] == "failed"
        assert info["steps"][0]["state"] == "failed"
        assert info["steps"][1]["state"] == "pending"  # never launched
        assert "doomed" in info["lastTransition"]["reason"]
        # a poisoned pipeline must never pin chips
        assert prg.job_versions.snapshot() == {}
        assert prg.metrics.counter_value(
            "workflow_runs_completed_total",
            {"workflow": "pipe", "result": "failed"}) == 1.0
        assert oracle(prg) == []
        # terminal is terminal: further ticks do nothing
        prg.workflow.tick()
        assert prg.workflow.workflow_info("pipe")["phase"] == "failed"

    def test_promote_rolls_service_through_update_machinery(self):
        prg = boot()
        prg.serving.create_service(ServiceCreate(
            service_name="web", image_name="serve", chips_per_replica=1,
            replicas=1, max_replicas=2))
        create_wf(prg, steps=[
            WorkflowStep(name="train", image="jax:train", chip_count=1),
            WorkflowStep(name="promote", kind="promote", deps=["train"],
                         service="web", image="model:v2"),
        ])
        info = drive(prg, "pipe")
        assert info["phase"] == "succeeded"
        assert prg.serving.service_info("web")["image"] == "model:v2"
        # the replica rolled exactly once (version 0 → 1)
        assert prg.job_versions.get("web.r0") == 1
        assert oracle(prg) == []

    def test_delete_tears_down_mid_flight(self):
        prg = boot()
        create_wf(prg)
        pump(prg, 1)  # train gang up
        assert prg.job_versions.snapshot() != {}
        prg.workflow.delete_workflow("pipe")
        assert prg.workflow_versions.snapshot() == {}
        assert prg.job_versions.snapshot() == {}
        assert prg.store.history(Resource.WORKFLOWS, "pipe") == []
        assert oracle(prg) == []
        with pytest.raises(errors.WorkflowNotExist):
            prg.workflow.workflow_info("pipe")


class TestCronSemantics:
    """Virtual clock only — no sleeps. Interval 100s throughout."""

    def _boot_cron(self, catchup="skip", **cfg_kw):
        clock = {"now": 1000.0}
        prg = boot(**cfg_kw)
        prg.workflow._clock = lambda: clock["now"]
        create_wf(prg, "cronwf",
                  steps=[WorkflowStep(name="pulse", image="jax",
                                      chip_count=1)],
                  cron_interval_s=100.0, cron_catchup=catchup)
        return prg, clock

    def test_overlapping_run_suppressed_and_schedule_realigned(self):
        prg, clock = self._boot_cron()
        pump(prg, 1)  # run 0 in flight
        clock["now"] += 250.0  # two boundaries elapse mid-run
        prg.workflow.tick()
        info = prg.workflow.workflow_info("cronwf")
        assert info["run"] == 0 and info["phase"] == "running"
        assert info["cron"]["suppressedTicks"] == 2
        assert info["cron"]["lastFireTs"] == 1200.0  # realigned
        # the backlog never bursts when the run ends
        info = drive(prg, "cronwf")
        assert info["phase"] == "succeeded" and info["run"] == 0
        clock["now"] = 1299.0
        prg.workflow.tick()
        assert prg.workflow.workflow_info("cronwf")["run"] == 0
        clock["now"] = 1301.0  # next boundary: an ordinary on-time fire
        prg.workflow.tick()
        info = prg.workflow.workflow_info("cronwf")
        assert info["run"] == 1 and info["phase"] == "running"
        assert info["cron"]["firedRuns"] == 1

    def test_missed_ticks_skip_policy_fires_nothing(self):
        prg, clock = self._boot_cron(catchup="skip")
        assert drive(prg, "cronwf")["phase"] == "succeeded"
        clock["now"] += 350.0  # daemon "down" across 3 boundaries
        prg.workflow.tick()
        info = prg.workflow.workflow_info("cronwf")
        assert info["run"] == 0 and info["phase"] == "succeeded"
        assert info["cron"]["skippedTicks"] == 3
        assert info["cron"]["lastFireTs"] == 1300.0  # next future boundary
        clock["now"] += 100.0
        prg.workflow.tick()
        assert prg.workflow.workflow_info("cronwf")["run"] == 1

    def test_missed_ticks_fire_once_catches_up_across_restart(self):
        kv, rt = MemoryKV(), FakeRuntime()
        clock = {"now": 1000.0}
        prg = boot(kv=kv, runtime=rt)
        prg.workflow._clock = lambda: clock["now"]
        create_wf(prg, "cronwf",
                  steps=[WorkflowStep(name="pulse", image="jax",
                                      chip_count=1)],
                  cron_interval_s=100.0, cron_catchup="fire_once")
        assert drive(prg, "cronwf")["phase"] == "succeeded"

        # the daemon dies for 3.5 intervals; a fresh one catches up with
        # exactly ONE run covering every missed boundary
        clock["now"] += 350.0
        prg2 = boot(kv=kv, runtime=rt)
        prg2.workflow._clock = lambda: clock["now"]
        prg2.workflow.tick()
        info = prg2.workflow.workflow_info("cronwf")
        assert info["run"] == 1 and info["phase"] == "running"
        assert info["cron"]["firedRuns"] == 1
        assert info["cron"]["skippedTicks"] == 2  # folded into the one run
        prg2.workflow.tick()  # no double fire on the same boundaries
        assert prg2.workflow.workflow_info("cronwf")["run"] == 1
        info = drive(prg2, "cronwf")
        assert info["phase"] == "succeeded" and info["run"] == 1
        assert oracle(prg2) == []

    def test_disable_mid_flight_finishes_run_fires_nothing(self):
        prg, clock = self._boot_cron()
        pump(prg, 1)  # run 0 in flight
        prg.workflow.patch_workflow("cronwf",
                                    WorkflowPatch(cron_enabled=False))
        info = drive(prg, "cronwf")  # the current run still finishes
        assert info["phase"] == "succeeded"
        clock["now"] += 1000.0
        prg.workflow.tick()
        info = prg.workflow.workflow_info("cronwf")
        assert info["run"] == 0 and info["cron"]["firedRuns"] == 0
        # re-enable: the dark stretch is governed by the catch-up policy
        prg.workflow.patch_workflow("cronwf",
                                    WorkflowPatch(cron_enabled=True))
        prg.workflow.tick()  # skip: realigns, fires nothing
        info = prg.workflow.workflow_info("cronwf")
        assert info["run"] == 0 and info["cron"]["skippedTicks"] >= 10
        clock["now"] += 100.0
        prg.workflow.tick()
        assert prg.workflow.workflow_info("cronwf")["run"] == 1

    def test_patch_validates_and_fences_versions(self):
        prg, _ = self._boot_cron()
        with pytest.raises(errors.BadRequest):
            prg.workflow.patch_workflow(
                "cronwf", WorkflowPatch(cron_catchup="rewind"))
        with pytest.raises(errors.BadRequest):
            prg.workflow.patch_workflow(
                "cronwf", WorkflowPatch(cron_interval_s=-5.0))
        with pytest.raises(errors.VersionNotMatch):
            prg.workflow.patch_workflow(
                "cronwf-7", WorkflowPatch(cron_enabled=False))


#: every workflow.* crash point and the flow that traverses it — asserted
#: against WORKFLOW_CRASH_POINTS by test_chaos's coverage matrix
WORKFLOW_CASES = (
    ("workflow.create.after_record", "create"),
    ("workflow.enqueue_step", "create"),
    ("workflow.after_launch", "launch"),
    ("workflow.after_complete_marker", "complete"),
    ("workflow.after_promote", "promote"),
    ("workflow.cron_fire", "cron"),
    ("workflow.delete.after_mark", "delete"),
)


class TestWorkflowChaos:
    """Kill the daemon at every workflow.* crash point; a fresh Program
    over the same store + runtime must reconcile the DAG forward to
    completion (or finish the delete), with every step effect applied
    exactly once, zero orphan gangs, and a fixpoint second sweep."""

    def _drive_to_crash(self, prg, flow, clock):
        if flow == "create":
            create_wf(prg)
        elif flow == "launch":
            create_wf(prg)
            prg.wq.replay_journal(include_local=True)
        elif flow == "complete":
            create_wf(prg)
            prg.wq.replay_journal(include_local=True)
            prg.job_svc.mark_gang_completed(step_base("pipe", 0, 0))
            prg.workflow.tick()  # journals the completion record
            prg.wq.replay_journal(include_local=True)
        elif flow == "promote":
            prg.serving.create_service(ServiceCreate(
                service_name="web", image_name="serve", chips_per_replica=1,
                replicas=1, max_replicas=2))
            create_wf(prg, steps=[
                WorkflowStep(name="promote", kind="promote",
                             service="web", image="model:v2")])
            prg.wq.replay_journal(include_local=True)
        elif flow == "cron":
            create_wf(prg, steps=[WorkflowStep(name="pulse", image="jax",
                                               chip_count=1)],
                      cron_interval_s=100.0, cron_catchup="fire_once")
            assert drive(prg, "pipe")["phase"] == "succeeded"
            clock["now"] += 250.0
            prg.workflow.tick()
        elif flow == "delete":
            create_wf(prg)
            prg.wq.replay_journal(include_local=True)
            prg.workflow.delete_workflow("pipe")
        else:  # pragma: no cover — keep the matrix exhaustive
            raise AssertionError(f"unmapped flow {flow}")

    @pytest.mark.parametrize("point,flow", WORKFLOW_CASES)
    def test_crash_converges_dag_to_completion(self, point, flow):
        kv, rt = MemoryKV(), FakeRuntime()
        clock = {"now": 1000.0}
        prg = boot(kv=kv, runtime=rt)
        prg.workflow._clock = lambda: clock["now"]
        with armed(point):
            with pytest.raises(SimulatedCrash):
                self._drive_to_crash(prg, flow, clock)

        # the daemon is dead; a fresh control plane boots over same state
        prg2 = boot(kv=kv, runtime=rt)
        prg2.workflow._clock = lambda: clock["now"]
        prg2.reconciler.reconcile()

        if flow == "delete":
            # teardown intent was durable: the sweep finished it
            assert prg2.workflow_versions.snapshot() == {}
            assert prg2.job_versions.snapshot() == {}
        else:
            info = drive(prg2, "pipe")
            assert info["phase"] == "succeeded", f"{point}: {info}"
            if flow == "promote":
                # the service keeps its (rolled) replica; the workflow
                # owns nothing. Belt (image comparison) + braces
                # (marker): the roll happened exactly once (v0 → v1)
                assert prg2.serving.service_info("web")["image"] == \
                    "model:v2"
                assert prg2.job_versions.snapshot() == {"web.r0": 1}
            else:
                assert prg2.job_versions.snapshot() == {}
            if flow == "cron":
                # the fire was durable before the kill: exactly one
                # catch-up run, never re-fired for the same boundaries
                assert info["run"] == 1
                assert info["cron"]["firedRuns"] == 1

        # exactly-once effects: no member container was ever created
        # twice across both daemons' lifetimes
        creates = created_names(rt)
        assert len(creates) == len(set(creates)), f"{point}: {creates}"
        problems = oracle(prg2)
        assert problems == [], f"{point}: {problems}"
        # journal drained; the repair is a fixpoint
        stats = prg2.wq.stats()
        assert stats["journal"]["pending"] == 0
        assert stats["journal"]["inflight"] == 0
        assert prg2.reconciler.reconcile()["actions"] == [], point


def boot_ha(kv, runtime, holder, clock) -> Program:
    """An HA fleet member over the shared KV + runtime: election on,
    writer subsystems follow the lease, virtual clock drives TTL expiry.
    The engine loop interval is 0 so writers stay inline-driven."""
    cfg = config_mod.Config(
        store_backend="memory", runtime_backend="fake",
        health_watch_interval=0, end_port=40099, host_probe_interval_s=0,
        job_supervise_interval=0, reconcile_interval=0,
        workflow_interval_s=0,
        leader_election=True, leader_ttl_s=30.0, leader_id=holder,
    )
    prg = Program(cfg, kv=kv, runtime=runtime,
                  leader_clock=lambda: clock["now"])
    prg.init()
    return prg


class TestWorkflowFailover:
    def test_leader_failover_mid_workflow_converges(self):
        """PR 7's two-Program shape, mid-workflow: a previous daemon
        journaled a step-launch record it never executed; leader A
        acquires (replaying it — the gang launches), then dies; standby B
        waits out the lease, acquires with a fresh epoch, and drives the
        DAG to completion — every step effect exactly once."""
        kv, rt = MemoryKV(), FakeRuntime()
        prg0 = boot(kv=kv, runtime=rt)
        create_wf(prg0)  # step 0 durably "launching", record journaled

        clock = {"now": 1000.0}
        a = boot_ha(kv, rt, "daemon-a", clock)
        with armed("leader.after_start_writers"):
            with pytest.raises(SimulatedCrash):
                a.leader_elector.step()
        # A's acquire replayed the dead daemon's journal before it died:
        # the train gang exists exactly once
        assert a.job_versions.get(step_base("pipe", 0, 0)) is not None

        b = boot_ha(kv, rt, "daemon-b", clock)
        b.leader_elector.step()
        assert not b.leader_elector.is_leader, "stole a live lease"
        deadline = json.loads(kv.get(keys.LEADER_LEASE_KEY))["deadline"]
        clock["now"] = deadline + 0.001
        b.leader_elector.step()
        assert b.leader_elector.is_leader
        assert b.leader_elector.epoch == 2

        info = drive(b, "pipe")
        assert info["phase"] == "succeeded"
        assert b.job_versions.snapshot() == {}
        # exactly-once across all three incarnations
        creates = created_names(rt)
        assert len(creates) == len(set(creates)), creates
        assert oracle(b) == []
        assert b.reconciler.reconcile()["actions"] == []
        # split-brain proof: the deposed leader's writes are fenced out
        with pytest.raises(errors.GuardFailed):
            a.kv.put("/apis/v1/fence-probe", "stale")


class TestPoisonQuarantine:
    """Satellite: a corrupt stored record quarantines its OWN family —
    loudly — while every other family (and the whole sweep) converges."""

    def test_corrupt_container_record_skips_family_not_sweep(self):
        kv = MemoryKV()
        prg = boot(kv=kv)
        # a healthy workflow mid-flight (its launch record pending) and a
        # container family whose record we then corrupt in place
        create_wf(prg, "pipe")
        from tpu_docker_api.schemas.container import ContainerRun
        prg.container_svc.run_container(ContainerRun(
            image_name="jax", container_name="bad", chip_count=1))
        kv.put(keys.version_key(Resource.CONTAINERS, "bad", 0),
               "{corrupt json")

        rep = prg.reconciler.reconcile()
        q = [a for a in rep["actions"]
             if a["action"] == "quarantine-poison-record"]
        assert [a["target"] for a in q] == ["bad-0"]
        assert "resource" in q[0] and q[0]["resource"] == "containers"
        assert prg.metrics.counter_value(
            "reconcile_quarantined_total",
            {"resource": "containers"}) == 1.0
        # the sweep kept going past the poison: the workflow's journaled
        # step record replayed and the DAG still converges
        info = drive(prg, "pipe")
        assert info["phase"] == "succeeded"
        problems = check_workflow_invariants(
            prg.store, prg.workflow_versions, prg.job_versions)
        problems += check_job_invariants(
            prg.pod, prg.pod_scheduler, prg.store, prg.job_versions)
        assert problems == []
        # steady state: the poison is skipped every sweep, never wedging
        acts = prg.reconciler.reconcile()["actions"]
        assert [a["action"] for a in acts] == ["quarantine-poison-record"]

    def test_corrupt_workflow_record_quarantined_others_advance(self):
        kv = MemoryKV()
        prg = boot(kv=kv)
        create_wf(prg, "good")
        create_wf(prg, "bad",
                  steps=[WorkflowStep(name="solo", image="jax",
                                      chip_count=1)])
        assert drive(prg, "bad")["phase"] == "succeeded"  # settle first:
        # no pending records reference the family we are about to poison
        kv.put(keys.version_key(Resource.WORKFLOWS, "bad", 0), "not json{")

        acts = prg.workflow.reconcile_workflows()
        q = [a for a in acts if a["action"] == "quarantine-poison-record"]
        assert [a["target"] for a in q] == ["bad-0"]
        assert prg.metrics.counter_value(
            "reconcile_quarantined_total",
            {"resource": "workflows"}) == 1.0
        # the good family still drives to completion
        info = drive(prg, "good")
        assert info["phase"] == "succeeded"


class TestDeadLetterRetryBudget:
    """Satellite: POST /dead-letters/retry is budgeted — each revival
    bumps a DURABLE per-record attempt count; past budget the retry is a
    typed refusal instead of an infinite operator crank."""

    def test_budget_exhausts_with_typed_refusal_and_survives_restart(self):
        kv = MemoryKV()
        prg = boot(kv=kv, queue_dead_letter_retry_budget=2)

        def boom(rec):
            raise RuntimeError("still broken")

        prg.wq.register("always_fail", boom)
        prg.wq.submit_record("always_fail", {})
        prg.wq.start()
        prg.wq.drain()
        letters = prg.wq.dead_letter_view()
        assert len(letters) == 1
        assert letters[0]["opRetries"] == 0 and letters[0]["retryable"]

        assert prg.wq.retry_dead_letters() == 1  # revival 1
        prg.wq.drain()
        assert prg.wq.dead_letter_view()[0]["opRetries"] == 1
        assert prg.wq.retry_dead_letters() == 1  # revival 2: budget spent
        prg.wq.drain()
        letters = prg.wq.dead_letter_view()
        assert letters[0]["opRetries"] == 2 and not letters[0]["retryable"]
        with pytest.raises(errors.RetryBudgetExhausted):
            prg.wq.retry_dead_letters()
        prg.wq.close()

        # the attempt count is durable: a fresh daemon still refuses
        prg2 = boot(kv=kv, queue_dead_letter_retry_budget=2)
        letters = prg2.wq.dead_letter_view()
        assert letters[0]["opRetries"] == 2 and not letters[0]["retryable"]
        prg2.wq.start()
        with pytest.raises(errors.RetryBudgetExhausted):
            prg2.wq.retry_dead_letters()
        prg2.wq.close()
        # the letter itself is still there (a refusal never loses data)
        assert len(prg2.wq.dead_letter_view()) == 1

    def test_mixed_set_retries_fresh_skips_exhausted(self):
        prg = boot(queue_dead_letter_retry_budget=1)

        def boom(rec):
            raise RuntimeError("boom")

        prg.wq.register("always_fail", boom)
        prg.wq.submit_record("always_fail", {"which": "a"})
        prg.wq.start()
        prg.wq.drain()
        assert prg.wq.retry_dead_letters() == 1  # a: budget now spent
        prg.wq.drain()
        prg.wq.submit_record("always_fail", {"which": "b"})
        prg.wq.drain()
        # a is past budget (skipped), b is fresh (requeued): n > 0 so the
        # call reports progress instead of raising
        assert prg.wq.retry_dead_letters() == 1
        prg.wq.drain()
        prg.wq.close()
        by_retries = sorted(r["opRetries"] for r in prg.wq.dead_letter_view())
        assert by_retries == [1, 1]


class TestHttpSurface:
    def test_workflow_routes_and_events(self):
        import urllib.request

        prg = boot()
        prg.start()
        port = prg.api_server.port

        def call(method, path, body=None):
            req = urllib.request.Request(
                f"http://127.0.0.1:{port}{path}", method=method,
                data=json.dumps(body).encode() if body is not None else None,
                headers={"Content-Type": "application/json"})
            with urllib.request.urlopen(req, timeout=10) as resp:
                return json.loads(resp.read())

        try:
            out = call("POST", "/api/v1/workflows", {
                "workflowName": "pipe",
                "binds": ["/mnt/artifacts:/artifacts"],
                "steps": [
                    {"name": "train", "image": "jax:train", "chipCount": 1},
                    {"name": "evaluate", "image": "jax:eval", "chipCount": 1,
                     "deps": ["train"]},
                ]})
            assert out["code"] == 200
            assert out["data"]["phase"] == "running"
            assert out["data"]["priorityClass"] == "batch"
            out = call("GET", "/api/v1/workflows")
            assert [w["name"] for w in out["data"]] == ["pipe-0"]
            out = call("PATCH", "/api/v1/workflows/pipe", {
                "cronIntervalS": 3600, "cronEnabled": True,
                "cronCatchup": "fire_once"})
            assert out["data"]["cron"]["intervalS"] == 3600.0
            bad = call("POST", "/api/v1/workflows", {
                "workflowName": "loop",
                "steps": [{"name": "a", "image": "i", "chipCount": 1,
                           "deps": ["a"]}]})
            assert bad["code"] == errors.BadRequest.code

            # drive the DAG over the live queue consumer (drain instead
            # of inline replay — the records run on the wq thread here)
            for _ in range(8):
                prg.wq.drain()
                info = prg.workflow.workflow_info("pipe")
                if info["phase"] != "running":
                    break
                for s in info["steps"]:
                    if s["state"] == "running" \
                            and s.get("jobPhase") == "running":
                        prg.job_svc.mark_gang_completed(s["job"])
                prg.workflow.tick()
            prg.wq.drain()
            info = call("GET", "/api/v1/workflows/pipe")["data"]
            assert info["phase"] == "succeeded"
            assert {s["name"]: s["state"] for s in info["steps"]} == \
                {"train": "succeeded", "evaluate": "succeeded"}
            events = call("GET", "/api/v1/events?limit=200")["data"]
            kinds = {e.get("event") for e in events}
            assert {"workflow-created", "workflow-step-succeeded"} <= kinds
            assert call("DELETE", "/api/v1/workflows/pipe")["code"] == 200
            out = call("GET", "/api/v1/workflows/pipe")
            assert out["code"] == errors.WorkflowNotExist.code
        finally:
            prg.stop()


class TestConfigValidation:
    def test_load_validates_workflow_keys(self, tmp_path):
        good = tmp_path / "good.toml"
        good.write_text('workflow_default_class = "production"\n'
                        "workflow_max_step_retries = 5\n"
                        "queue_dead_letter_retry_budget = 7\n")
        cfg = config_mod.load(str(good))
        assert cfg.workflow_default_class == "production"
        assert cfg.workflow_max_step_retries == 5
        assert cfg.queue_dead_letter_retry_budget == 7
        for bad in ('workflow_default_class = "gold"\n',
                    "workflow_interval_s = -1\n",
                    "workflow_max_step_retries = -1\n",
                    "workflow_max_step_retries = true\n",
                    "workflow_backoff_base_s = -0.5\n",
                    "workflow_backoff_base_s = 10.0\n"
                    "workflow_backoff_max_s = 1.0\n",
                    "queue_dead_letter_retry_budget = 0\n"):
            p = tmp_path / "bad.toml"
            p.write_text(bad)
            with pytest.raises(ValueError):
                config_mod.load(str(p))
