"""Pipeline parallelism tests (SURVEY.md §2.3 PP row).

The GPipe schedule is validated by equivalence: the pipelined forward/loss
over a pp-sharded mesh must match the plain scanned Llama forward bit-for-
tolerance — fill/drain indexing bugs show up as wrong microbatch routing and
break equality immediately. Backward is covered by a full train step.
"""

from __future__ import annotations

import dataclasses

import jax
import numpy as np
import pytest

#: JAX-compile heavy: excluded from the `-m 'not slow'` quick tier so it
#: fits its time budget; still runs in `make test` (the full suite)
pytestmark = pytest.mark.slow


from tpu_docker_api.models.llama import llama_init, llama_loss, llama_presets
from tpu_docker_api.parallel.mesh import MeshPlan, build_mesh
from tpu_docker_api.parallel.pipeline import (
    pipeline_loss,
    pipeline_rules,
)
from tpu_docker_api.parallel.sharding import LLAMA_RULES
from jax.sharding import PartitionSpec as P


def tiny_cfg(**kw):
    kw.setdefault("n_layers", 4)
    return dataclasses.replace(llama_presets()["tiny"], **kw)


class TestPipelineRules:
    def test_layer_rules_gain_pp_axis(self):
        rules = pipeline_rules(LLAMA_RULES)
        by_pattern = dict(rules)
        assert by_pattern["layers/attn/wq"] == P("pp", "fsdp", "tp")
        assert by_pattern["layers/mlp/w_down"] == P("pp", "tp", "fsdp")
        # non-layer rules untouched
        assert by_pattern["embed/tokens"] == P("tp", "fsdp")
        assert by_pattern["lm_head"] == P("fsdp", "tp")


class TestPipelineEquivalence:
    def test_matches_plain_forward(self):
        cfg = tiny_cfg()
        params = llama_init(cfg, jax.random.PRNGKey(0))
        tokens = jax.random.randint(jax.random.PRNGKey(1), (8, 17), 0,
                                    cfg.vocab_size, dtype="int32")
        ref = float(llama_loss(params, tokens, cfg))

        mesh = build_mesh(MeshPlan(dp=2, fsdp=1, tp=2, sp=1, pp=2))
        with mesh:
            got = float(jax.jit(
                lambda p, t: pipeline_loss(p, t, cfg, mesh, n_micro=4)
            )(params, tokens))
        np.testing.assert_allclose(got, ref, rtol=1e-4, atol=1e-4)

    def test_n_micro_equal_one_still_correct(self):
        cfg = tiny_cfg()
        params = llama_init(cfg, jax.random.PRNGKey(0))
        tokens = jax.random.randint(jax.random.PRNGKey(1), (2, 17), 0,
                                    cfg.vocab_size, dtype="int32")
        ref = float(llama_loss(params, tokens, cfg))
        mesh = build_mesh(MeshPlan(dp=1, fsdp=2, tp=1, sp=1, pp=4))
        with mesh:
            got = float(jax.jit(
                lambda p, t: pipeline_loss(p, t, cfg, mesh, n_micro=1)
            )(params, tokens))
        np.testing.assert_allclose(got, ref, rtol=1e-4, atol=1e-4)

    def test_bad_divisibility_raises(self):
        cfg = tiny_cfg(n_layers=3)
        params = llama_init(cfg, jax.random.PRNGKey(0))
        tokens = jax.random.randint(jax.random.PRNGKey(1), (4, 9), 0,
                                    cfg.vocab_size, dtype="int32")
        mesh = build_mesh(MeshPlan(dp=4, fsdp=1, tp=1, sp=1, pp=2))
        with pytest.raises(ValueError, match="not divisible by pp"):
            pipeline_loss(params, tokens, cfg, mesh, n_micro=2)
        cfg4 = tiny_cfg()
        params4 = llama_init(cfg4, jax.random.PRNGKey(0))
        with pytest.raises(ValueError, match="not divisible by n_micro"):
            pipeline_loss(params4, tokens, cfg4, mesh, n_micro=3)


class TestPipelineTraining:
    def test_full_train_step_with_pp_sharded_params(self):
        from tpu_docker_api.train.trainer import (
            create_train_state,
            make_train_step,
            synthetic_batch,
        )

        cfg = tiny_cfg()
        mesh = build_mesh(MeshPlan(dp=2, fsdp=1, tp=2, sp=1, pp=2))
        state, opt = create_train_state(
            cfg, mesh, jax.random.PRNGKey(0),
            rules=pipeline_rules(LLAMA_RULES))
        # layer weights actually sharded on pp
        spec = state.params["layers"]["attn"]["wq"].sharding.spec
        assert "pp" in str(spec)

        step = make_train_step(
            cfg, mesh, opt,
            loss_fn=lambda p, t: pipeline_loss(p, t, cfg, mesh, n_micro=4))
        tokens = synthetic_batch(jax.random.PRNGKey(1), 8, 16, cfg.vocab_size)
        losses = []
        for _ in range(3):
            state, metrics = step(state, tokens)
            losses.append(float(metrics["loss"]))
        assert all(np.isfinite(l) for l in losses)
        assert losses[-1] < losses[0]


class Test1F1BSchedule:
    """VERDICT r1 item 9: the hand-scheduled interleaved 1F1B must be loss-
    and grad-equal to autodiff GPipe (up to f32/bf16 reduction order)."""

    def _grads(self, cfg, mesh, tokens, n_micro):
        from tpu_docker_api.parallel.pipeline import pipeline_1f1b_grads

        params = llama_init(cfg, jax.random.PRNGKey(0))
        with mesh:
            loss_g, grads_g = jax.jit(jax.value_and_grad(
                lambda p, t: pipeline_loss(p, t, cfg, mesh, n_micro)
            ))(params, tokens)
            loss_f, grads_f = jax.jit(
                lambda p, t: pipeline_1f1b_grads(p, t, cfg, mesh, n_micro)
            )(params, tokens)
        return loss_g, grads_g, loss_f, grads_f

    def test_matches_gpipe_f32(self):
        cfg = tiny_cfg(dtype=jax.numpy.float32)
        tokens = jax.random.randint(jax.random.PRNGKey(1), (8, 17), 0,
                                    cfg.vocab_size, dtype="int32")
        mesh = build_mesh(MeshPlan(dp=2, fsdp=1, tp=2, sp=1, pp=2))
        loss_g, grads_g, loss_f, grads_f = self._grads(cfg, mesh, tokens, 4)
        np.testing.assert_allclose(float(loss_f), float(loss_g), rtol=1e-5)
        jax.tree_util.tree_map(
            lambda a, b: np.testing.assert_allclose(
                np.asarray(a), np.asarray(b), rtol=1e-4, atol=1e-5),
            grads_g, grads_f)

    def test_matches_gpipe_bf16(self):
        """Training dtype: grads are bf16, so agreement is to single-ulp
        reduction-order noise."""
        cfg = tiny_cfg()
        tokens = jax.random.randint(jax.random.PRNGKey(1), (8, 17), 0,
                                    cfg.vocab_size, dtype="int32")
        mesh = build_mesh(MeshPlan(dp=2, fsdp=1, tp=2, sp=1, pp=2))
        loss_g, grads_g, loss_f, grads_f = self._grads(cfg, mesh, tokens, 4)
        np.testing.assert_allclose(float(loss_f), float(loss_g), rtol=1e-4)
        jax.tree_util.tree_map(
            lambda a, b: np.testing.assert_allclose(
                np.asarray(a, np.float32), np.asarray(b, np.float32),
                rtol=3e-2, atol=2e-3),
            grads_g, grads_f)

    def test_deep_ring_stash_wraparound(self):
        """n_micro > 2·n_stages forces the 2S stash ring to wrap."""
        cfg = tiny_cfg(dtype=jax.numpy.float32)
        tokens = jax.random.randint(jax.random.PRNGKey(2), (10, 17), 0,
                                    cfg.vocab_size, dtype="int32")
        mesh = build_mesh(MeshPlan(dp=1, fsdp=1, tp=2, sp=1, pp=4))
        loss_g, grads_g, loss_f, grads_f = self._grads(cfg, mesh, tokens, 10)
        np.testing.assert_allclose(float(loss_f), float(loss_g), rtol=1e-5)
        jax.tree_util.tree_map(
            lambda a, b: np.testing.assert_allclose(
                np.asarray(a), np.asarray(b), rtol=1e-4, atol=1e-5),
            grads_g, grads_f)

    def test_train_step_via_grad_fn(self):
        from tpu_docker_api.parallel.pipeline import pipeline_1f1b_grads
        from tpu_docker_api.train.trainer import (
            create_train_state,
            make_train_step,
        )

        cfg = tiny_cfg()
        mesh = build_mesh(MeshPlan(dp=2, fsdp=1, tp=2, sp=1, pp=2))
        state, opt = create_train_state(cfg, mesh, jax.random.PRNGKey(0),
                                        rules=pipeline_rules(LLAMA_RULES))
        step = make_train_step(
            cfg, mesh, opt,
            grad_fn=lambda p, t: pipeline_1f1b_grads(p, t, cfg, mesh, 2))
        tokens = jax.random.randint(jax.random.PRNGKey(1), (4, 33), 0,
                                    cfg.vocab_size, dtype="int32")
        losses = []
        for _ in range(3):
            state, metrics = step(state, tokens)
            losses.append(float(metrics["loss"]))
        assert np.isfinite(losses).all()
        assert losses[-1] < losses[0]  # it learns

    def test_loss_fn_and_grad_fn_mutually_exclusive(self):
        from tpu_docker_api.train.trainer import make_train_step

        cfg = tiny_cfg()
        mesh = build_mesh(MeshPlan(dp=8))
        with pytest.raises(ValueError, match="not both"):
            make_train_step(cfg, mesh, None, loss_fn=lambda p, t: 0,
                            grad_fn=lambda p, t: (0, p))
