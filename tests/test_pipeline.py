"""Pipeline parallelism tests (SURVEY.md §2.3 PP row).

The GPipe schedule is validated by equivalence: the pipelined forward/loss
over a pp-sharded mesh must match the plain scanned Llama forward bit-for-
tolerance — fill/drain indexing bugs show up as wrong microbatch routing and
break equality immediately. Backward is covered by a full train step.
"""

from __future__ import annotations

import dataclasses

import jax
import numpy as np
import pytest

from tpu_docker_api.models.llama import llama_init, llama_loss, llama_presets
from tpu_docker_api.parallel.mesh import MeshPlan, build_mesh
from tpu_docker_api.parallel.pipeline import (
    pipeline_loss,
    pipeline_rules,
)
from tpu_docker_api.parallel.sharding import LLAMA_RULES
from jax.sharding import PartitionSpec as P


def tiny_cfg(**kw):
    kw.setdefault("n_layers", 4)
    return dataclasses.replace(llama_presets()["tiny"], **kw)


class TestPipelineRules:
    def test_layer_rules_gain_pp_axis(self):
        rules = pipeline_rules(LLAMA_RULES)
        by_pattern = dict(rules)
        assert by_pattern["layers/attn/wq"] == P("pp", "fsdp", "tp")
        assert by_pattern["layers/mlp/w_down"] == P("pp", "tp", "fsdp")
        # non-layer rules untouched
        assert by_pattern["embed/tokens"] == P("tp", "fsdp")
        assert by_pattern["lm_head"] == P("fsdp", "tp")


class TestPipelineEquivalence:
    def test_matches_plain_forward(self):
        cfg = tiny_cfg()
        params = llama_init(cfg, jax.random.PRNGKey(0))
        tokens = jax.random.randint(jax.random.PRNGKey(1), (8, 17), 0,
                                    cfg.vocab_size, dtype="int32")
        ref = float(llama_loss(params, tokens, cfg))

        mesh = build_mesh(MeshPlan(dp=2, fsdp=1, tp=2, sp=1, pp=2))
        with mesh:
            got = float(jax.jit(
                lambda p, t: pipeline_loss(p, t, cfg, mesh, n_micro=4)
            )(params, tokens))
        np.testing.assert_allclose(got, ref, rtol=1e-4, atol=1e-4)

    def test_n_micro_equal_one_still_correct(self):
        cfg = tiny_cfg()
        params = llama_init(cfg, jax.random.PRNGKey(0))
        tokens = jax.random.randint(jax.random.PRNGKey(1), (2, 17), 0,
                                    cfg.vocab_size, dtype="int32")
        ref = float(llama_loss(params, tokens, cfg))
        mesh = build_mesh(MeshPlan(dp=1, fsdp=2, tp=1, sp=1, pp=4))
        with mesh:
            got = float(jax.jit(
                lambda p, t: pipeline_loss(p, t, cfg, mesh, n_micro=1)
            )(params, tokens))
        np.testing.assert_allclose(got, ref, rtol=1e-4, atol=1e-4)

    def test_bad_divisibility_raises(self):
        cfg = tiny_cfg(n_layers=3)
        params = llama_init(cfg, jax.random.PRNGKey(0))
        tokens = jax.random.randint(jax.random.PRNGKey(1), (4, 9), 0,
                                    cfg.vocab_size, dtype="int32")
        mesh = build_mesh(MeshPlan(dp=4, fsdp=1, tp=1, sp=1, pp=2))
        with pytest.raises(ValueError, match="not divisible by pp"):
            pipeline_loss(params, tokens, cfg, mesh, n_micro=2)
        cfg4 = tiny_cfg()
        params4 = llama_init(cfg4, jax.random.PRNGKey(0))
        with pytest.raises(ValueError, match="not divisible by n_micro"):
            pipeline_loss(params4, tokens, cfg4, mesh, n_micro=3)


class TestPipelineTraining:
    def test_full_train_step_with_pp_sharded_params(self):
        from tpu_docker_api.train.trainer import (
            create_train_state,
            make_train_step,
            synthetic_batch,
        )

        cfg = tiny_cfg()
        mesh = build_mesh(MeshPlan(dp=2, fsdp=1, tp=2, sp=1, pp=2))
        state, opt = create_train_state(
            cfg, mesh, jax.random.PRNGKey(0),
            rules=pipeline_rules(LLAMA_RULES))
        # layer weights actually sharded on pp
        spec = state.params["layers"]["attn"]["wq"].sharding.spec
        assert "pp" in str(spec)

        step = make_train_step(
            cfg, mesh, opt,
            loss_fn=lambda p, t: pipeline_loss(p, t, cfg, mesh, n_micro=4))
        tokens = synthetic_batch(jax.random.PRNGKey(1), 8, 16, cfg.vocab_size)
        losses = []
        for _ in range(3):
            state, metrics = step(state, tokens)
            losses.append(float(metrics["loss"]))
        assert all(np.isfinite(l) for l in losses)
        assert losses[-1] < losses[0]
