"""Int8 quantized serving: weight quantization, the int8 linear path, and
end-to-end generation quality on a quantized Llama tree."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from tpu_docker_api.infer.engine import GenerateConfig, make_generate_fn
from tpu_docker_api.infer.quantize import (
    quantize_llama_params,
    quantized_bytes,
)
from tpu_docker_api.models.llama import (
    llama_forward,
    llama_init,
    llama_presets,
)
from tpu_docker_api.ops.quant import (
    QuantizedLinear,
    dequantize_weight,
    int8_linear,
    linear,
    quantize_weight,
)


class TestQuantizedWeight:
    def test_roundtrip_error_bounded(self):
        w = jax.random.normal(jax.random.PRNGKey(0), (64, 32), jnp.float32)
        q = quantize_weight(w)
        assert q.w_int8.dtype == jnp.int8
        assert q.scale.shape == (32,)
        # per-channel absmax scaling: error <= scale/2 per element
        err = np.abs(np.asarray(dequantize_weight(q) - w))
        assert (err <= np.asarray(q.scale)[None, :] / 2 + 1e-6).all()

    def test_stacked_layer_weights_quantize_per_layer(self):
        w = jax.random.normal(jax.random.PRNGKey(0), (3, 16, 8), jnp.float32)
        q = quantize_weight(w)
        assert q.w_int8.shape == (3, 16, 8)
        assert q.scale.shape == (3, 8)
        # each layer matches quantizing it alone
        q0 = quantize_weight(w[1])
        np.testing.assert_array_equal(np.asarray(q.w_int8[1]),
                                      np.asarray(q0.w_int8))

    def test_int8_linear_approximates_matmul(self):
        x = jax.random.normal(jax.random.PRNGKey(1), (4, 64), jnp.bfloat16)
        w = jax.random.normal(jax.random.PRNGKey(2), (64, 32), jnp.float32)
        got = np.asarray(int8_linear(x, quantize_weight(w),
                                     jnp.float32))
        ref = np.asarray(x.astype(jnp.float32) @ w)
        # two int8 quantizations (weight + activation): ~1% relative error
        denom = np.abs(ref).mean()
        assert np.abs(got - ref).mean() / denom < 0.02

    def test_linear_raw_path_is_plain_matmul(self):
        x = jax.random.normal(jax.random.PRNGKey(1), (4, 8), jnp.bfloat16)
        w = jax.random.normal(jax.random.PRNGKey(2), (8, 8), jnp.bfloat16)
        np.testing.assert_array_equal(np.asarray(linear(x, w)),
                                      np.asarray(x @ w))

    def test_linear_out_dtype_widens_accumulation(self):
        x = jnp.ones((2, 8), jnp.bfloat16)
        w = jnp.ones((8, 4), jnp.bfloat16)
        y = linear(x, w, out_dtype=jnp.float32)
        assert y.dtype == jnp.float32


class TestQuantizedLlama:
    @pytest.fixture(scope="class")
    def setup(self):
        cfg = llama_presets()["tiny"]
        params = llama_init(cfg, jax.random.PRNGKey(0))
        return cfg, params, quantize_llama_params(params)

    def test_tree_shape(self, setup):
        _, params, qparams = setup
        assert isinstance(qparams["lm_head"], QuantizedLinear)
        assert isinstance(qparams["layers"]["mlp"]["w_gate"], QuantizedLinear)
        # embed/norms untouched
        assert qparams["embed"]["tokens"].dtype == params["embed"]["tokens"].dtype
        assert quantized_bytes(qparams) < quantized_bytes(params)

    def test_logits_track_float_model(self, setup):
        cfg, params, qparams = setup
        tokens = jax.random.randint(jax.random.PRNGKey(1), (2, 32), 0,
                                    cfg.vocab_size, dtype=jnp.int32)
        ref = np.asarray(llama_forward(params, tokens, cfg))
        got = np.asarray(llama_forward(qparams, tokens, cfg))
        # cosine similarity per position: int8 serving tracks the float model
        ref_f = ref.reshape(-1, ref.shape[-1])
        got_f = got.reshape(-1, got.shape[-1])
        cos = (ref_f * got_f).sum(-1) / (
            np.linalg.norm(ref_f, axis=-1) * np.linalg.norm(got_f, axis=-1)
            + 1e-9)
        assert cos.min() > 0.98, f"min cosine {cos.min()}"

    def test_generate_runs_quantized(self, setup):
        cfg, _, qparams = setup
        gen = GenerateConfig(max_new_tokens=8, temperature=0.0, max_seq=64)
        fn = make_generate_fn(cfg, gen, mesh=None)
        prompt = jax.random.randint(jax.random.PRNGKey(2), (2, 16), 0,
                                    cfg.vocab_size, dtype=jnp.int32)
        out = fn(qparams, prompt, jax.random.PRNGKey(3))
        assert out["tokens"].shape == (2, 8)
        assert (np.asarray(out["tokens"]) >= 0).all()

    def test_sharded_quantized_serving(self, setup):
        """param_shardings expands QuantizedLinear into per-child specs
        (int8 weight + rank-reduced scales), so quantized trees device_put
        onto a tp/fsdp mesh and generate under it."""
        from tpu_docker_api.parallel.mesh import MeshPlan, build_mesh
        from tpu_docker_api.parallel.sharding import param_shardings

        cfg, _, qparams = setup
        mesh = build_mesh(MeshPlan(dp=2, fsdp=2, tp=2, sp=1))
        sh = param_shardings(qparams, mesh)
        q_sh = sh["layers"]["mlp"]["w_gate"]
        assert q_sh.w_int8.spec == jax.sharding.PartitionSpec(
            None, "fsdp", "tp")
        assert q_sh.scale.spec == jax.sharding.PartitionSpec(None, "tp")
        qp = jax.device_put(qparams, sh)
        gen = GenerateConfig(max_new_tokens=4, temperature=0.0, max_seq=64)
        fn = make_generate_fn(cfg, gen, mesh=mesh)
        prompt = jax.random.randint(jax.random.PRNGKey(5), (4, 16), 0,
                                    cfg.vocab_size, dtype=jnp.int32)
        out = fn(qp, prompt, jax.random.PRNGKey(6))
        assert out["tokens"].shape == (4, 4)

    def test_greedy_tokens_mostly_agree(self, setup):
        """Greedy decode on quantized vs float weights: the argmax should
        agree for most steps on a tiny random model (loose bound — random
        logits are near-uniform, the hardest case for quantization)."""
        cfg, params, qparams = setup
        gen = GenerateConfig(max_new_tokens=16, temperature=0.0, max_seq=64)
        fn = make_generate_fn(cfg, gen, mesh=None)
        prompt = jax.random.randint(jax.random.PRNGKey(4), (1, 8), 0,
                                    cfg.vocab_size, dtype=jnp.int32)
        a = np.asarray(fn(params, prompt, jax.random.PRNGKey(0))["tokens"])
        b = np.asarray(fn(qparams, prompt, jax.random.PRNGKey(0))["tokens"])
        assert (a == b).mean() > 0.5


class TestFusedProjections:
    """fuse_llama_projections: one w_qkv / w_gu dispatch must reproduce
    the unfused tree — bit-exact on the int8 path (same activation
    quantization, concatenated out-channels)."""

    def test_int8_fused_generate_bit_exact(self):
        from tpu_docker_api.infer.engine import (
            GenerateConfig, make_generate_fn)
        from tpu_docker_api.infer.quantize import (
            fuse_llama_projections, quantize_llama_params)
        from tpu_docker_api.models.llama import llama_init, llama_presets

        cfg = llama_presets()["tiny"]
        qparams = quantize_llama_params(
            llama_init(cfg, jax.random.PRNGKey(0)))
        fused = fuse_llama_projections(qparams)
        fn = make_generate_fn(cfg, GenerateConfig(
            max_new_tokens=8, temperature=0.0, max_seq=64))
        prompt = jnp.asarray([[3, 1, 4, 1, 5]], jnp.int32)
        a = fn(qparams, prompt, jax.random.PRNGKey(1))
        b = fn(fused, prompt, jax.random.PRNGKey(1))
        np.testing.assert_array_equal(np.asarray(a["tokens"]),
                                      np.asarray(b["tokens"]))

    def test_bf16_fused_forward_exact(self):
        from tpu_docker_api.infer.quantize import fuse_llama_projections
        from tpu_docker_api.models.llama import (
            llama_forward, llama_init, llama_presets)

        cfg = llama_presets()["tiny"]
        params = llama_init(cfg, jax.random.PRNGKey(0))
        fused = fuse_llama_projections(params)
        toks = jax.random.randint(jax.random.PRNGKey(1), (2, 12), 0,
                                  cfg.vocab_size, dtype=jnp.int32)
        np.testing.assert_allclose(
            np.asarray(llama_forward(fused, toks, cfg)),
            np.asarray(llama_forward(params, toks, cfg)),
            rtol=1e-5, atol=1e-5)

    def test_fused_through_slot_engine(self):
        from tpu_docker_api.infer.quantize import fuse_llama_projections
        from tpu_docker_api.infer.slots import SlotEngine
        from tpu_docker_api.models.llama import llama_init, llama_presets

        cfg = llama_presets()["tiny"]
        params = llama_init(cfg, jax.random.PRNGKey(7))
        engines = [SlotEngine(cfg, p, slots=2, max_seq=96, chunk=4)
                   for p in (params, fuse_llama_projections(params))]
        handles = [e.submit([2, 7, 1], 8) for e in engines]
        for e, h in zip(engines, handles):
            while not h.done():
                e.step()
        assert (handles[0].result(0)["tokens"]
                == handles[1].result(0)["tokens"])
