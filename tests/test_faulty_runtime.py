"""FaultyRuntime plan mechanics: scripted Nth-call faults, ambiguous
failures, latency, seeded probabilistic rules, and the pass-through seam."""

import pytest

from tpu_docker_api.runtime.fake import FakeRuntime
from tpu_docker_api.runtime.faulty import (
    FaultPlan,
    FaultRule,
    FaultyRuntime,
    InjectedFault,
    fail_nth,
)
from tpu_docker_api.runtime.spec import ContainerSpec


@pytest.fixture
def rt(tmp_path):
    inner = FakeRuntime(root=str(tmp_path))
    faulty = FaultyRuntime(inner, FaultPlan())
    yield faulty
    faulty.close()


def make(rt, name="t0"):
    rt.container_create(ContainerSpec(name=name, image="jax"))


class TestScriptedFaults:
    def test_fail_nth_fires_on_exactly_that_call(self, rt):
        make(rt)
        rt.add_rules([fail_nth("container_start", 2)])
        rt.container_start("t0")              # call 1: ok
        with pytest.raises(InjectedFault):
            rt.container_start("t0")          # call 2: injected
        rt.container_start("t0")              # call 3: rule burned out
        assert [c[2] for c in rt.calls if c[0] == "container_start"] == [
            "ok", "fail", "ok"]

    def test_fail_mode_has_no_effect(self, rt):
        make(rt)
        rt.add_rules([fail_nth("container_start", 1)])
        with pytest.raises(InjectedFault):
            rt.container_start("t0")
        assert not rt.inner.container_inspect("t0").running

    def test_ambiguous_mode_applies_effect_then_raises(self, rt):
        make(rt)
        rt.add_rules([fail_nth("container_start", 1, mode="ambiguous")])
        with pytest.raises(InjectedFault):
            rt.container_start("t0")
        assert rt.inner.container_inspect("t0").running  # effect landed

    def test_latency_mode_delays_but_succeeds(self, rt):
        make(rt)
        rt.add_rules([FaultRule(op="container_start", on_calls={1},
                                mode="latency", latency_s=0.01)])
        rt.container_start("t0")
        assert rt.container_inspect("t0").running
        assert ("container_start", "t0", "latency") in rt.calls

    def test_rule_times_forever(self, rt):
        rt.add_rules([FaultRule(op="container_list", times=-1)])
        for _ in range(3):
            with pytest.raises(InjectedFault):
                rt.container_list()

    def test_unknown_mode_rejected(self):
        with pytest.raises(ValueError):
            FaultRule(op="container_list", mode="explode")


class TestDeterminism:
    def _run_plan(self, tmp_path, seed):
        inner = FakeRuntime(root=str(tmp_path / f"s{seed}"))
        rt = FaultyRuntime(inner, FaultPlan(
            rules=[FaultRule(op="container_list", probability=0.5, times=-1)],
            seed=seed))
        pattern = []
        for _ in range(20):
            try:
                rt.container_list()
                pattern.append("ok")
            except InjectedFault:
                pattern.append("fault")
        rt.close()
        return pattern

    def test_same_seed_same_firing_pattern(self, tmp_path):
        a = self._run_plan(tmp_path / "a", seed=7)
        b = self._run_plan(tmp_path / "b", seed=7)
        assert a == b
        assert "fault" in a and "ok" in a  # p=0.5 over 20 draws

    def test_different_seed_different_pattern(self, tmp_path):
        assert (self._run_plan(tmp_path / "a", seed=1)
                != self._run_plan(tmp_path / "b", seed=2))


class TestSeam:
    def test_op_counts_and_journal(self, rt):
        make(rt)
        rt.container_start("t0")
        rt.container_inspect("t0")
        assert rt.op_count("container_create") == 1
        assert rt.op_count("container_start") == 1
        assert rt.op_count("container_stop") == 0
        assert rt.calls[0] == ("container_create", "t0", "ok")

    def test_backend_helpers_pass_through(self, rt):
        make(rt)
        rt.container_start("t0")
        rt.crash_container("t0")  # FakeRuntime-only helper
        info = rt.container_inspect("t0")
        assert not info.running and info.exit_code == 137

    def test_clear_rules(self, rt):
        rt.add_rules([FaultRule(op="container_list", times=-1)])
        with pytest.raises(InjectedFault):
            rt.container_list()
        rt.clear_rules()
        assert rt.container_list() == []
