"""Watch conformance across the three KV backends (docs/perf.md "Read
path").

One parametrized suite drives MemoryKV (in-process subscribers notified
under the mutation's lock hold), SqliteKV (changelog table written in the
SAME transaction as the data, tailed by indexed rev — including from a
second instance over the same file, the two-real-processes story), and
EtcdKV (native ``/v3/watch`` stream against the shared fake gateway,
tests/etcd_gateway.py). The contract under test is what the informer
(state/informer.py) builds on: list-then-watch loses nothing, revisions
are monotonic, ``delete_prefix`` expands per key, and any gap —
compaction, overflow — is a typed WatchLost, never a silent hole.
"""

import time

import pytest

from tpu_docker_api import errors
from tpu_docker_api.state.kv import (
    CountingKV,
    MemoryKV,
    SqliteKV,
    WatchEvent,
)

BACKENDS = ("memory", "sqlite", "etcd")


def drain(watch, want: int, timeout_s: float = 5.0) -> list[WatchEvent]:
    """Poll until ``want`` events arrived (tolerates per-backend delivery
    cadence: push for memory, poll for sqlite, stream for etcd)."""
    events: list[WatchEvent] = []
    deadline = time.monotonic() + timeout_s
    while len(events) < want and time.monotonic() < deadline:
        events.extend(watch.poll(0.1))
    return events


def expect_lost(watch, timeout_s: float = 5.0) -> errors.WatchLost:
    deadline = time.monotonic() + timeout_s
    while time.monotonic() < deadline:
        try:
            watch.poll(0.1)
        except errors.WatchLost as e:
            return e
    pytest.fail("watch never raised WatchLost")


@pytest.fixture(params=BACKENDS)
def kv(request, tmp_path):
    if request.param == "memory":
        yield MemoryKV()
    elif request.param == "sqlite":
        store = SqliteKV(str(tmp_path / "watch.db"))
        yield store
        store.close()
    else:
        requests = pytest.importorskip("requests")  # noqa: F841
        from etcd_gateway import start_gateway, stop_gateway

        from tpu_docker_api.state.kv import EtcdKV

        server, _ = start_gateway()
        store = EtcdKV(f"http://127.0.0.1:{server.server_address[1]}")
        yield store
        store.close()
        stop_gateway(server)


class TestWatchConformance:
    def test_list_then_watch_loses_nothing(self, kv):
        """The informer handshake: a snapshot at rev R plus a watch from R
        covers every mutation exactly once — no gap, no double."""
        kv.put("/w/seed", "s0")
        snap, rev = kv.range_prefix_with_rev("/w/")
        assert snap == {"/w/seed": "s0"}
        w = kv.watch("/w/", rev)
        try:
            kv.put("/w/a", "1")
            kv.put("/w/b", "2")
            kv.delete("/w/a")
            events = drain(w, 3)
            # the seed predates the snapshot: it must NOT be replayed
            assert [(e.op, e.key, e.value) for e in events] == [
                ("put", "/w/a", "1"), ("put", "/w/b", "2"),
                ("delete", "/w/a", None)]
            assert all(e.rev > rev for e in events)
        finally:
            w.close()

    def test_revs_monotonic_across_mutations(self, kv):
        w = kv.watch("/w/", kv.current_rev())
        try:
            for i in range(5):
                kv.put(f"/w/k{i}", str(i))
            events = drain(w, 5)
            revs = [e.rev for e in events]
            assert revs == sorted(revs)
            # separate mutations never share a revision
            assert len(set(revs)) == 5
        finally:
            w.close()

    def test_prefix_filtering(self, kv):
        w = kv.watch("/w/in/", kv.current_rev())
        try:
            kv.put("/w/out", "x")
            kv.put("/w/in/a", "y")
            kv.put("/other", "z")
            events = drain(w, 1)
            assert [(e.op, e.key) for e in events] == [("put", "/w/in/a")]
            assert not w.poll(0.2)
        finally:
            w.close()

    def test_apply_batch_delivered_whole_in_order(self, kv):
        kv.put("/w/gone", "old")
        w = kv.watch("/w/", kv.current_rev())
        try:
            kv.apply([("put", "/w/a", "1"), ("put", "/w/b", "2"),
                      ("delete", "/w/gone")])
            events = drain(w, 3)
            assert [(e.op, e.key) for e in events] == [
                ("put", "/w/a"), ("put", "/w/b"), ("delete", "/w/gone")]
            # non-decreasing within the batch (etcd stamps one rev per txn;
            # memory/sqlite one per key — both satisfy the contract)
            revs = [e.rev for e in events]
            assert revs == sorted(revs)
        finally:
            w.close()

    def test_delete_prefix_expands_per_existing_key(self, kv):
        """A cache fed by this stream never needs a relist for a family
        purge: each existing key gets its own delete event, and deleting
        nothing emits nothing."""
        kv.put("/w/fam/a", "1")
        kv.put("/w/fam/b", "2")
        kv.put("/w/other", "3")
        w = kv.watch("/w/", kv.current_rev())
        try:
            kv.delete("/w/absent")          # no such key: no event
            kv.delete_prefix("/w/nothing/")  # empty prefix: no event
            kv.delete_prefix("/w/fam/")
            events = drain(w, 2)
            assert sorted((e.op, e.key) for e in events) == [
                ("delete", "/w/fam/a"), ("delete", "/w/fam/b")]
            assert not w.poll(0.2)
        finally:
            w.close()

    def test_compacted_start_rev_is_typed_watch_lost(self, kv, tmp_path):
        """A watcher resuming from a revision the backend no longer
        retains must get WatchLost (relist signal), never a silent gap.
        Per-backend retention knob: tiny log for memory/sqlite; the etcd
        case (server-side compaction) lives in TestEtcdWatchGateway."""
        if isinstance(kv, MemoryKV):
            store = MemoryKV(log_retain=4)
        elif isinstance(kv, SqliteKV):
            store = SqliteKV(str(tmp_path / "compact.db"),
                             log_retain=4, trim_every=1)
        else:
            pytest.skip("etcd compaction covered by "
                        "TestEtcdWatchGateway::"
                        "test_compaction_cancel_maps_to_watch_lost")
        for i in range(12):
            store.put(f"/w/c{i}", str(i))
        w = store.watch("/w/", start_rev=1)
        try:
            expect_lost(w)
        finally:
            w.close()
            store.close()


class TestEtcdWatchGateway:
    """Gateway-specific watch behavior (real HTTP chunked stream)."""

    @pytest.fixture()
    def pair(self):
        pytest.importorskip("requests")
        from etcd_gateway import start_gateway, stop_gateway

        from tpu_docker_api.state.kv import EtcdKV

        server, _ = start_gateway()
        kv = EtcdKV(f"http://127.0.0.1:{server.server_address[1]}")
        try:
            yield server, kv
        finally:
            kv.close()
            stop_gateway(server)

    def test_txn_events_share_one_revision(self, pair):
        server, kv = pair
        w = kv.watch("/t/", kv.current_rev())
        try:
            kv.apply([("put", "/t/a", "1"), ("put", "/t/b", "2")])
            events = drain(w, 2)
            assert len(events) == 2
            assert events[0].rev == events[1].rev  # one txn, one revision
        finally:
            w.close()

    def test_compaction_cancel_maps_to_watch_lost(self, pair):
        server, kv = pair
        for i in range(5):
            kv.put(f"/t/k{i}", str(i))
        server.compacted = 3
        w = kv.watch("/t/", start_rev=1)
        try:
            e = expect_lost(w)
            assert "compact" in str(e)
        finally:
            w.close()

    def test_close_tears_down_the_stream(self, pair):
        server, kv = pair
        w = kv.watch("/t/", 0)
        w.close()
        assert w.poll(0.1) == []  # closed: quiet, not an error

    def test_range_with_rev_tracks_header_revision(self, pair):
        server, kv = pair
        _, rev0 = kv.range_prefix_with_rev("/t/")
        kv.put("/t/a", "1")
        snap, rev1 = kv.range_prefix_with_rev("/t/")
        assert snap == {"/t/a": "1"}
        assert rev1 == rev0 + 1


class TestSqliteChangelog:
    """The same-transaction property that makes shared-file watch sound."""

    def test_failed_guard_logs_nothing(self, tmp_path):
        """Data write and changelog row are one transaction: a rolled-back
        apply leaves NEITHER (a watcher can never see a mutation that did
        not happen)."""
        store = SqliteKV(str(tmp_path / "atomic.db"))
        store.put("/s/seed", "v")
        rev = store.current_rev()
        w = store.watch("/s/", rev)
        with pytest.raises(errors.GuardFailed):
            store.apply([("put", "/s/x", "1")],
                        guards=[("value", "/s/seed", "WRONG")])
        assert store.current_rev() == rev
        assert w.poll(0.3) == []
        assert store.get_or("/s/x") is None
        w.close()
        store.close()

    def test_second_instance_over_same_file_sees_events(self, tmp_path):
        """Two SqliteKV instances over one file = two processes sharing
        the store (the HA verification shape): a watch opened on B sees
        A's committed mutations, in order, with revisions assigned by the
        shared AUTOINCREMENT — monotonic across writers."""
        path = str(tmp_path / "shared.db")
        a, b = SqliteKV(path), SqliteKV(path)
        w = b.watch("/s/", b.current_rev())
        try:
            a.put("/s/1", "x")
            b.put("/s/2", "y")   # interleaved writers
            a.delete("/s/1")
            events = drain(w, 3)
            assert [(e.op, e.key) for e in events] == [
                ("put", "/s/1"), ("put", "/s/2"), ("delete", "/s/1")]
            assert [e.rev for e in events] == sorted(e.rev for e in events)
        finally:
            w.close()
            a.close()
            b.close()


class TestWrapperDelegation:
    """CountingKV/FencedKV sit in the daemon's store stack: watch and the
    rev-snapshot read must pass through (and watch traffic must be counted
    as ONE open, not per event — the amortization the bench audits)."""

    def test_counting_kv_counts_watch_once(self):
        counting = CountingKV(MemoryKV())
        snap, rev = counting.range_prefix_with_rev("/c/")
        w = counting.watch("/c/", rev)
        try:
            for i in range(10):
                counting.put(f"/c/k{i}", str(i))
            assert len(drain(w, 10)) == 10
            counts = counting.snapshot()
            assert counts["watch"] == 1          # one stream open
            assert counts["range_prefix"] == 1   # the list half
        finally:
            w.close()

    def test_fenced_kv_watch_is_unfenced_read(self):
        from tpu_docker_api.service.leader import FencedKV

        inner = MemoryKV()
        fenced = FencedKV(inner, lambda: [("value", "/nope", "never")])
        # every WRITE through the fence loses its guard...
        with pytest.raises(errors.GuardFailed):
            fenced.put("/f/a", "1")
        # ...but watch + rev-listing are reads: they must work regardless
        snap, rev = fenced.range_prefix_with_rev("/f/")
        w = fenced.watch("/f/", rev)
        try:
            inner.put("/f/a", "1")
            assert [(e.op, e.key) for e in drain(w, 1)] == [("put", "/f/a")]
        finally:
            w.close()
