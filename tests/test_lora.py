"""LoRA fine-tuning (train/lora.py): frozen base + low-rank adapters —
identity at init, adapter-only gradients, sharding, adapter-only
checkpoints, and the CLI/serve loop."""

import json
import os
import subprocess
import sys

import jax
import jax.numpy as jnp
import numpy as np
import pytest

#: JAX-compile heavy: excluded from the `-m 'not slow'` quick tier so it
#: fits its time budget; still runs in `make test` (the full suite)
pytestmark = pytest.mark.slow


from tpu_docker_api.models.llama import llama_loss, llama_presets
from tpu_docker_api.parallel.mesh import MeshPlan, build_mesh
from tpu_docker_api.train.lora import (
    create_lora_state,
    init_base_params,
    lora_init,
    lora_resume_or_init,
    lora_specs,
    make_lora_train_step,
    merge_lora,
)
from tpu_docker_api.train.trainer import synthetic_batch

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
TINY = llama_presets()["tiny"]


@pytest.fixture(scope="module")
def mesh():
    return build_mesh(MeshPlan(dp=2, fsdp=2, tp=2, sp=1))


@pytest.fixture(scope="module")
def base(mesh):
    return init_base_params(TINY, mesh, jax.random.PRNGKey(0))


class TestInitAndMerge:
    def test_structure_and_identity(self, base):
        adapters = lora_init(base, rank=4, key=jax.random.PRNGKey(1))
        # default targets: wq + wv, stacked over layers
        assert set(adapters["layers"]["attn"]) == {"wq", "wv"}
        a = adapters["layers"]["attn"]["wq"]["a"]
        b = adapters["layers"]["attn"]["wq"]["b"]
        assert a.shape == (TINY.n_layers, TINY.dim, 4)
        assert b.shape == (TINY.n_layers, 4,
                           TINY.n_heads * TINY.head_dim)
        assert float(jnp.abs(b).max()) == 0.0  # B = 0 ⇒ merge is identity
        merged = merge_lora(base, adapters)
        for path in (("layers", "attn", "wq"), ("layers", "attn", "wv"),
                     ("layers", "mlp", "w_gate"), ("lm_head",)):
            m, o = merged, base
            for k in path:
                m, o = m[k], o[k]
            np.testing.assert_array_equal(np.asarray(m), np.asarray(o))

    def test_merge_changes_only_targets(self, base):
        adapters = lora_init(base, rank=2, key=jax.random.PRNGKey(1),
                             targets=("wq",))
        # give B mass so the merge is non-trivial
        adapters["layers"]["attn"]["wq"]["b"] = jnp.ones_like(
            adapters["layers"]["attn"]["wq"]["b"])
        merged = merge_lora(base, adapters, alpha=2.0)
        assert not np.array_equal(
            np.asarray(merged["layers"]["attn"]["wq"]),
            np.asarray(base["layers"]["attn"]["wq"]))
        np.testing.assert_array_equal(
            np.asarray(merged["layers"]["attn"]["wv"]),
            np.asarray(base["layers"]["attn"]["wv"]))
        # dtype preserved (bf16 base stays bf16)
        assert merged["layers"]["attn"]["wq"].dtype == \
            base["layers"]["attn"]["wq"].dtype

    def test_custom_targets_and_validation(self, base):
        adapters = lora_init(base, rank=2, key=jax.random.PRNGKey(1),
                             targets=("w_gate", "lm_head"))
        assert "lm_head" in adapters and "mlp" in adapters["layers"]
        assert "attn" not in adapters["layers"]
        with pytest.raises(ValueError, match="no parameters matched"):
            lora_init(base, rank=2, key=jax.random.PRNGKey(1),
                      targets=("nope",))
        with pytest.raises(ValueError, match="rank"):
            lora_init(base, rank=0, key=jax.random.PRNGKey(1))

    def test_specs_follow_base_axes(self, base):
        adapters = lora_init(base, rank=2, key=jax.random.PRNGKey(1),
                             targets=("wq", "wo", "lm_head"))
        from jax.sharding import PartitionSpec as P

        specs = lora_specs(adapters)
        # wq column-parallel P(None, fsdp, tp): A keeps in-axis, B out-axis
        assert specs["layers"]["attn"]["wq"]["a"] == P(None, "fsdp", None)
        assert specs["layers"]["attn"]["wq"]["b"] == P(None, None, "tp")
        # wo row-parallel P(None, tp, fsdp)
        assert specs["layers"]["attn"]["wo"]["a"] == P(None, "tp", None)
        assert specs["layers"]["attn"]["wo"]["b"] == P(None, None, "fsdp")
        # lm_head 2-D P(fsdp, tp)
        assert specs["lm_head"]["a"] == P("fsdp", None)
        assert specs["lm_head"]["b"] == P(None, "tp")


class TestTraining:
    def test_loss_descends_base_frozen(self, mesh, base):
        state, opt = create_lora_state(TINY, mesh, jax.random.PRNGKey(1),
                                       rank=4)
        step = make_lora_train_step(TINY, mesh, opt, base)
        batch = synthetic_batch(jax.random.PRNGKey(2), 8, 32,
                                TINY.vocab_size)
        base_before = jax.tree_util.tree_map(np.asarray, base)
        first = last = None
        for _ in range(12):
            state, metrics = step(state, batch)
            last = float(metrics["loss"])
            first = first if first is not None else last
        assert last < first, (first, last)
        # frozen means frozen: base arrays bit-identical after training
        jax.tree_util.tree_map(
            lambda before, after: np.testing.assert_array_equal(
                before, np.asarray(after)),
            base_before, base)
        # adapters actually moved (B left zero-init)
        assert float(jnp.abs(
            state.params["layers"]["attn"]["wq"]["b"]).max()) > 0
        # the trained merge changes the model's loss vs the raw base
        merged_loss = float(llama_loss(
            merge_lora(base, state.params), batch, TINY, mesh))
        base_loss = float(llama_loss(base, batch, TINY, mesh))
        assert merged_loss < base_loss


class TestCheckpoint:
    def test_adapter_roundtrip_and_resume(self, mesh, base, tmp_path):
        state, opt, mgr = lora_resume_or_init(
            tmp_path, TINY, mesh, jax.random.PRNGKey(1), rank=4)
        step = make_lora_train_step(TINY, mesh, opt, base)
        batch = synthetic_batch(jax.random.PRNGKey(2), 8, 32,
                                TINY.vocab_size)
        for _ in range(3):
            state, _ = step(state, batch)
        mgr.save(state)
        mgr.close()
        state2, _, mgr2 = lora_resume_or_init(
            tmp_path, TINY, mesh, jax.random.PRNGKey(9), rank=4)
        mgr2.close()
        assert int(state2.step) == 3
        jax.tree_util.tree_map(
            lambda a, b: np.testing.assert_array_equal(
                np.asarray(a), np.asarray(b)),
            state.params, state2.params)


class TestBaseRestore:
    def test_base_from_int8_optimizer_checkpoint(self, mesh, tmp_path):
        """restore_base_params is metadata-driven: a base pretrained
        with adamw-int8 (different opt_state pytree) loads params-only
        without knowing the writing optimizer."""
        from tpu_docker_api.train.checkpoint import resume_or_init
        from tpu_docker_api.train.lora import restore_base_params
        from tpu_docker_api.train.optim import adamw_int8
        from tpu_docker_api.train.trainer import make_train_step

        state, opt, mgr = resume_or_init(tmp_path, TINY, mesh,
                                         jax.random.PRNGKey(0),
                                         optimizer=adamw_int8())
        step = make_train_step(TINY, mesh, opt)
        batch = synthetic_batch(jax.random.PRNGKey(2), 8, 32,
                                TINY.vocab_size)
        state, _ = step(state, batch)
        mgr.save(state)
        mgr.close()
        base = restore_base_params(tmp_path, TINY, mesh)
        jax.tree_util.tree_map(
            lambda a, b: np.testing.assert_array_equal(
                np.asarray(a), np.asarray(b)),
            base, state.params)

    def test_missing_base_dir_is_an_error(self, mesh, tmp_path):
        """An explicit --lora-base-ckpt with no checkpoints must error,
        never silently fine-tune against a random base."""
        from tpu_docker_api.train.lora import restore_base_params

        with pytest.raises(FileNotFoundError):
            restore_base_params(tmp_path / "empty", TINY, mesh)


class TestCli:
    def _run(self, args, timeout=300):
        env = {**os.environ, "PYTHONPATH": REPO}
        return subprocess.run(
            [sys.executable, "-m", "tpu_docker_api.train",
             "--preset", "tiny", "--batch", "8", "--seq", "32",
             "--platform", "cpu", "--virtual-devices", "4",
             "--fsdp", "2", "--log-every", "2", *args],
            capture_output=True, text=True, env=env, timeout=timeout)

    def test_lora_train_and_resume(self, tmp_path):
        ckpt = tmp_path / "adapters"
        r = self._run(["--steps", "4", "--lora-rank", "2",
                       "--ckpt-dir", str(ckpt), "--save-every", "2"])
        assert r.returncode == 0, r.stdout + r.stderr
        lines = [json.loads(ln) for ln in r.stdout.splitlines()
                 if ln.startswith("{")]
        assert lines[-1] == {"event": "done", "step": 4}
        # resume continues from the saved adapter step
        r2 = self._run(["--steps", "6", "--lora-rank", "2",
                        "--ckpt-dir", str(ckpt), "--save-every", "100"])
        assert r2.returncode == 0, r2.stdout + r2.stderr
        steps = [json.loads(ln)["step"] for ln in r2.stdout.splitlines()
                 if ln.startswith("{") and "step" in ln]
        assert steps[-1] == 6 and min(steps) > 4


class TestQLoRA:
    """Unmerged (attached) forward + int8 frozen base — train/lora.py
    attach_lora / quantize_base, ops/quant.py LoraLinear + the
    straight-through int8_linear vjp."""

    def test_ste_gradient_flows_through_int8_base(self):
        """Naive autodiff through the activation round() would return
        zero dL/dx; the custom vjp must return the dequantized-matmul
        gradient exactly."""
        from tpu_docker_api.ops.quant import (
            dequantize_weight, int8_linear, quantize_weight)

        x = jax.random.normal(jax.random.PRNGKey(0), (4, 32), jnp.float32)
        q = quantize_weight(
            jax.random.normal(jax.random.PRNGKey(1), (32, 16),
                              jnp.float32))
        co = jax.random.normal(jax.random.PRNGKey(2), (4, 16), jnp.float32)
        gx = jax.grad(
            lambda x: jnp.sum(int8_linear(x, q, jnp.float32) * co))(x)
        ref = co @ dequantize_weight(q).T
        np.testing.assert_allclose(np.asarray(gx), np.asarray(ref),
                                   rtol=1e-5, atol=1e-5)
        assert float(jnp.abs(gx).max()) > 0

    def test_attach_identity_at_init(self, base):
        """B = 0 ⇒ the attached tree's loss equals the plain base
        forward bit-exactly (bf16 base) and the plain int8 forward
        bit-exactly (quantized base)."""
        from tpu_docker_api.train.lora import attach_lora, quantize_base

        adapters = lora_init(base, rank=4, key=jax.random.PRNGKey(1))
        batch = synthetic_batch(jax.random.PRNGKey(2), 4, 32,
                                TINY.vocab_size)
        assert float(llama_loss(attach_lora(base, adapters), batch,
                                TINY)) \
            == float(llama_loss(base, batch, TINY))
        qbase = quantize_base(base)
        assert float(llama_loss(attach_lora(qbase, adapters), batch,
                                TINY)) \
            == float(llama_loss(qbase, batch, TINY))

    def test_attached_matches_merged_loss_and_grads(self, base):
        """On a float base, the unmerged forward is the same function as
        the merged one up to float addition order — losses and adapter
        grads must agree closely after real training-sized updates."""
        from tpu_docker_api.train.lora import attach_lora

        adapters = lora_init(base, rank=4, key=jax.random.PRNGKey(1))
        # give B real values so the two paths actually differ from base
        adapters = jax.tree_util.tree_map(
            lambda x: x + 0.01 * jax.random.normal(
                jax.random.PRNGKey(3), x.shape, x.dtype), adapters)
        batch = synthetic_batch(jax.random.PRNGKey(2), 4, 32,
                                TINY.vocab_size)

        def loss_m(a):
            return llama_loss(merge_lora(base, a), batch, TINY)

        def loss_a(a):
            return llama_loss(attach_lora(base, a), batch, TINY)

        lm, gm = jax.value_and_grad(loss_m)(adapters)
        la, ga = jax.value_and_grad(loss_a)(adapters)
        np.testing.assert_allclose(float(lm), float(la), rtol=2e-2)
        jax.tree_util.tree_map(
            lambda a, b: np.testing.assert_allclose(
                np.asarray(a), np.asarray(b), rtol=0.25, atol=5e-3),
            gm, ga)

    def test_qlora_training_descends_int8_base_frozen(self, mesh, base):
        from tpu_docker_api.train.lora import quantize_base

        qbase = quantize_base(base)
        state, opt = create_lora_state(TINY, mesh, jax.random.PRNGKey(1),
                                       rank=4)
        step = make_lora_train_step(TINY, mesh, opt, qbase,
                                    forward="attached")
        batch = synthetic_batch(jax.random.PRNGKey(2), 8, 32,
                                TINY.vocab_size)
        first = last = None
        for _ in range(12):
            state, metrics = step(state, batch)
            last = float(metrics["loss"])
            first = first if first is not None else last
        assert last < first, (first, last)
        assert float(jnp.abs(
            state.params["layers"]["attn"]["wq"]["b"]).max()) > 0

    def test_merged_over_int8_base_raises(self, base):
        from tpu_docker_api.train.lora import quantize_base

        adapters = lora_init(base, rank=2, key=jax.random.PRNGKey(1))
        with pytest.raises(ValueError, match="unmerged"):
            merge_lora(quantize_base(base), adapters)

    def test_qlora_cli_then_attached_serving(self, tmp_path):
        """The round trip the verdict names: --qlora training writes
        adapter checkpoints; serve --quantize --lora-forward attached
        loads them over the SAME int8 base numerics and generates."""
        ckpt = tmp_path / "qlora"
        env = {**os.environ, "PYTHONPATH": REPO}
        r = subprocess.run(
            [sys.executable, "-m", "tpu_docker_api.train",
             "--preset", "tiny", "--batch", "4", "--seq", "32",
             "--platform", "cpu", "--virtual-devices", "1",
             "--steps", "3", "--log-every", "1",
             "--lora-rank", "2", "--qlora",
             "--ckpt-dir", str(ckpt), "--save-every", "3"],
            capture_output=True, text=True, env=env, timeout=600)
        assert r.returncode == 0, r.stdout + r.stderr
        from tests.test_serve import _post, _spawn_server

        p, port = _spawn_server(
            ["--preset", "tiny", "--max-seq", "64", "--quantize",
             "--lora-ckpt", str(ckpt), "--lora-rank", "2",
             "--lora-forward", "attached"])
        try:
            out = _post(port, "/generate",
                        {"tokens": [[1, 2, 3]], "maxNewTokens": 4,
                         "temperature": 0.0})
            assert len(out["tokens"][0]) == 4
        finally:
            p.terminate()
            p.wait(timeout=30)

    def test_qlora_with_chunked_ce(self, mesh):
        """The 8B-on-one-chip shape: int8 base + attached forward +
        chunked cross-entropy (the int8 lm_head dequantizes once per
        step for the chunk scan — hardware-found r4 bug)."""
        import dataclasses

        from tpu_docker_api.infer.quantize import synth_quantized_params
        from tpu_docker_api.train.lora import make_lora_train_step

        cfg = dataclasses.replace(TINY, loss_chunk_rows=32)
        base = synth_quantized_params(cfg)
        state, opt = create_lora_state(cfg, mesh, jax.random.PRNGKey(1),
                                       rank=4)
        step = make_lora_train_step(cfg, mesh, opt, base,
                                    forward="attached")
        batch = synthetic_batch(jax.random.PRNGKey(2), 8, 32,
                                TINY.vocab_size)
        losses = []
        for _ in range(4):
            state, m = step(state, batch)
            losses.append(float(m["loss"]))
        assert all(np.isfinite(l) for l in losses)
        assert losses[-1] < losses[0]
