"""Capacity-market admission tests (docs/robustness.md "Capacity market").

Queue-ordering invariants, pinned property-style:

- victim selection is deterministic: strictly-lower priority only, lowest
  priority first, then YOUNGEST first (largest submit seq — the paged.py
  seniority rule), stopping at the minimal feasible prefix;
- backfill never starves the head past ``admission_max_skips``: once the
  bound is hit the queue stalls behind the blocked entry even though later
  entries would fit;
- a preempted job re-admits before an equal-priority queued job, even one
  with an older submit seq;
- ``stop_job`` on queued/preempted DEQUEUES, ``delete_job`` purges the
  admission record, ``restart_job``/rescale on dormant phases reject;
- ``admission_enabled=false`` keeps the legacy hard refusal byte-for-byte,
  while enabled deployments answer capacity refusal with a queue position
  (and flag never-placeable asks ``queueable: false``);
- zero preemptions when holes suffice (backfill proven, not asserted), and
  whole-host asks blocked only by fragmentation defragment via migration.
"""

import json

import pytest

from tpu_docker_api import config as config_mod
from tpu_docker_api import errors
from tpu_docker_api.daemon import Program
from tpu_docker_api.runtime.fake import FakeRuntime
from tpu_docker_api.schemas.job import JobDelete, JobPatchChips, JobRun
from tpu_docker_api.service.invariants import (
    check_invariants,
    check_job_invariants,
)
from tpu_docker_api.state import keys
from tpu_docker_api.state.kv import MemoryKV


def boot(n_hosts: int = 1, admission_enabled: bool = True,
         max_skips: int = 4, kv=None, runtimes=None) -> Program:
    """A Program over a fake pod: single-host (8 chips) by default, or an
    n-host grid; the admission loop is disabled (interval 0) so tests
    drive ``admit_once`` inline."""
    kv = kv if kv is not None else MemoryKV()
    runtimes = runtimes or {f"h{i}": FakeRuntime() for i in range(n_hosts)}
    cfg = config_mod.Config(
        store_backend="memory", runtime_backend="fake",
        health_watch_interval=0, end_port=40099,
        admission_enabled=admission_enabled, admission_interval_s=0,
        admission_max_skips=max_skips,
        pod_hosts=[] if n_hosts == 1 else [
            {"host_id": f"h{i}", "address": f"10.0.0.{i + 1}",
             "grid_coord": [i, 0, 0],
             **({"local": True} if i == 0 else {"runtime_backend": "fake"})}
            for i in range(n_hosts)
        ],
    )
    prg = Program(cfg, kv=kv, runtime=runtimes["h0"],
                  pod_runtimes={h: r for h, r in runtimes.items()
                                if h != "h0"})
    prg.init()
    return prg


def run(prg, name, chips, klass="batch", **kw):
    return prg.job_svc.run_job(JobRun(
        image_name="jax", job_name=name, chip_count=chips,
        priority_class=klass, **kw))


def phase(prg, base):
    return prg.store.get_job(
        f"{base}-{prg.job_versions.get(base)}").phase


def oracle(prg) -> list[str]:
    problems = check_job_invariants(
        prg.pod, prg.pod_scheduler, prg.store, prg.job_versions)
    problems += check_invariants(
        prg.runtime, prg.store, prg.container_versions,
        prg.chip_scheduler, prg.port_scheduler,
        job_versions=prg.job_versions)
    return problems


class TestOrderingInvariants:
    def test_victim_selection_lowest_priority_then_youngest(self):
        """Deterministic victim order: preemptible before batch, youngest
        (largest submit seq) first within a class, and the selection stops
        at the minimal feasible prefix."""
        prg = boot(n_hosts=2)
        run(prg, "a", 4, "batch")          # seq 0 → h0
        run(prg, "b", 4, "preemptible")    # seq 1 → h0 (fills it)
        run(prg, "c", 4, "preemptible")    # seq 2 → h1
        victims = prg.admission._victims_for(
            prg.admission.weight("production"), 16, 1, "req")
        # 16 chips = both hosts fully free ⇒ every victim must go; the
        # ORDER is the contract: preemptible (c youngest, then b), batch last
        assert victims == ["c", "b", "a"]
        # a sub-host ask stops at the minimal prefix (freeing c suffices:
        # h1 then has 8 free for a 6-chip ask)
        assert prg.admission._victims_for(
            prg.admission.weight("production"), 6, 1, "req") == ["c"]
        # eligibility is STRICTLY lower weight: nothing sits below the
        # lowest class, so a preemptible requester can never preempt
        assert prg.admission._victims_for(
            prg.admission.weight("preemptible"), 4, 1, "req") == []

    def test_preempted_readmits_before_equal_priority_queued(self):
        """A preempted batch job outranks a QUEUED batch job with an older
        submit seq: it already held capacity once."""
        prg = boot(n_hosts=2)
        run(prg, "a", 16, "batch")                       # fills the pool
        assert run(prg, "b", 16, "batch")["phase"] == "queued"
        assert run(prg, "c", 16, "production")["phase"] == "queued"
        # production preempts a (the only strictly-lower victim) and places
        assert [o["job"] for o in prg.admission.admit_once()] == ["c"]
        assert phase(prg, "a") == "preempted"
        assert phase(prg, "b") == "queued"
        # free the pool: a re-admits ahead of b despite b's older seq
        prg.job_svc.delete_job("c", JobDelete(
            force=True, del_state_and_version_record=True))
        assert [o["job"] for o in prg.admission.admit_once()] == ["a"]
        assert phase(prg, "a") == "running"
        assert phase(prg, "b") == "queued"
        assert oracle(prg) == []

    def test_backfill_never_starves_past_max_skips(self):
        """EASY backfill with the starvation bound: small jobs may pass a
        blocked head at most ``admission_max_skips`` times; then the queue
        stalls behind it — capacity or not — until the head places."""
        prg = boot(n_hosts=1, max_skips=2)

        def free(name):
            prg.job_svc.delete_job(name, JobDelete(
                force=True, del_state_and_version_record=True))

        run(prg, "base0", 4, "production")
        run(prg, "blockA", 4, "production")   # pool now full
        assert run(prg, "bigjob", 8, "production")["phase"] == "queued"
        for i in range(3):
            assert run(prg, f"f{i}", 4, "batch")["phase"] == "queued"
        # two rounds of freed holes the head cannot use: each backfill
        # past it charges one durable skip
        free("blockA")
        assert [o["job"] for o in prg.admission.admit_once()] == ["f0"]
        free("f0")
        assert [o["job"] for o in prg.admission.admit_once()] == ["f1"]
        view = prg.admission.status_view()
        head = next(e for e in view["entries"] if e["name"] == "bigjob")
        assert head["skips"] == 2 and head["position"] == 1
        # the bound is hit: f2 WOULD fit the next hole, but the queue now
        # stalls behind the head — capacity or not — until it places
        free("f1")
        assert prg.admission.admit_once() == []
        assert phase(prg, "f2") == "queued"
        # capacity for the head returns: it places FIRST
        free("base0")
        assert [o["job"] for o in prg.admission.admit_once()] == ["bigjob"]
        assert phase(prg, "bigjob") == "running"
        free("bigjob")
        assert [o["job"] for o in prg.admission.admit_once()] == ["f2"]
        assert oracle(prg) == []

    def test_preempted_records_exempt_from_starvation_gate(self):
        """A max-skipped head stalls QUEUED work behind it — but never a
        preempted victim's re-admission: victims restore capacity they
        already held, and stranding them dormant on idle chips would be
        the market defeating itself."""
        prg = boot(n_hosts=1, max_skips=2)
        run(prg, "base", 4, "production")   # un-preemptable blocker
        run(prg, "low", 4, "preemptible")
        # park low as preempted (the state a failed post-preempt placement
        # leaves behind)
        assert prg.admission._preempt_one(
            "low", for_base="big",
            requester_weight=prg.admission.weight("production"))
        assert phase(prg, "low") == "preempted"
        # a blocked head (8 chips; only 4 free, base is equal-class) that
        # already exhausted its skip budget
        assert run(prg, "big", 8, "production")["phase"] == "queued"
        rec = next(r for r in prg.admission.records() if r.base == "big")
        rec.skips = 2
        prg.kv.put(rec.key(), rec.to_json())
        # the head still cannot place (only 8 free minus nothing — but low
        # re-admitting takes 4): the pass must re-admit LOW through the
        # gate rather than break before reaching it
        placed = [o["job"] for o in prg.admission.admit_once()]
        assert "low" in placed
        assert phase(prg, "low") == "running"
        assert oracle(prg) == []

    def test_zero_preemptions_when_holes_suffice(self):
        """Backfill proven, not asserted: free capacity admits queued work
        without touching any running gang."""
        prg = boot(n_hosts=1)
        run(prg, "low1", 4, "preemptible")
        run(prg, "low2", 4, "preemptible")       # pool now full
        assert run(prg, "hi", 4, "production")["phase"] == "queued"
        prg.job_svc.delete_job("low2", JobDelete(
            force=True, del_state_and_version_record=True))
        assert [o["job"] for o in prg.admission.admit_once()] == ["hi"]
        assert phase(prg, "hi") == "running"
        assert phase(prg, "low1") == "running"   # untouched
        assert prg.admission.status_view()["preemptionsTotal"] == 0


class TestPhaseOperations:
    def test_stop_dequeues_queued_job(self):
        prg = boot(n_hosts=1)
        run(prg, "fill", 8, "batch")
        assert run(prg, "waiting", 4, "batch")["phase"] == "queued"
        prg.job_svc.stop_job("waiting")
        assert prg.admission.records() == []
        assert phase(prg, "waiting") == "stopped"
        # capacity later returns; the stopped job must NOT place
        prg.job_svc.delete_job("fill", JobDelete(
            force=True, del_state_and_version_record=True))
        assert prg.admission.admit_once() == []
        assert phase(prg, "waiting") == "stopped"

    def test_stop_dequeues_preempted_job(self):
        prg = boot(n_hosts=1)
        run(prg, "low", 8, "preemptible")
        run(prg, "hi", 8, "production")
        prg.admission.admit_once()
        assert phase(prg, "low") == "preempted"
        prg.job_svc.stop_job("low")
        assert prg.admission.records() == []
        assert phase(prg, "low") == "stopped"
        assert oracle(prg) == []

    def test_delete_purges_admission_record(self):
        prg = boot(n_hosts=1)
        run(prg, "fill", 8, "batch")
        run(prg, "waiting", 4, "batch")
        prg.job_svc.delete_job("waiting", JobDelete(
            force=True, del_state_and_version_record=True))
        assert prg.admission.records() == []
        assert prg.job_versions.get("waiting") is None
        assert prg.kv.range_prefix(
            keys.family_prefix(keys.Resource.JOBS, "waiting")) == {}

    def test_restart_and_rescale_reject_dormant_phases(self):
        prg = boot(n_hosts=1)
        run(prg, "fill", 8, "preemptible")
        run(prg, "waiting", 4, "batch")
        with pytest.raises(errors.BadRequest, match="queued"):
            prg.job_svc.restart_job("waiting")
        with pytest.raises(errors.BadRequest, match="queued"):
            prg.job_svc.patch_job_chips("waiting", JobPatchChips(chip_count=2))
        run(prg, "hi", 8, "production")
        prg.admission.admit_once()
        assert phase(prg, "fill") == "preempted"
        with pytest.raises(errors.BadRequest, match="preempted"):
            prg.job_svc.restart_job("fill")

    def test_restart_rejected_after_stop_of_grantless_job(self):
        """A stopped job normally retains its grant for resume — but one
        stopped out of queued/preempted owns NOTHING: restarting its old
        members would double-bind chips the scheduler may have granted
        elsewhere. Both shapes must reject loudly."""
        prg = boot(n_hosts=1)
        run(prg, "low", 8, "preemptible")
        run(prg, "hi", 8, "production")
        prg.admission.admit_once()
        assert phase(prg, "low") == "preempted"
        prg.job_svc.stop_job("low")          # dequeue: stays stopped
        with pytest.raises(errors.BadRequest, match="slice grant"):
            prg.job_svc.restart_job("low")
        # ex-queued: stopped before ever placing — no members at all
        run(prg, "ghost", 4, "batch")
        assert phase(prg, "ghost") == "queued"
        prg.job_svc.stop_job("ghost")
        with pytest.raises(errors.BadRequest, match="never placed"):
            prg.job_svc.restart_job("ghost")
        assert oracle(prg) == []

    def test_priority_and_seniority_survive_rescale(self):
        """Class and submit seq are FAMILY identity: a rolling rescale's
        new version must keep them, or the rescaled gang would drop to
        the default class and become junior (preemptable by accident)."""
        prg = boot(n_hosts=1)
        run(prg, "svc", 2, "production")
        seq0 = prg.store.get_job("svc-0").submitted_seq
        prg.job_svc.patch_job_chips("svc", JobPatchChips(chip_count=4))
        st = prg.store.get_job(f"svc-{prg.job_versions.get('svc')}")
        assert st.version == 1
        assert st.priority_class == "production"
        assert st.submitted_seq == seq0

    def test_supervisor_leaves_dormant_gangs_alone(self):
        """A preempted gang's stopped members are the market's doing — the
        supervisor must not restart them (that would double-bind the freed
        capacity under the admitted job)."""
        prg = boot(n_hosts=1)
        run(prg, "low", 8, "preemptible")
        run(prg, "hi", 8, "production")
        prg.admission.admit_once()
        assert phase(prg, "low") == "preempted"
        prg.job_supervisor.poll_once()
        assert phase(prg, "low") == "preempted"
        low = prg.store.get_job(f"low-{prg.job_versions.get('low')}")
        assert all(not prg.runtime.container_inspect(c).running
                   for _, c, *_ in low.placements)
        assert oracle(prg) == []


class TestRefusalErgonomics:
    def test_disabled_keeps_legacy_hard_fail(self):
        """admission_enabled=false: the 10601 refusal is byte-for-byte
        today's — same type, no data payload, nothing journaled."""
        prg = boot(n_hosts=1, admission_enabled=False)
        run(prg, "fill", 8)
        with pytest.raises(errors.ChipNotEnough) as ei:
            run(prg, "more", 4)
        assert ei.value.data is None
        assert prg.kv.range_prefix(keys.ADMISSION_PREFIX) == {}
        assert prg.job_versions.get("more") is None
        # the envelope a client sees is the legacy error shape exactly
        from tpu_docker_api.api import response
        assert json.loads(response.error(
            ei.value.code, str(ei.value), data=ei.value.data)) == {
                "code": 10601, "msg": str(ei.value), "data": None}

    def test_enabled_returns_queue_position(self):
        prg = boot(n_hosts=1)
        run(prg, "fill", 8)
        out = run(prg, "q1", 4, "batch")
        assert out["phase"] == "queued"
        assert out["queueable"] is True
        assert out["queuePosition"] == 1
        out = run(prg, "q2", 4, "batch")
        assert out["queuePosition"] == 2
        # GET /jobs/{name} surfaces the queue state too
        info = prg.job_svc.get_job_info("q2")
        assert info["phase"] == "queued"
        assert info["queuePosition"] == 2
        assert info["priorityClass"] == "batch"

    def test_never_placeable_ask_flags_queueable_false(self):
        """An ask no amount of preemption can satisfy hard-fails even with
        admission enabled — flagged so clients can tell policy from
        capacity."""
        prg = boot(n_hosts=1)
        with pytest.raises(errors.ChipNotEnough) as ei:
            run(prg, "huge", 64)
        assert ei.value.data == {"queueable": False}
        assert prg.kv.range_prefix(keys.ADMISSION_PREFIX) == {}

    def test_unknown_priority_class_rejected(self):
        prg = boot(n_hosts=1)
        with pytest.raises(errors.BadRequest, match="priorityClass"):
            run(prg, "x", 2, "platinum")


class TestDefragmentation:
    def test_whole_host_ask_defragments_via_migration(self):
        """Fragmentation, not scarcity: 8 free chips split 4+4 across two
        hosts block a whole-host ask — the market migrates a sub-host gang
        to compact, with ZERO preemptions (equal-priority jobs are never
        victims)."""
        prg = boot(n_hosts=3)
        run(prg, "a", 4, "production")     # → h0
        run(prg, "b", 8, "production")     # whole host → h1
        run(prg, "c", 4, "production")     # → h0 (fills it)
        run(prg, "d", 4, "production")     # → h2
        prg.job_svc.delete_job("c", JobDelete(
            force=True, del_state_and_version_record=True))
        # h0: 4 used (a), h1: full (b), h2: 4 used (d) — 8 free, 0 whole
        assert run(prg, "big", 8, "production")["phase"] == "queued"
        assert [o["job"] for o in prg.admission.admit_once()] == ["big"]
        assert phase(prg, "big") == "running"
        # nothing was preempted; a (or d) was MIGRATED to make a hole
        assert prg.admission.status_view()["preemptionsTotal"] == 0
        for name in ("a", "b", "d"):
            assert phase(prg, name) == "running"
        assert oracle(prg) == []


class TestOperatorSurface:
    def test_admission_route_events_and_health(self):
        from tpu_docker_api.api.app import build_router

        prg = boot(n_hosts=1)
        run(prg, "low", 8, "preemptible")
        run(prg, "hi", 8, "production")
        router = build_router(
            prg.container_svc, prg.volume_svc, prg.chip_scheduler,
            prg.port_scheduler, work_queue=prg.wq, metrics=prg.metrics,
            job_svc=prg.job_svc, pod_scheduler=prg.pod_scheduler,
            job_supervisor=prg.job_supervisor, admission=prg.admission)
        view = router.dispatch("GET", "/api/v1/admission", {})
        assert view["enabled"] is True
        assert view["depth"] == 1
        assert view["perClass"]["production"] == 1
        assert view["entries"][0]["name"] == "hi"
        assert view["classes"]["system"] > view["classes"]["production"]
        prg.admission.admit_once()
        view = router.dispatch("GET", "/api/v1/admission", {})
        assert view["depth"] == 1   # low parked for re-admission
        assert view["entries"][0]["state"] == "preempted"
        assert view["preemptionsTotal"] == 1
        assert view["admissionsTotal"] == 1
        # events ride the merged ring
        events = router.dispatch("GET", "/api/v1/events", {})
        kinds = {e["event"] for e in events if "event" in e}
        assert {"job-queued", "job-preempted", "job-admitted"} <= kinds
        # /healthz carries the one-set-of-books read-back
        health = router.dispatch("GET", "/healthz", {})
        assert health["admission"]["enabled"] is True
        assert health["admission"]["preemptionsTotal"] == 1
        # /api/v1/health/jobs surfaces the class next to the phase
        jobs = router.dispatch("GET", "/api/v1/health/jobs", {})
        assert jobs["jobs"]["low"]["priorityClass"] == "preemptible"
        assert jobs["jobs"]["low"]["phase"] == "preempted"

    def test_metrics_series(self):
        prg = boot(n_hosts=1)
        run(prg, "low", 8, "preemptible")
        run(prg, "hi", 8, "production")
        prg.admission.admit_once()
        text = prg.metrics.render()
        assert 'admission_queue_depth{class="preemptible"} 1' in text
        assert 'preemptions_total{victim_class="preemptible"} 1' in text
        assert "admission_wait_ms" in text

    def test_leader_standby_does_not_run_admission_loop(self):
        """The admission loop is a WRITER: with leader election on it must
        start/stop with the lease (daemon wiring), and with the loop
        interval 0 it never starts at all."""
        prg = boot(n_hosts=1)
        assert prg.admission._thread is None
        prg._start_writers()
        try:
            assert prg.admission._thread is None  # interval 0: inline only
        finally:
            prg._stop_writers()


class TestConfigValidation:
    def test_load_validates_admission_keys(self, tmp_path):
        bad = tmp_path / "bad.toml"
        bad.write_text('priority_class_default = "gold"\n')
        with pytest.raises(ValueError, match="priority_class_default"):
            config_mod.load(str(bad))
        bad.write_text("admission_max_skips = -1\n")
        with pytest.raises(ValueError, match="admission_max_skips"):
            config_mod.load(str(bad))
        bad.write_text("[priority_class_weights]\ngold = 1.5\n")
        with pytest.raises(ValueError, match="integer"):
            config_mod.load(str(bad))
        good = tmp_path / "good.toml"
        good.write_text(
            'admission_enabled = true\nadmission_max_skips = 7\n'
            'priority_class_default = "gold"\n'
            "[priority_class_weights]\ngold = 10\nbronze = 1\n")
        cfg = config_mod.load(str(good))
        assert cfg.priority_class_weights == {"gold": 10, "bronze": 1}
        assert cfg.admission_max_skips == 7

    def test_custom_ladder_drives_admission(self):
        kv = MemoryKV()
        rt = FakeRuntime()
        cfg = config_mod.Config(
            store_backend="memory", runtime_backend="fake",
            health_watch_interval=0, end_port=40099,
            admission_enabled=True, admission_interval_s=0,
            priority_class_weights={"gold": 10, "bronze": 1},
            priority_class_default="bronze",
        )
        prg = Program(cfg, kv=kv, runtime=rt)
        prg.init()
        run(prg, "cheap", 8, "")   # "" → the configured default, bronze
        with pytest.raises(errors.BadRequest, match="priorityClass"):
            run(prg, "x", 2, "batch")  # the default ladder is GONE
        assert run(prg, "vip", 8, "gold")["phase"] == "queued"
        prg.admission.admit_once()
        assert phase(prg, "vip") == "running"
        assert phase(prg, "cheap") == "preempted"


class TestPartialPreemption:
    """Elastic gangs in the capacity market (docs/robustness.md "Elastic
    gangs"), property-style:

    - spare members are taken from elastic strictly-lower-class gangs
      BEFORE any whole gang dies (zero full preemptions when shrink
      suffices);
    - a gang never shrinks below its ``minMembers`` floor — when spares
      cannot make room the victim is condemned WHOLE, exactly like PR 10;
    - only strictly-lower classes donate; youngest donors donate first;
    - with no elastic victim in range the plan is byte-for-byte
      ``_victims_for`` — non-elastic deployments keep PR 10 semantics;
    - the shrunken gang grows BACK through the admission queue once
      pressure lifts (preempted-grade precedence, record settled
      exactly-once).
    """

    def members(self, prg, base) -> int:
        return len(prg.store.get_job(
            f"{base}-{prg.job_versions.get(base)}").placements)

    def test_spare_members_taken_before_any_whole_gang_dies(self):
        prg = boot(n_hosts=4)
        run(prg, "don", 32, "preemptible", elastic=True, min_members=1)
        assert run(prg, "prod", 8, "production")["phase"] == "queued"
        assert [o["job"] for o in prg.admission.admit_once()] == ["prod"]
        # the donor SHRANK (4 → 3 members) and keeps running; nothing died
        assert phase(prg, "don") == "running"
        assert self.members(prg, "don") == 3
        assert phase(prg, "prod") == "running"
        view = prg.admission.status_view()
        assert view["preemptionsTotal"] == 0
        assert view["partialPreemptionsTotal"] == 1
        # the donation journaled a grow-back record at the donor's class
        recs = [(r.base, r.kind) for r in prg.admission.records()]
        assert ("don", "growback") in recs
        info = prg.job_svc.get_job_info("don")
        assert info["membersDesired"] == 4 and info["membersActual"] == 3
        assert info["growbackQueuePosition"] == 1
        assert info["lastResize"]["direction"] == "down"
        assert oracle(prg) == []

    def test_growback_lands_through_the_queue_after_pressure_lifts(self):
        prg = boot(n_hosts=4)
        run(prg, "don", 32, "preemptible", elastic=True, min_members=1)
        run(prg, "prod", 8, "production")
        prg.admission.admit_once()
        assert self.members(prg, "don") == 3
        # pressure stays: a pass with a full pool grows nothing
        assert prg.admission.admit_once() == []
        assert self.members(prg, "don") == 3
        # pressure lifts: the grow-back record admits through the queue
        prg.job_svc.delete_job("prod", JobDelete(
            force=True, del_state_and_version_record=True))
        assert [o["job"] for o in prg.admission.admit_once()] == ["don"]
        assert self.members(prg, "don") == 4
        assert phase(prg, "don") == "running"
        # settled exactly-once: no record left, a second pass is a no-op
        assert prg.admission.records() == []
        assert prg.admission.admit_once() == []
        assert oracle(prg) == []
        assert prg.reconciler.reconcile()["actions"] == []
        # job_resize_max bounds ATTEMPTS of one resize, never the
        # lifetime counter: with the bound at 2 and 2 resizes already on
        # the books, the next shrink/grow cycle still works
        prg.job_svc.resize_max = 2
        assert prg.store.get_job(
            f"don-{prg.job_versions.get('don')}").resizes == 2
        run(prg, "prod2", 8, "production")
        prg.admission.admit_once()
        assert self.members(prg, "don") == 3
        prg.job_svc.delete_job("prod2", JobDelete(
            force=True, del_state_and_version_record=True))
        prg.admission.admit_once()
        assert self.members(prg, "don") == 4
        assert phase(prg, "don") == "running"

    def test_growback_parks_while_resize_disabled(self):
        """job_resize_enabled=false parks a pending grow-back (the record
        survives, nothing grows); re-enabling resumes it."""
        prg = boot(n_hosts=4)
        run(prg, "don", 32, "preemptible", elastic=True, min_members=1)
        run(prg, "prod", 8, "production")
        prg.admission.admit_once()
        prg.job_svc.delete_job("prod", JobDelete(
            force=True, del_state_and_version_record=True))
        prg.job_svc.resize_enabled = False
        assert prg.admission.admit_once() == []
        assert self.members(prg, "don") == 3
        assert {r.kind for r in prg.admission.records()
                if r.base == "don"} == {"growback"}
        prg.job_svc.resize_enabled = True
        assert [o["job"] for o in prg.admission.admit_once()] == ["don"]
        assert self.members(prg, "don") == 4

    def test_never_below_min_members_whole_gang_condemned_instead(self):
        """Spares stop at the floor: a 4-member gang with minMembers=3 can
        donate ONE host; a 2-host ask then needs the whole gang — PR 10
        whole-gang preemption, never a below-floor shrink."""
        prg = boot(n_hosts=4)
        run(prg, "don", 32, "preemptible", elastic=True, min_members=3)
        assert run(prg, "prod", 16, "production")["phase"] == "queued"
        assert [o["job"] for o in prg.admission.admit_once()] == ["prod"]
        assert phase(prg, "prod") == "running"
        assert phase(prg, "don") == "preempted"
        view = prg.admission.status_view()
        assert view["preemptionsTotal"] == 1
        recs = {r.base: r.kind for r in prg.admission.records()}
        assert recs["don"] == "preempted"
        assert oracle(prg) == []

    def test_strictly_lower_class_only(self):
        """An elastic gang at the requester's own class never donates —
        eligibility is strictly-lower weight, same as whole-gang
        preemption."""
        prg = boot(n_hosts=2)
        run(prg, "peer", 16, "production", elastic=True, min_members=1)
        assert run(prg, "prod", 8, "production")["phase"] == "queued"
        assert prg.admission.admit_once() == []
        assert self.members(prg, "peer") == 2
        assert phase(prg, "prod") == "queued"
        assert oracle(prg) == []

    def test_youngest_elastic_donor_first(self):
        """Within the donor class the YOUNGEST gang donates first (the
        paged.py seniority rule, applied member-wise)."""
        prg = boot(n_hosts=4)
        run(prg, "old", 16, "preemptible", elastic=True, min_members=1)
        run(prg, "young", 16, "preemptible", elastic=True, min_members=1)
        assert run(prg, "prod", 8, "production")["phase"] == "queued"
        assert [o["job"] for o in prg.admission.admit_once()] == ["prod"]
        assert self.members(prg, "young") == 1   # donated
        assert self.members(prg, "old") == 2     # untouched
        assert oracle(prg) == []

    def test_plan_is_pr10_victims_byte_for_byte_without_elastic_donors(self):
        """With no elastic victim in range the partial-preemption planner
        degenerates to exactly ``_victims_for`` — the PR 10 contract the
        ordering tests above pin stays byte-for-byte."""
        prg = boot(n_hosts=2)
        run(prg, "a", 4, "batch")
        run(prg, "b", 4, "preemptible")
        run(prg, "c", 4, "preemptible")
        w = prg.admission.weight("production")
        for want in (16, 6, 4):
            assert (prg.admission._preempt_plan(w, want, 1, "req")
                    == [("full", b, 0)
                        for b in prg.admission._victims_for(w, want, 1,
                                                            "req")])

    def test_resize_disabled_keeps_whole_gang_preemption(self):
        """job_resize_enabled=false: the donor pool is ignored and the
        market behaves exactly like PR 10 — the elastic gang dies whole."""
        prg = boot(n_hosts=4)
        prg.job_svc.resize_enabled = False
        run(prg, "don", 32, "preemptible", elastic=True, min_members=1)
        run(prg, "prod", 8, "production")
        prg.admission.admit_once()
        assert phase(prg, "don") == "preempted"
        assert phase(prg, "prod") == "running"
        assert prg.admission.status_view()["partialPreemptionsTotal"] == 0
        assert oracle(prg) == []

    def test_elastic_validation(self):
        prg = boot(n_hosts=4)
        with pytest.raises(errors.BadRequest, match="single-slice"):
            run(prg, "x", 32, "batch", elastic=True, num_slices=2)
        with pytest.raises(errors.BadRequest, match=">= 2 whole hosts"):
            run(prg, "x", 4, "batch", elastic=True)
        with pytest.raises(errors.BadRequest, match="minMembers"):
            run(prg, "x", 16, "batch", elastic=True, min_members=5)
        with pytest.raises(errors.BadRequest, match="elastic"):
            run(prg, "x", 16, "batch", min_members=1)
        # an elastic job queues with its contract intact (resolved at
        # admission time like the rest of the spec)
        run(prg, "fill", 32, "production")
        out = run(prg, "el", 16, "batch", elastic=True, min_members=2)
        assert out["phase"] == "queued"
        st = prg.store.get_job(f"el-{prg.job_versions.get('el')}")
        assert st.elastic and st.min_members == 2 and st.members_desired == 2

    def test_rescale_updates_elastic_contract(self):
        """A user rescale rewrites membersDesired (grow-back targets the
        new shape) and rejects shapes the elastic contract cannot hold."""
        prg = boot(n_hosts=4)
        run(prg, "el", 32, "batch", elastic=True, min_members=3)
        with pytest.raises(errors.BadRequest, match="whole-host"):
            prg.job_svc.patch_job_chips("el", JobPatchChips(chip_count=4))
        with pytest.raises(errors.BadRequest, match="minMembers"):
            # 2 hosts is a legal elastic shape but below the floor of 3
            prg.job_svc.patch_job_chips(
                "el", JobPatchChips(chip_count=16))
        prg.job_svc.patch_job_chips("el", JobPatchChips(chip_count=24))
        st = prg.store.get_job(f"el-{prg.job_versions.get('el')}")
        assert st.members_desired == 3 and st.elastic
        assert oracle(prg) == []

    def test_blocked_growback_never_freezes_queued_admissions(self):
        """A grow-back that cannot place (its gang needs a WHOLE host,
        only sub-host holes churn) waits indefinitely by design — but it
        must never accrue skips and trip the starvation gate: queued work
        keeps backfilling past it forever."""
        prg = boot(n_hosts=4, max_skips=2)
        run(prg, "halfpin", 4, "batch")    # h0 half-used: never fully free
        run(prg, "don", 24, "batch", elastic=True, min_members=1)  # h1-h3
        assert run(prg, "prod", 8, "production")["phase"] == "queued"
        prg.admission.admit_once()         # don shrinks 3 → 2, prod places
        assert self.members(prg, "don") == 2
        assert phase(prg, "prod") == "running"
        run(prg, "g0", 4, "batch")         # takes h0's last 4 chips
        # churn sub-host holes past the blocked grow-back, beyond max_skips
        prev = "g0"
        for i in range(3):
            assert run(prg, f"f{i}", 4, "batch")["phase"] == "queued"
            prg.job_svc.delete_job(prev, JobDelete(
                force=True, del_state_and_version_record=True))
            assert [o["job"] for o in prg.admission.admit_once()] \
                == [f"f{i}"], f"queued admission froze at backfill {i}"
            prev = f"f{i}"
        # the grow-back is still waiting, uncharged, and the gang intact
        rec = next(r for r in prg.admission.records() if r.base == "don")
        assert rec.kind == "growback" and rec.skips == 0
        assert self.members(prg, "don") == 2
        assert oracle(prg) == []
