"""The bench artifact pipeline (VERDICT r4 item 1 / weak #1).

Round 3's BENCH artifact failed to parse (one giant line overflowed the
driver's bounded tail read) and round 4's was empty (riders blew the
driver's time budget before the single end-of-run print, rc 124). These
tests pin the fix: the headline prints FIRST and every rider flushes its
own compact, schema-valid JSON line, so a timeout at ANY point still
leaves a parseable artifact; a budget guard skips riders loudly instead
of running into the kill.
"""

import io
import json
import sys
import time
from contextlib import redirect_stdout
from pathlib import Path

import pytest

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))

import bench  # noqa: E402

SCHEMA_KEYS = {"metric", "value", "unit", "vs_baseline"}


def _run_riders(plan, deadline):
    buf = io.StringIO()
    summary: dict = {}
    skipped: list = []
    with redirect_stdout(buf):
        bench.run_riders(plan, deadline, summary, skipped)
    lines = [json.loads(ln) for ln in buf.getvalue().splitlines() if ln]
    return lines, summary, skipped


def test_rider_lines_are_schema_valid_and_incremental():
    """Each rider emits its own line the moment it completes, carrying
    the driver schema — whichever line a bounded tail parse lands on
    must parse as a {metric, value, unit, vs_baseline} record."""
    plan = [
        ("a", 0, lambda: (1.5, "tok/s", 2.0, {"detail": "x"})),
        ("b", 0, lambda: (7, "ms", 1.0, {})),
    ]
    lines, summary, skipped = _run_riders(plan, time.monotonic() + 60)
    assert len(lines) == 2
    for ln in lines:
        assert SCHEMA_KEYS <= set(ln)
        assert "rider_wall_s" in ln["extra"]
    assert lines[0]["metric"] == "rider_a" and lines[0]["value"] == 1.5
    assert summary == {"a": 1.5, "b": 7}
    assert skipped == []


def test_budget_guard_skips_loudly_not_silently():
    """A rider whose estimate exceeds the remaining budget is skipped
    with an explicit line — the BENCH_r04 failure mode (run into the
    driver kill, lose everything) must be impossible by construction."""
    ran = []
    plan = [
        ("cheap", 0, lambda: (ran.append("cheap") or 1, "x", 1.0, {})),
        ("expensive", 10_000, lambda: (ran.append("boom") or 1, "x", 1.0, {})),
        ("cheap2", 0, lambda: (ran.append("cheap2") or 2, "x", 1.0, {})),
    ]
    lines, summary, skipped = _run_riders(plan, time.monotonic() + 30)
    assert ran == ["cheap", "cheap2"]  # expensive never started
    assert skipped == ["expensive"]
    skip_line = next(ln for ln in lines if ln.get("skipped"))
    assert skip_line["metric"] == "rider_expensive"
    assert "budget" in skip_line["reason"]
    assert SCHEMA_KEYS <= set(skip_line)  # still schema-shaped


def test_rider_error_is_contained_and_reported():
    """One failing rider must not sink the riders after it (the per-rider
    independence rule the old measure_serving applied, kept here)."""
    def boom():
        raise RuntimeError("synthetic rider failure")

    plan = [
        ("bad", 0, boom),
        ("good", 0, lambda: (3, "x", 1.0, {})),
    ]
    lines, summary, skipped = _run_riders(plan, time.monotonic() + 60)
    bad = next(ln for ln in lines if ln["metric"] == "rider_bad")
    assert "synthetic rider failure" in bad["error"]
    assert bad["value"] is None
    assert summary == {"bad": None, "good": 3}


def test_default_plan_covers_verdict_done_set():
    """VERDICT r4 item 1 'done' = rider lines for 8B decode (fused),
    slot serving, and paged capacity. The default plan must carry them
    even when --full is off, in priority order ahead of the tail."""
    names = [name for name, _, _ in bench.riders(full=False)]
    assert "llama3_8b_decode_fused" in names
    assert any("slot_serving" in n for n in names)
    assert "paged_capacity_8b" in names
    full_names = [name for name, _, _ in bench.riders(full=True)]
    assert set(names) < set(full_names)
    # estimates are present and sane (the guard arithmetic relies on them)
    assert all(est > 0 for _, est, _ in bench.riders(full=True))


class TestChurnFamily:
    """The control-plane churn family (``make bench-churn``): runs green on
    the fake runtime at tiny scale and emits exactly the schema the driver
    pipeline (scripts/check_churn_schema.py) consumes."""

    @pytest.fixture(scope="class")
    def churn(self):
        return bench.measure_control_plane_churn(n_containers=3, n_gangs=2)

    def test_schema_checker_accepts_the_emitted_line(self, churn):
        sys.path.insert(0, str(Path(__file__).resolve().parent.parent
                               / "scripts"))
        try:
            from check_churn_schema import validate_lines
        finally:
            sys.path.pop(0)
        line = {"metric": "control_plane_churn_create_ready_ms_p50",
                "value": churn["create_ready_ms_p50"], "unit": "ms",
                "vs_baseline": 1.0, "extra": churn}
        assert validate_lines([line]) == []
        # the checker is not a rubber stamp: a broken gate must fail it
        bad = json.loads(json.dumps(line))
        bad["extra"]["gates"]["ok"] = False
        assert any("gate" in p for p in validate_lines([bad]))
        del bad["extra"]["round_trips"]["gang_create_4host"]
        assert any("gang_create_4host" in p for p in validate_lines([bad]))

    def test_round_trip_gates_hold(self, churn):
        """The tentpole invariants, pinned in tier-1 at tiny scale:
        container create stays within 3 atomic applies and a gang's apply
        count is O(1) in its member count."""
        gates = churn["gates"]
        assert gates["ok"] is True
        assert 1 <= gates["container_create_applies"] <= 3
        assert gates["gang_apply_o1_in_members"] is True
        rt = churn["round_trips"]
        assert (rt["gang_create_2host"]["apply"]
                == rt["gang_create_4host"]["apply"] >= 1)
        # quantiles are internally consistent
        stats = churn["containers"]
        for flow in ("create", "replace", "delete"):
            assert (stats[f"{flow}_ms_p50"] <= stats[f"{flow}_ms_p95"]
                    <= stats[f"{flow}_ms_max"])


class TestFailoverFamily:
    """The HA failover family (``make bench-failover``): two leader-elected
    daemons over one store, the leader hard-killed under churn load, at
    tiny scale — pinning both the artifact schema
    (scripts/check_churn_schema.py) and the tentpole invariants: writes
    recover on the standby within the TTL-derived budget and every deposed
    leader's epoch-fenced write is rejected by the store."""

    @pytest.fixture(scope="class")
    def failover(self):
        return bench.measure_control_plane_failover(n_failovers=2,
                                                    ttl_s=0.5)

    def test_schema_checker_accepts_the_emitted_line(self, failover):
        sys.path.insert(0, str(Path(__file__).resolve().parent.parent
                               / "scripts"))
        try:
            from check_churn_schema import validate_lines
        finally:
            sys.path.pop(0)
        line = {"metric": "control_plane_failover_recovery_ms_p50",
                "value": failover["recovery_ms"]["p50"], "unit": "ms",
                "vs_baseline": 1.0, "extra": failover}
        assert validate_lines([line]) == []
        # the checker is not a rubber stamp: a broken gate must fail it,
        # and so must a fenced write that LANDED
        bad = json.loads(json.dumps(line))
        bad["extra"]["gates"]["ok"] = False
        assert any("gate" in p for p in validate_lines([bad]))
        bad = json.loads(json.dumps(line))
        bad["extra"]["fenced"]["rejected"] = 0
        assert any("rejected" in p for p in validate_lines([bad]))

    def test_failover_gates_hold(self, failover):
        gates = failover["gates"]
        assert gates["ok"] is True
        assert gates["recovered_all"] is True
        assert gates["fenced_rejected_all"] is True
        assert gates["epoch_monotonic"] is True
        rec = failover["recovery_ms"]
        assert rec["p50"] <= rec["p95"] <= rec["max"]
        assert rec["p95"] <= gates["recovery_p95_budget_ms"]
        # each handoff bumped the fencing epoch exactly once
        assert failover["epochs"] == sorted(failover["epochs"])
        assert len(failover["recoveries_ms"]) == 2


class TestBrownoutFamily:
    """The store-brownout family (``make bench-brownout``) at tiny scale —
    pinning both the artifact schema (scripts/check_churn_schema.py) and
    the tentpole invariants: with the STORE slow and then dark under churn,
    every call resolves typed and bounded (no hangs), reads ride the
    informer mirror explicitly marked stale, the steady gang is never
    touched by a spurious repair, and writes recover within the
    probe-derived budget after every heal."""

    @pytest.fixture(scope="class")
    def brownout(self):
        return bench.measure_control_plane_brownout(
            n_cycles=6, n_outages=2, outage_s=0.5)

    def test_schema_checker_accepts_the_emitted_line(self, brownout):
        sys.path.insert(0, str(Path(__file__).resolve().parent.parent
                               / "scripts"))
        try:
            from check_churn_schema import validate_lines
        finally:
            sys.path.pop(0)
        line = {"metric": "control_plane_brownout_recovery_ms_p50",
                "value": brownout["recovery_ms"]["p50"], "unit": "ms",
                "vs_baseline": 1.0, "extra": brownout}
        assert validate_lines([line]) == []
        # the checker is not a rubber stamp: a broken gate must fail it
        bad = json.loads(json.dumps(line))
        bad["extra"]["gates"]["ok"] = False
        assert any("gate" in p for p in validate_lines([bad]))
        # ... an untyped refusal leaking through must fail
        bad = json.loads(json.dumps(line))
        bad["extra"]["outage_mutation_codes"]["10301"] = 1
        assert any("untyped" in p for p in validate_lines([bad]))
        # ... an outage window that never served a stale read is vacuous
        bad = json.loads(json.dumps(line))
        bad["extra"]["stale_reads"] = 0
        assert any("stale_reads" in p for p in validate_lines([bad]))
        # ... and a run that ends with the store still dark must fail
        bad = json.loads(json.dumps(line))
        bad["extra"]["store_health"]["mode"] = "outage"
        assert any("end healthy" in p for p in validate_lines([bad]))

    def test_brownout_gates_hold(self, brownout):
        gates = brownout["gates"]
        assert gates["ok"] is True
        assert gates["all_calls_resolved"] is True
        assert gates["mutations_typed"] is True
        assert gates["stale_reads_marked"] is True
        assert gates["stale_lag_bounded"] is True
        assert gates["steady_gang_untouched"] is True
        assert gates["steady_gang_alive"] is True
        assert gates["mode_healed"] is True
        assert gates["outages_counted"] is True
        rec = brownout["recovery_ms"]
        assert rec["p50"] <= rec["p95"] <= rec["max"]
        assert rec["p95"] <= gates["recovery_p95_budget_ms"]
        assert len(brownout["recoveries_ms"]) == 2
        # every mutation thrown at the dark store was refused typed
        assert set(brownout["outage_mutation_codes"]) <= {"10502", "10506"}
        assert brownout["stale_reads"] > 0
        assert brownout["store_health"]["outagesTotal"] == 2


class TestReadsFamily:
    """The watch-fed read-path family (``make bench-reads``): leader +
    informer standby + read-through standby over one store at tiny scale —
    pinning both the artifact schema (scripts/check_churn_schema.py) and
    the tentpole invariants: standby informer GETs audit at ~0 store round
    trips per request, read-through still audits ≥ 1 per request (so the
    informer's zero is proven against a live counter, not a bypassed one),
    and a leader write becomes standby-visible within the lag budget."""

    @pytest.fixture(scope="class")
    def reads(self):
        return bench.measure_control_plane_reads(n_reads=60, readers=3,
                                                 audit_reads=10)

    def test_schema_checker_accepts_the_emitted_line(self, reads):
        sys.path.insert(0, str(Path(__file__).resolve().parent.parent
                               / "scripts"))
        try:
            from check_churn_schema import validate_lines
        finally:
            sys.path.pop(0)
        line = {"metric": "control_plane_reads_standby_informer_rps",
                "value": reads["roles"]["standby_informer"]["rps"],
                "unit": "reads/s", "vs_baseline": 1.0, "extra": reads}
        assert validate_lines([line]) == []
        # the checker is not a rubber stamp: a broken gate must fail it
        bad = json.loads(json.dumps(line))
        bad["extra"]["gates"]["ok"] = False
        assert any("gate" in p for p in validate_lines([bad]))
        # ... and so must a read-through audit of zero — the vacuous-
        # counter failure mode this family exists to catch
        bad = json.loads(json.dumps(line))
        bad["extra"]["gates"]["read_through_reads_per_req"] = 0
        assert any("read-through" in p for p in validate_lines([bad]))
        bad = json.loads(json.dumps(line))
        del bad["extra"]["roles"]["standby_informer"]
        assert any("standby_informer" in p for p in validate_lines([bad]))

    def test_reads_gates_hold(self, reads):
        gates = reads["gates"]
        assert gates["ok"] is True
        # the tentpole: watch-fed standby reads cost ~0 store round trips
        assert (gates["standby_informer_reads_per_req"]
                <= gates["standby_informer_reads_budget"])
        # the audit is live: the uncached role pays ≥ 1 read per request
        assert gates["read_through_reads_per_req"] >= 1.0
        # leader-write → standby-visible within the documented lag bound
        assert 0 < gates["visibility_lag_ms"] <= gates[
            "visibility_lag_budget_ms"]
        for role in ("leader", "standby_informer", "standby_read_through"):
            stats = reads["roles"][role]
            assert stats["p50_ms"] <= stats["p95_ms"] <= stats["max_ms"]
            assert stats["rps"] > 0


class TestFanoutFamily:
    """The runtime fan-out family (``make bench-fanout``): gang lifecycle
    at member counts {2,4,8} against latency-injected engines at tiny
    scale — pinning both the artifact schema (scripts/check_churn_schema
    .py) and the tentpole invariants: 8-member gang create wall-clock
    stays within the 2.5× budget of the 2-member wall (serial would be
    ~4×), the cross-host ordering audit holds (coordinator-start strictly
    first, coordinator-stop strictly last), and gang create still costs
    ≤ 3 store applies, O(1) in member count (no regression of the PR 6
    churn gate under concurrency)."""

    @pytest.fixture(scope="class")
    def fanout(self):
        return bench.measure_control_plane_fanout(iters=1, latency_ms=25.0)

    def test_schema_checker_accepts_the_emitted_line(self, fanout):
        sys.path.insert(0, str(Path(__file__).resolve().parent.parent
                               / "scripts"))
        try:
            from check_churn_schema import validate_lines
        finally:
            sys.path.pop(0)
        line = {"metric": "control_plane_fanout_gang8_create_ms",
                "value": fanout["members"]["8"]["create_ms_min"],
                "unit": "ms", "vs_baseline": 1.0, "extra": fanout}
        assert validate_lines([line]) == []
        # the checker is not a rubber stamp: a broken gate must fail it
        bad = json.loads(json.dumps(line))
        bad["extra"]["gates"]["ok"] = False
        assert any("gate" in p for p in validate_lines([bad]))
        # ... and so must a wall ratio past the budget (a fan-out that
        # quietly serialized) or a failed ordering audit
        bad = json.loads(json.dumps(line))
        bad["extra"]["gates"]["wall_ratio_8v2"] = 3.9
        assert any("serializing" in p for p in validate_lines([bad]))
        bad = json.loads(json.dumps(line))
        bad["extra"]["gates"]["ordering_ok"] = False
        assert any("ordering" in p for p in validate_lines([bad]))
        bad = json.loads(json.dumps(line))
        del bad["extra"]["members"]["4"]
        assert any("members.4" in p for p in validate_lines([bad]))

    def test_fanout_gates_hold(self, fanout):
        gates = fanout["gates"]
        assert gates["ok"] is True
        # the tentpole: lifecycle wall-clock is O(slowest host), not
        # O(members) — 4x the members must NOT cost 4x the wall
        assert 0 < gates["wall_ratio_8v2"] <= gates["wall_ratio_budget"]
        # concurrency never broke the gang barriers
        assert gates["ordering_ok"] is True
        assert fanout["ordering_problems"] == []
        # ... and never added store round trips (the PR 6 invariant)
        assert 1 <= gates["gang_create_applies"] <= 3
        assert gates["gang_apply_o1_in_members"] is True
        applies = fanout["gang_create_applies"]
        assert applies["2"] == applies["4"] == applies["8"]
        for m in ("2", "4", "8"):
            stats = fanout["members"][m]
            for flow in ("create", "stop", "delete"):
                assert 0 < stats[f"{flow}_ms_min"] <= stats[f"{flow}_ms_max"]


class TestResizeFamily:
    """The elastic-gang family (``make bench-resize``) at tiny scale —
    pinning both the artifact schema (scripts/check_churn_schema.py) and
    the tentpole invariants: a production burst into a full pod is
    satisfied by SHRINKING the elastic gang (zero full preemptions when
    shrink suffices), the gang grows BACK through the admission queue
    once pressure lifts, and a host loss shrinks the gang with zero
    restart/migration budget burned."""

    @pytest.fixture(scope="class")
    def resize(self):
        return bench.measure_control_plane_resize(iters=2)

    def test_schema_checker_accepts_the_emitted_line(self, resize):
        sys.path.insert(0, str(Path(__file__).resolve().parent.parent
                               / "scripts"))
        try:
            from check_churn_schema import validate_lines
        finally:
            sys.path.pop(0)
        line = {"metric": "control_plane_resize_time_to_shrunk_ms_p50",
                "value": resize["time_to_shrunk_ms"]["p50"],
                "unit": "ms", "vs_baseline": 1.0, "extra": resize}
        assert validate_lines([line]) == []
        # the checker is not a rubber stamp: a broken gate must fail it
        bad = json.loads(json.dumps(line))
        bad["extra"]["gates"]["ok"] = False
        assert any("gate" in p for p in validate_lines([bad]))
        # ... and so must a full preemption where shrink sufficed (the
        # failure mode this family exists to catch), a grow-back that
        # bypassed the queue, or a blown shrink budget
        bad = json.loads(json.dumps(line))
        bad["extra"]["gates"]["full_preemptions"] = 1
        assert any("whole gang died" in p for p in validate_lines([bad]))
        bad = json.loads(json.dumps(line))
        bad["extra"]["gates"]["growback_admits"] = 0
        assert any("admission queue" in p for p in validate_lines([bad]))
        bad = json.loads(json.dumps(line))
        bad["extra"]["time_to_shrunk_ms"]["p95"] = (
            bad["extra"]["gates"]["shrink_budget_ms"] + 1)
        assert any("budget" in p for p in validate_lines([bad]))
        bad = json.loads(json.dumps(line))
        bad["extra"]["gates"]["host_loss_zero_restarts"] = False
        assert any("burned a restart" in p for p in validate_lines([bad]))

    def test_resize_gates_hold(self, resize):
        gates = resize["gates"]
        assert gates["ok"] is True
        # the tentpole: shrink sufficed, so NOTHING died whole
        assert gates["zero_full_preemptions"] is True
        assert gates["full_preemptions"] == 0
        assert gates["partial_preemptions"] >= 2
        # grow-back landed through the queue, with the journal events
        assert gates["growback_via_queue"] is True
        assert gates["growback_admits"] >= 2
        assert gates["partial_preempt_event"] is True
        assert gates["growback_queued_event"] is True
        # host loss: absorbed by the shrink, budgets untouched
        assert gates["host_loss_zero_restarts"] is True
        assert gates["host_loss_zero_migrations"] is True
        assert gates["host_loss_growback_queued"] is True
        tts = resize["time_to_shrunk_ms"]
        assert 0 < tts["p50"] <= tts["p95"] <= tts["max"]
        assert tts["p95"] <= gates["shrink_budget_ms"]
        assert len(resize["shrunk_ms"]) == 3  # 2 cycles + host loss


class TestPreemptFamily:
    """The capacity-market family (``make bench-preempt``): fill the pool
    with preemptible gangs on the fake runtime, submit production gangs,
    at tiny scale — pinning both the artifact schema
    (scripts/check_churn_schema.py) and the tentpole invariants: every
    high-priority job places (the market never strands a production ask a
    preemption could satisfy), ZERO preemptions when free holes suffice
    (backfill proven, not asserted), and ``admission_enabled=false``
    still answers a full pool with the byte-for-byte 10601 refusal."""

    @pytest.fixture(scope="class")
    def preempt(self):
        return bench.measure_control_plane_preempt(n_low=4, n_high=2)

    def test_schema_checker_accepts_the_emitted_line(self, preempt):
        sys.path.insert(0, str(Path(__file__).resolve().parent.parent
                               / "scripts"))
        try:
            from check_churn_schema import validate_lines
        finally:
            sys.path.pop(0)
        line = {"metric": "control_plane_preempt_time_to_placed_ms_p50",
                "value": preempt["time_to_placed_ms"]["p50"],
                "unit": "ms", "vs_baseline": 1.0, "extra": preempt}
        assert validate_lines([line]) == []
        # the checker is not a rubber stamp: a broken gate must fail it
        bad = json.loads(json.dumps(line))
        bad["extra"]["gates"]["ok"] = False
        assert any("gate" in p for p in validate_lines([bad]))
        # ... and so must a preemption where holes sufficed (the
        # backfill-broken failure mode this family exists to catch), a
        # changed legacy refusal code, or a stranded high-priority job
        bad = json.loads(json.dumps(line))
        bad["extra"]["preemptions"]["with_holes"] = 1
        assert any("holes" in p for p in validate_lines([bad]))
        bad = json.loads(json.dumps(line))
        bad["extra"]["gates"]["legacy_refusal_code"] = 10302
        assert any("10601" in p for p in validate_lines([bad]))
        bad = json.loads(json.dumps(line))
        bad["extra"]["gates"]["all_placed"] = False
        assert any("never placed" in p for p in validate_lines([bad]))

    def test_preempt_gates_hold(self, preempt):
        gates = preempt["gates"]
        assert gates["ok"] is True
        # the tentpole: every production submission placed, and pressure
        # was resolved by preemption — not by luck or spare capacity
        assert gates["all_placed"] is True
        assert gates["preempted_under_pressure"] is True
        assert preempt["preemptions"]["under_pressure"] >= 1
        # zero preemptions when holes sufficed (backfill proven)
        assert gates["zero_preempt_with_holes"] is True
        assert preempt["preemptions"]["with_holes"] == 0
        # admission_enabled=false keeps today's refusal contract
        assert gates["legacy_refusal_ok"] is True
        assert gates["legacy_refusal_code"] == 10601
        ttp = preempt["time_to_placed_ms"]
        assert 0 < ttp["p50"] <= ttp["p95"] <= ttp["max"]
        assert len(preempt["placed_ms"]) == 2


class TestServeScaleFamily:
    """The service-autoscaling family (``make bench-serve-scale``) at tiny
    scale — pinning the artifact schema (scripts/check_churn_schema.py)
    and the tentpole invariants: after an offered-load step the service
    reaches its target replica count with the SLO recovered, the last
    replica entered THROUGH the admission queue (preempting the batch
    filler — journal events present), zero manual operations were issued,
    and shedding the load scales back down and re-admits the preempted
    training gang."""

    @pytest.fixture(scope="class")
    def serve(self):
        return bench.measure_control_plane_serve_scale(iters=2)

    def test_schema_checker_accepts_the_emitted_line(self, serve):
        sys.path.insert(0, str(Path(__file__).resolve().parent.parent
                               / "scripts"))
        try:
            from check_churn_schema import validate_lines
        finally:
            sys.path.pop(0)
        line = {"metric": "control_plane_serve_scale_time_to_scaled_ms_p50",
                "value": serve["time_to_scaled_ms"]["p50"],
                "unit": "ms", "vs_baseline": 1.0, "extra": serve}
        assert validate_lines([line]) == []
        # the checker is not a rubber stamp: a broken gate must fail it
        bad = json.loads(json.dumps(line))
        bad["extra"]["gates"]["ok"] = False
        assert any("gate" in p for p in validate_lines([bad]))
        # ... and so must an autoscaler that never touched the admission
        # queue, leaned on manual ops, or blew the scaling budget
        bad = json.loads(json.dumps(line))
        bad["extra"]["gates"]["admitted_via_queue"] = 0
        assert any("admission journal" in p for p in validate_lines([bad]))
        bad = json.loads(json.dumps(line))
        bad["extra"]["gates"]["zero_manual_ops"] = False
        assert any("manual" in p for p in validate_lines([bad]))
        bad = json.loads(json.dumps(line))
        bad["extra"]["gates"]["time_to_scaled_p50_ms"] = 1e9
        assert any("budget" in p for p in validate_lines([bad]))

    def test_serve_scale_gates_hold(self, serve):
        gates = serve["gates"]
        assert gates["ok"] is True
        # the tentpole: the load step scaled the service to target with
        # the SLO recovered, THROUGH the capacity market
        assert gates["reached_target"] is True
        assert gates["slo_recovered"] is True
        assert gates["admitted_via_queue"] >= 1
        assert gates["batch_preempted"] is True
        # zero manual operations: the autoscaler did this alone
        assert gates["zero_manual_ops"] is True
        assert gates["manual_ops"] == 0
        # scale-down released capacity back to training
        assert gates["scale_down_converged"] is True
        tts = serve["time_to_scaled_ms"]
        assert 0 < tts["p50"] <= tts["p95"] <= tts["max"]
        assert tts["p50"] <= gates["time_to_scaled_budget_ms"]
        assert len(serve["scaled_ms"]) == 2


class TestServeTrafficFamily:
    """The serving-gateway traffic family (``make bench-serve-traffic``)
    at tiny scale — pinning the artifact schema and the tentpole
    invariants: open-loop streamed load rides through an autoscale, a
    rolling spec update and a hard replica kill with ZERO dropped
    requests, the gateway's TTFT overhead stays in budget, prefix
    affinity beats random, rolls are released by gateway acks (not by
    burning the drain deadline), and overload sheds with a typed 429."""

    @pytest.fixture(scope="class")
    def traffic(self):
        return bench.measure_control_plane_serve_traffic(
            duration_s=2.0, rps=30.0)

    def test_schema_checker_accepts_the_emitted_line(self, traffic):
        sys.path.insert(0, str(Path(__file__).resolve().parent.parent
                               / "scripts"))
        try:
            from check_churn_schema import validate_lines
        finally:
            sys.path.pop(0)
        line = {"metric": "control_plane_serve_traffic_ttft_p95_ms",
                "value": traffic["ttft_ms"]["p95"],
                "unit": "ms", "vs_baseline": 1.0, "extra": traffic}
        assert validate_lines([line]) == []
        # the checker is not a rubber stamp: a broken gate must fail it
        bad = json.loads(json.dumps(line))
        bad["extra"]["gates"]["ok"] = False
        assert any("gate" in p for p in validate_lines([bad]))
        # ... a dropped request must fail even if gates.ok still lies
        bad = json.loads(json.dumps(line))
        bad["extra"]["requests"]["failed"] = 3
        assert any("zero-drop" in p or "zero_dropped" in p
                   for p in validate_lines([bad]))
        # ... and a roll that burned a drain deadline is the ack
        # regression this family exists to catch
        bad = json.loads(json.dumps(line))
        bad["extra"]["gates"]["roll_patch_s"] = 10.0
        assert any("ack" in p for p in validate_lines([bad]))
        bad = json.loads(json.dumps(line))
        bad["extra"]["shed_probe"]["status"] = 503
        assert any("shed_typed" in p for p in validate_lines([bad]))

    def test_serve_traffic_gates_hold(self, traffic):
        gates = traffic["gates"]
        assert gates["ok"] is True
        # the tentpole: zero dropped requests across all three events
        req = traffic["requests"]
        assert req["failed"] == 0 and req["truncated"] == 0 \
            and req["shed"] == 0 and req["ok"] >= 20
        assert gates["scaled_under_load"] is True
        assert gates["rolled_under_load"] is True
        assert gates["kill_recovered"] is True
        # rolls are released by gateway roll-acks, not deadline expiry
        assert gates["roll_acked_fast"] is True
        assert gates["roll_patch_s"] < 5.0
        assert gates["ttft_overhead_ok"] is True
        assert gates["affinity_beats_random"] is True
        # the shed probe got the typed refusal contract
        shed = traffic["shed_probe"]
        assert shed["status"] == 429
        assert shed["retry_after"] is not None
        assert isinstance(shed["code"], int)


class TestScaleFamily:
    """The O(100k)-object scale family (``make bench-scale``) at tiny
    scale — pinning the artifact schema (scripts/check_churn_schema.py)
    and the tentpole invariants: a zero-change steady-state reconcile
    pass runs in ``dirty`` mode at O(changes) store reads while the
    measured full scan really is O(N) (the contrast that makes the budget
    non-vacuous), a limit-bounded list page costs the same at both world
    sizes and a continue-token walk is exact, and churned families
    compact down to retention with the latest pointer and live-referenced
    versions protected."""

    @pytest.fixture(scope="class")
    def scale(self):
        return bench.measure_control_plane_scale(
            n_objects=400, n_small=240, n_gangs=12, retention=3,
            list_iters=20, churn_families=6)

    def test_schema_checker_accepts_the_emitted_line(self, scale):
        sys.path.insert(0, str(Path(__file__).resolve().parent.parent
                               / "scripts"))
        try:
            from check_churn_schema import validate_lines
        finally:
            sys.path.pop(0)
        line = {"metric": "control_plane_scale_steady_reconcile_reads",
                "value": scale["steady_reads"],
                "unit": "reads", "vs_baseline": 1.0, "extra": scale}
        assert validate_lines([line]) == []
        # the checker is not a rubber stamp: a broken gate must fail it
        bad = json.loads(json.dumps(line))
        bad["extra"]["gates"]["ok"] = False
        assert any("gate" in p for p in validate_lines([bad]))
        # ... a steady pass that regressed to the O(N) scan must fail
        bad = json.loads(json.dumps(line))
        bad["extra"]["gates"]["steady_reads"] = 10_000_000
        assert any("scanning" in p for p in validate_lines([bad]))
        # ... a bypassed read counter must fail, never pass vacuously
        bad = json.loads(json.dumps(line))
        bad["extra"]["gates"]["full_scan_reads"] = 0
        assert any("bypassed" in p for p in validate_lines([bad]))
        # ... and history growing past retention must fail
        bad = json.loads(json.dumps(line))
        bad["extra"]["gates"]["retention_worst_versions"] = 99
        assert any("compaction" in p for p in validate_lines([bad]))

    def test_scale_gates_hold(self, scale):
        gates = scale["gates"]
        assert gates["ok"] is True
        # the tentpole: O(changes) steady state, measured against a
        # genuinely-counted O(N) full scan
        assert gates["steady_mode"] == "dirty"
        assert gates["steady_reads"] <= gates["steady_read_budget"]
        assert gates["full_scan_reads"] >= 400
        assert gates["steady_clean"] is True
        # bounded pages: flat cost and an exact no-dup/no-skip walk
        assert gates["list_flat"] is True
        assert gates["walk_exact"] is True
        # bounded history: retention held, protections honored
        assert gates["retention_worst_versions"] <= gates["retention"]
        assert gates["latest_protected"] is True
        assert gates["live_version_protected"] is True
        assert scale["compact"]["trimmedTotal"] > 0


class TestShardFamily:
    """The sharded-writer-plane family (``make bench-shard``) at tiny
    scale — pinning the artifact schema (scripts/check_churn_schema.py)
    and the tentpole invariants: a 3-shard fleet over one shared store
    out-churns the single-leader daemon (the partitioned version-lock
    mechanism — the tiny cell gates a reduced floor; the make target's
    default run self-gates the full 2.2x), every cell is error-free,
    and hard-killing one shard's leader mid-load leaves the survivors
    unharmed while the victim's keyspace recovers within the TTL-bounded
    budget on a survivor."""

    @pytest.fixture(scope="class")
    def shard(self):
        return bench.measure_control_plane_shard(
            n_cycles=8, ttl_s=1.2, store_rtt_ms=30.0, clients=16,
            speedup_min=1.5)

    def test_schema_checker_accepts_the_emitted_line(self, shard):
        sys.path.insert(0, str(Path(__file__).resolve().parent.parent
                               / "scripts"))
        try:
            from check_churn_schema import validate_lines
        finally:
            sys.path.pop(0)
        line = {"metric": "control_plane_shard_churn_speedup",
                "value": shard["speedup"], "unit": "x",
                "vs_baseline": 1.0, "extra": shard}
        assert validate_lines([line]) == []
        # the checker is not a rubber stamp: a broken gate must fail it
        bad = json.loads(json.dumps(line))
        bad["extra"]["gates"]["ok"] = False
        assert any("gate" in p for p in validate_lines([bad]))
        # ... a speedup that contradicts the raw cell rates must fail
        bad = json.loads(json.dumps(line))
        bad["extra"]["speedup"] = 99.0
        assert any("stale arithmetic" in p for p in validate_lines([bad]))
        # ... hidden survivor failures must fail
        bad = json.loads(json.dumps(line))
        bad["extra"]["blast_radius"]["survivor"]["failures"] = 7
        assert any("survivor failures" in p for p in validate_lines([bad]))
        # ... and a blast phase that never drove the survivors is vacuous
        bad = json.loads(json.dumps(line))
        bad["extra"]["blast_radius"]["survivor"]["requests"] = 0
        assert any("never driven" in p for p in validate_lines([bad]))

    def test_shard_gates_hold(self, shard):
        gates = shard["gates"]
        assert gates["ok"] is True
        # the tentpole: partitioning the writer plane buys real
        # throughput over ONE shared store (reduced tiny-scale floor)
        assert shard["speedup"] >= 1.5
        assert gates["cells_error_free"] is True
        # blast radius: one dead shard leader harms <= 1/N of the
        # keyspace and nothing else
        assert gates["survivors_zero_failures"] is True
        assert gates["survivor_p95_ok"] is True
        assert gates["victim_recovered_in_budget"] is True
        assert shard["blast_radius"]["survivor"]["requests"] >= 1
        assert shard["cells"]["one_shard"]["cycles"] \
            == shard["cells"]["sharded"]["cycles"]


@pytest.mark.slow
def test_headline_prints_first_end_to_end():
    """Full subprocess run on CPU: line 1 is the backend-boot diagnostic
    (emitted BEFORE any backend-dependent work, so the artifact is never
    empty even when the backend wedges), line 2 is the headline, every
    line parses, and the last line repeats the headline with a compact
    rider digest (so a last-line tail parse also lands on the headline)."""
    import subprocess

    proc = subprocess.run(
        [sys.executable, "bench.py", "--preset", "tiny", "--platform",
         "cpu", "--steps", "2", "--warmup", "1"],
        cwd=Path(__file__).resolve().parent.parent,
        capture_output=True, text=True, timeout=600,
        env={**__import__("os").environ, "JAX_PLATFORMS": "cpu"})
    assert proc.returncode == 0, proc.stderr[-2000:]
    lines = [json.loads(ln) for ln in proc.stdout.splitlines() if ln]
    assert len(lines) >= 3
    boot = lines[0]
    assert boot["metric"] == "bench_boot"
    assert SCHEMA_KEYS <= set(boot)
    assert boot["rc"] == 0 and boot["value"] >= 1
    assert boot["extra"]["platform"] == "cpu"
    assert lines[1]["metric"] == "llama_train_tokens_per_sec_per_chip"
    assert SCHEMA_KEYS <= set(lines[1])
    last = lines[-1]
    assert last["metric"] == "llama_train_tokens_per_sec_per_chip"
    assert "riders" in last["extra"]
    # the tail-parse anchor stays compact (r3's parsed:null was a
    # multi-KB line overflowing the driver's bounded tail read)
    assert len(json.dumps(last)) < 1024


class TestFamilyBudget:
    """Per-family wall budgets: a hung control-plane family must emit a
    structured timeout line within ITS slice of the wall — incrementally,
    before the driver's hard kill — and the families queued behind it
    still run."""

    def test_fast_family_passes_through_with_wall_stamp(self, monkeypatch):
        monkeypatch.setattr(bench, "_run_cp_family",
                            lambda fam, args: {"family": fam})
        cp = bench._run_cp_family_budgeted("churn", None, 5.0)
        assert cp["family"] == "churn"
        assert cp["wall_s"] >= 0

    def test_hung_family_raises_timeout_within_budget(self, monkeypatch):
        monkeypatch.setattr(bench, "_run_cp_family",
                            lambda fam, args: time.sleep(60))
        t0 = time.monotonic()
        with pytest.raises(TimeoutError, match="wall budget"):
            bench._run_cp_family_budgeted("churn", None, 0.1)
        assert time.monotonic() - t0 < 5

    def test_family_error_propagates_untouched(self, monkeypatch):
        def boom(fam, args):
            raise RuntimeError("family exploded")

        monkeypatch.setattr(bench, "_run_cp_family", boom)
        with pytest.raises(RuntimeError, match="family exploded"):
            bench._run_cp_family_budgeted("churn", None, 5.0)

    def test_degraded_path_contains_the_hang_and_keeps_going(
            self, monkeypatch):
        """End-to-end through degraded_control_plane_evidence: family one
        hangs past its budget → rc-1 timeout line on the artifact; family
        two still runs green; the summary line closes the artifact."""
        calls = []

        def fake_run(fam, args):
            calls.append(fam)
            if fam == "churn":
                time.sleep(30)
            return {"family": fam, "gates": {"ok": True},
                    "create_ready_ms_p50": 1.0,
                    "time_to_shrunk_ms": {"p50": 1.0}}

        monkeypatch.setattr(bench, "_run_cp_family", fake_run)
        monkeypatch.setenv("BENCH_DEGRADED_FAMILIES", "churn,resize")
        monkeypatch.setenv("BENCH_FAMILY_BUDGET_S", "0.1")
        args = bench.argparse.Namespace(family_budget=0.0)
        buf = io.StringIO()
        with redirect_stdout(buf):
            rc = bench.degraded_control_plane_evidence(
                args, time.monotonic() + 60)
        lines = [json.loads(ln) for ln in buf.getvalue().splitlines() if ln]
        assert rc == 0  # resize was green, so the artifact is partial-green
        assert calls == ["churn", "resize"]
        assert all(SCHEMA_KEYS <= set(ln) for ln in lines)
        churn_ln = next(ln for ln in lines
                        if (ln.get("error") or {}).get("family") == "churn")
        assert churn_ln["rc"] == 1
        assert "wall budget exhausted" in churn_ln["error"]["error"]
        resize_ln = next(ln for ln in lines
                         if (ln.get("extra") or {}).get("family") == "resize")
        assert resize_ln["rc"] == 0
        assert lines[-1]["metric"] == "bench_degraded"
        assert lines[-1]["value"] == 1


def test_bench_boot_line_fails_fast_on_backend_init_error():
    """A dead backend must produce a STRUCTURED first line, never a
    silent hang into the driver's kill (the class that emptied
    BENCH_r04.json / MULTICHIP_r05.json). With ``--skip-cp-evidence`` the
    legacy contract holds exactly: one line, nonzero exit."""
    import subprocess

    proc = subprocess.run(
        [sys.executable, "bench.py", "--preset", "tiny", "--platform",
         "definitely_not_a_platform", "--steps", "2", "--warmup", "1",
         "--skip-cp-evidence"],
        cwd=Path(__file__).resolve().parent.parent,
        capture_output=True, text=True, timeout=120,
        env={**__import__("os").environ, "JAX_PLATFORMS": "cpu"})
    assert proc.returncode != 0
    lines = [json.loads(ln) for ln in proc.stdout.splitlines() if ln]
    assert len(lines) == 1
    assert lines[0]["metric"] == "bench_boot"
    assert lines[0]["rc"] == 1
    assert "backend-init" in lines[0]["error"]
    assert SCHEMA_KEYS <= set(lines[0])


def test_dead_backend_degrades_to_control_plane_evidence():
    """ROADMAP item 5 first slice: WITHOUT the skip flag, a dead backend
    yields a partial-but-GREEN artifact — the bench_boot error line is
    followed by gated control-plane family lines (none needs a TPU) and a
    bench_degraded summary, and the process exits 0. Evidence degrades
    instead of vanishing."""
    import subprocess

    proc = subprocess.run(
        [sys.executable, "bench.py", "--preset", "tiny", "--platform",
         "definitely_not_a_platform", "--steps", "2", "--warmup", "1",
         "--serve-iters", "2"],
        cwd=Path(__file__).resolve().parent.parent,
        capture_output=True, text=True, timeout=180,
        env={**__import__("os").environ, "JAX_PLATFORMS": "cpu",
             # one quick family keeps the pin fast; the full default set
             # (churn,preempt,resize,serve-scale,scale) runs in real
             # BENCH captures
             "BENCH_DEGRADED_FAMILIES": "serve-scale"})
    assert proc.returncode == 0, proc.stdout[-2000:] + proc.stderr[-2000:]
    lines = [json.loads(ln) for ln in proc.stdout.splitlines() if ln]
    assert lines[0]["metric"] == "bench_boot"
    assert lines[0]["rc"] == 1  # the backend IS dead — reported, not hidden
    assert all(SCHEMA_KEYS <= set(ln) for ln in lines)
    serve = [ln for ln in lines
             if (ln.get("extra") or {}).get("family") == "serve-scale"]
    assert len(serve) == 1 and serve[0]["rc"] == 0
    assert serve[0]["extra"]["gates"]["ok"] is True
    last = lines[-1]
    assert last["metric"] == "bench_degraded"
    assert last["rc"] == 0 and last["value"] >= 1


class TestTraceFamily:
    """The trace completeness gate riding the churn family (``make
    trace-check``): at tiny scale, every audited flow must yield one
    rooted trace with >= 80% span coverage, the container delete's async
    purge tail must ride its trace, and the disabled-mode accounting must
    stay within 1% of the flow p50 — pinned in tier-1 with the schema
    checker's validate_trace tamper checks."""

    @pytest.fixture(scope="class")
    def churn(self):
        return bench.measure_control_plane_churn(n_containers=3, n_gangs=2)

    def test_trace_gates_hold(self, churn):
        tr = churn["trace"]
        gates = churn["gates"]
        assert gates["trace_ok"] is True
        assert gates["trace_rooted"] is True
        assert gates["trace_async_tail"] is True
        assert gates["trace_coverage_worst"] >= gates["trace_coverage_min"]
        assert (gates["trace_disabled_overhead_pct"]
                <= gates["trace_disabled_overhead_budget_pct"])
        flows = tr["flows"]
        assert set(flows) == {"container_create", "container_replace",
                              "container_delete", "gang_create",
                              "gang_delete"}
        for flow, f in flows.items():
            assert f["rooted"] is True, flow
            assert f["coverage"] >= 0.8, (flow, f)
            assert f["spans"] >= 2, flow
            assert f["rootMs"] > 0, flow
        # the async purge tail landed in the SAME trace as the delete
        assert flows["container_delete"]["asyncTailSpans"] >= 1
        assert tr["enabled"] is True
        # a real disabled-mode pass ran for the record
        assert tr["disabled_create_ms_p50"] > 0

    def test_schema_checker_pins_the_trace_invariants(self, churn):
        sys.path.insert(0, str(Path(__file__).resolve().parent.parent
                               / "scripts"))
        try:
            from check_churn_schema import validate_lines
        finally:
            sys.path.pop(0)
        line = {"metric": "control_plane_churn_create_ready_ms_p50",
                "value": churn["create_ready_ms_p50"], "unit": "ms",
                "vs_baseline": 1.0, "extra": churn}
        assert validate_lines([line]) == []
        # not a rubber stamp: a lost root must fail at the schema layer
        bad = json.loads(json.dumps(line))
        bad["extra"]["trace"]["flows"]["gang_create"]["rooted"] = False
        assert any("rooted" in p for p in validate_lines([bad]))
        # ... so must invisible time ...
        bad = json.loads(json.dumps(line))
        bad["extra"]["trace"]["flows"]["container_create"]["coverage"] = 0.5
        assert any("coverage" in p for p in validate_lines([bad]))
        # ... a purge tail that escaped its trace ...
        bad = json.loads(json.dumps(line))
        bad["extra"]["trace"]["flows"]["container_delete"][
            "asyncTailSpans"] = 0
        assert any("async" in p for p in validate_lines([bad]))
        # ... and a blown disabled-mode budget
        bad = json.loads(json.dumps(line))
        bad["extra"]["gates"]["trace_disabled_overhead_pct"] = 5.0
        assert any("budget" in p for p in validate_lines([bad]))
