"""Reconciler unit tier: each drift class in isolation on the fake runtime
(the chaos suite in test_chaos.py covers the crash-produced combinations)."""

import pytest

from tpu_docker_api import errors
from tpu_docker_api.runtime.fake import FakeRuntime
from tpu_docker_api.runtime.spec import ContainerSpec
from tpu_docker_api.scheduler.ports import PortScheduler
from tpu_docker_api.scheduler.slices import ChipScheduler
from tpu_docker_api.scheduler.topology import HostTopology
from tpu_docker_api.schemas.container import (
    ContainerPatchChips,
    ContainerPort,
    ContainerRun,
)
from tpu_docker_api.service.container import ContainerService
from tpu_docker_api.service.invariants import check_invariants
from tpu_docker_api.service.reconcile import Reconciler
from tpu_docker_api.state import keys
from tpu_docker_api.state.kv import MemoryKV
from tpu_docker_api.state.store import StateStore
from tpu_docker_api.state.version import VersionMap
from tpu_docker_api.state.workqueue import WorkQueue
from tpu_docker_api.telemetry.metrics import MetricsRegistry


class Env:
    def __init__(self, tmp_path):
        self.kv = MemoryKV()
        self.store = StateStore(self.kv)
        self.runtime = FakeRuntime(root=str(tmp_path))
        self.chips = ChipScheduler(HostTopology.build("v5e-8"), self.kv)
        self.ports = PortScheduler(self.kv, 40000, 40099)
        self.versions = VersionMap(self.kv, keys.VERSIONS_CONTAINER_KEY)
        self.wq = WorkQueue(self.kv)
        self.wq.start()
        self.svc = ContainerService(
            self.runtime, self.store, self.chips, self.ports,
            self.versions, self.wq,
        )
        self.registry = MetricsRegistry()
        self.rec = Reconciler(
            self.runtime, self.store, self.chips, self.ports, self.versions,
            container_svc=self.svc, registry=self.registry,
        )

    def run(self, name, chips=0, **kw):
        out = self.svc.run_container(ContainerRun(
            image_name="jax", container_name=name, chip_count=chips, **kw
        ))
        self.wq.drain()
        return out

    def check(self):
        return check_invariants(self.runtime, self.store, self.versions,
                                self.chips, self.ports)

    def close(self):
        self.wq.close()


@pytest.fixture
def env(tmp_path):
    e = Env(tmp_path)
    yield e
    e.close()


def action_kinds(report):
    return [a["action"] for a in report["actions"]]


class TestHealthySteadyState:
    def test_empty_plane_is_clean(self, env):
        assert env.rec.reconcile()["actions"] == []

    def test_running_family_untouched(self, env):
        env.run("t", chips=2, container_ports=[ContainerPort(80)])
        assert env.rec.reconcile()["actions"] == []
        assert env.check() == []

    def test_deliberately_stopped_family_untouched(self, env):
        env.run("t", chips=2)
        env.svc.stop_container("t-0")
        assert env.rec.reconcile()["actions"] == []
        assert not env.runtime.container_inspect("t-0").running

    def test_retired_versions_untouched(self, env):
        env.run("t", chips=2)
        env.svc.patch_container_chips("t", ContainerPatchChips(chip_count=3))
        env.wq.drain()
        assert env.rec.reconcile()["actions"] == []
        assert env.check() == []


class TestDriftRepair:
    def test_out_of_band_removal_frees_resources(self, env):
        env.run("t", chips=4, container_ports=[ContainerPort(80)])
        env.runtime.container_remove("t-0", force=True)
        report = env.rec.reconcile()
        assert "mark-family-lost" in action_kinds(report)
        assert len(env.chips.free_chips) == 8
        assert env.ports.n_free == 100
        assert env.check() == []
        # repair is stable: the family stays lost, no flapping
        assert env.rec.reconcile()["actions"] == []

    def test_crashed_container_restarted(self, env):
        env.run("t", chips=2)
        env.runtime.crash_container("t-0")
        report = env.rec.reconcile()
        assert action_kinds(report) == ["restart-dead"]
        assert env.runtime.container_inspect("t-0").running
        assert env.check() == []

    def test_two_running_versions_retires_stale(self, env):
        env.run("t", chips=2)
        env.svc.patch_container_chips("t", ContainerPatchChips(chip_count=3))
        env.wq.drain()
        env.runtime.container_start("t-0")  # out-of-band resurrection
        report = env.rec.reconcile()
        assert action_kinds(report) == ["retire-stale-version"]
        assert not env.runtime.container_inspect("t-0").running
        assert env.runtime.container_inspect("t-1").running
        assert env.check() == []

    def test_orphan_with_state_adopted(self, env):
        env.run("t", chips=2, container_ports=[ContainerPort(80)])
        env.versions.remove("t")  # lost pointer (simulated corruption)
        report = env.rec.reconcile()
        assert "adopt-orphan" in action_kinds(report)
        assert env.versions.get("t") == 0
        assert env.check() == []

    def test_orphan_without_state_removed(self, env):
        spec = ContainerSpec(name="ghost-0", image="jax")
        env.runtime.container_create(spec)
        report = env.rec.reconcile()
        assert action_kinds(report) == ["remove-orphan"]
        assert not env.runtime.container_exists("ghost-0")

    def test_unversioned_container_names_ignored(self, env):
        # a container not matching base-N is not ours — never touched
        env.runtime.container_create(ContainerSpec(name="foreign", image="x"))
        assert env.rec.reconcile()["actions"] == []
        assert env.runtime.container_exists("foreign")

    def test_leaked_chips_of_unknown_owner_swept(self, env):
        env.chips.apply_chips(2, owner="ghost")
        env.ports.apply_ports(1, owner="ghost")
        report = env.rec.reconcile()
        assert sorted(action_kinds(report)) == [
            "free-leaked-chips", "free-leaked-ports"]
        assert len(env.chips.free_chips) == 8
        assert env.ports.n_free == 100

    def test_shared_owner_maps_protect_job_claims(self, env):
        job_versions = VersionMap(env.kv, keys.VERSIONS_JOB_KEY)
        job_versions.set("trainjob", 0)
        env.chips.apply_chips(2, owner="trainjob")
        env.rec._shared_maps = [job_versions]
        assert env.rec.reconcile()["actions"] == []
        assert env.chips.owned_chips("trainjob") == [0, 1]

    def test_failing_repair_does_not_abort_the_sweep(self, env, monkeypatch):
        """One family's broken repair must not leave the next family's
        drift unrepaired (code review: per-action error isolation)."""
        env.run("a", chips=1)
        env.run("b", chips=1)
        env.runtime.crash_container("a-0")
        env.runtime.crash_container("b-0")

        real_restart = env.runtime.container_restart

        def flaky_restart(name):
            if name == "a-0":
                raise RuntimeError("image gone")
            real_restart(name)

        monkeypatch.setattr(env.runtime, "container_restart", flaky_restart)
        report = env.rec.reconcile()
        by_target = {a["target"]: a for a in report["actions"]}
        assert by_target["a-0"]["error"].startswith("RuntimeError")
        assert "error" not in by_target["b-0"]
        assert env.runtime.container_inspect("b-0").running
        assert 'reconcile_action_failures_total{action="restart-dead"}' \
            in env.registry.render()

    def test_orphan_sweep_rechecks_version_pointer(self, env):
        """A family that gained its version pointer after the sweep's
        snapshot (concurrent create) must not be treated as an orphan
        (code review: mid-create force-remove race)."""
        env.run("t", chips=1)
        # simulate the stale snapshot: call the orphan path directly even
        # though the family is fully registered
        env.rec._reconcile_orphan("t", [], dry_run=False)
        assert env.runtime.container_exists("t-0")
        assert env.versions.get("t") == 0

    def test_foreign_owner_sweep_rechecks_before_freeing(self, env):
        """The leak sweep re-checks ownership under the family lock before
        freeing — a claim whose family registered after the sweep's
        snapshot (in-flight create: chips claimed before the version
        pointer exists) must survive (code review)."""
        env.chips.apply_chips(2, owner="mid")   # snapshot would see "unknown"
        env.versions.set("mid", 0)              # ...then the create registers
        env.rec._free_foreign(
            lambda items, owner=None: pytest.fail(
                "freed an in-flight create's chips"),
            "mid", [0, 1])
        assert env.chips.owned_chips("mid") == [0, 1]

    def test_explicit_out_of_range_host_port_is_not_drift(self, env):
        """User-specified host ports outside the scheduler pool were never
        pool-allocated; they must not produce phantom conflicts or block
        crash restarts (code review)."""
        env.run("t", chips=1,
                container_ports=[ContainerPort(80, host_port=39000)])
        assert env.rec.reconcile()["actions"] == []
        assert env.check() == []
        env.runtime.crash_container("t-0")
        report = env.rec.reconcile()
        assert action_kinds(report) == ["restart-dead"]
        assert env.runtime.container_inspect("t-0").running

    def test_version_pointer_without_spec_rolled_back(self, env):
        env.run("t", chips=1)
        env.versions.set("t", 5)  # pointer advanced, spec never persisted
        report = env.rec.reconcile()
        assert "rollback-version-pointer" in action_kinds(report)
        assert env.versions.get("t") == 0
        assert env.check() == []


class TestDryRunAndObservability:
    def test_dry_run_reports_without_mutating(self, env):
        env.run("t", chips=2)
        env.runtime.crash_container("t-0")
        before = dict(env.kv.range_prefix("/"))
        report = env.rec.reconcile(dry_run=True)
        assert report["dryRun"] and action_kinds(report) == ["restart-dead"]
        assert dict(env.kv.range_prefix("/")) == before
        assert not env.runtime.container_inspect("t-0").running

    def test_actions_recorded_as_events_and_metrics(self, env):
        env.run("t", chips=1)
        env.runtime.crash_container("t-0")
        env.rec.reconcile()
        events = env.rec.events_view()
        assert events and events[-1]["action"] == "restart-dead"
        rendered = env.registry.render()
        assert 'reconcile_actions_total{action="restart-dead"' in rendered
        assert "reconcile_runs_total" in rendered

    def test_last_report_kept(self, env):
        assert env.rec.last_report() is None
        env.rec.reconcile()
        assert env.rec.last_report()["actions"] == []

    def test_periodic_mode_runs_and_closes(self, env):
        env.run("t", chips=1)
        env.runtime.crash_container("t-0")
        env.rec.start_periodic(0.01)
        import time
        deadline = time.time() + 5
        while time.time() < deadline:
            if env.runtime.container_inspect("t-0").running:
                break
            time.sleep(0.01)
        env.rec.close()
        assert env.runtime.container_inspect("t-0").running


class TestSchedulerClaims:
    def test_try_claim_chips_all_or_nothing(self, env):
        env.chips.apply_chips(2, owner="a")  # chips 0,1
        assert env.chips.try_claim_chips([1, 2], owner="b") == [1]
        assert env.chips.owned_chips("b") == []  # nothing claimed
        assert env.chips.try_claim_chips([2, 3], owner="b") == []
        assert env.chips.owned_chips("b") == [2, 3]
        # idempotent re-claim of own chips
        assert env.chips.try_claim_chips([2, 3], owner="b") == []

    def test_try_claim_ports_all_or_nothing(self, env):
        env.ports.apply_ports(1, owner="a")  # 40000
        assert env.ports.try_claim_ports([40000, 40001], owner="b") == [40000]
        assert env.ports.try_claim_ports([40001], owner="b") == []
        assert env.ports.status()["owners"][40001] == "b"
        # out-of-range ports are conflicts, not silent claims
        assert env.ports.try_claim_ports([99999], owner="b") == [99999]


# -- event-driven reconcile (ISSUE 12): the dirty-set + pass modes -------------


class DirtyEnv(Env):
    """Env plus the watch-fed dirty feed: an Informer over the same store
    wired into a full_interval_s reconciler, exactly the daemon's shape
    when reconcile_full_interval_s > 0."""

    def __init__(self, tmp_path, log_retain=4096):
        super().__init__(tmp_path)
        from tpu_docker_api.state.informer import Informer

        self.kv2 = self.kv  # same store; the feed watches it raw
        self.rec = Reconciler(
            self.runtime, self.store, self.chips, self.ports, self.versions,
            container_svc=self.svc, registry=self.registry,
            full_interval_s=3600,
        )
        self.informer = Informer(self.kv, keys.PREFIX + "/",
                                 registry=self.registry)
        self.rec.attach_dirty_feed(self.informer)
        self.informer.start()

    def wait_quiet(self, timeout_s=10.0):
        """Sync + the mark counter stable: events drained into the set."""
        import time

        deadline = time.time() + timeout_s
        last = -1
        while time.time() < deadline:
            if self.informer.synced:
                cur = self.rec.dirty_view()["marksTotal"]
                if cur == last:
                    return
                last = cur
            time.sleep(0.05)
        raise AssertionError("dirty feed never went quiet")

    def close(self):
        self.informer.close()
        super().close()


@pytest.fixture
def denv(tmp_path):
    e = DirtyEnv(tmp_path)
    yield e
    e.close()


class TestDirtyReconcile:
    def test_first_pass_full_then_dirty(self, denv):
        denv.run("a", chips=1)
        denv.wait_quiet()
        first = denv.rec.reconcile()
        assert first["mode"] == "full"  # startup: everything dirty once
        denv.wait_quiet()
        second = denv.rec.reconcile()
        assert second["mode"] == "dirty"

    def test_dirty_pass_visits_only_dirty_families(self, denv):
        for name in ("a", "b", "c"):
            denv.run(name, chips=1)
        denv.wait_quiet()
        denv.rec.reconcile()  # settle (full)
        denv.wait_quiet()
        denv.rec.reconcile()  # drain the marks the settle pass re-emitted
        denv.svc.stop_container("b-0")
        denv.wait_quiet()
        report = denv.rec.reconcile()
        assert report["mode"] == "dirty"
        assert report["visitedFamilies"] == 1
        assert report["actions"] == []  # a clean stop is not drift

    def test_dirty_pass_repairs_kv_visible_drift(self, denv):
        denv.run("a", chips=1)
        denv.run("noise", chips=1)
        denv.wait_quiet()
        denv.rec.reconcile()
        denv.wait_quiet()
        denv.rec.reconcile()
        # KV-visible drift: a runtime death the watcher would miss plus a
        # state touch (the put emits the event that marks the family)
        denv.runtime.crash_container("a-0")
        denv.store.put_container(denv.store.get_container("a-0"))
        denv.wait_quiet()
        report = denv.rec.reconcile()
        assert report["mode"] == "dirty"
        assert "restart-dead" in action_kinds(report)
        assert denv.runtime.container_inspect("a-0").running
        assert denv.check() == []

    def test_orphan_adoption_through_dirty_pass(self, denv):
        denv.run("a", chips=0)
        denv.wait_quiet()
        denv.rec.reconcile()
        denv.wait_quiet()
        denv.rec.reconcile()
        # the family's stored records vanish (store surgery / interrupted
        # purge): the delete events mark the family; the dirty pass sees a
        # pointer naming nothing stored and converges it away, removing
        # the now-unadoptable runtime member in the SAME pass
        denv.kv.delete_prefix(keys.family_prefix(
            keys.Resource.CONTAINERS, "a"))
        denv.wait_quiet()
        report = denv.rec.reconcile()
        assert report["mode"] == "dirty"
        assert "drop-empty-family" in action_kinds(report)
        assert denv.versions.get("a") is None
        assert not denv.runtime.container_exists("a-0")

    def test_forced_modes_and_report(self, denv):
        denv.wait_quiet()
        assert denv.rec.reconcile(mode="full")["mode"] == "full"
        assert denv.rec.reconcile(mode="dirty")["mode"] == "dirty"
        with pytest.raises(ValueError):
            denv.rec.reconcile(mode="bogus")

    def test_forced_dirty_honors_pending_full(self, denv):
        denv.wait_quiet()
        # fresh feed: full is pending (startup) — a forced dirty pass must
        # not skip the unaccounted backlog
        assert denv.rec.dirty_view()["fullPending"] is True
        assert denv.rec.reconcile(mode="dirty")["mode"] == "full"

    def test_no_feed_always_full(self, env):
        assert env.rec.reconcile(mode="dirty")["mode"] == "full"
        assert env.rec.reconcile()["mode"] == "full"

    def test_watch_lost_relist_marks_everything_dirty_once(self, tmp_path):
        denv = DirtyEnv(tmp_path.joinpath("wl"), log_retain=4)
        try:
            # rebuild the store small so ONE burst overflows the watch
            # buffer deterministically (maxlen rides log_retain)
            from tpu_docker_api.state.kv import MemoryKV
            from tpu_docker_api.state.informer import Informer

            kv = MemoryKV(log_retain=4)
            informer = Informer(kv, keys.PREFIX + "/")
            rec = Reconciler(
                denv.runtime, StateStore(kv), denv.chips, denv.ports,
                VersionMap(kv, keys.VERSIONS_CONTAINER_KEY),
                registry=MetricsRegistry(), full_interval_s=3600)
            rec.attach_dirty_feed(informer)
            informer.start()
            import time

            deadline = time.time() + 5
            while not informer.synced and time.time() < deadline:
                time.sleep(0.02)
            rec.reconcile()  # consume the startup full
            assert rec.dirty_view()["fullPending"] is False
            # one 6-event batch into a 4-slot buffer: overflow => WatchLost
            # => relist => the hook marks everything dirty again
            kv.apply([("put", f"{keys.PREFIX}/containers/x{i}/latest", "0")
                      for i in range(6)])
            deadline = time.time() + 5
            while time.time() < deadline:
                if rec.dirty_view()["fullPending"]:
                    break
                time.sleep(0.02)
            assert rec.dirty_view()["fullPending"] is True
            assert rec.dirty_view()["fullReason"] == "relist"
            assert rec.reconcile()["mode"] == "full"
            informer.close()
        finally:
            denv.close()

    def test_restart_replays_as_full_pass(self, denv, tmp_path):
        """The dirty-set is in-process: whatever was dirty when a daemon
        died is unknown, so a fresh reconciler over the same store starts
        with a pending full — nothing marked before the death is lost."""
        denv.run("a", chips=1)
        denv.wait_quiet()
        denv.rec.reconcile()
        # "restart": a second reconciler + feed over the SAME store
        from tpu_docker_api.state.informer import Informer

        informer2 = Informer(denv.kv, keys.PREFIX + "/")
        rec2 = Reconciler(
            denv.runtime, denv.store, denv.chips, denv.ports, denv.versions,
            container_svc=denv.svc, registry=MetricsRegistry(),
            full_interval_s=3600)
        rec2.attach_dirty_feed(informer2)
        informer2.start()
        try:
            assert rec2.dirty_view()["fullPending"] is True
            assert rec2.reconcile(mode="dirty")["mode"] == "full"
        finally:
            informer2.close()

    def test_crash_mid_dirty_pass_reinserts_the_batch(self, denv):
        from tpu_docker_api.service.crashpoints import SimulatedCrash, armed

        denv.run("a", chips=1)
        denv.wait_quiet()
        denv.rec.reconcile()
        denv.wait_quiet()
        denv.rec.reconcile()
        denv.svc.stop_container("a-0")
        denv.wait_quiet()
        assert denv.rec.dirty_view()["dirty"]["containers"] == 1
        with armed("reconcile.dirty_drained"):
            with pytest.raises(SimulatedCrash):
                denv.rec.reconcile(mode="dirty")
        # the drained batch went back: nothing silently lost
        assert denv.rec.dirty_view()["dirty"]["containers"] == 1
        report = denv.rec.reconcile(mode="dirty")
        assert report["visitedFamilies"] == 1
