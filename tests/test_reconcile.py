"""Reconciler unit tier: each drift class in isolation on the fake runtime
(the chaos suite in test_chaos.py covers the crash-produced combinations)."""

import pytest

from tpu_docker_api import errors
from tpu_docker_api.runtime.fake import FakeRuntime
from tpu_docker_api.runtime.spec import ContainerSpec
from tpu_docker_api.scheduler.ports import PortScheduler
from tpu_docker_api.scheduler.slices import ChipScheduler
from tpu_docker_api.scheduler.topology import HostTopology
from tpu_docker_api.schemas.container import (
    ContainerPatchChips,
    ContainerPort,
    ContainerRun,
)
from tpu_docker_api.service.container import ContainerService
from tpu_docker_api.service.invariants import check_invariants
from tpu_docker_api.service.reconcile import Reconciler
from tpu_docker_api.state import keys
from tpu_docker_api.state.kv import MemoryKV
from tpu_docker_api.state.store import StateStore
from tpu_docker_api.state.version import VersionMap
from tpu_docker_api.state.workqueue import WorkQueue
from tpu_docker_api.telemetry.metrics import MetricsRegistry


class Env:
    def __init__(self, tmp_path):
        self.kv = MemoryKV()
        self.store = StateStore(self.kv)
        self.runtime = FakeRuntime(root=str(tmp_path))
        self.chips = ChipScheduler(HostTopology.build("v5e-8"), self.kv)
        self.ports = PortScheduler(self.kv, 40000, 40099)
        self.versions = VersionMap(self.kv, keys.VERSIONS_CONTAINER_KEY)
        self.wq = WorkQueue(self.kv)
        self.wq.start()
        self.svc = ContainerService(
            self.runtime, self.store, self.chips, self.ports,
            self.versions, self.wq,
        )
        self.registry = MetricsRegistry()
        self.rec = Reconciler(
            self.runtime, self.store, self.chips, self.ports, self.versions,
            container_svc=self.svc, registry=self.registry,
        )

    def run(self, name, chips=0, **kw):
        out = self.svc.run_container(ContainerRun(
            image_name="jax", container_name=name, chip_count=chips, **kw
        ))
        self.wq.drain()
        return out

    def check(self):
        return check_invariants(self.runtime, self.store, self.versions,
                                self.chips, self.ports)

    def close(self):
        self.wq.close()


@pytest.fixture
def env(tmp_path):
    e = Env(tmp_path)
    yield e
    e.close()


def action_kinds(report):
    return [a["action"] for a in report["actions"]]


class TestHealthySteadyState:
    def test_empty_plane_is_clean(self, env):
        assert env.rec.reconcile()["actions"] == []

    def test_running_family_untouched(self, env):
        env.run("t", chips=2, container_ports=[ContainerPort(80)])
        assert env.rec.reconcile()["actions"] == []
        assert env.check() == []

    def test_deliberately_stopped_family_untouched(self, env):
        env.run("t", chips=2)
        env.svc.stop_container("t-0")
        assert env.rec.reconcile()["actions"] == []
        assert not env.runtime.container_inspect("t-0").running

    def test_retired_versions_untouched(self, env):
        env.run("t", chips=2)
        env.svc.patch_container_chips("t", ContainerPatchChips(chip_count=3))
        env.wq.drain()
        assert env.rec.reconcile()["actions"] == []
        assert env.check() == []


class TestDriftRepair:
    def test_out_of_band_removal_frees_resources(self, env):
        env.run("t", chips=4, container_ports=[ContainerPort(80)])
        env.runtime.container_remove("t-0", force=True)
        report = env.rec.reconcile()
        assert "mark-family-lost" in action_kinds(report)
        assert len(env.chips.free_chips) == 8
        assert env.ports.n_free == 100
        assert env.check() == []
        # repair is stable: the family stays lost, no flapping
        assert env.rec.reconcile()["actions"] == []

    def test_crashed_container_restarted(self, env):
        env.run("t", chips=2)
        env.runtime.crash_container("t-0")
        report = env.rec.reconcile()
        assert action_kinds(report) == ["restart-dead"]
        assert env.runtime.container_inspect("t-0").running
        assert env.check() == []

    def test_two_running_versions_retires_stale(self, env):
        env.run("t", chips=2)
        env.svc.patch_container_chips("t", ContainerPatchChips(chip_count=3))
        env.wq.drain()
        env.runtime.container_start("t-0")  # out-of-band resurrection
        report = env.rec.reconcile()
        assert action_kinds(report) == ["retire-stale-version"]
        assert not env.runtime.container_inspect("t-0").running
        assert env.runtime.container_inspect("t-1").running
        assert env.check() == []

    def test_orphan_with_state_adopted(self, env):
        env.run("t", chips=2, container_ports=[ContainerPort(80)])
        env.versions.remove("t")  # lost pointer (simulated corruption)
        report = env.rec.reconcile()
        assert "adopt-orphan" in action_kinds(report)
        assert env.versions.get("t") == 0
        assert env.check() == []

    def test_orphan_without_state_removed(self, env):
        spec = ContainerSpec(name="ghost-0", image="jax")
        env.runtime.container_create(spec)
        report = env.rec.reconcile()
        assert action_kinds(report) == ["remove-orphan"]
        assert not env.runtime.container_exists("ghost-0")

    def test_unversioned_container_names_ignored(self, env):
        # a container not matching base-N is not ours — never touched
        env.runtime.container_create(ContainerSpec(name="foreign", image="x"))
        assert env.rec.reconcile()["actions"] == []
        assert env.runtime.container_exists("foreign")

    def test_leaked_chips_of_unknown_owner_swept(self, env):
        env.chips.apply_chips(2, owner="ghost")
        env.ports.apply_ports(1, owner="ghost")
        report = env.rec.reconcile()
        assert sorted(action_kinds(report)) == [
            "free-leaked-chips", "free-leaked-ports"]
        assert len(env.chips.free_chips) == 8
        assert env.ports.n_free == 100

    def test_shared_owner_maps_protect_job_claims(self, env):
        job_versions = VersionMap(env.kv, keys.VERSIONS_JOB_KEY)
        job_versions.set("trainjob", 0)
        env.chips.apply_chips(2, owner="trainjob")
        env.rec._shared_maps = [job_versions]
        assert env.rec.reconcile()["actions"] == []
        assert env.chips.owned_chips("trainjob") == [0, 1]

    def test_failing_repair_does_not_abort_the_sweep(self, env, monkeypatch):
        """One family's broken repair must not leave the next family's
        drift unrepaired (code review: per-action error isolation)."""
        env.run("a", chips=1)
        env.run("b", chips=1)
        env.runtime.crash_container("a-0")
        env.runtime.crash_container("b-0")

        real_restart = env.runtime.container_restart

        def flaky_restart(name):
            if name == "a-0":
                raise RuntimeError("image gone")
            real_restart(name)

        monkeypatch.setattr(env.runtime, "container_restart", flaky_restart)
        report = env.rec.reconcile()
        by_target = {a["target"]: a for a in report["actions"]}
        assert by_target["a-0"]["error"].startswith("RuntimeError")
        assert "error" not in by_target["b-0"]
        assert env.runtime.container_inspect("b-0").running
        assert 'reconcile_action_failures_total{action="restart-dead"}' \
            in env.registry.render()

    def test_orphan_sweep_rechecks_version_pointer(self, env):
        """A family that gained its version pointer after the sweep's
        snapshot (concurrent create) must not be treated as an orphan
        (code review: mid-create force-remove race)."""
        env.run("t", chips=1)
        # simulate the stale snapshot: call the orphan path directly even
        # though the family is fully registered
        env.rec._reconcile_orphan("t", [], dry_run=False)
        assert env.runtime.container_exists("t-0")
        assert env.versions.get("t") == 0

    def test_foreign_owner_sweep_rechecks_before_freeing(self, env):
        """The leak sweep re-checks ownership under the family lock before
        freeing — a claim whose family registered after the sweep's
        snapshot (in-flight create: chips claimed before the version
        pointer exists) must survive (code review)."""
        env.chips.apply_chips(2, owner="mid")   # snapshot would see "unknown"
        env.versions.set("mid", 0)              # ...then the create registers
        env.rec._free_foreign(
            lambda items, owner=None: pytest.fail(
                "freed an in-flight create's chips"),
            "mid", [0, 1])
        assert env.chips.owned_chips("mid") == [0, 1]

    def test_explicit_out_of_range_host_port_is_not_drift(self, env):
        """User-specified host ports outside the scheduler pool were never
        pool-allocated; they must not produce phantom conflicts or block
        crash restarts (code review)."""
        env.run("t", chips=1,
                container_ports=[ContainerPort(80, host_port=39000)])
        assert env.rec.reconcile()["actions"] == []
        assert env.check() == []
        env.runtime.crash_container("t-0")
        report = env.rec.reconcile()
        assert action_kinds(report) == ["restart-dead"]
        assert env.runtime.container_inspect("t-0").running

    def test_version_pointer_without_spec_rolled_back(self, env):
        env.run("t", chips=1)
        env.versions.set("t", 5)  # pointer advanced, spec never persisted
        report = env.rec.reconcile()
        assert "rollback-version-pointer" in action_kinds(report)
        assert env.versions.get("t") == 0
        assert env.check() == []


class TestDryRunAndObservability:
    def test_dry_run_reports_without_mutating(self, env):
        env.run("t", chips=2)
        env.runtime.crash_container("t-0")
        before = dict(env.kv.range_prefix("/"))
        report = env.rec.reconcile(dry_run=True)
        assert report["dryRun"] and action_kinds(report) == ["restart-dead"]
        assert dict(env.kv.range_prefix("/")) == before
        assert not env.runtime.container_inspect("t-0").running

    def test_actions_recorded_as_events_and_metrics(self, env):
        env.run("t", chips=1)
        env.runtime.crash_container("t-0")
        env.rec.reconcile()
        events = env.rec.events_view()
        assert events and events[-1]["action"] == "restart-dead"
        rendered = env.registry.render()
        assert 'reconcile_actions_total{action="restart-dead"' in rendered
        assert "reconcile_runs_total" in rendered

    def test_last_report_kept(self, env):
        assert env.rec.last_report() is None
        env.rec.reconcile()
        assert env.rec.last_report()["actions"] == []

    def test_periodic_mode_runs_and_closes(self, env):
        env.run("t", chips=1)
        env.runtime.crash_container("t-0")
        env.rec.start_periodic(0.01)
        import time
        deadline = time.time() + 5
        while time.time() < deadline:
            if env.runtime.container_inspect("t-0").running:
                break
            time.sleep(0.01)
        env.rec.close()
        assert env.runtime.container_inspect("t-0").running


class TestSchedulerClaims:
    def test_try_claim_chips_all_or_nothing(self, env):
        env.chips.apply_chips(2, owner="a")  # chips 0,1
        assert env.chips.try_claim_chips([1, 2], owner="b") == [1]
        assert env.chips.owned_chips("b") == []  # nothing claimed
        assert env.chips.try_claim_chips([2, 3], owner="b") == []
        assert env.chips.owned_chips("b") == [2, 3]
        # idempotent re-claim of own chips
        assert env.chips.try_claim_chips([2, 3], owner="b") == []

    def test_try_claim_ports_all_or_nothing(self, env):
        env.ports.apply_ports(1, owner="a")  # 40000
        assert env.ports.try_claim_ports([40000, 40001], owner="b") == [40000]
        assert env.ports.try_claim_ports([40001], owner="b") == []
        assert env.ports.status()["owners"][40001] == "b"
        # out-of-range ports are conflicts, not silent claims
        assert env.ports.try_claim_ports([99999], owner="b") == [99999]
