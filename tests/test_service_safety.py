"""Resource-safety regressions from code review: double-free, shrink rollback,
concurrent flows, family-wide delete, copy-failure compensation."""

import threading

import pytest

from tpu_docker_api import errors
from tpu_docker_api.runtime.fake import FakeRuntime
from tpu_docker_api.scheduler.ports import PortScheduler
from tpu_docker_api.scheduler.slices import ChipScheduler
from tpu_docker_api.scheduler.topology import HostTopology
from tpu_docker_api.schemas.container import (
    ContainerDelete,
    ContainerPatchChips,
    ContainerPort,
    ContainerRun,
)
from tpu_docker_api.schemas.volume import parse_size
from tpu_docker_api.service.container import ContainerService
from tpu_docker_api.state import keys
from tpu_docker_api.state.kv import MemoryKV
from tpu_docker_api.state.store import StateStore
from tpu_docker_api.state.version import VersionMap
from tpu_docker_api.state.workqueue import WorkQueue


class Env:
    def __init__(self, tmp_path, acc="v5e-8", **wq_kwargs):
        self.kv = MemoryKV()
        self.store = StateStore(self.kv)
        self.runtime = FakeRuntime(root=str(tmp_path))
        self.chips = ChipScheduler(HostTopology.build(acc), self.kv)
        self.ports = PortScheduler(self.kv, 40000, 40099)
        self.versions = VersionMap(self.kv, keys.VERSIONS_CONTAINER_KEY)
        self.wq = WorkQueue(self.kv, **wq_kwargs)
        self.wq.start()
        self.svc = ContainerService(
            self.runtime, self.store, self.chips, self.ports,
            self.versions, self.wq,
        )

    def run(self, name, chips=0, **kw):
        out = self.svc.run_container(ContainerRun(
            image_name="jax", container_name=name, chip_count=chips, **kw
        ))
        self.wq.drain()
        return out

    def close(self):
        self.wq.close()


@pytest.fixture
def env(tmp_path):
    e = Env(tmp_path)
    yield e
    e.close()


def test_stop_then_delete_does_not_double_free(env):
    """A's chips, freed on stop and re-allocated to B, must survive A's delete."""
    env.run("a", chips=2)
    env.svc.stop_container("a-0")          # chips 0,1 freed
    out_b = env.run("b", chips=2)          # B takes chips 0,1
    assert out_b["chipIds"] == [0, 1]
    env.svc.delete_container("a-0", ContainerDelete(force=True))
    env.wq.drain()
    # B's chips must still be allocated to B
    status = env.chips.status()
    owners = {c["chipId"]: c["owner"] for c in status["chips"] if c["used"]}
    assert owners == {0: "b", 1: "b"}


def test_stop_then_delete_does_not_double_free_ports(env):
    env.svc.run_container(ContainerRun(
        image_name="jax", container_name="a", chip_count=0,
        container_ports=[ContainerPort(80)],
    ))
    env.wq.drain()
    env.svc.stop_container("a-0")          # port 40000 freed
    env.svc.run_container(ContainerRun(
        image_name="jax", container_name="b", chip_count=0,
        container_ports=[ContainerPort(80)],
    ))
    env.wq.drain()
    env.svc.delete_container("a-0", ContainerDelete(force=True))
    env.wq.drain()
    # b's port must still be held
    assert env.ports.status()["usedCount"] == 1


def test_failed_shrink_keeps_chips_allocated(env, monkeypatch):
    """If the replacement create fails mid-shrink, the still-running old
    container's chips must remain allocated."""
    env.run("t", chips=4)

    def boom(spec):
        raise RuntimeError("create failed")

    monkeypatch.setattr(env.runtime, "container_create", boom)
    with pytest.raises(RuntimeError):
        env.svc.patch_container_chips("t-0", ContainerPatchChips(chip_count=2))
    # old container untouched, all 4 chips still allocated
    assert env.runtime.container_inspect("t-0").running
    assert len(env.chips.free_chips) == 4


def test_concurrent_same_name_creates_one_family(tmp_path):
    env = Env(tmp_path)
    try:
        results, errs = [], []

        def create():
            try:
                results.append(env.svc.run_container(ContainerRun(
                    image_name="jax", container_name="dup", chip_count=1
                )))
            except errors.ContainerExisted:
                errs.append(1)

        threads = [threading.Thread(target=create) for _ in range(4)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        assert len(results) == 1 and len(errs) == 3
        assert env.versions.get("dup") == 0
    finally:
        env.close()


def test_concurrent_patches_serialize(tmp_path):
    env = Env(tmp_path)
    try:
        env.run("t", chips=1)
        outcomes = []

        def patch(n):
            try:
                outcomes.append(
                    env.svc.patch_container_chips("t", ContainerPatchChips(chip_count=n))
                )
            except errors.ApiError as e:
                outcomes.append(e)

        threads = [threading.Thread(target=patch, args=(n,)) for n in (2, 3)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        env.wq.drain()
        # both may succeed (serialized), but versions must be distinct
        names = [o["name"] for o in outcomes if isinstance(o, dict)]
        assert len(names) == len(set(names))
        latest = env.versions.get("t")
        assert latest == len(names)  # 0 + one bump per successful patch
    finally:
        env.close()


def test_delete_removes_all_versions(env):
    env.run("t", chips=1)
    env.svc.patch_container_chips("t-0", ContainerPatchChips(chip_count=2))
    env.wq.drain()
    env.svc.patch_container_chips("t-1", ContainerPatchChips(chip_count=3))
    env.wq.drain()
    assert env.runtime.container_list() == ["t-0", "t-1", "t-2"]
    env.svc.delete_container("t-2", ContainerDelete(
        force=True, del_etcd_info_and_version_record=True
    ))
    env.wq.drain()
    assert env.runtime.container_list() == []
    assert len(env.chips.free_chips) == 8
    # recreate after purge works from version 0
    out = env.run("t", chips=1)
    assert out["name"] == "t-0"


def test_spec_persist_is_synchronous(env):
    """A patch immediately after run must find the spec (no async persist
    race) — store write happens before run_container returns."""
    env.svc.run_container(ContainerRun(
        image_name="jax", container_name="t", chip_count=1
    ))
    # note: no wq.drain() here
    out = env.svc.patch_container_chips("t-0", ContainerPatchChips(chip_count=2))
    assert out["name"] == "t-1"
    env.wq.drain()


def test_copy_dead_letter_restarts_old_container(tmp_path, monkeypatch):
    env = Env(tmp_path, max_retries=2, backoff_base_s=0.001)
    try:
        env.run("t", chips=1)

        def bad_copy(src, dst):
            raise OSError("disk full")

        env.wq._copy = bad_copy
        env.svc.patch_container_chips("t-0", ContainerPatchChips(chip_count=2))
        env.wq.drain()
        # copy dead-lettered: compensation restarted the old container
        assert len(env.wq.dead_letters) == 1
        assert env.runtime.container_inspect("t-0").running
        assert not env.runtime.container_inspect("t-1").running
        assert env.wq.dead_letter_view()[0]["error"].startswith("OSError")
    finally:
        env.close()


def test_info_serves_historical_versions(env):
    env.run("t", chips=1)
    env.svc.patch_container_chips("t-0", ContainerPatchChips(chip_count=2))
    env.wq.drain()
    old = env.svc.get_container_info("t-0")
    assert old["state"]["version"] == 0
    assert old["runtime"]["running"] is False


def test_parse_size_fractional():
    assert parse_size("1.5GB") == int(1.5 * 1024**3)
    assert parse_size("0.5MB") == 512 * 1024
