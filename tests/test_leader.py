"""Leader election, role-split daemon, and standby HTTP contract
(docs/robustness.md "HA control plane").

The elector unit tests drive :meth:`LeaderElector.step` with a virtual
clock; the daemon tests boot two real ``Program``s over one shared KV and
assert the role split end to end over HTTP: API serving is always-on,
writer subsystems follow the lease, standbys answer mutations with 503 +
the leader hint, and ``leader_election = false`` (the default) keeps the
single-process behavior.
"""

import json
import time
import urllib.error
import urllib.request

import pytest

from tpu_docker_api import config as config_mod
from tpu_docker_api import errors
from tpu_docker_api.daemon import Program
from tpu_docker_api.runtime.fake import FakeRuntime
from tpu_docker_api.service.leader import FencedKV, LeaderElector
from tpu_docker_api.state import keys
from tpu_docker_api.state.kv import MemoryKV


def lease(kv) -> dict | None:
    raw = kv.get_or(keys.LEADER_LEASE_KEY)
    return None if raw is None else json.loads(raw)


class TestLeaderElector:
    def _pair(self, kv=None, ttl=10.0, **kwargs):
        kv = kv or MemoryKV()
        clock = {"now": 1000.0}
        mk = lambda name: LeaderElector(kv, name, ttl_s=ttl,
                                        clock=lambda: clock["now"], **kwargs)
        return kv, clock, mk("a"), mk("b")

    def test_acquire_on_empty_store_epoch_one(self):
        kv, clock, a, _ = self._pair()
        a.step()
        assert a.is_leader and a.epoch == 1
        rec = lease(kv)
        assert rec["holderId"] == "a" and rec["epoch"] == 1
        assert rec["deadline"] == pytest.approx(1000.0 + 10.0)
        assert kv.get(keys.LEADER_EPOCH_KEY) == "1"
        view = a.status_view()
        assert view["role"] == "leader" and view["fencingEpoch"] == 1

    def test_standby_defers_to_live_lease_then_steals_expired(self):
        kv, clock, a, b = self._pair()
        a.step()
        b.step()
        assert not b.is_leader  # live lease: no steal, no split brain
        assert b.status_view()["role"] == "standby"
        assert b.status_view()["holderId"] == "a"
        clock["now"] += 10.001  # a went silent past its TTL
        b.step()
        assert b.is_leader and b.epoch == 2
        assert lease(kv)["holderId"] == "b"

    def test_renew_extends_deadline_within_ttl(self):
        kv, clock, a, b = self._pair()
        a.step()
        clock["now"] += 6.0
        a.step()  # renew at t+6: deadline pushed to t+16
        assert lease(kv)["deadline"] == pytest.approx(1006.0 + 10.0)
        clock["now"] += 6.0  # t+12: original deadline passed, renewed not
        b.step()
        assert not b.is_leader  # the renewal kept the lease alive
        assert a.is_leader

    def test_deposed_leader_demotes_on_renew_and_fires_on_loss(self):
        losses = []
        kv = MemoryKV()
        clock = {"now": 0.0}
        a = LeaderElector(kv, "a", ttl_s=5.0, clock=lambda: clock["now"],
                          on_loss=lambda reason: losses.append(reason))
        b = LeaderElector(kv, "b", ttl_s=5.0, clock=lambda: clock["now"])
        a.step()
        clock["now"] += 6.0
        b.step()  # steals the expired lease
        assert b.is_leader
        a.step()  # a's renew CAS loses against b's record
        assert not a.is_leader
        assert len(losses) == 1 and "stolen" in losses[0]
        # the fencing epoch survives demotion: in-flight writes keep failing
        assert a.epoch == 1
        assert a.fence_guards() == [("value", keys.LEADER_EPOCH_KEY, "1")]

    def test_on_acquire_fires_with_epoch(self):
        acquired = []
        kv = MemoryKV()
        a = LeaderElector(kv, "a", ttl_s=5.0, clock=lambda: 0.0,
                          on_acquire=lambda epoch: acquired.append(epoch))
        a.step()
        a.step()  # renewals must NOT re-fire the callback
        assert acquired == [1]

    def test_losing_contender_stays_standby_without_callbacks(self):
        kv, clock, a, b = self._pair()
        a.step()
        # b races on the same expired view a just refreshed — the CAS on
        # the exact observed value makes b lose cleanly
        clock["now"] += 10.001
        a.step()  # a renews late but first
        b.step()  # b read the OLD record... a's renew already landed
        # exactly one leader either way
        assert a.is_leader != b.is_leader or not (a.is_leader and b.is_leader)
        assert lease(kv)["epoch"] == max(a.epoch, b.epoch)

    def test_fence_guards_empty_before_first_acquire(self):
        kv, _, a, _ = self._pair()
        assert a.fence_guards() == []
        fenced = FencedKV(kv, a.fence_guards)
        fenced.put("/boot", "ok")  # pre-acquire writes pass unfenced
        assert kv.get("/boot") == "ok"

    def test_unreadable_lease_record_is_treated_as_expired(self):
        kv, clock, a, _ = self._pair()
        kv.put(keys.LEADER_LEASE_KEY, "not json {")
        a.step()
        assert a.is_leader and a.epoch == 1

    def test_epoch_outgrows_tampered_epoch_key(self):
        """The epoch key may outrun the lease record (a release keeps it);
        acquisition must take the max of both before bumping."""
        kv, clock, a, _ = self._pair()
        kv.put(keys.LEADER_EPOCH_KEY, "41")
        a.step()
        assert a.epoch == 42

    def test_hard_close_keeps_lease_for_ttl(self):
        kv, clock, a, b = self._pair()
        a.step()
        a.close(release=False)  # the bench/chaos hard-kill model
        b.step()
        assert not b.is_leader  # lease still held until expiry
        clock["now"] += 10.001
        b.step()
        assert b.is_leader

    def test_mutation_gate_closed_until_on_acquire_completes(self):
        """accepts_mutations opens only AFTER on_acquire returns (the API
        gate must not admit writes against mirrors the leadership handoff
        is still re-seeding), and closes before on_loss fires."""
        seen = {}
        kv = MemoryKV()
        clock = {"now": 1000.0}
        a = LeaderElector(
            kv, "a", ttl_s=10.0, clock=lambda: clock["now"],
            on_acquire=lambda e: seen.update(
                during_acquire=a.accepts_mutations),
            on_loss=lambda r: seen.update(during_loss=a.accepts_mutations))
        assert not a.accepts_mutations
        a.step()
        assert seen["during_acquire"] is False  # boot window: gate closed
        assert a.accepts_mutations  # ... and opens once writers are up
        # deposed: the gate closes before the writers are torn down
        b = LeaderElector(kv, "b", ttl_s=10.0, clock=lambda: clock["now"])
        clock["now"] += 10.001
        b.step()
        a.step()  # renew loses its CAS → demote
        assert seen["during_loss"] is False
        assert not a.accepts_mutations and not a.is_leader

    def test_leader_hint_served_without_store_reads(self):
        """The 503 path must not turn a retry storm against a standby into
        store traffic: after a heartbeat observed the lease, the hint is
        answered from memory (fresh reads happen at heartbeat cadence)."""
        reads = {"n": 0}

        class _CountingReads(MemoryKV):
            def get(self, key):
                reads["n"] += 1
                return super().get(key)

        kv = _CountingReads()
        clock = {"now": 1000.0}
        mk = lambda n: LeaderElector(kv, n, ttl_s=10.0,
                                     clock=lambda: clock["now"])
        a, b = mk("a"), mk("b")
        a.step()
        b.step()  # standby heartbeat: observes a's lease
        before = reads["n"]
        for _ in range(50):
            msg = b.standby_message()
            assert "a" in msg
            assert b.leader_hint()["holderId"] == "a"
        assert reads["n"] == before  # zero store reads on the 503 path
        # the leader's own hint is equally store-free
        before = reads["n"]
        assert a.leader_hint()["holderId"] == "a"
        assert reads["n"] == before
        # the next heartbeat refreshes the observation (bounded staleness)
        clock["now"] += 10.001
        b.step()
        assert b.is_leader
        assert b.leader_hint()["holderId"] == "b"


def _ha_config(**over):
    base = dict(
        port=0, store_backend="memory", runtime_backend="fake",
        start_port=41000, end_port=41099, health_watch_interval=0,
        host_probe_interval_s=0, job_supervise_interval=0,
        reconcile_interval=0,
        leader_election=True, leader_ttl_s=5.0,
        leader_renew_interval_s=0.05,
    )
    base.update(over)
    return config_mod.Config(**base)


def call(port, method, path, body=None):
    """(http_status, envelope) — urllib raises on 503, the standby's
    whole point, so both arms funnel to one return shape."""
    req = urllib.request.Request(
        f"http://127.0.0.1:{port}{path}", method=method,
        data=json.dumps(body).encode() if body is not None else None,
        headers={"Content-Type": "application/json"})
    try:
        with urllib.request.urlopen(req) as resp:
            return resp.status, json.loads(resp.read())
    except urllib.error.HTTPError as e:
        return e.code, json.loads(e.read())


def wait_until(fn, timeout_s=10.0, what="condition"):
    deadline = time.monotonic() + timeout_s
    while time.monotonic() < deadline:
        if fn():
            return
        time.sleep(0.01)
    pytest.fail(f"timed out waiting for {what}")


class TestDaemonRoleSplit:
    @pytest.fixture()
    def fleet(self):
        """Two daemons, one shared KV + runtime, virtual lease clock (the
        heartbeat threads are real; the frozen clock pins who may steal)."""
        kv = MemoryKV()
        runtime = FakeRuntime()
        clock = {"now": 0.0}
        progs = []
        for name in ("alpha", "beta"):
            prg = Program(_ha_config(leader_id=name), host="127.0.0.1",
                          kv=kv, runtime=runtime,
                          leader_clock=lambda: clock["now"])
            prg.init()
            progs.append(prg)
        progs[0].start()
        wait_until(lambda: progs[0].leader_elector.is_leader, what="alpha lease")
        progs[1].start()
        try:
            yield kv, clock, progs
        finally:
            for prg in progs:
                try:
                    prg.stop()
                except Exception:
                    pass

    def test_standby_serves_reads_and_503s_mutations_with_hint(self, fleet):
        kv, clock, (alpha, beta) = fleet
        a_port, b_port = alpha.api_server.port, beta.api_server.port
        assert not beta.leader_elector.is_leader

        # the leader takes the mutation
        status, out = call(a_port, "POST", "/api/v1/containers", {
            "imageName": "jax", "containerName": "web", "chipCount": 0})
        assert (status, out["code"]) == (200, 200)

        # the standby serves reads — including state the leader just wrote
        # (visibility is bounded by watch lag now, not by one store read,
        # so wait for the mirror to catch up rather than racing it)
        wait_until(lambda: call(b_port, "GET",
                                "/api/v1/containers/web-0")[1]["code"] == 200,
                   what="standby observing web-0")
        status, out = call(b_port, "GET", "/api/v1/containers/web-0")
        assert (status, out["code"]) == (200, 200)
        status, out = call(b_port, "GET", "/healthz")
        assert out["data"]["role"] == "standby"
        # the standby's reads were served watch-fed, not per-read re-seeded
        assert out["data"]["informer"]["synced"] is True
        assert out["data"]["informer"]["cacheHits"] >= 1

        # ... and 503s every mutation, with the leader as the hint
        status, out = call(b_port, "POST", "/api/v1/containers", {
            "imageName": "jax", "containerName": "nope", "chipCount": 0})
        assert status == 503
        assert out["code"] == errors.NotLeader.code
        assert "alpha" in out["msg"]
        status, out = call(b_port, "DELETE", "/api/v1/containers/web")
        assert (status, out["code"]) == (503, errors.NotLeader.code)
        # nothing half-validated, nothing created
        assert beta.container_versions.get("nope") is None

        # role views agree
        _, out = call(a_port, "GET", "/api/v1/leader")
        assert out["data"]["role"] == "leader"
        assert out["data"]["holderId"] == "alpha"
        assert out["data"]["epoch"] == 1
        _, out = call(b_port, "GET", "/api/v1/leader")
        assert out["data"]["role"] == "standby"
        assert out["data"]["holderId"] == "alpha"
        _, out = call(a_port, "GET", "/healthz")
        assert out["data"]["role"] == "leader"

        # writer subsystems follow the lease: only the leader's queue runs
        assert not alpha.wq.closed
        assert beta.wq._thread is None

    def test_standby_reads_track_leader_rolls_and_deletes(self, fleet):
        """Staleness on a standby is bounded by WATCH LAG (informer read
        cache), not by the standby's lifetime: version bumps (rolling
        replace) and family deletes the leader performs after the standby
        booted must become visible within the lag bound — with zero store
        reads per request, not one."""
        kv, clock, (alpha, beta) = fleet
        a_port, b_port = alpha.api_server.port, beta.api_server.port

        status, out = call(a_port, "POST", "/api/v1/containers", {
            "imageName": "jax", "containerName": "web", "chipCount": 2})
        assert (status, out["code"]) == (200, 200)
        wait_until(lambda: beta.container_versions.get("web") == 0,
                   what="standby observing web-0")

        # the leader rolls web 0 → 1 behind the standby's back
        status, out = call(a_port, "PATCH", "/api/v1/containers/web-0/tpu",
                           {"chipCount": 4})
        assert (status, out["code"]) == (200, 200)
        wait_until(lambda: beta.container_versions.get("web") == 1,
                   what="standby observing the roll")
        status, out = call(b_port, "GET", "/api/v1/containers/web-1")
        assert (status, out["code"]) == (200, 200)

        # ... and deletes the family: the standby must not resurrect it
        status, out = call(a_port, "DELETE", "/api/v1/containers/web", {
            "force": True, "delEtcdInfoAndVersionRecord": True})
        assert (status, out["code"]) == (200, 200)
        wait_until(lambda: beta.container_versions.get("web") is None,
                   what="standby observing the delete")
        assert "web" not in beta.container_versions.snapshot()

    def test_graceful_stop_hands_over_without_ttl_wait(self, fleet):
        kv, clock, (alpha, beta) = fleet
        b_port = beta.api_server.port
        alpha.stop()  # releases the lease (clock frozen: no expiry path)
        # accepts_mutations, not is_leader: the gate stays closed until
        # beta's writer subsystems finish booting
        wait_until(lambda: beta.leader_elector.accepts_mutations,
                   what="beta lease")
        status, out = call(b_port, "POST", "/api/v1/containers", {
            "imageName": "jax", "containerName": "after", "chipCount": 0})
        assert (status, out["code"]) == (200, 200)
        _, out = call(b_port, "GET", "/api/v1/leader")
        assert out["data"]["role"] == "leader"
        assert out["data"]["epoch"] == 2  # epochs only ever go up

    def test_election_disabled_default_single_process_behavior(self, tmp_path):
        """leader_election = false (the default): no elector, writers start
        unconditionally, mutations work, /healthz says single."""
        cfg = config_mod.Config(
            port=0, store_backend="memory", runtime_backend="fake",
            start_port=41100, end_port=41199, health_watch_interval=0)
        assert cfg.leader_election is False
        prg = Program(cfg, host="127.0.0.1", kv=MemoryKV(),
                      runtime=FakeRuntime())
        prg.init()
        assert prg.leader_elector is None
        assert prg.kv is prg._raw_kv  # no fencing wrapper in the write path
        prg.start()
        try:
            port = prg.api_server.port
            assert prg.wq._thread is not None  # writers started in start()
            status, out = call(port, "POST", "/api/v1/containers", {
                "imageName": "jax", "containerName": "solo", "chipCount": 0})
            assert (status, out["code"]) == (200, 200)
            _, out = call(port, "GET", "/healthz")
            assert out["data"]["role"] == "single"
            _, out = call(port, "GET", "/api/v1/leader")
            # storeHealth reports on every role (a single-process daemon
            # browns out too); the election surface itself is unchanged
            store_health = out["data"].pop("storeHealth")
            assert store_health["mode"] == "healthy"
            assert out["data"] == {
                "election": False, "role": "single", "accepting": True,
                "selfId": None, "holderId": None, "epoch": None,
                "deadline": None, "advertise": "", "ttlS": None,
                "fencingEpoch": 0}
        finally:
            prg.stop()


class TestProgramStopPartialInit:
    """Satellite: stop() tolerates a partially-completed init, so a failed
    boot surfaces its root cause instead of an AttributeError from cleanup."""

    def test_stop_before_init_is_safe(self):
        prg = Program(config_mod.Config(store_backend="memory",
                                        runtime_backend="fake"))
        prg.stop()  # nothing initialized: must be a clean no-op

    def test_stop_after_failed_store_open_is_safe(self):
        cfg = config_mod.Config(store_backend="etcd",
                                etcd_addr="http://127.0.0.1:9",  # discard port
                                runtime_backend="fake")
        prg = Program(cfg)
        with pytest.raises(errors.StoreUnavailable):
            prg.init()
        prg.stop()  # kv/wq/pod never materialized

    def test_stop_after_mid_init_failure_closes_what_exists(self):
        """Die between the work queue and the pod (the detect sidecar is
        unreachable): stop() must close the live subsystems and skip the
        missing ones."""
        pytest.importorskip("requests")
        cfg = config_mod.Config(
            store_backend="memory", runtime_backend="fake",
            detect_tpu_addr="http://127.0.0.1:9")
        prg = Program(cfg, kv=MemoryKV(), runtime=FakeRuntime())
        with pytest.raises(Exception):
            prg.init()  # topology discovery explodes after kv/wq exist
        assert hasattr(prg, "wq") and not hasattr(prg, "pod")
        prg.stop()
        assert prg.wq.closed
