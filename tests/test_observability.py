"""Observability tests: metrics registry, /metrics + request tracing on the
HTTP surface, health watcher transitions and bounded auto-restart
(SURVEY.md §5.1/§5.3/§5.5 — all absent in the reference)."""

from __future__ import annotations

import http.client
import json

import pytest

from tpu_docker_api.runtime.fake import FakeRuntime
from tpu_docker_api.runtime.spec import ContainerSpec
from tpu_docker_api.service.watch import HealthWatcher
from tpu_docker_api.telemetry.metrics import MetricsRegistry


class TestMetricsRegistry:
    def test_counter_and_labels(self):
        r = MetricsRegistry()
        r.counter_inc("reqs", {"route": "/a"}, help="requests")
        r.counter_inc("reqs", {"route": "/a"})
        r.counter_inc("reqs", {"route": "/b"})
        text = r.render()
        assert '# TYPE reqs counter' in text
        assert 'reqs{route="/a"} 2' in text
        assert 'reqs{route="/b"} 1' in text

    def test_gauge_fn_pull(self):
        r = MetricsRegistry()
        vals = {"x": 3.0}
        r.gauge_fn("depth", lambda: vals["x"], help="queue depth")
        assert "depth 3" in r.render()
        vals["x"] = 7.0
        assert "depth 7" in r.render()

    def test_histogram_buckets(self):
        r = MetricsRegistry()
        for v in (0.001, 0.03, 2.0):
            r.observe("lat", v, {"route": "/a"}, buckets=(0.01, 0.1, 1.0))
        text = r.render()
        assert 'lat_bucket{le="0.01",route="/a"} 1' in text
        assert 'lat_bucket{le="0.1",route="/a"} 2' in text
        assert 'lat_bucket{le="1",route="/a"} 2' in text
        assert 'lat_bucket{le="+Inf",route="/a"} 3' in text
        assert 'lat_count{route="/a"} 3' in text

    def test_broken_gauge_fn_never_breaks_render(self):
        r = MetricsRegistry()
        r.gauge_fn("bad", lambda: 1 / 0)
        r.counter_inc("ok")
        assert "ok 1" in r.render()


@pytest.fixture
def api_server():
    """Minimal live server on the fake runtime (daemon wiring, port 0)."""
    from tpu_docker_api.api.app import ApiServer, build_router
    from tpu_docker_api.scheduler.ports import PortScheduler
    from tpu_docker_api.scheduler.slices import ChipScheduler
    from tpu_docker_api.scheduler.topology import HostTopology
    from tpu_docker_api.service.container import ContainerService
    from tpu_docker_api.service.volume import VolumeService
    from tpu_docker_api.state import keys
    from tpu_docker_api.state.kv import open_store
    from tpu_docker_api.state.store import StateStore
    from tpu_docker_api.state.version import VersionMap
    from tpu_docker_api.state.workqueue import WorkQueue

    kv = open_store("memory")
    store = StateStore(kv)
    runtime = FakeRuntime()
    wq = WorkQueue(kv)
    wq.start()
    chips = ChipScheduler(HostTopology.build("v5e-8"), kv)
    ports = PortScheduler(kv, 41000, 41099)
    csvc = ContainerService(
        runtime, store, chips, ports,
        VersionMap(kv, keys.VERSIONS_CONTAINER_KEY), wq)
    vsvc = VolumeService(runtime, store,
                         VersionMap(kv, keys.VERSIONS_VOLUME_KEY), wq)
    watcher = HealthWatcher(runtime, interval_s=3600,  # manual ticks only
                            restart_policy="on-failure",
                            crash_handler=csvc.handle_crash)
    router = build_router(csvc, vsvc, chips, ports, work_queue=wq,
                          health_watcher=watcher)
    server = ApiServer(router, port=0)
    server.start()
    yield server, runtime, watcher, csvc, chips, wq
    server.close()
    wq.close()
    kv.close()


def _req(port, method, path, body=None):
    conn = http.client.HTTPConnection("127.0.0.1", port, timeout=10)
    conn.request(method, path, json.dumps(body) if body else None)
    resp = conn.getresponse()
    raw = resp.read()
    headers = dict(resp.getheaders())
    conn.close()
    return raw, headers


class TestHttpObservability:
    def test_metrics_endpoint_and_request_id(self, api_server):
        server, *_ = api_server
        raw, headers = _req(server.port, "POST", "/api/v1/containers",
                            {"imageName": "jax", "containerName": "m",
                             "chipCount": 1})
        assert json.loads(raw)["code"] == 200
        assert "X-Request-Id" in headers

        raw, headers = _req(server.port, "GET", "/metrics")
        text = raw.decode()
        assert "text/plain" in headers["Content-Type"]
        assert 'api_requests_total{code="200",method="POST",route="/api/v1/containers"} 1' in text
        assert "api_request_duration_seconds_bucket" in text
        assert "tpu_chips_free 7" in text
        assert "tpu_chips_total 8" in text

    def test_request_id_propagates(self, api_server):
        server, *_ = api_server
        conn = http.client.HTTPConnection("127.0.0.1", server.port, timeout=10)
        conn.request("GET", "/healthz", headers={"X-Request-Id": "abc123"})
        resp = conn.getresponse()
        resp.read()
        assert resp.getheader("X-Request-Id") == "abc123"
        conn.close()

    def test_events_endpoint(self, api_server):
        server, runtime, watcher, *_ = api_server
        runtime.container_create(ContainerSpec(image="i", name="e-0"))
        runtime.container_start("e-0")
        watcher.poll_once()
        raw, _ = _req(server.port, "GET", "/api/v1/events")
        events = json.loads(raw)["data"]
        assert any(e["container"] == "e-0" and e["event"] == "observed"
                   for e in events)


class TestHealthWatcher:
    def _mk(self, policy="none", max_restarts=3):
        rt = FakeRuntime()
        w = HealthWatcher(rt, interval_s=3600, restart_policy=policy,
                          max_restarts=max_restarts)
        return rt, w

    def test_records_lifecycle_transitions(self):
        rt, w = self._mk()
        rt.container_create(ContainerSpec(image="i", name="c-0"))
        rt.container_start("c-0")
        w.poll_once()
        rt.container_stop("c-0")
        w.poll_once()
        rt.container_remove("c-0", force=True)
        w.poll_once()
        kinds = [e["event"] for e in w.events_view()]
        assert kinds == ["observed", "died", "removed"]

    def test_on_failure_restarts_crashed_container(self):
        rt, w = self._mk(policy="on-failure")
        rt.container_create(ContainerSpec(image="i", name="c-0"))
        rt.container_start("c-0")
        w.poll_once()
        rt.crash_container("c-0", exit_code=137)
        w.poll_once()
        assert rt.container_inspect("c-0").running  # restarted
        kinds = [e["event"] for e in w.events_view()]
        assert "died" in kinds and "restarted" in kinds

    def test_clean_exit_not_restarted(self):
        rt, w = self._mk(policy="on-failure")
        rt.container_create(ContainerSpec(image="i", name="c-0"))
        rt.container_start("c-0")
        w.poll_once()
        rt.crash_container("c-0", exit_code=0)
        w.poll_once()
        assert not rt.container_inspect("c-0").running

    def test_restart_budget_bounded(self):
        rt, w = self._mk(policy="on-failure", max_restarts=2)
        rt.container_create(ContainerSpec(image="i", name="c-0"))
        rt.container_start("c-0")
        w.poll_once()
        for _ in range(4):
            rt.crash_container("c-0", exit_code=1)
            w.poll_once()
        kinds = [e["event"] for e in w.events_view()]
        assert kinds.count("restarted") == 2
        assert "restart-budget-exhausted" in kinds


class TestCrashRecoveryIntegration:
    """Watcher + ContainerService.handle_crash: recovery honors declarative
    liveness and scheduler accounting (no double allocation)."""

    def test_crash_of_desired_running_container_recovers(self, api_server):
        from tpu_docker_api.schemas.container import ContainerRun

        _, runtime, watcher, csvc, chips, wq = api_server
        csvc.run_container(ContainerRun.from_dict(
            {"imageName": "jax", "containerName": "crashy", "chipCount": 2}))
        watcher.poll_once()
        free_before = chips.status()["freeChips"]
        runtime.crash_container("crashy-0", exit_code=137)
        watcher.poll_once()
        assert runtime.container_inspect("crashy-0").running
        # crash + recovery must not touch chip accounting
        assert chips.status()["freeChips"] == free_before

    def test_user_stop_never_resurrected(self, api_server):
        from tpu_docker_api.schemas.container import ContainerRun

        _, runtime, watcher, csvc, chips, wq = api_server
        csvc.run_container(ContainerRun.from_dict(
            {"imageName": "jax", "containerName": "stoppy", "chipCount": 2}))
        watcher.poll_once()
        csvc.stop_container("stoppy-0")  # releases chips, desired_running=False
        free_after_stop = chips.status()["freeChips"]
        # docker-style: deliberate stop still reports a nonzero exit code
        runtime.crash_container("stoppy-0", exit_code=143)
        watcher.poll_once()
        assert not runtime.container_inspect("stoppy-0").running
        kinds = [e["event"] for e in watcher.events_view()]
        assert "restart-declined" in kinds
        # and the released chips stay released (no double allocation setup)
        assert chips.status()["freeChips"] == free_after_stop

    def test_retired_version_not_resurrected(self, api_server):
        from tpu_docker_api.schemas.container import (
            ContainerPatchChips,
            ContainerRun,
        )

        _, runtime, watcher, csvc, chips, wq = api_server
        csvc.run_container(ContainerRun.from_dict(
            {"imageName": "jax", "containerName": "roll", "chipCount": 1}))
        watcher.poll_once()
        csvc.patch_container_chips("roll-0", ContainerPatchChips(chip_count=2))
        wq.drain()  # quiesce->copy->start is ordered on the work queue
        watcher.poll_once()  # observe roll-1, see roll-0 died
        if runtime.container_inspect("roll-0").running:
            runtime.crash_container("roll-0", exit_code=1)
        watcher.poll_once()
        assert not runtime.container_inspect("roll-0").running
        assert runtime.container_inspect("roll-1").running


def test_debug_threads_dump(api_server):
    """GET /debug/threads: the pprof-goroutine analog (SURVEY.md §5.1) —
    every live thread appears with a python stack."""
    server, *_ = api_server
    raw, _headers = _req(server.port, "GET", "/api/v1/debug/threads")
    out = json.loads(raw)
    assert out["code"] == 200
    threads = out["data"]["threads"]
    assert len(threads) >= 2  # main + http worker at minimum
    names = {t["name"] for t in threads}
    assert any(t["stack"] for t in threads)
    assert any("MainThread" in n for n in names)


class TestBuildInfo:
    """Reference parity: ldflags-injected BRANCH/VERSION/COMMIT
    (cmd/gpu-docker-api/main.go:25-31) — here env-or-git resolved and
    surfaced on /healthz."""

    def test_env_override_wins(self, monkeypatch):
        from tpu_docker_api import buildinfo

        buildinfo.build_info.cache_clear()
        monkeypatch.setenv("TPU_DOCKER_API_VERSION", "v9.9")
        monkeypatch.setenv("TPU_DOCKER_API_BRANCH", "rel")
        monkeypatch.setenv("TPU_DOCKER_API_COMMIT", "abc123")
        try:
            assert buildinfo.build_info() == {
                "version": "v9.9", "branch": "rel", "commit": "abc123"}
        finally:
            buildinfo.build_info.cache_clear()

    def test_fields_always_present(self):
        from tpu_docker_api.buildinfo import build_info

        info = build_info()
        assert set(info) == {"version", "branch", "commit"}
        assert all(isinstance(v, str) and v for v in info.values())


class TestExpositionConformance:
    """Prometheus text-exposition conformance (ISSUE 14 satellite): the
    format was previously unpinned — a malformed line (raw newline in a
    label, HELP after series, one name under two types) would ship
    silently and break every scraper downstream."""

    @staticmethod
    def _parse(text):
        """(help_lines, type_lines, series) with line indexes."""
        helps, types, series = {}, {}, []
        for i, line in enumerate(text.rstrip("\n").split("\n")):
            if line.startswith("# HELP "):
                name = line.split(" ", 3)[2]
                assert name not in helps, f"duplicate HELP for {name}"
                helps[name] = i
            elif line.startswith("# TYPE "):
                name = line.split(" ", 3)[2]
                assert name not in types, f"duplicate TYPE for {name}"
                types[name] = i
            elif line.strip():
                series.append((i, line))
        return helps, types, series

    def test_help_then_type_then_series_ordering(self):
        from tpu_docker_api.telemetry.metrics import MetricsRegistry

        reg = MetricsRegistry()
        reg.counter_inc("a_total", {"x": "1"}, help="a counter")
        reg.gauge_set("b_gauge", 2.0, help="a gauge")
        reg.observe("c_ms", 1.5, help="a histogram")
        helps, types, series = self._parse(reg.render())
        for name in ("a_total", "b_gauge", "c_ms"):
            assert helps[name] < types[name], f"{name}: TYPE before HELP"
        for i, line in series:
            base = line.split("{")[0].split(" ")[0]
            base = (base.removesuffix("_bucket").removesuffix("_sum")
                    .removesuffix("_count"))
            assert types[base] < i, f"series line {line!r} before its TYPE"

    def test_label_escaping_survives_hostile_values(self):
        from tpu_docker_api.telemetry.metrics import MetricsRegistry

        reg = MetricsRegistry()
        reg.counter_inc("esc_total",
                        {"path": 'a"b\\c\nd'}, help="hostile labels")
        text = reg.render()
        # exactly one series line — an unescaped newline would split it
        lines = [ln for ln in text.split("\n")
                 if ln.startswith("esc_total{")]
        assert len(lines) == 1
        assert lines[0] == 'esc_total{path="a\\"b\\\\c\\nd"} 1'

    def test_help_escaping(self):
        from tpu_docker_api.telemetry.metrics import MetricsRegistry

        reg = MetricsRegistry()
        reg.counter_inc("h_total", help="line one\nline two \\ slash")
        text = reg.render()
        assert "# HELP h_total line one\\nline two \\\\ slash" in text
        assert "\nline two" not in text.replace("\\nline two", "")

    def test_duplicate_name_different_type_fails_loudly(self):
        from tpu_docker_api.telemetry.metrics import MetricsRegistry

        reg = MetricsRegistry()
        reg.counter_inc("dup_metric", help="as counter")
        with pytest.raises(ValueError, match="already registered"):
            reg.gauge_set("dup_metric", 1.0)
        with pytest.raises(ValueError, match="already registered"):
            reg.observe("dup_metric", 1.0)
        with pytest.raises(ValueError, match="already registered"):
            reg.gauge_fn("dup_metric", lambda: 0.0)
        # same type re-registration stays fine
        reg.counter_inc("dup_metric")
        assert reg.counter_value("dup_metric") == 2.0

    def test_histogram_buckets_cumulative_and_ordered(self):
        from tpu_docker_api.telemetry.metrics import MetricsRegistry

        reg = MetricsRegistry()
        for v in (0.001, 0.3, 7.0):
            reg.observe("lat_s", v, help="latencies")
        text = reg.render()
        buckets = []
        for line in text.split("\n"):
            if line.startswith("lat_s_bucket"):
                le = line.split('le="')[1].split('"')[0]
                buckets.append((le, int(line.rsplit(" ", 1)[1])))
        assert buckets[-1][0] == "+Inf" and buckets[-1][1] == 3
        counts = [c for _, c in buckets]
        assert counts == sorted(counts), "bucket counts must be cumulative"
        assert "lat_s_count 3" in text
