"""Continuous-batching slot engine (infer/slots.py).

The correctness contract: per-stream outputs are token-exact vs an
isolated greedy decode of the same prompt through the legacy
whole-generation engine (infer/engine.py make_generate_fn), for any
admission order, slot reuse, and mixed prompt lengths — the VERDICT r2
item-1 "done" bar.
"""

import threading

import jax
import jax.numpy as jnp
import numpy as np
import pytest

#: JAX-compile heavy: excluded from the `-m 'not slow'` quick tier so it
#: fits its time budget; still runs in `make test` (the full suite)
pytestmark = pytest.mark.slow


from tpu_docker_api.infer.engine import GenerateConfig, make_generate_fn
from tpu_docker_api.infer.slots import Handle, SlotEngine, _default_buckets
from tpu_docker_api.models.llama import LlamaConfig, llama_init, llama_presets

MAX_SEQ = 96


@pytest.fixture(scope="module")
def setup():
    cfg = llama_presets()["tiny"]
    params = llama_init(cfg, jax.random.PRNGKey(7))
    return cfg, params


def isolated_greedy(cfg, params, prompt, max_new, eos_id=None,
                    max_seq=MAX_SEQ):
    """Reference decode: the legacy engine, batch of one."""
    fn = make_generate_fn(
        cfg, GenerateConfig(max_new_tokens=max_new, temperature=0.0,
                            eos_id=eos_id, max_seq=max_seq))
    out = fn(params, jnp.asarray([prompt], jnp.int32), jax.random.PRNGKey(0))
    toks = np.asarray(out["tokens"])[0]
    n = int(np.asarray(out["lengths"])[0])
    return toks[:n].tolist()


class TestTokenExact:
    def test_single_request_matches_isolated(self, setup):
        cfg, params = setup
        eng = SlotEngine(cfg, params, slots=4, max_seq=MAX_SEQ, chunk=4)
        prompt = [3, 1, 4, 1, 5]
        h = eng.submit(prompt, max_new=12)
        while not h.done():
            assert eng.step()
        got = h.result(0)
        assert got["tokens"] == isolated_greedy(cfg, params, prompt, 12)
        assert got["length"] == 12

    def test_concurrent_mixed_lengths_token_exact(self, setup):
        cfg, params = setup
        eng = SlotEngine(cfg, params, slots=4, max_seq=MAX_SEQ, chunk=4)
        prompts = [[2, 7, 1], [9] * 20, [5, 5], [1, 2, 3, 4, 5, 6, 7],
                   [8, 6, 4], [11, 13]]
        max_news = [10, 6, 13, 9, 5, 16]
        handles = [eng.submit(p, m) for p, m in zip(prompts, max_news)]
        for _ in range(200):
            if all(h.done() for h in handles):
                break
            eng.step()
        for p, m, h in zip(prompts, max_news, handles):
            assert h.result(0)["tokens"] == isolated_greedy(cfg, params, p, m)

    def test_staggered_admission_and_slot_reuse(self, setup):
        """More requests than slots, submitted while decode is running —
        slots must recycle and late requests stay token-exact."""
        cfg, params = setup
        eng = SlotEngine(cfg, params, slots=2, max_seq=MAX_SEQ, chunk=3)
        prompts = [[i + 1, i + 2, i + 3] for i in range(7)]
        handles = [eng.submit(p, 8) for p in prompts[:3]]
        for step in range(300):
            eng.step()
            if step == 2:
                handles += [eng.submit(p, 8) for p in prompts[3:]]
            if len(handles) == 7 and all(h.done() for h in handles):
                break
        assert eng.stats["completed"] == 7
        for p, h in zip(prompts, handles):
            assert h.result(0)["tokens"] == isolated_greedy(cfg, params, p, 8)

    def test_eos_truncates_and_frees_slot(self, setup):
        cfg, params = setup
        # pick eos = the first greedily generated token of some prompt so
        # the request terminates on eos, not max_new
        prompt = [3, 1, 4, 1, 5]
        ref_free = isolated_greedy(cfg, params, prompt, 12)
        eos = ref_free[3]  # terminate at the 4th emitted token
        ref = isolated_greedy(cfg, params, prompt, 12, eos_id=eos)
        assert len(ref) < 12  # the run actually hits eos
        eng = SlotEngine(cfg, params, slots=2, max_seq=MAX_SEQ, chunk=4,
                         eos_id=eos)
        h = eng.submit(prompt, 12)
        while not h.done():
            eng.step()
        got = h.result(0)
        assert got["tokens"] == ref
        assert got["tokens"][-1] == eos
        assert all(s is None for s in eng._table.values())

    def test_max_new_one_completes_at_admission(self, setup):
        cfg, params = setup
        eng = SlotEngine(cfg, params, slots=2, max_seq=MAX_SEQ, chunk=4)
        h = eng.submit([4, 2], max_new=1)
        eng.step()
        got = h.result(0)
        assert got["tokens"] == isolated_greedy(cfg, params, [4, 2], 1)
        assert eng.stats["decode_chunks"] == 0  # never needed a chunk


class TestSampling:
    def test_temperature_zero_slots_unaffected_by_sampled_neighbor(self, setup):
        """A sampled stream in the next slot must not perturb greedy
        streams (per-slot temperature, shared program)."""
        cfg, params = setup
        eng = SlotEngine(cfg, params, slots=3, max_seq=MAX_SEQ, chunk=4)
        hg = eng.submit([3, 1, 4, 1, 5], 10)
        hs = eng.submit([3, 1, 4, 1, 5], 10, temperature=1.3)
        while not (hg.done() and hs.done()):
            eng.step()
        assert hg.result(0)["tokens"] == isolated_greedy(
            cfg, params, [3, 1, 4, 1, 5], 10)
        assert len(hs.result(0)["tokens"]) == 10

    def test_top_k_one_is_greedy_at_any_temperature(self, setup):
        """top_k=1 forces the argmax token regardless of temperature —
        an end-to-end proof of the traced filtering math."""
        cfg, params = setup
        prompt = [3, 1, 4, 1, 5]
        eng = SlotEngine(cfg, params, slots=2, max_seq=MAX_SEQ, chunk=4)
        h = eng.submit(prompt, 10, temperature=1.7, top_k=1)
        while not h.done():
            eng.step()
        assert h.result(0)["tokens"] == isolated_greedy(
            cfg, params, prompt, 10)

    def test_top_p_tiny_is_greedy(self, setup):
        """top_p→0 keeps only the most probable token (the first sorted
        token always survives) — again greedy-equivalent."""
        cfg, params = setup
        prompt = [2, 7, 1]
        eng = SlotEngine(cfg, params, slots=2, max_seq=MAX_SEQ, chunk=4)
        h = eng.submit(prompt, 8, temperature=1.3, top_p=1e-6)
        while not h.done():
            eng.step()
        assert h.result(0)["tokens"] == isolated_greedy(
            cfg, params, prompt, 8)

    def test_filtered_neighbor_does_not_perturb_greedy_slot(self, setup):
        """A top-k sampled stream co-batched with a greedy stream: the
        greedy slot stays token-exact even though the chunk runs the
        filtered variant."""
        cfg, params = setup
        prompt = [3, 1, 4, 1, 5]
        eng = SlotEngine(cfg, params, slots=2, max_seq=MAX_SEQ, chunk=4)
        hg = eng.submit(prompt, 10)
        hs = eng.submit(prompt, 10, temperature=1.2, top_k=5)
        while not (hg.done() and hs.done()):
            eng.step()
        assert hg.result(0)["tokens"] == isolated_greedy(
            cfg, params, prompt, 10)
        assert len(hs.result(0)["tokens"]) == 10

    def test_filtered_variant_compiles_only_when_needed(self, setup):
        cfg, params = setup
        eng = SlotEngine(cfg, params, slots=2, max_seq=MAX_SEQ, chunk=4)
        h = eng.submit([1, 2, 3], 6)  # pure greedy
        while not h.done():
            eng.step()
        assert all(not filt for _, filt in eng._decode_fns)
        with pytest.raises(ValueError, match="top_k"):
            eng.submit([1, 2], 4, top_k=-1)
        with pytest.raises(ValueError, match="top_p"):
            eng.submit([1, 2], 4, top_p=0.0)

    def test_sampled_tokens_vary_across_requests(self, setup):
        cfg, params = setup
        eng = SlotEngine(cfg, params, slots=2, max_seq=MAX_SEQ, chunk=4,
                         seed=123)
        outs = []
        for _ in range(3):
            h = eng.submit([1, 2, 3], 12, temperature=2.0)
            while not h.done():
                eng.step()
            outs.append(tuple(h.result(0)["tokens"]))
        assert len(set(outs)) > 1  # temperature 2 on a random-init model


class TestStreamingAndEos:
    def test_per_request_eos(self, setup):
        """Two requests, same prompt, different eos — each truncates at
        its own token (host-side check; shared compiled programs)."""
        cfg, params = setup
        prompt = [3, 1, 4, 1, 5]
        ref = isolated_greedy(cfg, params, prompt, 12)
        eos_a = ref[2]
        first_a = ref.index(eos_a) + 1
        eos_b = next(t for t in range(cfg.vocab_size) if t not in ref)
        eng = SlotEngine(cfg, params, slots=2, max_seq=MAX_SEQ, chunk=4)
        ha = eng.submit(prompt, 12, eos_id=eos_a)
        hb = eng.submit(prompt, 12, eos_id=eos_b)  # never fires
        while not (ha.done() and hb.done()):
            eng.step()
        assert ha.result(0)["tokens"] == ref[:first_a]
        assert hb.result(0)["tokens"] == ref

    def test_stream_yields_tokens_incrementally_and_exactly(self, setup):
        cfg, params = setup
        prompt = [2, 7, 1, 8]
        ref = isolated_greedy(cfg, params, prompt, 11)
        with SlotEngine(cfg, params, slots=2, max_seq=MAX_SEQ,
                        chunk=3) as eng:
            h = eng.submit(prompt, 11, stream=True)
            got = list(h.stream())
        assert got == ref
        assert h.result(0)["tokens"] == ref

    def test_stream_on_nonstreaming_handle_raises(self, setup):
        cfg, params = setup
        eng = SlotEngine(cfg, params, slots=1, max_seq=MAX_SEQ, chunk=2)
        h = eng.submit([1, 2], 3)
        with pytest.raises(RuntimeError, match="not a streaming"):
            next(h.stream())
        while not h.done():
            eng.step()

    def test_stream_surfaces_engine_failure(self, setup):
        cfg, params = setup
        eng = SlotEngine(cfg, params, slots=1, max_seq=MAX_SEQ, chunk=2)

        def boom(*a, **k):
            raise RuntimeError("synthetic failure")

        eng._admit = boom
        h = Handle(_stream=__import__("queue").SimpleQueue())
        eng._pending.put(([1, 2], 4, 0.0, None, h))
        eng.start()
        with pytest.raises(RuntimeError, match="engine failed"):
            list(h.stream())
        eng.close()


class TestAdmissionAndLimits:
    def test_rejects_before_queueing(self, setup):
        cfg, params = setup
        eng = SlotEngine(cfg, params, slots=2, max_seq=MAX_SEQ, chunk=2)
        with pytest.raises(ValueError, match="max_new"):
            eng.submit([1, 2], 0)
        with pytest.raises(ValueError, match="non-empty"):
            eng.submit([], 4)
        with pytest.raises(ValueError, match="capacity"):
            eng.submit([1] * 10, MAX_SEQ)
        with pytest.raises(ValueError, match="bucket"):
            eng.submit([1] * (MAX_SEQ + 1), 1)

    def test_default_buckets_cover_max_seq(self):
        assert _default_buckets(96) == (32, 64, 96)
        assert _default_buckets(128) == (32, 64, 128)
        assert _default_buckets(24) == (24,)

    def test_backpressure_queue_full(self, setup):
        """max_pending bounds the admission queue: submits past it shed
        load with QueueFull instead of growing latency unbounded."""
        from tpu_docker_api.infer.slots import QueueFull

        cfg, params = setup
        eng = SlotEngine(cfg, params, slots=1, max_seq=MAX_SEQ, chunk=2,
                         max_pending=2)
        handles = [eng.submit([1, 2], 4) for _ in range(2)]
        with pytest.raises(QueueFull):
            eng.submit([1, 2], 4)
        for _ in range(100):
            if all(h.done() for h in handles):
                break
            eng.step()
        assert all(h.done() for h in handles)
        eng.submit([1, 2], 4)  # queue drained: admits again

    def test_admission_burst_batches_prefills(self, setup):
        """4 same-bucket requests admitted together run ONE batched
        prefill dispatch, not 4 — and stay token-exact."""
        cfg, params = setup
        eng = SlotEngine(cfg, params, slots=4, max_seq=MAX_SEQ, chunk=4)
        prompts = [[i + 1, i + 2, i + 3] for i in range(4)]
        handles = [eng.submit(p, 6) for p in prompts]
        eng.step()
        assert eng.stats["prefills"] == 1  # one (bucket, 4) program
        while not all(h.done() for h in handles):
            eng.step()
        for p, h in zip(prompts, handles):
            assert h.result(0)["tokens"] == isolated_greedy(
                cfg, params, p, 6)

    def test_queue_deeper_than_slots_drains(self, setup):
        cfg, params = setup
        eng = SlotEngine(cfg, params, slots=2, max_seq=MAX_SEQ, chunk=4)
        handles = [eng.submit([i + 1, i + 2], 5) for i in range(6)]
        for _ in range(200):
            if all(h.done() for h in handles):
                break
            eng.step()
        assert eng.stats["completed"] == 6
        for i, h in enumerate(handles):
            assert h.result(0)["tokens"] == isolated_greedy(
                cfg, params, [i + 1, i + 2], 5)


class TestThreadedServing:
    def test_background_thread_with_concurrent_submitters(self, setup):
        """The serve-integration shape: N client threads submit while the
        engine thread drains — everything completes token-exact."""
        cfg, params = setup
        prompts = [[i + 2, i + 5, i + 1] for i in range(8)]
        refs = [isolated_greedy(cfg, params, p, 7) for p in prompts]
        results = [None] * 8

        with SlotEngine(cfg, params, slots=4, max_seq=MAX_SEQ,
                        chunk=4) as eng:
            def client(i):
                h = eng.submit(prompts[i], 7)
                results[i] = h.result(timeout=120)

            threads = [threading.Thread(target=client, args=(i,))
                       for i in range(8)]
            for t in threads:
                t.start()
            for t in threads:
                t.join(timeout=120)
        for i in range(8):
            assert results[i] is not None, f"client {i} timed out"
            assert results[i]["tokens"] == refs[i]

    def test_engine_failure_fails_handles_and_rejects_fast(self, setup):
        """An exception on the engine thread must fail every in-flight
        handle immediately (not strand clients on result timeouts) and
        mark the engine dead so submit() rejects."""
        cfg, params = setup
        eng = SlotEngine(cfg, params, slots=2, max_seq=MAX_SEQ, chunk=2)

        def boom(*a, **k):
            raise RuntimeError("synthetic dispatch failure")

        eng._admit = boom
        h = Handle()
        eng._pending.put(([1, 2], 4, 0.0, h))  # queued before the thread
        eng.start()
        with pytest.raises(RuntimeError, match="engine failed"):
            h.result(timeout=30)
        assert eng.dead and "synthetic" in eng.dead
        with pytest.raises(RuntimeError, match="engine failed"):
            eng.submit([1, 2], 4)
        eng.close()

    def test_warmup_compiles_before_start(self, setup):
        cfg, params = setup
        eng = SlotEngine(cfg, params, slots=2, max_seq=MAX_SEQ, chunk=2)
        eng.warmup()  # all buckets + decode chunk, on dummy data
        eng.start()
        with pytest.raises(RuntimeError, match="before start"):
            eng.warmup()
        h = eng.submit([1, 2, 3], 5)
        got = h.result(timeout=120)
        assert got["tokens"] == isolated_greedy(cfg, params, [1, 2, 3], 5)
        eng.close()

    def test_close_with_drain_completes_in_flight(self, setup):
        """close(drain=N): new submits reject, in-flight requests finish
        instead of failing — the serving SIGTERM contract."""
        cfg, params = setup
        eng = SlotEngine(cfg, params, slots=2, max_seq=MAX_SEQ,
                         chunk=4).start()
        h = eng.submit([5, 1, 2], 8)
        eng.close(drain=60)
        assert h.result(0)["tokens"] == isolated_greedy(
            cfg, params, [5, 1, 2], 8)
        with pytest.raises(RuntimeError, match="closed"):
            eng.submit([1], 2)

    def test_close_fails_queued_requests(self, setup):
        cfg, params = setup
        eng = SlotEngine(cfg, params, slots=1, max_seq=MAX_SEQ, chunk=2)
        h = eng.submit([1, 2], 4)  # never stepped
        eng.close()
        with pytest.raises(RuntimeError, match="closed"):
            h.result(1)
        with pytest.raises(RuntimeError, match="closed"):
            eng.submit([1, 2], 4)


class TestKvBucketedDecode:
    def test_bucketed_decode_token_exact(self, setup):
        """A cache much larger than the active positions: decode must use
        the bucketed (cache[:limit]) programs and stay token-exact."""
        cfg, params = setup
        eng = SlotEngine(cfg, params, slots=2, max_seq=384, chunk=4)
        assert eng._kv_buckets == (128, 256)
        prompts = [[3, 1, 4, 1, 5], [9, 8, 7]]
        handles = [eng.submit(p, 20) for p in prompts]
        while not all(h.done() for h in handles):
            eng.step()
        assert eng.stats["bucketed_chunks"] > 0
        assert eng.stats["bucketed_chunks"] == eng.stats["decode_chunks"]
        for p, h in zip(prompts, handles):
            assert h.result(0)["tokens"] == isolated_greedy(
                cfg, params, p, 20)

    def test_long_slot_escalates_bucket(self, setup):
        """One slot pushing past a bucket boundary moves the WHOLE batch
        to the next bucket (the limit covers every active slot)."""
        cfg, params = setup
        eng = SlotEngine(cfg, params, slots=2, max_seq=384, chunk=8)
        h = eng.submit([7] * 90, 34)  # reaches position ~124+: crosses 128
        while not h.done():
            eng.step()
        assert h.result(0)["tokens"] == isolated_greedy(
            cfg, params, [7] * 90, 34, max_seq=384)
        # both the 128 and 256 buckets were compiled and used
        assert {k for k, _ in eng._decode_fns} >= {128, 256}


class TestMeshEngine:
    """Continuous batching on a tensor-parallel mesh: the cache's kv-head
    dim shards over tp, slots stay replicated, XLA inserts collectives.
    f32 config so mesh-vs-unsharded is numerically tight."""

    def _setup(self):
        import dataclasses

        from tpu_docker_api.parallel.mesh import MeshPlan, build_mesh
        from tpu_docker_api.parallel.sharding import (
            LLAMA_RULES, param_shardings)

        cfg = dataclasses.replace(llama_presets()["tiny"],
                                  dtype=jnp.float32)
        params = llama_init(cfg, jax.random.PRNGKey(7))
        mesh = build_mesh(MeshPlan(dp=1, fsdp=1, tp=2, sp=1),
                          devices=jax.devices()[:2])
        params_s = jax.device_put(
            params, param_shardings(params, mesh, LLAMA_RULES))
        return cfg, params, params_s, mesh

    def test_tp_mesh_token_exact(self):
        cfg, params, params_s, mesh = self._setup()
        eng = SlotEngine(cfg, params_s, slots=3, max_seq=MAX_SEQ,
                         chunk=4, mesh=mesh)
        prompts = [[3, 1, 4, 1, 5], [9, 8], [2, 6, 4, 7]]
        handles = [eng.submit(p, 9) for p in prompts]
        for _ in range(200):
            if all(h.done() for h in handles):
                break
            eng.step()
        for p, h in zip(prompts, handles):
            assert h.result(0)["tokens"] == isolated_greedy(
                cfg, params, p, 9)  # unsharded single-device reference

    @pytest.mark.parametrize("plan_kw", [
        dict(dp=2), dict(sp=2), dict(pp=2), dict(ep=2)])
    def test_non_tp_meshes_rejected(self, plan_kw):
        from tpu_docker_api.parallel.mesh import MeshPlan, build_mesh

        cfg = llama_presets()["tiny"]
        params = llama_init(cfg, jax.random.PRNGKey(7))
        mesh = build_mesh(MeshPlan(dp=plan_kw.get("dp", 1), fsdp=1, tp=1,
                                   sp=plan_kw.get("sp", 1),
                                   pp=plan_kw.get("pp", 1),
                                   ep=plan_kw.get("ep", 1)),
                          devices=jax.devices()[:2])
        with pytest.raises(ValueError, match="tp/fsdp-only"):
            SlotEngine(cfg, params, slots=2, max_seq=MAX_SEQ, mesh=mesh)


class TestMoeFamily:
    def test_moe_slot_engine_token_exact_with_buckets(self):
        """The MoE family through the slot engine, including the
        bucketed kv_limit path (moe_forward_cached threads it)."""
        from tpu_docker_api.models.moe import moe_init, moe_presets

        cfg = moe_presets()["moe-tiny"]
        params = moe_init(cfg, jax.random.PRNGKey(3))
        eng = SlotEngine(cfg, params, slots=2, max_seq=192, chunk=4)
        assert eng._kv_buckets == (128,)
        prompts = [[5, 3, 1], [2, 4, 6, 8]]
        handles = [eng.submit(p, 8) for p in prompts]
        while not all(h.done() for h in handles):
            eng.step()
        assert eng.stats["bucketed_chunks"] > 0
        for p, h in zip(prompts, handles):
            assert h.result(0)["tokens"] == isolated_greedy(
                cfg, params, p, 8, max_seq=192)


class TestCacheIsolation:
    def test_long_then_short_slot_reuse_no_bleed(self, setup):
        """A short prompt reusing a slot that previously held a longer
        sequence must not attend the stale tail (per-row causal mask)."""
        cfg, params = setup
        eng = SlotEngine(cfg, params, slots=1, max_seq=MAX_SEQ, chunk=4)
        h1 = eng.submit([9] * 40, 8)
        while not h1.done():
            eng.step()
        h2 = eng.submit([2, 7], 8)
        while not h2.done():
            eng.step()
        assert h2.result(0)["tokens"] == isolated_greedy(
            cfg, params, [2, 7], 8)


class TestPrefixCache:
    """Prefix caching: suffix-only prefill against a registered prefix
    must be token-exact vs a full prefill of the same prompt (the prefix
    k/v + traced start_pos reproduce the identical math)."""

    PREFIX = [7, 3, 9, 1, 4, 4, 2, 8, 6, 5] * 4  # 40 tokens → bucket 64

    def test_mixed_prefix_and_full_admissions_token_exact(self, setup):
        cfg, params = setup
        eng = SlotEngine(cfg, params, slots=4, max_seq=MAX_SEQ, chunk=4)
        pid = eng.register_prefix(self.PREFIX)
        (snap,) = eng.prefixes()
        assert snap["id"] == pid and snap["length"] == 40
        assert snap["bytes"] == eng.stats["prefix_bytes"] > 0
        prompts = [self.PREFIX + [11, 12], self.PREFIX + [13],
                   [1, 2, 3], self.PREFIX + [11, 12]]
        handles = [eng.submit(p, 8) for p in prompts]
        for _ in range(200):
            if all(h.done() for h in handles):
                break
            eng.step()
        for p, h in zip(prompts, handles):
            assert h.result(0)["tokens"] == isolated_greedy(
                cfg, params, p, 8)
        assert eng.stats["prefix_hits"] == 3  # [1,2,3] went the full path

    def test_longest_match_and_dedup(self, setup):
        cfg, params = setup
        eng = SlotEngine(cfg, params, slots=2, max_seq=MAX_SEQ, chunk=4)
        short = eng.register_prefix(self.PREFIX[:8])
        long = eng.register_prefix(self.PREFIX)
        assert eng.register_prefix(self.PREFIX) == long  # dedup
        prompt = self.PREFIX + [21]
        assert eng._resolve_prefix(prompt).pid == long
        h = eng.submit(prompt, 6)
        while not h.done():
            eng.step()
        assert h.result(0)["tokens"] == isolated_greedy(
            cfg, params, prompt, 6)
        assert eng.unregister_prefix(long)
        # now the SHORT prefix is the longest (still strict) match
        assert eng._resolve_prefix(prompt).pid == short

    def test_prompt_equal_to_prefix_takes_full_path(self, setup):
        """A match must be STRICT (>= 1 suffix token): prompt == prefix
        runs the ordinary full prefill and stays exact."""
        cfg, params = setup
        eng = SlotEngine(cfg, params, slots=2, max_seq=MAX_SEQ, chunk=4)
        eng.register_prefix(self.PREFIX)
        h = eng.submit(list(self.PREFIX), 6)
        while not h.done():
            eng.step()
        assert h.result(0)["tokens"] == isolated_greedy(
            cfg, params, self.PREFIX, 6)
        assert eng.stats["prefix_hits"] == 0

    def test_prompt_beyond_largest_bucket_served_via_prefix(self, setup):
        """A prefix can cover the overflow of a prompt the bucket list
        alone could not serve — and unregistering it mid-flight fails
        the handle instead of the engine loop."""
        cfg, params = setup
        eng = SlotEngine(cfg, params, slots=2, max_seq=MAX_SEQ, chunk=4,
                         buckets=(16, 32))
        prefix = self.PREFIX[:30]
        pid = eng.register_prefix(prefix)
        prompt = prefix + [11, 12, 13]   # 33 > largest bucket 32
        h = eng.submit(prompt, 6)
        while not h.done():
            eng.step()
        assert h.result(0)["tokens"] == isolated_greedy(
            cfg, params, prompt, 6)
        # without the prefix the same prompt is rejected at validate
        eng2 = SlotEngine(cfg, params, slots=2, max_seq=MAX_SEQ, chunk=4,
                          buckets=(16, 32))
        with pytest.raises(ValueError, match="largest prefill bucket"):
            eng2.submit(prompt, 6)
        # race: queued via the prefix, prefix gone before admission
        h2 = eng.submit(prompt, 6)
        eng.unregister_prefix(pid)
        eng.step()
        with pytest.raises(ValueError, match="covering prefix is gone"):
            h2.result(5)

    def test_near_capacity_prefix_clamped_temp_cache_exact(self, setup):
        """plen + suffix-bucket can nominally overrun max_seq (the temp
        cache clamps and pad-tail writes drop); real positions must stay
        exact at the capacity edge."""
        cfg, params = setup
        eng = SlotEngine(cfg, params, slots=2, max_seq=MAX_SEQ, chunk=4)
        prefix = [((i * 7) % 251) + 1 for i in range(90)]  # pbucket 96
        eng.register_prefix(prefix)
        prompt = prefix + [11, 12, 13]   # 93 + sbucket 32 > max_seq 96
        h = eng.submit(prompt, 4)        # 93 + 4 - 1 = 96 = capacity
        while not h.done():
            eng.step()
        assert eng.stats["prefix_hits"] == 1
        assert h.result(0)["tokens"] == isolated_greedy(
            cfg, params, prompt, 4)

    def test_registry_capacity_and_validation(self, setup):
        cfg, params = setup
        eng = SlotEngine(cfg, params, slots=2, max_seq=MAX_SEQ, chunk=4,
                         max_prefixes=2)
        eng.register_prefix([1, 2, 3])
        eng.register_prefix([4, 5, 6])
        with pytest.raises(ValueError, match="registry full"):
            eng.register_prefix([7, 8, 9])
        with pytest.raises(ValueError, match="non-empty"):
            eng.register_prefix([])
        with pytest.raises(ValueError, match="no room"):
            eng.register_prefix([1] * (MAX_SEQ - 1))
        assert not eng.unregister_prefix("nope")

    def test_registry_byte_budget(self, setup):
        """Each prefix pins device HBM; a byte budget must reject a
        registration that would exceed it, and unregistering must return
        the bytes to the budget (ADVICE r3)."""
        cfg, params = setup
        probe = SlotEngine(cfg, params, slots=2, max_seq=MAX_SEQ, chunk=4)
        probe.register_prefix([1, 2, 3])
        per = probe.stats["prefix_bytes"]  # bucket-32 prefix cost
        eng = SlotEngine(cfg, params, slots=2, max_seq=MAX_SEQ, chunk=4,
                         max_prefix_bytes=per)
        pid = eng.register_prefix([1, 2, 3])
        with pytest.raises(ValueError, match="byte budget"):
            eng.register_prefix([4, 5, 6])
        assert eng.register_prefix([1, 2, 3]) == pid  # dedup: no charge
        assert eng.unregister_prefix(pid)
        assert eng.stats["prefix_bytes"] == 0
        eng.register_prefix([4, 5, 6])  # freed budget admits again
        assert eng.stats["prefix_bytes"] == per

    def test_speculative_engine_rejects_prefixes(self):
        from tpu_docker_api.infer.slots import SpeculativeSlotEngine

        cfg = llama_presets()["tiny"]
        params = llama_init(cfg, jax.random.PRNGKey(7))
        eng = SpeculativeSlotEngine(cfg, params, draft_cfg=cfg,
                                    draft_params=params, n_spec=2,
                                    slots=2, max_seq=MAX_SEQ)
        with pytest.raises(ValueError, match="not supported"):
            eng.register_prefix([1, 2, 3])


class TestChunkedPrefill:
    """Chunked prefill: long prompts admit in segments interleaved with
    decode — token-exact for both the segmented request and every
    concurrently decoding stream (no cache bleed from the parked row)."""

    def test_segmented_long_prompt_token_exact(self, setup):
        cfg, params = setup
        eng = SlotEngine(cfg, params, slots=2, max_seq=MAX_SEQ, chunk=4,
                         prefill_chunk=8)
        prompt = [((i * 5) % 251) + 1 for i in range(30)]  # 4 segments
        h = eng.submit(prompt, 8)
        while not h.done():
            eng.step()
        assert eng.stats["segment_prefills"] == 4
        assert eng.stats["prefills"] == 1
        assert h.result(0)["tokens"] == isolated_greedy(
            cfg, params, prompt, 8)

    def test_concurrent_stream_unharmed_by_segmented_admission(self, setup):
        """An active short stream must produce EXACTLY its isolated
        tokens while a long prompt prefills in segments next to it —
        the parked-position write-drop in action."""
        cfg, params = setup
        eng = SlotEngine(cfg, params, slots=2, max_seq=MAX_SEQ, chunk=2,
                         prefill_chunk=8)
        short = [3, 1, 4]
        h1 = eng.submit(short, 14)
        eng.step()  # h1 decoding
        long_p = [((i * 7) % 251) + 1 for i in range(40)]
        h2 = eng.submit(long_p, 6)
        while not (h1.done() and h2.done()):
            eng.step()
        assert h1.result(0)["tokens"] == isolated_greedy(
            cfg, params, short, 14)
        assert h2.result(0)["tokens"] == isolated_greedy(
            cfg, params, long_p, 6)
        assert eng.stats["segment_prefills"] == 5

    def test_concurrent_long_admissions_round_robin_exact(self, setup):
        """Several long prompts prefilling at once: segments round-robin
        (one per step — the stall bound is global, not per-slot) and
        every request stays token-exact."""
        cfg, params = setup
        eng = SlotEngine(cfg, params, slots=3, max_seq=MAX_SEQ, chunk=2,
                         prefill_chunk=8)
        short = [2, 7]
        h0 = eng.submit(short, 10)
        eng.step()
        longs = [[((i * k) % 251) + 1 for i in range(33)]
                 for k in (3, 7)]
        hs = [eng.submit(p, 5) for p in longs]
        while not (h0.done() and all(h.done() for h in hs)):
            eng.step()
        assert h0.result(0)["tokens"] == isolated_greedy(
            cfg, params, short, 10)
        for p, h in zip(longs, hs):
            assert h.result(0)["tokens"] == isolated_greedy(
                cfg, params, p, 5)
        assert eng.stats["segment_prefills"] == 10  # 2 prompts x 5 segs

    def test_short_prompts_keep_whole_prompt_admission(self, setup):
        cfg, params = setup
        eng = SlotEngine(cfg, params, slots=2, max_seq=MAX_SEQ, chunk=4,
                         prefill_chunk=16)
        h = eng.submit([1, 2, 3], 6)
        while not h.done():
            eng.step()
        assert eng.stats["segment_prefills"] == 0
        assert h.result(0)["tokens"] == isolated_greedy(
            cfg, params, [1, 2, 3], 6)

    def test_segmented_max_new_one_and_slot_reuse(self, setup):
        cfg, params = setup
        eng = SlotEngine(cfg, params, slots=1, max_seq=MAX_SEQ, chunk=4,
                         prefill_chunk=8)
        long_p = [((i * 3) % 251) + 1 for i in range(20)]
        h = eng.submit(long_p, 1)
        for _ in range(10):
            if h.done():
                break
            eng.step()
        assert h.result(0)["tokens"] == isolated_greedy(
            cfg, params, long_p, 1)
        # the slot recycles cleanly into an ordinary request
        h2 = eng.submit([9, 8], 6)
        while not h2.done():
            eng.step()
        assert h2.result(0)["tokens"] == isolated_greedy(
            cfg, params, [9, 8], 6)

    def test_sampling_through_segments(self, setup):
        """top_k=1 at temperature > 0 must equal greedy through the
        segmented path (the final segment arms the per-slot filters)."""
        cfg, params = setup
        eng = SlotEngine(cfg, params, slots=2, max_seq=MAX_SEQ, chunk=4,
                         prefill_chunk=8)
        prompt = [((i * 11) % 251) + 1 for i in range(20)]
        h = eng.submit(prompt, 6, temperature=0.9, top_k=1)
        while not h.done():
            eng.step()
        assert h.result(0)["tokens"] == isolated_greedy(
            cfg, params, prompt, 6)

    def test_long_suffix_prefers_segments_over_prefix(self, setup):
        """A prefix hit whose SUFFIX exceeds prefill_chunk falls through
        to segmentation: the bounded-stall contract outranks prefix
        reuse (one long suffix dispatch would stall every stream)."""
        cfg, params = setup
        eng = SlotEngine(cfg, params, slots=2, max_seq=MAX_SEQ, chunk=4,
                         prefill_chunk=8)
        prefix = [5, 6, 7, 8]
        eng.register_prefix(prefix)
        long_sfx = prefix + [((i * 9) % 251) + 1 for i in range(24)]
        h = eng.submit(long_sfx, 6)
        while not h.done():
            eng.step()
        assert eng.stats["prefix_hits"] == 0
        assert eng.stats["segment_prefills"] == 4  # ceil(28/8)
        assert h.result(0)["tokens"] == isolated_greedy(
            cfg, params, long_sfx, 6)
        # short suffix still rides the prefix path
        short_sfx = prefix + [11, 12]
        h2 = eng.submit(short_sfx, 6)
        while not h2.done():
            eng.step()
        assert eng.stats["prefix_hits"] == 1
        assert h2.result(0)["tokens"] == isolated_greedy(
            cfg, params, short_sfx, 6)

    def test_prompt_beyond_largest_bucket_served_by_segments(self, setup):
        """With chunked prefill on, segment clamping serves prompts the
        bucket list alone could not (no prefix needed)."""
        cfg, params = setup
        eng = SlotEngine(cfg, params, slots=2, max_seq=MAX_SEQ, chunk=4,
                         buckets=(16, 32), prefill_chunk=40)
        prompt = [((i * 13) % 251) + 1 for i in range(38)]  # > bucket 32
        h = eng.submit(prompt, 5)
        while not h.done():
            eng.step()
        assert eng.stats["segment_prefills"] == 2  # 32 + 6
        assert h.result(0)["tokens"] == isolated_greedy(
            cfg, params, prompt, 5)

    def test_speculative_rejects_prefill_chunk(self):
        from tpu_docker_api.infer.slots import SpeculativeSlotEngine

        cfg = llama_presets()["tiny"]
        params = llama_init(cfg, jax.random.PRNGKey(7))
        with pytest.raises(ValueError, match="chunked prefill"):
            SpeculativeSlotEngine(cfg, params, draft_cfg=cfg,
                                  draft_params=params, n_spec=2,
                                  slots=2, max_seq=MAX_SEQ,
                                  prefill_chunk=8)


class TestSpeculativeSlots:
    """Speculative decoding x continuous batching: greedy verification is
    token-exact vs plain greedy REGARDLESS of draft quality."""

    def _engines(self, draft_seed, n_spec=3, slots=3, draft_layers=None):
        import dataclasses

        from tpu_docker_api.infer.slots import SpeculativeSlotEngine

        cfg = llama_presets()["tiny"]
        params = llama_init(cfg, jax.random.PRNGKey(7))
        dcfg = cfg if draft_layers is None else dataclasses.replace(
            cfg, n_layers=draft_layers)
        dparams = (params if draft_seed == 7 and draft_layers is None
                   else llama_init(dcfg, jax.random.PRNGKey(draft_seed)))
        eng = SpeculativeSlotEngine(
            cfg, params, draft_cfg=dcfg, draft_params=dparams,
            n_spec=n_spec, slots=slots, max_seq=MAX_SEQ)
        return cfg, params, eng

    def test_perfect_draft_token_exact_and_fast_path(self):
        """draft == target: every proposal accepted, slots advance
        n_spec+1 per round, outputs exact."""
        cfg, params, eng = self._engines(draft_seed=7)
        prompts = [[3, 1, 4, 1, 5], [9, 8]]
        handles = [eng.submit(p, 12) for p in prompts]
        for _ in range(100):
            if all(h.done() for h in handles):
                break
            eng.step()
        for p, h in zip(prompts, handles):
            assert h.result(0)["tokens"] == isolated_greedy(
                cfg, params, p, 12)
        # acceptance ~1 must PERSIST across fully-accepted rounds (a
        # draft-cache hole at the last proposal's position would collapse
        # it): 11 new tokens at n_spec+1=4/round = 3 real rounds, plus at
        # most pipeline+1 lag rounds at the tail
        assert eng.stats["decode_chunks"] <= 3 + eng.pipeline + 1
        assert eng.stats["accepted_tokens"] > 0

    def test_garbage_draft_still_token_exact(self):
        """A random different-weights draft: proposals mostly rejected —
        the rollback path runs constantly and output stays EXACT."""
        cfg, params, eng = self._engines(draft_seed=99, draft_layers=1)
        prompts = [[2, 7, 1], [5, 5, 5, 5], [8]]
        handles = [eng.submit(p, 10) for p in prompts]
        for _ in range(300):
            if all(h.done() for h in handles):
                break
            eng.step()
        for p, h in zip(prompts, handles):
            assert h.result(0)["tokens"] == isolated_greedy(
                cfg, params, p, 10)

    def test_eos_and_slot_reuse(self):
        cfg, params, eng = self._engines(draft_seed=7, slots=1)
        prompt = [3, 1, 4, 1, 5]
        ref = isolated_greedy(cfg, params, prompt, 12)
        eos = ref[3]
        first = ref.index(eos) + 1
        h1 = eng.submit(prompt, 12, eos_id=eos)
        while not h1.done():
            eng.step()
        assert h1.result(0)["tokens"] == ref[:first]
        h2 = eng.submit([9, 2], 6)  # slot + both caches recycled
        while not h2.done():
            eng.step()
        assert h2.result(0)["tokens"] == isolated_greedy(
            cfg, params, [9, 2], 6)

    def test_sampling_rejected(self):
        cfg, params, eng = self._engines(draft_seed=7)
        with pytest.raises(ValueError, match="greedy-only"):
            eng.submit([1, 2], 4, temperature=0.5)
        with pytest.raises(ValueError, match="greedy-only"):
            eng.submit([1, 2], 4, top_k=3)

    def test_streaming_through_spec_slots(self):
        cfg, params, eng = self._engines(draft_seed=7)
        prompt = [2, 7, 1, 8]
        ref = isolated_greedy(cfg, params, prompt, 9)
        eng.start()
        h = eng.submit(prompt, 9, stream=True)
        got = list(h.stream(timeout=120))
        eng.close()
        assert got == ref


class TestLatencyStats:
    """Per-request SLO recording (VERDICT r4 next #5): the engine
    derives TTFT/ITL on completion, keeps a bounded percentile ring,
    and fans samples out to ``metrics_hook`` (the serve layer's
    Prometheus bridge)."""

    def test_samples_recorded_and_hook_called(self, setup):
        cfg, params = setup
        eng = SlotEngine(cfg, params, slots=2, max_seq=MAX_SEQ, chunk=4)
        seen = []
        eng.metrics_hook = lambda ttft, itl, n: seen.append((ttft, itl, n))
        handles = [eng.submit([1 + i, 2, 3], 6) for i in range(3)]
        for _ in range(200):
            if all(h.done() for h in handles):
                break
            eng.step()
        stats = eng.latency_stats()
        assert stats["n"] == 3
        assert stats["ttft_p50_ms"] > 0
        assert stats["itl_p50_ms"] is not None
        assert len(seen) == 3
        for ttft, itl, n in seen:
            assert ttft > 0 and n == 6
            assert itl is not None and itl >= 0
        # handles carry the raw timestamps in submit → first → done order
        h = handles[0]
        assert h.submitted_at < h.first_token_at <= h.completed_at

    def test_single_token_request_has_no_itl(self, setup):
        cfg, params = setup
        eng = SlotEngine(cfg, params, slots=2, max_seq=MAX_SEQ, chunk=4)
        h = eng.submit([5, 6], 1)
        for _ in range(100):
            if h.done():
                break
            eng.step()
        stats = eng.latency_stats()
        assert stats["n"] == 1
        assert stats["itl_p50_ms"] is None  # one token: no gap exists

    def test_hook_error_cannot_kill_serving(self, setup):
        cfg, params = setup
        eng = SlotEngine(cfg, params, slots=2, max_seq=MAX_SEQ, chunk=4)

        def bad_hook(*a):
            raise RuntimeError("sink down")

        eng.metrics_hook = bad_hook
        h = eng.submit([7, 8, 9], 4)
        for _ in range(100):
            if h.done():
                break
            eng.step()
        assert h.result(0)["length"] == 4  # completion unaffected
