"""ViT family (models/vit.py): the non-causal model — forward shapes,
training behavior, permutation equivariance sanity, and the generic trainer
with a tuple batch on a sharded mesh."""

import pytest

#: JAX-compile heavy: excluded from the `-m 'not slow'` quick tier so it
#: fits its time budget; still runs in `make test` (the full suite)
pytestmark = pytest.mark.slow


import dataclasses

import jax
import jax.numpy as jnp
import numpy as np

from tpu_docker_api.models.vit import (
    ViTConfig,
    vit_forward,
    vit_init,
    vit_loss,
    vit_presets,
    vit_synthetic_batch,
)

TINY = vit_presets()["tiny"]


class TestForward:
    def test_shapes_and_dtypes(self):
        params = vit_init(TINY, jax.random.PRNGKey(0))
        images, labels = vit_synthetic_batch(jax.random.PRNGKey(1), 4, TINY)
        logits = vit_forward(params, images, TINY)
        assert logits.shape == (4, TINY.n_classes)
        assert logits.dtype == jnp.float32
        loss = vit_loss(params, (images, labels), TINY)
        assert np.isfinite(float(loss))
        # untrained ≈ uniform over classes
        assert abs(float(loss) - np.log(TINY.n_classes)) < 0.5

    def test_presets_well_formed(self):
        for name, cfg in vit_presets().items():
            assert cfg.image_size % cfg.patch_size == 0, name
            assert cfg.dim % cfg.n_heads == 0, name
            assert cfg.flops_per_image() > 0, name
        # the TPU presets keep token counts 128-aligned for the flash kernel
        assert vit_presets()["vit-b16"].n_patches % 128 == 0

    def test_patch_permutation_changes_only_via_pos_emb(self):
        """With pos_emb zeroed, mean-pooled logits must be invariant to
        shuffling patches — catches patchify/attention wiring bugs."""
        params = vit_init(TINY, jax.random.PRNGKey(0))
        params = dict(params, pos_emb=jnp.zeros_like(params["pos_emb"]))
        images, _ = vit_synthetic_batch(jax.random.PRNGKey(2), 2, TINY)
        p = TINY.patch_size
        # swap two patch-aligned row bands (a pure patch permutation)
        shuffled = jnp.concatenate(
            [images[:, p:2 * p], images[:, :p], images[:, 2 * p:]], axis=1)
        a = vit_forward(params, images, TINY)
        b = vit_forward(params, shuffled, TINY)
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=2e-2, atol=2e-2)

    def test_remat_matches_no_remat(self):
        cfg_r = dataclasses.replace(TINY, remat=True)
        params = vit_init(TINY, jax.random.PRNGKey(0))
        batch = vit_synthetic_batch(jax.random.PRNGKey(3), 2, TINY)
        l1 = float(vit_loss(params, batch, TINY))
        l2 = float(vit_loss(params, batch, cfg_r))
        np.testing.assert_allclose(l1, l2, rtol=1e-5)


class TestTraining:
    def test_gradients_flow_everywhere(self):
        params = vit_init(TINY, jax.random.PRNGKey(0))
        batch = vit_synthetic_batch(jax.random.PRNGKey(4), 4, TINY)
        grads = jax.grad(lambda p: vit_loss(p, batch, TINY))(params)
        for path, g in jax.tree_util.tree_leaves_with_path(grads):
            assert float(jnp.abs(g.astype(jnp.float32)).max()) > 0, path

    def test_trains_through_generic_trainer_on_mesh(self):
        """The model_fns seam + tuple-batch sharding: ViT runs through the
        SAME make_train_step as the decoder families, on an fsdp/tp mesh,
        and memorizes a small fixed batch."""
        from tpu_docker_api.parallel.mesh import MeshPlan, build_mesh
        from tpu_docker_api.train.trainer import (
            create_train_state,
            default_optimizer,
            make_train_step,
        )

        mesh = build_mesh(MeshPlan(dp=2, fsdp=2, tp=2, sp=1))
        state, opt = create_train_state(
            TINY, mesh, jax.random.PRNGKey(0),
            optimizer=default_optimizer(lr=3e-3))
        step = make_train_step(TINY, mesh, opt)
        batch = vit_synthetic_batch(jax.random.PRNGKey(5), 8, TINY)
        first = None
        for _ in range(30):
            state, metrics = step(state, batch)
            if first is None:
                first = float(metrics["loss"])
        last = float(metrics["loss"])
        assert last < first * 0.5, (first, last)


class TestSyntheticData:
    def test_row_keyed_generation_is_process_count_invariant(self):
        """The resume/rescale data contract: the global batch at step i must
        not depend on how many processes generate it — each global row is
        keyed individually, so local generation with row_offset reproduces
        exactly the rows of a single-process run."""
        cfg = TINY
        k = jax.random.PRNGKey(7)
        full_i, full_l = vit_synthetic_batch(k, 8, cfg)
        a_i, a_l = vit_synthetic_batch(k, 4, cfg, row_offset=0)
        b_i, b_l = vit_synthetic_batch(k, 4, cfg, row_offset=4)
        np.testing.assert_array_equal(
            np.asarray(full_i), np.concatenate([a_i, b_i]))
        np.testing.assert_array_equal(
            np.asarray(full_l), np.concatenate([a_l, b_l]))
