"""Store brownout tier (docs/robustness.md "Store brownouts"):
``FaultyKV`` fault injection (state/faulty.py), the ``StoreHealth`` mode
machine + ``StoreHealthKV`` feed (service/store_health.py), deadline
threading (config ``store_op_deadline_s`` → backend budgets), the writer
-loop outage gates across every loop, the leader-lease-under-outage pin,
the API surfacing contract (typed 503 + Retry-After, stale envelope +
``X-Stale-Read``, /healthz + /metrics + events), and mid-flow chaos:
a mutation the outage interrupts converges after the heal.
"""

import json
import time
import types
import urllib.error
import urllib.request

import pytest

from tpu_docker_api import errors
from tpu_docker_api.config import Config
from tpu_docker_api.daemon import Program
from tpu_docker_api.runtime.fake import FakeRuntime
from tpu_docker_api.service.host_health import HostMonitor
from tpu_docker_api.service.store_health import (
    StoreHealth,
    StoreHealthKV,
    consume_stale_read,
    mark_stale_read,
)
from tpu_docker_api.state.faulty import FaultyKV
from tpu_docker_api.state.kv import MemoryKV, SqliteKV, open_store
from tpu_docker_api.telemetry.metrics import MetricsRegistry


def wait_until(fn, timeout_s=10.0, what="condition"):
    deadline = time.monotonic() + timeout_s
    while time.monotonic() < deadline:
        if fn():
            return
        time.sleep(0.005)
    pytest.fail(f"timed out waiting for {what}")


class TestFaultyKV:
    def test_fail_nth_is_deterministic_and_typed(self):
        kv = FaultyKV(MemoryKV())
        kv.put("/a", "1")
        kv.fail_nth("get", 2)
        assert kv.get("/a") == "1"          # call 1: healthy
        with pytest.raises(errors.StoreUnavailable):
            kv.get("/a")                     # call 2: scripted failure
        assert kv.get("/a") == "1"          # call 3: healed
        outcomes = [o for op, _, o in kv.calls if op == "get"]
        assert outcomes == ["ok", "fail", "ok"]

    def test_ambiguous_write_lands_then_errors(self):
        """The classic timeout-after-commit: the caller sees a store error
        but the write took effect — exactly what an idempotent retry (or
        the journal replay) must absorb."""
        kv = FaultyKV(MemoryKV())
        kv.fail_nth("put", 1, mode="ambiguous")
        with pytest.raises(errors.StoreUnavailable):
            kv.put("/amb", "landed")
        assert kv.inner.get("/amb") == "landed"
        assert ("put", "/amb", "ambiguous") in kv.calls

    def test_partition_overlaps_both_directions(self):
        """A scan of a broader prefix must fail too — it would otherwise
        silently exclude the partitioned subtree from its result."""
        kv = FaultyKV(MemoryKV())
        kv.put("/q/a", "1")
        kv.put("/other", "2")
        kv.set_partition("/q/")
        with pytest.raises(errors.StoreUnavailable):
            kv.put("/q/b", "x")              # under the partition
        with pytest.raises(errors.StoreUnavailable):
            kv.range_prefix("/")             # scan OVERLAPS the partition
        assert kv.get("/other") == "2"       # disjoint keys stay healthy
        kv.set_partition("/q/", active=False)
        assert kv.get("/q/a") == "1"

    def test_outage_covers_watch_poll(self):
        """A dead store cannot stream events: an informer that kept
        draining a live watch through an "outage" would never degrade."""
        kv = FaultyKV(MemoryKV())
        w = kv.watch("/")
        kv.put("/w/a", "1")
        assert [e.key for e in w.poll(0.01)] == ["/w/a"]
        kv.set_outage(True)
        with pytest.raises(errors.StoreUnavailable):
            w.poll(0.01)
        with pytest.raises(errors.StoreUnavailable):
            kv.get("/w/a")
        kv.set_outage(False)
        assert w.poll(0.01) == []            # drained again after heal
        w.close()

    def test_latency_window_slows_but_succeeds(self):
        kv = FaultyKV(MemoryKV())
        kv.put("/slow", "1")
        kv.set_latency(0.05)
        t0 = time.perf_counter()
        assert kv.get("/slow") == "1"
        assert time.perf_counter() - t0 >= 0.05
        kv.set_latency(0.0)
        t0 = time.perf_counter()
        assert kv.get("/slow") == "1"
        assert time.perf_counter() - t0 < 0.05

    def test_inner_passthrough(self):
        """Backend helpers reach around the fault layer (the test-harness
        seam _records(kv.inner) depends on), and unknown attrs delegate."""
        kv = FaultyKV(MemoryKV())
        kv.put("/p", "1")
        kv.set_outage(True)
        assert kv.inner.get("/p") == "1"     # the harness reaches around
        kv.set_outage(False)
        assert kv.current_rev() >= 1         # KV surface intact


class _Clock:
    def __init__(self):
        self.now = 100.0

    def __call__(self):
        return self.now


class TestStoreHealth:
    def _health(self, **kw):
        clock = _Clock()
        kw.setdefault("fail_threshold", 3)
        kw.setdefault("outage_grace_s", 2.0)
        kw.setdefault("probe_interval_s", 1.0)
        h = StoreHealth(clock=clock, registry=MetricsRegistry(), **kw)
        return h, clock

    def _fail(self, h, n=1):
        for _ in range(n):
            h.observe("get", 1.0, ok=False, error="refused")

    def test_blips_below_threshold_never_flip(self):
        h, _ = self._health()
        self._fail(h, 2)
        assert h.mode == "healthy"
        h.observe("get", 1.0, ok=True)
        self._fail(h, 2)                     # streak reset: still healthy
        assert h.mode == "healthy"
        assert h.status_view()["consecutiveFailures"] == 2

    def test_degraded_then_outage_after_grace(self):
        h, clock = self._health()
        self._fail(h, 3)
        assert h.mode == "degraded"
        assert h.allows_writes()             # degraded still writes
        clock.now += 1.9
        self._fail(h)
        assert h.mode == "degraded"          # inside the grace window
        clock.now += 0.2
        self._fail(h)
        assert h.mode == "outage"
        assert not h.allows_writes()
        assert h.serve_stale_reads()
        kinds = [e["event"] for e in h.events_view()]
        assert kinds == ["store-mode-degraded", "store-mode-outage"]

    def test_success_heals_and_fires_on_recover(self):
        h, clock = self._health()
        fired = []
        h.on_recover(lambda: fired.append(1))
        self._fail(h, 3)
        clock.now += 3.0
        self._fail(h)
        assert h.mode == "outage"
        h.observe("get", 1.0, ok=True)
        assert h.mode == "healthy"
        assert fired == [1]                  # outage → healthy fires hooks
        assert h.status_view()["outagesTotal"] == 1
        # degraded → healthy does NOT fire (nothing was held)
        self._fail(h, 3)
        h.observe("get", 1.0, ok=True)
        assert fired == [1]

    def test_app_errors_count_as_alive(self):
        """NotExistInStore & co. prove the store answered — three of them
        must not push the machine toward degraded."""
        h, _ = self._health()
        kv = StoreHealthKV(MemoryKV(), h)
        for _ in range(5):
            with pytest.raises(errors.NotExistInStore):
                kv.get("/missing")
        assert h.mode == "healthy"
        assert h.status_view()["opsOk"] == 5

    def test_admit_mutation_probe_slot_then_typed_503(self):
        h, clock = self._health()
        self._fail(h, 3)
        clock.now += 3.0
        self._fail(h)
        assert h.mode == "outage"
        h.admit_mutation()                   # first caller IS the probe
        with pytest.raises(errors.StoreDegraded) as ei:
            h.admit_mutation()               # single-flight: held
        assert ei.value.http_status == 503
        assert ei.value.code == 10506
        assert 0 < ei.value.retry_after_s <= 1.0
        assert ei.value.data == {"storeMode": "outage"}
        clock.now += 1.1                     # probe interval elapsed
        h.admit_mutation()                   # next probe admitted
        h.observe("get", 1.0, ok=True)
        h.admit_mutation()                   # healthy: free passage

    def test_healthy_path_admits_without_probe_accounting(self):
        h, _ = self._health()
        for _ in range(10):
            h.admit_mutation()
        assert h.mode == "healthy"


class TestStaleMarker:
    def test_consume_pops(self):
        """Pop semantics: a keep-alive HTTP thread serves many requests
        and a marker must never leak into the next one."""
        consume_stale_read()                 # clear any test residue
        mark_stale_read(42.0)
        assert consume_stale_read() == 42.0
        assert consume_stale_read() is None

    def test_note_stale_read_counts_and_marks(self):
        h = StoreHealth(registry=MetricsRegistry())
        consume_stale_read()
        h.note_stale_read(17.0)
        assert consume_stale_read() == 17.0
        assert h.status_view()["staleReads"] == 1


class TestDeadlineThreading:
    def test_open_store_threads_deadline_to_sqlite(self, tmp_path):
        s = open_store("sqlite", sqlite_path=str(tmp_path / "d.db"),
                       op_deadline_s=0.07)
        assert s._busy_timeout_s == 0.07
        s.close()

    def test_default_zero_keeps_legacy_budgets(self, tmp_path):
        s = open_store("sqlite", sqlite_path=str(tmp_path / "d.db"))
        assert s._busy_timeout_s == SqliteKV.BUSY_TIMEOUT_S
        s.close()

    def test_etcd_deadline_overrides_op_timeout(self, monkeypatch):
        from tpu_docker_api.state.kv import EtcdKV
        monkeypatch.setattr(EtcdKV, "_post", lambda self, *a, **k: {})
        e = EtcdKV("http://127.0.0.1:1", op_deadline_s=0.25)
        assert e._op_timeout_s == 0.25
        e = EtcdKV("http://127.0.0.1:1")     # default: legacy 1 s budget
        assert e._op_timeout_s == EtcdKV.OP_TIMEOUT_S


class TestHostMonitorGate:
    """A store outage defers the DOWN verdict (a store-mutating cascade)
    but never stops the grace clock: heal ⇒ immediate confirmation."""

    def _monitor(self, gate):
        clock = _Clock()

        class _Sched:
            def __init__(self):
                self.down = {}

            def set_host_down(self, hid, flag):
                self.down[hid] = flag

        runtime = types.SimpleNamespace(
            container_list=lambda: (_ for _ in ()).throw(
                OSError("connection refused")))
        pod = types.SimpleNamespace(
            hosts={"h1": types.SimpleNamespace(runtime=runtime)})
        sched = _Sched()
        mon = HostMonitor(pod, sched, down_grace_s=5.0, clock=clock,
                          registry=MetricsRegistry(), store_gate=gate)
        return mon, sched, clock

    def test_down_verdict_held_then_confirmed_after_heal(self):
        store_up = {"up": True}
        mon, sched, clock = self._monitor(lambda: store_up["up"])
        mon.probe_once()                     # healthy → suspect
        assert mon.host_state("h1") == "suspect"
        clock.now += 6.0                     # grace elapsed
        store_up["up"] = False               # store outage begins
        mon.probe_once()
        assert mon.host_state("h1") == "suspect"    # verdict DEFERRED
        assert mon.store_skips == 1
        assert sched.down == {}
        kinds = [e["event"] for e in mon.events_view()]
        assert "store-outage-hold" in kinds
        store_up["up"] = True                # store heals
        mon.probe_once()                     # still failing ⇒ down NOW
        assert mon.host_state("h1") == "down"
        assert sched.down == {"h1": True}
        kinds = [e["event"] for e in mon.events_view()]
        assert "store-outage-over" in kinds

    def test_ungated_monitor_unchanged(self):
        mon, sched, clock = self._monitor(None)
        mon.probe_once()
        clock.now += 6.0
        mon.probe_once()
        assert mon.host_state("h1") == "down"
        assert mon.store_skips == 0


def _boot(**overrides) -> tuple[Program, FaultyKV]:
    kv = FaultyKV(MemoryKV())
    cfg = dict(port=0, store_backend="memory", runtime_backend="fake",
               start_port=46000, end_port=46999, health_watch_interval=0,
               reconcile_interval=0, leader_election=True,
               leader_ttl_s=30.0, leader_id="brownout-test",
               store_health_fail_threshold=3,
               store_health_outage_grace_s=0.15,
               store_health_probe_interval_s=0.1)
    cfg.update(overrides)
    prg = Program(Config(**cfg), host="127.0.0.1", kv=kv,
                  runtime=FakeRuntime())
    prg.init()
    prg.start()
    wait_until(lambda: prg.leader_elector.is_leader, what="lease acquire")
    wait_until(lambda: prg.leader_elector.accepts_mutations,
               what="writer boot")
    return prg, kv


def _shutdown(prg: Program) -> None:
    try:
        prg.leader_elector.close(release=True)
        prg.api_server.close()
        prg._stop_writers()
    except Exception:
        pass


def _force_outage(prg: Program, kv: FaultyKV) -> None:
    kv.set_outage(True)
    wait_until(lambda: prg.store_health.mode == "outage",
               what="outage mode")


def _call(prg, method, path, body=None, timeout=5.0):
    req = urllib.request.Request(
        f"http://127.0.0.1:{prg.api_server.port}{path}", method=method,
        data=json.dumps(body).encode() if body is not None else None,
        headers={"Content-Type": "application/json"})
    try:
        with urllib.request.urlopen(req, timeout=timeout) as resp:
            return resp.status, dict(resp.headers), json.loads(resp.read())
    except urllib.error.HTTPError as e:
        return e.code, dict(e.headers), json.loads(e.read())


class TestWriterLoopGates:
    """Every writer loop holds during a store outage: observes, skips,
    emits the edge event — and resumes after the heal."""

    @pytest.fixture(scope="class")
    def booted(self):
        prg, kv = _boot(history_retention_versions=2)
        yield prg, kv
        kv.set_outage(False)
        _shutdown(prg)

    def test_all_loops_hold_and_resume(self, booted):
        prg, kv = booted
        _force_outage(prg, kv)
        try:
            # supervisor: poll_once returns without store traffic
            prg.job_supervisor.poll_once()
            prg.job_supervisor.poll_once()
            assert prg.job_supervisor.store_skips >= 2
            kinds = [e["event"]
                     for e in prg.job_supervisor.events_view(limit=200)]
            assert kinds.count("store-outage-hold") == 1  # edge, not level
            # reconciler: mutating pass reports itself skipped...
            out = prg.reconciler.reconcile()
            assert out["mode"] == "skipped"
            assert out["skipped"] == "store-outage"
            assert out["actions"] == []
            # ...but a dry run still sweeps (observation is free)
            dry = prg.reconciler.reconcile(dry_run=True)
            assert dry.get("skipped") != "store-outage"
            # admission, autoscaler, workflow engine, compactor
            assert prg.admission.admit_once() == []
            assert prg.admission.store_skips >= 1
            prg.serving.tick()
            assert prg.serving.store_skips >= 1
            prg.workflow.tick()
            assert prg.workflow.store_skips >= 1
            out = prg.compactor.compact_once()
            assert out["skipped"] == "store-outage"
            assert out["trimmed"] == {}
        finally:
            kv.set_outage(False)
        wait_until(lambda: prg.store_health.mode == "healthy",
                   what="heal")
        # resumed: the loops run for real again and emit the over-edge
        prg.job_supervisor.poll_once()
        kinds = [e["event"]
                 for e in prg.job_supervisor.events_view(limit=200)]
        assert "store-outage-over" in kinds
        out = prg.reconciler.reconcile()
        assert out.get("skipped") != "store-outage"

    def test_workqueue_holds_execution_until_heal(self, booted):
        prg, kv = booted
        ran = []
        prg.wq.register("brownout-probe", lambda rec: ran.append(1))
        _force_outage(prg, kv)
        try:
            skips0 = prg.wq.store_skips
            # enqueued mid-outage: journal write degrades loudly, and the
            # sync loop HOLDS before executing (close overrides the hold)
            prg.wq.submit_record("brownout-probe", {})
            deadline = time.monotonic() + 0.5
            while time.monotonic() < deadline and not ran:
                time.sleep(0.01)
            assert ran == []                 # held, not executed
            assert prg.wq.store_skips >= skips0 + 1
        finally:
            kv.set_outage(False)
        wait_until(lambda: prg.store_health.mode == "healthy", what="heal")
        wait_until(lambda: ran == [1], what="held record executes on heal")
        events = [e["event"] for e in prg.wq.stats()["events"]]
        assert "store-outage-hold" in events
        assert "store-outage-over" in events


class TestLeaderLeaseUnderOutage:
    def test_renew_holds_until_own_deadline_then_demotes(self):
        """The elector's outage contract, pinned: renew failures before
        the lease's own deadline keep leadership (the lease is still
        legally ours — no standby may steal it yet); past the deadline a
        standby MAY have stolen it, so the leader demotes itself."""
        prg, kv = _boot(leader_ttl_s=1.0)
        try:
            _force_outage(prg, kv)
            assert prg.leader_elector.is_leader   # deadline not reached
            prg.leader_elector.step()             # renew fails typed
            assert prg.leader_elector.is_leader
            deadline = json.loads(
                prg.leader_elector._lease_raw)["deadline"]
            wait_until(lambda: time.time() > deadline + 0.05,
                       timeout_s=5.0, what="lease deadline")
            prg.leader_elector.step()             # past OWN deadline
            assert not prg.leader_elector.is_leader
            assert not prg.leader_elector.accepts_mutations
        finally:
            kv.set_outage(False)
            _shutdown(prg)


class TestApiSurfacing:
    @pytest.fixture(scope="class")
    def booted(self):
        prg, kv = _boot()
        _, _, out = _call(prg, "POST", "/api/v1/containers",
                          {"imageName": "jax", "containerName": "canary",
                           "chipCount": 1})
        assert out["code"] == 200
        yield prg, kv
        kv.set_outage(False)
        _shutdown(prg)

    def test_healthy_surface_has_no_stale_and_reports_mode(self, booted):
        prg, kv = booted
        st, hdr, out = _call(prg, "GET", "/api/v1/containers/canary")
        assert out["code"] == 200
        assert "stale" not in out            # legacy envelope byte-for-byte
        assert "X-Stale-Read" not in hdr
        _, _, hz = _call(prg, "GET", "/healthz")
        assert hz["data"]["storeHealth"]["mode"] == "healthy"
        _, _, ld = _call(prg, "GET", "/api/v1/leader")
        assert ld["data"]["storeHealth"]["mode"] == "healthy"

    def test_outage_contract_stale_reads_typed_mutations(self, booted):
        prg, kv = booted
        _force_outage(prg, kv)
        try:
            # reads ride the mirror, explicitly marked
            st, hdr, out = _call(prg, "GET", "/api/v1/containers/canary")
            assert out["code"] == 200
            assert out["stale"]["lagMs"] >= 0
            assert float(hdr["X-Stale-Read"]) == out["stale"]["lagMs"]
            # first mutation is the admitted probe: typed StoreUnavailable
            st, hdr, out = _call(prg, "POST", "/api/v1/containers",
                                 {"imageName": "jax",
                                  "containerName": "denied",
                                  "chipCount": 1})
            assert out["code"] == 10502
            # immediately after: fail-fast 503 with Retry-After, and ZERO
            # store round trips for the refusal
            n0 = len(kv.calls)
            st, hdr, out = _call(prg, "POST", "/api/v1/containers",
                                 {"imageName": "jax",
                                  "containerName": "denied2",
                                  "chipCount": 1})
            assert st == 503
            assert out["code"] == 10506
            assert out["data"] == {"storeMode": "outage"}
            assert int(hdr["Retry-After"]) >= 1
            assert len(kv.calls) == n0
            # mode + episode surfaced on /healthz and the events ring
            _, _, hz = _call(prg, "GET", "/healthz")
            sh = hz["data"]["storeHealth"]
            assert sh["mode"] == "outage"
            assert sh["outagesTotal"] >= 1
            _, _, ev = _call(prg, "GET", "/api/v1/events?limit=200")
            kinds = [e.get("event") for e in ev["data"]]
            assert "store-mode-degraded" in kinds
            assert "store-mode-outage" in kinds
        finally:
            kv.set_outage(False)
        wait_until(lambda: prg.store_health.mode == "healthy", what="heal")
        st, hdr, out = _call(prg, "GET", "/api/v1/containers/canary")
        assert out["code"] == 200 and "stale" not in out

    def test_metrics_export_store_series(self, booted):
        prg, kv = booted
        req = urllib.request.Request(
            f"http://127.0.0.1:{prg.api_server.port}/metrics")
        with urllib.request.urlopen(req, timeout=5) as r:
            body = r.read().decode()
        assert 'store_ops_total{outcome="ok"}' in body
        assert "store_mode" in body
        assert "store_op_ms_bucket" in body


class TestChaosMidflow:
    """The matrix point the bench churns statistically, pinned
    deterministically: a store outage interrupting a mutation mid-flow
    leaves typed errors and a world that converges after the heal."""

    def test_container_replace_midflow_converges(self):
        # ttl 600 keeps the elector's renew off the apply stream so the
        # scripted fail-nth window hits the replace flow, not the lease
        prg, kv = _boot(leader_ttl_s=600.0)
        try:
            _, _, out = _call(prg, "POST", "/api/v1/containers",
                              {"imageName": "jax", "containerName": "vic",
                               "chipCount": 2})
            assert out["code"] == 200
            # the NEXT batched store writes die: the rolling replace is
            # interrupted partway — version pointer advanced, record write
            # refused — and the caller sees a typed error, not a hang
            kv.fail_nth("apply", kv.op_count("apply") + 1, times=2)
            st, _, out = _call(prg, "PATCH", "/api/v1/containers/vic/tpu",
                               {"chipCount": 1})
            assert out["code"] == errors.StoreUnavailable.code
            # reads still serve the last consistent version
            _, _, info = _call(prg, "GET", "/api/v1/containers/vic")
            assert info["code"] == 200
            # heal: burn the remaining scripted failures on a scratch key
            for i in range(10):
                try:
                    kv.apply([("put", "/chaos/drain", str(i))])
                    break
                except errors.StoreUnavailable:
                    continue
            # the anti-entropy pass repairs the dangling version pointer...
            rec = prg.reconciler.reconcile()
            repairs = [a["action"] for a in rec["actions"]]
            assert "rollback-version-pointer" in repairs
            # ...after which the same intent lands cleanly
            st, _, out = _call(prg, "PATCH", "/api/v1/containers/vic/tpu",
                               {"chipCount": 1})
            assert out["code"] == 200
            _, _, info = _call(prg, "GET", "/api/v1/containers/vic")
            assert info["code"] == 200
            assert len(info["data"]["state"]["spec"]["chip_ids"]) == 1
        finally:
            kv.set_outage(False)
            _shutdown(prg)

    def test_gang_mutation_during_outage_refused_then_lands(self):
        prg, kv = _boot()
        try:
            _, _, out = _call(prg, "POST", "/api/v1/jobs",
                              {"imageName": "jax", "jobName": "gang",
                               "chipCount": 1})
            assert out["code"] == 200
            _force_outage(prg, kv)
            # burn the probe slot, then the delete is refused typed+fast
            _call(prg, "POST", "/api/v1/containers",
                  {"imageName": "jax", "containerName": "burn",
                   "chipCount": 1})
            st, _, out = _call(prg, "DELETE", "/api/v1/jobs/gang",
                               {"force": True,
                                "delStateAndVersionRecord": True})
            assert out["code"] in (10502, 10506)
            _, _, info = _call(prg, "GET", "/api/v1/jobs/gang")
            assert info["code"] == 200       # stale-served, still there
            kv.set_outage(False)
            wait_until(lambda: prg.store_health.mode == "healthy",
                       what="heal")
            deadline = time.monotonic() + 10.0
            while time.monotonic() < deadline:
                st, _, out = _call(prg, "DELETE", "/api/v1/jobs/gang",
                                   {"force": True,
                                    "delStateAndVersionRecord": True})
                if out["code"] == 200:
                    break
                time.sleep(0.05)
            assert out["code"] == 200
        finally:
            kv.set_outage(False)
            _shutdown(prg)

    def test_recovery_hook_marks_world_dirty(self):
        # reconcile_full_interval_s > 0 wires the event-driven dirty feed —
        # the configuration where a swallowed watch event COULD cause a
        # missed repair, and exactly what the recovery hook guards
        prg, kv = _boot(reconcile_full_interval_s=60.0)
        try:
            _force_outage(prg, kv)
            kv.set_outage(False)
            wait_until(lambda: prg.store_health.mode == "healthy",
                       what="heal")
            # loss-free recovery: the heal demands a FULL next pass (the
            # hook marks store-recovered; the informer's own relist may
            # re-mark it — either reason proves nothing can be missed)
            wait_until(lambda: prg.reconciler.dirty_view()["fullPending"],
                       timeout_s=5.0, what="dirty-all after heal")
            assert prg.reconciler.dirty_view()["fullReason"] in (
                "store-recovered", "relist")
        finally:
            kv.set_outage(False)
            _shutdown(prg)
