"""Schedulers: port allocator and the ICI-topology-aware chip/slice allocator."""

import pytest

from tpu_docker_api import errors
from tpu_docker_api.scheduler.ports import PortScheduler
from tpu_docker_api.scheduler.slices import ChipScheduler, candidate_shapes
from tpu_docker_api.scheduler.topology import (
    HostTopology,
    default_mesh_shape,
    parse_accelerator_type,
    parse_slice_shape,
)
from tpu_docker_api.state.kv import CountingKV, MemoryKV
from tpu_docker_api.state.txn import StoreTxn


class TestTopology:
    def test_parse_accelerator_type(self):
        gen, chips = parse_accelerator_type("v5e-8")
        assert gen.name == "v5e" and chips == 8
        gen, chips = parse_accelerator_type("v5p-16")  # 16 cores = 8 chips
        assert gen.name == "v5p" and chips == 8
        gen, chips = parse_accelerator_type("v4-8")
        assert gen.name == "v4" and chips == 4
        with pytest.raises(ValueError):
            parse_accelerator_type("h100-8")

    def test_parse_slice_shape(self):
        assert parse_slice_shape("2x2") == (2, 2, 1)
        assert parse_slice_shape("2x2x4") == (2, 2, 4)
        assert parse_slice_shape("4") == (4, 1, 1)
        with pytest.raises(ValueError):
            parse_slice_shape("2x0")

    def test_default_mesh_shapes(self):
        gen, _ = parse_accelerator_type("v5e-8")
        assert default_mesh_shape(gen, 8) == (2, 4, 1)
        assert default_mesh_shape(gen, 16) == (2, 8, 1)
        gen_p, _ = parse_accelerator_type("v5p-8")
        assert default_mesh_shape(gen_p, 4) == (2, 2, 1)
        assert default_mesh_shape(gen_p, 8) == (2, 2, 2)  # 3D torus tiles in z

    def test_build_topology(self):
        topo = HostTopology.build("v5e-8")
        assert topo.n_chips == 8
        assert topo.mesh_shape == (2, 4, 1)
        assert sorted(topo.coords) == list(range(8))
        # coordinates are unique and in-bounds
        assert len(set(topo.coords.values())) == 8
        for x, y, z in topo.coords.values():
            assert 0 <= x < 2 and 0 <= y < 4 and z == 0


class TestCandidateShapes:
    def test_compact_first(self):
        shapes = candidate_shapes(4, (4, 4, 4))
        assert shapes[0] == (2, 2, 1)  # most compact before lines
        assert (4, 1, 1) in shapes and (1, 1, 4) in shapes

    def test_respects_mesh_bounds(self):
        shapes = candidate_shapes(8, (2, 4, 1))
        assert (2, 4, 1) in shapes
        assert all(a <= 2 and b <= 4 and c <= 1 for a, b, c in shapes)


class TestChipScheduler:
    def make(self, acc="v5e-8"):
        kv = MemoryKV()
        return ChipScheduler(HostTopology.build(acc), kv), kv

    def test_alloc_contiguous_2x2(self):
        sched, _ = self.make()
        ids, contiguous = sched.apply_chips(4)
        assert contiguous and len(ids) == 4
        coords = [sched.topology.coords[c] for c in ids]
        xs = {c[0] for c in coords}
        ys = {c[1] for c in coords}
        assert len(xs) == 2 and len(ys) == 2  # a 2x2 block, not a line

    def test_boot_restore_is_read_only(self):
        """Re-constructing over an existing registry must issue ZERO store
        writes: under HA a standby boots while the leader is live, and a
        boot write-back (unfenced — the standby never held an epoch) would
        clobber claims the leader committed after the standby's read. Only
        a topology change (stored chips outside the current grid) may
        persist, because dropping them is a genuine repair."""
        sched, kv = self.make()
        sched.apply_chips(4, owner="held")
        counting = CountingKV(kv)
        restored = ChipScheduler(HostTopology.build("v5e-8"), counting)
        assert restored.status()["freeChips"] == 4  # the claim survived
        writes = {m: n for m, n in counting.snapshot().items()
                  if m in ("put", "delete", "delete_prefix", "apply")}
        assert writes == {}, f"boot restore wrote to the store: {writes}"
        # the repair path still persists: a shrunk topology drops chips
        sched.apply_chips(4, owner="rest")  # now every chip 0-7 is owned
        smaller = ChipScheduler(HostTopology.build("v5e-4"), counting)
        after = counting.snapshot()
        assert after.get("apply", 0) + after.get("put", 0) >= 1
        assert smaller.status()["totalChips"] == 4
        assert smaller.status()["freeChips"] == 0  # in-grid claims kept

    def test_deterministic(self):
        """Reference iterates a Go map ⇒ nondeterministic pick
        (gpuscheduler/scheduler.go:74-82). Ours must be reproducible."""
        picks = set()
        for _ in range(5):
            sched, _ = self.make()
            ids, _ = sched.apply_chips(2)
            picks.add(tuple(ids))
        assert len(picks) == 1

    def test_explicit_shape(self):
        sched, _ = self.make()
        ids, contiguous = sched.apply_chips(0, shape="2x2")
        assert contiguous and len(ids) == 4

    def test_explicit_shape_exhausted_raises(self):
        sched, _ = self.make()
        sched.apply_chips(0, shape="2x4")  # takes the whole host
        with pytest.raises(errors.ChipNotEnough):
            sched.apply_chips(0, shape="2x2")

    def test_scattered_fallback(self):
        """When fragmentation prevents a contiguous block, allocation still
        succeeds (parity: reference never guarantees adjacency) but reports
        non-contiguous."""
        sched, _ = self.make()
        everything, _ = sched.apply_chips(8)
        # free two opposite corners of the 2x4 mesh: (0,0) and (1,3)
        corner_a = sched.topology.chip_at((0, 0, 0))
        corner_b = sched.topology.chip_at((1, 3, 0))
        sched.restore_chips([corner_a, corner_b])
        ids, contiguous = sched.apply_chips(2)
        assert sorted(ids) == sorted([corner_a, corner_b])
        assert not contiguous  # corners share no ICI link

    def test_exhaustion_raises(self):
        sched, _ = self.make()
        sched.apply_chips(8)
        with pytest.raises(errors.ChipNotEnough):
            sched.apply_chips(1)

    def test_restore_and_refill(self):
        sched, _ = self.make()
        ids, _ = sched.apply_chips(8)
        sched.restore_chips(ids[:4])
        assert len(sched.free_chips) == 4
        again, _ = sched.apply_chips(4)
        assert sorted(again) == sorted(ids[:4])

    def test_state_survives_restart(self):
        """Reference persists only on graceful Close (scheduler.go:59-61);
        ours persists on every mutation."""
        sched, kv = self.make()
        ids, _ = sched.apply_chips(4, owner="train")
        sched2 = ChipScheduler(HostTopology.build("v5e-8"), kv)
        assert sched2.free_chips == sched.free_chips
        status = sched2.status()
        owners = {c["chipId"]: c["owner"] for c in status["chips"] if c["used"]}
        assert all(o == "train" for o in owners.values())

    def test_status_is_snapshot(self):
        sched, _ = self.make()
        st = sched.status()
        st["chips"][0]["used"] = True  # mutating the view must not leak
        assert not sched.status()["chips"][0]["used"]

    def test_zero_request(self):
        sched, _ = self.make()
        ids, contiguous = sched.apply_chips(0)
        assert ids == [] and contiguous

    def test_largest_free_block_tracks_fragmentation(self):
        sched, _ = self.make()
        assert sched.status()["largestFreeBlock"] == 8
        sched.apply_chips(0, shape="2x2")
        assert sched.status()["largestFreeBlock"] == 4

    def test_v5p_3d_block(self):
        sched, _ = self.make("v5p-16")  # 8 chips, mesh 2x2x2
        ids, contiguous = sched.apply_chips(0, shape="2x2x2")
        assert contiguous and len(ids) == 8


class TestBulkClaims:
    """Gang-level claim/release primitives: every member of a batch claims
    all-or-nothing ACROSS the batch, in one lock hold and one persist (or
    zero, deferred into a StoreTxn) — the scheduler half of the tentpole."""

    def test_chips_bulk_claims_all_members_in_one_persist(self):
        kv = CountingKV(MemoryKV())
        sched = ChipScheduler(HostTopology.build("v5e-8"), kv)
        base = kv.snapshot()
        assert sched.try_claim_chips_bulk(
            [("g-0", [0, 1]), ("g-1", [2, 3])]) == []
        assert CountingKV.delta(base, kv.snapshot()) == {"put": 1}
        assert len(sched.free_chips) == 4

    def test_chips_bulk_conflict_anywhere_claims_nothing(self):
        sched = ChipScheduler(HostTopology.build("v5e-8"), MemoryKV())
        sched.try_claim_chips([3], "taken")
        conflicts = sched.try_claim_chips_bulk(
            [("g-0", [0, 1]), ("g-1", [3]), ("g-2", [99])])
        assert conflicts == [3, 99]
        # the feasible first member claimed NOTHING
        assert len(sched.free_chips) == 7

    def test_chips_bulk_cross_owner_duplicate_is_a_conflict(self):
        """Two members of one batch asking for the SAME free chip must
        conflict — a double-grant silently resolved by member order would
        hand one chip to two containers."""
        sched = ChipScheduler(HostTopology.build("v5e-8"), MemoryKV())
        assert sched.try_claim_chips_bulk(
            [("g-0", [3, 4]), ("g-1", [3])]) == [3]
        assert len(sched.free_chips) == 8  # nothing claimed
        # same owner re-listing a chip is idempotent, not a conflict
        assert sched.try_claim_chips_bulk(
            [("g-0", [3]), ("g-0", [3, 4])]) == []

    def test_ports_bulk_cross_owner_duplicate_is_a_conflict(self):
        ps = PortScheduler(MemoryKV(), 40000, 40009)
        assert ps.try_claim_ports_bulk(
            [("a", [40000]), ("b", [40000, 40001])]) == [40000]
        assert ps.n_free == 10
        assert ps.try_claim_ports_bulk(
            [("a", [40000]), ("a", [40000])]) == []

    def test_chips_bulk_defers_into_txn(self):
        kv = MemoryKV()
        sched = ChipScheduler(HostTopology.build("v5e-8"), kv, "/chips")
        txn = StoreTxn(kv)
        assert sched.try_claim_chips_bulk([("g", [0, 1])], txn=txn) == []
        assert kv.get_or("/chips") is None  # nothing durable pre-commit
        txn.commit()
        assert "g" in kv.get("/chips")
        # the release mirrors: deferred, then durable in the same shape
        txn2 = StoreTxn(kv)
        sched.restore_chips([0, 1], owner="g", txn=txn2)
        assert "g" in kv.get("/chips")
        txn2.commit()
        assert "g" not in kv.get("/chips")

    def test_ports_bulk_mirror(self):
        kv = CountingKV(MemoryKV())
        ps = PortScheduler(kv, 40000, 40009, store_key="/ports")
        base = kv.snapshot()
        assert ps.try_claim_ports_bulk(
            [("a", [40000, 40001]), ("b", [40002])]) == []
        assert CountingKV.delta(base, kv.snapshot()) == {"put": 1}
        # conflict anywhere in the batch claims nothing
        assert ps.try_claim_ports_bulk(
            [("c", [40003]), ("c", [40001])]) == [40001]
        assert ps.n_free == 7
        # bulk release: both owners' ports free in ONE atomic apply
        txn = StoreTxn(kv)
        ps.restore_ports([40000, 40001], owner="a", txn=txn)
        ps.restore_ports([40002], owner="b", txn=txn)
        base = kv.snapshot()
        txn.commit()
        assert CountingKV.delta(base, kv.snapshot()) == {"apply": 1}
        assert ps.n_free == 10

    def test_bulk_claim_survives_restart(self):
        kv = MemoryKV()
        sched = ChipScheduler(HostTopology.build("v5e-8"), kv)
        txn = StoreTxn(kv)
        sched.try_claim_chips_bulk([("g-0", [0]), ("g-1", [1])], txn=txn)
        txn.commit()
        sched2 = ChipScheduler(HostTopology.build("v5e-8"), kv)
        assert sched2.try_claim_chips([0], "g-0") == []  # idempotent re-own
        assert sched2.try_claim_chips([1], "intruder") == [1]


class TestPortScheduler:
    def test_alloc_and_restore(self):
        kv = MemoryKV()
        ps = PortScheduler(kv, 40000, 40009)
        ports = ps.apply_ports(3)
        assert ports == [40000, 40001, 40002]
        ps.restore_ports(ports[:1])
        assert ps.n_free == 8

    def test_cursor_avoids_immediate_reuse(self):
        ps = PortScheduler(MemoryKV(), 40000, 40009)
        a = ps.apply_ports(2)
        ps.restore_ports(a)
        b = ps.apply_ports(2)
        assert b == [40002, 40003]  # cursor moved past the released pair

    def test_exhaustion(self):
        ps = PortScheduler(MemoryKV(), 40000, 40002)
        ps.apply_ports(3)
        with pytest.raises(errors.PortNotEnough):
            ps.apply_ports(1)

    def test_wraparound(self):
        ps = PortScheduler(MemoryKV(), 40000, 40004)
        first = ps.apply_ports(4)
        ps.restore_ports(first[:2])  # free 40000, 40001
        got = ps.apply_ports(3)      # must wrap: 40004 then 40000, 40001
        assert got == [40004, 40000, 40001]

    def test_state_survives_restart(self):
        kv = MemoryKV()
        ps = PortScheduler(kv, 40000, 40009)
        ps.apply_ports(5)
        ps2 = PortScheduler(kv, 40000, 40009)
        assert ps2.n_free == 5
        assert ps2.status()["usedPorts"] == [40000, 40001, 40002, 40003, 40004]

    def test_status_sorted(self):
        ps = PortScheduler(MemoryKV(), 40000, 40009)
        ps.apply_ports(3)
        st = ps.status()
        assert st["usedPorts"] == sorted(st["usedPorts"])
        assert st["usedCount"] == 3
