"""Host failure domains (docs/robustness.md "Host failure domains"):
circuit breaker transitions, the healthy→suspect→down state machine and
its grace window, cordon persistence across daemon restarts, and the
scheduler's refusal to place on cordoned/down hosts."""

import pytest

pytestmark = pytest.mark.chaos  # rides `make chaos` with the fault tier

from tpu_docker_api import config as config_mod
from tpu_docker_api import errors
from tpu_docker_api.daemon import Program
from tpu_docker_api.runtime.fake import FakeRuntime
from tpu_docker_api.runtime.faulty import FaultPlan, FaultyRuntime
from tpu_docker_api.schemas.job import JobRun
from tpu_docker_api.service.host_health import BreakerRuntime, HostMonitor
from tpu_docker_api.state.kv import MemoryKV


def boot_pod(kv, local_rt, remote_rt) -> Program:
    """2-host v5e pod; h1 remote (breaker-wrapped by the daemon)."""
    cfg = config_mod.Config(
        store_backend="memory", runtime_backend="fake",
        health_watch_interval=0, end_port=40099,
        pod_hosts=[
            {"host_id": "h0", "address": "10.0.0.1", "grid_coord": [0, 0, 0],
             "local": True},
            {"host_id": "h1", "address": "10.0.0.2", "grid_coord": [1, 0, 0],
             "runtime_backend": "fake"},
        ],
    )
    prg = Program(cfg, kv=kv, runtime=local_rt,
                  pod_runtimes={"h1": remote_rt})
    prg.init()
    return prg


class TestBreaker:
    def _mk(self, threshold=3, cooldown=5.0):
        clock = {"now": 0.0}
        inner = FaultyRuntime(FakeRuntime(), FaultPlan())
        br = BreakerRuntime(inner, host_id="h1", threshold=threshold,
                            cooldown_s=cooldown, clock=lambda: clock["now"])
        return br, inner, clock

    def test_opens_after_threshold_and_fast_fails(self):
        br, inner, clock = self._mk(threshold=3)
        inner.set_unreachable(True)
        for _ in range(3):
            with pytest.raises(errors.HostUnreachable):
                br.container_list()
        assert br.view()["state"] == "open"
        # open: fast-fail WITHOUT touching the inner engine
        inner_calls = len(inner.calls)
        with pytest.raises(errors.HostUnreachable, match="circuit open"):
            br.container_list()
        assert len(inner.calls) == inner_calls

    def test_half_open_probe_closes_on_success(self):
        br, inner, clock = self._mk(threshold=2, cooldown=5.0)
        inner.set_unreachable(True)
        for _ in range(2):
            with pytest.raises(errors.HostUnreachable):
                br.container_list()
        assert br.view()["state"] == "open"
        inner.set_unreachable(False)
        # inside the cooldown: still fast-failing (engine never touched)
        clock["now"] = 4.0
        with pytest.raises(errors.HostUnreachable, match="circuit open"):
            br.container_list()
        # past the cooldown: the next call IS the half-open probe
        clock["now"] = 6.0
        assert br.container_list() == []
        assert br.view()["state"] == "closed"
        assert br.view()["consecutiveFailures"] == 0

    def test_half_open_probe_failure_reopens(self):
        br, inner, clock = self._mk(threshold=2, cooldown=5.0)
        inner.set_unreachable(True)
        for _ in range(2):
            with pytest.raises(errors.HostUnreachable):
                br.container_list()
        clock["now"] = 6.0
        with pytest.raises(errors.HostUnreachable):
            br.container_list()  # probe goes through, fails
        assert br.view()["state"] == "open"
        # re-armed for another full cooldown
        clock["now"] = 7.0
        with pytest.raises(errors.HostUnreachable, match="circuit open"):
            br.container_list()

    def test_application_errors_prove_the_host_alive(self):
        br, inner, clock = self._mk(threshold=2)
        inner.set_unreachable(True)
        with pytest.raises(errors.HostUnreachable):
            br.container_list()
        inner.set_unreachable(False)
        # an app error (container missing) resets the failure streak
        with pytest.raises(errors.ContainerNotExist):
            br.container_inspect("nope")
        assert br.view()["consecutiveFailures"] == 0
        inner.set_unreachable(True)
        with pytest.raises(errors.HostUnreachable):
            br.container_list()
        assert br.view()["state"] == "closed"  # streak restarted at 1/2

    def test_normalizes_connection_errors(self):
        class Sock(FakeRuntime):
            def container_list(self):
                raise ConnectionRefusedError("boom")

        br = BreakerRuntime(Sock(), host_id="h9", threshold=3)
        with pytest.raises(errors.HostUnreachable, match="h9"):
            br.container_list()


class TestHostStateMachine:
    def _mk(self, grace=15.0):
        kv = MemoryKV()
        rt1 = FaultyRuntime(FakeRuntime(), FaultPlan())
        prg = boot_pod(kv, FakeRuntime(), rt1)
        clock = {"now": 0.0}
        mon = HostMonitor(prg.pod, prg.pod_scheduler, down_grace_s=grace,
                          clock=lambda: clock["now"])
        return prg, rt1, mon, clock

    def test_blip_inside_grace_never_goes_down(self):
        prg, rt1, mon, clock = self._mk(grace=15.0)
        mon.probe_once()
        assert mon.host_state("h1") == "healthy"
        rt1.set_unreachable(True)
        mon.probe_once()
        assert mon.host_state("h1") == "suspect"
        clock["now"] = 10.0  # inside the grace window
        mon.probe_once()
        assert mon.host_state("h1") == "suspect"
        assert not mon.is_down("h1")
        assert prg.pod_scheduler.down_hosts() == set()
        rt1.set_unreachable(False)
        clock["now"] = 12.0
        mon.probe_once()
        assert mon.host_state("h1") == "healthy"
        events = [e["event"] for e in mon.events_view()]
        assert "host-suspect" in events and "host-blip-over" in events
        assert "host-down" not in events

    def test_grace_elapsed_marks_down_and_unschedulable(self):
        prg, rt1, mon, clock = self._mk(grace=15.0)
        rt1.set_unreachable(True)
        down_hook = []
        mon._on_down = down_hook.append
        mon.probe_once()                  # → suspect at t=0
        clock["now"] = 15.0
        mon.probe_once()                  # grace elapsed → down
        assert mon.is_down("h1")
        assert prg.pod_scheduler.down_hosts() == {"h1"}
        assert not prg.pod_scheduler.host_schedulable("h1")
        assert down_hook == ["h1"]
        # recovery: probe succeeds → healthy again, schedulable again
        # (only two probes failed, so h1's breaker never opened and the
        # recovery probe passes straight through; with an open breaker the
        # post-cooldown probe doubles as the half-open trial)
        rt1.set_unreachable(False)
        clock["now"] = 30.0
        mon.probe_once()
        assert mon.host_state("h1") == "healthy"
        assert prg.pod_scheduler.down_hosts() == set()
        events = [e["event"] for e in mon.events_view()]
        assert "host-down" in events and "host-recovered" in events

    def test_status_view_reports_breaker_and_schedulability(self):
        kv = MemoryKV()
        rt1 = FaultyRuntime(FakeRuntime(), FaultPlan())
        prg = boot_pod(kv, FakeRuntime(), rt1)
        mon = HostMonitor(prg.pod, prg.pod_scheduler)
        view = mon.status_view()
        assert set(view["hosts"]) == {"h0", "h1"}
        assert view["hosts"]["h1"]["state"] == "healthy"
        assert view["hosts"]["h1"]["schedulable"]
        # h1 is breaker-wrapped by the daemon (breaker_threshold default)
        assert view["hosts"]["h1"]["breaker"]["state"] == "closed"
        rt1.set_unreachable(True)
        for _ in range(4):
            mon.probe_once()
        view = mon.status_view()
        assert view["hosts"]["h1"]["state"] == "suspect"
        assert view["hosts"]["h1"]["breaker"]["state"] == "open"


class TestCordon:
    def test_cordoned_host_receives_no_placements_until_uncordon(self):
        kv = MemoryKV()
        prg = boot_pod(kv, FakeRuntime(), FakeRuntime())
        prg.pod_scheduler.cordon_host("h1")
        # whole-host ask lands on the only schedulable host
        g1 = prg.pod_scheduler.apply_slice(n_chips=8, owner="a")
        assert [h for h, _ in g1.hosts] == ["h0"]
        with pytest.raises(errors.ChipNotEnough, match="cordoned"):
            prg.pod_scheduler.apply_slice(n_chips=8, owner="b")
        # sub-host asks skip it too
        with pytest.raises(errors.ChipNotEnough):
            prg.pod_scheduler.apply_slice(n_chips=4, owner="c")
        view = prg.pod_scheduler.host_view("h1")
        assert view["cordoned"] and not view["schedulable"]
        prg.pod_scheduler.uncordon_host("h1")
        g2 = prg.pod_scheduler.apply_slice(n_chips=8, owner="b")
        assert [h for h, _ in g2.hosts] == ["h1"]

    def test_cordon_survives_daemon_restart(self):
        kv = MemoryKV()
        rt0, rt1 = FakeRuntime(), FakeRuntime()
        prg = boot_pod(kv, rt0, rt1)
        prg.pod_scheduler.cordon_host("h1")
        # the daemon dies; a fresh control plane boots over the same KV
        prg2 = boot_pod(kv, rt0, rt1)
        assert prg2.pod_scheduler.cordoned_hosts() == {"h1"}
        assert not prg2.pod_scheduler.host_schedulable("h1")
        with pytest.raises(errors.ChipNotEnough):
            prg2.pod_scheduler.apply_slice(n_chips=16, owner="big")
        prg2.pod_scheduler.uncordon_host("h1")
        # ... and the uncordon persists as well
        prg3 = boot_pod(kv, rt0, rt1)
        assert prg3.pod_scheduler.cordoned_hosts() == set()

    def test_cordon_unknown_host_rejected(self):
        prg = boot_pod(MemoryKV(), FakeRuntime(), FakeRuntime())
        with pytest.raises(errors.ContainerNotExist):
            prg.pod_scheduler.cordon_host("nope")

    def test_capacity_accounting_excludes_unschedulable(self):
        prg = boot_pod(MemoryKV(), FakeRuntime(), FakeRuntime())
        st = prg.pod_scheduler.status()
        assert st["freeHosts"] == 2
        assert st["schedulableChips"] == 16
        prg.pod_scheduler.cordon_host("h1")
        st = prg.pod_scheduler.status()
        assert st["freeHosts"] == 1
        assert st["schedulableChips"] == 8
        assert st["freeSchedulableChips"] == 8
        assert st["cordonedHosts"] == ["h1"]
        prg.pod_scheduler.set_host_down("h0", True)
        st = prg.pod_scheduler.status()
        assert st["freeHosts"] == 0
        assert st["downHosts"] == ["h0"]

    def test_exclude_hosts_param_bans_for_one_grant(self):
        prg = boot_pod(MemoryKV(), FakeRuntime(), FakeRuntime())
        g = prg.pod_scheduler.apply_slice(n_chips=8, owner="a",
                                          exclude_hosts={"h0"})
        assert [h for h, _ in g.hosts] == ["h1"]
        # the exclusion was per-grant, not sticky
        g2 = prg.pod_scheduler.apply_slice(n_chips=8, owner="b")
        assert [h for h, _ in g2.hosts] == ["h0"]


class TestOperatorSurface:
    def test_cordon_drain_health_routes(self):
        """The HTTP surface: cordon/uncordon flip schedulability, drain
        queues migrations, /health/hosts serves the monitor view."""
        import json
        import urllib.request

        kv = MemoryKV()
        prg = boot_pod(kv, FakeRuntime(), FakeRuntime())
        prg.cfg.port = 0                    # ephemeral bind
        prg.cfg.reconcile_on_start = False
        prg.cfg.job_supervise_interval = 0
        prg.host_monitor._interval = 3600   # no surprise probes mid-test
        try:
            prg.start()

            def call(method, path):
                req = urllib.request.Request(
                    f"http://127.0.0.1:{prg.api_server.port}{path}",
                    method=method, data=b"{}" if method == "POST" else None,
                    headers={"Content-Type": "application/json"})
                with urllib.request.urlopen(req) as resp:
                    return json.loads(resp.read())

            out = call("POST", "/api/v1/hosts/h1/cordon")
            assert out["code"] == 200 and out["data"]["cordoned"]
            health = call("GET", "/api/v1/health/hosts")
            assert health["data"]["hosts"]["h1"]["cordoned"]
            assert not health["data"]["hosts"]["h1"]["schedulable"]
            out = call("POST", "/api/v1/hosts/h1/uncordon")
            assert not out["data"]["cordoned"]
            # drain with no jobs: cordons, queues nothing
            out = call("POST", "/api/v1/hosts/h1/drain")
            assert out["data"]["cordoned"]
            assert out["data"]["drainingJobs"] == []
            # host events reach the merged operator ring
            events = call("GET", "/api/v1/events")["data"]
            kinds = [e.get("event") for e in events]
            assert "host-cordoned" in kinds
            assert "host-drain-queued" in kinds
        finally:
            prg.stop()

    def test_drain_queues_migration_for_placed_jobs(self):
        kv = MemoryKV()
        prg = boot_pod(kv, FakeRuntime(), FakeRuntime())
        prg.job_svc.run_job(JobRun(image_name="jax", job_name="train",
                                   chip_count=4))  # sub-host → h0
        mon = prg.host_monitor
        out = mon.drain("h0")
        assert out["drainingJobs"] == ["train"]
        assert prg.pod_scheduler.cordoned_hosts() == {"h0"}
        # run the queued migration synchronously: the gang moves to h1
        prg.wq.start()
        prg.wq.drain()
        prg.wq.close()
        st = prg.store.get_job("train-1")
        assert st.phase == "running"
        assert all(h == "h1" for h, *_ in st.placements)
        # drain is operator-driven: the fault-migration budget is untouched
        assert st.migrations == 0


class TestDrainShrink:
    """Elastic gangs drain by SHRINKING (docs/robustness.md "Elastic
    gangs"): the drained host's members are dropped (never below
    minMembers) instead of re-placing the whole gang — fewer moved bytes
    on a live drain — and the dropped members grow back through the
    admission queue onto other hosts (the drained one is cordoned)."""

    def _pod4(self, admission=True):
        kv = MemoryKV()
        rts = {f"h{i}": FakeRuntime() for i in range(4)}
        cfg = config_mod.Config(
            store_backend="memory", runtime_backend="fake",
            health_watch_interval=0, end_port=40099,
            admission_enabled=admission, admission_interval_s=0,
            pod_hosts=[
                {"host_id": f"h{i}", "address": f"10.0.0.{i + 1}",
                 "grid_coord": [i, 0, 0],
                 **({"local": True} if i == 0
                    else {"runtime_backend": "fake"})}
                for i in range(4)
            ],
        )
        prg = Program(cfg, kv=kv, runtime=rts["h0"],
                      pod_runtimes={h: r for h, r in rts.items()
                                    if h != "h0"})
        prg.init()
        return prg

    def test_drain_offers_shrink_before_migration(self):
        prg = self._pod4()
        prg.job_svc.run_job(JobRun(image_name="jax", job_name="train",
                                   chip_count=32, elastic=True,
                                   min_members=1))
        out = prg.host_monitor.drain("h3")
        assert out["drainingJobs"] == ["train"]
        prg.wq.start()
        prg.wq.drain()
        prg.wq.close()
        st = prg.store.get_job(f"train-{prg.job_versions.get('train')}")
        assert st.phase == "running"
        assert len(st.placements) == 3
        assert all(h != "h3" for h, *_ in st.placements)
        # a shrink, not a migration: neither budget was touched
        assert st.migrations == 0 and st.restarts == 0
        assert st.resizes == 1
        kinds = [e.get("event")
                 for e in prg.host_monitor.events_view(limit=100)]
        assert "job-drain-shrunk" in kinds
        # the dropped member waits in the admission queue; the drained
        # host is cordoned, so the grow-back holds until capacity returns
        recs = {r.base: r.kind for r in prg.admission.records()}
        assert recs.get("train") == "growback"
        assert prg.admission.admit_once() == []
        assert len(prg.store.get_job(
            f"train-{prg.job_versions.get('train')}").placements) == 3
        # uncordon: the next pass grows the gang back to full size
        prg.host_monitor.uncordon("h3")
        assert [o["job"] for o in prg.admission.admit_once()] == ["train"]
        st = prg.store.get_job(f"train-{prg.job_versions.get('train')}")
        assert len(st.placements) == 4 and st.phase == "running"

    def test_drain_below_floor_falls_back_to_migration(self):
        """A gang already at its minMembers floor cannot shrink: the
        drain falls back to whole-gang migration (the pre-elastic
        behavior), keeping the drain promise — moved, never stopped."""
        prg = self._pod4()
        prg.job_svc.run_job(JobRun(image_name="jax", job_name="train",
                                   chip_count=16, elastic=True,
                                   min_members=2))  # 2 hosts, floor 2
        st = prg.store.get_job("train-0")
        drained = st.placements[0][0]
        out = prg.host_monitor.drain(drained)
        assert out["drainingJobs"] == ["train"]
        prg.wq.start()
        prg.wq.drain()
        prg.wq.close()
        st = prg.store.get_job(f"train-{prg.job_versions.get('train')}")
        assert st.phase == "running"
        assert len(st.placements) == 2          # full size preserved
        assert all(h != drained for h, *_ in st.placements)
        assert st.resizes == 0                   # no shrink happened
        kinds = [e.get("event")
                 for e in prg.host_monitor.events_view(limit=100)]
        assert "job-drained" in kinds
