"""Encoder-decoder family: cross-attention correctness and trainer
integration (the architecture surface Llama/MoE/ViT don't cover)."""

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import pytest

#: JAX-compile heavy: excluded from the `-m 'not slow'` quick tier so it
#: fits its time budget; still runs in `make test` (the full suite)
pytestmark = pytest.mark.slow


from tpu_docker_api.models import model_fns
from tpu_docker_api.models.encdec import (
    ENCDEC_RULES,
    EncDecConfig,
    encdec_forward,
    encdec_init,
    encdec_loss,
    encdec_presets,
    encdec_synthetic_batch,
)
from tpu_docker_api.parallel.mesh import MeshPlan, build_mesh
from tpu_docker_api.parallel.sharding import param_shardings, spec_for
from jax.sharding import PartitionSpec as P

TINY = encdec_presets()["tiny"]


@pytest.fixture(scope="module")
def tiny_params():
    return encdec_init(TINY, jax.random.PRNGKey(0))


class TestForward:
    def test_shapes_and_finite(self, tiny_params):
        src = jnp.zeros((2, 12), jnp.int32)
        tgt = jnp.zeros((2, 8), jnp.int32)
        logits = encdec_forward(tiny_params, (src, tgt), TINY)
        assert logits.shape == (2, 8, TINY.vocab_size)
        assert bool(jnp.all(jnp.isfinite(logits)))

    def test_decoder_is_causal(self, tiny_params):
        """Changing tgt position j must not affect logits before j."""
        src = jax.random.randint(jax.random.PRNGKey(1), (1, 12), 0, 256,
                                 dtype=jnp.int32)
        tgt = jax.random.randint(jax.random.PRNGKey(2), (1, 8), 0, 256,
                                 dtype=jnp.int32)
        base = encdec_forward(tiny_params, (src, tgt), TINY)
        tgt2 = tgt.at[0, 5].set((tgt[0, 5] + 1) % 256)
        mod = encdec_forward(tiny_params, (src, tgt2), TINY)
        np.testing.assert_array_equal(np.asarray(base[:, :5]),
                                      np.asarray(mod[:, :5]))
        assert not np.allclose(np.asarray(base[:, 5:]), np.asarray(mod[:, 5:]))

    def test_encoder_is_bidirectional_through_cross(self, tiny_params):
        """Changing ANY src position must reach EVERY decoder position —
        the encoder is non-causal and cross-attention sees all of it."""
        src = jax.random.randint(jax.random.PRNGKey(1), (1, 12), 0, 256,
                                 dtype=jnp.int32)
        tgt = jax.random.randint(jax.random.PRNGKey(2), (1, 8), 0, 256,
                                 dtype=jnp.int32)
        base = encdec_forward(tiny_params, (src, tgt), TINY)
        src2 = src.at[0, 11].set((src[0, 11] + 1) % 256)  # LAST src token
        mod = encdec_forward(tiny_params, (src2, tgt), TINY)
        # every decoder position shifts, including position 0
        diff = np.abs(np.asarray(base) - np.asarray(mod)).max(axis=-1)[0]
        assert (diff > 0).all()

    def test_cross_attention_kv_lengths_differ(self, tiny_params):
        """src and tgt lengths are independent (the cross path's whole
        point)."""
        src = jnp.zeros((2, 24), jnp.int32)
        tgt = jnp.zeros((2, 6), jnp.int32)
        logits = encdec_forward(tiny_params, (src, tgt), TINY)
        assert logits.shape == (2, 6, TINY.vocab_size)


class TestShardingRules:
    def test_rule_lookup(self):
        assert spec_for("enc_layers/attn/wq", ENCDEC_RULES) == \
            P(None, "fsdp", "tp")
        assert spec_for("enc_layers/attn/wo", ENCDEC_RULES) == \
            P(None, "tp", "fsdp")
        assert spec_for("dec_layers/cross_attn/wk", ENCDEC_RULES) == \
            P(None, "fsdp", "tp")
        assert spec_for("dec_layers/cross_attn/wo", ENCDEC_RULES) == \
            P(None, "tp", "fsdp")
        assert spec_for("dec_layers/mlp/w_down", ENCDEC_RULES) == \
            P(None, "tp", "fsdp")
        assert spec_for("dec_layers/self_norm", ENCDEC_RULES) == P()

    def test_shardable_on_mesh(self, tiny_params):
        mesh = build_mesh(MeshPlan(dp=2, fsdp=2, tp=2, sp=1))
        sharded = jax.device_put(
            tiny_params, param_shardings(tiny_params, mesh, ENCDEC_RULES))
        leaf = sharded["dec_layers"]["cross_attn"]["wq"]
        assert len(leaf.addressable_shards) == 8

    def test_sequence_parallel_loss_matches_unsharded(self, tiny_params):
        """sp mesh (round 3): encoder non-causal ring + decoder causal
        zigzag ring + sp-gathered cross — loss must match the unsharded
        forward within bf16 reduction tolerance."""
        from tpu_docker_api.models.encdec import (
            encdec_loss, encdec_synthetic_batch)

        batch = encdec_synthetic_batch(jax.random.PRNGKey(1), 4, 32, 32,
                                       TINY)
        ref = float(encdec_loss(tiny_params, batch, TINY))
        mesh = build_mesh(MeshPlan(dp=2, fsdp=1, tp=2, sp=2))
        with mesh:
            got = float(jax.jit(
                lambda p, b: encdec_loss(p, b, TINY, mesh))(
                    tiny_params, batch))
        np.testing.assert_allclose(got, ref, rtol=2e-3, atol=2e-3)


class TestTraining:
    def test_registry_dispatch(self):
        init, loss, rules = model_fns(TINY)
        assert init is encdec_init and loss is encdec_loss
        assert rules is ENCDEC_RULES

    def test_loss_descends_on_mesh(self):
        from tpu_docker_api.train.trainer import (
            create_train_state,
            default_optimizer,
            make_train_step,
        )

        mesh = build_mesh(MeshPlan(dp=2, fsdp=2, tp=2, sp=1))
        state, opt = create_train_state(
            TINY, mesh, jax.random.PRNGKey(0),
            optimizer=default_optimizer(lr=1e-2))
        step = make_train_step(TINY, mesh, opt)
        batch = encdec_synthetic_batch(jax.random.PRNGKey(1), 8, 16, 16,
                                       TINY)
        losses = []
        for _ in range(6):
            state, metrics = step(state, batch)
            losses.append(float(metrics["loss"]))
        assert np.isfinite(losses).all()
        assert losses[-1] < losses[0]

    def test_remat_path(self):
        cfg = dataclasses.replace(TINY, remat=True)
        params = encdec_init(cfg, jax.random.PRNGKey(0))
        batch = encdec_synthetic_batch(jax.random.PRNGKey(1), 2, 8, 8, cfg)
        loss, grads = jax.value_and_grad(
            lambda p: encdec_loss(p, batch, cfg))(params)
        assert np.isfinite(float(loss))
        assert all(bool(jnp.all(jnp.isfinite(g.astype(jnp.float32))))
                   for g in jax.tree_util.tree_leaves(grads))

    def test_synthetic_batch_row_offset_contract(self):
        """Rows derive from global indices: a 2-process split must produce
        exactly the single-process rows (the rescale contract every data
        path honors)."""
        full_src, full_tgt = encdec_synthetic_batch(
            jax.random.PRNGKey(3), 4, 8, 8, TINY)
        lo = encdec_synthetic_batch(jax.random.PRNGKey(3), 2, 8, 8, TINY,
                                    row_offset=0)
        hi = encdec_synthetic_batch(jax.random.PRNGKey(3), 2, 8, 8, TINY,
                                    row_offset=2)
        np.testing.assert_array_equal(
            np.asarray(full_src), np.concatenate([lo[0], hi[0]]))
        np.testing.assert_array_equal(
            np.asarray(full_tgt), np.concatenate([lo[1], hi[1]]))


class TestGenerate:
    def test_greedy_matches_full_recompute(self):
        """KV-cached greedy decode must produce exactly the tokens a
        recompute-from-scratch greedy loop produces (f32: cache mechanics
        must not change the math, per the llama cached-path tests)."""
        from tpu_docker_api.models.encdec import encdec_generate

        cfg = dataclasses.replace(TINY, dtype=jnp.float32)
        params = encdec_init(cfg, jax.random.PRNGKey(0))
        src = jax.random.randint(jax.random.PRNGKey(1), (2, 12), 0, 256,
                                 dtype=jnp.int32)
        n = 6
        got = encdec_generate(params, src, cfg, max_new_tokens=n, bos_id=3)

        toks = jnp.full((2, 1), 3, jnp.int32)
        ref = []
        for _ in range(n):
            logits = encdec_forward(params, (src, toks), cfg)
            nxt = jnp.argmax(logits[:, -1], axis=-1).astype(jnp.int32)
            ref.append(nxt)
            toks = jnp.concatenate([toks, nxt[:, None]], axis=1)
        ref = jnp.stack(ref, axis=1)
        np.testing.assert_array_equal(np.asarray(got), np.asarray(ref))

    def test_jit_and_shapes(self, tiny_params):
        from tpu_docker_api.models.encdec import encdec_generate

        src = jnp.zeros((3, 8), jnp.int32)
        fn = jax.jit(lambda p, s: encdec_generate(p, s, TINY,
                                                  max_new_tokens=5))
        out = fn(tiny_params, src)
        assert out.shape == (3, 5) and out.dtype == jnp.int32
        assert bool(jnp.all((out >= 0) & (out < TINY.vocab_size)))

    def test_top_k_1_and_tiny_top_p_equal_greedy(self, tiny_params):
        """Sampling shares make_sampler semantics with the llama engine:
        top_k=1 and top_p→0 both collapse the filtered distribution to
        the argmax token, so they must reproduce greedy exactly even at
        temperature > 0 (round-3 closes the greedy-only line item)."""
        from tpu_docker_api.models.encdec import encdec_generate

        src = jax.random.randint(jax.random.PRNGKey(7), (2, 9), 0, 256,
                                 dtype=jnp.int32)
        greedy = np.asarray(encdec_generate(tiny_params, src, TINY,
                                            max_new_tokens=6))
        for kw in ({"top_k": 1}, {"top_p": 1e-6}):
            got = np.asarray(encdec_generate(
                tiny_params, src, TINY, max_new_tokens=6, temperature=0.9,
                rng=jax.random.PRNGKey(11), **kw))
            np.testing.assert_array_equal(got, greedy)

    def test_sampling_deterministic_in_rng(self, tiny_params):
        from tpu_docker_api.models.encdec import encdec_generate

        src = jax.random.randint(jax.random.PRNGKey(8), (2, 9), 0, 256,
                                 dtype=jnp.int32)
        gen = lambda seed: np.asarray(encdec_generate(  # noqa: E731
            tiny_params, src, TINY, max_new_tokens=16, temperature=1.5,
            rng=jax.random.PRNGKey(seed)))
        a, b = gen(0), gen(0)
        np.testing.assert_array_equal(a, b)
        assert (a >= 0).all() and (a < TINY.vocab_size).all()
        # a different stream decodes a different sequence at temp 1.5
        # (random-init logits are near-uniform — collision ≈ impossible)
        assert not np.array_equal(a, gen(1))

    def test_eos_truncates_with_lengths(self, tiny_params):
        """eos_id: same truncate-at-eos-inclusive + pad-after contract
        as the llama engine (round-3 closes VERDICT r2 weak #6)."""
        from tpu_docker_api.models.encdec import encdec_generate

        src = jax.random.randint(jax.random.PRNGKey(4), (2, 10), 0, 256,
                                 dtype=jnp.int32)
        free = np.asarray(encdec_generate(tiny_params, src, TINY,
                                          max_new_tokens=8))
        eos = int(free[0, 2])  # row 0 hits it at position <= 2
        out = jax.jit(lambda p, s: encdec_generate(
            p, s, TINY, max_new_tokens=8, eos_id=eos,
            pad_id=0))(tiny_params, src)
        toks, lengths = np.asarray(out["tokens"]), np.asarray(out["lengths"])
        for r in range(2):
            row_free = free[r].tolist()
            n = int(lengths[r])
            if eos in row_free:
                assert n == row_free.index(eos) + 1
                assert toks[r, n - 1] == eos
            else:
                assert n == 8
            assert toks[r, :n].tolist() == row_free[:n]
            assert (toks[r, n:] == 0).all()
