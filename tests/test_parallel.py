"""Parallelism layer on the 8-device virtual CPU mesh: mesh building,
sharding rules, ring attention exactness."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

#: JAX-compile heavy: excluded from the `-m 'not slow'` quick tier so it
#: fits its time budget; still runs in `make test` (the full suite)
pytestmark = pytest.mark.slow

from jax.sharding import PartitionSpec as P

from tpu_docker_api.models.llama import LlamaConfig, llama_init
from tpu_docker_api.ops.attention import _dense_attention
from tpu_docker_api.parallel.mesh import MeshPlan, build_mesh
from tpu_docker_api.parallel.ring import ring_attention
from tpu_docker_api.parallel.sharding import (
    flatten_paths,
    param_shardings,
    param_specs,
    spec_for,
)


def test_eight_devices_available():
    assert jax.device_count() == 8  # conftest forces the virtual CPU mesh


class TestMesh:
    def test_default_plan_absorbs_devices(self):
        mesh = build_mesh(MeshPlan())
        assert dict(mesh.shape) == {"pp": 1, "dp": 8, "fsdp": 1, "ep": 1,
                                    "tp": 1, "sp": 1}

    def test_explicit_plan(self):
        mesh = build_mesh(MeshPlan(dp=2, fsdp=2, tp=2, sp=1))
        assert dict(mesh.shape) == {"pp": 1, "dp": 2, "fsdp": 2, "ep": 1,
                                    "tp": 2, "sp": 1}

    def test_pp_ep_axes(self):
        mesh = build_mesh(MeshPlan(dp=1, fsdp=1, tp=1, sp=1, pp=2, ep=4))
        assert dict(mesh.shape) == {"pp": 2, "dp": 1, "fsdp": 1, "ep": 4,
                                    "tp": 1, "sp": 1}

    def test_bad_plan_raises(self):
        with pytest.raises(ValueError):
            build_mesh(MeshPlan(dp=3, fsdp=1, tp=1, sp=1))
        with pytest.raises(ValueError):
            build_mesh(MeshPlan(dp=-1, fsdp=3, tp=1, sp=1))


class TestShardingRules:
    def test_spec_lookup(self):
        assert spec_for("layers/attn/wq") == P(None, "fsdp", "tp")
        assert spec_for("layers/attn/wo") == P(None, "tp", "fsdp")
        assert spec_for("embed/tokens") == P("tp", "fsdp")
        assert spec_for("layers/attn_norm") == P()
        assert spec_for("something/else") == P()

    def test_param_specs_cover_llama(self):
        cfg = LlamaConfig(vocab_size=256, dim=64, n_layers=2, n_heads=4,
                          n_kv_heads=2, ffn_dim=128, max_seq_len=64)
        params = llama_init(cfg, jax.random.PRNGKey(0))
        specs = param_specs(params)
        flat_p = flatten_paths(params)
        flat_s = flatten_paths(specs)
        assert set(flat_p) == set(flat_s)
        # every spec's rank must not exceed the param's rank
        for path, spec in flat_s.items():
            assert len(spec) <= flat_p[path].ndim, path

    def test_shardable_on_mesh(self):
        """Every param must actually placeable with its sharding on a
        2x2x2 (fsdp×tp×...) mesh — catches specs that don't divide dims."""
        mesh = build_mesh(MeshPlan(dp=2, fsdp=2, tp=2, sp=1))
        cfg = LlamaConfig(vocab_size=256, dim=64, n_layers=2, n_heads=4,
                          n_kv_heads=2, ffn_dim=128, max_seq_len=64)
        params = llama_init(cfg, jax.random.PRNGKey(0))
        sharded = jax.device_put(params, param_shardings(params, mesh))
        leaf = sharded["layers"]["attn"]["wq"]
        assert len(leaf.addressable_shards) == 8


class TestRingAttention:
    def _qkv(self, heads=4, kv_heads=4, seq=64, hd=32, dtype=jnp.float32):
        ks = jax.random.split(jax.random.PRNGKey(7), 3)
        q = jax.random.normal(ks[0], (2, seq, heads, hd), dtype)
        k = jax.random.normal(ks[1], (2, seq, kv_heads, hd), dtype)
        v = jax.random.normal(ks[2], (2, seq, kv_heads, hd), dtype)
        return q, k, v

    @pytest.mark.parametrize("sp", [2, 4, 8])
    def test_matches_dense_causal(self, sp):
        """Exactness: ring attention over sp shards == single-device dense."""
        mesh = build_mesh(MeshPlan(dp=1, fsdp=1, tp=1, sp=sp),
                          devices=jax.devices()[:sp])
        q, k, v = self._qkv(seq=64)
        ref = _dense_attention(q, k, v, causal=True)
        got = ring_attention(q, k, v, mesh, causal=True)
        np.testing.assert_allclose(np.asarray(got), np.asarray(ref),
                                   rtol=2e-4, atol=2e-4)

    def test_matches_dense_bf16(self):
        """The training dtype path: bf16 operands with f32 accumulation.
        Looser tolerance — ring downcasts probs to bf16 for the p·v dot
        (flash-kernel numerics), dense keeps f32 probs."""
        mesh = build_mesh(MeshPlan(dp=1, fsdp=1, tp=1, sp=4),
                          devices=jax.devices()[:4])
        q, k, v = self._qkv(seq=64, dtype=jnp.bfloat16)
        ref = _dense_attention(q, k, v, causal=True)
        got = ring_attention(q, k, v, mesh, causal=True)
        np.testing.assert_allclose(
            np.asarray(got, np.float32), np.asarray(ref, np.float32),
            rtol=2e-2, atol=2e-2)

    def test_matches_dense_non_causal(self):
        mesh = build_mesh(MeshPlan(dp=1, fsdp=1, tp=1, sp=4),
                          devices=jax.devices()[:4])
        q, k, v = self._qkv(seq=32)
        ref = _dense_attention(q, k, v, causal=False)
        got = ring_attention(q, k, v, mesh, causal=False)
        np.testing.assert_allclose(np.asarray(got), np.asarray(ref),
                                   rtol=2e-4, atol=2e-4)

    def test_gqa(self):
        mesh = build_mesh(MeshPlan(dp=1, fsdp=1, tp=1, sp=4),
                          devices=jax.devices()[:4])
        q, k, v = self._qkv(heads=4, kv_heads=2, seq=32)
        ref = _dense_attention(q, k, v, causal=True)
        got = ring_attention(q, k, v, mesh, causal=True)
        np.testing.assert_allclose(np.asarray(got), np.asarray(ref),
                                   rtol=2e-4, atol=2e-4)

    def test_composes_with_dp_and_tp(self):
        mesh = build_mesh(MeshPlan(dp=2, fsdp=1, tp=2, sp=2))
        q, k, v = self._qkv(heads=4, kv_heads=4, seq=32)
        ref = _dense_attention(q, k, v, causal=True)
        got = ring_attention(q, k, v, mesh, causal=True)
        np.testing.assert_allclose(np.asarray(got), np.asarray(ref),
                                   rtol=2e-4, atol=2e-4)

    @pytest.mark.parametrize("sp", [4, 8])
    def test_causal_skips_fully_masked_blocks(self, sp):
        """VERDICT r1 item 6: the causal path must COMPUTE only the
        lower-triangular (q,k) blocks — device i exactly i+1 of sp — not
        compute-and-mask all sp² of them."""
        mesh = build_mesh(MeshPlan(dp=1, fsdp=1, tp=1, sp=sp),
                          devices=jax.devices()[:sp])
        q, k, v = self._qkv(seq=8 * sp)
        _, counts = ring_attention(q, k, v, mesh, causal=True,
                                   with_block_counts=True)
        assert sorted(np.asarray(counts).tolist()) == list(range(1, sp + 1))
        assert int(np.asarray(counts).sum()) == sp * (sp + 1) // 2

        _, counts_nc = ring_attention(q, k, v, mesh, causal=False,
                                      with_block_counts=True)
        assert np.asarray(counts_nc).tolist() == [sp] * sp
        # exactly the (sp+1)/(2·sp) fraction of the non-causal block-work
        # (→ 1/2 as sp grows)
        assert (int(np.asarray(counts).sum()) * 2 * sp
                == int(np.asarray(counts_nc).sum()) * (sp + 1))


class TestUlyssesAttention:
    def _qkv(self, heads=4, kv_heads=4, seq=64, hd=32, dtype=jnp.float32):
        ks = jax.random.split(jax.random.PRNGKey(7), 3)
        q = jax.random.normal(ks[0], (2, seq, heads, hd), dtype)
        k = jax.random.normal(ks[1], (2, seq, kv_heads, hd), dtype)
        v = jax.random.normal(ks[2], (2, seq, kv_heads, hd), dtype)
        return q, k, v

    @pytest.mark.parametrize("sp", [2, 4])
    def test_matches_dense_causal(self, sp):
        from tpu_docker_api.parallel.ulysses import ulysses_attention

        mesh = build_mesh(MeshPlan(dp=1, fsdp=1, tp=1, sp=sp),
                          devices=jax.devices()[:sp])
        q, k, v = self._qkv(seq=64)
        ref = _dense_attention(q, k, v, causal=True)
        got = ulysses_attention(q, k, v, mesh, causal=True)
        np.testing.assert_allclose(np.asarray(got), np.asarray(ref),
                                   rtol=2e-4, atol=2e-4)

    def test_gqa_and_tp_compose(self):
        from tpu_docker_api.parallel.ulysses import ulysses_attention

        mesh = build_mesh(MeshPlan(dp=2, fsdp=1, tp=2, sp=2))
        q, k, v = self._qkv(heads=8, kv_heads=4, seq=32)
        ref = _dense_attention(q, k, v, causal=True)
        got = ulysses_attention(q, k, v, mesh, causal=True)
        np.testing.assert_allclose(np.asarray(got), np.asarray(ref),
                                   rtol=2e-4, atol=2e-4)

    def test_head_divisibility_guard(self):
        from tpu_docker_api.parallel.ulysses import ulysses_attention

        mesh = build_mesh(MeshPlan(dp=1, fsdp=1, tp=1, sp=4),
                          devices=jax.devices()[:4])
        q, k, v = self._qkv(heads=4, kv_heads=2, seq=32)  # kv 2 < sp 4
        with pytest.raises(ValueError, match="divisible by sp"):
            ulysses_attention(q, k, v, mesh, causal=True)

    def test_trains_as_llama_attention_impl(self):
        import dataclasses

        from tpu_docker_api.models.llama import (
            llama_init,
            llama_loss,
            llama_presets,
        )

        cfg = dataclasses.replace(llama_presets()["tiny"], n_kv_heads=4,
                                  attention_impl="ulysses")
        params = llama_init(cfg, jax.random.PRNGKey(0))
        tokens = jax.random.randint(jax.random.PRNGKey(1), (4, 33), 0,
                                    cfg.vocab_size, dtype="int32")
        ref_cfg = dataclasses.replace(cfg, attention_impl="auto")
        ref = float(llama_loss(params, tokens, ref_cfg))
        mesh = build_mesh(MeshPlan(dp=2, fsdp=1, tp=2, sp=2))
        with mesh:
            got = float(jax.jit(
                lambda p, t: llama_loss(p, t, cfg, mesh))(params, tokens))
        np.testing.assert_allclose(got, ref, rtol=1e-3, atol=1e-3)


class TestChunkedLossOnMesh:
    def test_chunked_loss_matches_dense_under_fsdp_tp(self):
        """The chunked-CE training loss (ops.xent) must compile and agree
        with the dense loss under a sharded mesh — its scan-carried f32 dw
        accumulator and row-chunk reshapes all run through GSPMD here."""
        import dataclasses

        from tpu_docker_api.models.llama import (
            llama_init,
            llama_loss,
            llama_presets,
        )

        cfg = llama_presets()["tiny"]
        chunk_cfg = dataclasses.replace(cfg, loss_chunk_rows=16)
        params = llama_init(cfg, jax.random.PRNGKey(0))
        tokens = jax.random.randint(jax.random.PRNGKey(1), (4, 33), 0,
                                    cfg.vocab_size, dtype="int32")
        ref = float(llama_loss(params, tokens, cfg))
        mesh = build_mesh(MeshPlan(dp=2, fsdp=2, tp=2, sp=1))
        with mesh:
            got = float(jax.jit(
                lambda p, t: llama_loss(p, t, chunk_cfg, mesh))(
                    params, tokens))
        np.testing.assert_allclose(got, ref, rtol=1e-3, atol=1e-3)


class TestZigzagRingAttention:
    """Zigzag placement: each device holds a head AND a tail stripe, so
    per-device causal work is exactly uniform (2·sp+1 half-stripe pairs)
    instead of the contiguous layout's 1..sp whole-block skew."""

    def _qkv(self, heads=4, kv_heads=2, seq=64, hd=32, dtype=jnp.float32):
        ks = jax.random.split(jax.random.PRNGKey(7), 3)
        q = jax.random.normal(ks[0], (2, seq, heads, hd), dtype)
        k = jax.random.normal(ks[1], (2, seq, kv_heads, hd), dtype)
        v = jax.random.normal(ks[2], (2, seq, kv_heads, hd), dtype)
        return q, k, v

    @pytest.mark.parametrize("sp", [2, 4, 8])
    def test_matches_dense(self, sp):
        mesh = build_mesh(MeshPlan(dp=1, fsdp=1, tp=1, sp=sp),
                          devices=jax.devices()[:sp])
        q, k, v = self._qkv(seq=64)
        ref = _dense_attention(q, k, v, causal=True)
        got = ring_attention(q, k, v, mesh, causal=True, placement="zigzag")
        np.testing.assert_allclose(np.asarray(got), np.asarray(ref),
                                   rtol=2e-4, atol=2e-4)

    def test_matches_dense_bf16(self):
        mesh = build_mesh(MeshPlan(dp=1, fsdp=1, tp=1, sp=4),
                          devices=jax.devices()[:4])
        q, k, v = self._qkv(seq=64, dtype=jnp.bfloat16)
        ref = _dense_attention(q, k, v, causal=True)
        got = ring_attention(q, k, v, mesh, causal=True, placement="zigzag")
        np.testing.assert_allclose(
            np.asarray(got, np.float32), np.asarray(ref, np.float32),
            rtol=2e-2, atol=2e-2)

    def test_composes_with_dp_and_tp(self):
        mesh = build_mesh(MeshPlan(dp=2, fsdp=1, tp=2, sp=2))
        q, k, v = self._qkv(heads=4, kv_heads=4, seq=32)
        ref = _dense_attention(q, k, v, causal=True)
        got = ring_attention(q, k, v, mesh, causal=True, placement="zigzag")
        np.testing.assert_allclose(np.asarray(got), np.asarray(ref),
                                   rtol=2e-4, atol=2e-4)

    def test_auto_impl_selects_zigzag_on_causal_sp_mesh(self):
        """attention_impl="auto" on a mesh with a real sp axis must
        dispatch to ring-zigzag (round-3 default: uniform per-device
        causal block counts justify it) — loss identical to the explicit
        ring-zigzag config and close to the unsharded reference."""
        import dataclasses

        from tpu_docker_api.models.llama import (
            llama_init,
            llama_loss,
            llama_presets,
        )

        cfg = llama_presets()["tiny"]
        params = llama_init(cfg, jax.random.PRNGKey(0))
        tokens = jax.random.randint(jax.random.PRNGKey(1), (4, 33), 0,
                                    cfg.vocab_size, dtype="int32")
        ref = float(llama_loss(params, tokens, cfg))  # unsharded dense
        mesh = build_mesh(MeshPlan(dp=2, fsdp=1, tp=2, sp=2))
        zig_cfg = dataclasses.replace(cfg, attention_impl="ring-zigzag")
        with mesh:
            auto = float(jax.jit(
                lambda p, t: llama_loss(p, t, cfg, mesh))(params, tokens))
            zig = float(jax.jit(
                lambda p, t: llama_loss(p, t, zig_cfg, mesh))(
                    params, tokens))
        assert auto == zig  # same program: auto resolved to ring-zigzag
        np.testing.assert_allclose(auto, ref, rtol=1e-3, atol=1e-3)

    @pytest.mark.parametrize("sp", [2, 4, 8])
    def test_block_work_is_uniform(self, sp):
        """THE zigzag property: identical per-device block counts. Total
        work is n(2n+1) half-stripe pairs — slightly BELOW the contiguous
        skip's 2n(n+1) half-units, since half-stripe granularity also
        trims the wasted masked quadrants of diagonal blocks."""
        mesh = build_mesh(MeshPlan(dp=1, fsdp=1, tp=1, sp=sp),
                          devices=jax.devices()[:sp])
        q, k, v = self._qkv(seq=16 * sp)
        _, counts = ring_attention(q, k, v, mesh, causal=True,
                                   placement="zigzag",
                                   with_block_counts=True)
        assert np.asarray(counts).tolist() == [2 * sp + 1] * sp

    def test_gradients_flow(self):
        mesh = build_mesh(MeshPlan(dp=1, fsdp=1, tp=1, sp=4),
                          devices=jax.devices()[:4])
        q, k, v = self._qkv(seq=32)

        def loss(fn_kwargs, q, k, v):
            return jnp.sum(ring_attention(
                q, k, v, mesh, causal=True, **fn_kwargs) ** 2)

        gz = jax.grad(lambda q, k, v: loss(
            dict(placement="zigzag"), q, k, v), argnums=(0, 1, 2))(q, k, v)
        gc = jax.grad(lambda q, k, v: loss(
            dict(placement="contiguous"), q, k, v), argnums=(0, 1, 2))(q, k, v)
        for a, b in zip(gz, gc):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       rtol=2e-4, atol=2e-4)

    def test_non_causal_rejected(self):
        mesh = build_mesh(MeshPlan(dp=1, fsdp=1, tp=1, sp=2),
                          devices=jax.devices()[:2])
        q, k, v = self._qkv(seq=32)
        with pytest.raises(ValueError, match="causal"):
            ring_attention(q, k, v, mesh, causal=False, placement="zigzag")
        with pytest.raises(ValueError, match="unknown placement"):
            ring_attention(q, k, v, mesh, causal=True, placement="striped")
