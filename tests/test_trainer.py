"""Sharded training: state creation, train step on dp/fsdp/tp meshes,
loss descent, donation."""

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import pytest

#: JAX-compile heavy: excluded from the `-m 'not slow'` quick tier so it
#: fits its time budget; still runs in `make test` (the full suite)
pytestmark = pytest.mark.slow


from tpu_docker_api.models.llama import llama_presets
from tpu_docker_api.parallel.mesh import MeshPlan, build_mesh
from tpu_docker_api.train.trainer import (
    create_train_state,
    default_optimizer,
    make_train_step,
    synthetic_batch,
)

TINY = llama_presets()["tiny"]


@pytest.mark.parametrize("plan", [
    MeshPlan(dp=8, fsdp=1, tp=1, sp=1),
    MeshPlan(dp=2, fsdp=2, tp=2, sp=1),
    MeshPlan(dp=1, fsdp=4, tp=2, sp=1),
])
def test_train_step_runs_on_mesh(plan):
    mesh = build_mesh(plan)
    state, opt = create_train_state(TINY, mesh, jax.random.PRNGKey(0))
    step = make_train_step(TINY, mesh, opt)
    tokens = synthetic_batch(jax.random.PRNGKey(1), 8, 32, TINY.vocab_size)
    state, metrics = step(state, tokens)
    assert int(metrics["step"]) == 1
    assert np.isfinite(float(metrics["loss"]))
    state, metrics = step(state, tokens)
    assert int(metrics["step"]) == 2


def test_params_actually_sharded():
    mesh = build_mesh(MeshPlan(dp=1, fsdp=4, tp=2, sp=1))
    state, _ = create_train_state(TINY, mesh, jax.random.PRNGKey(0))
    wq = state.params["layers"]["attn"]["wq"]
    assert len(wq.addressable_shards) == 8
    # fsdp axis shards dim=1 (64/4=16), tp shards dim=2
    shard_shape = wq.addressable_shards[0].data.shape
    assert shard_shape == (TINY.n_layers, TINY.dim // 4,
                           TINY.n_heads * TINY.head_dim // 2)
    # adam moments follow param shardings
    mu = state.opt_state[1][0].mu["layers"]["attn"]["wq"]
    assert mu.addressable_shards[0].data.shape == shard_shape


def test_loss_descends_on_repeated_batch():
    mesh = build_mesh(MeshPlan(dp=2, fsdp=2, tp=2, sp=1))
    state, opt = create_train_state(
        TINY, mesh, jax.random.PRNGKey(0),
        optimizer=default_optimizer(lr=1e-2),
    )
    step = make_train_step(TINY, mesh, opt)
    tokens = synthetic_batch(jax.random.PRNGKey(1), 4, 32, TINY.vocab_size)
    losses = []
    for _ in range(8):
        state, metrics = step(state, tokens)
        losses.append(float(metrics["loss"]))
    assert losses[-1] < losses[0] - 0.5, losses


def test_dp_equals_single_device_math():
    """The same batch gives the same loss whether sharded dp=8 or dp=1 —
    GSPMD must not change the numbers, only the placement."""
    tokens = synthetic_batch(jax.random.PRNGKey(1), 8, 32, TINY.vocab_size)

    def loss_on(plan, devices=None):
        mesh = build_mesh(plan, devices=devices)
        state, opt = create_train_state(TINY, mesh, jax.random.PRNGKey(0))
        step = make_train_step(TINY, mesh, opt)
        _, metrics = step(state, tokens)
        return float(metrics["loss"])

    l_dp = loss_on(MeshPlan(dp=8, fsdp=1, tp=1, sp=1))
    l_single = loss_on(MeshPlan(dp=1, fsdp=1, tp=1, sp=1),
                       devices=jax.devices()[:1])
    np.testing.assert_allclose(l_dp, l_single, rtol=1e-4)


def test_train_step_with_ring_attention():
    """Full train step with the sequence axis sharded (sp=2) and ring
    attention inside the scanned blocks — both stripe placements in ONE
    test so the loss agreement always actually runs."""
    losses = {}
    for impl in ("ring", "ring-zigzag"):
        cfg = dataclasses.replace(TINY, attention_impl=impl)
        mesh = build_mesh(MeshPlan(dp=2, fsdp=1, tp=2, sp=2))
        state, opt = create_train_state(cfg, mesh, jax.random.PRNGKey(0))
        step = make_train_step(cfg, mesh, opt)
        # seq must shard over sp: 32 tokens + 1 → train on 32
        tokens = synthetic_batch(jax.random.PRNGKey(1), 4, 32, cfg.vocab_size)
        state, metrics = step(state, tokens)
        losses[impl] = float(metrics["loss"])
        assert np.isfinite(losses[impl])
    # same math, different placement: bf16 reduction-order tolerance only
    np.testing.assert_allclose(losses["ring"], losses["ring-zigzag"],
                               rtol=5e-3)
