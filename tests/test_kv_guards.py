"""Guarded-write (CAS) conformance across all three KV backends.

One contract, three implementations: etcd's native ``/v3/kv/txn`` compares,
sqlite's compare-inside-the-transaction (BEGIN IMMEDIATE), and memory's
compare-under-the-lock. The suite pins the properties the HA control plane
rides on:

- the contention LOSER gets the typed :class:`errors.GuardFailed` and the
  store shows the winner's write untouched;
- create-if-absent (``expected=None``) admits exactly one creator;
- a failed guard applies NOTHING of a multi-op batch (compare and commit
  are one atomic unit);
- on etcd, a guarded apply is ONE ``/v3/kv/txn`` round trip riding the
  normalize-but-never-retry WRITE path;
- a deposed leader's epoch-fenced write is rejected on every backend
  (the acceptance-criteria split-brain proof, driven through the real
  LeaderElector + FencedKV pair).
"""

import threading

import pytest

from tpu_docker_api import errors
from tpu_docker_api.state import keys
from tpu_docker_api.state.kv import EtcdKV, MemoryKV, SqliteKV

BACKENDS = ("memory", "sqlite", "etcd")


@pytest.fixture()
def gateway():
    # the bytes-level fake etcd grpc-gateway (tests/etcd_gateway.py,
    # shared with test_etcd_kv/test_kv_watch; pytest puts this directory
    # on sys.path in no-package layouts)
    pytest.importorskip("requests")
    from etcd_gateway import start_gateway, stop_gateway

    server, _ = start_gateway()
    try:
        yield server
    finally:
        stop_gateway(server)


@pytest.fixture(params=BACKENDS)
def kv(request, tmp_path):
    if request.param == "memory":
        yield MemoryKV()
    elif request.param == "sqlite":
        s = SqliteKV(str(tmp_path / "guards.db"))
        yield s
        s.close()
    else:
        gw = request.getfixturevalue("gateway")
        yield EtcdKV(f"http://127.0.0.1:{gw.server_address[1]}")


class TestGuardContract:
    def test_cas_create_if_absent_admits_one_creator(self, kv):
        kv.cas("/lease", None, "holder-a")
        assert kv.get("/lease") == "holder-a"
        with pytest.raises(errors.GuardFailed):
            kv.cas("/lease", None, "holder-b")  # the loser, typed
        assert kv.get("/lease") == "holder-a"   # winner untouched

    def test_cas_value_compare_loser_gets_typed_failure(self, kv):
        kv.put("/k", "v1")
        kv.cas("/k", "v1", "v2")
        with pytest.raises(errors.GuardFailed):
            kv.cas("/k", "v1", "v3")  # stale expectation
        assert kv.get("/k") == "v2"

    def test_guard_against_absent_key_fails_value_compare(self, kv):
        with pytest.raises(errors.GuardFailed):
            kv.cas("/missing", "anything", "new")
        assert kv.get_or("/missing") is None

    def test_failed_guard_applies_nothing_of_the_batch(self, kv):
        """Compare and commit are one atomic unit: a lost guard must not
        leak ANY op of a multi-op batch (the lease + epoch write pair the
        elector issues)."""
        kv.put("/lease", "someone-else")
        kv.put("/epoch", "7")
        kv.put("/fam/a", "1")
        with pytest.raises(errors.GuardFailed):
            kv.apply(
                [("put", "/lease", "me"), ("put", "/epoch", "8"),
                 ("delete", "/fam/a"), ("delete_prefix", "/fam/")],
                guards=[("value", "/lease", "nobody")])
        assert kv.get("/lease") == "someone-else"
        assert kv.get("/epoch") == "7"
        assert kv.get("/fam/a") == "1"

    def test_guard_only_apply_asserts_without_writing(self, kv):
        """An ops-free guarded apply is a pure fencing assert: it raises on
        mismatch and writes nothing on success."""
        kv.put("/epoch", "3")
        kv.apply([], guards=[("value", "/epoch", "3")])
        with pytest.raises(errors.GuardFailed):
            kv.apply([], guards=[("value", "/epoch", "4")])

    def test_guarded_apply_passes_and_lands_whole_batch(self, kv):
        kv.put("/lease", "old")
        kv.apply([("put", "/lease", "new"), ("put", "/epoch", "1")],
                 guards=[("value", "/lease", "old")])
        assert kv.get("/lease") == "new"
        assert kv.get("/epoch") == "1"

    def test_malformed_guard_rejected_before_any_write(self, kv):
        for bad in [("value", "/k"), ("version", "/k", "1"),
                    ("value", "/k", 7)]:
            with pytest.raises(ValueError):
                kv.apply([("put", "/ok", "1")], guards=[bad])
        assert kv.get_or("/ok") is None

    def test_racing_cas_admits_exactly_one_winner(self, kv):
        """The elector race at its smallest: N writers CAS from the same
        observed base; exactly one lands, the rest get the typed loss."""
        kv.put("/lease", "expired")
        outcomes: list[str] = []
        mu = threading.Lock()

        def contender(name: str):
            try:
                kv.cas("/lease", "expired", name)
                with mu:
                    outcomes.append(name)
            except errors.GuardFailed:
                pass

        threads = [threading.Thread(target=contender, args=(f"w{i}",))
                   for i in range(4)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        assert len(outcomes) == 1
        assert kv.get("/lease") == outcomes[0]


class TestEtcdGuardWire:
    """etcd specifics: the guarded apply is ONE native txn with compares,
    and it rides the WRITE path — normalized to StoreUnavailable after
    exactly one attempt, never blind-retried (an ambiguous timeout could
    have committed; a retry could double-steal a lease)."""

    def _kv(self, gateway, attempts=3):
        return EtcdKV(f"http://127.0.0.1:{gateway.server_address[1]}",
                      retry_attempts=attempts, retry_base_s=0.001,
                      retry_max_s=0.01)

    def test_guarded_apply_is_one_txn_round_trip(self, gateway):
        kv = self._kv(gateway)
        kv.put("/lease", "old")
        gateway.txn_count = 0
        kv.apply([("put", "/lease", "new"), ("put", "/epoch", "5")],
                 guards=[("value", "/lease", "old")])
        assert gateway.txn_count == 1  # compare + both puts: ONE round trip
        gateway.txn_count = 0
        with pytest.raises(errors.GuardFailed):
            kv.apply([("put", "/lease", "x")],
                     guards=[("value", "/lease", "old")])
        assert gateway.txn_count == 1  # the loss is also a single trip

    def test_absence_guard_maps_to_version_zero_compare(self, gateway):
        kv = self._kv(gateway)
        kv.apply([("put", "/lease", "me")],
                 guards=[("value", "/lease", None)])
        assert kv.get("/lease") == "me"
        with pytest.raises(errors.GuardFailed):
            kv.apply([("put", "/lease", "you")],
                     guards=[("value", "/lease", None)])
        assert kv.get("/lease") == "me"

    def test_guarded_write_never_retried_on_connection_fault(self, gateway):
        kv = self._kv(gateway, attempts=3)
        gateway.fail_next = 1
        with pytest.raises(errors.StoreUnavailable):
            kv.cas("/lease", None, "me")
        # exactly ONE attempt consumed the fault despite the read budget
        assert gateway.fail_seen == 1
        assert gateway.fail_next == 0
        assert kv.get_or("/lease") is None

    def test_guard_failure_is_not_store_unavailable(self, gateway):
        """The two txn outcomes must stay distinguishable: a lost compare
        is a typed app-level loss, not an outage (and vice versa)."""
        kv = self._kv(gateway)
        kv.put("/k", "v")
        with pytest.raises(errors.GuardFailed) as ei:
            kv.cas("/k", "stale", "new")
        assert not isinstance(ei.value, errors.StoreUnavailable)


@pytest.mark.chaos
class TestEpochFencingAcrossBackends:
    """Acceptance criterion: a deposed leader's epoch-fenced write is
    rejected on all three KV backends — driven through the real elector +
    FencedKV pair, exactly as the daemon wires them."""

    def test_deposed_leader_write_rejected(self, kv):
        from tpu_docker_api.service.leader import FencedKV, LeaderElector

        clock = {"now": 100.0}
        a = LeaderElector(kv, "daemon-a", ttl_s=10.0,
                          clock=lambda: clock["now"])
        b = LeaderElector(kv, "daemon-b", ttl_s=10.0,
                          clock=lambda: clock["now"])
        fenced_a = FencedKV(kv, a.fence_guards)
        fenced_b = FencedKV(kv, b.fence_guards)

        a.step()
        assert a.is_leader and a.epoch == 1
        fenced_a.put("/apis/v1/probe", "from-a")  # fenced write while leading

        # A goes silent past its TTL; B steals with a bumped epoch
        clock["now"] += 11.0
        b.step()
        assert b.is_leader and b.epoch == 2
        assert kv.get(keys.LEADER_EPOCH_KEY) == "2"

        # A still BELIEVES it leads (a partitioned daemon does); its next
        # write loses the epoch compare on the store itself
        assert a.is_leader
        with pytest.raises(errors.GuardFailed):
            fenced_a.put("/apis/v1/probe", "stale-from-a")
        with pytest.raises(errors.GuardFailed):
            fenced_a.apply([("delete", "/apis/v1/probe")])
        assert kv.get("/apis/v1/probe") == "from-a"
        # ... and the new leader's writes sail through
        fenced_b.put("/apis/v1/probe", "from-b")
        assert kv.get("/apis/v1/probe") == "from-b"

    def test_release_keeps_epoch_monotonic(self, kv):
        """A graceful release deletes the lease but never the epoch key:
        leadership handed back and forth must yield strictly increasing
        epochs, or fencing would admit a stale writer."""
        from tpu_docker_api.service.leader import LeaderElector

        clock = {"now": 0.0}
        a = LeaderElector(kv, "a", ttl_s=5.0, clock=lambda: clock["now"])
        b = LeaderElector(kv, "b", ttl_s=5.0, clock=lambda: clock["now"])
        a.step()
        assert a.epoch == 1
        a.close(release=True)
        assert kv.get_or(keys.LEADER_LEASE_KEY) is None
        assert kv.get(keys.LEADER_EPOCH_KEY) == "1"
        b.step()  # immediate acquire — no TTL wait after a clean release
        assert b.is_leader and b.epoch == 2
