"""In-process etcd grpc-gateway fake, shared by the KV test suites.

Implements etcd's contract at the BYTES level (store keyed by raw bytes,
[key, range_end) byte-interval comparison) over real HTTP, so EtcdKV's
wire behavior — base64 keys/values, the single-``\\0`` "everything from
key" sentinel, txn compare evaluation, duplicate-key txn rejection — is
testable without a server. Grown for the watch layer (ISSUE 8): every
mutation bumps a server revision and appends per-key events (one revision
per REQUEST, shared by all keys a txn/deleterange touches — etcd
semantics), and ``/v3/watch`` streams them back chunked, proto3-JSON
shaped (PUT type omitted, ``compact_revision`` cancel for a start
revision at or below ``server.compacted``).
"""

import base64
import json
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer


class FakeGateway(BaseHTTPRequestHandler):
    protocol_version = "HTTP/1.1"

    def log_message(self, *args):
        pass

    def finish(self):
        # a watch client tearing its socket down mid-stream is normal
        # teardown, not a handler error worth a stderr traceback
        try:
            super().finish()
        except (BrokenPipeError, ConnectionResetError, OSError):
            pass

    @property
    def store(self) -> dict[bytes, bytes]:
        return self.server.store

    def do_POST(self):
        # connection-fault injection: abort the next N requests at the
        # socket level (no HTTP response at all) — what a dying etcd or a
        # mid-restart gateway looks like to the client
        if getattr(self.server, "fail_next", 0) > 0:
            self.server.fail_next -= 1
            self.server.fail_seen += 1
            self.close_connection = True
            self.connection.close()
            return
        self._do_POST()

    def _emit(self, op: str, key: bytes, value: bytes | None) -> None:
        """One event at the server's CURRENT revision (the caller bumped
        it once for the whole request, etcd-style)."""
        self.server.events.append((self.server.rev, op, key, value))

    def _do_POST(self):
        length = int(self.headers.get("Content-Length") or 0)
        body = json.loads(self.rfile.read(length))
        if self.path == "/v3/kv/txn":
            return self._do_txn(body)
        if self.path == "/v3/watch":
            return self._do_watch(body)
        key = base64.b64decode(body["key"])
        range_end = (base64.b64decode(body["range_end"])
                     if "range_end" in body else None)

        def in_range(k: bytes) -> bool:
            if range_end is None:
                return k == key
            if range_end == b"\0":   # etcd sentinel: all keys >= key
                return k >= key
            return key <= k < range_end

        if self.path == "/v3/kv/put":
            self.server.rev += 1
            self.store[key] = base64.b64decode(body["value"])
            self._emit("put", key, self.store[key])
            return self._reply({"header": self._header()})
        if self.path == "/v3/kv/range":
            rev_q = int(body.get("revision", 0) or 0)
            if rev_q:
                # MVCC emulation without MVCC storage: serve at an old
                # revision only when the requested range provably hasn't
                # changed since it (the live store IS that snapshot);
                # otherwise answer the real server's compaction error —
                # conservative but contract-compatible (the client's only
                # recovery either way is a fresh first page)
                changed = any(
                    r > rev_q and (k2 == key if range_end is None
                                   else in_range(k2))
                    for (r, _op, k2, _v) in self.server.events)
                if changed or rev_q <= self.server.compacted:
                    return self._reply_error(
                        400, "etcdserver: mvcc: required revision has "
                             "been compacted")
            keys_only = bool(body.get("keys_only"))
            kvs = [
                {"key": base64.b64encode(k).decode(),
                 **({} if keys_only
                    else {"value": base64.b64encode(v).decode()})}
                for k, v in sorted(self.store.items()) if in_range(k)
            ]
            limit = int(body.get("limit", 0))
            if limit:
                kvs = kvs[:limit]
            resp = {"header": self._header(), "count": str(len(kvs))}
            if kvs:  # the gateway omits empty kvs arrays
                resp["kvs"] = kvs
            return self._reply(resp)
        if self.path == "/v3/kv/deleterange":
            doomed = sorted(k for k in self.store if in_range(k))
            if doomed:
                # one revision for the whole request, one event per key —
                # exactly how etcd expands a range delete
                self.server.rev += 1
                for k in doomed:
                    del self.store[k]
                    self._emit("delete", k, None)
            return self._reply({"header": self._header(),
                                "deleted": str(len(doomed))})
        self.send_error(404)

    def _header(self) -> dict:
        return {"revision": str(self.server.rev)}

    def _reply(self, payload: dict):
        data = json.dumps(payload).encode()
        self.send_response(200)
        self.send_header("Content-Type", "application/json")
        self.send_header("Content-Length", str(len(data)))
        self.end_headers()
        self.wfile.write(data)

    def _reply_error(self, status: int, message: str):
        """grpc-gateway error shape: JSON {error, code} on a non-200 —
        what a real gateway answers for e.g. a compacted revision."""
        data = json.dumps({"error": message, "code": 11}).encode()
        self.send_response(status)
        self.send_header("Content-Type", "application/json")
        self.send_header("Content-Length", str(len(data)))
        self.end_headers()
        self.wfile.write(data)

    def _do_txn(self, body: dict):
        """Txn with compare support: evaluate the ``compare`` list against
        the live store first — any mismatch answers with ``succeeded``
        omitted (proto3 JSON drops false booleans) and commits NOTHING.
        The success branch then commits atomically — staged against a copy
        so a rejected batch changes nothing. Enforces etcd's duplicate-key
        rule (server txn.go checkIntervals: a put may not overlap another
        put or a delete range in the same branch), so a production batch
        the real server would reject fails here too."""
        self.server.txn_count += 1
        for cmp_ in body.get("compare", []):
            k = base64.b64decode(cmp_["key"])
            if cmp_.get("target") == "VERSION":
                # the absence guard: VERSION == 0 ⇔ key never put
                want_absent = str(cmp_.get("version", "0")) == "0"
                if (k in self.store) == want_absent:
                    return self._reply({"header": self._header()})
            elif cmp_.get("target") == "VALUE":
                want = base64.b64decode(cmp_.get("value", ""))
                if self.store.get(k) != want:
                    return self._reply({"header": self._header()})
            else:
                return self.send_error(400, "unsupported compare target")

        def covers(k: bytes, key: bytes, range_end: bytes | None) -> bool:
            if range_end is None:
                return k == key
            if range_end == b"\0":   # etcd sentinel: all keys >= key
                return k >= key
            return key <= k < range_end

        staged = dict(self.store)
        events: list[tuple[str, bytes, bytes | None]] = []
        put_keys: set[bytes] = set()
        del_ranges: list[tuple[bytes, bytes | None]] = []
        for req in body.get("success", []):
            if "requestPut" in req:
                put = req["requestPut"]
                k = base64.b64decode(put["key"])
                if k in put_keys:
                    return self.send_error(
                        400, "duplicate key given in txn request")
                put_keys.add(k)
                staged[k] = base64.b64decode(put["value"])
                events.append(("put", k, staged[k]))
            elif "requestDeleteRange" in req:
                dr = req["requestDeleteRange"]
                key = base64.b64decode(dr["key"])
                range_end = (base64.b64decode(dr["range_end"])
                             if "range_end" in dr else None)
                del_ranges.append((key, range_end))
                for k in sorted(staged):
                    if covers(k, key, range_end):
                        del staged[k]
                        events.append(("delete", k, None))
            else:
                return self.send_error(400)
        for k in put_keys:
            if any(covers(k, key, end) for key, end in del_ranges):
                return self.send_error(
                    400, "duplicate key given in txn request")
        self.store.clear()
        self.store.update(staged)
        if events:
            # a committed txn is ONE revision, stamped on every event
            self.server.rev += 1
            for op, k, v in events:
                self._emit(op, k, v)
        return self._reply({"header": self._header(), "succeeded": True})

    def _do_watch(self, body: dict):
        """Chunked ``/v3/watch`` stream: a created response first, then
        event batches as the server's log grows, until the client closes
        the connection. ``start_revision`` is INCLUSIVE (etcd semantics);
        at or below ``server.compacted`` the stream is canceled with
        ``compact_revision`` — the client maps that to WatchLost."""
        self.close_connection = True  # a watch stream never pipelines
        create = body.get("create_request", {})
        key = base64.b64decode(create["key"])
        range_end = (base64.b64decode(create["range_end"])
                     if "range_end" in create else None)
        start_rev = int(create.get("start_revision", 0) or 0)

        def in_range(k: bytes) -> bool:
            if range_end is None:
                return k == key
            if range_end == b"\0":
                return k >= key
            return key <= k < range_end

        self.send_response(200)
        self.send_header("Content-Type", "application/json")
        self.send_header("Transfer-Encoding", "chunked")
        self.end_headers()

        def write_chunk(obj: dict) -> None:
            data = json.dumps(obj).encode() + b"\n"
            self.wfile.write(f"{len(data):x}\r\n".encode() + data + b"\r\n")
            self.wfile.flush()

        if start_rev and start_rev <= getattr(self.server, "compacted", 0):
            write_chunk({"result": {
                "header": self._header(), "canceled": True,
                "compact_revision": str(self.server.compacted)}})
            self.wfile.write(b"0\r\n\r\n")
            return
        write_chunk({"result": {"header": self._header(), "created": True}})
        delivered = max(start_rev - 1, 0)  # inclusive start
        try:
            while not getattr(self.server, "watch_stop", False):
                batch = [e for e in self.server.events
                         if e[0] > delivered and in_range(e[2])]
                pending = [e for e in self.server.events if e[0] > delivered]
                if batch:
                    events = []
                    for rev, op, k, v in batch:
                        ev = {"kv": {"key": base64.b64encode(k).decode(),
                                     "mod_revision": str(rev)}}
                        if op == "put":
                            ev["kv"]["value"] = base64.b64encode(v).decode()
                        else:  # proto3 JSON omits the default PUT type
                            ev["type"] = "DELETE"
                        events.append(ev)
                    write_chunk({"result": {"header": self._header(),
                                            "events": events}})
                if pending:
                    delivered = max(e[0] for e in pending)
                time.sleep(0.02)
        except (BrokenPipeError, ConnectionResetError, OSError):
            pass  # client closed the stream: normal watch teardown


def make_gateway() -> ThreadingHTTPServer:
    """A started-state server object (caller runs serve_forever)."""
    server = ThreadingHTTPServer(("127.0.0.1", 0), FakeGateway)
    # watch streams are long-lived handler threads: never block close on
    # them (watch_stop unblocks their loops, daemon covers the stragglers)
    server.daemon_threads = True
    server.block_on_close = False
    server.store = {}
    server.fail_next = 0
    server.fail_seen = 0
    server.txn_count = 0
    server.rev = 0
    server.events = []
    server.compacted = 0
    server.watch_stop = False
    return server


def start_gateway() -> tuple[ThreadingHTTPServer, threading.Thread]:
    server = make_gateway()
    t = threading.Thread(target=server.serve_forever, daemon=True)
    t.start()
    return server, t


def stop_gateway(server: ThreadingHTTPServer) -> None:
    # unblock any open watch streams first, or shutdown() waits on them
    server.watch_stop = True
    server.shutdown()
    server.server_close()
