"""Container service flows on the fake runtime — the hermetic tier the
reference never had (SURVEY.md §4)."""

import pytest

from tpu_docker_api import errors
from tpu_docker_api.runtime.fake import FakeRuntime
from tpu_docker_api.scheduler.ports import PortScheduler
from tpu_docker_api.scheduler.slices import ChipScheduler
from tpu_docker_api.scheduler.topology import HostTopology
from tpu_docker_api.schemas.container import (
    Bind,
    ContainerCommit,
    ContainerDelete,
    ContainerExecute,
    ContainerPatchChips,
    ContainerPatchVolume,
    ContainerPort,
    ContainerRun,
    ContainerStop,
)
from tpu_docker_api.service.container import ContainerService
from tpu_docker_api.state import keys
from tpu_docker_api.state.kv import MemoryKV
from tpu_docker_api.state.store import StateStore
from tpu_docker_api.state.version import VersionMap
from tpu_docker_api.state.workqueue import WorkQueue


class Env:
    def __init__(self, tmp_path, acc="v5e-8"):
        self.kv = MemoryKV()
        self.store = StateStore(self.kv)
        self.runtime = FakeRuntime(root=str(tmp_path))
        self.chips = ChipScheduler(HostTopology.build(acc), self.kv)
        self.ports = PortScheduler(self.kv, 40000, 40099)
        self.versions = VersionMap(self.kv, keys.VERSIONS_CONTAINER_KEY)
        self.wq = WorkQueue(self.kv)
        self.wq.start()
        self.svc = ContainerService(
            self.runtime, self.store, self.chips, self.ports,
            self.versions, self.wq,
        )

    def close(self):
        self.wq.close()


@pytest.fixture
def env(tmp_path):
    e = Env(tmp_path)
    yield e
    e.close()


def run_default(env, name="train", chips=4, **kw):
    out = env.svc.run_container(ContainerRun(
        image_name="jax:latest", container_name=name, chip_count=chips, **kw
    ))
    env.wq.drain()
    return out


class TestRun:
    def test_run_tpu_container(self, env):
        out = run_default(env)
        assert out["name"] == "train-0"
        assert len(out["chipIds"]) == 4 and out["iciContiguous"]
        info = env.runtime.container_inspect("train-0")
        assert info.running
        assert [d.host_path for d in info.spec.devices] == [
            f"/dev/accel{i}" for i in out["chipIds"]
        ]
        # state persisted asynchronously (reference :528-532)
        state = env.store.get_container("train-0")
        assert state.version == 0

    def test_run_cardless(self, env):
        out = run_default(env, name="smoke", chips=0)
        info = env.runtime.container_inspect("smoke-0")
        assert info.spec.devices == [] and info.spec.chip_ids == []

    def test_run_with_ports(self, env):
        out = env.svc.run_container(ContainerRun(
            image_name="jax", container_name="srv", chip_count=0,
            container_ports=[ContainerPort(8080), ContainerPort(2222)],
        ))
        env.wq.drain()
        info = env.runtime.container_inspect("srv-0")
        hosts = [pb.host_port for pb in info.spec.port_bindings]
        assert hosts == [40000, 40001]

    def test_duplicate_family_rejected(self, env):
        run_default(env)
        with pytest.raises(errors.ContainerExisted):
            run_default(env)

    def test_chip_exhaustion_rejected_and_rolls_back(self, env):
        with pytest.raises(errors.ChipNotEnough):
            run_default(env, chips=9)
        assert len(env.chips.free_chips) == 8
        assert env.versions.get("train") is None

    def test_explicit_slice_shape(self, env):
        out = run_default(env, name="slice", chips=0, slice_shape="2x2")
        assert len(out["chipIds"]) == 4 and out["iciContiguous"]

    def test_start_failure_rolls_back_everything(self, env, monkeypatch):
        def boom(name):
            raise RuntimeError("start failed")

        monkeypatch.setattr(env.runtime, "container_start", boom)
        with pytest.raises(RuntimeError):
            env.svc.run_container(ContainerRun(
                image_name="jax", container_name="bad", chip_count=2,
                container_ports=[ContainerPort(80)],
            ))
        # container removed, chips+ports returned, version rolled back
        assert not env.runtime.container_exists("bad-0")
        assert len(env.chips.free_chips) == 8
        assert env.ports.n_free == 100
        assert env.versions.get("bad") is None


class TestPatchChips:
    def test_grow_rolls_new_version(self, env):
        run_default(env, chips=2)
        out = env.svc.patch_container_chips("train-0", ContainerPatchChips(chip_count=4))
        env.wq.drain()
        assert out["name"] == "train-1"
        assert len(out["chipIds"]) == 4
        # old stopped, new running (quiesce→copy→start, SURVEY.md §5.4)
        assert not env.runtime.container_inspect("train-0").running
        assert env.runtime.container_inspect("train-1").running
        # 4 chips in use total
        assert len(env.chips.free_chips) == 4

    def test_data_migrated_before_start(self, env, tmp_path):
        run_default(env, chips=2)
        # write "checkpoint" data into the old container's fs
        old_dir = env.runtime.container_data_dir("train-0")
        with open(f"{old_dir}/ckpt.txt", "w") as f:
            f.write("step=100")
        env.svc.patch_container_chips("train-0", ContainerPatchChips(chip_count=4))
        env.wq.drain()
        new_dir = env.runtime.container_data_dir("train-1")
        with open(f"{new_dir}/ckpt.txt") as f:
            assert f.read() == "step=100"
        # engine saw: stop(old) strictly before start(new)
        calls = env.runtime.calls
        assert calls.index(("stop", "train-0")) < calls.index(("start", "train-1"))

    def test_shrink(self, env):
        run_default(env, chips=4)
        out = env.svc.patch_container_chips("train-0", ContainerPatchChips(chip_count=1))
        env.wq.drain()
        assert len(out["chipIds"]) == 1
        assert len(env.chips.free_chips) == 7

    def test_shrink_to_cardless(self, env):
        run_default(env, chips=2)
        out = env.svc.patch_container_chips("train-0", ContainerPatchChips(chip_count=0))
        env.wq.drain()
        info = env.runtime.container_inspect(out["name"])
        assert info.spec.devices == []
        assert len(env.chips.free_chips) == 8

    def test_cardless_to_carded(self, env):
        run_default(env, name="cpu", chips=0)
        out = env.svc.patch_container_chips("cpu-0", ContainerPatchChips(chip_count=2))
        env.wq.drain()
        assert len(out["chipIds"]) == 2

    def test_noop_patch_rejected(self, env):
        run_default(env, chips=2)
        with pytest.raises(errors.NoPatchRequired):
            env.svc.patch_container_chips("train-0", ContainerPatchChips(chip_count=2))

    def test_version_mismatch_rejected(self, env):
        run_default(env, chips=2)
        env.svc.patch_container_chips("train-0", ContainerPatchChips(chip_count=3))
        env.wq.drain()
        with pytest.raises(errors.VersionNotMatch):
            env.svc.patch_container_chips("train-0", ContainerPatchChips(chip_count=4))

    def test_patch_by_base_name_hits_latest(self, env):
        run_default(env, chips=2)
        out = env.svc.patch_container_chips("train", ContainerPatchChips(chip_count=3))
        env.wq.drain()
        assert out["name"] == "train-1"

    def test_fresh_ports_on_new_version(self, env):
        env.svc.run_container(ContainerRun(
            image_name="jax", container_name="srv", chip_count=1,
            container_ports=[ContainerPort(8080)],
        ))
        env.wq.drain()
        old_port = env.runtime.container_inspect("srv-0").spec.port_bindings[0].host_port
        env.svc.patch_container_chips("srv-0", ContainerPatchChips(chip_count=2))
        env.wq.drain()
        new_port = env.runtime.container_inspect("srv-1").spec.port_bindings[0].host_port
        assert new_port != old_port
        # old port returned to the pool
        assert old_port not in env.ports.status()["usedPorts"]


class TestPatchVolume:
    def test_swap_bind(self, env, tmp_path):
        (tmp_path / "v1").mkdir()
        (tmp_path / "v2").mkdir()
        env.svc.run_container(ContainerRun(
            image_name="jax", container_name="train", chip_count=1,
            binds=[Bind(str(tmp_path / "v1"), "/data")],
        ))
        env.wq.drain()
        out = env.svc.patch_container_volume("train-0", ContainerPatchVolume(
            old_bind=Bind(str(tmp_path / "v1"), "/data"),
            new_bind=Bind(str(tmp_path / "v2"), "/data"),
        ))
        env.wq.drain()
        info = env.runtime.container_inspect(out["name"])
        assert info.spec.binds == [f"{tmp_path}/v2:/data"]

    def test_unknown_old_bind_rejected(self, env):
        run_default(env, chips=0)
        with pytest.raises(errors.BadRequest):
            env.svc.patch_container_volume("train-0", ContainerPatchVolume(
                old_bind=Bind("/nope", "/data"), new_bind=Bind("/x", "/data"),
            ))

    def test_identical_bind_noop(self, env):
        run_default(env, chips=0)
        with pytest.raises(errors.NoPatchRequired):
            env.svc.patch_container_volume("train-0", ContainerPatchVolume(
                old_bind=Bind("/a", "/d"), new_bind=Bind("/a", "/d"),
            ))


class TestStopRestartDeleteExecCommitInfo:
    def test_stop_restores_resources(self, env):
        run_default(env, chips=4)
        env.svc.stop_container("train-0")
        assert len(env.chips.free_chips) == 8
        assert not env.runtime.container_inspect("train-0").running

    def test_restart_cardless_in_place(self, env):
        run_default(env, name="cpu", chips=0)
        out = env.svc.restart_container("cpu-0")
        assert out["name"] == "cpu-0"  # no version bump

    def test_restart_running_carded_in_place(self, env):
        run_default(env, chips=2)
        out = env.svc.restart_container("train-0")
        assert out["name"] == "train-0"

    def test_restart_stopped_carded_rolls_version(self, env):
        """Stopped carded container lost its chips; restart re-allocates and
        rolls a new version (reference :390-425)."""
        run_default(env, chips=2)
        env.svc.stop_container("train-0")
        out = env.svc.restart_container("train-0")
        env.wq.drain()
        assert out["name"] == "train-1"
        assert len(out["chipIds"]) == 2
        assert env.runtime.container_inspect("train-1").running

    def test_delete_returns_resources(self, env):
        env.svc.run_container(ContainerRun(
            image_name="jax", container_name="train", chip_count=4,
            container_ports=[ContainerPort(8080)],
        ))
        env.wq.drain()
        env.svc.delete_container("train-0", ContainerDelete(
            force=True, del_etcd_info_and_version_record=True,
        ))
        env.wq.drain()
        assert len(env.chips.free_chips) == 8
        assert env.ports.n_free == 100
        assert env.versions.get("train") is None
        with pytest.raises(errors.NotExistInStore):
            env.store.get_container("train-0")

    def test_delete_keeps_state_without_flag(self, env):
        run_default(env, chips=1)
        env.svc.delete_container("train-0", ContainerDelete(force=True))
        env.wq.drain()
        assert env.store.get_container("train-0").version == 0
        assert env.versions.get("train") == 0

    def test_execute(self, tmp_path):
        e = Env(tmp_path)
        e.runtime._allow_exec = True
        try:
            e.svc.run_container(ContainerRun(
                image_name="jax", container_name="smoke", chip_count=0
            ))
            import sys
            out = e.svc.execute_container("smoke-0", ContainerExecute(
                cmd=[sys.executable, "-c", "print(6 * 7)"]
            ))
            assert out.strip() == "42"
        finally:
            e.close()

    def test_commit_requires_image_name(self, env):
        run_default(env, chips=0)
        with pytest.raises(errors.BadRequest):
            env.svc.commit_container("train-0", ContainerCommit())
        img = env.svc.commit_container("train-0", ContainerCommit("snap:v1"))
        assert img.startswith("sha256:")

    def test_info(self, env):
        run_default(env, chips=2)
        info = env.svc.get_container_info("train-0")
        assert info["state"]["version"] == 0
        assert info["runtime"]["running"]

    def test_ops_on_missing_container(self, env):
        with pytest.raises(errors.ContainerNotExist):
            env.svc.stop_container("ghost-0")
        with pytest.raises(errors.ContainerNotExist):
            env.svc.get_container_info("ghost")


class TestHistoryRollback:
    """Version history + rollback — the capability the reference README
    advertises (README.md:142-144) but its latest-wins etcd layout cannot
    deliver (SURVEY.md appendix)."""

    def _grow_family(self, env):
        """train-0 (2 chips) → patch → train-1 (4 chips)."""
        run_default(env, chips=2)
        env.svc.patch_container_chips("train-0",
                                      ContainerPatchChips(chip_count=4))
        env.wq.drain()

    def test_history_lists_all_versions(self, env):
        self._grow_family(env)
        hist = env.svc.get_container_history("train")
        assert hist["latest"] == 1
        assert [v["version"] for v in hist["versions"]] == [0, 1]
        assert hist["versions"][0]["chipCount"] == 2
        assert hist["versions"][1]["chipCount"] == 4
        assert hist["versions"][1]["latest"]
        # the retired version is retained in the runtime (rollback material)
        assert hist["versions"][0]["inRuntime"]

    def test_rollback_restores_old_spec_with_newest_data(self, env):
        from tpu_docker_api.schemas.container import ContainerRollback

        self._grow_family(env)
        # newest data lives in train-1
        with open(f"{env.runtime.container_data_dir('train-1')}/ckpt.txt",
                  "w") as f:
            f.write("step=200")
        out = env.svc.rollback_container(
            "train", ContainerRollback(version=0))
        env.wq.drain()
        assert out == {"name": "train-2", "fromVersion": 0,
                       "chipIds": out["chipIds"]}
        # spec rolled back: 2 chips again, scheduler freed the other 2
        assert len(out["chipIds"]) == 2
        assert len(env.chips.free_chips) == 6
        assert env.runtime.container_inspect("train-2").running
        assert not env.runtime.container_inspect("train-1").running
        # data came from the LATEST version (default dataFrom)
        with open(f"{env.runtime.container_data_dir('train-2')}/ckpt.txt") as f:
            assert f.read() == "step=200"

    def test_rollback_snapshot_restore_from_target(self, env):
        from tpu_docker_api.schemas.container import ContainerRollback

        run_default(env, chips=2)
        with open(f"{env.runtime.container_data_dir('train-0')}/ckpt.txt",
                  "w") as f:
            f.write("old-snapshot")
        env.svc.patch_container_chips("train-0",
                                      ContainerPatchChips(chip_count=4))
        env.wq.drain()
        # diverge the new version's data
        with open(f"{env.runtime.container_data_dir('train-1')}/ckpt.txt",
                  "w") as f:
            f.write("newer")
        out = env.svc.rollback_container(
            "train", ContainerRollback(version=0, data_from="target"))
        env.wq.drain()
        with open(f"{env.runtime.container_data_dir(out['name'])}/ckpt.txt") as f:
            assert f.read() == "old-snapshot"

    def test_rollback_validation(self, env):
        from tpu_docker_api.schemas.container import ContainerRollback

        self._grow_family(env)
        with pytest.raises(errors.NoPatchRequired):
            env.svc.rollback_container("train", ContainerRollback(version=1))
        with pytest.raises(errors.BadRequest):
            env.svc.rollback_container("train", ContainerRollback(version=7))
        with pytest.raises(errors.BadRequest):
            env.svc.rollback_container(
                "train", ContainerRollback(version=0, data_from="nope"))
        # optimistic concurrency: stale versioned name refused
        with pytest.raises(errors.VersionNotMatch):
            env.svc.rollback_container("train-0",
                                       ContainerRollback(version=0))

    def test_rollback_is_itself_versioned(self, env):
        """Rolling back twice keeps moving forward: rollback never mutates."""
        from tpu_docker_api.schemas.container import ContainerRollback

        self._grow_family(env)
        env.svc.rollback_container("train", ContainerRollback(version=0))
        env.wq.drain()
        out = env.svc.rollback_container("train", ContainerRollback(version=1))
        env.wq.drain()
        assert out["name"] == "train-3"
        assert len(out["chipIds"]) == 4
        hist = env.svc.get_container_history("train")
        assert [v["version"] for v in hist["versions"]] == [0, 1, 2, 3]

    def test_rollback_of_stopped_family_reclaims_chips(self, env):
        """A stopped family's chips went back to the pool (and may belong to
        someone else); rollback must claim fresh chips through the
        scheduler, never attach the stored spec's stale chip ids."""
        from tpu_docker_api.schemas.container import ContainerRollback

        self._grow_family(env)                   # train-1 holds chips 0-3
        env.svc.stop_container("train")          # chips 0-3 back to pool
        run_default(env, name="other", chips=4)  # takes (some of) them
        out = env.svc.rollback_container("train", ContainerRollback(version=0))
        env.wq.drain()
        other_chips = set(
            env.runtime.container_inspect("other-0").spec.chip_ids)
        # no double attachment, and the scheduler knows train's new claim
        assert not (set(out["chipIds"]) & other_chips)
        assert set(env.chips.owned_chips("train")) == set(out["chipIds"])
        assert len(env.chips.free_chips) == 8 - 4 - 2

    def test_patch_chips_of_stopped_family_reclaims_chips(self, env):
        """Same scheduler-truth discipline on the patch path."""
        run_default(env, chips=2)                # train-0: chips 0,1
        env.svc.stop_container("train")          # freed
        run_default(env, name="other", chips=2)  # takes chips
        out = env.svc.patch_container_chips(
            "train", ContainerPatchChips(chip_count=3))
        env.wq.drain()
        other_chips = set(
            env.runtime.container_inspect("other-0").spec.chip_ids)
        assert not (set(out["chipIds"]) & other_chips)
        assert set(env.chips.owned_chips("train")) == set(out["chipIds"])

    def test_rollback_stopped_family_keeps_old_stopped_and_ports_clean(self, env):
        """Rolling back a STOPPED family must not run the quiesce branch
        (its ports were already returned on stop) and must not restart the
        deliberately-stopped old container."""
        from tpu_docker_api.schemas.container import ContainerRollback

        run_default(env, chips=2,
                    container_ports=[ContainerPort(container_port=8888)])
        env.svc.patch_container_chips("train",
                                      ContainerPatchChips(chip_count=1))
        env.wq.drain()
        env.svc.stop_container("train")   # train-1 stopped, ports freed
        before = env.ports.status()["usedCount"]
        out = env.svc.rollback_container("train", ContainerRollback(version=0))
        env.wq.drain()
        # old stays stopped; new running; exactly the new version's port set
        # is allocated (no double-free, no leak)
        assert not env.runtime.container_inspect("train-1").running
        assert env.runtime.container_inspect(out["name"]).running
        assert env.ports.status()["usedCount"] == before + 1
        # train-1's only start predates its deliberate stop — the rollback
        # flow never restarted it
        calls = env.runtime.calls
        last_start = max(i for i, c in enumerate(calls)
                         if c == ("start", "train-1"))
        stop_idx = max(i for i, c in enumerate(calls)
                       if c == ("stop", "train-1"))
        assert last_start < stop_idx


class TestQueueBackpressureCompensation:
    """A rejected submit (QueueSaturated/QueueClosed) must leave NOTHING
    half-applied (docs/robustness.md "Backpressure and shutdown"): the
    rejected record cannot replay, so the flow must unwind inline."""

    def test_saturated_replace_unquiesces_old_and_retires_new(
            self, env, tmp_path, monkeypatch):
        (tmp_path / "v1").mkdir()
        (tmp_path / "v2").mkdir()
        env.svc.run_container(ContainerRun(
            image_name="jax", container_name="web", chip_count=2,
            container_ports=[ContainerPort(80)],
            binds=[Bind(str(tmp_path / "v1"), "/data")],
        ))
        env.wq.drain()
        used_before = env.ports.status()["usedCount"]

        def saturated(*a, **k):
            raise errors.QueueSaturated("full")

        monkeypatch.setattr(env.wq, "submit_record", saturated)
        with pytest.raises(errors.QueueSaturated):
            env.svc.patch_container_volume("web", ContainerPatchVolume(
                old_bind=Bind(str(tmp_path / "v1"), "/data"),
                new_bind=Bind(str(tmp_path / "v2"), "/data"),
            ))
        # old container back up with its ports re-claimed; replacement gone
        assert env.runtime.container_inspect("web-0").running
        assert env.versions.get("web") == 0
        assert not env.runtime.container_exists("web-1")
        assert env.ports.status()["usedCount"] == used_before
        with pytest.raises(errors.NotExistInStore):
            env.store.get_container("web-1")

    def test_saturated_purge_keeps_version_pointer_for_retry(
            self, env, monkeypatch):
        run_default(env, chips=2)
        real_submit = env.wq.submit_record

        def saturated(*a, **k):
            raise errors.QueueSaturated("full")

        monkeypatch.setattr(env.wq, "submit_record", saturated)
        with pytest.raises(errors.QueueSaturated):
            env.svc.delete_container("train-0", ContainerDelete(
                force=True, del_etcd_info_and_version_record=True))
        # the pointer survives the rejected purge — a retried delete must
        # still resolve the family and reach the purge path (remove-first
        # would 404 forever and leak the state family)
        assert env.versions.get("train") == 0
        monkeypatch.setattr(env.wq, "submit_record", real_submit)
        env.svc.delete_container("train-0", ContainerDelete(
            force=True, del_etcd_info_and_version_record=True))
        env.wq.drain()
        assert env.versions.get("train") is None
        with pytest.raises(errors.NotExistInStore):
            env.store.get_container("train-0")
