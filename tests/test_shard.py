"""Sharded writer plane (service/shard.py, docs/robustness.md "Sharded
writer plane").

Three layers of proof:

1. Property tests on the shard map itself — assignment is deterministic
   and total, a service colocates with its replica gangs, a
   ``shard_count`` change moves only the minimal family set (rendezvous),
   and raw store keys classify back to exactly one owner (or global).
2. Router + coordination-record contracts — mutations are always gated by
   the owning shard's lease (creates by body name, named routes by path,
   non-family ops by shard 0) while reads are NEVER gated; a lost
   coordination CAS is retried while benign and surfaces as a typed
   GuardFailed when genuinely contended; a lost shard FENCE is never
   retried.
3. The shard chaos matrix — two real Programs over one KV; the leader of
   a shard portfolio is killed at every ``leader.*`` and ``shard.coord.*``
   crash point; the survivor's shards never block, the victim's shards
   recover within one lease TTL with exactly-once journal replay, and
   every write the deposed leader still attempts is fenced by the store.

Plus the compatibility pins: ``shard_count=1`` / ``leader_election=false``
deployments must keep today's key layout and record bytes exactly.
"""

from __future__ import annotations

import json

import pytest

from tpu_docker_api import config as config_mod, errors
from tpu_docker_api.daemon import Program
from tpu_docker_api.schemas.container import Bind, ContainerPort, ContainerRun
from tpu_docker_api.service.crashpoints import (
    LEADER_CRASH_POINTS,
    SHARD_CRASH_POINTS,
    SimulatedCrash,
    armed,
)
from tpu_docker_api.service.invariants import check_invariants
from tpu_docker_api.service.shard import ShardMap, ShardedKV, ShardPlane, coord_seq
from tpu_docker_api.state import keys
from tpu_docker_api.runtime.fake import FakeRuntime
from tpu_docker_api.state.kv import MemoryKV

pytestmark = pytest.mark.chaos

#: the matrix this module drives — pinned against the registry by
#: tests/test_chaos.py::test_case_matrix_covers_every_crash_point
SHARD_CHAOS_POINTS = LEADER_CRASH_POINTS + SHARD_CRASH_POINTS


def base_for_shard(smap: ShardMap, shard: int, tag: str = "f") -> str:
    """A family base name owned by ``shard`` under ``smap`` (deterministic
    scan, so tests never hardcode hash outputs)."""
    for i in range(10_000):
        name = f"{tag}{i}"
        if smap.shard_of(name) == shard:
            return name
    raise AssertionError(f"no base found for shard {shard}")


# -- 1. shard-map properties ---------------------------------------------------


class TestShardMap:
    def test_assignment_is_stable_total_and_single_shard_degenerate(self):
        m3a, m3b = ShardMap(3), ShardMap(3)
        for i in range(300):
            base = f"fam{i}"
            s = m3a.shard_of(base)
            assert 0 <= s < 3
            # deterministic across instances (and therefore processes)
            assert s == m3b.shard_of(base)
        # shard_count=1 is the degenerate total function: everything is 0
        m1 = ShardMap(1)
        assert {m1.shard_of(f"fam{i}") for i in range(300)} == {0}

    def test_service_and_replica_gangs_colocate(self):
        m = ShardMap(5)
        for svc in ("api", "frontend", "ranker7"):
            home = m.shard_of(svc)
            for r in range(8):
                assert m.shard_of(f"{svc}.r{r}") == home

    def test_count_change_moves_only_the_minimal_family_set(self):
        roots = [f"fam{i}" for i in range(400)]
        m3 = ShardMap(3)
        moved = m3.moved_families(roots, 4)
        # rendezvous: growing 3 → 4 moves ~1/4 of roots, and every mover
        # goes TO the new shard (an old shard can never newly win a
        # contest it already lost)
        assert len(moved) / len(roots) < 0.45
        m4 = ShardMap(4)
        for r in moved:
            assert m4.shard_of(r) == 3
        # the families that stayed kept their exact shard
        for r in set(roots) - set(moved):
            assert m4.shard_of(r) == m3.shard_of(r)
        # shrinking back is the inverse: the SAME set moves, nothing else
        assert sorted(m4.moved_families(roots, 3)) == sorted(moved)

    def test_key_classification_round_trips_the_layout(self):
        m = ShardMap(3)
        s_train = m.shard_of("train")
        # family keys classify to the family's shard
        assert m.shard_of_key("/apis/v1/containers/train/latest") == s_train
        assert m.shard_of_key(
            "/apis/v1/containers/train/v/0000000001") == s_train
        assert m.shard_of_key("/apis/v1/jobs/train/latest") == s_train
        # queue + admission: flat = shard 0, s<i>/ = shard i
        assert m.shard_of_key(keys.queue_task_key(7)) == 0
        assert m.shard_of_key(keys.queue_task_key(7, 2)) == 2
        assert m.shard_of_key(keys.queue_marker_key("t1", 1)) == 1
        assert m.shard_of_key(keys.admission_record_key(3)) == 0
        assert m.shard_of_key(keys.admission_record_key(3, 2)) == 2
        # versions: legacy singleton = shard 0, shard subkeys = shard i
        assert m.shard_of_key(keys.VERSIONS_CONTAINER_KEY) == 0
        assert m.shard_of_key(
            keys.versions_shard_key(keys.Resource.JOBS, 2)) == 2
        # globals stay global: scheduler maps, cordons, leases, coord
        for k in (keys.SCHEDULER_CHIPS_KEY, keys.SCHEDULER_PORTS_KEY,
                  keys.HOSTS_CORDONED_KEY, keys.LEADER_LEASE_KEY,
                  keys.shard_lease_key(1), keys.SHARD_COORD_KEY):
            assert m.shard_of_key(k) is None, k

    def test_shard_zero_owns_every_legacy_key(self):
        """The migration-free adoption pin: a shard_count bump must read
        an existing single-leader store as shard 0's keyspace."""
        assert keys.shard_lease_key(0) == keys.LEADER_LEASE_KEY
        assert keys.shard_epoch_key(0) == keys.LEADER_EPOCH_KEY
        assert keys.queue_tasks_prefix(0) == keys.QUEUE_TASKS_PREFIX
        assert keys.queue_markers_prefix(0) == keys.QUEUE_MARKERS_PREFIX
        assert keys.admission_prefix(0) == keys.ADMISSION_PREFIX
        assert (keys.versions_shard_key(keys.Resource.CONTAINERS, 0)
                == keys.VERSIONS_CONTAINER_KEY)


# -- 2a. router: mutations always routed, reads never -------------------------


class TestMutationRouting:
    def _plane(self, count=3):
        import types

        return types.SimpleNamespace(map=ShardMap(count))

    def test_family_mutations_route_by_name(self):
        from tpu_docker_api.api.app import _shard_for_request

        plane = self._plane()
        m = plane.map
        for res, field in (("containers", "containerName"),
                           ("volumes", "volumeName"),
                           ("jobs", "jobName"),
                           ("services", "serviceName")):
            # named routes: the (version-stripped) path segment decides
            assert _shard_for_request(
                plane, f"/api/v1/{res}/train-3/stop", b"") \
                == m.shard_of("train")
            assert _shard_for_request(
                plane, f"/api/v1/{res}/train", b"") == m.shard_of("train")
            # creates: the body's *Name field decides
            raw = json.dumps({field: "webapp"}).encode()
            assert _shard_for_request(
                plane, f"/api/v1/{res}", raw) == m.shard_of("webapp")

    def test_non_family_mutations_belong_to_shard_zero(self):
        from tpu_docker_api.api.app import _shard_for_request

        plane = self._plane()
        for path in ("/api/v1/hosts/h1/cordon", "/api/v1/hosts/h1/drain",
                     "/api/v1/reconcile", "/api/v1/dead-letters/retry",
                     "/api/v1/compact"):
            assert _shard_for_request(plane, path, b"") == 0
        # unparsable / nameless creates classify to 0 so the handler's own
        # validation error surfaces (never masked by a wrong-shard 503)
        assert _shard_for_request(plane, "/api/v1/containers", b"{nope") == 0
        assert _shard_for_request(plane, "/api/v1/containers", b"{}") == 0

    def test_reads_are_never_gated_and_wrong_shard_mutations_503(self):
        """In-process HTTP round trip: a process holding only SOME shards
        serves every read, owns its shards' mutations, and 503s the rest
        with the owning shard named — zero store reads on the 503 path."""
        kv = MemoryKV()
        rt = FakeRuntime()
        clock = {"now": 100.0}
        smap = ShardMap(3)
        alpha = boot_shard(kv, rt, "alpha", clock, preferred=(0, 1))
        beta = boot_shard(kv, rt, "beta", clock, preferred=(2,))
        try:
            alpha.start()
            beta.start()
            wait_until(lambda: sorted(alpha.shard_plane.held) == [0, 1],
                       what="alpha holding shards 0,1")
            wait_until(lambda: sorted(beta.shard_plane.held) == [2],
                       what="beta holding shard 2")

            mine = base_for_shard(smap, 0)
            theirs = base_for_shard(smap, 2)
            # owned mutation lands
            status, out = http_call(
                alpha, "POST", "/api/v1/containers",
                {"imageName": "jax", "containerName": mine, "chipCount": 0})
            assert (status, out["code"]) == (200, 200)
            # wrong-shard mutation: 503 naming the owning shard + holder,
            # counted with zero store reads once the heartbeat has
            # observed the owning lease (the PR 7 hint contract per shard)
            wait_until(lambda: alpha.shard_plane.electors[2]
                       .leader_hint()["holderId"] == "beta",
                       what="alpha observing beta's shard-2 lease")
            reads = count_reads(kv)
            status, out = http_call(
                alpha, "POST", "/api/v1/containers",
                {"imageName": "jax", "containerName": theirs, "chipCount": 0})
            assert status == 503
            assert out["code"] == errors.NotLeader.code
            assert "shard 2" in out["msg"] and "beta" in out["msg"]
            assert reads() == 0
            assert alpha.container_versions.get(theirs) is None
            # reads are never routed: any process answers any family
            status, out = http_call(
                beta, "GET", f"/api/v1/containers/{mine}-0")
            assert (status, out["code"]) == (200, 200)
            # the shard table is public and store-read-free
            _, out = http_call(alpha, "GET", "/api/v1/shards")
            view = out["data"]
            assert view["sharded"] is True
            assert view["shardCount"] == 3
            assert view["held"] == [0, 1]
            holders = {s["shard"]: s["holderId"] for s in view["shards"]}
            assert holders == {0: "alpha", 1: "alpha", 2: "beta"}
            _, out = http_call(alpha, "GET", "/healthz")
            assert out["data"]["role"] == "leader"
            assert out["data"]["shards"] == {"count": 3, "held": [0, 1]}
            # leadership events are in the merged ring, shard-stamped
            _, out = http_call(alpha, "GET", "/api/v1/events")
            acquired = [e for e in out["data"]
                        if e.get("event") == "shard-acquired"]
            assert {e["shard"] for e in acquired} == {0, 1}
        finally:
            alpha.stop()
            beta.stop()


# -- 2b. cross-shard coordination record --------------------------------------


class _StaleCoordKV(MemoryKV):
    """get_or returns a stale coordination seq the first ``n`` times —
    the deterministic stand-in for another shard leader winning the CAS
    between our read and our apply."""

    def __init__(self, stale_reads: int) -> None:
        super().__init__()
        self.stale_left = stale_reads

    def get_or(self, key, default=None):
        val = super().get_or(key, default)
        if key == keys.SHARD_COORD_KEY and self.stale_left > 0:
            self.stale_left -= 1
            return None if val is None else json.dumps({"seq": -1})
        return val


class TestCoordinationRecord:
    def _plane(self, kv, count=3):
        plane = ShardPlane(kv, ShardMap(count), "me", ttl_s=30.0,
                           clock=lambda: 100.0)
        plane.step_all()
        assert plane.held == frozenset(range(count))
        return plane

    def _two_shard_ops(self, smap):
        a = base_for_shard(smap, 0)
        b = base_for_shard(smap, 1)
        return [("put", keys.latest_key(keys.Resource.CONTAINERS, a), "1"),
                ("put", keys.latest_key(keys.Resource.CONTAINERS, b), "1")]

    def test_cross_shard_batches_bump_the_seq_single_shard_do_not(self):
        kv = MemoryKV()
        plane = self._plane(kv)
        skv = ShardedKV(kv, plane)
        smap = plane.map
        assert coord_seq(kv) == 0
        # single-shard batch: no coordination involved
        a = base_for_shard(smap, 0)
        skv.apply([("put", keys.latest_key(keys.Resource.CONTAINERS, a), "0")])
        assert coord_seq(kv) == 0
        # two shards: one atomic apply carries the seq bump
        skv.apply(self._two_shard_ops(smap))
        assert coord_seq(kv) == 1
        # shard + global singleton: also coordinated
        skv.apply([
            ("put", keys.latest_key(keys.Resource.CONTAINERS, a), "0"),
            ("put", keys.SCHEDULER_CHIPS_KEY, "{}"),
        ])
        assert coord_seq(kv) == 2
        # a pure global-singleton batch (a chip claim) coordinates too:
        # several shard leaders write the ledgers concurrently, and the
        # CAS is what serializes them
        skv.apply([("put", keys.SCHEDULER_CHIPS_KEY, "{}")])
        assert coord_seq(kv) == 3

    @staticmethod
    def _real_seq(kv) -> int:
        # bypass the stale-read shim: the store's actual record
        raw = MemoryKV.get_or(kv, keys.SHARD_COORD_KEY)
        return json.loads(raw)["seq"] if raw else 0

    def test_benign_cas_loss_is_retried_to_success(self):
        kv = _StaleCoordKV(stale_reads=3)
        kv.put(keys.SHARD_COORD_KEY, json.dumps({"seq": 5}, sort_keys=True))
        plane = self._plane(kv)
        skv = ShardedKV(kv, plane)
        ops = self._two_shard_ops(plane.map)
        skv.apply(ops)  # three lost races, then the re-read wins
        assert kv.stale_left == 0
        assert self._real_seq(kv) == 6
        assert kv.get(ops[0][1]) == "1"

    def test_contended_past_budget_is_a_typed_guard_failed(self):
        kv = _StaleCoordKV(stale_reads=10_000)
        kv.put(keys.SHARD_COORD_KEY, json.dumps({"seq": 5}, sort_keys=True))
        plane = self._plane(kv)
        skv = ShardedKV(kv, plane)
        ops = self._two_shard_ops(plane.map)
        with pytest.raises(errors.GuardFailed) as ei:
            skv.apply(ops)
        assert "coordination record" in str(ei.value)
        # the loser left nothing behind
        assert kv.get_or(ops[0][1]) is None
        assert self._real_seq(kv) == 5

    def test_fence_loss_is_never_retried_as_contention(self):
        """A deposed shard leader's cross-shard batch must surface the
        FENCE failure (and leave the seq unbumped) — retrying it as benign
        contention would be split-brain with extra steps."""
        kv = MemoryKV()
        clock = {"now": 100.0}
        plane_a = ShardPlane(kv, ShardMap(3), "a", ttl_s=30.0,
                             clock=lambda: clock["now"])
        plane_a.step_all()
        skv_a = ShardedKV(kv, plane_a)
        # b steals every shard after the TTL
        clock["now"] += 31.0
        plane_b = ShardPlane(kv, ShardMap(3), "b", ttl_s=30.0,
                             clock=lambda: clock["now"])
        plane_b.step_all()
        assert plane_b.held == frozenset({0, 1, 2})
        ops = TestCoordinationRecord._two_shard_ops(self, plane_a.map)
        with pytest.raises(errors.GuardFailed) as ei:
            skv_a.apply(ops)
        assert keys.SHARD_COORD_KEY not in str(ei.value)
        assert coord_seq(kv) == 0
        assert kv.get_or(ops[0][1]) is None
        # the rightful holder's identical batch sails
        ShardedKV(kv, plane_b).apply(ops)
        assert coord_seq(kv) == 1


# -- compatibility pins --------------------------------------------------------


class TestSingleShardCompat:
    def test_task_and_admission_records_omit_shard_zero(self):
        """Byte-for-byte pin: shard-0 (and therefore every unsharded)
        record serializes exactly as before the sharded plane existed."""
        from tpu_docker_api.service.admission import AdmissionRecord
        from tpu_docker_api.state.workqueue import TaskRecord

        rec = TaskRecord(task_id="t", kind="put_kv", params={}, seq=1)
        assert "shard" not in json.loads(rec.to_json())
        assert TaskRecord.from_json(rec.to_json()).shard == 0
        rec2 = TaskRecord(task_id="t", kind="put_kv", params={}, seq=1,
                          shard=2)
        assert json.loads(rec2.to_json())["shard"] == 2
        adm = AdmissionRecord(seq=1, base="b", kind="queued",
                              klass="batch")
        assert "shard" not in json.loads(adm.to_json())
        assert AdmissionRecord.from_json(adm.to_json()).shard == 0

    def test_unsharded_store_carries_no_shard_artifacts(self, tmp_path):
        """leader_election=false (and implicitly shard_count=1): the store
        a workload produces contains no shard leases, no coordination
        record, no sub-prefixed journal keys — today's layout exactly."""
        kv = MemoryKV()
        rt = FakeRuntime(root=str(tmp_path / "rt"))
        cfg = config_mod.Config(
            store_backend="memory", runtime_backend="fake",
            health_watch_interval=0, host_probe_interval_s=0,
            job_supervise_interval=0, reconcile_interval=0)
        prg = Program(cfg, kv=kv, runtime=rt)
        prg.init()
        assert prg.shard_plane is None and prg.shard_map is None
        prg.container_svc.run_container(ContainerRun(
            image_name="jax", container_name="web", chip_count=2,
            container_ports=[ContainerPort(8080)]))
        store = kv.range_prefix("/")
        assert not any("/leader/" in k for k in store)
        assert not any("/queue/tasks/s" in k or "/queue/markers/s" in k
                       or "/admission/s" in k or "/versions/shards/" in k
                       for k in store)
        for k, v in store.items():
            if k.startswith(keys.QUEUE_TASKS_PREFIX):
                assert "shard" not in json.loads(v)


# -- 3. the shard chaos matrix -------------------------------------------------


def boot_shard(kv, runtime, holder, clock, preferred=(),
               shard_count=3) -> Program:
    """A sharded fleet member over the shared KV + runtime: three leases,
    writer loops follow the shard portfolio, virtual clock drives TTL
    expiry. Elector threads are never started unless the test calls
    ``start()`` — the matrix steps them by hand."""
    cfg = config_mod.Config(
        port=0, store_backend="memory", runtime_backend="fake",
        health_watch_interval=0, end_port=40099, host_probe_interval_s=0,
        job_supervise_interval=0, reconcile_interval=0,
        leader_election=True, leader_ttl_s=30.0, leader_id=holder,
        leader_renew_interval_s=0.05,
        shard_count=shard_count, shard_preferred=list(preferred),
        shard_standby_delay_s=50.0,
    )
    prg = Program(cfg, host="127.0.0.1", kv=kv, runtime=runtime,
                  leader_clock=lambda: clock["now"])
    prg.init()
    return prg


def step_fleet(*progs):
    for p in progs:
        p.shard_plane.step_all()


def http_call(prg, method, path, body=None):
    import urllib.error
    import urllib.request

    req = urllib.request.Request(
        f"http://127.0.0.1:{prg.api_server.port}{path}", method=method,
        data=json.dumps(body).encode() if body is not None else None,
        headers={"Content-Type": "application/json"})
    try:
        with urllib.request.urlopen(req) as resp:
            return resp.status, json.loads(resp.read())
    except urllib.error.HTTPError as e:
        return e.code, json.loads(e.read())


def wait_until(fn, timeout_s=10.0, what="condition"):
    import time

    deadline = time.monotonic() + timeout_s
    while time.monotonic() < deadline:
        if fn():
            return
        time.sleep(0.01)
    pytest.fail(f"timed out waiting for {what}")


def count_reads(kv):
    """Returns a closure reporting how many get/range calls hit ``kv``
    since construction (the zero-store-reads 503 pin)."""
    calls = {"n": 0}
    real_get, real_range = kv.get_or, kv.range_prefix

    def get_or(key, default=None):
        calls["n"] += 1
        return real_get(key, default)

    def range_prefix(prefix):
        calls["n"] += 1
        return real_range(prefix)

    kv.get_or, kv.range_prefix = get_or, range_prefix
    start = calls["n"]
    return lambda: calls["n"] - start


class TestShardChaos:
    """Kill a shard-portfolio leader at every election and coordination
    crash point. Throughout: the SURVIVING shard's writes never block, the
    victim shards recover within one lease TTL, journal replay is
    exactly-once, and the deposed leader is fenced out of everything it
    no longer holds."""

    @pytest.mark.parametrize("point", SHARD_CHAOS_POINTS)
    def test_shard_leader_killed_survivors_unblocked_victim_recovers(
            self, tmp_path, point):
        kv = MemoryKV()
        runtime = FakeRuntime(root=str(tmp_path / "rt"))
        clock = {"now": 1000.0}
        smap = ShardMap(3)

        # beta: the survivor — holds shard 2 only (its preferred), defers
        # the vacant rest long enough for alpha to claim them
        beta = boot_shard(kv, runtime, "beta", clock, preferred=(2,))
        step_fleet(beta)
        assert sorted(beta.shard_plane.held) == [2]

        # a PREVIOUS unsharded incarnation left an interrupted rolling
        # replace: train-1 created, the copy+start record journaled (flat
        # prefix ⇒ shard 0's journal) but never executed
        seed = unsharded_seed(kv, runtime, tmp_path)

        alpha = boot_shard(kv, runtime, "alpha", clock, preferred=(0, 1))
        if point == "leader.after_renew":
            # an ESTABLISHED portfolio: acquire 0+1 cleanly (replaying the
            # seed under alpha's epochs), then die right after a renewal
            step_fleet(alpha)
            assert sorted(alpha.shard_plane.held) == [0, 1]
            clock["now"] += 10.0
            with armed(point):
                with pytest.raises(SimulatedCrash):
                    alpha.shard_plane.step_all()
        elif point.startswith("leader."):
            # dies mid-acquire of shard 0: the lease is durable; for
            # after_acquire the takeover callbacks (journal replay) never
            # ran, for after_start_writers they completed
            with armed(point):
                with pytest.raises(SimulatedCrash):
                    alpha.shard_plane.step_all()
            assert alpha.shard_plane.electors[0].epoch >= 1
        else:
            # shard.coord.*: alpha acquires its shards, then dies INSIDE a
            # cross-shard apply — a chip-claiming create is family keys +
            # the global chip map, so it coordinates
            step_fleet(alpha)
            assert sorted(alpha.shard_plane.held) == [0, 1]
            victim_base = base_for_shard(smap, 1, tag="coordfam")
            with armed(point):
                with pytest.raises(SimulatedCrash):
                    alpha.container_svc.run_container(ContainerRun(
                        image_name="jax", container_name=victim_base,
                        chip_count=2))

        # the survivor's shard never blocks: while alpha's leases are
        # still live (and alpha is dead), beta keeps writing to shard 2
        survivor_base = base_for_shard(smap, 2, tag="live")
        out = beta.container_svc.run_container(ContainerRun(
            image_name="jax", container_name=survivor_base, chip_count=0))
        assert out["name"] == f"{survivor_base}-0"

        # beta steals alpha's shards at the first step past the deadline
        # (≤ TTL) — and past its standby delay for the never-acquired ones
        deadlines = [
            json.loads(kv.get(keys.shard_lease_key(i)))["deadline"]
            for i in range(2) if kv.get_or(keys.shard_lease_key(i))]
        assert deadlines, "alpha died without any durable lease"
        assert max(deadlines) - clock["now"] <= beta.cfg.leader_ttl_s
        clock["now"] = max(deadlines + [clock["now"] + 50.0]) + 0.001
        step_fleet(beta)
        assert sorted(beta.shard_plane.held) == [0, 1, 2]

        # exactly-once: the interrupted replace converged forward — one
        # live version, checkpoint data carried, zero leaked chips/ports
        problems = check_invariants(
            runtime, beta.store, beta.container_versions,
            beta.chip_scheduler, beta.port_scheduler)
        assert problems == [], f"{point}: {problems}"
        assert beta.container_versions.get(seed) == 1
        running = [n for n in runtime.container_list()
                   if runtime.container_inspect(n).running
                   and n.startswith(seed)]
        assert running == [f"{seed}-1"]
        with open(f"{runtime.container_data_dir(seed + '-1')}/ckpt.txt") as f:
            assert f.read() == "step=100"
        stats = beta.wq.stats()
        assert stats["journal"]["pending"] == 0
        assert stats["journal"]["inflight"] == 0
        # the repair is a fixpoint
        assert beta.reconciler.reconcile()["actions"] == []

        # the deposed leader still believes in its shards; the store does
        # not. Single-shard puts, cross-shard applies, and writes to
        # never-held shards all lose their compare
        store_before = dict(kv.range_prefix("/"))
        fam0 = keys.latest_key(keys.Resource.CONTAINERS,
                               base_for_shard(smap, 0, tag="probe"))
        fam1 = keys.latest_key(keys.Resource.CONTAINERS,
                               base_for_shard(smap, 1, tag="probe"))
        fam2 = keys.latest_key(keys.Resource.CONTAINERS,
                               base_for_shard(smap, 2, tag="probe"))
        for ops in ([("put", fam0, "stale")],
                    [("put", fam1, "stale"), ("put", fam0, "stale")],
                    [("put", fam2, "stale")]):
            with pytest.raises(errors.GuardFailed):
                alpha.kv.apply(ops)
        assert dict(kv.range_prefix("/")) == store_before
        # ... while the new holder's writes (all three shards) sail
        beta.kv.apply([("put", fam0, "fresh"), ("put", fam1, "fresh")])
        assert kv.get(fam0) == "fresh"

        alpha.stop()
        beta.stop()


def unsharded_seed(kv, runtime, tmp_path) -> str:
    """Seed the shared store with an interrupted rolling replace of family
    ``train`` via a plain unsharded Program (its queue never runs, so the
    copy+start record stays pending in shard 0's — the flat — journal).
    Returns the family base."""
    from tpu_docker_api.schemas.container import ContainerPatchChips

    cfg = config_mod.Config(
        store_backend="memory", runtime_backend="fake",
        health_watch_interval=0, end_port=40099, host_probe_interval_s=0,
        job_supervise_interval=0, reconcile_interval=0)
    prg = Program(cfg, kv=kv, runtime=runtime)
    prg.init()
    (tmp_path / "v1").mkdir(exist_ok=True)
    prg.container_svc.run_container(ContainerRun(
        image_name="jax", container_name="train", chip_count=2,
        container_ports=[ContainerPort(8080)],
        binds=[Bind(str(tmp_path / "v1"), "/data")]))
    with open(f"{runtime.container_data_dir('train-0')}/ckpt.txt", "w") as f:
        f.write("step=100")
    prg.container_svc.patch_container_chips(
        "train", ContainerPatchChips(chip_count=4))
    pending = kv.range_prefix(keys.QUEUE_TASKS_PREFIX)
    assert pending, "seed produced no journaled intent"
    return "train"
