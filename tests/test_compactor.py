"""History compactor (service/compactor.py): retention bounds version
history without ever touching the latest pointer's version or a version a
live runtime member still references; settled admission records and acked
queue markers drain; deletes ride ≤100-op chunks."""

import json
import types

import pytest

from tpu_docker_api.runtime.fake import FakeRuntime
from tpu_docker_api.runtime.spec import ContainerSpec
from tpu_docker_api.schemas.job import JobState
from tpu_docker_api.schemas.state import ContainerState
from tpu_docker_api.service.compactor import HistoryCompactor
from tpu_docker_api.state import keys
from tpu_docker_api.state.keys import Resource
from tpu_docker_api.state.kv import KV, MemoryKV
from tpu_docker_api.state.store import StateStore
from tpu_docker_api.state.version import VersionMap
from tpu_docker_api.telemetry.metrics import MetricsRegistry


class RecordingKV(KV):
    """Pass-through wrapper that records every apply's op count."""

    def __init__(self, inner):
        self.inner = inner
        self.apply_sizes: list[int] = []

    def put(self, k, v):
        self.inner.put(k, v)

    def get(self, k):
        return self.inner.get(k)

    def delete(self, k):
        self.inner.delete(k)

    def range_prefix(self, p):
        return self.inner.range_prefix(p)

    def keys_prefix(self, p, limit=0, start_after=""):
        return self.inner.keys_prefix(p, limit=limit, start_after=start_after)

    def _apply(self, ops, guards=None):
        self.apply_sizes.append(len(ops))
        self.inner._apply(ops, guards)


class Env:
    def __init__(self, tmp_path, retention=3, runtime=None):
        self.kv = RecordingKV(MemoryKV())
        self.store = StateStore(self.kv)
        self.runtime = runtime if runtime is not None else FakeRuntime(
            root=str(tmp_path))
        self.cvm = VersionMap(self.kv, keys.VERSIONS_CONTAINER_KEY)
        self.jvm = VersionMap(self.kv, keys.VERSIONS_JOB_KEY)
        self.compactor = HistoryCompactor(
            self.kv, self.store,
            maps=[(Resource.CONTAINERS, self.cvm), (Resource.JOBS, self.jvm)],
            retention=retention, runtime=self.runtime,
            registry=MetricsRegistry(),
        )

    def seed_container_family(self, base, versions, latest=None):
        for v in range(versions):
            spec = ContainerSpec(name=f"{base}-{v}", image="jax").to_dict()
            self.store.put_container(ContainerState(
                container_name=f"{base}-{v}", version=v, spec=spec))
        if latest is not None:
            self.kv.put(keys.latest_key(Resource.CONTAINERS, base),
                        str(latest))
        self.cvm.set(base, latest if latest is not None else versions - 1)

    def history(self, base):
        return self.store.history(Resource.CONTAINERS, base)


@pytest.fixture
def env(tmp_path):
    return Env(tmp_path)


class TestRetention:
    def test_trims_past_retention_keeping_newest(self, env):
        env.seed_container_family("t", versions=8)
        report = env.compactor.compact_once()
        assert env.history("t") == [5, 6, 7]
        assert report["trimmed"] == {"containers": 5}

    def test_under_retention_untouched(self, env):
        env.seed_container_family("t", versions=2)
        assert env.compactor.compact_once()["trimmedTotal"] == 0
        assert env.history("t") == [0, 1]

    def test_latest_pointer_version_survives_any_age(self, env):
        # rolled back: the pointer names an OLD version — it must survive
        # even though the age rule would trim it
        env.seed_container_family("t", versions=8, latest=1)
        env.compactor.compact_once()
        assert env.history("t") == [1, 5, 6, 7]

    def test_live_member_version_survives(self, env):
        env.seed_container_family("t", versions=8)
        # an old version's container still exists in the runtime
        env.runtime.seed_running(["t-2"], ContainerSpec(name="t-2",
                                                        image="jax"),
                                 running=False)
        report = env.compactor.compact_once()
        assert env.history("t") == [2, 5, 6, 7]
        assert report["protectedLive"] == 1
        # the spared version trims the moment its member is gone
        env.runtime.container_remove("t-2", force=True)
        env.compactor.compact_once()
        assert env.history("t") == [5, 6, 7]

    def test_live_job_member_version_survives(self, tmp_path):
        rt = FakeRuntime(root=str(tmp_path))
        env = Env(tmp_path, runtime=rt)
        host = types.SimpleNamespace(runtime=rt)
        env.compactor._pod = types.SimpleNamespace(hosts={"h0": host})
        for v in range(6):
            env.store.put_job(JobState(
                job_name=f"j-{v}", version=v, image="jax", cmd=[], env=[],
                binds=[], chip_count=0, coordinator_port=0,
                placements=[["h0", f"j-{v}-p0", 0, [], 0]]))
        env.jvm.set("j", 5)
        rt.seed_running(["j-1-p0"], ContainerSpec(name="j-1-p0", image="jax"),
                        running=False)
        env.compactor.compact_once()
        assert env.store.history(Resource.JOBS, "j") == [1, 3, 4, 5]

    def test_deletes_ride_chunks_under_etcd_ceiling(self, tmp_path):
        env = Env(tmp_path, retention=2)
        for i in range(3):
            env.seed_container_family(f"t{i}", versions=60)
        env.kv.apply_sizes.clear()
        env.compactor.compact_once()
        doomed = 3 * (60 - 2)
        assert sum(env.kv.apply_sizes) == doomed
        assert max(env.kv.apply_sizes) <= 100
        assert len(env.kv.apply_sizes) >= 2


class TestDrains:
    def test_orphan_admission_record_purged_live_kept(self, env):
        env.store.put_job(JobState(
            job_name="alive-0", version=0, image="jax", cmd=[], env=[],
            binds=[], chip_count=0, coordinator_port=0, placements=[]))
        env.jvm.set("alive", 0)
        env.kv.put(keys.admission_record_key(1), json.dumps(
            {"seq": 1, "base": "ghost", "kind": "queued", "class": "batch"}))
        env.kv.put(keys.admission_record_key(2), json.dumps(
            {"seq": 2, "base": "alive", "kind": "queued", "class": "batch"}))
        report = env.compactor.compact_once()
        assert report["admissionPurged"] == 1
        left = env.kv.range_prefix(keys.ADMISSION_PREFIX)
        assert list(left) == [keys.admission_record_key(2)]

    def test_acked_markers_swept(self, tmp_path):
        from tpu_docker_api.state.workqueue import WorkQueue

        env = Env(tmp_path)
        wq = WorkQueue(env.kv)
        env.compactor._wq = wq
        env.kv.put(keys.queue_marker_key("dead-task"), "{}")
        env.compactor.compact_once()
        assert env.kv.range_prefix(keys.QUEUE_MARKERS_PREFIX) == {}

    def test_probe_failure_protects_the_version(self, env, monkeypatch):
        env.seed_container_family("t", versions=6)

        def boom(name):
            raise RuntimeError("engine down")

        monkeypatch.setattr(env.runtime, "container_exists", boom)
        env.compactor.compact_once()
        # nothing trimmed: every probe failed, every version protected
        assert env.history("t") == [0, 1, 2, 3, 4, 5]
