"""8-bit Adam moments (train/optim.py): quantization round-trip, training
behavior vs full-precision adamw, and sharded init on a virtual mesh."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from tpu_docker_api.train.optim import (
    ScaleByAdamInt8State,
    _dequant_signed,
    _dequant_sqrt,
    _quant_signed,
    _quant_sqrt,
    adamw_int8,
)


class TestQuantRoundTrip:
    def test_signed_blockwise(self):
        x = jax.random.normal(jax.random.PRNGKey(0), (4, 512)) * 3.0
        q, s = _quant_signed(x, 256)
        assert q.shape == x.shape and q.dtype == jnp.int8
        # scales are (segments, blocks_per_segment, rows): rows on lanes
        assert s.shape == (1, 2, 4)
        back = _dequant_signed(q, s)
        # worst-case linear-quant error: blockmax/127 per element
        bound = (np.repeat(np.asarray(s[0]).T.reshape(-1), 256) * 0.5
                 + 1e-7).reshape(x.shape)
        assert (np.abs(np.asarray(back - x)) <= bound).all()

    def test_sqrt_domain_nonneg(self):
        # nu-like data: positive, several decades of dynamic range
        x = jnp.exp(jax.random.normal(jax.random.PRNGKey(1), (2, 256)) * 3)
        q, s = _quant_sqrt(x, 256)
        back = _dequant_sqrt(q, s)
        assert (np.asarray(back) >= 0).all()
        # block-max elements are represented to <1% relative error
        xb = np.asarray(x).reshape(2, 1, 256)
        mx = xb.max(axis=-1)
        bk = np.asarray(back).reshape(2, 1, 256).max(axis=-1)
        np.testing.assert_allclose(bk, mx, rtol=2e-2)

    def test_odd_last_dim_falls_back_to_divisor(self):
        x = jax.random.normal(jax.random.PRNGKey(2), (3, 100))
        q, s = _quant_signed(x, 256)  # 100 < 256 → one block of 100 per row
        assert s.shape == (1, 1, 3)
        q2, s2 = _quant_signed(jax.random.normal(jax.random.PRNGKey(3), (330,)), 256)
        assert s2.shape[2] == 1 and 330 % (s2.shape[0] * s2.shape[1]) == 0


class TestAdamWInt8:
    def test_moments_are_int8(self):
        params = {"w": jnp.ones((8, 256), jnp.bfloat16)}
        opt = adamw_int8()
        st = opt.init(params)
        inner = [s for s in jax.tree_util.tree_leaves(
            st, is_leaf=lambda x: isinstance(x, ScaleByAdamInt8State))
            if isinstance(s, ScaleByAdamInt8State)][0]
        assert inner.mu_q["w"].dtype == jnp.int8
        assert inner.nu_q["w"].dtype == jnp.int8
        assert inner.mu_scale["w"].shape == (1, 1, 8)

    def test_trains_tiny_llama_like_adamw(self):
        """int8 moments must train the tiny model to (nearly) the same loss
        as full-precision adamw — the 8-bit-Adam claim, checked end-to-end."""
        from tpu_docker_api.models.llama import llama_init, llama_loss, llama_presets
        from tpu_docker_api.train.trainer import default_optimizer

        cfg = llama_presets()["tiny"]
        tokens = jax.random.randint(jax.random.PRNGKey(1), (8, 64), 0,
                                    cfg.vocab_size, dtype="int32")

        def train(opt, steps=30):
            params = llama_init(cfg, jax.random.PRNGKey(0))
            st = opt.init(params)

            @jax.jit
            def step(params, st):
                loss, g = jax.value_and_grad(
                    lambda p: llama_loss(p, tokens, cfg))(params)
                upd, st = opt.update(g, st, params)
                return jax.tree_util.tree_map(
                    lambda p, u: (p.astype(jnp.float32)
                                  + u.astype(jnp.float32)).astype(p.dtype),
                    params, upd), st, loss

            for _ in range(steps):
                params, st, loss = step(params, st)
            return float(loss)

        l_int8 = train(adamw_int8(lr=1e-2))
        l_ref = train(default_optimizer(lr=1e-2))
        l0 = float(jnp.log(jnp.float32(llama_presets()["tiny"].vocab_size)))
        # both optimizers make real progress, and int8 tracks full precision
        assert l_int8 < l0 * 0.8
        assert l_int8 < l_ref * 1.25

    def test_pallas_kernel_matches_xla_path(self):
        """The TPU Pallas update kernel (interpret mode here) must produce
        the same updates and quantized state as the pure-XLA reference."""
        from tpu_docker_api.train.optim import scale_by_adam_int8

        params = {
            "w": jax.random.normal(jax.random.PRNGKey(0), (8, 512),
                                   jnp.bfloat16),
            "b": jax.random.normal(jax.random.PRNGKey(1), (96,), jnp.float32),
        }
        grads = jax.tree_util.tree_map(
            lambda p: jax.random.normal(jax.random.PRNGKey(2), p.shape,
                                        p.dtype), params)
        for step in range(3):
            if step == 0:
                st_x = scale_by_adam_int8(impl="xla").init(params)
                st_p = st_x
            ux, st_x = scale_by_adam_int8(impl="xla").update(grads, st_x)
            up, st_p = scale_by_adam_int8(
                impl="pallas_interpret").update(grads, st_p)
            for (pa, lx), (_, lp) in zip(
                jax.tree_util.tree_leaves_with_path(ux),
                jax.tree_util.tree_leaves_with_path(up),
            ):
                np.testing.assert_allclose(
                    np.asarray(lx, np.float32), np.asarray(lp, np.float32),
                    rtol=1e-5, atol=1e-6, err_msg=f"step {step} {pa}")
            np.testing.assert_array_equal(
                np.asarray(st_x.mu_q["w"]), np.asarray(st_p.mu_q["w"]))
            np.testing.assert_array_equal(
                np.asarray(st_x.nu_q["w"]), np.asarray(st_p.nu_q["w"]))

    def test_sharded_init_on_mesh(self):
        """create_train_state with int8 moments under fsdp/tp: int8 moment
        leaves inherit the param specs (same shapes), quantization scales
        replicate (different shapes) — the _opt_shardings shape check."""
        from tpu_docker_api.models.llama import llama_presets
        from tpu_docker_api.parallel.mesh import MeshPlan, build_mesh
        from tpu_docker_api.train.trainer import (
            create_train_state,
            make_train_step,
            synthetic_batch,
        )

        cfg = llama_presets()["tiny"]
        mesh = build_mesh(MeshPlan(dp=2, fsdp=2, tp=2, sp=1))
        state, opt = create_train_state(cfg, mesh, jax.random.PRNGKey(0),
                                        optimizer=adamw_int8())
        step_fn = make_train_step(cfg, mesh, opt)
        tokens = synthetic_batch(jax.random.PRNGKey(1), 8, 64, cfg.vocab_size)
        state, metrics = step_fn(state, tokens)
        assert np.isfinite(float(metrics["loss"]))


class TestResolveImpl:
    """ADVICE round 1: "auto" must not pick the pallas kernel on a
    multi-device mesh — pallas_call has no GSPMD partitioning rule, so XLA
    would replicate the int8 moment buffers around the custom call."""

    def test_explicit_impls_pass_through(self):
        from tpu_docker_api.train import optim
        for impl in ("xla", "pallas", "pallas_interpret"):
            assert optim._resolve_impl(impl) == impl

    def test_auto_pallas_only_on_single_device_tpu(self, monkeypatch):
        from tpu_docker_api.train import optim
        monkeypatch.setattr(optim.jax, "default_backend", lambda: "tpu")
        monkeypatch.setattr(optim.jax, "device_count", lambda: 1)
        assert optim._resolve_impl("auto") == "pallas"
        monkeypatch.setattr(optim.jax, "device_count", lambda: 8)
        assert optim._resolve_impl("auto") == "xla"
        monkeypatch.setattr(optim.jax, "default_backend", lambda: "cpu")
        monkeypatch.setattr(optim.jax, "device_count", lambda: 1)
        assert optim._resolve_impl("auto") == "xla"
