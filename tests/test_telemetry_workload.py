"""Telemetry (sidecar, probe, native shim) and workload env rendering."""

import json
import os
import subprocess
import urllib.error
import urllib.request

import pytest

from tpu_docker_api.scheduler.topology import HostTopology
from tpu_docker_api.schemas.tpu import HostTopologyInfo
from tpu_docker_api.telemetry.probe import topology_from_info
from tpu_docker_api.telemetry.sidecar import SidecarServer, fake_host_info
from tpu_docker_api.workload.jaxenv import (
    DistributedJob,
    ProcessPlacement,
    render_job_specs,
)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


class TestSidecar:
    def test_fake_topology_roundtrip(self):
        info = fake_host_info("v5e-8")
        assert len(info.chips) == 8
        assert info.mesh_shape == (2, 4, 1)
        again = HostTopologyInfo.from_dict(
            json.loads(json.dumps(info.to_dict()))
        )
        assert again.accelerator_type == "v5e-8"
        assert [c.coords for c in again.chips] == [c.coords for c in info.chips]

    def test_http_endpoint(self):
        srv = SidecarServer(host="127.0.0.1", port=0, fake="v5p-16")
        srv.start()
        try:
            with urllib.request.urlopen(
                f"http://127.0.0.1:{srv.port}/api/v1/detect/tpu"
            ) as resp:
                body = json.loads(resp.read())
            assert body["code"] == 200
            info = HostTopologyInfo.from_dict(body["data"])
            assert info.generation == "v5p"
            assert len(info.chips) == 8  # v5p-16 = 16 cores = 8 chips
            # scheduler can seed from the wire format
            topo = topology_from_info(info)
            assert topo.n_chips == 8
        finally:
            srv.close()

    def test_health_and_unknown_route(self):
        srv = SidecarServer(host="127.0.0.1", port=0, fake="v5e-8")
        srv.start()
        try:
            with urllib.request.urlopen(
                f"http://127.0.0.1:{srv.port}/healthz"
            ) as resp:
                assert json.loads(resp.read())["code"] == 200
            # unknown routes are real HTTP 404s (naive clients fail cleanly)
            with pytest.raises(urllib.error.HTTPError) as exc:
                urllib.request.urlopen(f"http://127.0.0.1:{srv.port}/nope")
            assert exc.value.code == 404
            assert json.loads(exc.value.read())["code"] == 10001
        finally:
            srv.close()


class TestNativeShim:
    @pytest.fixture(autouse=True)
    def built(self):
        lib = os.path.join(REPO, "tpu_native", "libtpushim.so")
        if not os.path.exists(lib):
            rc = subprocess.run(["make", "-C", os.path.join(REPO, "tpu_native")],
                                capture_output=True)
            if rc.returncode != 0:
                pytest.skip("native toolchain unavailable")

    def test_loads_and_enumerates(self):
        from tpu_docker_api.telemetry.shim import load_shim

        shim = load_shim()
        n = shim.chip_count()
        assert n >= 0  # no /dev/accel on CI hosts
        if n == 0:
            with pytest.raises(IndexError):
                shim.chip_metrics(0)

    def test_libtpu_probe_absent(self):
        from tpu_docker_api.telemetry.shim import load_shim

        # nonexistent lib → "" (rc != 0), never a crash
        assert load_shim().libtpu_version("/nonexistent/libtpu.so") == ""


class TestJaxEnv:
    def make_job(self):
        placements = [
            ProcessPlacement(0, "10.0.0.1", [0, 1, 2, 3], 8476),
            ProcessPlacement(1, "10.0.0.2", [0, 1, 2, 3], 8476),
        ]
        return DistributedJob("train", placements, coordinator_port=40000)

    def test_env_rendering(self):
        topo = HostTopology.build("v5e-8")
        specs = render_job_specs(
            self.make_job(), topo, image="maxtext:latest",
            cmd=["python", "train.py"],
        )
        env = dict(e.split("=", 1) for e in specs[1].env)
        assert env["JAX_COORDINATOR_ADDRESS"] == "10.0.0.1:40000"
        assert env["JAX_NUM_PROCESSES"] == "2"
        assert env["JAX_PROCESS_ID"] == "1"
        assert env["TPU_PROCESS_BOUNDS"] == "2,1,1"
        assert env["TPU_CHIPS_PER_PROCESS_BOUNDS"] == "2,2,1"  # chips 0-3 ⇒ 2x2
        assert env["TPU_PROCESS_ADDRESSES"] == "10.0.0.1:8476,10.0.0.2:8476"
        assert env["CLOUD_TPU_TASK_ID"] == "1"
        assert env["TPU_VISIBLE_CHIPS"] == "0,1,2,3"

    def test_single_slice_renders_no_megascale(self):
        topo = HostTopology.build("v5e-8")
        specs = render_job_specs(self.make_job(), topo, image="i", cmd=["c"])
        assert not any("MEGASCALE" in e for s in specs for e in s.env)

    def test_multislice_megascale_env(self):
        """num_slices > 1 ⇒ every process gets the MEGASCALE_* DCN vars
        (SURVEY.md §2.3 comm-backend row) and slice 0's coordinator
        publishes the megascale port."""
        placements = [
            ProcessPlacement(0, "10.0.0.1", [0, 1, 2, 3], 8476, slice_id=0),
            ProcessPlacement(1, "10.0.0.2", [0, 1, 2, 3], 8476, slice_id=1),
        ]
        job = DistributedJob("train", placements, coordinator_port=40000,
                             num_slices=2)
        topo = HostTopology.build("v5e-8")
        specs = render_job_specs(job, topo, image="i", cmd=["c"])
        for i, spec in enumerate(specs):
            env = dict(e.split("=", 1) for e in spec.env)
            assert env["MEGASCALE_COORDINATOR_ADDRESS"] == "10.0.0.1:40001"
            assert env["MEGASCALE_NUM_SLICES"] == "2"
            assert env["MEGASCALE_SLICE_ID"] == str(i)
            assert env["MEGASCALE_PORT"] == "40001"
            # the libtpu ICI mesh is per-slice: each slice here is a single
            # process, so no cross-slice hosts leak into the mesh vars
            assert env["TPU_PROCESS_ADDRESSES"] == (
                f"10.0.0.{i + 1}:8476")
            assert env["TPU_PROCESS_BOUNDS"] == "1,1,1"
            assert env["CLOUD_TPU_TASK_ID"] == "0"
        p0_ports = {pb.host_port for pb in specs[0].port_bindings}
        assert {8476, 40000, 40001} <= p0_ports

    def test_multislice_explicit_megascale_port(self):
        placements = [
            ProcessPlacement(0, "10.0.0.1", [0], 8476, slice_id=0),
            ProcessPlacement(1, "10.0.0.2", [0], 8476, slice_id=1),
        ]
        job = DistributedJob("train", placements, coordinator_port=40000,
                             num_slices=2, megascale_port=45555)
        topo = HostTopology.build("v5e-8")
        env = dict(e.split("=", 1) for e in render_job_specs(
            job, topo, image="i", cmd=["c"])[0].env)
        assert env["MEGASCALE_COORDINATOR_ADDRESS"] == "10.0.0.1:45555"

    def test_coordinator_address_tracks_process_0_not_list_order(self):
        placements = [
            ProcessPlacement(1, "10.0.0.2", [0, 1, 2, 3], 8476),
            ProcessPlacement(0, "10.0.0.1", [0, 1, 2, 3], 8476),
        ]
        job = DistributedJob("train", placements, coordinator_port=40000)
        # process 0 publishes the coordinator port; the address must be its
        # host even when it is not placements[0]
        assert job.coordinator_address == "10.0.0.1:40000"

    def test_job_specs(self):
        topo = HostTopology.build("v5e-8")
        specs = render_job_specs(
            self.make_job(), topo, image="maxtext:latest",
            cmd=["python", "train.py"], base_env=["MODEL=llama3-8b"],
        )
        assert [s.name for s in specs] == ["train-p0", "train-p1"]
        for spec in specs:
            assert "MODEL=llama3-8b" in spec.env
            assert len(spec.devices) == 4
            assert spec.devices[0].host_path == "/dev/accel0"
        # ports are actually published: libtpu mesh port everywhere, the
        # coordinator port on process 0 only
        p0_ports = {(pb.container_port, pb.host_port)
                    for pb in specs[0].port_bindings}
        assert p0_ports == {(8476, 8476), (40000, 40000)}
        p1_ports = {(pb.container_port, pb.host_port)
                    for pb in specs[1].port_bindings}
        assert p1_ports == {(8476, 8476)}

    def test_job_specs_idempotent_rerender(self):
        """Rebuilding a job spec (patch path) must not stack TPU env lines."""
        from tpu_docker_api.runtime.spec import render_tpu_attachment

        topo = HostTopology.build("v5e-8")
        spec = render_job_specs(self.make_job(), topo, image="x",
                                cmd=["y"])[0]
        render_tpu_attachment(spec, [0, 1], topo)
        visible = [e for e in spec.env if e.startswith("TPU_VISIBLE_CHIPS=")]
        assert visible == ["TPU_VISIBLE_CHIPS=0,1"]
