"""Durable work queue unit tests (docs/robustness.md "Durable work queue"):
journal lifecycle, replay-on-restart, durable dead letters, bounded submit /
typed-close semantics, and store-outage degradation. The chaos-level
end-to-end matrix (crash at every ``queue.*`` point during a data copy and
a drain) lives in tests/test_chaos.py::TestDurableQueueChaos."""

import threading
import time

import pytest

from tpu_docker_api import errors
from tpu_docker_api.state import keys
from tpu_docker_api.state.faulty import FaultyKV
from tpu_docker_api.state.kv import MemoryKV
from tpu_docker_api.state.workqueue import (
    FnTask,
    TaskRecord,
    WorkQueue,
    submit_state_put,
)


def _records(kv) -> list[TaskRecord]:
    return [TaskRecord.from_json(v)
            for _, v in sorted(kv.range_prefix(keys.QUEUE_TASKS_PREFIX).items())]


class TestJournalLifecycle:
    def test_submit_journals_pending_record(self):
        kv = MemoryKV()
        wq = WorkQueue(kv)
        wq.register("probe", lambda rec: None)
        tid = wq.submit_record("probe", {"x": 1}, idempotency_key="p:1")
        recs = _records(kv)
        assert len(recs) == 1
        assert recs[0].task_id == tid
        assert recs[0].state == "pending"
        assert recs[0].kind == "probe"
        assert recs[0].params == {"x": 1}
        assert recs[0].idempotency_key == "p:1"

    def test_ack_deletes_journal_entry_and_marker(self):
        kv = MemoryKV()
        wq = WorkQueue(kv)
        seen = []

        def _exec(rec):
            wq.mark_done(rec.task_id)
            seen.append(rec.task_id)

        wq.register("probe", _exec)
        wq.start()
        tid = wq.submit_record("probe", {})
        wq.drain()
        wq.close()
        assert seen == [tid]
        assert _records(kv) == []
        assert kv.range_prefix(keys.QUEUE_MARKERS_PREFIX) == {}

    def test_built_in_kinds_execute(self, tmp_path):
        kv = MemoryKV()
        wq = WorkQueue(kv)
        wq.start()
        submit_state_put(wq, "/t/a", {"v": 1})
        wq.submit_record("del_key", {"key": "/t/a"})
        wq.submit_record("put_kv", {"key": "/t/b", "value": "2"})
        wq.drain()
        wq.close()
        assert kv.get_or("/t/a") is None
        assert kv.get("/t/b") == "2"
        assert _records(kv) == []

    def test_submit_order_is_journal_order(self):
        kv = MemoryKV()
        wq = WorkQueue(kv)
        wq.register("probe", lambda rec: None)
        for i in range(12):
            wq.submit_record("probe", {"i": i})
        recs = _records(kv)
        assert [r.params["i"] for r in recs] == list(range(12))
        assert [r.seq for r in recs] == sorted(r.seq for r in recs)

    def test_idempotency_key_dedupes_active_submit(self):
        kv = MemoryKV()
        wq = WorkQueue(kv)
        wq.register("probe", lambda rec: None)
        t1 = wq.submit_record("probe", {}, idempotency_key="k")
        t2 = wq.submit_record("probe", {}, idempotency_key="k")
        assert t1 == t2
        assert len(_records(kv)) == 1

    def test_unknown_kind_dead_letters_without_retrying(self):
        kv = MemoryKV()
        wq = WorkQueue(kv, max_retries=5, backoff_base_s=10.0)  # a retry would hang
        wq.start()
        wq.submit_record("no_such_kind", {})
        wq.drain()
        wq.close()
        letters = wq.dead_letter_view()
        assert len(letters) == 1
        assert "no handler registered" in letters[0]["error"]
        # deterministic failure: dead-lettered on first sight, no backoff
        assert letters[0]["attempts"] == 1

    def test_idempotency_dedup_survives_restart(self):
        kv = MemoryKV()
        dead = WorkQueue(kv)
        dead.register("probe", lambda rec: None)
        t1 = dead.submit_record("probe", {}, idempotency_key="k")
        # the daemon dies; the next one must dedup against the journaled
        # record, not only its own in-memory submissions
        wq2 = WorkQueue(kv)
        wq2.register("probe", lambda rec: None)
        assert wq2.submit_record("probe", {}, idempotency_key="k") == t1
        assert len(_records(kv)) == 1


class TestReplayOnRestart:
    """The journal is the contract between a dead daemon and its successor:
    a fresh WorkQueue over the same KV replays pending/in-flight records in
    submit order, and resumes the sequence counter without collisions."""

    def _restarted(self, kv) -> WorkQueue:
        wq = WorkQueue(kv)
        return wq

    def test_pending_records_replay_in_order(self):
        kv = MemoryKV()
        dead = WorkQueue(kv)
        dead.register("probe", lambda rec: None)
        for i in range(5):
            dead.submit_record("probe", {"i": i})
        # the daemon dies: its loop never ran, the records are pure intent

        ran = []
        wq2 = self._restarted(kv)
        wq2.register("probe", lambda rec: ran.append(rec.params["i"]))
        outcomes = wq2.replay_journal()
        assert ran == list(range(5))
        assert [o["state"] for o in outcomes] == ["done"] * 5
        assert _records(kv) == []

    def test_inflight_record_replays(self):
        kv = MemoryKV()
        dead = WorkQueue(kv)
        dead.register("probe", lambda rec: None)
        dead.submit_record("probe", {})
        rec = _records(kv)[0]
        rec.state = "inflight"  # the dead daemon claimed it, then died
        kv.put(keys.queue_task_key(rec.seq), rec.to_json())

        ran = []
        wq2 = self._restarted(kv)
        wq2.register("probe", lambda rec: ran.append(rec.task_id))
        wq2.replay_journal()
        assert ran == [rec.task_id]
        assert _records(kv) == []

    def test_replay_skips_records_owned_by_this_process(self):
        kv = MemoryKV()
        wq = WorkQueue(kv)
        wq.register("probe", lambda rec: None)
        wq.submit_record("probe", {})  # queued in THIS process, loop not run
        assert wq.journal_replayable() == []
        assert wq.replay_journal() == []
        # ... but an adopting (restarted) queue sees it
        wq2 = self._restarted(kv)
        assert len(wq2.journal_replayable()) == 1

    def test_marker_makes_replay_skip_completed_side_effect(self):
        kv = MemoryKV()
        dead = WorkQueue(kv)
        applied = []

        def _exec_once(rec):
            if not dead.marker_done(rec.task_id):
                applied.append("dead")
                dead.mark_done(rec.task_id)
            # simulated crash AFTER the side effect, BEFORE the ack

        dead.register("probe", _exec_once)
        dead.submit_record("probe", {})
        rec = _records(kv)[0]
        _exec_once(rec)  # side effect lands; journal still pending

        wq2 = self._restarted(kv)

        def _exec_replay(rec):
            if not wq2.marker_done(rec.task_id):
                applied.append("replay")
                wq2.mark_done(rec.task_id)

        wq2.register("probe", _exec_replay)
        wq2.replay_journal()
        assert applied == ["dead"]  # effectively once
        assert _records(kv) == []

    def test_concurrent_replays_run_each_record_once(self):
        kv = MemoryKV()
        dead = WorkQueue(kv)
        dead.register("probe", lambda rec: None)
        dead.submit_record("probe", {})

        ran = []
        wq2 = WorkQueue(kv)
        wq2.register("probe", lambda rec: (time.sleep(0.05),
                                           ran.append(rec.task_id)))
        # periodic reconcile and the HTTP route racing: the second replayer
        # must re-read the journal AFTER the first finishes, not adopt the
        # same record twice
        threads = [threading.Thread(target=wq2.replay_journal)
                   for _ in range(2)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        assert len(ran) == 1
        assert _records(kv) == []

    def test_seq_resumes_past_surviving_entries(self):
        kv = MemoryKV()
        dead = WorkQueue(kv)
        dead.register("probe", lambda rec: None)
        for i in range(3):
            dead.submit_record("probe", {"i": i})
        top = max(r.seq for r in _records(kv))

        wq2 = self._restarted(kv)
        wq2.register("probe", lambda rec: None)
        wq2.submit_record("probe", {"i": 99})
        assert max(r.seq for r in _records(kv)) == top + 1  # no collision


class TestDurableDeadLetters:
    def test_dead_letters_survive_restart(self):
        kv = MemoryKV()
        wq = WorkQueue(kv, max_retries=2, backoff_base_s=0.001)
        wq.register("boom", lambda rec: (_ for _ in ()).throw(OSError("disk")))
        wq.start()
        wq.submit_record("boom", {})
        wq.drain()
        wq.close()
        assert len(wq.dead_letter_view()) == 1

        wq2 = WorkQueue(kv)  # the next daemon
        letters = wq2.dead_letter_view()
        assert len(letters) == 1
        assert letters[0]["durable"]
        assert letters[0]["kind"] == "boom"
        assert letters[0]["error"].startswith("OSError")
        # dead records are NOT replayed — only an operator retry revives them
        assert wq2.journal_replayable() == []

    def test_retry_drains_durable_set_with_fresh_budget(self):
        kv = MemoryKV()
        healthy = []
        wq = WorkQueue(kv, max_retries=2, backoff_base_s=0.001)

        def _flaky(rec):
            if not healthy:
                raise OSError("disk full")

        wq.register("flaky", _flaky)
        wq.start()
        wq.submit_record("flaky", {})
        wq.drain()
        assert len(wq.dead_letter_view()) == 1
        # retried while the fault persists: dead-letters again, no spin
        assert wq.retry_dead_letters() == 1
        wq.drain()
        assert len(wq.dead_letter_view()) == 1

        healthy.append(True)
        assert wq.retry_dead_letters() == 1
        wq.drain()
        wq.close()
        assert wq.dead_letter_view() == []
        assert _records(kv) == []

    def test_compensation_fires_on_durable_dead_letter(self):
        kv = MemoryKV()
        compensated = []
        wq = WorkQueue(kv, max_retries=1, backoff_base_s=0.001)
        wq.register("boom", lambda rec: (_ for _ in ()).throw(OSError("x")),
                    on_fail=lambda rec: compensated.append(rec.params["who"]))
        wq.start()
        wq.submit_record("boom", {"who": "t-1"})
        wq.drain()
        wq.close()
        assert compensated == ["t-1"]


class TestBoundedSubmitAndClose:
    def test_full_queue_raises_queue_saturated(self):
        kv = MemoryKV()
        wq = WorkQueue(kv, capacity=1, submit_timeout_s=0.05)
        wq.register("probe", lambda rec: None)
        # no consumer: the first submit fills the queue
        wq.submit_record("probe", {})
        with pytest.raises(errors.QueueSaturated):
            wq.submit_record("probe", {})
        with pytest.raises(errors.QueueSaturated):
            wq.submit(FnTask(fn=lambda: None))
        # the rejected record must NOT linger in the journal (it would
        # execute later behind the caller's back)
        assert len(_records(kv)) == 1

    def test_queue_saturated_maps_to_http_429(self):
        assert errors.QueueSaturated.http_status == 429
        assert errors.ApiError.http_status == 200  # everything else: envelope

    def test_submit_after_close_raises_queue_closed(self):
        kv = MemoryKV()
        wq = WorkQueue(kv)
        wq.register("probe", lambda rec: None)
        wq.start()
        wq.close()
        with pytest.raises(errors.QueueClosed):
            wq.submit_record("probe", {})
        with pytest.raises(errors.QueueClosed):
            wq.submit(FnTask(fn=lambda: None))

    def test_close_deadline_bounds_hung_engine(self):
        kv = MemoryKV()
        release = threading.Event()
        wq = WorkQueue(kv, close_deadline_s=0.2)
        wq.register("hang", lambda rec: release.wait(30))
        wq.start()
        wq.submit_record("hang", {})
        t0 = time.monotonic()
        wq.close()  # must return within ~the deadline, not after 30 s
        assert time.monotonic() - t0 < 5.0
        stats = wq.stats()
        assert any(e["event"] == "queue-close-abandoned"
                   for e in stats["events"])
        release.set()
        # the abandoned record is still journaled for the next daemon
        assert len(_records(kv)) == 1


def _journal_outage(kv: FaultyKV, down: bool = True) -> None:
    """Partition the queue journal's keyspace — the store-outage the
    queue must degrade through, scoped so everything else stays healthy
    (state/faulty.py replaces the old ad-hoc ``_OutageKV`` wrapper)."""
    kv.set_partition(keys.QUEUE_PREFIX, active=down)


class TestStoreOutageDegradation:
    def test_submit_degrades_loudly_and_still_executes(self):
        kv = FaultyKV(MemoryKV())
        ran = []
        wq = WorkQueue(kv)
        wq.register("probe", lambda rec: ran.append(rec.params["i"]))
        wq.start()
        _journal_outage(kv)
        wq.submit_record("probe", {"i": 1})  # journal write fails — LOUDLY
        wq.drain()
        _journal_outage(kv, down=False)
        wq.submit_record("probe", {"i": 2})  # back to durable
        wq.drain()
        wq.close()
        assert ran == [1, 2]
        stats = wq.stats()
        assert stats["journalWriteFailures"] >= 1
        assert any(e["event"] == "journal-write-failed"
                   for e in stats["events"])
        assert _records(kv.inner) == []  # the durable one was acked

    def test_degraded_submit_dead_letter_stays_observable(self):
        kv = FaultyKV(MemoryKV())
        wq = WorkQueue(kv, max_retries=1, backoff_base_s=0.001)
        wq.register("boom", lambda rec: (_ for _ in ()).throw(OSError("x")))
        wq.start()
        _journal_outage(kv)  # journal write fails: the record is in-memory only
        wq.submit_record("boom", {"who": "t"})
        wq.drain()
        _journal_outage(kv, down=False)
        # exhausted: with no journal entry to hold state="dead", the record
        # must land with the ephemeral letters, never vanish silently
        letters = wq.dead_letter_view()
        assert len(letters) == 1
        assert letters[0]["kind"] == "boom" and letters[0]["durable"] is False
        # ... and stays retryable
        wq.register("boom", lambda rec: None)
        assert wq.retry_dead_letters() == 1
        wq.drain()
        wq.close()
        assert wq.dead_letter_view() == []

    def test_retry_with_full_queue_keeps_ephemeral_letters(self):
        kv = MemoryKV()
        wq = WorkQueue(kv, capacity=1, submit_timeout_s=0.05,
                       max_retries=1, backoff_base_s=0.001)
        wq.register("boom", lambda rec: (_ for _ in ()).throw(OSError("x")))
        wq.start()
        wq.submit(FnTask(fn=lambda: (_ for _ in ()).throw(OSError("y")),
                         description="eph"))
        wq.drain()
        assert len(wq.dead_letter_view()) == 1
        # wedge the consumer and fill the queue so the re-enqueue cannot fit
        gate = threading.Event()
        wq.submit(FnTask(fn=gate.wait, description="wedge"))
        wq.submit(FnTask(fn=lambda: None, description="filler"))
        # bounded: returns (no deadlock holding the lifecycle lock), and the
        # un-enqueued letter is restored rather than dropped
        assert wq.retry_dead_letters() == 0
        assert len(wq.dead_letter_view()) == 1
        gate.set()
        wq.drain()
        wq.close()

    def test_stats_survive_journal_outage(self):
        kv = FaultyKV(MemoryKV())
        wq = WorkQueue(kv)
        _journal_outage(kv)
        out = wq.stats()
        assert "error" in out["journal"]

    def test_ack_outage_leaves_entry_for_replay(self):
        kv = FaultyKV(MemoryKV())
        wq = WorkQueue(kv)
        ran = []
        wq.register("probe", lambda rec: ran.append(1))
        wq.start()
        tid = wq.submit_record("probe", {})
        _journal_outage(kv)  # the ack delete will fail
        wq.drain()
        wq.close()
        _journal_outage(kv, down=False)
        assert ran == [1]
        recs = _records(kv.inner)
        # the claim write failed too, so the entry survives as pending
        # (or inflight, had the outage begun later) — either replays
        assert len(recs) == 1 and recs[0].state in ("pending", "inflight")
        # the next daemon adopts and re-acks it (idempotent handler)
        wq2 = WorkQueue(kv)
        wq2.register("probe", lambda rec: ran.append(2))
        wq2.replay_journal()
        assert _records(kv.inner) == []


class TestQueueStatsView:
    def test_stats_counts_lifecycle_states(self):
        kv = MemoryKV()
        wq = WorkQueue(kv, max_retries=1, backoff_base_s=0.001)
        wq.register("ok", lambda rec: None)
        wq.register("boom", lambda rec: (_ for _ in ()).throw(OSError("x")))
        wq.submit_record("ok", {})
        out = wq.stats()
        assert out["depth"] == 1
        assert out["journal"]["pending"] == 1
        assert out["capacity"] == 110
        assert out["closed"] is False
        wq.start()
        wq.submit_record("boom", {})
        wq.drain()
        wq.close()
        out = wq.stats()
        assert out["journal"]["dead"] == 1
        assert out["journal"]["pending"] == 0
        assert out["closed"] is True
