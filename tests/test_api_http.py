"""End-to-end HTTP tier: real ThreadingHTTPServer + fake backends.

Exercises the full stack the way the reference's sample-interface transcripts
do (api/gpu-docker-api-sample-interface.md), asserting the uniform
``{code,msg,data}`` envelope with HTTP 200 on every path (response.go:15-29).
"""

import json
import sys
import urllib.request

import pytest

from tpu_docker_api.api.app import ApiServer, build_router
from tpu_docker_api.runtime.fake import FakeRuntime
from tpu_docker_api.scheduler.ports import PortScheduler
from tpu_docker_api.scheduler.slices import ChipScheduler
from tpu_docker_api.scheduler.topology import HostTopology
from tpu_docker_api.service.container import ContainerService
from tpu_docker_api.service.volume import VolumeService
from tpu_docker_api.state import keys
from tpu_docker_api.state.kv import MemoryKV
from tpu_docker_api.state.store import StateStore
from tpu_docker_api.state.version import VersionMap
from tpu_docker_api.state.workqueue import WorkQueue


@pytest.fixture
def server(tmp_path):
    kv = MemoryKV()
    store = StateStore(kv)
    runtime = FakeRuntime(root=str(tmp_path), allow_exec=True)
    chips = ChipScheduler(HostTopology.build("v5e-8"), kv)
    ports = PortScheduler(kv, 40000, 40099)
    wq = WorkQueue(kv)
    wq.start()
    c_svc = ContainerService(
        runtime, store, chips, ports,
        VersionMap(kv, keys.VERSIONS_CONTAINER_KEY), wq,
    )
    v_svc = VolumeService(runtime, store, VersionMap(kv, keys.VERSIONS_VOLUME_KEY), wq)
    from tpu_docker_api.service.reconcile import Reconciler

    reconciler = Reconciler(runtime, store, chips, ports, c_svc.versions,
                            container_svc=c_svc)
    srv = ApiServer(build_router(c_svc, v_svc, chips, ports, work_queue=wq,
                                 reconciler=reconciler), port=0)
    srv.start()
    srv.wq = wq        # test hooks
    srv.runtime = runtime
    yield srv
    srv.close()
    wq.close()


def call(server, method, path, body=None):
    req = urllib.request.Request(
        f"http://127.0.0.1:{server.port}{path}",
        method=method,
        data=json.dumps(body).encode() if body is not None else None,
        headers={"Content-Type": "application/json"},
    )
    with urllib.request.urlopen(req) as resp:
        assert resp.status == 200  # envelope carries the real outcome
        return json.loads(resp.read())


class TestContainerRoutes:
    def test_create_exec_delete_happy_path(self, server):
        """BASELINE.json config #1: cardless container + exec smoke test."""
        out = call(server, "POST", "/api/v1/containers", {
            "imageName": "jax:latest", "containerName": "smoke", "chipCount": 0,
        })
        assert out["code"] == 200
        assert out["data"]["name"] == "smoke-0"

        out = call(server, "POST", "/api/v1/containers/smoke-0/execute", {
            "cmd": [sys.executable, "-c", "print(21 * 2)"],
        })
        assert out["code"] == 200
        assert out["data"]["stdout"].strip() == "42"

        out = call(server, "DELETE", "/api/v1/containers/smoke-0", {
            "force": True, "delEtcdInfoAndVersionRecord": True,
        })
        assert out["code"] == 200

    def test_tpu_container_and_patch(self, server):
        out = call(server, "POST", "/api/v1/containers", {
            "imageName": "jax:latest", "containerName": "train", "chipCount": 2,
        })
        assert out["data"]["chipIds"] == [0, 1]

        out = call(server, "PATCH", "/api/v1/containers/train-0/tpu",
                   {"chipCount": 4})
        assert out["code"] == 200
        assert out["data"]["name"] == "train-1"
        server.wq.drain()

        out = call(server, "GET", "/api/v1/containers/train-1")
        assert out["data"]["runtime"]["running"]

    def test_gpu_route_alias(self, server):
        call(server, "POST", "/api/v1/containers", {
            "imageName": "jax", "containerName": "t", "chipCount": 1,
        })
        out = call(server, "PATCH", "/api/v1/containers/t-0/gpu", {"gpuCount": 2})
        assert out["code"] == 200

    def test_validation_name_with_dash_rejected(self, server):
        out = call(server, "POST", "/api/v1/containers", {
            "imageName": "jax", "containerName": "bad-name", "chipCount": 0,
        })
        assert out["code"] == 10001

    def test_validation_missing_image(self, server):
        out = call(server, "POST", "/api/v1/containers",
                   {"containerName": "x", "chipCount": 0})
        assert out["code"] == 10001

    def test_chip_exhaustion_maps_to_code(self, server):
        out = call(server, "POST", "/api/v1/containers", {
            "imageName": "jax", "containerName": "big", "chipCount": 99,
        })
        assert out["code"] == 10601  # ChipNotEnough (reference CodeContainerGpuNotEnough)

    def test_missing_container_info(self, server):
        out = call(server, "GET", "/api/v1/containers/ghost-0")
        assert out["code"] == 10302

    def test_stop_restart(self, server):
        call(server, "POST", "/api/v1/containers", {
            "imageName": "jax", "containerName": "t", "chipCount": 1,
        })
        assert call(server, "POST", "/api/v1/containers/t-0/stop", {})["code"] == 200
        out = call(server, "PATCH", "/api/v1/containers/t-0/restart", {})
        assert out["code"] == 200
        assert out["data"]["name"] == "t-1"  # stopped carded ⇒ new version

    def test_commit(self, server):
        call(server, "POST", "/api/v1/containers", {
            "imageName": "jax", "containerName": "t", "chipCount": 0,
        })
        out = call(server, "POST", "/api/v1/containers/t-0/commit",
                   {"newImageName": "snap:v1"})
        assert out["code"] == 200 and out["data"]["imageId"].startswith("sha256:")

    def test_invalid_json_enveloped(self, server):
        req = urllib.request.Request(
            f"http://127.0.0.1:{server.port}/api/v1/containers",
            method="POST", data=b"{not json",
            headers={"Content-Type": "application/json"},
        )
        with urllib.request.urlopen(req) as resp:
            out = json.loads(resp.read())
        assert out["code"] == 10001

    def test_unknown_route(self, server):
        out = call(server, "GET", "/api/v1/nope")
        assert out["code"] == 10001


class TestHistoryRollbackRoutes:
    def test_container_history_and_rollback(self, server):
        call(server, "POST", "/api/v1/containers", {
            "imageName": "jax", "containerName": "hr", "chipCount": 2,
        })
        call(server, "PATCH", "/api/v1/containers/hr-0/tpu", {"chipCount": 4})
        server.wq.drain()

        out = call(server, "GET", "/api/v1/containers/hr/history")
        assert out["code"] == 200
        assert [v["version"] for v in out["data"]["versions"]] == [0, 1]

        out = call(server, "PATCH", "/api/v1/containers/hr/rollback",
                   {"version": 0})
        assert out["code"] == 200
        assert out["data"]["name"] == "hr-2"
        assert len(out["data"]["chipIds"]) == 2
        server.wq.drain()

        out = call(server, "PATCH", "/api/v1/containers/hr/rollback", {})
        assert out["code"] != 200  # version required

        # non-numeric version is a 10001 bad request, not a 500
        # (ADVICE round 1: int() coercion must not escape as SERVER_ERROR)
        out = call(server, "PATCH", "/api/v1/containers/hr/rollback",
                   {"version": "abc"})
        assert out["code"] == 10001

    def test_volume_history_and_rollback(self, server):
        call(server, "POST", "/api/v1/volumes",
             {"volumeName": "vh", "size": "10GB"})
        call(server, "PATCH", "/api/v1/volumes/vh-0/size", {"size": "20GB"})
        server.wq.drain()

        out = call(server, "GET", "/api/v1/volumes/vh/history")
        assert [v["size"] for v in out["data"]["versions"]] == ["10GB", "20GB"]

        out = call(server, "PATCH", "/api/v1/volumes/vh/rollback",
                   {"version": "abc"})
        assert out["code"] == 10001

        out = call(server, "PATCH", "/api/v1/volumes/vh/rollback",
                   {"version": 0})
        assert out["code"] == 200
        assert out["data"] == {"name": "vh-2", "fromVersion": 0,
                               "size": "10GB"}


class TestVolumeRoutes:
    def test_create_resize_info_delete(self, server):
        out = call(server, "POST", "/api/v1/volumes",
                   {"volumeName": "data", "size": "10GB"})
        assert out["data"]["name"] == "data-0"

        out = call(server, "PATCH", "/api/v1/volumes/data-0/size", {"size": "20GB"})
        assert out["data"]["name"] == "data-1"
        server.wq.drain()

        out = call(server, "GET", "/api/v1/volumes/data-1")
        assert out["data"]["state"]["size"] == "20GB"

        out = call(server, "DELETE", "/api/v1/volumes/data-1",
                   {"delEtcdInfoAndVersionRecord": True})
        assert out["code"] == 200

    def test_bad_size_unit(self, server):
        out = call(server, "POST", "/api/v1/volumes",
                   {"volumeName": "data", "size": "10XB"})
        assert out["code"] != 200


class TestResourceRoutes:
    def test_tpus_view(self, server):
        call(server, "POST", "/api/v1/containers", {
            "imageName": "jax", "containerName": "t", "chipCount": 4,
        })
        out = call(server, "GET", "/api/v1/resources/tpus")
        data = out["data"]
        assert data["totalChips"] == 8 and data["freeChips"] == 4
        owners = {c["owner"] for c in data["chips"] if c["used"]}
        assert owners == {"t"}
        # alias kept for reference compatibility
        assert call(server, "GET", "/api/v1/resources/gpus")["data"]["totalChips"] == 8

    def test_ports_view(self, server):
        out = call(server, "GET", "/api/v1/resources/ports")
        assert out["data"]["startPort"] == 40000

    def test_healthz(self, server):
        assert call(server, "GET", "/healthz")["data"]["status"] == "ok"


class TestRobustnessRoutes:
    def test_reconcile_dry_run_then_apply(self, server):
        call(server, "POST", "/api/v1/containers", {
            "imageName": "jax", "containerName": "t", "chipCount": 2,
        })
        server.runtime.crash_container("t-0")

        out = call(server, "GET", "/api/v1/reconcile?dryRun=true")
        assert out["code"] == 200
        assert out["data"]["dryRun"] is True
        assert [a["action"] for a in out["data"]["actions"]] == ["restart-dead"]
        # dry run did not touch the runtime
        assert not server.runtime.container_inspect("t-0").running

        out = call(server, "GET", "/api/v1/reconcile")
        assert out["data"]["dryRun"] is False
        assert server.runtime.container_inspect("t-0").running

        out = call(server, "GET", "/api/v1/reconcile/events")
        assert out["data"][-1]["action"] == "restart-dead"

    def test_dead_letter_retry_roundtrip(self, server):
        from tpu_docker_api.state.workqueue import FnTask

        server.wq._max_retries = 1
        server.wq._backoff_base_s = 0.001
        healthy = []

        def flaky():
            if not healthy:
                raise OSError("disk full")

        server.wq.submit(FnTask(fn=flaky, description="flaky"))
        server.wq.drain()
        assert len(call(server, "GET", "/api/v1/debug/deadletters")["data"]) == 1

        healthy.append(True)
        out = call(server, "POST", "/api/v1/dead-letters/retry")
        assert out["data"] == {"requeued": 1}
        server.wq.drain()
        assert call(server, "GET", "/api/v1/debug/deadletters")["data"] == []


class TestDurableQueueRoutes:
    def test_queue_stats_and_durable_dead_letters(self, server):
        server.wq._max_retries = 1
        server.wq._backoff_base_s = 0.001

        def boom(rec):
            raise OSError("disk full")

        server.wq.register("always_fail", boom)
        server.wq.submit_record("always_fail", {"x": 1})
        server.wq.drain()

        st = call(server, "GET", "/api/v1/queue")["data"]
        assert st["capacity"] == 110 and st["closed"] is False
        assert st["journal"]["dead"] == 1 and st["journal"]["inflight"] == 0

        dl = call(server, "GET", "/api/v1/dead-letters")["data"]
        assert len(dl) == 1
        assert dl[0]["kind"] == "always_fail" and dl[0]["durable"] is True
        assert "disk full" in dl[0]["error"]

        # operator fixed the fault → HTTP retry drains the DURABLE set
        server.wq.register("always_fail", lambda rec: None)
        assert call(server, "POST", "/api/v1/dead-letters/retry")["data"] == {
            "requeued": 1}
        server.wq.drain()
        assert call(server, "GET", "/api/v1/dead-letters")["data"] == []
        assert call(server, "GET", "/api/v1/queue")["data"]["journal"]["entries"] == 0

    def test_queue_saturation_surfaces_http_429(self, server):
        import threading
        import urllib.error

        from tpu_docker_api import errors
        from tpu_docker_api.state.workqueue import FnTask

        call(server, "POST", "/api/v1/containers", {
            "imageName": "jax", "containerName": "sat", "chipCount": 0,
        })
        gate = threading.Event()
        server.wq.submit(FnTask(fn=gate.wait, description="wedge the loop"))
        server.wq._submit_timeout_s = 0.05
        try:
            for _ in range(200):  # fill every slot behind the wedged task
                try:
                    server.wq.submit(FnTask(fn=lambda: None))
                except errors.QueueSaturated:
                    break
            else:
                pytest.fail("queue never saturated")

            # the purge submit inside DELETE hits the full queue → a real
            # HTTP 429 (the one deviation from the always-200 envelope)
            req = urllib.request.Request(
                f"http://127.0.0.1:{server.port}/api/v1/containers/sat-0",
                method="DELETE",
                data=json.dumps({"force": True,
                                 "delEtcdInfoAndVersionRecord": True}).encode(),
                headers={"Content-Type": "application/json"},
            )
            with pytest.raises(urllib.error.HTTPError) as ei:
                urllib.request.urlopen(req)
            assert ei.value.code == 429
            body = json.loads(ei.value.read())
            assert body["code"] == 10801
            assert "retry later" in body["msg"]
        finally:
            gate.set()  # unwedge so fixture teardown's close() drains fast


class TestTraceRoutes:
    """The tracing surface (ISSUE 14): per-request root spans keyed by
    X-Request-Id/traceparent, the /api/v1/traces exporters, the requestId
    echo in the error envelope, the events?traceId= join, and the
    http_requests_total/http_request_ms satellite metrics."""

    @pytest.fixture
    def traced(self, tmp_path):
        from tpu_docker_api.telemetry.trace import Tracer

        kv = MemoryKV()
        store = StateStore(kv)
        runtime = FakeRuntime(root=str(tmp_path), allow_exec=True)
        chips = ChipScheduler(HostTopology.build("v5e-8"), kv)
        ports = PortScheduler(kv, 40100, 40199)
        tracer = Tracer(buffer_size=32, slow_ms=0.0001)
        wq = WorkQueue(kv, tracer=tracer)
        wq.start()
        c_svc = ContainerService(
            runtime, store, chips, ports,
            VersionMap(kv, keys.VERSIONS_CONTAINER_KEY), wq,
        )
        v_svc = VolumeService(runtime, store,
                              VersionMap(kv, keys.VERSIONS_VOLUME_KEY), wq)
        srv = ApiServer(build_router(c_svc, v_svc, chips, ports,
                                     work_queue=wq, tracer=tracer), port=0)
        srv.start()
        srv.wq = wq
        srv.tracer = tracer
        yield srv
        srv.close()
        wq.close()

    def _call(self, server, method, path, body=None, headers=None):
        req = urllib.request.Request(
            f"http://127.0.0.1:{server.port}{path}", method=method,
            data=json.dumps(body).encode() if body is not None else None,
            headers={"Content-Type": "application/json", **(headers or {})},
        )
        with urllib.request.urlopen(req) as resp:
            return json.loads(resp.read()), dict(resp.headers)

    def test_request_id_is_the_trace_id(self, traced):
        out, hdr = self._call(traced, "POST", "/api/v1/containers",
                              {"imageName": "jax", "containerName": "tr",
                               "chipCount": 2},
                              headers={"X-Request-Id": "req42"})
        assert out["code"] == 200
        assert hdr["X-Request-Id"] == "req42"
        tree, _ = self._call(traced, "GET", "/api/v1/traces/req42")
        spans = tree["data"]["spans"]
        roots = [s for s in spans if not s["parentId"]]
        assert len(roots) == 1
        assert roots[0]["name"] == "http:POST /api/v1/containers"
        assert roots[0]["attrs"]["requestId"] == "req42"
        names = {s["name"] for s in spans}
        assert "dispatch:/api/v1/containers" in names
        assert "kv.apply" in names
        assert "sched.chips.claim" in names

    def test_traceparent_continues_remote_context(self, traced):
        tid = "0af7651916cd43dd8448eb211c80319c"
        sid = "b7ad6b7169203331"
        self._call(traced, "GET", "/api/v1/resources/tpus",
                   headers={"traceparent": f"00-{tid}-{sid}-01"})
        tree, _ = self._call(traced, "GET", f"/api/v1/traces/{tid}")
        http_span = next(s for s in tree["data"]["spans"]
                         if s["name"].startswith("http:"))
        assert http_span["parentId"] == sid  # remote parent, not a root

    def test_error_envelope_carries_request_id(self, traced):
        out, hdr = self._call(traced, "GET", "/api/v1/containers/nope-1",
                              headers={"X-Request-Id": "bugreport7"})
        assert out["code"] == 10302
        assert out["requestId"] == "bugreport7"
        assert hdr["X-Request-Id"] == "bugreport7"
        # success envelopes keep the legacy three-key shape
        ok, _ = self._call(traced, "GET", "/api/v1/resources/tpus")
        assert set(ok) == {"code", "msg", "data"}

    def test_trace_list_and_unknown_trace(self, traced):
        self._call(traced, "GET", "/api/v1/resources/tpus",
                   headers={"X-Request-Id": "listme"})
        ls, _ = self._call(traced, "GET", "/api/v1/traces?limit=5")
        data = ls["data"]
        assert data["enabled"] is True
        assert any(i["traceId"] == "listme" for i in data["items"])
        assert data["items"][0]["rootCount"] == 1
        missing, _ = self._call(traced, "GET", "/api/v1/traces/ghost")
        assert missing["code"] == 10501

    def test_events_filter_by_trace_id(self, traced):
        # slow_ms is armed at ~0: every request emits a slow-trace event
        self._call(traced, "GET", "/api/v1/resources/tpus",
                   headers={"X-Request-Id": "evta"})
        self._call(traced, "GET", "/api/v1/resources/ports",
                   headers={"X-Request-Id": "evtb"})
        evts, _ = self._call(traced, "GET", "/api/v1/events?traceId=evta")
        assert evts["data"], "no events matched the trace"
        assert all(e["traceId"] == "evta" for e in evts["data"])
        allevts, _ = self._call(traced, "GET", "/api/v1/events")
        assert len(allevts["data"]) > len(evts["data"])

    def test_http_metrics_exposed(self, traced):
        self._call(traced, "GET", "/api/v1/resources/tpus")
        text = urllib.request.urlopen(
            f"http://127.0.0.1:{traced.port}/metrics").read().decode()
        assert "# TYPE http_requests_total counter" in text
        assert ('http_requests_total{code="200",method="GET",'
                'route="/api/v1/resources/tpus"}') in text
        assert "# TYPE http_request_ms histogram" in text
        assert 'http_request_ms_bucket{le="+Inf"' in text

    def test_async_tail_joins_the_request_trace(self, traced):
        self._call(traced, "POST", "/api/v1/containers",
                   {"imageName": "jax", "containerName": "tail",
                    "chipCount": 0})
        self._call(traced, "DELETE", "/api/v1/containers/tail",
                   {"force": True, "delEtcdInfoAndVersionRecord": True},
                   headers={"X-Request-Id": "deltail"})
        traced.wq.drain()
        tree, _ = self._call(traced, "GET", "/api/v1/traces/deltail")
        names = [s["name"] for s in tree["data"]["spans"]]
        assert "queue.task:delete_state_family" in names

    def test_dual_header_trace_reachable_by_request_id(self, traced):
        tid = "1af7651916cd43dd8448eb211c80319c"
        self._call(traced, "GET", "/api/v1/resources/tpus",
                   headers={"traceparent": f"00-{tid}-b7ad6b7169203331-01",
                            "X-Request-Id": "proxyreq"})
        # keyed by the traceparent id, but the runbook greps by the
        # echoed requestId — the fallback root-attr index serves it
        tree, _ = self._call(traced, "GET", "/api/v1/traces/proxyreq")
        assert tree["code"] == 200
        assert tree["data"]["traceId"] == tid

    def test_events_filter_reaches_past_the_limit_window(self, traced):
        self._call(traced, "GET", "/api/v1/resources/tpus",
                   headers={"X-Request-Id": "oldtrace"})
        # flood the tracer ring with newer slow-trace events (ring holds
        # 128) so oldtrace's event falls outside the newest-20 window
        for _ in range(40):
            self._call(traced, "GET", "/api/v1/resources/ports")
        unfiltered, _ = self._call(traced, "GET", "/api/v1/events?limit=20")
        assert all(e.get("traceId") != "oldtrace" for e in unfiltered["data"])
        filtered, _ = self._call(traced, "GET",
                                 "/api/v1/events?traceId=oldtrace&limit=20")
        assert filtered["data"], "filter lost events older than the window"
        assert all(e["traceId"] == "oldtrace" for e in filtered["data"])

    def test_crlf_in_request_id_cannot_split_response(self, traced):
        import socket

        # http.client's parse_headers preserves obs-fold CRLFs inside a
        # header value — an unsanitized echo would emit the injected line
        # as a real response header (response splitting)
        raw = (b"GET /api/v1/resources/tpus HTTP/1.1\r\n"
               b"Host: x\r\n"
               b"X-Request-Id: abc\r\n Set-Cookie: pwned=1\r\n"
               b"Connection: close\r\n\r\n")
        with socket.create_connection(("127.0.0.1", traced.port)) as s:
            s.sendall(raw)
            resp = b""
            while True:
                chunk = s.recv(4096)
                if not chunk:
                    break
                resp += chunk
        head = resp.split(b"\r\n\r\n", 1)[0].decode()
        # the injected text may survive INSIDE the echoed value (harmless,
        # one line) — what must never exist is a separate header LINE
        lines = head.split("\r\n")
        assert not any(ln.lower().startswith("set-cookie:")
                       for ln in lines), head
        echoed = next(ln for ln in lines
                      if ln.lower().startswith("x-request-id:"))
        assert "\r" not in echoed and "\n" not in echoed

    def test_traceparent_continued_request_is_still_a_local_root(self, traced):
        tid = "3af7651916cd43dd8448eb211c80319c"
        _, hdr = self._call(
            traced, "GET", "/api/v1/resources/tpus",
            headers={"traceparent": f"00-{tid}-b7ad6b7169203331-01"})
        # the W3C echo names the serving span
        out_tp = hdr.get("traceparent", "")
        assert out_tp.startswith(f"00-{tid}-")
        # remote parentage does not demote the handler span: it is the
        # LOCAL root (summaries count it, slow_ms fires on it)
        ls = traced.tracer.summaries(limit=50)
        entry = next(i for i in ls["items"] if i["traceId"] == tid)
        assert entry["rootCount"] == 1
        assert any(e.get("traceId") == tid
                   for e in traced.tracer.events_view(limit=500))
