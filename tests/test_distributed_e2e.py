"""N-process distributed bootstrap, executed for real (VERDICT r1 item 2).

The reference's cross-container duty is wiring ports into containers
(service/container.go:489-501); the TPU analog is rendering the JAX
distributed env. Rendering alone is not parity — these tests EXECUTE it:
child processes receive their env verbatim from
``workload.jaxenv.render_job_specs`` output, run
``bootstrap_jax → jax.distributed.initialize`` (gloo collectives on CPU),
assemble one global mesh across processes, and train with per-process
local rows through the ``jax.process_count() > 1`` branch of
``train.trainer.make_train_step``. The parent then reruns the identical
schedule single-process and compares losses.
"""

import json
import os
import pathlib
import socket
import subprocess
import sys
import time

import numpy as np
import pytest

from tpu_docker_api.scheduler.topology import HostTopology
from tpu_docker_api.workload.jaxenv import (
    DistributedJob,
    ProcessPlacement,
    render_job_specs,
)

REPO_ROOT = pathlib.Path(__file__).resolve().parent.parent
CHILD = pathlib.Path(__file__).resolve().parent / "distributed_child.py"

N_PROC = 2
LOCAL_DEVICES = 2
STEPS = 3
GLOBAL_BATCH = 4
SEQ = 32


def _free_ports(n: int) -> list[int]:
    socks, ports = [], []
    for _ in range(n):
        s = socket.socket()
        s.bind(("127.0.0.1", 0))
        socks.append(s)
        ports.append(s.getsockname()[1])
    for s in socks:
        s.close()
    return ports


def _rendered_env() -> list[dict[str, str]]:
    """Per-process env dicts, rendered by the SAME code path the job
    service uses for real containers (render_job_specs), verbatim."""
    coord_port, p0, p1 = _free_ports(3)
    job = DistributedJob(
        "e2e",
        [ProcessPlacement(0, "127.0.0.1", [0, 1], p0),
         ProcessPlacement(1, "127.0.0.1", [2, 3], p1)],
        coordinator_port=coord_port,
    )
    topo = HostTopology.build("v5e-4")
    specs = render_job_specs(job, topo, image="workload", cmd=["python"])
    return [dict(e.split("=", 1) for e in s.env) for s in specs]


@pytest.fixture(scope="module")
def token_file(tmp_path_factory):
    from tpu_docker_api.data.loader import write_token_file

    rng = np.random.default_rng(7)
    tokens = rng.integers(0, 256, size=4096, dtype=np.int64)
    path = tmp_path_factory.mktemp("tok") / "stream.bin"
    return str(write_token_file(tokens, path))


@pytest.mark.slow
class TestDistributedBootstrapE2E:
    def _run_children(self, tmp_path, token_file):
        envs = _rendered_env()
        procs, outs = [], []
        for pid in range(N_PROC):
            out = tmp_path / f"proc{pid}.json"
            outs.append(out)
            env = {k: v for k, v in os.environ.items()
                   if not k.startswith(("JAX_", "TPU_", "MEGASCALE_"))}
            env.update(envs[pid])
            env["E2E_TOKENS"] = token_file
            env["PYTHONPATH"] = os.pathsep.join(
                [str(REPO_ROOT), env.get("PYTHONPATH", "")]).rstrip(":")
            procs.append(subprocess.Popen(
                [sys.executable, str(CHILD), str(out), str(LOCAL_DEVICES),
                 str(STEPS), str(GLOBAL_BATCH)],
                env=env, stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
                text=True, cwd=str(REPO_ROOT)))
        # poll (not sequential communicate): an early crash in either child
        # must surface its traceback immediately, not hide behind the
        # sibling blocking on the coordinator for the full timeout
        try:
            deadline = time.monotonic() + 300
            pending = dict(enumerate(procs))
            while pending:
                if time.monotonic() > deadline:
                    raise AssertionError(
                        f"children {sorted(pending)} timed out")
                for pid, p in list(pending.items()):
                    if p.poll() is None:
                        continue
                    out_text = p.stdout.read()
                    assert p.returncode == 0, (
                        f"child {pid} failed (rc={p.returncode}):\n{out_text}")
                    del pending[pid]
                time.sleep(0.2)
        finally:
            for p in procs:
                if p.poll() is None:
                    p.kill()
        return [json.loads(out.read_text()) for out in outs]

    def _single_process_losses(self, token_file):
        import jax

        from tpu_docker_api.data.loader import make_batch_fn, open_token_files
        from tpu_docker_api.models.llama import llama_presets
        from tpu_docker_api.parallel.mesh import MeshPlan, build_mesh
        from tpu_docker_api.train.trainer import (
            create_train_state,
            make_train_step,
        )

        n_dev = N_PROC * LOCAL_DEVICES
        mesh = build_mesh(MeshPlan(dp=n_dev // 2, fsdp=2),
                          devices=jax.devices()[:n_dev])
        cfg = llama_presets()["tiny"]
        src = open_token_files(token_file, window=SEQ + 1)
        batch_fn = make_batch_fn(src, GLOBAL_BATCH, seed=0)
        state, opt = create_train_state(cfg, mesh, jax.random.PRNGKey(0))
        step = make_train_step(cfg, mesh, opt)
        losses = []
        for s in range(STEPS):
            state, metrics = step(state, batch_fn(s))
            losses.append(float(metrics["loss"]))
        return losses

    def test_two_process_train_matches_single_process(self, tmp_path,
                                                      token_file):
        results = self._run_children(tmp_path, token_file)

        for r in results:
            assert r["process_count"] == N_PROC
            assert r["device_count"] == N_PROC * LOCAL_DEVICES
        # the replicated loss must agree across processes exactly
        assert results[0]["losses"] == results[1]["losses"]
        # cross-process global sum was verified inside each child; echo it
        assert results[0]["global_sum"] == results[1]["global_sum"]

        ref = self._single_process_losses(token_file)
        np.testing.assert_allclose(results[0]["losses"], ref, rtol=1e-4)
        # training actually progressed
        assert ref[-1] < ref[0]
