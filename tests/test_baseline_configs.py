"""The five BASELINE.json configs as end-to-end flows.

Each test drives the exact scenario the baseline names, on the hermetic
backends (fake runtime, memory KV, virtual CPU devices) so they run anywhere;
the real-hardware counterpart of the compute path is bench.py.

  #1 cardless container + JAX-CPU matmul via POST /containers + exec
  #2 single-chip container via chip patch
  #3 v5e-4 single host: inference-shaped job placement
  #4 v5p-64: GSPMD DP ranks placed over an 8-host ICI domain
  #5 rolling rescale 4→8 chips mid-train with checkpoint migration
     (the real trainer CLI: SIGTERM quiesce → checkpoint → resume on the
     bigger mesh, orchestrated around the job service's rescale flow)
"""

import json
import os
import pathlib
import signal
import subprocess
import sys
import time

import pytest

from tpu_docker_api.schemas.container import (
    ContainerExecute,
    ContainerPatchChips,
    ContainerRun,
)
from tpu_docker_api.schemas.job import JobPatchChips, JobRun

from tests.test_pod import make_pod  # the 8-host v5p fixture builder

REPO = pathlib.Path(__file__).resolve().parent.parent


def _container_stack(acc="v5e-8"):
    from tpu_docker_api.runtime.fake import FakeRuntime
    from tpu_docker_api.scheduler.ports import PortScheduler
    from tpu_docker_api.scheduler.slices import ChipScheduler
    from tpu_docker_api.scheduler.topology import HostTopology
    from tpu_docker_api.service.container import ContainerService
    from tpu_docker_api.state import keys
    from tpu_docker_api.state.kv import MemoryKV
    from tpu_docker_api.state.store import StateStore
    from tpu_docker_api.state.version import VersionMap
    from tpu_docker_api.state.workqueue import WorkQueue

    kv = MemoryKV()
    topo = HostTopology.build(acc)
    rt = FakeRuntime(allow_exec=True)
    wq = WorkQueue(kv)
    wq.start()
    svc = ContainerService(
        rt, StateStore(kv), ChipScheduler(topo, kv),
        PortScheduler(kv, 40000, 40099),
        VersionMap(kv, keys.VERSIONS_CONTAINER_KEY), wq,
    )
    return svc, rt, wq


def _job_stack(grid=(2, 2, 2), acc="v5p-8"):
    from tpu_docker_api.scheduler.pod import PodScheduler
    from tpu_docker_api.service.job import JobService
    from tpu_docker_api.state import keys
    from tpu_docker_api.state.kv import MemoryKV
    from tpu_docker_api.state.store import StateStore
    from tpu_docker_api.state.version import VersionMap

    kv = MemoryKV()
    pod = make_pod(kv, grid=grid, acc=acc)
    svc = JobService(pod, PodScheduler(pod, kv), StateStore(kv),
                     VersionMap(kv, keys.VERSIONS_JOB_KEY))
    return svc, pod


class TestConfig1CardlessExec:
    """BASELINE config #1: 0-chip container, JAX-CPU matmul via exec."""

    def test_cardless_matmul(self):
        svc, rt, wq = _container_stack()
        try:
            out = svc.run_container(ContainerRun(
                image_name="python:3.11", container_name="smoke", chip_count=0))
            assert out["chipIds"] == []
            spec = rt.container_inspect("smoke-0").spec
            # cardless: no accel devices, no TPU env rendered
            assert spec.devices == []
            assert not any(e.startswith("TPU_") for e in spec.env)
            result = svc.execute_container("smoke-0", ContainerExecute(cmd=[
                sys.executable, "-c",
                "import jax; jax.config.update('jax_platforms','cpu'); "
                "import jax.numpy as jnp; "
                "x = jnp.ones((128, 128), jnp.float32); "
                "print(float((x @ x).sum()))",
            ]))
            assert "2097152.0" in result
        finally:
            wq.close()


class TestConfig2SingleChip:
    """BASELINE config #2: patch a cardless container up to one TPU chip."""

    def test_patch_to_one_chip(self):
        svc, rt, wq = _container_stack()
        try:
            svc.run_container(ContainerRun(
                image_name="jax:tpu", container_name="mnist", chip_count=0))
            out = svc.patch_container_chips("mnist-0",
                                            ContainerPatchChips(chip_count=1))
            assert out["name"] == "mnist-1"
            spec = rt.container_inspect("mnist-1").spec
            assert [d.host_path for d in spec.devices] == [
                f"/dev/accel{spec.chip_ids[0]}"]
            env = dict(e.split("=", 1) for e in spec.env)
            assert env["TPU_CHIPS_PER_PROCESS_BOUNDS"] == "1,1,1"
            # old version quiesced, data-copy task completed
            assert not rt.container_inspect("mnist-0").running
        finally:
            wq.close()


class TestConfig3V5e4Inference:
    """BASELINE config #3: v5e-4 single host serving Llama inference."""

    def test_v5e4_placement(self):
        svc, pod = _job_stack(grid=(1, 1, 1), acc="v5e-8")
        info = svc.run_job(JobRun(
            image_name="llama-serve:tpu", job_name="serve", chip_count=4,
            cmd=["python", "-m", "tpu_docker_api.serve",
                 "--preset", "llama3-8b", "--tp", "4"]))
        assert len(info["processes"]) == 1
        proc = info["processes"][0]
        assert len(proc["chipIds"]) == 4
        spec = pod.hosts[proc["hostId"]].runtime.container_inspect(
            proc["container"]).spec
        env = dict(e.split("=", 1) for e in spec.env)
        # 4 chips of a v5e host form a contiguous 2x2 ICI block
        assert env["TPU_CHIPS_PER_PROCESS_BOUNDS"] == "2,2,1"
        assert spec.ici_contiguous

    def test_v5e4_inference_engine_runs(self):
        """The compute half: KV-cached generate on the 4-device tp mesh the
        placement above would hand the container."""
        import jax

        from tpu_docker_api.infer.engine import GenerateConfig, make_generate_fn
        from tpu_docker_api.models.llama import llama_init, llama_presets
        from tpu_docker_api.parallel.mesh import MeshPlan, build_mesh

        mesh = build_mesh(MeshPlan(dp=1, fsdp=1, tp=4, sp=1),
                          devices=jax.devices()[:4])
        cfg = llama_presets()["tiny"]
        params = llama_init(cfg, jax.random.PRNGKey(0))
        gen = make_generate_fn(cfg, GenerateConfig(max_new_tokens=4, max_seq=16),
                               mesh=mesh)
        out = gen(params, jax.numpy.ones((2, 8), jax.numpy.int32),
                  jax.random.PRNGKey(1))
        assert out["tokens"].shape == (2, 4)


class TestConfig4V5p64DataParallel:
    """BASELINE config #4: v5p-64 pretrain, DP ranks over an 8-host pod."""

    def test_dp_rank_placement(self):
        svc, pod = _job_stack()  # 2x2x2 host grid = 32 chips = v5p-64
        info = svc.run_job(JobRun(
            image_name="maxtext:tpu", job_name="pretrain",
            accelerator_type="v5p-64",
            binds=["/nfs/ckpt:/ckpt"],
            cmd=["python", "-m", "tpu_docker_api.train",
                 "--preset", "llama3-8b", "--ckpt-dir", "/ckpt"]))
        assert info["chipCount"] == 32
        assert len(info["processes"]) == 8
        coord_addrs = set()
        for proc in info["processes"]:
            spec = pod.hosts[proc["hostId"]].runtime.container_inspect(
                proc["container"]).spec
            env = dict(e.split("=", 1) for e in spec.env)
            assert env["JAX_NUM_PROCESSES"] == "8"
            assert env["TPU_PROCESS_BOUNDS"] == "2,2,2"
            assert env["TPU_CHIPS_PER_PROCESS_BOUNDS"] == "2,2,1"
            coord_addrs.add(env["JAX_COORDINATOR_ADDRESS"])
        assert len(coord_addrs) == 1  # every rank agrees on the coordinator


@pytest.mark.slow
class TestConfig5RollingRescaleMidTrain:
    """BASELINE config #5: foo-0 (4 chips) → foo-1 (8 chips) mid-train.

    The full loop with the REAL trainer (subprocess, virtual CPU devices):
    train on a 4-device mesh writing checkpoints to the shared dir, SIGTERM
    (= the job service's graceful stop) → quiesce checkpoint, control-plane
    rescale 4→8, then the trainer resumes on an 8-device mesh from the
    quiesced step — checkpoint continuity across the mesh change.
    """

    def _launch(self, ckpt, devices, fsdp, steps):
        env = {**os.environ, "PYTHONPATH": str(REPO)}
        return subprocess.Popen(
            [sys.executable, "-m", "tpu_docker_api.train",
             "--preset", "tiny", "--steps", str(steps), "--batch", "8",
             "--seq", "64", "--platform", "cpu",
             "--virtual-devices", str(devices), "--fsdp", str(fsdp),
             "--ckpt-dir", str(ckpt), "--save-every", "1000",
             "--log-every", "5"],
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
            env=env)

    def test_rescale_mid_train(self, tmp_path):
        ckpt = tmp_path / "ckpt"
        # phase 1: job-0 on 4 devices; let it make progress, then quiesce
        p = self._launch(ckpt, devices=4, fsdp=2, steps=10_000)
        deadline = time.monotonic() + 240
        progressed = False
        seen: list[str] = []
        while time.monotonic() < deadline:
            if p.poll() is not None:  # died early — stop, report its output
                seen.extend(p.stdout.readlines())  # drain buffered traceback
                break
            line = p.stdout.readline()
            if not line:
                continue
            seen.append(line)
            if '"step"' in line and json.loads(line)["step"] >= 10:
                progressed = True
                break
        assert progressed, f"trainer never reached step 10; output: {seen!r}"
        p.send_signal(signal.SIGTERM)
        out, _ = p.communicate(timeout=120)
        assert p.returncode == 0
        quiesced = json.loads(
            [ln for ln in out.splitlines() if "quiesced" in ln][-1])
        assert quiesced["step"] >= 10

        # control plane: the rescale that would relaunch the container
        svc, pod = _job_stack(grid=(2, 1, 1))
        svc.run_job(JobRun(image_name="trainer", job_name="foo", chip_count=4,
                           binds=[f"{ckpt}:/ckpt"]))
        info = svc.patch_job_chips("foo", JobPatchChips(chip_count=8))
        assert info["name"] == "foo-1" and info["chipCount"] == 8

        # phase 2: job-1 on 8 devices resumes from the quiesced step
        p2 = self._launch(ckpt, devices=8, fsdp=2,
                          steps=quiesced["step"] + 10)
        out2, _ = p2.communicate(timeout=360)
        assert p2.returncode == 0, out2
        done = json.loads([ln for ln in out2.splitlines() if "done" in ln][-1])
        assert done["step"] == quiesced["step"] + 10
        steps_logged = [json.loads(ln)["step"] for ln in out2.splitlines()
                        if '"loss"' in ln]
        # resumed, not restarted: no step below the quiesce point is re-run
        assert min(steps_logged) > quiesced["step"]
